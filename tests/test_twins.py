"""Device/host twin parity suite — the runtime half of the ``ops.TWINS``
contract (AVDB9xx).

Every pair registered in ``annotatedvdb_tpu/ops/__init__.py`` is driven
here, kernel and twin on the SAME inputs, answers compared exactly
(``assert_array_equal``, never allclose: the twins are the bytes the
serving breaker / ``host_only`` / remote-link fallbacks actually serve).
The static analyzer's AVDB903 requires each registered pair to co-appear
in one test file — this file is that proof, by construction: it imports
every kernel and every twin by name.

The registry itself is audited first: every TWINS entry must import, and
every jitted symbol this file exercises must be registered.
"""

from __future__ import annotations

import importlib

import numpy as np
import pytest

from annotatedvdb_tpu.ops import TWINS
from annotatedvdb_tpu.ops.annotate import (
    annotate_kernel_jit,
    annotate_kernel_mesh,
    annotate_kernel_np,
)
from annotatedvdb_tpu.ops.annotate_pallas import annotate_bin_pallas
from annotatedvdb_tpu.ops.binindex import (
    bin_index_kernel_jit,
    bin_index_kernel_mesh,
)
from annotatedvdb_tpu.ops.cadd_join import (
    cadd_join_host,
    cadd_join_kernel,
)
from annotatedvdb_tpu.ops.dedup import (
    lookup_in_sorted_jit,
    lookup_in_sorted_multi_jit,
    lookup_in_sorted_multi_np,
    lookup_in_sorted_np,
    mark_batch_duplicates_jit,
    mark_batch_duplicates_mesh,
    mark_batch_duplicates_multi_jit,
    mark_batch_duplicates_multi_np,
    mark_batch_duplicates_np,
    mix_chrom_hash,
)
from annotatedvdb_tpu.ops.export_pack import (
    export_pack_host,
    export_pack_kernel_jit,
)
from annotatedvdb_tpu.ops.hashing import (
    allele_hash_jit,
    allele_hash_mesh,
    allele_hash_np,
)
from annotatedvdb_tpu.ops.intervals import (
    bits_spans_kernel_jit,
    bits_spans_stacked_host,
    bits_spans_stacked_jit,
    interval_spans_host,
)
from annotatedvdb_tpu.ops.stats import (
    STATS_MISSING,
    stats_panel_host,
    stats_panel_kernel_jit,
    windowed_stats_host,
    windowed_stats_kernel_jit,
)
from annotatedvdb_tpu.ops.pack import (
    encode_alleles_nibble,
    inflate_alleles_jit,
    inflate_alleles_np,
    pack_outputs_jit,
    pack_outputs_np,
    pack_vep_outputs_jit,
    pack_vep_outputs_np,
    unpack_outputs,
)
from annotatedvdb_tpu.oracle.binindex import closed_form_bin
from annotatedvdb_tpu.types import encode_allele_array
from annotatedvdb_tpu.utils.arrays import POS_SENTINEL

WIDTH = 8
BASES = "ACGT"


def _random_alleles(rng, n, width=WIDTH, max_len=None):
    """Random in-width allele batch: byte matrices + lengths + strings."""
    max_len = max_len or width
    strs = []
    for _ in range(n):
        k = int(rng.integers(1, max_len + 1))
        strs.append("".join(BASES[i] for i in rng.integers(0, 4, k)))
    mat, lens = encode_allele_array(strs, width)
    return mat, lens, strs


def _allele_batch(rng, n):
    ref, ref_len, _ = _random_alleles(rng, n)
    alt, alt_len, _ = _random_alleles(rng, n)
    pos = np.sort(rng.integers(1, 5_000_000, n)).astype(np.int32)
    return pos, ref, alt, ref_len, alt_len


# ---------------------------------------------------------------------------
# registry audit


def test_every_twins_entry_imports():
    """Each registered name (kernel AND twin) resolves to a callable."""
    for kernel, twin in TWINS.items():
        for dotted in (kernel, twin):
            mod, attr = dotted.rsplit(".", 1)
            obj = getattr(
                importlib.import_module(f"annotatedvdb_tpu.{mod}"), attr
            )
            assert callable(obj), dotted


def test_this_suite_references_every_pair():
    """AVDB903's contract, self-checked: every registered kernel and twin
    name appears in this file's source."""
    src = open(__file__, encoding="utf-8").read()
    for kernel, twin in TWINS.items():
        assert kernel.rsplit(".", 1)[1] in src, kernel
        assert twin.rsplit(".", 1)[1] in src, twin


# ---------------------------------------------------------------------------
# annotate family


def test_annotate_kernel_vs_np_twin():
    rng = np.random.default_rng(7)
    pos, ref, alt, ref_len, alt_len = _allele_batch(rng, 256)
    dev = annotate_kernel_jit(pos, ref, alt, ref_len, alt_len)
    host = annotate_kernel_np(pos, ref, alt, ref_len, alt_len)
    assert set(dev) == set(host)
    for key in dev:
        d = np.asarray(dev[key])
        h = np.asarray(host[key])
        assert d.dtype == h.dtype, (key, d.dtype, h.dtype)
        np.testing.assert_array_equal(d, h, err_msg=key)


def test_annotate_kernel_np_dup_motif_case():
    """The duplication-motif branch, pinned explicitly on both sides
    (random batches rarely produce one)."""
    refs, alts = ["AGG", "ATGTG"], ["AGGGG", "AT"]
    ref, ref_len = encode_allele_array(refs, WIDTH)
    alt, alt_len = encode_allele_array(alts, WIDTH)
    pos = np.array([100, 200], np.int32)
    dev = annotate_kernel_jit(pos, ref, alt, ref_len, alt_len)
    host = annotate_kernel_np(pos, ref, alt, ref_len, alt_len)
    for key in dev:
        np.testing.assert_array_equal(
            np.asarray(dev[key]), np.asarray(host[key]), err_msg=key
        )


def test_annotate_pallas_vs_np_twin():
    """The fused Pallas kernel against the SAME host twin (its annotate
    half must agree field for field; the bin half is pinned against the
    bin kernel/oracle in test_annotate_pallas)."""
    rng = np.random.default_rng(11)
    pos, ref, alt, ref_len, alt_len = _allele_batch(rng, 192)
    pal = annotate_bin_pallas(pos, ref, alt, ref_len, alt_len,
                              block_n=128, interpret=True)
    host = annotate_kernel_np(pos, ref, alt, ref_len, alt_len)
    for key in ("prefix_len", "norm_ref_len", "norm_alt_len",
                "end_location", "location_start", "location_end",
                "variant_class", "is_dup_motif", "needs_digest",
                "host_fallback"):
        np.testing.assert_array_equal(
            np.asarray(pal[key]), np.asarray(host[key]), err_msg=key
        )


# ---------------------------------------------------------------------------
# bin index


def test_bin_index_kernel_vs_oracle_twin():
    rng = np.random.default_rng(13)
    start = rng.integers(1, 240_000_000, 512).astype(np.int64)
    end = start + rng.integers(0, 100_000, 512)
    level, leaf = bin_index_kernel_jit(start, end)
    for i in range(len(start)):
        o_level, o_leaf = closed_form_bin(int(start[i]), int(end[i]))
        assert int(level[i]) == o_level, i
        assert int(leaf[i]) == o_leaf, i


# ---------------------------------------------------------------------------
# cadd join


def test_cadd_join_kernel_vs_host_twin():
    rng = np.random.default_rng(17)
    k_rows = 64
    spos = np.sort(rng.integers(1, 10_000, k_rows)).astype(np.int32)
    spos[-8:] = np.iinfo(np.int32).max  # sentinel padding
    sref, _, _ = _random_alleles(rng, k_rows, max_len=2)
    salt, _, _ = _random_alleles(rng, k_rows, max_len=2)
    n = 128
    vpos = rng.integers(1, 10_000, n).astype(np.int32)
    # half the queries copy a real row (guaranteed hits incl. alleles)
    take = rng.integers(0, k_rows - 8, n // 2)
    vpos[: n // 2] = spos[take]
    vref = np.zeros((n, WIDTH), np.uint8)
    valt = np.zeros((n, WIDTH), np.uint8)
    vref[: n // 2] = sref[take]
    valt[: n // 2] = salt[take]
    r2, _, _ = _random_alleles(rng, n - n // 2, max_len=2)
    a2, _, _ = _random_alleles(rng, n - n // 2, max_len=2)
    vref[n // 2:] = r2
    valt[n // 2:] = a2
    d_matched, d_idx = cadd_join_kernel(vpos, vref, valt, spos, sref, salt)
    h_matched, h_idx = cadd_join_host(vpos, vref, valt, spos, sref, salt)
    np.testing.assert_array_equal(np.asarray(d_matched), h_matched)
    np.testing.assert_array_equal(np.asarray(d_idx), h_idx)
    assert h_matched[: n // 2].all()  # the planted hits actually hit


# ---------------------------------------------------------------------------
# dedup / membership


def _dup_batch(rng, n):
    pos, ref, alt, ref_len, alt_len = _allele_batch(rng, n)
    h = allele_hash_np(ref, alt, ref_len, alt_len)
    # plant exact duplicates (identical identity) and a (pos, h) collision
    # with different bytes (must NOT count as duplicate)
    for i in range(0, n - 8, 7):
        j = i + rng.integers(1, 6)
        pos[j] = pos[i]
        ref[j], alt[j] = ref[i], alt[i]
        ref_len[j], alt_len[j] = ref_len[i], alt_len[i]
        h[j] = h[i]
    return pos, h, ref, alt, ref_len, alt_len


def test_mark_batch_duplicates_vs_np_twin():
    rng = np.random.default_rng(19)
    pos, h, ref, alt, ref_len, alt_len = _dup_batch(rng, 128)
    dev = mark_batch_duplicates_jit(pos, h, ref, alt, ref_len, alt_len)
    host = mark_batch_duplicates_np(pos, h, ref, alt, ref_len, alt_len)
    np.testing.assert_array_equal(np.asarray(dev), host)
    assert host.any()  # the planted duplicates were seen


def test_mark_batch_duplicates_multi_vs_np_twin():
    rng = np.random.default_rng(23)
    pos, h, ref, alt, ref_len, alt_len = _dup_batch(rng, 128)
    chrom = rng.integers(1, 4, 128).astype(np.int32)
    dev = mark_batch_duplicates_multi_jit(
        chrom, pos, h, ref, alt, ref_len, alt_len
    )
    host = mark_batch_duplicates_multi_np(
        chrom, pos, h, ref, alt, ref_len, alt_len
    )
    np.testing.assert_array_equal(np.asarray(dev), host)


def _sorted_store(rng, m):
    pos, ref, alt, ref_len, alt_len = _allele_batch(rng, m)
    h = allele_hash_np(ref, alt, ref_len, alt_len)
    order = np.lexsort((h, pos))
    return (pos[order], h[order], ref[order], alt[order],
            ref_len[order], alt_len[order])


def test_lookup_in_sorted_vs_np_twin():
    rng = np.random.default_rng(29)
    spos, sh, sref, salt, srlen, salen = _sorted_store(rng, 256)
    n = 96
    qpos, qref, qalt, qrlen, qalen = _allele_batch(rng, n)
    qh = allele_hash_np(qref, qalt, qrlen, qalen)
    hit = rng.integers(0, 256, n // 2)
    qpos[: n // 2] = spos[hit]
    qh[: n // 2] = sh[hit]
    qref[: n // 2], qalt[: n // 2] = sref[hit], salt[hit]
    qrlen[: n // 2], qalen[: n // 2] = srlen[hit], salen[hit]
    dev = lookup_in_sorted_jit(
        spos, sh, sref, salt, srlen, salen,
        qpos, qh, qref, qalt, qrlen, qalen,
    )
    host = lookup_in_sorted_np(
        spos, sh, sref, salt, srlen, salen,
        qpos, qh, qref, qalt, qrlen, qalen,
    )
    np.testing.assert_array_equal(np.asarray(dev[0]), host[0])
    np.testing.assert_array_equal(np.asarray(dev[1]), host[1])
    assert host[0][: n // 2].all()


def test_lookup_in_sorted_multi_vs_np_twin():
    rng = np.random.default_rng(31)
    spos, sh, sref, salt, srlen, salen = _sorted_store(rng, 256)
    schrom = rng.integers(1, 4, 256).astype(np.int32)
    shm = np.array(mix_chrom_hash(sh, schrom))
    order = np.lexsort((shm, spos))
    schrom, spos, shm = schrom[order], spos[order], shm[order]
    sref, salt = sref[order], salt[order]
    srlen, salen = srlen[order], salen[order]
    n = 96
    qpos, qref, qalt, qrlen, qalen = _allele_batch(rng, n)
    qchrom = rng.integers(1, 4, n).astype(np.int32)
    qhm = np.array(mix_chrom_hash(
        allele_hash_np(qref, qalt, qrlen, qalen), qchrom
    ))
    hit = rng.integers(0, 256, n // 2)
    qchrom[: n // 2] = schrom[hit]
    qpos[: n // 2] = spos[hit]
    qhm[: n // 2] = shm[hit]
    qref[: n // 2], qalt[: n // 2] = sref[hit], salt[hit]
    qrlen[: n // 2], qalen[: n // 2] = srlen[hit], salen[hit]
    dev = lookup_in_sorted_multi_jit(
        schrom, spos, shm, sref, salt, srlen, salen,
        qchrom, qpos, qhm, qref, qalt, qrlen, qalen,
    )
    host = lookup_in_sorted_multi_np(
        schrom, spos, shm, sref, salt, srlen, salen,
        qchrom, qpos, qhm, qref, qalt, qrlen, qalen,
    )
    np.testing.assert_array_equal(np.asarray(dev[0]), host[0])
    np.testing.assert_array_equal(np.asarray(dev[1]), host[1])


# ---------------------------------------------------------------------------
# hashing


def test_allele_hash_vs_np_twin():
    rng = np.random.default_rng(37)
    _pos, ref, alt, ref_len, alt_len = _allele_batch(rng, 512)
    dev = np.asarray(allele_hash_jit(ref, alt, ref_len, alt_len))
    host = allele_hash_np(ref, alt, ref_len, alt_len)
    assert dev.dtype == host.dtype == np.uint32
    np.testing.assert_array_equal(dev, host)


# ---------------------------------------------------------------------------
# intervals (BITS)


def test_bits_spans_kernel_vs_host_twin():
    rng = np.random.default_rng(41)
    m = 512
    pos = np.sort(rng.integers(1, 2_000_000, m)).astype(np.int32)
    q = 128
    starts = rng.integers(1, 2_000_000, q).astype(np.int32)
    ends = (starts + rng.integers(0, 50_000, q)).astype(np.int32)
    # raw kernel on already-clamped in-range inputs == host twin
    d_lo, d_hi, d_level, d_leaf = bits_spans_kernel_jit(pos, starts, ends)
    h_lo, h_hi, h_level, h_leaf = interval_spans_host(pos, starts, ends)
    np.testing.assert_array_equal(np.asarray(d_lo), h_lo)
    np.testing.assert_array_equal(np.asarray(d_hi), h_hi)
    np.testing.assert_array_equal(np.asarray(d_level), h_level)
    np.testing.assert_array_equal(np.asarray(d_leaf), h_leaf)
    assert int(POS_SENTINEL) > 2_000_000  # inputs stayed in-range


def _stats_columns(rng, m):
    pos = np.sort(rng.integers(1, 2_000_000, m)).astype(np.int32)
    af = rng.integers(STATS_MISSING, 1_000_001, m).astype(np.int32)
    cadd = rng.integers(STATS_MISSING, 100_001, m).astype(np.int32)
    rank = rng.integers(STATS_MISSING, 40, m).astype(np.int32)
    return pos, af, cadd, rank


def test_stats_panel_kernel_vs_host_twin():
    """The fused analytics panel: integer-only reductions, so the twin
    is byte-exact (the deeper battery lives in tests/test_stats.py)."""
    rng = np.random.default_rng(42)
    pos, af, cadd, rank = _stats_columns(rng, 512)
    q = 64
    starts = rng.integers(1, 2_000_000, q).astype(np.int32)
    ends = (starts + rng.integers(0, 50_000, q)).astype(np.int32)
    dev = stats_panel_kernel_jit(pos, af, cadd, rank, starts, ends)
    host = stats_panel_host(pos, af, cadd, rank, starts, ends)
    for d, h, name in zip(dev, host, ("lo", "hi", "af_lanes", "af_hist",
                                      "cadd_lanes", "cadd_hist", "ranks")):
        np.testing.assert_array_equal(np.asarray(d), np.asarray(h),
                                      err_msg=name)


def test_windowed_stats_kernel_vs_host_twin():
    rng = np.random.default_rng(43)
    pos, _af, cadd, _rank = _stats_columns(rng, 509)
    q = 48
    starts = rng.integers(1, 2_000_000, q).astype(np.int32)
    ends = (starts + rng.integers(0, 50_000, q)).astype(np.int32)
    dev = windowed_stats_kernel_jit(pos, cadd, starts, ends, windows=6)
    host = windowed_stats_host(pos, cadd, starts, ends, 6)
    for d, h, name in zip(dev, host, ("counts", "present", "lanes")):
        np.testing.assert_array_equal(np.asarray(d), np.asarray(h),
                                      err_msg=name)


# ---------------------------------------------------------------------------
# mesh-sharded kernel surfaces (mesh_pjit): same twins, sharded compute.
# Each mesh surface is driven against ITS registered host twin on an
# odd-sized batch (forces the pad-and-slice path) over the live mesh
# (conftest forces an 8-virtual-device CPU backend).


def test_annotate_kernel_mesh_vs_np_twin():
    rng = np.random.default_rng(61)
    pos, ref, alt, ref_len, alt_len = _allele_batch(rng, 333)
    dev = annotate_kernel_mesh(pos, ref, alt, ref_len, alt_len)
    host = annotate_kernel_np(pos, ref, alt, ref_len, alt_len)
    assert set(dev) == set(host)
    for key in dev:
        np.testing.assert_array_equal(
            np.asarray(dev[key]), np.asarray(host[key]), err_msg=key
        )


def test_allele_hash_mesh_vs_np_twin():
    rng = np.random.default_rng(62)
    _pos, ref, alt, ref_len, alt_len = _allele_batch(rng, 301)
    dev = np.asarray(allele_hash_mesh(ref, alt, ref_len, alt_len))
    host = allele_hash_np(ref, alt, ref_len, alt_len)
    assert dev.dtype == host.dtype == np.uint32
    np.testing.assert_array_equal(dev, host)


def test_bin_index_kernel_mesh_vs_oracle_twin():
    rng = np.random.default_rng(63)
    starts = rng.integers(1, 200_000_000, 203).astype(np.int32)
    ends = (starts + rng.integers(0, 100_000, 203)).astype(np.int32)
    level, leaf = bin_index_kernel_mesh(starts, ends)
    level, leaf = np.asarray(level), np.asarray(leaf)
    for i in range(starts.shape[0]):
        want_level, want_leaf = closed_form_bin(int(starts[i]), int(ends[i]))
        assert (int(level[i]), int(leaf[i])) == (want_level, want_leaf)


def test_mark_batch_duplicates_mesh_vs_np_twin():
    rng = np.random.default_rng(64)
    pos, ref, alt, ref_len, alt_len = _allele_batch(rng, 229)
    # plant duplicate runs so the global sharded sort has real work
    pos[50:60] = pos[40]
    ref[50:60] = ref[40]
    alt[50:60] = alt[40]
    ref_len[50:60] = ref_len[40]
    alt_len[50:60] = alt_len[40]
    h = allele_hash_np(ref, alt, ref_len, alt_len)
    dev = np.asarray(
        mark_batch_duplicates_mesh(pos, h, ref, alt, ref_len, alt_len)
    )
    host = mark_batch_duplicates_np(pos, h, ref, alt, ref_len, alt_len)
    np.testing.assert_array_equal(dev, host)


def test_bits_spans_stacked_vs_host_twin():
    rng = np.random.default_rng(65)
    b, r, q = 8, 256, 32
    pos = np.sort(rng.integers(1, 2_000_000, (b, r)).astype(np.int32),
                  axis=1)
    pos[3, :] = POS_SENTINEL  # an empty (all-pad) group row
    starts = rng.integers(1, 2_000_000, (b, q)).astype(np.int32)
    ends = (starts + rng.integers(0, 50_000, (b, q))).astype(np.int32)
    dev = bits_spans_stacked_jit(pos, starts, ends)
    host = bits_spans_stacked_host(pos, starts, ends)
    for d, h, name in zip(dev, host, ("lo", "hi", "level", "leaf")):
        np.testing.assert_array_equal(np.asarray(d), np.asarray(h),
                                      err_msg=name)


# ---------------------------------------------------------------------------
# pack / transport


def test_pack_outputs_vs_np_twin():
    h = np.array([0x01020304, 0xFFFFFFFF, 0, 0xDEADBEEF], np.uint32)
    leaf = np.array([-1, 2**31 - 1, -(2**31), 1234], np.int32)
    level = np.array([0, 13, 7, 255], np.int32)
    t = np.array([True, False, True, False])
    dev = np.asarray(pack_outputs_jit(h, t, level, leaf, ~t, t))
    host = pack_outputs_np(h, t, level, leaf, ~t, t)
    np.testing.assert_array_equal(dev, host)
    # and the host-packed buffer unpacks exactly like the device one
    d_cols, h_cols = unpack_outputs(dev), unpack_outputs(host)
    for key in d_cols:
        np.testing.assert_array_equal(d_cols[key], h_cols[key], err_msg=key)


def test_inflate_alleles_vs_np_twin():
    probe = np.zeros((4, 7), np.uint8)
    probe[0, :5] = np.frombuffer(b"ACGTN", np.uint8)
    probe[1, :3] = np.frombuffer(b"acg", np.uint8)
    probe[2, :7] = np.frombuffer(b"*.-TGCA", np.uint8)
    probe[3, :1] = np.frombuffer(b"G", np.uint8)
    enc = encode_alleles_nibble(probe, probe[::-1].copy())
    assert enc is not None
    d_ref, d_alt = inflate_alleles_jit(enc[0], enc[1], 7)
    h_ref, h_alt = inflate_alleles_np(enc[0], enc[1], 7)
    np.testing.assert_array_equal(np.asarray(d_ref), h_ref)
    np.testing.assert_array_equal(np.asarray(d_alt), h_alt)
    np.testing.assert_array_equal(h_ref, probe)  # the round trip itself


def test_pack_vep_outputs_vs_np_twin():
    h = np.array([1, 0xCAFEBABE, 2**32 - 1], np.uint32)
    prefix = np.array([0, 3, 255], np.int32)
    fb = np.array([False, True, False])
    dev = np.asarray(pack_vep_outputs_jit(h, prefix, fb))
    host = pack_vep_outputs_np(h, prefix, fb)
    np.testing.assert_array_equal(dev, host)


def test_export_pack_vs_host_twin():
    """Corpus-export batch packing: elementwise int32/int8 arithmetic on
    both sides, so padded-lane masking and bin derivation are byte-exact
    (the corpus-level battery lives in tests/test_export.py)."""
    rng = np.random.RandomState(11)
    b, n_valid = 64, 41
    pos = np.sort(rng.randint(1, 3_000_000, b)).astype(np.int32)
    end = (pos + rng.randint(0, 8, b)).astype(np.int32)
    ref_code = rng.randint(0, 50, b).astype(np.int32)
    alt_code = rng.randint(0, 50, b).astype(np.int32)
    af_fp = rng.randint(-1, 10**6, b).astype(np.int32)
    cadd_fp = rng.randint(-1, 4000, b).astype(np.int32)
    rank_i = rng.randint(-1, 30, b).astype(np.int32)
    dev = export_pack_kernel_jit(pos, end, ref_code, alt_code, af_fp,
                                 cadd_fp, rank_i, n_valid)
    host = export_pack_host(pos, end, ref_code, alt_code, af_fp,
                            cadd_fp, rank_i, n_valid)
    names = ("mask", "bin_level", "leaf_bin", "pos", "ref_code",
             "alt_code", "af_fp", "cadd_fp", "rank_i")
    for d, h, name in zip(dev, host, names):
        d, h = np.asarray(d), np.asarray(h)
        assert d.dtype == h.dtype, name
        np.testing.assert_array_equal(d, h, err_msg=name)
    # padded lanes are uniformly dead on both sides
    assert not np.asarray(dev[0])[n_valid:].any()
    for lane in dev[1:]:
        assert (np.asarray(lane)[n_valid:] == -1).all()


# ---------------------------------------------------------------------------
# the registry stays audited by the static analyzer too


def test_static_rule_knows_these_kernels():
    """The analyzer's kernel discovery and this registry agree (a kernel
    added without a TWINS entry fails avdb_check as AVDB901; this pins
    the discovery side against the live tree)."""
    import os

    from annotatedvdb_tpu.analysis import run_paths
    from annotatedvdb_tpu.analysis.core import ProjectFacts, find_repo_root
    from annotatedvdb_tpu.analysis import rules_twins

    repo = find_repo_root(os.path.dirname(os.path.abspath(__file__)))
    ops_dir = os.path.join(repo, "annotatedvdb_tpu", "ops")
    findings, _n = run_paths([ops_dir], root=repo)
    assert [f for f in findings if f.code.startswith("AVDB9")] == [], [
        f.render() for f in findings
    ]
    # discovery sees exactly the registered kernels
    from annotatedvdb_tpu.analysis.core import FileContext, load_project

    facts = ProjectFacts()
    project = load_project(repo)
    for fn in sorted(os.listdir(ops_dir)):
        if fn.endswith(".py"):
            path = os.path.join(ops_dir, fn)
            with open(path, encoding="utf-8") as f:
                rules_twins.collect(
                    FileContext(path, f.read()), facts, project
                )
    discovered = {name for _p, _l, name in facts.ops_kernels}
    assert discovered == set(TWINS)
