"""ADSP QC pVCF update tests (reference ``update_from_qc_pvcf_file.py``)."""

import json
import subprocess
import sys

import numpy as np
import pytest

from annotatedvdb_tpu.loaders import TpuQcPvcfLoader, TpuVcfLoader
from annotatedvdb_tpu.store import AlgorithmLedger, VariantStore

BASE_VCF = """##fileformat=VCFv4.2
#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO
1\t100\t.\tA\tG\t.\t.\t.
1\t200\t.\tC\tT\t.\t.\t.
2\t100\t.\tT\tA\t.\t.\t.
"""

QC_VCF = """##fileformat=VCFv4.2
#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\tFORMAT
1\t100\t.\tA\tG\t50\tPASS\tABHet=0.5;AC=3\tGT:DP
1\t200\t.\tC\tT\t10\tLowQual\tAC=1\tGT
2\t500\t.\tG\tC\t99\tPASS\tAC=7\tGT
"""


def build_store(tmp_path):
    store = VariantStore(width=49)
    ledger = AlgorithmLedger(str(tmp_path / "ledger.jsonl"))
    vcf = tmp_path / "base.vcf"
    vcf.write_text(BASE_VCF)
    TpuVcfLoader(store, ledger, log=lambda *a: None).load_file(str(vcf), commit=True)
    return store, ledger


def find_row(store, code, pos):
    shard = store.shard(code)
    i = int(np.searchsorted(shard.cols["pos"], pos))
    assert shard.cols["pos"][i] == pos
    return shard, i


def test_qc_update_and_novel_insert(tmp_path):
    store, ledger = build_store(tmp_path)
    qc = tmp_path / "qc.vcf"
    qc.write_text(QC_VCF)
    loader = TpuQcPvcfLoader(store, ledger, "r4", log=lambda *a: None)
    counters = loader.load_file(str(qc), commit=True)
    assert counters["update"] == 2
    assert store.n == 4  # novel 2:500 G>C inserted

    shard, i = find_row(store, 1, 100)
    qc_ann = shard.annotations["adsp_qc"][i]
    assert qc_ann == {
        "r4": {"info": {"ABHet": 0.5, "AC": 3}, "filter": "PASS",
               "qual": "50", "format": "GT:DP"}
    }
    assert shard.cols["is_adsp_variant"][i] == 1  # PASS -> true

    # LowQual row: flag stays NULL (-1), not false (reference :139)
    shard, i = find_row(store, 1, 200)
    assert shard.cols["is_adsp_variant"][i] == -1
    assert shard.annotations["adsp_qc"][i]["r4"]["filter"] == "LowQual"

    # novel insert got QC values + PASS flag
    shard, i = find_row(store, 2, 500)
    assert shard.cols["is_adsp_variant"][i] == 1
    assert shard.annotations["adsp_qc"][i]["r4"]["qual"] == "99"
    assert shard.cols["h"][i] != 0  # full insert path (identity hash assigned)

    # untouched row keeps NULL qc
    shard, i = find_row(store, 2, 100)
    assert shard.annotations["adsp_qc"][i] is None


def test_qc_skip_existing_release_and_merge(tmp_path):
    store, ledger = build_store(tmp_path)
    qc = tmp_path / "qc.vcf"
    qc.write_text(QC_VCF)
    TpuQcPvcfLoader(store, ledger, "r4", log=lambda *a: None).load_file(
        str(qc), commit=True
    )
    # same release again: all known rows skipped
    c2 = TpuQcPvcfLoader(store, ledger, "r4", log=lambda *a: None).load_file(
        str(qc), commit=True
    )
    assert c2["update"] == 0 and c2["skipped"] == 3

    # new release merges alongside the old one (jsonb_merge semantics)
    c3 = TpuQcPvcfLoader(store, ledger, "r5", log=lambda *a: None).load_file(
        str(qc), commit=True
    )
    assert c3["update"] == 3
    shard, i = find_row(store, 1, 100)
    assert set(shard.annotations["adsp_qc"][i]) == {"r4", "r5"}

    # --updateExistingValues forces the update
    c4 = TpuQcPvcfLoader(
        store, ledger, "r4", update_existing=True, log=lambda *a: None
    ).load_file(str(qc), commit=True)
    assert c4["update"] == 3


def test_qc_infinity_rejected(tmp_path):
    store, ledger = build_store(tmp_path)
    qc = tmp_path / "qc.vcf"
    qc.write_text(
        "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\tFORMAT\n"
        "1\t100\t.\tA\tG\t50\tPASS\tAB=Infinity\tGT\n"
    )
    loader = TpuQcPvcfLoader(store, ledger, "r4", log=lambda *a: None)
    with pytest.raises(ValueError, match="Infinity"):
        loader.load_file(str(qc), commit=True)


def test_qc_dry_run(tmp_path):
    store, ledger = build_store(tmp_path)
    qc = tmp_path / "qc.vcf"
    qc.write_text(QC_VCF)
    counters = TpuQcPvcfLoader(store, ledger, "r4", log=lambda *a: None).load_file(
        str(qc), commit=False
    )
    assert counters["update"] == 2
    assert store.n == 3  # no insert
    shard, i = find_row(store, 1, 100)
    assert shard.annotations["adsp_qc"][i] is None


def test_qc_cli(tmp_path):
    store, ledger = build_store(tmp_path)
    store_dir = tmp_path / "vdb"
    store.save(str(store_dir))
    qc = tmp_path / "qc.vcf"
    qc.write_text(QC_VCF)
    res = subprocess.run(
        [sys.executable, "-m", "annotatedvdb_tpu.cli.update_qc",
         "--fileName", str(qc), "--storeDir", str(store_dir),
         "--version", "r4", "--commit"],
        capture_output=True, text=True, check=True,
    )
    counters = json.loads(res.stdout.splitlines()[0])
    assert counters["update"] == 2
    reloaded = VariantStore.load(str(store_dir))
    assert reloaded.n == 4
    shard, i = find_row(reloaded, 1, 100)
    assert shard.annotations["adsp_qc"][i]["r4"]["filter"] == "PASS"


def test_info_to_json_parity():
    """info_to_json must emit JSON that parses to exactly parse_info's
    dict, for every token class (fast paths AND fallbacks)."""
    import json

    import pytest

    from annotatedvdb_tpu.io.vcf import info_to_json, parse_info

    cases = [
        "ABHet=0.5;AC=3",
        "RS=12;RSPOS=100;FREQ=GnomAD:0.5,0.25|TOPMED:.,0.1",
        "DP=100;VDB=1.3e-2;INDEL;MQ0F=0",
        "K=007;NEG=-5;PLUS=+12;UND=1_0",
        "S=INDEL;T=NA;U=GT:DP;EMPTY=;DOT=.",
        "WS= 12 ;TAB=\t3\t",
        "ESC=a\\x2cb;HASH=a#b;SLASH=c\\x59d",
        'QUOTE="x";BACK=a\\b',
        "BIG=123456789012345678901234567890",
        "F=.5;G=5.;H=1e3;I=-1.5E-3",
        "MIXED=12ab;UNI=é",
        "NANISH=nankeeper;INFY=infinite",  # prefixes, NOT float words
    ]
    for s in cases:
        assert json.loads(info_to_json(s)) == parse_info(s), s
    for bad in ("X=inf", "X=Infinity", "X=nan", "X=NaN", "X=-inf",
                "X= inf ", "X=1e400", "X=-1e999"):
        with pytest.raises(ValueError):
            info_to_json(bad)
    # trailing-newline values must not splice control characters (or dodge
    # the abort) via '$'-anchor newline matching
    assert json.loads(info_to_json("X=abc\n")) == parse_info("X=abc\n")
    assert json.loads(info_to_json("X=5\n")) == parse_info("X=5\n")
    with pytest.raises(ValueError):
        info_to_json("X=inf\n")


def test_info_to_json_duplicate_keys_byte_parity():
    """Repeated INFO keys (malformed but occurring in the wild) must
    de-duplicate last-wins at first position — BYTE-identical to the
    parse_info + json.dumps fallback, so persisted raw text never diverges
    between the fast path and the dict path (ADVICE r5 #4)."""
    import json

    from annotatedvdb_tpu.io.vcf import info_to_json, parse_info

    cases = [
        "AC=1;AC=2",                      # simple last-wins
        "AC=1;DP=9;AC=2",                 # position = first occurrence
        "FLAG;FLAG",                      # repeated bare flag
        "AC;AC=3",                        # flag then pair, same key
        "AC=3;AC",                        # pair then flag
        "A=1;B=2;A=x;C=3;B=0.5",          # interleaved, type changes
        "X=1;X=1e400;X=2",                # overflowing middle replaced
    ]
    for s in cases:
        fast = info_to_json(s)
        exact = json.dumps(
            parse_info(s), separators=(",", ":"), allow_nan=False
        )
        assert fast == exact, (s, fast, exact)
