"""Shipped chromosome-length assets + the bounds checks they drive."""

import subprocess
import sys

import numpy as np

from annotatedvdb_tpu.genome.assemblies import (
    chromosome_lengths,
    genome_length,
    length_table,
)


def test_shipped_builds_load():
    for build in ("GRCh38", "hg19", "GRCh37", "hg38"):
        lengths = chromosome_lengths(build)
        assert len(lengths) == 25
        assert lengths[25] == 16569  # chrM is build-invariant
    # reference-parity spot checks against Load/data/hg19_chr_map.txt:1-25
    hg19 = chromosome_lengths("hg19")
    assert hg19[1] == 249250621 and hg19[22] == 51304566
    assert hg19[23] == 155270560 and hg19[24] == 59373566
    grch38 = chromosome_lengths("GRCh38")
    assert grch38[1] == 248956422 and grch38[22] == 50818468
    assert genome_length("GRCh38") > 3_000_000_000


def test_length_table_pads_safe():
    t = length_table("GRCh38")
    assert t.shape == (26,)
    assert t[0] == np.iinfo(np.int64).max  # pad code never out-of-bounds
    assert t[21] == chromosome_lengths("GRCh38")[21]


def test_loader_flags_out_of_bounds(tmp_path):
    from annotatedvdb_tpu.loaders import TpuVcfLoader
    from annotatedvdb_tpu.store import AlgorithmLedger, VariantStore

    vcf = tmp_path / "oob.vcf"
    vcf.write_text(
        "##fileformat=VCFv4.2\n"
        "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\n"
        "21\t1000\t.\tA\tC\t.\t.\t.\n"
        "21\t999999999\t.\tG\tT\t.\t.\t.\n"  # beyond chr21 (46.7Mb)
    )
    logs = []
    store = VariantStore(width=49)
    ledger = AlgorithmLedger(str(tmp_path / "l.jsonl"))
    loader = TpuVcfLoader(store, ledger, log=lambda *a: logs.append(a))
    counters = loader.load_file(str(vcf), commit=True)
    assert counters["out_of_bounds"] == 1
    assert counters["variant"] == 2  # flagged, not dropped
    assert any("beyond chromosome bounds" in str(l) for l in logs)


def test_bin_ref_cli_defaults_to_shipped_build(tmp_path):
    out = tmp_path / "bins.tsv"
    res = subprocess.run(
        [sys.executable, "-m",
         "annotatedvdb_tpu.cli.generate_bin_index_references",
         "--genomeBuild", "hg19", "-o", str(out)],
        capture_output=True, text=True, timeout=300,
    )
    assert res.returncode == 0, res.stderr
    first = out.read_text().split("\n", 1)[0].split("\t")
    assert first[0] == "chr1" and first[4] == "(0,249250621]"
    assert "25 chromosomes" in res.stderr
