"""Resilient-serving battery: deadline propagation (admission / batcher
queue / executor sheds, 504 mapping, slot release under a full queue),
the brownout ladder (governor state machine, region limit caps,
cache-first points, bulk/region shedding, liveness-vs-readiness split),
the device circuit breaker (trip/half-open/re-close, snapshot swap while
open), the SIGTERM-vs-stream drain fix, and the /_chaos arming route."""

from __future__ import annotations

import json
import socket
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from annotatedvdb_tpu.serve import (
    DeadlineExceeded,
    DeviceBreaker,
    OverloadGovernor,
    QueryBatcher,
    QueryEngine,
    SnapshotManager,
    StaticSnapshots,
)
from annotatedvdb_tpu.serve import resilience
from annotatedvdb_tpu.store import VariantStore
from annotatedvdb_tpu.utils import faults
from test_serve import _build_store, _commit_more_rows, _vid


@pytest.fixture(autouse=True)
def _disarm():
    yield
    faults.reset("")


# ---------------------------------------------------------------------------
# fixtures


@pytest.fixture(scope="module")
def store(tmp_path_factory):
    store_dir = str(tmp_path_factory.mktemp("resil_store"))
    truth = _build_store(store_dir)
    return store_dir, truth


def _wide_store(n: int = 2000) -> VariantStore:
    """One chr8 segment with n rows — enough that the brownout region cap
    (256) and chunked streaming both actually bite."""
    from annotatedvdb_tpu.loaders.lookup import identity_hashes
    from annotatedvdb_tpu.types import encode_allele_array

    width = 8
    store = VariantStore(width=width)
    refs = ["A", "C"] * (n // 2)
    alts = ["G", "T"] * (n // 2)
    ref, ref_len = encode_allele_array(refs, width)
    alt, alt_len = encode_allele_array(alts, width)
    store.shard(8).append(
        {"pos": np.arange(1000, 1000 + 7 * n, 7, dtype=np.int32)[:n],
         "h": identity_hashes(width, ref, alt, ref_len, alt_len, refs, alts),
         "ref_len": ref_len, "alt_len": alt_len},
        ref, alt,
        annotations={"info": [{"p": "x" * 64} for _ in range(n)]},
    )
    return store


def _get(port: int, path: str, headers=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", headers=headers or {}
    )
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, r.read().decode(), dict(r.headers)
    except urllib.error.HTTPError as err:
        return err.code, err.read().decode(), dict(err.headers)


def _post(port: int, path: str, payload: bytes, headers=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=payload, method="POST",
        headers=headers or {},
    )
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as err:
        return err.code, err.read().decode()


# ---------------------------------------------------------------------------
# OverloadGovernor: the ladder state machine (injected clock + depth)


class _Sim:
    def __init__(self):
        self.t = 0.0
        self.depth = 0

    def governor(self, **kw):
        return OverloadGovernor(
            depth_fn=lambda: self.depth, max_queue=100,
            p99_target_s=0.1, clock=lambda: self.t,
            eval_interval_s=0.1, hold_s=0.5, **kw,
        )


def test_governor_escalates_one_level_per_eval_on_depth():
    sim = _Sim()
    g = sim.governor()
    sim.depth = 80  # 0.8 of the bound: hot
    for want in (1, 2, 3, 3):  # one level per evaluation, capped at 3
        sim.t += 0.11
        assert g.maybe_step() == want
    assert g.shed_bulk() and g.cache_first()
    assert g.region_limit_cap() == resilience.BROWNOUT_REGION_LIMIT


def test_governor_latency_exceedance_escalates():
    sim = _Sim()
    g = sim.governor()
    for _ in range(100):
        g.note_latency(0.5)  # 5x the target: exceedance ewma saturates
    sim.t += 0.11
    assert g.maybe_step() == 1


def test_governor_hysteresis_holds_then_deescalates():
    sim = _Sim()
    g = sim.governor()
    sim.depth = 80
    sim.t += 0.11
    assert g.maybe_step() == 1
    sim.depth = 0  # instantly calm — but the hold must out-wait flapping
    sim.t += 0.11
    assert g.maybe_step() == 1  # inside hold_s: stays up
    sim.t += 0.6
    assert g.maybe_step() == 0  # past hold: steps down


def test_governor_idle_decay_releases_latency_signal():
    sim = _Sim()
    g = sim.governor()
    for _ in range(100):
        g.note_latency(0.5)
    sim.t += 0.11
    assert g.maybe_step() == 1
    # no further samples: the ewma halves per idle eval until calm
    level = 1
    for _ in range(20):
        sim.t += 0.6
        level = g.maybe_step()
        if level == 0:
            break
    assert level == 0


# ---------------------------------------------------------------------------
# deadline: batcher-queue shedding under a FULL queue (satellite)


class _GatedEngine:
    """lookup_many blocks until released — a drain in progress while the
    queue fills behind it."""

    def __init__(self):
        self.gate = threading.Event()
        self.calls = 0

    def lookup_many(self, ids, parsed=None):
        self.calls += 1
        assert self.gate.wait(10), "test gate never released"
        return [None] * len(ids)


def test_deadline_shed_under_full_queue_releases_admission_slots():
    engine = _GatedEngine()
    batcher = QueryBatcher(engine, max_batch=1, max_wait_s=0.0, max_queue=4)
    try:
        # drain 1 picks up the first pending and blocks in the engine
        first = batcher.submit_nowait("3:10:A:C")
        deadline = time.monotonic() + 2
        while batcher.depth() > 0 and time.monotonic() < deadline:
            time.sleep(0.005)
        # the queue fills with requests whose budget dies immediately
        dead = [
            batcher.submit_nowait(
                "3:10:A:C", deadline_t=time.monotonic() + 0.01
            )
            for _ in range(4)
        ]
        # admission bound reached: the 429 path still works
        from annotatedvdb_tpu.serve import QueueFull

        with pytest.raises(QueueFull):
            batcher.submit_nowait("3:10:A:C")
        time.sleep(0.05)  # every queued deadline lapses
        engine.gate.set()
        # the shed drains release their queue slots and fail their callers
        # with the honest cause
        for pending in dead:
            assert pending.done.wait(5)
            assert isinstance(pending.error, DeadlineExceeded)
        assert first.done.wait(5) and first.error is None
        # slots released: a fresh submission is admitted AND served
        assert batcher.submit("3:10:A:C") is None
        # the shed batch never reached the engine: exactly the first
        # drain and the fresh one executed
        assert engine.calls == 2
    finally:
        engine.gate.set()
        batcher.close()


def test_blocking_submit_surfaces_deadline_exceeded():
    engine = _GatedEngine()
    batcher = QueryBatcher(engine, max_batch=1, max_wait_s=0.0, max_queue=8)
    try:
        batcher.submit_nowait("3:10:A:C")  # occupies the drain thread
        time.sleep(0.02)
        with pytest.raises(DeadlineExceeded):
            batcher.submit("3:10:A:C",
                           deadline_t=time.monotonic() + 0.05)
    finally:
        engine.gate.set()
        batcher.close()


# ---------------------------------------------------------------------------
# deadline: HTTP 504 end-to-end on BOTH front ends


def _deadline_server(kind: str, store_dir: str):
    """A server whose batcher waits 80ms before draining: a 10ms request
    deadline deterministically lapses in the queue."""
    if kind == "aio":
        from annotatedvdb_tpu.serve.aio import build_aio_server

        server = build_aio_server(
            store_dir=store_dir, port=0, max_wait_s=0.08
        )
        server.start_background()
        return server, server.server_address[1], server
    from annotatedvdb_tpu.serve.http import build_server

    httpd = build_server(store_dir=store_dir, port=0, max_wait_s=0.08)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd, httpd.server_address[1], None


@pytest.mark.parametrize("kind", ["threaded", "aio"])
def test_point_deadline_maps_to_504_and_counter(store, kind):
    store_dir, truth = store
    server, port, aio = _deadline_server(kind, store_dir)
    try:
        vid = _vid(truth[0])
        # generous deadline: served normally
        status, _body, _ = _get(port, f"/variant/{vid}",
                                headers={"X-Deadline-Ms": "5000"})
        assert status == 200
        # a 10ms budget dies in the 80ms batch-wait window: shed as 504
        status, body, _ = _get(port, f"/variant/{vid}",
                               headers={"X-Deadline-Ms": "10"})
        assert status == 504, body
        assert "deadline" in body
        # the 504 races the drain's shed by design (the caller stops
        # waiting first): poll until the batcher-side counter lands
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            _s, metrics, _h = _get(port, "/metrics")
            if 'avdb_deadline_shed_total{stage="batcher"} 1' in metrics:
                break
            time.sleep(0.05)
        assert 'avdb_deadline_shed_total{stage="batcher"} 1' in metrics
    finally:
        if kind == "aio":
            server.shutdown()
        else:
            server.shutdown()
            server.server_close()
        server.ctx.batcher.close()


# ---------------------------------------------------------------------------
# brownout ladder end-to-end (forced levels; both front ends)


@pytest.fixture()
def ladder_servers():
    """Both front ends over the wide store (region cap must bite)."""
    from annotatedvdb_tpu.serve.aio import build_aio_server
    from annotatedvdb_tpu.serve.http import build_server

    wide = _wide_store()
    aio = build_aio_server(manager=StaticSnapshots(wide), port=0)
    aio.start_background()
    httpd = build_server(manager=StaticSnapshots(wide), port=0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        yield aio, httpd
    finally:
        aio.shutdown()
        httpd.shutdown()
        httpd.server_close()
        aio.ctx.batcher.close()
        httpd.ctx.batcher.close()


def _ports(ladder_servers):
    aio, httpd = ladder_servers
    return ((aio.ctx, aio.server_address[1]),
            (httpd.ctx, httpd.server_address[1]))


def test_brownout_level1_caps_region_limits(ladder_servers):
    for ctx, port in _ports(ladder_servers):
        status, body, _ = _get(port, "/region/8:1-100000?limit=2000")
        assert status == 200 and json.loads(body)["returned"] == 2000
        ctx.governor.force_level(1)
        try:
            status, body, _ = _get(port, "/region/8:1-100000?limit=2000")
            assert status == 200
            assert json.loads(body)["returned"] \
                == resilience.BROWNOUT_REGION_LIMIT
        finally:
            ctx.governor.force_level(0)


def test_brownout_level2_serves_points_cache_first(ladder_servers):
    for ctx, port in _ports(ladder_servers):
        # level 0 populates the id-keyed cache (hit and miss both cache)
        s1, cached_body, _ = _get(port, "/variant/8:1000:A:G")
        assert s1 == 200
        s2, _b, _ = _get(port, "/variant/8:999:A:G")
        assert s2 == 404
        ctx.governor.force_level(2)
        real = ctx.engine.lookup_many

        def boom(ids, parsed=None):
            raise RuntimeError("engine must not be consulted")

        ctx.engine.lookup_many = boom
        try:
            # cached id answers without touching the (broken) engine —
            # byte-identical to the level-0 response
            status, body, _ = _get(port, "/variant/8:1000:A:G")
            assert (status, body) == (200, cached_body)
            status, _body, _ = _get(port, "/variant/8:999:A:G")
            assert status == 404  # cached absence is absence
            # an UNcached id still goes to the engine (and fails here)
            status, _body, _ = _get(port, "/variant/8:1001:C:T")
            assert status == 500
        finally:
            ctx.engine.lookup_many = real
            ctx.governor.force_level(0)


def test_brownout_level3_sheds_bulk_region_keeps_points(ladder_servers):
    for ctx, port in _ports(ladder_servers):
        ctx.governor.force_level(3)
        try:
            status, body, headers = _get(port, "/region/8:1-100000")
            assert status == 503 and "brownout" in body
            assert headers.get("Retry-After") == "1"
            status, body = _post(
                port, "/variants",
                json.dumps({"ids": ["8:1000:A:G"]}).encode(),
            )
            assert status == 503 and "brownout" in body
            # the traffic that matters keeps serving
            status, _body, _ = _get(port, "/variant/8:1000:A:G")
            assert status == 200
            # readiness flips (liveness stays 200); re-pin the level
            # right before the probes — health polls legitimately step
            # the ladder, and a slow test run must not race the hold
            ctx.governor.force_level(3)
            status, body, _ = _get(port, "/readyz")
            assert status == 503 and not json.loads(body)["ready"]
            ctx.governor.force_level(3)
            status, body, _ = _get(port, "/healthz")
            assert status == 200
            h = json.loads(body)
            assert h["brownout_level"] == 3 and h["ready"] is False
        finally:
            ctx.governor.force_level(0)
        status, _body, _ = _get(port, "/readyz")
        assert status == 200


def test_health_polls_deescalate_a_fully_drained_worker(ladder_servers):
    """A shed_bulk worker a router has DRAINED completes no requests —
    on the threaded front end the router's own readiness probes must be
    enough for the idle ladder to step back down to ready (the aio front
    end additionally has its maintenance tick)."""
    for ctx, port in _ports(ladder_servers):
        g = ctx.governor
        old_interval, old_hold = g.eval_interval_s, g.hold_s
        g.eval_interval_s = 0.0
        g.hold_s = 0.0
        g.force_level(3)
        try:
            status = None
            for _ in range(10):  # readiness probes ONLY, no data traffic
                status, _body, _ = _get(port, "/readyz")
                if status == 200:
                    break
                # a pre-existing eval window (set before the test shrank
                # the interval) may still be open: pace the probes like a
                # real router would
                time.sleep(0.3)
            assert status == 200
            assert g.level < 3  # readiness returns as soon as shed_bulk clears
            # and continued probes unwind the ladder all the way down
            for _ in range(10):
                if g.level == 0:
                    break
                _get(port, "/readyz")
                time.sleep(0.15)
            assert g.level == 0
        finally:
            g.eval_interval_s, g.hold_s = old_interval, old_hold
            g.force_level(0)


def test_healthz_and_readyz_parity_across_front_ends(ladder_servers):
    aio, httpd = ladder_servers
    ap, tp = aio.server_address[1], httpd.server_address[1]
    for path in ("/healthz", "/readyz"):
        astatus, abody, _ = _get(ap, path)
        tstatus, tbody, _ = _get(tp, path)
        assert (astatus, abody) == (tstatus, tbody), path


def test_snapshot_manager_reports_swapping_during_generation_load(
        tmp_path, monkeypatch):
    """The REAL readiness signal: while refresh() loads a new generation
    the manager reports ``swapping`` (readyz 503), and the flag clears
    whether the swap lands or fails."""
    store_dir = str(tmp_path / "swapstore")
    _build_store(store_dir)
    manager = SnapshotManager(store_dir)
    assert manager.swapping is False
    _commit_more_rows(store_dir)
    seen = {}
    real_load = VariantStore.load

    def spy(d, readonly=False):
        seen["during_load"] = manager.swapping
        return real_load(d, readonly=readonly)

    monkeypatch.setattr(VariantStore, "load", spy)
    assert manager.refresh() is True
    assert seen["during_load"] is True
    assert manager.swapping is False
    # a FAILED swap (snapshot.swap raise) must clear the flag too
    _commit_more_rows(store_dir)
    faults.reset("snapshot.swap:1:raise")
    with pytest.raises(Exception):
        manager.refresh()
    assert manager.swapping is False


def test_readyz_not_ready_during_snapshot_swap(ladder_servers):
    aio, _httpd = ladder_servers
    port = aio.server_address[1]
    manager = aio.ctx.manager
    manager.swapping = True  # StaticSnapshots: simulate a loading swap
    try:
        status, body, _ = _get(port, "/readyz")
        assert status == 503
        assert "swap" in json.loads(body)["reason"]
    finally:
        manager.swapping = False
    status, _body, _ = _get(port, "/readyz")
    assert status == 200


# ---------------------------------------------------------------------------
# circuit breaker: snapshot swap arriving while OPEN (satellite)


def test_snapshot_swap_while_breaker_open_serves_host_then_recloses(
        tmp_path, store):
    store_dir, truth = store
    clock = {"t": 0.0}
    manager = SnapshotManager(store_dir)
    breaker = DeviceBreaker(cooldown_s=5.0, clock=lambda: clock["t"])
    engine = QueryEngine(manager, region_cache_size=0, breaker=breaker)
    vid = _vid(truth[0])
    want = engine.lookup(vid)
    assert want is not None

    # trip the breaker for this id's chromosome group
    faults.reset("engine.device_probe:prob:1.0:eio")
    code = truth[0]["chrom"]
    for _ in range(breaker.failure_threshold):
        assert engine.lookup(vid) == want
    assert breaker.state(code) == "open"

    # a loader commit lands and swaps in WHILE the breaker is open: the
    # new generation must serve (host path) immediately — including rows
    # only the new generation has — with the breaker still open
    _commit_more_rows(store_dir)  # appends 8:5000000+11i A->C rows
    assert manager.refresh() is True
    assert breaker.state(code) == "open"
    assert engine.lookup(vid) == want  # old row: byte-stable across gens
    got = engine.lookup("8:5000000:A:C")
    assert got is not None and '"position":5000000' in got

    # fault gone + cooldown over: the new generation re-probes the device
    # path half-open and re-closes
    faults.reset("")
    clock["t"] = 100.0
    assert engine.lookup(vid) == want
    assert breaker.state(code) == "closed"


# ---------------------------------------------------------------------------
# SIGTERM drain vs in-flight chunked stream (satellite regression)


def _dechunk(raw: bytes) -> tuple[bytes, bool]:
    """(body, saw_terminator) from a chunked-encoded byte stream."""
    body = b""
    saw_end = False
    while raw:
        line, _, rest = raw.partition(b"\r\n")
        size = int(line, 16)
        if size == 0:
            saw_end = True
            break
        body += rest[:size]
        raw = rest[size + 2:]
    return body, saw_end


def test_drain_mid_stream_truncates_cleanly_with_trailer():
    from annotatedvdb_tpu.serve.aio import build_aio_server

    wide = _wide_store(6000)
    server = build_aio_server(
        manager=StaticSnapshots(wide), port=0, stream_threshold=4
    )
    server.drain_s = 2.0
    server.start_background()
    port = server.server_address[1]
    sock = socket.create_connection(("127.0.0.1", port), timeout=10)
    received = bytearray()
    done = threading.Event()

    def read_slowly():
        # a slow consumer: the server MUST be mid-stream when the drain
        # starts (the whole 1MB+ body cannot fit the socket buffers)
        try:
            while True:
                chunk = sock.recv(2048)
                if not chunk:
                    break
                received.extend(chunk)
                time.sleep(0.005)
        except OSError:
            pass
        finally:
            done.set()

    try:
        sock.sendall(b"GET /region/8:1-100000 HTTP/1.1\r\nHost: t\r\n\r\n")
        reader = threading.Thread(target=read_slowly, daemon=True)
        reader.start()
        deadline = time.monotonic() + 10
        while len(received) < 4096 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert len(received) >= 4096, "stream never started"
        server.shutdown()  # SIGTERM-equivalent drain, stream in flight
        assert done.wait(30), "client never saw the stream end"
    finally:
        sock.close()
        server.ctx.batcher.close()

    head, _, rest = bytes(received).partition(b"\r\n\r\n")
    assert b"200 OK" in head and b"chunked" in head
    body, saw_end = _dechunk(rest)
    # the framing terminated properly (no torn chunk), and the body is
    # VALID JSON that says whether it was cut short
    assert saw_end, "chunked framing was torn (no terminating 0-chunk)"
    doc = json.loads(body)
    if len(doc["variants"]) < doc["count"]:
        assert doc.get("truncated") is True
    else:
        assert doc["returned"] == doc["count"]


# ---------------------------------------------------------------------------
# /_chaos runtime arming route


def test_chaos_route_is_gated_and_arms_with_ttl(store, monkeypatch):
    from annotatedvdb_tpu.serve.aio import build_aio_server

    store_dir, _truth = store
    # gate OFF: the route does not exist
    server = build_aio_server(store_dir=store_dir, port=0)
    server.start_background()
    try:
        status, body = _post(server.server_address[1], "/_chaos",
                             b'{"spec": "serve.batch:1:raise"}')
        assert status == 404
    finally:
        server.shutdown()
        server.ctx.batcher.close()

    # gate ON: arms in-process, ttl auto-disarms
    monkeypatch.setenv("AVDB_SERVE_CHAOS", "1")
    server = build_aio_server(store_dir=store_dir, port=0)
    server.start_background()
    try:
        port = server.server_address[1]
        status, body = _post(
            port, "/_chaos",
            json.dumps({"spec": "serve.batch:1:raise",
                        "ttl_s": 0.2}).encode(),
        )
        assert status == 200 and json.loads(body)["armed"] \
            == "serve.batch:1:raise"
        assert faults.armed_point() == "serve.batch"
        deadline = time.monotonic() + 5
        while faults.armed_point() is not None \
                and time.monotonic() < deadline:
            time.sleep(0.05)
        assert faults.armed_point() is None  # ttl disarmed it
        status, body = _post(port, "/_chaos", b'{"spec": "nope:1"}')
        assert status == 400
        # a malformed ttl must refuse BEFORE arming (a fault armed with
        # its promised auto-disarm missing is the dangerous outcome)
        status, _body = _post(
            port, "/_chaos",
            b'{"spec": "serve.batch:1:raise", "ttl_s": "bogus"}',
        )
        assert status == 400
        assert faults.armed_point() is None
        # non-object bodies are 400, not a dropped connection
        status, _body = _post(port, "/_chaos", b"[1, 2]")
        assert status == 400
        # a stale ttl timer must not disarm a NEWER arming
        status, _body = _post(
            port, "/_chaos",
            json.dumps({"spec": "serve.batch:1:raise",
                        "ttl_s": 0.2}).encode(),
        )
        assert status == 200
        status, _body = _post(
            port, "/_chaos",
            json.dumps({"spec": "serve.accept:1:raise"}).encode(),
        )
        assert status == 200
        time.sleep(0.5)  # the first arm's ttl fires into the second arm
        assert faults.armed_point() == "serve.accept"
    finally:
        server.shutdown()
        server.ctx.batcher.close()
        faults.reset("")
