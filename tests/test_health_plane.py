"""The health plane (PR 17): metrics time-series history, SLO burn-rate
alerting, and their serving surfaces.

Layers under test, bottom up: bucket-quantile estimation against numpy
ground truth; the sample arithmetic (counter deltas/rates, histogram
window deltas, fraction-above interpolation); the snapshot ring with its
persisted mirror, harvest, and replay; the SLO state machines
(multi-window burn + ok -> pending -> firing -> resolved hysteresis);
and the ``/alerts`` + ``/metrics/history`` routes byte-identical across
both front ends, with the fleet views and ``doctor slo`` on top."""

import json
import threading
import time

import numpy as np
import pytest

from annotatedvdb_tpu.obs.metrics import MetricsRegistry, bucket_quantile
from annotatedvdb_tpu.obs.slo import (
    HealthPlane,
    SloRegistry,
    SloSpec,
    default_slos,
    fraction_above,
    replay_history,
    worst_of,
)
from annotatedvdb_tpu.obs.timeseries import (
    TimeSeriesRing,
    counter_delta,
    counter_rate,
    derive_series,
    harvest,
    histogram_window,
    history_path,
    list_history,
    load_history,
    trailing_samples,
    window_samples,
)

# ---------------------------------------------------------------------------
# quantile estimation (the satellite: pinned against numpy)


EDGES = tuple(round(0.1 * i, 1) for i in range(1, 101))  # 0.1 .. 10.0


def test_histogram_quantile_matches_numpy_within_bucket_width():
    rng = np.random.default_rng(7)
    vals = rng.uniform(0.0, 9.5, size=2_000)
    reg = MetricsRegistry()
    h = reg.histogram("t_q", EDGES, "test")
    for v in vals:
        h.observe(float(v))
    for q in (0.1, 0.5, 0.9, 0.99):
        est = h.quantile(q)
        truth = float(np.percentile(vals, q * 100))
        # bucket interpolation cannot beat the bucket width
        assert abs(est - truth) <= 0.1 + 1e-9, (q, est, truth)


def test_histogram_quantile_open_top_bucket_returns_max_edge():
    reg = MetricsRegistry()
    h = reg.histogram("t_top", (0.1, 1.0), "test")
    for _ in range(10):
        h.observe(50.0)  # all land in the +Inf tail
    # the honest answer is "at least the highest finite edge"
    assert h.quantile(0.5) == 1.0
    assert h.quantile(0.99) == 1.0


def test_histogram_quantile_empty_is_none_and_bad_q_raises():
    reg = MetricsRegistry()
    h = reg.histogram("t_empty", (0.1, 1.0), "test")
    assert h.quantile(0.5) is None
    with pytest.raises(ValueError):
        bucket_quantile((0.1,), [0, 0], 0, 1.5)
    # malformed counts row (length mismatch) is a no-answer, not a crash
    assert bucket_quantile((0.1, 1.0), [1, 2], 3, 0.5) is None


# ---------------------------------------------------------------------------
# sample arithmetic


def _counter_sample(t: float, name: str, value: float,
                    labels: dict | None = None) -> dict:
    return {"t": t, "metrics": {
        name: [{"kind": "counter", "labels": labels or {},
                "value": value}],
    }}


def test_counter_delta_and_rate_clamp_worker_restart():
    a = _counter_sample(100.0, "reqs", 500.0)
    b = _counter_sample(110.0, "reqs", 550.0)
    assert counter_delta(a, b, "reqs") == 50.0
    assert counter_rate(a, b, "reqs") == 5.0
    # a respawned worker restarts its counters: negative delta is a
    # restart, not negative work
    c = _counter_sample(120.0, "reqs", 30.0)
    assert counter_delta(b, c, "reqs") == 0.0
    # absent metric in the newer sample = no judgment
    assert counter_delta(a, {"t": 130.0, "metrics": {}}, "reqs") is None


def test_histogram_window_is_the_delta_histogram():
    def hsample(t, counts, count):
        return {"t": t, "metrics": {"lat": [
            {"kind": "histogram", "labels": {}, "edges": [0.1, 1.0],
             "counts": counts, "count": count},
        ]}}

    first = hsample(0.0, [5, 1, 0], 6)
    last = hsample(10.0, [15, 3, 2], 20)
    edges, counts, count = histogram_window(first, last, "lat")
    assert edges == [0.1, 1.0]
    assert counts == [10, 2, 2]
    assert count == 14


def test_fraction_above_interpolates_inside_the_split_bucket():
    edges, counts, count = (0.1, 1.0), [8, 2, 0], 10
    # threshold on an edge: everything in the upper buckets is above
    assert fraction_above(edges, counts, count, 0.1) == pytest.approx(0.2)
    # threshold splitting the first bucket (0..0.1): linear share above
    assert fraction_above(edges, counts, count, 0.05) == pytest.approx(0.6)
    # +Inf tail is always above
    assert fraction_above((0.1,), [0, 4], 4, 0.1) == 1.0
    assert fraction_above(edges, [0, 0, 0], 0, 0.1) is None


def test_window_samples_bracketing():
    samples = [{"t": float(t)} for t in range(10)]
    first, last = window_samples(samples, 3.0)
    assert (first["t"], last["t"]) == (6.0, 9.0)
    # a young ring spans less than the window: the honest span it has
    first, last = window_samples(samples[:2], 60.0)
    assert (first["t"], last["t"]) == (0.0, 1.0)
    assert window_samples(samples[:1], 60.0) is None
    # zero-width window still yields a delta (last two samples)
    first, last = window_samples(samples, 0.0)
    assert (first["t"], last["t"]) == (8.0, 9.0)


def test_derive_series_rates_gauges_and_quantiles():
    samples = [
        {"t": 0.0, "metrics": {
            "reqs": [{"kind": "counter", "labels": {}, "value": 0.0}],
            "depth": [{"kind": "gauge", "labels": {}, "value": 1.0}],
            "lat": [{"kind": "histogram", "labels": {},
                     "edges": [0.1, 1.0], "counts": [0, 0, 0],
                     "count": 0}],
        }},
        {"t": 10.0, "metrics": {
            "reqs": [{"kind": "counter", "labels": {}, "value": 50.0}],
            "depth": [{"kind": "gauge", "labels": {}, "value": 2.0}],
            "lat": [{"kind": "histogram", "labels": {},
                     "edges": [0.1, 1.0], "counts": [10, 0, 0],
                     "count": 10}],
        }},
    ]
    series = {(s["name"]): s for s in derive_series(samples)}
    assert [p["value"] for p in series["depth"]["points"]] == [1.0, 2.0]
    assert series["reqs"]["points"] == [{"t": 10.0, "rate": 5.0}]
    [lat_point] = series["lat"]["points"]
    assert lat_point["rate"] == 1.0
    # all 10 observations inside (0, 0.1]: p50 interpolates to the middle
    assert lat_point["p50"] == pytest.approx(0.05)
    assert lat_point["p99"] == pytest.approx(0.099)


# ---------------------------------------------------------------------------
# the ring: sample / prune / persist / load / harvest


def test_ring_roundtrip_prune_persist_harvest(tmp_path):
    store_dir = str(tmp_path / "store")
    clk = {"t": 1000.0}
    reg = MetricsRegistry()
    c = reg.counter("work_total", "test")
    ring = TimeSeriesRing(
        reg, worker=3, path=history_path(store_dir, 3),
        tick_s=1.0, history_s=5.0, clock=lambda: clk["t"],
    )
    assert ring.enabled
    for _ in range(8):
        c.inc(10)
        ring.sample()
        clk["t"] += 1.0
    # retention pruned: only the trailing history_s seconds remain
    samples = ring.samples()
    assert 5 <= len(samples) <= 6
    assert float(samples[-1]["t"]) - float(samples[0]["t"]) <= 5.0
    assert ring.span_s() == float(samples[-1]["t"]) - float(samples[0]["t"])

    assert ring.persist({"firing": 0}, force=True)
    doc = load_history(ring.path)
    assert doc["worker"] == 3 and doc["type"] == "timeseries"
    assert doc["firing"] == 0
    assert len(doc["samples"]) == len(samples)

    # harvest preserves the mirror with the death reason stamped in
    out = harvest(ring.path, store_dir, 3, "died rc=-9")
    assert out is not None
    hdoc = load_history(out)
    assert hdoc["harvested"]["reason"] == "died rc=-9"
    files = list_history(store_dir)
    assert files["live"] == [ring.path]
    assert files["harvested"] == [out]

    # a foreign file refuses to load
    bad = tmp_path / "store" / "history" / "junk.ts.json"
    bad.write_text(json.dumps({"type": "flight"}))
    with pytest.raises(ValueError):
        load_history(str(bad))


def test_ring_disabled_when_either_knob_zero(tmp_path):
    reg = MetricsRegistry()
    for tick_s, history_s in ((0.0, 300.0), (1.0, 0.0)):
        ring = TimeSeriesRing(reg, tick_s=tick_s, history_s=history_s)
        assert not ring.enabled
        assert not ring.due()
        assert ring.tick() is False
        assert ring.samples() == []


def test_env_knobs_fail_loudly_on_junk(monkeypatch):
    from annotatedvdb_tpu.obs import slo as slo_mod
    from annotatedvdb_tpu.obs import timeseries as ts_mod

    cases = [
        ("AVDB_OBS_TICK_S", ts_mod.obs_tick_from_env),
        ("AVDB_OBS_HISTORY_S", ts_mod.obs_history_from_env),
        ("AVDB_SLO_FAST_S", slo_mod.slo_fast_window_from_env),
        ("AVDB_SLO_SLOW_S", slo_mod.slo_slow_window_from_env),
        ("AVDB_SLO_BURN", slo_mod.slo_burn_from_env),
        ("AVDB_SLO_AVAIL_TARGET", slo_mod.slo_avail_target_from_env),
        ("AVDB_SLO_LOAD_FLOOR", slo_mod.slo_load_floor_from_env),
    ]
    for var, reader in cases:
        monkeypatch.setenv(var, "banana")
        with pytest.raises(ValueError, match=var):
            reader()
        monkeypatch.delenv(var)
        assert reader() >= 0  # defaults parse
    # domain checks beyond "is a number"
    monkeypatch.setenv("AVDB_SLO_AVAIL_TARGET", "1.5")
    with pytest.raises(ValueError):
        slo_mod.slo_avail_target_from_env()
    monkeypatch.delenv("AVDB_SLO_AVAIL_TARGET")
    monkeypatch.setenv("AVDB_SLO_BURN", "0")
    with pytest.raises(ValueError):
        slo_mod.slo_burn_from_env()
    monkeypatch.delenv("AVDB_SLO_BURN")
    # the slow window must sit beyond the fast window
    monkeypatch.setenv("AVDB_SLO_FAST_S", "60")
    monkeypatch.setenv("AVDB_SLO_SLOW_S", "30")
    with pytest.raises(ValueError):
        slo_mod.slo_slow_window_from_env()


# ---------------------------------------------------------------------------
# the SLO state machine: burn arithmetic + hysteresis


def _avail_sample(t: float, served: float, errors: float) -> dict:
    return {"t": t, "metrics": {
        "avdb_query_requests_total": [
            {"kind": "counter", "labels": {"kind": "point"},
             "value": served},
        ],
        "avdb_query_errors_total": [
            {"kind": "counter", "labels": {"kind": "point"},
             "value": errors},
        ],
    }}


def _breach_timeline() -> list:
    """100 requests/tick throughout; 50 errors/tick on ticks 3-4 only.
    With fast=1 tick and slow=2 ticks of window, the expected walk is
    ok(t<=2) -> pending(t=3) -> firing(t=4) -> resolved(t=7)."""
    samples, served, errors = [], 0.0, 0.0
    for t in range(8):
        if t in (3, 4):
            errors += 50.0
        served += 100.0
        samples.append(_avail_sample(float(t), served, errors))
    return samples


AVAIL_SPEC = dict(target=0.999)


def _avail_registry():
    return SloRegistry(
        MetricsRegistry(),
        specs=[SloSpec("availability", "availability", "test",
                       **AVAIL_SPEC)],
        fast_s=1.0, slow_s=2.0, burn_threshold=2.0,
    )


def test_slo_hysteresis_walks_ok_pending_firing_resolved():
    slos = _avail_registry()
    samples = _breach_timeline()
    states = []
    for i in range(len(samples)):
        [row] = slos.evaluate(samples[: i + 1],
                              now=float(samples[i]["t"]))
        states.append(row["state"])
    assert states == ["ok", "ok", "ok", "pending", "firing",
                      "firing", "firing", "resolved"]
    [final] = slos.alerts()
    assert final["fired_total"] == 1
    assert slos.firing() == 0
    assert slos.worst_state() == "resolved"
    # the breach burn hit the cap: 33% errors against a 0.1% budget
    assert final["burn_fast"] == 0.0  # clean at the final tick


def test_slo_burn_requires_both_windows():
    """One hot fast window never pages: the slow window must agree."""
    slos = SloRegistry(
        MetricsRegistry(),
        specs=[SloSpec("availability", "availability", "test",
                       **AVAIL_SPEC)],
        fast_s=1.0, slow_s=60.0, burn_threshold=2.0,
    )
    # long clean history, then one hot tick: the slow window dilutes the
    # burst below threshold, so the state never leaves ok
    samples, served = [], 0.0
    for t in range(60):
        served += 100.0
        samples.append(_avail_sample(float(t), served, 0.0))
    samples.append(_avail_sample(60.0, served + 100.0, 5.0))
    for i in range(len(samples)):
        [row] = slos.evaluate(samples[: i + 1],
                              now=float(samples[i]["t"]))
    assert row["state"] == "ok"
    assert row["burn_fast"] > 2.0  # the fast window IS hot
    assert row["burn_slow"] < 2.0  # ... but the slow window says budget


def test_replay_history_reproduces_the_episode():
    replay = replay_history(_breach_timeline(), fast_s=1.0, slow_s=2.0,
                            burn_threshold=2.0)
    walks = [(e["from"], e["to"], e["t"]) for e in replay["episodes"]
             if e["slo"] == "availability"]
    assert walks == [("ok", "pending", 3.0), ("pending", "firing", 4.0),
                     ("firing", "resolved", 7.0)]
    assert replay["ticks"] == 8 and replay["span_s"] == 7.0
    assert replay["max_burn"]["availability"] > 2.0
    [avail] = [a for a in replay["alerts"] if a["slo"] == "availability"]
    assert avail["state"] == "resolved" and avail["fired_total"] == 1


def test_worst_of_ranking():
    assert worst_of([]) == "ok"
    assert worst_of(["ok", "resolved"]) == "resolved"
    assert worst_of(["resolved", "pending", "ok"]) == "pending"
    assert worst_of(["pending", "firing"]) == "firing"


# ---------------------------------------------------------------------------
# the gauge-ceiling kind (PR 18: follower replication lag)


def _lag_sample(t: float, lag: float | None) -> dict:
    metrics = {} if lag is None else {
        "avdb_replication_lag_seconds": [
            {"kind": "gauge", "labels": {}, "value": lag},
        ],
    }
    return {"t": t, "metrics": metrics}


LAG_SPEC = dict(metric="avdb_replication_lag_seconds", ceiling=5.0,
                objective=0.9)


def test_gauge_ceiling_burn_is_the_breached_point_fraction():
    spec = SloSpec("replication_lag", "gauge_ceiling", "t", **LAG_SPEC)
    # 10 points, 3 past the ceiling: frac 0.3 against a 0.1 budget = 3.0
    win = [_lag_sample(float(t), 8.0 if t < 3 else 0.1)
           for t in range(10)]
    assert spec.burn((win[0], win[-1]), window=win) == pytest.approx(3.0)
    # every point clean -> burn 0; every point hot -> 1/0.1 = 10
    clean = [_lag_sample(float(t), 0.2) for t in range(4)]
    assert spec.burn((clean[0], clean[-1]), window=clean) == 0.0
    hot = [_lag_sample(float(t), 9.0) for t in range(4)]
    assert spec.burn((hot[0], hot[-1]), window=hot) \
        == pytest.approx(10.0)
    # metric absent (not a follower) = no judgment, never a clean 0
    bare = [_lag_sample(float(t), None) for t in range(4)]
    assert spec.burn((bare[0], bare[-1]), window=bare) is None
    # ceiling 0 = dormant (the AVDB_REPL_MAX_LAG_S=0 story), even hot
    dormant = SloSpec("replication_lag", "gauge_ceiling", "t",
                      metric="avdb_replication_lag_seconds", ceiling=0.0)
    assert dormant.burn((hot[0], hot[-1]), window=hot) is None
    # pair-only callers (no window kwarg) get the two-point fallback
    assert spec.burn((hot[0], hot[-1])) == pytest.approx(10.0)
    note = spec.target_note()
    assert note == {"ceiling": 5.0, "objective": 0.9}


def test_trailing_samples_bracketing():
    samples = [_lag_sample(float(t), 0.0) for t in range(10)]
    win = trailing_samples(samples, 3.0, now=9.0)
    assert [s["t"] for s in win] == [6.0, 7.0, 8.0, 9.0]
    # a window thinner than two samples falls back to the newest two
    assert [s["t"] for s in trailing_samples(samples, 0.0, now=20.0)] \
        == [8.0, 9.0]
    assert trailing_samples(samples[:1], 3.0) is None


def test_replication_lag_slo_fires_on_sustained_breach_then_resolves():
    """The lag-gauge walk mirrors the availability one: a follower stuck
    past the bound for both windows pages; catching back up resolves."""
    slos = SloRegistry(
        MetricsRegistry(),
        specs=[SloSpec("replication_lag", "gauge_ceiling", "test",
                       **LAG_SPEC)],
        fast_s=1.0, slow_s=2.0, burn_threshold=2.0,
    )
    # lag healthy (ticks 0-2), stuck at 30s (ticks 3-5), recovered
    lag = {0: 0.1, 1: 0.1, 2: 0.1, 3: 30.0, 4: 30.0, 5: 30.0,
           6: 0.1, 7: 0.1, 8: 0.1, 9: 0.1}
    samples, states = [], []
    for t in range(10):
        samples.append(_lag_sample(float(t), lag[t]))
        [row] = slos.evaluate(list(samples), now=float(t))
        states.append(row["state"])
    assert states[:3] == ["ok", "ok", "ok"]
    assert "firing" in states
    assert states[-1] == "resolved"
    [final] = slos.alerts()
    assert final["fired_total"] == 1
    assert final["kind"] == "gauge_ceiling"
    assert final["ceiling"] == 5.0


def test_default_slos_declare_replication_lag(monkeypatch):
    [spec] = [s for s in default_slos() if s.name == "replication_lag"]
    assert spec.kind == "gauge_ceiling"
    assert spec.params["metric"] == "avdb_replication_lag_seconds"
    assert spec.params["ceiling"] == 5.0  # the AVDB_REPL_MAX_LAG_S default
    # the readiness knob IS the alerting knob: 0 disables both planes
    monkeypatch.setenv("AVDB_REPL_MAX_LAG_S", "0")
    [spec] = [s for s in default_slos() if s.name == "replication_lag"]
    assert spec.params["ceiling"] == 0.0
    hot = [_lag_sample(float(t), 99.0) for t in range(4)]
    assert spec.burn((hot[0], hot[-1]), window=hot) is None


def test_health_plane_tick_persists_alert_extras(tmp_path):
    store_dir = str(tmp_path / "store")
    clk = {"t": 500.0}
    reg = MetricsRegistry()
    hp = HealthPlane(
        reg, store_dir=store_dir, worker=0,
        specs=[SloSpec("availability", "availability", "t",
                       **AVAIL_SPEC)],
        tick_s=1.0, history_s=60.0, fast_s=1.0, slow_s=2.0,
        burn_threshold=2.0, clock=lambda: clk["t"],
    )
    assert hp.enabled and hp.errors == 0
    assert hp.tick()
    clk["t"] += 1.0
    hp.close()  # forced final persist
    doc = load_history(hp.ring.path)
    assert doc["firing"] == 0
    assert [a["slo"] for a in doc["alerts"]] == ["availability"]


# ---------------------------------------------------------------------------
# serving surfaces: /alerts + /metrics/history on BOTH front ends


def _build_store(store_dir: str) -> None:
    from annotatedvdb_tpu.loaders.lookup import identity_hashes
    from annotatedvdb_tpu.store import VariantStore
    from annotatedvdb_tpu.types import encode_allele_array

    width = 8
    store = VariantStore(width=width)
    n = 16
    refs, alts = ["A"] * n, ["G"] * n
    ref, ref_len = encode_allele_array(refs, width)
    alt, alt_len = encode_allele_array(alts, width)
    h = identity_hashes(width, ref, alt, ref_len, alt_len, refs, alts)
    store.shard(8).append(
        {"pos": np.arange(1000, 1000 + 10 * n, 10, dtype=np.int32),
         "h": h, "ref_len": ref_len, "alt_len": alt_len},
        ref, alt,
    )
    store.save(store_dir)


def _get(port: int, path: str):
    import urllib.error
    import urllib.request

    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30
        ) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as err:
        return err.code, err.read().decode()


@pytest.fixture()
def health_served(tmp_path):
    """Both front ends over one store sharing ONE HealthPlane (tick_s
    high enough that only the test's manual ticks move it — the payloads
    must be deterministic for byte-parity)."""
    from annotatedvdb_tpu.serve.aio import build_aio_server
    from annotatedvdb_tpu.serve.http import build_server

    store_dir = str(tmp_path / "store")
    _build_store(store_dir)
    clk = {"t": 2000.0}
    registry = MetricsRegistry()
    health = HealthPlane(
        registry, store_dir=store_dir, worker=0,
        specs=[SloSpec("availability", "availability", "test",
                       **AVAIL_SPEC)],
        tick_s=30.0, history_s=600.0, fast_s=1.0, slow_s=2.0,
        burn_threshold=2.0, clock=lambda: clk["t"],
    )
    # start the time-gate NOW: neither front end's driver may sneak a
    # startup tick in — only the test's manual ticks move the ring
    health.ring._last_tick = time.monotonic()
    httpd = build_server(store_dir=store_dir, port=0, registry=registry,
                        health=health)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    aio = build_aio_server(store_dir=store_dir, port=0,
                           registry=registry, health=health)
    aio.start_background()
    try:
        yield (store_dir, clk, health, httpd.server_address[1],
               aio.server_address[1])
    finally:
        httpd.shutdown()
        httpd.server_close()
        httpd.ctx.batcher.close()
        aio.shutdown()
        aio.ctx.batcher.close()


def _tick_n(clk, health, n: int, step: float = 1.0) -> None:
    for _ in range(n):
        assert health.tick()
        clk["t"] += step


def test_alerts_and_history_byte_parity_across_front_ends(health_served):
    _store_dir, clk, health, tport, aport = health_served
    _tick_n(clk, health, 4)
    for path in ("/alerts", "/metrics/history", "/metrics/history?window=2",
                 "/metrics/history?window=junk"):
        ts, tbody = _get(tport, path)
        as_, abody = _get(aport, path)
        assert ts == as_ == 200, (path, ts, as_)
        assert tbody == abody, path

    rec = json.loads(_get(tport, "/alerts")[1])
    assert rec["enabled"] is True and rec["worker"] == 0
    assert rec["state"] == "ok" and rec["firing"] == 0
    assert rec["windows"] == {"fast_s": 1.0, "slow_s": 2.0}
    assert [a["slo"] for a in rec["alerts"]] == ["availability"]

    hist = json.loads(_get(tport, "/metrics/history")[1])
    assert hist["enabled"] is True and hist["samples"] == 4
    assert hist["span_s"] == 3.0
    assert any(s["name"] == "avdb_slo_burn_rate" for s in hist["series"])
    # ?window trims to the trailing seconds; junk windows are ignored
    trimmed = json.loads(_get(tport, "/metrics/history?window=1.5")[1])
    assert trimmed["samples"] == 2
    sloppy = json.loads(_get(tport, "/metrics/history?window=junk")[1])
    assert sloppy["samples"] == 4


def test_healthz_and_prometheus_carry_alert_state(health_served):
    _store_dir, clk, health, tport, aport = health_served
    _tick_n(clk, health, 2)
    for port in (tport, aport):
        hz = json.loads(_get(port, "/healthz")[1])
        assert hz["alerts"] == "ok" and hz["alerts_firing"] == 0
        _status, metrics = _get(port, "/metrics")
        assert "avdb_slo_burn_rate" in metrics
        assert "avdb_alerts_firing" in metrics


def test_fleet_views_merge_sibling_mirrors(health_served):
    store_dir, clk, health, tport, aport = health_served
    _tick_n(clk, health, 3)
    # a sibling worker's persisted mirror (fresh enough for the TTL)
    sib_reg = MetricsRegistry()
    sib_reg.counter("sib_total", "t").inc(7)
    sib = TimeSeriesRing(sib_reg, worker=1,
                         path=history_path(store_dir, 1),
                         tick_s=1.0, history_s=60.0)
    sib.sample()
    sib.sample()
    sib.persist({"alerts": [{"slo": "availability", "state": "firing"}],
                 "firing": 1}, force=True)
    for port in (tport, aport):
        rec = json.loads(_get(port, "/alerts?fleet=1")[1])
        assert rec["fleet"] is True
        assert set(rec["workers"]) == {"0", "1"}
        assert rec["workers"]["1"]["state"] == "firing"
        assert rec["firing"] == 1
        assert rec["state"] == "firing"  # worst across the fleet
        hist = json.loads(_get(port, "/metrics/history?fleet=1")[1])
        assert set(hist["workers"]) == {"0", "1"}
        assert hist["workers"]["1"]["samples"] == 2


def test_disabled_plane_payloads(tmp_path):
    from annotatedvdb_tpu.serve.http import build_server

    store_dir = str(tmp_path / "store")
    _build_store(store_dir)
    httpd = build_server(store_dir=store_dir, port=0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        port = httpd.server_address[1]
        rec = json.loads(_get(port, "/alerts")[1])
        assert rec == {"enabled": False, "worker": 0,
                       "state": "disabled", "firing": 0, "alerts": []}
        hist = json.loads(_get(port, "/metrics/history")[1])
        assert hist["enabled"] is False and hist["series"] == []
        hz = json.loads(_get(port, "/healthz")[1])
        assert hz["alerts"] == "disabled" and hz["alerts_firing"] == 0
    finally:
        httpd.shutdown()
        httpd.server_close()
        httpd.ctx.batcher.close()


# ---------------------------------------------------------------------------
# doctor slo


def test_doctor_slo_replays_harvested_history(tmp_path, capsys):
    from annotatedvdb_tpu.cli import doctor

    store_dir = tmp_path / "store"
    hist_dir = store_dir / "history"
    hist_dir.mkdir(parents=True)
    doc = {
        "type": "timeseries", "worker": 2, "t": time.time(),
        "tick_s": 1.0, "history_s": 60.0,
        "samples": _breach_timeline(),
        "harvested": {"reason": "died rc=-9", "t": time.time()},
    }
    (hist_dir / "1700000000000-w2.json").write_text(json.dumps(doc))
    rc = doctor.main([
        "slo", "--storeDir", str(store_dir), "--fast", "1.0",
        "--slow", "2.0", "--burn", "2.0", "--json",
    ])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    [rep] = out["replays"]
    assert rep["worker"] == 2
    assert rep["harvested"]["reason"] == "died rc=-9"
    walks = [(e["from"], e["to"]) for e in rep["episodes"]
             if e["slo"] == "availability"]
    assert ("pending", "firing") in walks
    assert ("firing", "resolved") in walks

    # human rendering names the file, the reason and the states
    rc = doctor.main(["slo", "--storeDir", str(store_dir),
                      "--fast", "1.0", "--slow", "2.0", "--burn", "2.0"])
    assert rc == 0
    err = capsys.readouterr().err
    assert "died rc=-9" in err and "availability" in err


def test_doctor_slo_no_history_exits_2(tmp_path, capsys):
    from annotatedvdb_tpu.cli import doctor

    empty = tmp_path / "store"
    empty.mkdir()
    assert doctor.main(["slo", "--storeDir", str(empty)]) == 2
    err = capsys.readouterr().err
    assert "AVDB_OBS_TICK_S" in err
    assert doctor.main(["slo", "--storeDir",
                        str(tmp_path / "missing")]) == 2
