"""Parity tests: closed-form bin kernel vs the recursive tree oracle
(SURVEY.md §7.2 step 2)."""

import numpy as np
import jax.numpy as jnp

from annotatedvdb_tpu.oracle.binindex import BinTree, closed_form_path, LEAF_SIZE
from annotatedvdb_tpu.ops.binindex import bin_index_kernel_jit


def lookup(tree, intervals):
    starts = jnp.asarray(np.array([s for s, _ in intervals], dtype=np.int32))
    ends = jnp.asarray(np.array([e for _, e in intervals], dtype=np.int32))
    level, leaf = bin_index_kernel_jit(starts, ends)
    return np.asarray(level), np.asarray(leaf)


def test_small_chromosome_parity(rng):
    """Exhaustive-ish parity on a small fake chromosome (200kb)."""
    tree = BinTree("chrT", 200_000)
    intervals = []
    for _ in range(300):
        start = rng.randint(1, 200_000)
        end = min(200_000, start + rng.choice([0, 1, 5, 100, 20_000, 150_000]))
        intervals.append((start, end))
    level, leaf = lookup(tree, intervals)
    for i, (s, e) in enumerate(intervals):
        want_level, want_path = tree.find_bin(s, e)
        assert level[i] == want_level, (s, e)
        assert closed_form_path("chrT", int(level[i]), int(leaf[i])) == want_path, (s, e)


def test_chr1_scale_parity(rng):
    """hg38 chr1-sized chromosome: sparse random checks against the oracle."""
    seq_len = 248_956_422
    tree = BinTree("chr1", seq_len)
    intervals = []
    for _ in range(200):
        start = rng.randint(1, seq_len)
        end = min(seq_len, start + rng.choice([0, 2, 30, 15_000, 70_000, 5_000_000]))
        intervals.append((start, end))
    # boundary cases: bin edges (bins are (lower, upper])
    for mult in (1, 2, 4096, 4097):
        edge = LEAF_SIZE * mult
        intervals += [(edge, edge), (edge + 1, edge + 1), (edge, edge + 1)]
    level, leaf = lookup(tree, intervals)
    for i, (s, e) in enumerate(intervals):
        want_level, want_path = tree.find_bin(s, e)
        assert level[i] == want_level, (s, e)
        assert closed_form_path("chr1", int(level[i]), int(leaf[i])) == want_path, (s, e)


def test_snv_leaf_level():
    """Point variants always land in a leaf (level 13 = nlevel 27 ltree path,
    the cacheability condition at bin_index.py:67)."""
    starts = jnp.asarray(np.array([1, 100, LEAF_SIZE, LEAF_SIZE + 1, 64_000_000], dtype=np.int32))
    level, leaf = bin_index_kernel_jit(starts, starts)
    assert (np.asarray(level) == 13).all()
    # ltree nlevel = 1 + 2*level
    path = closed_form_path("chr9", 13, int(np.asarray(leaf)[0]))
    assert len(path.split(".")) == 27


def test_wide_interval_levels():
    """A 64Mb-spanning interval escalates to a broad bin (level <= 1)."""
    starts = jnp.asarray(np.array([1, 1], dtype=np.int32))
    ends = jnp.asarray(np.array([63_999_999, 64_000_001], dtype=np.int32))
    level, _ = bin_index_kernel_jit(starts, ends)
    assert np.asarray(level)[0] >= 1
    assert np.asarray(level)[1] == 0
