"""Parity tests for the fused Pallas annotate+bin kernel vs the jnp kernels.

Runs in Mosaic interpreter mode on the CPU test mesh; the same kernel is
compile- and parity-verified on real TPU hardware by ``bench.py`` (which
prefers the Pallas path when it is available and falls back to jnp)."""

import numpy as np
import pytest

from annotatedvdb_tpu.ops.annotate import annotate_kernel_jit
from annotatedvdb_tpu.ops.annotate_pallas import annotate_bin_pallas
from annotatedvdb_tpu.ops.binindex import bin_index_kernel_jit
from annotatedvdb_tpu.types import VariantBatch

from conftest import random_variants
from test_annotate import HARD_VARIANTS

# curated branch-coverage cases: SNV, MNV, inversion, palindrome, ins, dup
# (single + multi-copy), indel, del, shared-prefix normalization, identical
# alleles, allele longer than width (host fallback)
EDGE_VARIANTS = [
    ("1", 100, "A", "G"),
    ("2", 200, "AC", "GT"),
    ("3", 300, "ACGT", "ACGT"),
    ("4", 62_500_000, "AAGCTT", "AAGCTT"[::-1]),
    ("5", 400, "ATAT", "ATAT"[::-1]),       # palindrome: inversion & identical
    ("6", 500, "A", "AGG"),
    ("7", 600, "AGG", "A"),
    ("8", 700, "ACA", "ACACA"),
    ("9", 800, "AGCGC", "AGC"),
    ("10", 900, "AGC", "AGCGCGC"),          # dup: inserted GCGC vs ref[1:] GC
    ("10", 950, "AC", "C"),                 # prefix-0 tiling: ref[1:] == alt
    ("10", 960, "GCC", "C"),                # (the lag-0 dup-flag case the
                                            # twin suite caught missing)
    ("11", 1000, "ATTT", "GTT"),
    ("12", 1100, "CAAA", "CAAAA"),
    ("13", 15_625, "A", "ACCCCCCCCCCCCCCCCCCCCC"),  # crosses a leaf-bin edge
    ("14", 15_626, "AT", "A"),
    ("X", 1_000_000, "ACGTACGTACGTACGTACGT", "A"),
    ("Y", 1, "A", "C"),
]


def _run_both(variants, width):
    batch = VariantBatch.from_tuples(variants, width=width)
    ref_out = annotate_kernel_jit(
        batch.pos, batch.ref, batch.alt, batch.ref_len, batch.alt_len
    )
    lvl, leaf = bin_index_kernel_jit(batch.pos, ref_out["end_location"])
    pal = annotate_bin_pallas(
        batch.pos, batch.ref, batch.alt, batch.ref_len, batch.alt_len,
        block_n=128, interpret=True,
    )
    return ref_out, lvl, leaf, pal


def _assert_parity(ref_out, lvl, leaf, pal):
    ok = ~np.asarray(ref_out["host_fallback"])
    for key in ref_out:
        a = np.asarray(ref_out[key])
        p = np.asarray(pal[key])
        mismatch = (a != p) & ok
        assert not mismatch.any(), f"{key}: rows {np.where(mismatch)[0][:5]}"
    assert (np.asarray(pal["host_fallback"]) == np.asarray(ref_out["host_fallback"])).all()
    assert (np.asarray(pal["bin_level"])[ok] == np.asarray(lvl)[ok]).all()
    assert (np.asarray(pal["leaf_bin"])[ok] == np.asarray(leaf)[ok]).all()


def test_pallas_parity_edge_cases():
    _assert_parity(*_run_both(EDGE_VARIANTS, width=16))


def test_pallas_parity_hard_indels_host_fallback():
    # the reference's hard indels exceed any device width -> flagged fallback
    ref_out, lvl, leaf, pal = _run_both(EDGE_VARIANTS + HARD_VARIANTS, width=16)
    assert np.asarray(pal["host_fallback"])[-len(HARD_VARIANTS):].all()
    _assert_parity(ref_out, lvl, leaf, pal)


@pytest.mark.parametrize("width", [8, 16])
def test_pallas_parity_random(rng, width):
    variants = random_variants(rng, 300, max_len=width + 4)
    _assert_parity(*_run_both(variants, width=width))


def test_pallas_parity_unaligned_batch(rng):
    # N not a multiple of block_n exercises the pad/slice path
    variants = random_variants(rng, 77, max_len=12)
    _assert_parity(*_run_both(variants, width=16))
