"""Packed single-fetch output transport (ops/pack.py): bit-exact parity."""

import numpy as np

from annotatedvdb_tpu.ops.pack import WIDTH, pack_outputs_jit, unpack_outputs


def test_pack_roundtrip_random():
    rng = np.random.default_rng(7)
    n = 4096
    h = rng.integers(0, 2**32, n, dtype=np.uint32)
    dup = rng.random(n) < 0.3
    level = rng.integers(0, 15, n).astype(np.int32)
    leaf = rng.integers(-1, 20000, n).astype(np.int32)
    nd = rng.random(n) < 0.01
    hf = rng.random(n) < 0.01
    packed = np.asarray(pack_outputs_jit(h, dup, level, leaf, nd, hf))
    assert packed.shape == (n, WIDTH) and packed.dtype == np.uint8
    cols = unpack_outputs(packed)
    assert (cols["h"] == h).all()
    assert (cols["dup"] == dup).all()
    assert (cols["bin_level"] == level).all()
    assert (cols["leaf_bin"] == leaf).all()          # negatives survive
    assert (cols["needs_digest"] == nd).all()
    assert (cols["host_fallback"] == hf).all()


def test_pack_vep_roundtrip():
    from annotatedvdb_tpu.ops.pack import (
        VEP_WIDTH,
        pack_vep_outputs_jit,
        unpack_vep_outputs,
    )

    rng = np.random.default_rng(3)
    n = 2048
    h = rng.integers(0, 2**32, n, dtype=np.uint32)
    prefix = rng.integers(0, 50, n).astype(np.int32)
    host = rng.random(n) < 0.02
    packed = np.asarray(pack_vep_outputs_jit(h, prefix, host))
    assert packed.shape == (n, VEP_WIDTH)
    cols = unpack_vep_outputs(packed)
    assert (cols["h"] == h).all()
    assert (cols["prefix_len"] == prefix).all()
    assert (cols["host_fallback"] == host).all()


def test_transport_probe():
    import sys

    from annotatedvdb_tpu.ops.pack import transport_verified

    ok = transport_verified()
    assert isinstance(ok, bool)
    if sys.byteorder == "little":
        # on a little-endian host with the (little-endian) CPU/TPU backends
        # the packed transport must verify; elsewhere False is the designed
        # degradation, not a failure
        assert ok is True


def test_nibble_alleles_roundtrip():
    from annotatedvdb_tpu.ops.pack import (
        encode_alleles_nibble,
        inflate_alleles_jit,
    )

    rng = np.random.default_rng(11)
    for width in (16, 49):  # even and odd widths
        alphabet = np.frombuffer(b"ACGTNacgtn*.-", np.uint8)
        lens = rng.integers(1, width + 1, 512)
        ref = np.zeros((512, width), np.uint8)
        alt = np.zeros((512, width), np.uint8)
        for i, L in enumerate(lens):
            ref[i, :L] = rng.choice(alphabet, L)
            alt[i, :L] = rng.choice(alphabet, L)
        enc = encode_alleles_nibble(ref, alt)
        assert enc is not None
        assert enc[0].shape == (512, (width + 1) // 2)
        r, a = inflate_alleles_jit(enc[0], enc[1], width)
        assert (np.asarray(r) == ref).all()
        assert (np.asarray(a) == alt).all()


def test_nibble_alleles_rejects_exotic_bytes():
    from annotatedvdb_tpu.ops.pack import encode_alleles_nibble

    ref = np.zeros((4, 8), np.uint8)
    alt = np.zeros((4, 8), np.uint8)
    ref[0, :3] = np.frombuffer(b"ACG", np.uint8)
    alt[2, :5] = np.frombuffer(b"<DEL>", np.uint8)  # symbolic allele
    assert encode_alleles_nibble(ref, alt) is None


def test_host_identity_twins_match_kernels():
    """allele_hash_np / vep_identity_np must be BIT-EXACT with the jitted
    kernels: store membership compares host hashes against device-computed
    ones, so divergence silently breaks dedup on slow links."""
    from annotatedvdb_tpu.io.synth import synthetic_batch
    from annotatedvdb_tpu.models.pipeline import annotate_fn
    from annotatedvdb_tpu.ops.annotate import vep_identity_np
    from annotatedvdb_tpu.ops.hashing import allele_hash_jit, allele_hash_np

    for width in (16, 49):
        b = synthetic_batch(2048, width=width)
        h_dev = np.asarray(
            allele_hash_jit(b.ref, b.alt, b.ref_len, b.alt_len)
        )
        h_np = allele_hash_np(b.ref, b.alt, b.ref_len, b.alt_len)
        assert (h_dev == h_np).all()
        ann = annotate_fn()(b.chrom, b.pos, b.ref, b.alt, b.ref_len, b.alt_len)
        prefix, host = vep_identity_np(b.ref, b.alt, b.ref_len, b.alt_len)
        assert (np.asarray(ann.prefix_len) == prefix).all()
        assert (np.asarray(ann.host_fallback) == host).all()


def test_pack_extreme_values():
    h = np.array([0, 1, 0xFFFFFFFF, 0xDEADBEEF], np.uint32)
    leaf = np.array([-(2**31), 2**31 - 1, 0, -1], np.int32)
    level = np.array([0, 255, 13, 1], np.int32)
    t = np.array([True, False, True, False])
    cols = unpack_outputs(
        np.asarray(pack_outputs_jit(h, t, level, leaf, ~t, t))
    )
    assert (cols["h"] == h).all()
    assert (cols["leaf_bin"] == leaf).all()
    assert (cols["bin_level"] == (level & 0xFF)).all()
    assert (cols["dup"] == t).all()
    assert (cols["needs_digest"] == ~t).all()
    assert (cols["host_fallback"] == t).all()
