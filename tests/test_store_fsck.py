"""store_fsck / cli.doctor: integrity detection, repair verbs, the
checked-in corrupted-store fixture, and AVDB_VERIFY deep checksumming."""

import json
import os
import shutil

import numpy as np
import pytest

from annotatedvdb_tpu.store import (
    AlgorithmLedger,
    StoreCorruptError,
    VariantStore,
)
from annotatedvdb_tpu.store.fsck import fsck

FIXTURE = os.path.join(os.path.dirname(__file__), "data", "corrupt_store")


def _mkstore(path, n=6, chrom=1):
    store = VariantStore(width=8)
    store.shard(chrom).append(
        {"pos": np.arange(100, 100 + n, dtype=np.int32),
         "h": np.arange(n, dtype=np.uint32) + 7,
         "ref_len": np.full(n, 1, np.int32),
         "alt_len": np.full(n, 1, np.int32)},
        np.full((n, 8), 65, np.uint8), np.full((n, 8), 67, np.uint8),
        annotations={"other_annotation": [{"k": int(i)} for i in range(n)]},
    )
    store.save(path)
    return store


def _codes(report):
    return {f["code"] for f in report["findings"]}


# ---------------------------------------------------------------------------
# verbs


def test_clean_store_is_clean(tmp_path):
    d = str(tmp_path / "vdb")
    _mkstore(d)
    report = fsck(d, deep=True, log=lambda m: None)
    assert report["status"] == "clean"
    assert report["exit_code"] == 0


def test_missing_manifest_is_fatal(tmp_path):
    report = fsck(str(tmp_path), log=lambda m: None)
    assert report["exit_code"] == 2
    assert "manifest-missing" in _codes(report)


def test_orphans_and_tmp_are_pruned(tmp_path):
    d = str(tmp_path / "vdb")
    _mkstore(d)
    open(os.path.join(d, "chr5.000050.npz"), "wb").write(b"x")
    open(os.path.join(d, "chr5.000050.ann.jsonl"), "w").write("")
    open(os.path.join(d, ".chr1.000001.tmp99.npz"), "wb").write(b"x")
    report = fsck(d, log=lambda m: None)
    assert report["exit_code"] == 1
    assert {"segment-orphan", "stale-tmp"} <= _codes(report)
    report = fsck(d, repair=True, log=lambda m: None)
    assert report["repairs"]
    assert fsck(d, log=lambda m: None)["status"] == "clean"


def test_torn_segment_detected_and_rolled_back(tmp_path):
    d = str(tmp_path / "vdb")
    _mkstore(d)
    # a ledger run record feeds the reload-hint prescription
    led = AlgorithmLedger(os.path.join(d, "ledger.jsonl"))
    led.run({"script": "load-vcf", "input": "demo.vcf"})
    seg = [f for f in os.listdir(d)
           if f.startswith("chr1.") and f.endswith(".npz")][0]
    fp = os.path.join(d, seg)
    with open(fp, "r+b") as f:
        f.truncate(os.path.getsize(fp) // 2)
    # size check catches the tear at plain load time
    with pytest.raises(StoreCorruptError, match="store_fsck"):
        VariantStore.load(d)
    report = fsck(d, log=lambda m: None)
    assert report["exit_code"] == 2
    assert "segment-torn" in _codes(report)
    # repair rolls the shard back to its last consistent state (here: empty)
    report = fsck(d, repair=True, log=lambda m: None)
    assert "segment-torn" in _codes(report)
    assert any("re-load" in f["message"] or "reload" in f["code"]
               for f in report["findings"])
    recovered = VariantStore.load(d)
    assert recovered.n == 0  # the only group was damaged; rows reported lost


def test_foreign_file_flagged_never_deleted(tmp_path):
    d = str(tmp_path / "vdb")
    _mkstore(d)
    foreign = os.path.join(d, "notours.npz")
    open(foreign, "wb").write(b"someone else's data")
    report = fsck(d, repair=True, log=lambda m: None)
    assert "foreign-file" in _codes(report)
    assert os.path.exists(foreign)


def test_dangling_undo_intent_flagged(tmp_path):
    d = str(tmp_path / "vdb")
    _mkstore(d)
    led = AlgorithmLedger(os.path.join(d, "ledger.jsonl"))
    led.undo_intent(3)
    report = fsck(d, log=lambda m: None)
    assert "undo-intent-dangling" in _codes(report)
    assert any("--algId 3" in f["message"] for f in report["findings"])
    # a completing undo clears the flag
    led.undo(3, removed=0)
    report = fsck(d, log=lambda m: None)
    assert "undo-intent-dangling" not in _codes(report)


def test_undo_cli_crash_between_save_and_record_is_detectable(tmp_path):
    """The undo path appends its intent BEFORE store.save: kill the undo
    after the save (fault point ledger.append on the completing record) and
    fsck must flag the dangling intent."""
    from annotatedvdb_tpu.cli import undo_load
    from annotatedvdb_tpu.utils import faults
    from annotatedvdb_tpu.utils.faults import InjectedFault

    d = str(tmp_path / "vdb")
    _mkstore(d)
    # intent is append #1, the completing undo record is append #2
    faults.reset("ledger.append:2:raise")
    try:
        with pytest.raises(InjectedFault):
            undo_load.main(["--storeDir", d, "--algId", "7", "--commit"])
    finally:
        faults.reset("")
    report = fsck(d, log=lambda m: None)
    assert "undo-intent-dangling" in _codes(report)
    # the prescribed re-run completes and clears the flag
    undo_load.main(["--storeDir", d, "--algId", "7", "--commit"])
    assert "undo-intent-dangling" not in _codes(fsck(d, log=lambda m: None))


# ---------------------------------------------------------------------------
# the checked-in corrupted-store fixture, end to end through the CLI verb


def test_corrupt_fixture_repairs_end_to_end(tmp_path):
    d = str(tmp_path / "vdb")
    shutil.copytree(FIXTURE, d)
    # broken as shipped: plain load refuses with an actionable error
    with pytest.raises(StoreCorruptError, match="store_fsck"):
        VariantStore.load(d)
    report = fsck(d, log=lambda m: None)
    assert report["exit_code"] == 2
    assert {"segment-torn", "segment-orphan", "stale-tmp", "compact-tmp",
            "wal-pending", "wal-tmp", "flush-tmp",
            "repl-tmp", "repl-cursor", "export-tmp",
            "ledger-torn", "undo-intent-dangling"} <= _codes(report)
    # the abandoned compaction/flush temps and the WAL are attributed,
    # never "foreign"
    assert "foreign-file" not in _codes(report)
    # doctor --repair through the CLI entry point
    from annotatedvdb_tpu.cli import doctor

    rc = doctor.main(["--storeDir", d, "--repair", "--json"])
    assert rc == 1  # repaired (damage findings downgrade once resolved)
    recovered = VariantStore.load(d)
    # chr1 survives intact, the torn chr2 group was rolled back
    assert recovered.shard(1).n == 6
    assert 2 not in {c for c, s in recovered.shards.items() if s.n}
    # the reload hint prescribed re-loading the original input
    assert any(
        f["code"] == "reload-hint" and "demo.vcf" in f["message"]
        for f in report["findings"]
    )


def test_fsck_script_entrypoint(tmp_path):
    """tools/store_fsck.py drives the same core (exit code contract)."""
    import subprocess
    import sys

    d = str(tmp_path / "vdb")
    _mkstore(d)
    script = os.path.join(os.path.dirname(__file__), os.pardir,
                          "tools", "store_fsck.py")
    p = subprocess.run(
        [sys.executable, script, "--storeDir", d, "--json"],
        capture_output=True, text=True, timeout=240,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert p.returncode == 0, p.stderr[-2000:]
    assert json.loads(p.stdout)["status"] == "clean"


# ---------------------------------------------------------------------------
# AVDB_VERIFY deep mode


@pytest.mark.parametrize("ext", [".npz", ".ann.jsonl"])
def test_deep_verify_catches_flipped_byte(tmp_path, monkeypatch, ext):
    d = str(tmp_path / "vdb")
    _mkstore(d)
    target = [f for f in os.listdir(d) if f.endswith(ext)
              and not f.endswith(".tmp" + ext)][0]
    fp = os.path.join(d, target)
    blob = bytearray(open(fp, "rb").read())
    blob[len(blob) // 2] ^= 0xFF  # flip one byte, size unchanged
    open(fp, "wb").write(bytes(blob))

    # default size-only mode cannot see it ... (jsonl flips may still break
    # the JSON parse; the npz flip lands mid-array and loads silently)
    monkeypatch.delenv("AVDB_VERIFY", raising=False)
    if ext == ".npz":
        VariantStore.load(d)

    # ... deep mode always does
    monkeypatch.setenv("AVDB_VERIFY", "deep")
    with pytest.raises(StoreCorruptError, match="crc32 mismatch"):
        VariantStore.load(d)
    # and fsck --deep agrees
    report = fsck(d, deep=True, log=lambda m: None)
    assert "segment-bitrot" in _codes(report)


# ---------------------------------------------------------------------------
# live-write-path debris: WAL files, rotation temps, flush temps


def test_wal_debris_attributed_and_pruned(tmp_path):
    """``*.wal`` / ``*.wal.tmp`` / ``*.flush.tmp.*`` from the upsert path
    get dedicated finding codes (never ``foreign-file``); --repair prunes
    them, with the wal-pending message naming what is lost and the
    non-destructive alternative (a serve-worker restart replays it)."""
    from annotatedvdb_tpu.store.wal import WriteAheadLog

    d = str(tmp_path / "vdb")
    _mkstore(d)
    wal = WriteAheadLog(d, "serve-w0", log=lambda m: None)
    wal.append({"rows": [{"code": 1, "pos": 150, "ref": "A", "alt": "G",
                          "ref_snp": None, "ann": None}]})
    wal.close()
    open(os.path.join(d, "serve-w1.000003.wal.tmp"), "wb").write(
        b'{"wal": 1}\n')
    open(os.path.join(d, "chr1.000060.flush.tmp.ann.jsonl"), "wb").write(
        b"")
    report = fsck(d, log=lambda m: None)
    codes = _codes(report)
    assert {"wal-pending", "wal-tmp", "flush-tmp"} <= codes
    assert "foreign-file" not in codes
    assert report["exit_code"] == 1  # warnings, not errors
    pending = [f for f in report["findings"] if f["code"] == "wal-pending"]
    assert "restart the serve worker" in pending[0]["message"]
    assert "LOST" in pending[0]["message"]
    # detection alone never deletes
    assert any(f.endswith(".wal") for f in os.listdir(d))
    report = fsck(d, repair=True, log=lambda m: None)
    assert report["repairs"]
    left = os.listdir(d)
    assert not any(".wal" in f or ".flush.tmp" in f for f in left), left
    assert fsck(d, log=lambda m: None)["status"] == "clean"


def test_wal_survives_loader_save_cleanup(tmp_path):
    """A loader commit's orphan cleanup must never touch WAL files — the
    durability of another process's acknowledged upserts."""
    from annotatedvdb_tpu.store.wal import WriteAheadLog

    d = str(tmp_path / "vdb")
    store = _mkstore(d)
    wal = WriteAheadLog(d, "serve-w0", log=lambda m: None)
    wal.append({"rows": []})
    wal.close()
    store.shard(1).set_col("ref_snp", [0], [77])  # dirty a segment
    store.save(d)  # save() prunes orphans; the WAL must survive
    assert any(f.endswith(".wal") for f in os.listdir(d))


# ---------------------------------------------------------------------------
# replication debris: bootstrap chunk temps, dangling tail cursors


def test_repl_debris_attributed_and_pruned(tmp_path):
    """``*.repl.tmp`` (a bootstrap chunk stream killed mid-transfer) and
    ``repl.cursor.json`` (a follower's tail cursor) get dedicated finding
    codes — never ``foreign-file`` — and ``--repair`` prunes both while
    naming the non-destructive recovery: re-running bootstrap
    (``serve --follow``) refetches/rebuilds everything pruned here."""
    d = str(tmp_path / "vdb")
    _mkstore(d)
    tmp = os.path.join(d, "chr1.000001.npz.repl.tmp")
    open(tmp, "wb").write(b"half a shipped segment")
    cursor = os.path.join(d, "repl.cursor.json")
    with open(cursor, "w") as f:
        json.dump({"repl_cursor": 1, "leader": "http://127.0.0.1:1",
                   "fingerprint": [1, 2, 3], "epoch": 0, "offsets": {}}, f)
    report = fsck(d, log=lambda m: None)
    codes = _codes(report)
    assert {"repl-tmp", "repl-cursor"} <= codes
    assert "foreign-file" not in codes
    assert report["exit_code"] == 1  # warnings, not fatal damage
    # both findings prescribe the bootstrap re-run, and detection alone
    # never deletes
    for code in ("repl-tmp", "repl-cursor"):
        f = [x for x in report["findings"] if x["code"] == code][0]
        assert "bootstrap" in f["message"]
    assert os.path.exists(tmp) and os.path.exists(cursor)
    report = fsck(d, repair=True, log=lambda m: None)
    assert any("bootstrap" in r or "refetches" in r
               for r in report["repairs"])
    assert not os.path.exists(tmp) and not os.path.exists(cursor)
    assert fsck(d, log=lambda m: None)["status"] == "clean"
