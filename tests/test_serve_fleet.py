"""Multi-process serve fleet: N workers on one port over one readonly
store generation, in both port-sharing modes (SO_REUSEPORT and the
parent accept-handoff fallback), with graceful SIGTERM drain.  The
dead-worker restart case lives in tests/test_fault_matrix.py (fault
point ``serve.worker``)."""

from __future__ import annotations

import json
import re
import signal
import subprocess
import sys
import time
import urllib.request

import pytest

from test_serve import _build_store, _vid


@pytest.fixture(scope="module")
def fleet_store(tmp_path_factory):
    store_dir = str(tmp_path_factory.mktemp("fleet_store"))
    truth = _build_store(store_dir)
    return store_dir, truth


def _spawn_fleet(store_dir: str, workers: int = 2, extra=()):
    proc = subprocess.Popen(
        [sys.executable, "-m", "annotatedvdb_tpu", "serve",
         "--storeDir", store_dir, "--port", "0",
         "--workers", str(workers), *extra],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    line = proc.stdout.readline()
    m = re.search(r"http://([\d.]+):(\d+)", line)
    assert m, f"no fleet address line: {line!r}"
    return proc, m.group(1), int(m.group(2))


def _get(host: str, port: int, path: str, timeout: float = 5.0):
    with urllib.request.urlopen(
        f"http://{host}:{port}{path}", timeout=timeout
    ) as r:
        return r.status, r.read().decode()


def _wait_healthy(host: str, port: int, deadline_s: float = 90.0):
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        try:
            status, _ = _get(host, port, "/healthz")
            if status == 200:
                return
        except OSError:
            pass
        time.sleep(0.25)
    raise AssertionError("fleet never became healthy")


@pytest.mark.parametrize("extra,label", [
    ((), "reuseport-or-default"),
    (("--_forceHandoff",), "parent-accept-handoff"),
])
def test_fleet_serves_and_drains(fleet_store, extra, label):
    store_dir, truth = fleet_store
    proc, host, port = _spawn_fleet(store_dir, workers=2, extra=extra)
    try:
        _wait_healthy(host, port)
        # all three query kinds answer through the shared port
        status, body = _get(host, port, f"/variant/{_vid(truth[0])}")
        assert status == 200
        assert json.loads(body)["position"] == truth[0]["pos"]
        status, body = _get(host, port, "/region/8:1-10000?limit=3")
        assert status == 200 and json.loads(body)["returned"] == 3
        ok = sum(
            1 for r in truth[:20]
            if _get(host, port, f"/variant/{_vid(r)}")[0] == 200
        )
        assert ok == 20, f"{label}: {ok}/20 served"
    finally:
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=60)
    assert rc == 0, proc.stdout.read()[-2000:]


def test_fleet_reuseport_detection_runs():
    from annotatedvdb_tpu.serve.fleet import reuseport_available

    assert isinstance(reuseport_available(), bool)


def test_bad_workers_env_exits_cleanly(tmp_path, capsys, monkeypatch):
    """A malformed AVDB_SERVE_WORKERS must exit ``serve: cannot start``
    rc=1 like every other knob, not an unhandled traceback."""
    from annotatedvdb_tpu.cli.serve import main

    monkeypatch.setenv("AVDB_SERVE_WORKERS", "two")
    rc = main(["--storeDir", str(tmp_path / "missing")])
    assert rc == 1
    assert "bad AVDB_SERVE_WORKERS" in capsys.readouterr().err


def test_fleet_gives_up_on_instant_death_workers(fleet_store, monkeypatch):
    """A worker that can never start (bad inherited env knob) must end
    the fleet with rc=1 after MAX_RAPID_DEATHS consecutive rapid deaths,
    not respawn forever."""
    from annotatedvdb_tpu.serve.fleet import ServeFleet

    store_dir, _truth = fleet_store
    monkeypatch.setenv("AVDB_SERVE_CLIENT_RATE", "abc")
    lines: list[str] = []
    fleet = ServeFleet(store_dir, workers=1, restart_backoff_s=0.01,
                       drain_s=2.0, log=lines.append)
    fleet.MAX_RAPID_DEATHS = 2
    rc = fleet.run()
    assert rc == 1
    assert any("giving up" in ln for ln in lines), lines


def test_fleet_splits_hbm_budget_across_workers(monkeypatch):
    """The HBM budget caps ONE shared device: each worker must get an
    equal share, never the full budget (flag and env var alike)."""
    from annotatedvdb_tpu.cli.serve import _build_parser, _knob_args

    monkeypatch.delenv("AVDB_SERVE_HBM_BUDGET", raising=False)
    args = _build_parser().parse_args(
        ["--storeDir", "x", "--hbmBudget", "1g"]
    )
    knobs = _knob_args(args, workers=4)
    assert knobs[knobs.index("--hbmBudget") + 1] == str((1 << 30) // 4)
    # the inherited env var would re-apply the FULL budget in every
    # worker: the explicit (divided) flag must always be forwarded
    monkeypatch.setenv("AVDB_SERVE_HBM_BUDGET", "512k")
    args = _build_parser().parse_args(["--storeDir", "x"])
    knobs = _knob_args(args, workers=2)
    assert knobs[knobs.index("--hbmBudget") + 1] == str((512 << 10) // 2)
    # unmanaged stays unmanaged
    monkeypatch.delenv("AVDB_SERVE_HBM_BUDGET")
    assert "--hbmBudget" not in _knob_args(args, workers=2)
    # an explicit 0 is the managed degenerate case (nothing resident),
    # NOT unmanaged: it must reach the workers
    args = _build_parser().parse_args(
        ["--storeDir", "x", "--hbmBudget", "0"]
    )
    knobs = _knob_args(args, workers=2)
    assert knobs[knobs.index("--hbmBudget") + 1] == "0"
