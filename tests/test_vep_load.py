"""VEP parser + update-only VEP load tests."""

import gzip
import json

import numpy as np
import pytest

from annotatedvdb_tpu.conseq import ConsequenceRanker
from annotatedvdb_tpu.io.vep import VepResultParser
from annotatedvdb_tpu.loaders import TpuVcfLoader, TpuVepLoader
from annotatedvdb_tpu.store import AlgorithmLedger, VariantStore

VCF = """#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO
1\t10039\trs978760828\tA\tC\t.\t.\tRS=978760828
1\t10051\trs1052373574\tA\tG,T\t.\t.\tRS=1052373574
2\t955\trs1234\tCA\tC\t.\t.\tRS=1234
"""


def vep_result(chrom, pos, vid, ref, alt, norm_alt, rank_terms, freqs=None):
    """Minimal VEP result JSON for one variant."""
    cv = [{"allele_string": f"{ref}/{alt}", "id": vid}]
    if freqs:
        cv[0]["frequencies"] = freqs
        cv[0]["minor_allele"] = norm_alt
        cv[0]["minor_allele_freq"] = 0.01
    return {
        "input": f"{chrom}\t{pos}\t{vid}\t{ref}\t{alt}\t.\t.\t.",
        "most_severe_consequence": rank_terms[0],
        "transcript_consequences": [
            {
                "variant_allele": norm_alt,
                "consequence_terms": rank_terms,
                "gene_id": "ENSG0001",
            },
            {
                "variant_allele": norm_alt,
                "consequence_terms": ["intron_variant"],
                "gene_id": "ENSG0002",
            },
        ],
        "colocated_variants": cv,
    }


@pytest.fixture
def loaded_store(tmp_path):
    vcf = tmp_path / "s.vcf"
    vcf.write_text(VCF)
    store = VariantStore(width=49)
    ledger = AlgorithmLedger(str(tmp_path / "ledger.jsonl"))
    TpuVcfLoader(store, ledger, log=lambda *a: None).load_file(str(vcf), commit=True)
    assert store.n == 4
    return store, ledger


def test_parser_rank_sort_and_most_severe():
    ranker = ConsequenceRanker()
    p = VepResultParser(ranker)
    ann = vep_result("1", 10039, "rs978760828", "A", "C", "C",
                     ["missense_variant"])
    p.rank_and_sort(ann)
    tc = ann["transcript_consequences"]
    assert set(tc.keys()) == {"C"}
    # missense outranks intron -> sorted first, original order preserved in field
    assert tc["C"][0]["consequence_terms"] == ["missense_variant"]
    assert tc["C"][0]["rank"] < tc["C"][1]["rank"]
    assert tc["C"][0]["consequence_is_coding"] is True
    assert tc["C"][1]["consequence_is_coding"] is False
    ms = VepResultParser.most_severe_consequence(ann, "C")
    assert ms["consequence_terms"] == ["missense_variant"]
    assert VepResultParser.most_severe_consequence(ann, "G") is None


def test_parser_frequency_grouping():
    freqs = {"C": {"gnomad": 0.01, "gnomad_afr": 0.02, "af": 0.03, "aa": 0.04}}
    out = VepResultParser._group_by_source(freqs)
    assert out == {
        "C": {
            "GnomAD": {"gnomad": 0.01, "gnomad_afr": 0.02},
            "1000Genomes": {"af": 0.03},
            "ESP": {"aa": 0.04},
        }
    }


def test_parser_cosmic_filtered_and_refsnp_disambiguation():
    ann = {
        "colocated_variants": [
            {"allele_string": "COSMIC_MUTATION", "id": "COSV1",
             "frequencies": {"C": {"af": 0.9}}},
            {"allele_string": "A/C", "id": "rs111",
             "frequencies": {"C": {"af": 0.1}}},
            {"allele_string": "A/C", "id": "rs222",
             "frequencies": {"C": {"af": 0.2}}},
        ]
    }
    # with a matching id, only that covar's frequencies return
    out = VepResultParser.frequencies(ann, "rs111")
    assert out["values"] == {"C": {"1000Genomes": {"af": 0.1}}}
    # without, last non-cosmic wins (reference iterates and overwrites)
    out = VepResultParser.frequencies(ann)
    assert out["values"] == {"C": {"1000Genomes": {"af": 0.2}}}


@pytest.mark.parametrize("link_fast", [True, False])
def test_vep_load_updates_store(tmp_path, loaded_store, link_fast, monkeypatch):
    store, ledger = loaded_store
    # link_fast False forces the slow-link host path (numpy hash/prefix
    # twins) — results must be identical either way
    from annotatedvdb_tpu.store import variant_store as vs

    monkeypatch.setattr(vs, "_TRANSFER_FAST", link_fast)
    results = [
        vep_result("1", 10039, "rs978760828", "A", "C", "C",
                   ["missense_variant", "splice_region_variant"],
                   freqs={"C": {"gnomad": 0.015, "af": 0.02}}),
        vep_result("1", 10051, "rs1052373574", "A", "G,T", "G",
                   ["intron_variant"]),
        # deletion: normalized alt is '-' (VEP convention)
        vep_result("2", 955, "rs1234", "CA", "C", "-",
                   ["frameshift_variant"]),
        # unknown variant -> not_found counter
        vep_result("2", 99999, "rs999", "G", "A", "A", ["intron_variant"]),
    ]
    path = tmp_path / "vep.json.gz"
    with gzip.open(path, "wt") as f:
        for r in results:
            f.write(json.dumps(r) + "\n")

    ranker = ConsequenceRanker()
    loader = TpuVepLoader(store, ledger, ranker, datasource="dbSNP",
                          log=lambda *a: None)
    counters = loader.load_file(str(path), commit=True)
    # 10039, both alts of 10051 (T just gets empty conseq dicts, like the
    # reference writing '{}'), and the 955 deletion
    assert counters["update"] == 4
    assert counters["not_found"] == 1  # rs999 only
    # novel combo was learned during the load
    assert ranker.rank_of("missense_variant,splice_region_variant") is not None

    s1 = store.shard(1)
    i = int(np.where(s1.cols["pos"] == 10039)[0][0])
    ms = s1.annotations["adsp_most_severe_consequence"][i]
    assert ms["consequence_terms"] == ["missense_variant", "splice_region_variant"]
    assert ms["consequence_is_coding"] is True
    assert s1.annotations["allele_frequencies"][i] == {
        "GnomAD": {"gnomad": 0.015}, "1000Genomes": {"af": 0.02},
    }
    ranked = s1.annotations["adsp_ranked_consequences"][i]
    assert len(ranked["transcript_consequences"]) == 2
    # cleaned vep_output: extracted blocks removed, input structured
    vo = s1.annotations["vep_output"][i]
    assert "transcript_consequences" not in vo
    assert "colocated_variants" not in vo
    assert vo["input"]["pos"] == 10039
    # deletion matched via '-' normalized allele
    s2 = store.shard(2)
    j = int(np.where(s2.cols["pos"] == 955)[0][0])
    ms2 = s2.annotations["adsp_most_severe_consequence"][j]
    assert ms2["consequence_terms"] == ["frameshift_variant"]

    # skip_existing: second pass skips rows that already have vep_output
    loader2 = TpuVepLoader(store, ledger, ranker, skip_existing=True,
                           log=lambda *a: None)
    counters2 = loader2.load_file(str(path), commit=True)
    assert counters2["duplicates"] == 4
    assert counters2["update"] == 0


def test_fresh_copy_tolerates_numpy_scalars():
    """_fresh (the store-update un-aliasing copy) must not crash on
    numpy-typed values — a rank field that skips prefetch_ranks' coercion
    would otherwise turn a working load into a mid-load TypeError."""
    import numpy as np

    from annotatedvdb_tpu.loaders.vep_loader import _fresh

    src = {
        "rank": np.int32(7),
        "af": np.float64(0.25),
        "is_coding": np.bool_(True),
        "nested": {"vals": [np.int64(1), 2, "x"]},
    }
    out = _fresh(src)
    assert out == {"rank": 7, "af": 0.25, "is_coding": True,
                   "nested": {"vals": [1, 2, "x"]}}
    assert type(out["rank"]) is int and type(out["is_coding"]) is bool
    out["nested"]["vals"].append(3)
    assert len(src["nested"]["vals"]) == 3  # un-aliased
