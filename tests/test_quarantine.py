"""Quarantine sink + error budgets across the four parser families
(VCF / VEP JSON / CADD TSV / annotation TSV): rejected lines are preserved
replayably (reject -> fix -> replay round trip), and ``--maxErrors`` aborts
deterministically."""

import json
import os

import numpy as np
import pytest

from annotatedvdb_tpu.config import StoreConfig
from annotatedvdb_tpu.store import AlgorithmLedger, VariantStore
from annotatedvdb_tpu.utils.quarantine import (
    ErrorBudget,
    ErrorBudgetExceeded,
    QuarantineSink,
    read_rejects,
    write_replay,
)

_SILENT = lambda *a, **k: None  # noqa: E731


def _sink(store_dir, input_path, loader, max_errors=-1):
    return QuarantineSink(
        store_dir, input_path, loader, budget=ErrorBudget(max_errors)
    )


# ---------------------------------------------------------------------------
# VCF


def _write_vcf(path, rows, with_header=True):
    with open(path, "w") as f:
        if with_header:
            f.write("##fileformat=VCFv4.2\n"
                    "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\n")
        for r in rows:
            f.write(r + "\n")


GOOD_VCF = [f"8\t{1000 + 3 * i}\trs{i}\tA\tG\t.\t.\t." for i in range(8)]
BAD_VCF = ["8\tnot-a-position\trsX\tA\tG\t.\t.\t.",
           "too\tfew"]


def test_vcf_quarantine_roundtrip(tmp_path, monkeypatch):
    from annotatedvdb_tpu.loaders import TpuVcfLoader

    monkeypatch.setenv("AVDB_INGEST_ENGINE", "python")  # capture content
    store_dir = str(tmp_path / "vdb")
    vcf = str(tmp_path / "in.vcf")
    # interleave bad rows mid-file
    _write_vcf(vcf, GOOD_VCF[:4] + BAD_VCF + GOOD_VCF[4:])
    sink = _sink(store_dir, vcf, "load-vcf")
    store, ledger = StoreConfig(store_dir).open()
    loader = TpuVcfLoader(store, ledger, batch_size=64, log=_SILENT,
                          quarantine=sink)
    counters = loader.load_file(vcf, commit=True,
                                persist=lambda: store.save(store_dir))
    loader.close()
    store.save(store_dir)
    assert counters["variant"] == 8
    assert counters["rejected"] == 2
    meta, records = read_rejects(sink.path)
    assert meta["loader"] == "load-vcf"
    assert [r["raw"] for r in records] == BAD_VCF
    assert all(r["line"] for r in records)  # line numbers captured

    # fix the quarantined lines in place, replay, and load the replay file
    fixed = ["8\t50000\trsX\tA\tG\t.\t.\t.",
             "8\t50003\trsY\tA\tG\t.\t.\t."]
    with open(sink.path) as f:
        recs = [json.loads(l) for l in f if l.strip()]
    for rec, line in zip([r for r in recs if "meta" not in r], fixed):
        rec["raw"] = line
    with open(sink.path, "w") as f:
        for rec in recs:
            f.write(json.dumps(rec) + "\n")
    replay = str(tmp_path / "replay.vcf")
    assert write_replay(sink.path, replay) == 2
    counters = loader.load_file(replay, commit=True,
                                persist=lambda: store.save(store_dir))
    loader.close()
    store.save(store_dir)
    assert counters["variant"] == 8 + 2  # cumulative: the 2 fixed rows landed
    assert VariantStore.load(store_dir).n == 10


def test_vcf_error_budget_aborts(tmp_path, monkeypatch):
    from annotatedvdb_tpu.loaders import TpuVcfLoader

    monkeypatch.setenv("AVDB_INGEST_ENGINE", "python")
    store_dir = str(tmp_path / "vdb")
    vcf = str(tmp_path / "in.vcf")
    _write_vcf(vcf, BAD_VCF + GOOD_VCF)
    store, ledger = StoreConfig(store_dir).open()
    loader = TpuVcfLoader(
        store, ledger, batch_size=64, log=_SILENT,
        quarantine=_sink(store_dir, vcf, "load-vcf", max_errors=0),
    )
    with pytest.raises(ErrorBudgetExceeded):
        loader.load_file(vcf, commit=False)
    loader.close()
    # the aborting row itself was preserved before the abort
    _meta, records = read_rejects(
        os.path.join(store_dir, "quarantine",
                     os.path.basename(vcf) + ".rejects.jsonl")
    )
    assert records and records[0]["raw"] == BAD_VCF[0]


# ---------------------------------------------------------------------------
# VEP JSON


GOOD_VEP = json.dumps({"input": "1\t100\trs1\tA\tG", "id": "rs1"})
BAD_VEP = '{"input": "1\\t100\\trs1\\tA\\tG", BROKEN'


def _vep_loader(store_dir, quarantine=None, max_errors=-1):
    from annotatedvdb_tpu.conseq import ConsequenceRanker
    from annotatedvdb_tpu.loaders import TpuVepLoader

    store, ledger = StoreConfig(store_dir).open()
    return TpuVepLoader(
        store, ledger, ConsequenceRanker(), log=_SILENT,
        quarantine=quarantine, max_errors=max_errors,
    )


def test_vep_quarantine_roundtrip(tmp_path):
    store_dir = str(tmp_path / "vdb")
    vep = str(tmp_path / "r.json")
    with open(vep, "w") as f:
        f.write(GOOD_VEP + "\n" + BAD_VEP + "\n" + GOOD_VEP + "\n")
    sink = _sink(store_dir, vep, "load-vep")
    loader = _vep_loader(store_dir, quarantine=sink)
    counters = loader.load_file(vep, commit=False)
    assert counters["rejected"] == 1
    assert counters["line"] == 3
    _meta, records = read_rejects(sink.path)
    assert records[0]["raw"] == BAD_VEP

    # fix + replay: the repaired line loads with no rejects
    with open(sink.path) as f:
        recs = [json.loads(l) for l in f if l.strip()]
    for rec in recs:
        if "meta" not in rec:
            rec["raw"] = GOOD_VEP
    with open(sink.path, "w") as f:
        for rec in recs:
            f.write(json.dumps(rec) + "\n")
    replay = str(tmp_path / "replay.json")
    assert write_replay(sink.path, replay) == 1
    loader2 = _vep_loader(store_dir)
    counters = loader2.load_file(replay, commit=False)
    assert counters.get("rejected", 0) == 0
    assert counters["line"] == 1


def test_vep_multidoc_line_loads_every_doc(tmp_path, monkeypatch):
    """One physical line carrying two comma-joined docs must load BOTH and
    must not desync later docs' line attribution (the whole-batch decode
    falls back to per-line pairing when counts mismatch).  Pins the Python
    decode path — the native transformer's treatment of such malformed
    lines (first doc wins) predates this code."""
    monkeypatch.setenv("AVDB_NATIVE_VEP", "0")
    store_dir = str(tmp_path / "vdb")
    vep = str(tmp_path / "r.json")
    with open(vep, "w") as f:
        f.write(GOOD_VEP + "," + GOOD_VEP + "\n" + GOOD_VEP + "\n")
    loader = _vep_loader(store_dir, quarantine=_sink(store_dir, vep,
                                                     "load-vep"))
    counters = loader.load_file(vep, commit=False)
    assert counters.get("rejected", 0) == 0
    assert counters["line"] == 2
    assert counters["variant"] == 3  # all three docs parsed


def test_vep_error_budget_aborts(tmp_path):
    store_dir = str(tmp_path / "vdb")
    vep = str(tmp_path / "r.json")
    with open(vep, "w") as f:
        f.write(BAD_VEP + "\n" + GOOD_VEP + "\n")
    loader = _vep_loader(
        store_dir, quarantine=_sink(store_dir, vep, "load-vep", max_errors=0)
    )
    with pytest.raises(ErrorBudgetExceeded):
        loader.load_file(vep, commit=False)


# ---------------------------------------------------------------------------
# CADD TSV


CADD_HEADER = "#Chrom\tPos\tRef\tAlt\tRawScore\tPHRED"
GOOD_CADD = ["1\t10\tA\tC\t0.5\t10.1", "1\t11\tA\tG\t0.6\t11.0"]
BAD_CADD = ["1\tnot-a-pos\tA\tC\t0.5\t10.1"]


def _cadd_store(tmp_path):
    store_dir = str(tmp_path / "vdb")
    store, ledger = StoreConfig(store_dir).open()
    w = store.width
    store.shard(1).append(
        {"pos": np.asarray([10], np.int32),
         "h": np.asarray([7], np.uint32),
         "ref_len": np.full(1, 1, np.int32),
         "alt_len": np.full(1, 1, np.int32)},
        np.full((1, w), 65, np.uint8), np.full((1, w), 67, np.uint8),
    )
    return store_dir, store, ledger


def test_cadd_quarantine_and_budget(tmp_path, monkeypatch):
    from annotatedvdb_tpu.loaders.cadd_loader import TpuCaddUpdater

    monkeypatch.setenv("AVDB_NATIVE_CADD", "0")  # content capture
    store_dir, store, ledger = _cadd_store(tmp_path)
    dbdir = str(tmp_path / "cadd")
    os.makedirs(dbdir)
    snv = "snvs.tsv"
    with open(os.path.join(dbdir, snv), "w") as f:
        f.write("\n".join([CADD_HEADER] + GOOD_CADD[:1] + BAD_CADD
                          + GOOD_CADD[1:]) + "\n")
    sink = _sink(store_dir, os.path.join(dbdir, "cadd-scores"), "load-cadd")
    updater = TpuCaddUpdater(store, ledger, dbdir, snv_file=snv,
                             log=_SILENT, quarantine=sink)
    counters = updater.update_all(commit=False)
    assert counters["rejected"] == 1
    _meta, records = read_rejects(sink.path)
    assert records[0]["raw"] == BAD_CADD[0]
    assert snv in records[0]["reason"]  # table attribution
    assert records[0]["line"] == 3

    # budget: zero tolerance aborts on the bad row
    b = tmp_path / "b"
    b.mkdir()
    _store_dir2, store2, ledger2 = _cadd_store(b)
    updater2 = TpuCaddUpdater(store2, ledger2, dbdir, snv_file=snv,
                              log=_SILENT, max_errors=0)
    with pytest.raises(ErrorBudgetExceeded):
        updater2.update_all(commit=False)

    # replay round trip at the reader level: fixed lines parse cleanly
    with open(sink.path) as f:
        recs = [json.loads(l) for l in f if l.strip()]
    for rec in recs:
        if "meta" not in rec:
            rec["raw"] = "1\t12\tA\tT\t0.7\t12.0"
    with open(sink.path, "w") as f:
        for rec in recs:
            f.write(json.dumps(rec) + "\n")
    replay = str(tmp_path / "replay.tsv")
    assert write_replay(sink.path, replay) == 1
    from annotatedvdb_tpu.io.cadd import CaddFileReader

    rejects2 = []
    blocks = list(CaddFileReader(
        replay, width=8,
        on_reject=lambda *a: rejects2.append(a),
    ).blocks_all())
    assert rejects2 == []
    assert sum(b.n for _c, b in blocks) == 1


# ---------------------------------------------------------------------------
# annotation TSV


TSV_HEADER = "variant\tother_annotation"
GOOD_TSV = ['1:10:A:C\t{"source": "x"}']
BAD_TSV = ['garbage-id\t{"source": "y"}',      # unparseable variant id
           '1:20:A:G\t{not-json']              # bad JSON cell


def test_tsv_quarantine_roundtrip_and_budget(tmp_path):
    from annotatedvdb_tpu.loaders.txt_loader import TpuTextLoader

    store_dir = str(tmp_path / "vdb")
    tsv = str(tmp_path / "ann.tsv")
    with open(tsv, "w") as f:
        f.write("\n".join([TSV_HEADER] + GOOD_TSV + BAD_TSV) + "\n")
    sink = _sink(store_dir, tsv, "update-variant-annotation")
    store, ledger = StoreConfig(store_dir).open()
    loader = TpuTextLoader(store, ledger, log=_SILENT, quarantine=sink)
    counters = loader.load_file(tsv, commit=True,
                                persist=lambda: store.save(store_dir))
    store.save(store_dir)
    assert counters["rejected"] == 2
    assert counters["inserted"] == 1  # the good metaseq row inserted
    meta, records = read_rejects(sink.path)
    assert meta["header"] == TSV_HEADER  # replay restores the header
    assert [r["raw"] for r in records] == BAD_TSV

    # fix + replay: header is reconstructed, both rows land
    with open(sink.path) as f:
        recs = [json.loads(l) for l in f if l.strip()]
    fixed = iter(['1:30:A:C\t{"source": "y"}', '1:20:A:G\t{"source": "z"}'])
    for rec in recs:
        if "meta" not in rec:
            rec["raw"] = next(fixed)
    with open(sink.path, "w") as f:
        for rec in recs:
            f.write(json.dumps(rec) + "\n")
    replay = str(tmp_path / "replay.tsv")
    assert write_replay(sink.path, replay) == 2
    assert open(replay).readline().rstrip("\n") == TSV_HEADER
    loader2 = TpuTextLoader(store, ledger, log=_SILENT)
    counters = loader2.load_file(replay, commit=True,
                                 persist=lambda: store.save(store_dir))
    store.save(store_dir)
    assert counters.get("rejected", 0) == 0
    assert counters["inserted"] == 2
    assert VariantStore.load(store_dir).n == 3

    # budget: zero tolerance aborts on the first bad row
    store_dir2 = str(tmp_path / "vdb2")
    store2, ledger2 = StoreConfig(store_dir2).open()
    loader3 = TpuTextLoader(
        store2, ledger2, log=_SILENT,
        quarantine=_sink(store_dir2, tsv, "update-variant-annotation",
                         max_errors=0),
    )
    with pytest.raises(ErrorBudgetExceeded):
        loader3.load_file(tsv, commit=False)


def test_sink_rotates_unreplayed_rejects(tmp_path):
    """A second load sharing the input basename must not clobber the first
    load's un-replayed rejects: one prior generation survives at <path>.1."""
    store_dir = str(tmp_path / "vdb")
    s1 = _sink(store_dir, "x.vcf", "load-vcf")
    s1.reject(1, "first-gen line", "bad")
    s1.close()
    s2 = _sink(store_dir, "x.vcf", "load-vep")
    s2.reject(9, "second-gen line", "bad")
    s2.close()
    _meta, records = read_rejects(s2.path)
    assert records[0]["raw"] == "second-gen line"
    _meta1, records1 = read_rejects(s2.path + ".1")
    assert records1[0]["raw"] == "first-gen line"


# ---------------------------------------------------------------------------
# update loaders (VCF-driven) share the same reader hook


def test_update_loader_budget_aborts(tmp_path, monkeypatch):
    from annotatedvdb_tpu.loaders.qc_loader import TpuQcPvcfLoader

    monkeypatch.setenv("AVDB_INGEST_ENGINE", "python")
    store_dir = str(tmp_path / "vdb")
    vcf = str(tmp_path / "qc.vcf")
    _write_vcf(vcf, BAD_VCF + GOOD_VCF)
    store, ledger = StoreConfig(store_dir).open()
    loader = TpuQcPvcfLoader(
        store, ledger, "r4", log=_SILENT,
        quarantine=_sink(store_dir, vcf, "update-qc", max_errors=0),
    )
    with pytest.raises(ErrorBudgetExceeded):
        loader.load_file(vcf, commit=False)
    _meta, records = read_rejects(
        os.path.join(store_dir, "quarantine",
                     os.path.basename(vcf) + ".rejects.jsonl")
    )
    assert records[0]["raw"] == BAD_VCF[0]
