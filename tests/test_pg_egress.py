"""Postgres schema generation + COPY egress tests (no live database: the
DDL is checked structurally and the COPY stream is parsed back and compared
against the store row-for-row)."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from annotatedvdb_tpu.io.pg_egress import (
    VARIANT_COPY_COLUMNS, export_store, pg_escape, shard_rows,
)
from annotatedvdb_tpu.loaders import TpuVcfLoader
from annotatedvdb_tpu.oracle.binindex import BinTree, closed_form_bin, closed_form_path
from annotatedvdb_tpu.sql import full_schema
from annotatedvdb_tpu.sql.schema import PARTITION_LABELS, SCHEMA
from annotatedvdb_tpu.store import AlgorithmLedger, VariantStore
from annotatedvdb_tpu.store.variant_store import JSONB_COLUMNS

VCF = """\
#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO
1\t100\trs1\tA\tG,T\t.\t.\tRS=1;FREQ=GnomAD:0.5,0.25,0.1
22\t15625\t.\tAT\tA\t.\t.\t.
X\t70000\t.\tC\tCAGAGAG\t.\t.\t.
"""


def build_store(tmp_path):
    store = VariantStore(width=16)
    ledger = AlgorithmLedger(str(tmp_path / "ledger.jsonl"))
    vcf = tmp_path / "t.vcf"
    vcf.write_text(VCF)
    TpuVcfLoader(store, ledger, log=lambda *a: None).load_file(
        str(vcf), commit=True
    )
    return store, ledger


def test_schema_structure():
    sqls = dict(full_schema())
    variant = sqls["05_variant_table"]
    assert "PARTITION BY LIST (chromosome)" in variant
    assert "UNLOGGED" in variant
    for label in PARTITION_LABELS:
        assert f"Variant_{label} " in variant
    assert len(PARTITION_LABELS) == 25
    for col in JSONB_COLUMNS:
        assert f"{col} JSONB" in variant
    idx = sqls["07_variant_indexes"]
    assert "USING HASH (record_primary_key)" in idx
    assert "USING GIST (bin_index)" in idx
    assert "LEFT(metaseq_id, 50)" in idx
    assert "row_algorithm_id" in idx
    assert "find_bin_index" in sqls["03_find_bin_index"]
    assert "jsonb_merge" in sqls["02_jsonb_merge"]
    assert "SERIAL PRIMARY KEY" in sqls["08_algorithm_invocation"]
    assert "alter_variant_autovacuum" in sqls["09_autovacuum"]
    assert "set_bin_index" in sqls["06_bin_index_trigger"]
    assert "find_variant_by_metaseq_id" in sqls["11_metaseq_lookup"]


def test_find_bin_index_sql_matches_oracle():
    """Evaluate the PLpgSQL closed-form logic (re-expressed in Python) against
    the recursive BinTree oracle — guards the arithmetic embedded in the
    generated SQL."""
    tree = BinTree("chr9", 141_213_431)
    rng = np.random.default_rng(7)
    for _ in range(150):
        start = int(rng.integers(1, 141_000_000))
        end = start + int(rng.integers(0, 50_000))
        lvl, leaf = closed_form_bin(start, end)
        path = closed_form_path("chr9", lvl, leaf)
        want_level, want_path = tree.find_bin(start, end)
        assert (lvl, path) == (want_level, want_path), (start, end)


def test_pg_escape():
    assert pg_escape(None) == "\\N"
    assert pg_escape(True) == "t"
    assert pg_escape(False) == "f"
    assert pg_escape("a\tb\nc\\d") == "a\\tb\\nc\\\\d"
    assert pg_escape(42) == "42"


def test_export_roundtrip(tmp_path):
    store, ledger = build_store(tmp_path)
    out = tmp_path / "pg"
    counts = export_store(store, str(out), ledger)
    assert sum(counts.values()) == store.n == 4
    # schema + load script present
    assert (out / "load.sql").exists()
    assert (out / "schema" / "05_variant_table.sql").exists()
    load = (out / "load.sql").read_text()
    assert "\\copy" in load and "ON_ERROR_STOP" in load

    # parse chr1 COPY rows back and verify against the store
    rows = [
        line.split("\t")
        for line in (out / "data" / "variant_chr1.copy").read_text().splitlines()
    ]
    assert len(rows) == 2  # multi-allelic expansion of 1:100 A>G,T
    cols = {c: i for i, c in enumerate(VARIANT_COPY_COLUMNS)}
    # rows are stored sorted by (pos, allele-hash), not input order
    first = next(r for r in rows if r[cols["metaseq_id"]] == "1:100:A:G")
    assert first[cols["chromosome"]] == "chr1"
    assert first[cols["record_primary_key"]] == "1:100:A:G:rs1"
    assert first[cols["metaseq_id"]] == "1:100:A:G"
    assert first[cols["position"]] == "100"
    assert first[cols["is_multi_allelic"]] == "t"
    assert first[cols["ref_snp_id"]] == "rs1"
    assert first[cols["bin_index"]].startswith("chr1.L1.B1")
    display = json.loads(first[cols["display_attributes"]])
    assert display["variant_class"] == "single nucleotide variant"
    freqs = json.loads(first[cols["allele_frequencies"]])
    assert freqs["GnomAD"]["gmaf"] == 0.25
    # NULL JSONB columns dump as \N
    assert first[cols["cadd_scores"]] == "\\N"

    # the 22:15625 deletion crosses a leaf boundary -> shallower bin level
    row22 = (out / "data" / "variant_chr22.copy").read_text().splitlines()[0].split("\t")
    assert row22[cols["bin_index"]] == closed_form_path("chr22", 12, 0)

    # ledger rows dumped for undo parity
    inv = (out / "data" / "algorithm_invocation.copy").read_text().splitlines()
    assert len(inv) == 1 and inv[0].split("\t")[0] == "1"


def test_install_schema_cli(tmp_path):
    store, _ = build_store(tmp_path)
    store.save(str(tmp_path / "vdb"))
    env = dict(os.environ, PYTHONPATH=os.pathsep.join(sys.path))
    proc = subprocess.run(
        [sys.executable, "-m", "annotatedvdb_tpu.cli.install_schema",
         "--outputDir", str(tmp_path / "pgx")],
        capture_output=True, text=True, env=env,
    )
    assert proc.returncode == 0, proc.stderr
    assert (tmp_path / "pgx" / "schema" / "03_find_bin_index.sql").exists()
