"""Test harness config: force JAX onto a virtual 8-device CPU mesh so
multi-chip sharding is exercised without TPU hardware (SURVEY.md §4d).

Must run before any ``import jax`` in test modules — pytest imports conftest
first, so setting the env here is sufficient."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # hard override: the ambient env pins the TPU platform
# CLI subprocess tests inherit this: utils.runtime.pin_platform short-circuits
# on it (no accelerator probe, instant CPU pin) so no test can hang on the tunnel
os.environ["AVDB_JAX_PLATFORM"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Persistent XLA compilation cache: the suite's wall time is dominated by
# jit compiles (the mesh programs alone are ~10s each), and tier-1 runs
# under a hard timeout.  The cache is content-keyed — a stale entry can
# never serve wrong code — and subprocess tests (serve fleet workers,
# CLI loads) inherit it through the environment, so re-runs and
# sibling-process first-touches load from disk instead of recompiling.
# setdefault: an explicit caller choice (or disabling with an empty
# value) always wins.
import tempfile as _tempfile

_uid = getattr(os, "getuid", lambda: "u")()
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(_tempfile.gettempdir(), f"avdb_test_xla_cache.{_uid}"),
)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "-1")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")

# A sitecustomize.py in this image re-pins jax_platforms to the TPU tunnel at
# import time, overriding the env var — so the env alone is not enough. Update
# the config after import; the backend is initialized lazily, so this wins as
# long as it runs before the first jax.devices() call.
import jax

jax.config.update("jax_platforms", "cpu")

import random

import numpy as np
import pytest


@pytest.fixture
def rng():
    return random.Random(20260729)


BASES = "ACGT"


def random_allele(rng, min_len=1, max_len=12):
    return "".join(rng.choice(BASES) for _ in range(rng.randint(min_len, max_len)))


def random_variants(rng, n, max_len=12):
    """Mix of shapes: SNVs, MNVs, inversions, pure ins/del, indels, dups,
    shared-prefix pairs — the cases that exercise every branch of the
    reference's annotator."""
    out = []
    for _ in range(n):
        kind = rng.randrange(8)
        chrom = rng.choice([str(c) for c in range(1, 23)] + ["X", "Y", "M"])
        pos = rng.randint(1, 248_000_000)
        if kind == 0:  # SNV
            ref = rng.choice(BASES)
            alt = rng.choice(BASES.replace(ref, ""))
        elif kind == 1:  # MNV (maybe accidental inversion)
            L = rng.randint(2, max_len)
            ref = random_allele(rng, L, L)
            alt = random_allele(rng, L, L)
        elif kind == 2:  # inversion
            ref = random_allele(rng, 2, max_len)
            alt = ref[::-1]
        elif kind == 3:  # pure insertion (anchored)
            ref = rng.choice(BASES)
            alt = ref + random_allele(rng, 1, max_len - 1)
        elif kind == 4:  # duplication: ref[1:] = k copies of inserted motif
            motif = random_allele(rng, 1, 4)
            k = rng.randint(1, 3)
            anchor = rng.choice(BASES)
            ref = anchor + motif * k
            alt = ref + motif
        elif kind == 5:  # deletion (anchored)
            alt = rng.choice(BASES)
            ref = alt + random_allele(rng, 1, max_len - 1)
        elif kind == 6:  # indel with shared prefix
            shared = random_allele(rng, 1, 4)
            ref = shared + random_allele(rng, 1, 5)
            alt = shared + random_allele(rng, 1, 5)
        else:  # arbitrary ragged pair
            ref = random_allele(rng, 1, max_len)
            alt = random_allele(rng, 1, max_len)
        out.append((chrom, pos, ref, alt))
    return out
