"""Batch region join (``POST /regions`` / ``QueryEngine.regions_serve``)
oracle-parity battery.

The contract under test: every per-interval envelope of a batch answer is
**byte-identical** to (a) the corresponding single ``region()`` call and
(b) a brute-force per-row host reference scan that shares only the record
renderer with the engine — across filters, limit, count-only, the
``host_only`` fallback, the forced-device path, and both HTTP front ends.
The interval-index build (including its collision fallback, exercised by
a planted shadowed duplicate) and the tokenization output are pinned
against the scalar bin oracle and the brute counts.
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from annotatedvdb_tpu.loaders.lookup import identity_hashes
from annotatedvdb_tpu.oracle.binindex import closed_form_bin, closed_form_path
from annotatedvdb_tpu.serve import (
    DeviceBreaker,
    QueryEngine,
    QueryError,
    SnapshotManager,
    StaticSnapshots,
    render_variant,
)
from annotatedvdb_tpu.store import VariantStore
from annotatedvdb_tpu.store.variant_store import RawJson, Segment
from annotatedvdb_tpu.types import chromosome_label, encode_allele_array

WIDTH = 8
CHROMS = (1, 8, 23)
BASES = ("A", "C", "G", "T")


# ---------------------------------------------------------------------------
# synthetic multi-chromosome store (the test_serve shape: three disjoint
# segments per chromosome plus one OVERLAPPING chr8 segment carrying a
# shadowed duplicate — which forces the interval index down its
# collision-dedup path)


def _rows_for(code: int, base_pos: int, n: int, salt: int):
    rows = []
    for i in range(n):
        pos = base_pos + 977 * i
        k = (i + salt) % 4
        ref = BASES[k]
        alt = BASES[(k + 1) % 4] if i % 3 else ref + "TG"
        rows.append({
            "chrom": code, "pos": pos, "ref": ref, "alt": alt,
            "rs": (1000 * code + i) if i % 2 else -1,
            "cadd": round(0.5 * i + code, 2) if i % 3 == 0 else None,
            "rank": (i % 30) + 1 if i % 4 == 0 else None,
            "vep": i % 5 == 0,
        })
    return rows


def _append(shard, rows, direct: bool = False):
    refs = [r["ref"] for r in rows]
    alts = [r["alt"] for r in rows]
    ref, ref_len = encode_allele_array(refs, WIDTH)
    alt, alt_len = encode_allele_array(alts, WIDTH)
    h = identity_hashes(WIDTH, ref, alt, ref_len, alt_len, refs, alts)
    cols = {
        "pos": np.asarray([r["pos"] for r in rows], np.int32),
        "h": h, "ref_len": ref_len, "alt_len": alt_len,
        "ref_snp": np.asarray([r["rs"] for r in rows], np.int64),
    }
    ann = {
        "cadd_scores": [
            {"CADD_raw_score": r["cadd"] / 10, "CADD_phred": r["cadd"]}
            if r["cadd"] is not None else None for r in rows
        ],
        "adsp_most_severe_consequence": [
            {"conseq": "missense_variant", "rank": r["rank"]}
            if r["rank"] is not None else None for r in rows
        ],
        "vep_output": [
            RawJson(f'{{"input":"{r["chrom"]}:{r["pos"]}","n":{i}}}')
            if r["vep"] else None for i, r in enumerate(rows)
        ],
    }
    long_alleles = [
        (r["ref"], r["alt"])
        if len(r["ref"]) > WIDTH or len(r["alt"]) > WIDTH else None
        for r in rows
    ]
    if direct:
        shard.append_segment(Segment.build(
            cols, ref, alt, annotations=ann, long_alleles=long_alleles
        ))
        shard._starts_cache = None
    else:
        shard.append(cols, ref, alt, annotations=ann,
                     long_alleles=long_alleles)


def _build_store(store_dir: str | None):
    store = VariantStore(width=WIDTH)
    truth: list[dict] = []
    for code in CHROMS:
        shard = store.shard(code)
        for run, base in enumerate((500, 120_000, 2_000_000)):
            rows = _rows_for(code, base, 40, salt=run)
            _append(shard, rows)
            truth.extend(rows)
    shard = store.shard(8)
    dup_src = next(r for r in truth if r["chrom"] == 8 and r["pos"] == 500)
    shadowed = dict(dup_src, cadd=999.0, rank=1, vep=False)
    fresh = {"chrom": 8, "pos": 501, "ref": "T", "alt": "C", "rs": 77,
             "cadd": 33.3, "rank": 2, "vep": False}
    _append(shard, [shadowed, fresh], direct=True)
    truth.append(fresh)
    if store_dir is not None:
        store.save(store_dir)
    return store, truth


# ---------------------------------------------------------------------------
# brute-force reference (plain host Python; shares only the renderer)


def _brute_region_rows(shard, start: int, end: int):
    rows = []
    for si, seg in enumerate(shard.segments):
        for j in range(seg.n):
            p = int(seg.cols["pos"][j])
            if start <= p <= end:
                rows.append((p, int(seg.cols["h"][j]), si, j))
    rows.sort(key=lambda r: (r[0], r[1], r[2]))
    starts = shard._starts()
    kept, seen = [], set()
    for p, h, si, j in rows:
        ident = (p, h) + shard.alleles(int(starts[si]) + j)
        if ident in seen:
            continue
        seen.add(ident)
        kept.append((si, j))
    return kept


def _brute_region_text(store, generation: int, code: int, start: int,
                       end: int, min_cadd=None, max_rank=None, limit=None):
    label = chromosome_label(code)
    level, leaf = closed_form_bin(start, end)
    shard = store.shards.get(code)
    kept = _brute_region_rows(shard, start, end) if shard is not None else []
    if min_cadd is not None or max_rank is not None:
        filtered = []
        for si, j in kept:
            seg = shard.segments[si]

            def field(col, name):
                v = seg.obj[col][j] if seg.obj[col] is not None else None
                return v.get(name) if v is not None else None

            if min_cadd is not None:
                phred = field("cadd_scores", "CADD_phred")
                if phred is None or phred < min_cadd:
                    continue
            if max_rank is not None:
                rank = field("adsp_most_severe_consequence", "rank")
                if rank is None or rank > max_rank:
                    continue
            filtered.append((si, j))
        kept = filtered
    shown = kept if limit is None else kept[:limit]
    starts = shard._starts() if shard is not None else None
    rendered = [
        render_variant(shard, code, int(starts[si]) + j) for si, j in shown
    ]
    return (
        f'{{"region":{json.dumps(f"{label}:{start}-{end}")}'
        f',"bin_level":{level}'
        f',"bin_index":{json.dumps(closed_form_path(label, level, leaf))}'
        f',"count":{len(kept)}'
        f',"returned":{len(rendered)}'
        f',"generation":{generation}'
        ',"variants":[' + ",".join(rendered) + "]}"
    )


#: panel covering every interesting shape: dup/long-allele corners, segment
#: interiors, whole loaded ranges, gaps, an unloaded chromosome, repeats
PANEL = [
    (8, 1, 10_000), (8, 490, 600), (8, 120_000, 160_000),
    (1, 1, 3_000_000), (23, 2_000_000, 2_005_000), (8, 50_000, 60_000),
    (11, 1, 5_000), (1, 500, 500), (8, 490, 600),
    (23, 1, 4_000_000), (1, 2_000_000, 2_038_000),
]


def _specs():
    return [f"{chromosome_label(c)}:{s}-{e}" for c, s, e in PANEL]


@pytest.fixture(scope="module")
def served(tmp_path_factory):
    store_dir = str(tmp_path_factory.mktemp("regions_store"))
    _store, truth = _build_store(store_dir)
    manager = SnapshotManager(store_dir)
    engine = QueryEngine(manager, region_cache_size=8)
    return store_dir, truth, manager, engine


# ---------------------------------------------------------------------------
# engine parity


def test_regions_parity_vs_sequential_and_brute(served):
    _dir, _truth, manager, engine = served
    snap = manager.current()
    specs = _specs()
    result = engine.regions_serve(specs)
    assert len(result.pages) == len(specs)
    for (code, start, end), spec, page in zip(PANEL, specs, result.pages):
        body = page.assemble()
        assert body == engine.region(spec), spec
        assert body == _brute_region_text(
            snap.store, snap.generation, code, start, end
        ), spec


def test_regions_filters_and_limit_parity(served):
    _dir, _truth, manager, engine = served
    snap = manager.current()
    specs = _specs()
    for min_cadd, max_rank, limit in (
        (10.0, None, None), (None, 5, None), (4.0, 10, None),
        (None, None, 3), (1.0, 25, 2), (None, None, 0), (5.0, None, 0),
    ):
        result = engine.regions_serve(
            specs, min_cadd=min_cadd, max_conseq_rank=max_rank, limit=limit
        )
        for (code, start, end), spec, page in zip(PANEL, specs,
                                                  result.pages):
            body = page.assemble()
            assert body == engine.region(
                spec, min_cadd=min_cadd, max_conseq_rank=max_rank,
                limit=limit,
            ), (spec, min_cadd, max_rank, limit)
            assert body == _brute_region_text(
                snap.store, snap.generation, code, start, end,
                min_cadd=min_cadd, max_rank=max_rank, limit=limit,
            ), (spec, min_cadd, max_rank, limit)


def test_count_only_never_materializes_rows(served):
    """limit=0 with no filters must answer from span widths alone — no
    (segment, row) pair is ever located."""
    _dir, _truth, _manager, engine = served
    result = engine.regions_serve(_specs(), limit=0)
    for page in result.pages:
        assert page.shown == []
    counts = [json.loads(p.assemble())["count"] for p in result.pages]
    assert counts[0] > 0 and counts[6] == 0  # loaded vs unloaded chrom


def test_shadowed_duplicate_stays_hidden_in_batch(served):
    """The chr8 overlapping segment's duplicate identity (cadd=999) must
    stay first-wins-shadowed through the interval index's collision
    build path."""
    _dir, _truth, _manager, engine = served
    result = engine.regions_serve(["8:490-600"])
    recs = json.loads(result.pages[0].assemble())["variants"]
    dup = [r for r in recs if r["position"] == 500]
    assert dup, "expected the pos-500 row in range"
    for r in dup:
        cadd = r["annotations"].get("cadd_scores")
        assert cadd is None or cadd["CADD_phred"] != 999.0


def test_host_only_and_forced_device_byte_identical(served):
    store_dir, _truth, _manager, engine = served
    specs = _specs()
    want = [p.assemble() for p in engine.regions_serve(specs).pages]
    host = engine.regions_serve(specs, host_only=True)
    assert [p.assemble() for p in host.pages] == want
    # forced device: every group goes through the jitted kernel
    dev_engine = QueryEngine(
        SnapshotManager(store_dir), region_cache_size=0,
        regions_device_min=0,
    )
    dev = dev_engine.regions_serve(specs)
    assert [p.assemble() for p in dev.pages] == want
    # the single-region route rides the same machinery
    for spec, body in zip(specs, want):
        assert dev_engine.region(spec) == body
        assert dev_engine.region(spec, host_only=True) == body


def test_device_failure_falls_back_host_and_feeds_breaker(served):
    store_dir, _truth, _manager, engine = served
    specs = _specs()
    want = [p.assemble() for p in engine.regions_serve(specs).pages]
    breaker = DeviceBreaker(cooldown_s=30.0)
    sick = QueryEngine(
        SnapshotManager(store_dir), region_cache_size=0,
        regions_device_min=0, breaker=breaker,
    )
    calls = {"n": 0}

    def boom(index, starts, ends):
        calls["n"] += 1
        raise RuntimeError("injected device kernel failure")

    sick._device_spans = boom
    for _ in range(breaker.failure_threshold):
        got = sick.regions_serve(specs)
        # correct bytes every time: the host twin answered
        assert [p.assemble() for p in got.pages] == want
    # every touched group tripped open; the device path stops being paid
    codes = sorted({c for c, _s, _e in PANEL
                    if sick.snapshots.current().store.shards.get(c)})
    assert all(breaker.state(c) == "open" for c in codes)
    before = calls["n"]
    got = sick.regions_serve(specs)
    assert [p.assemble() for p in got.pages] == want
    assert calls["n"] == before  # open breaker: no device attempt


def test_batch_grammar_and_cap(served):
    store_dir, _truth, _manager, engine = served
    with pytest.raises(QueryError):
        engine.regions_serve(["8:1-100", "not-a-region"])
    with pytest.raises(QueryError):
        engine.regions_serve(["8:9-3"])
    capped = QueryEngine(
        SnapshotManager(store_dir), region_cache_size=0, regions_max=2
    )
    with pytest.raises(QueryError, match="cap"):
        capped.regions_serve(["8:1-10", "8:1-10", "8:1-10"])


def test_tokenize_matches_oracle_and_brute_counts(served):
    _dir, _truth, manager, engine = served
    snap = manager.current()
    specs = _specs()
    result = engine.regions_serve(specs, limit=0, tokenize=True)
    obj = json.loads(result.assemble())
    tok = obj["tokens"]
    assert tok["generation"] == snap.generation
    for i, (code, start, end) in enumerate(PANEL):
        level, leaf = closed_form_bin(start, end)
        label = chromosome_label(code)
        assert tok["bin_level"][i] == level
        assert tok["leaf_bin"][i] == leaf
        assert tok["bin_index"][i] == closed_form_path(label, level, leaf)
        shard = snap.store.shards.get(code)
        brute = len(_brute_region_rows(shard, start, end)) \
            if shard is not None else 0
        assert tok["count"][i] == brute, (i, specs[i])
        if shard is None:
            assert tok["row_lo"][i] == tok["row_hi"][i] == -1
        else:
            assert tok["row_hi"][i] - tok["row_lo"][i] == brute
            # the span indexes the generation's dedup'd position-sorted
            # index: every spanned position sits inside the interval
            index = engine._interval_index(snap, code)
            span = index.pos[tok["row_lo"][i]:tok["row_hi"][i]]
            assert ((span >= start) & (span <= end)).all()


def test_absurd_bounds_answer_identically_on_both_routes(served):
    """A grammatical region whose end bound exceeds int32 must not 500
    on the single route while the batch route answers: both clamp below
    the position sentinel identically (no store position can reach the
    clamp, so the answer — zero rows — is exact)."""
    _dir, _truth, _manager, engine = served
    spec = "8:2147483645-2147483650"
    single = engine.region(spec)
    batch = engine.regions_serve([spec]).pages[0].assemble()
    assert single == batch
    assert json.loads(single)["count"] == 0


def test_index_device_copies_are_byte_bounded(served):
    """Retained device copies of interval indexes live under
    INDEX_DEVICE_BYTES: forcing every group to the device and shrinking
    the ceiling below two copies must leave only the most recent index
    device-resident (answers stay byte-identical off the host arrays)."""
    store_dir, _truth, _manager, _engine = served
    engine = QueryEngine(SnapshotManager(store_dir), region_cache_size=0,
                         regions_device_min=0)
    snap = engine.snapshots.current()
    one = engine._interval_index(snap, 8)
    engine.INDEX_DEVICE_BYTES = one.n * 4  # room for ~one padded copy
    want = [engine.region("8:1-10000"), engine.region("1:1-10000")]
    engine.regions_serve(["8:1-10000"])
    idx8 = engine._interval_index(snap, 8)
    assert idx8._dev_pos is not None
    engine.regions_serve(["1:1-10000"])
    idx1 = engine._interval_index(snap, 1)
    assert idx1._dev_pos is not None
    assert idx8._dev_pos is None  # evicted by the byte ledger
    # the ledger holds only the just-used copy (it always stays, even
    # when its pow2-padded size alone brushes the ceiling)
    assert len(engine._index_device) == 1
    # correctness is unaffected: the host arrays still answer, and the
    # chr8 index transparently re-uploads on its next device call
    got = [engine.regions_serve(["8:1-10000"]).pages[0].assemble(),
           engine.regions_serve(["1:1-10000"]).pages[0].assemble()]
    assert got == want


def test_unfiltered_limit_keeps_full_count_with_lazy_materialization(served):
    """With no filters, only ``limit`` rows are materialized per
    interval but ``count`` must still report the FULL span width (the
    lazy slice must never truncate the count)."""
    _dir, _truth, _manager, engine = served
    result = engine.regions_serve(["8:1-3000000", "1:1-3000000"], limit=3)
    for page in result.pages:
        assert len(page.shown) == 3
        env = json.loads(page.assemble())
        assert env["returned"] == 3
        assert env["count"] > 3  # the whole chromosome matched


def test_concurrent_index_builds_deduplicate(served):
    """After a generation swap every request misses the index cache at
    once: concurrent builders must coalesce onto ONE full-chromosome
    build (a stampede of identical sorts is an N-fold memory spike)."""
    import threading as _threading

    from annotatedvdb_tpu.serve import engine as engine_mod

    store_dir, _truth, _manager, _engine = served
    engine = QueryEngine(SnapshotManager(store_dir), region_cache_size=0)
    snap = engine.snapshots.current()
    builds = {"n": 0}
    real_build = engine_mod.IntervalIndex.build.__func__

    def slow_build(shard):
        builds["n"] += 1
        import time as _time

        _time.sleep(0.05)  # widen the race window
        return real_build(engine_mod.IntervalIndex, shard)

    engine_mod.IntervalIndex.build = slow_build
    try:
        got = []
        threads = [
            _threading.Thread(
                target=lambda: got.append(engine._interval_index(snap, 8))
            )
            for _ in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
    finally:
        engine_mod.IntervalIndex.build = classmethod(real_build)
    assert builds["n"] == 1, builds["n"]
    assert len(got) == 8 and all(i is got[0] for i in got)


def test_aio_malformed_content_length_is_400_parity(both_servers):
    """A bogus Content-Length on POST /regions must answer 400 on BOTH
    front ends (the aio fallthrough used to 404 it)."""
    import socket

    raw = (b"POST /regions HTTP/1.1\r\nHost: t\r\n"
           b"Content-Length: abc\r\n\r\n")
    for port in both_servers:
        with socket.create_connection(("127.0.0.1", port), timeout=15) as s:
            s.sendall(raw)
            s.settimeout(15)
            head = s.recv(4096)
        assert b" 400 " in head.split(b"\r\n", 1)[0], (port, head[:80])


def test_cursor_walk_unaffected_by_interleaved_batches(served):
    """Cursor interplay: a paged single-region walk stays byte-correct
    while /regions panels run between its pages, and the pages
    reassemble the unpaged answer."""
    _dir, _truth, _manager, engine = served
    spec = "8:1-3000000"
    unpaged = json.loads(engine.region(spec))
    rows, cursor, pages = [], "", 0
    while True:
        page = json.loads(engine.region(spec, limit=7, cursor=cursor))
        rows.extend(page["variants"])
        pages += 1
        engine.regions_serve(_specs())  # interleaved batch traffic
        if not page.get("next"):
            break
        cursor = page["next"]
    assert pages > 3
    assert rows == unpaged["variants"]


def test_regions_reflect_snapshot_swap(tmp_path):
    store_dir = str(tmp_path / "swap_store")
    _build_store(store_dir)
    manager = SnapshotManager(store_dir)
    engine = QueryEngine(manager, region_cache_size=0)
    before = json.loads(engine.regions_serve(["8:4999999-5001000"])
                        .pages[0].assemble())
    assert before["count"] == 0

    store = VariantStore.load(store_dir)
    rows = [{"chrom": 8, "pos": 5_000_000 + 11 * i, "ref": "A", "alt": "C",
             "rs": -1, "cadd": None, "rank": None, "vep": False}
            for i in range(25)]
    _append(store.shard(8), rows)
    store.save(store_dir)

    # un-refreshed: the pinned generation (and its index) still answers
    assert json.loads(engine.regions_serve(["8:4999999-5001000"])
                      .pages[0].assemble())["count"] == 0
    assert manager.refresh() is True
    after = json.loads(engine.regions_serve(["8:4999999-5001000"])
                       .pages[0].assemble())
    assert after["count"] == 25
    assert after["generation"] == before["generation"] + 1
    # parity holds on the new generation too
    assert engine.regions_serve(["8:4999999-5001000"]).pages[0].assemble() \
        == engine.region("8:4999999-5001000")


def test_interval_index_cache_bounded_and_generation_keyed(served):
    store_dir, _truth, _manager, _engine = served
    engine = QueryEngine(SnapshotManager(store_dir), region_cache_size=0)
    engine.INDEX_CACHE = 2
    engine.regions_serve(_specs())  # touches 3 loaded chromosomes
    assert len(engine._index_cache) <= 2
    # a re-query rebuilds the evicted index transparently (still correct)
    assert json.loads(engine.regions_serve(["1:1-3000000"])
                      .pages[0].assemble())["count"] > 0


# ---------------------------------------------------------------------------
# HTTP front ends


def _get(port: int, path: str):
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30
        ) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as err:
        return err.code, err.read().decode()


def _post(port: int, path: str, payload) -> tuple[int, str]:
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(), method="POST",
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as err:
        return err.code, err.read().decode()


@pytest.fixture()
def both_servers(served):
    from annotatedvdb_tpu.serve.aio import build_aio_server
    from annotatedvdb_tpu.serve.http import build_server

    store_dir, truth, _manager, _engine = served
    httpd = build_server(store_dir=store_dir, port=0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    aio = build_aio_server(store_dir=store_dir, port=0, stream_threshold=16)
    aio.start_background()
    try:
        yield httpd.server_address[1], aio.server_address[1]
    finally:
        httpd.shutdown()
        httpd.server_close()
        httpd.ctx.batcher.close()
        aio.shutdown()
        aio.ctx.batcher.close()


def test_http_regions_byte_parity_both_front_ends(both_servers):
    tport, aport = both_servers
    specs = _specs()
    payload = {"regions": specs, "minCadd": 4.0, "limit": 6}
    st_t, body_t = _post(tport, "/regions", payload)
    st_a, body_a = _post(aport, "/regions", payload)
    assert st_t == st_a == 200
    assert body_t == body_a  # cross-front-end parity (aio streams: 11*6
    # rows < threshold? returned <= 66 > 16 -> CHUNKED; de-chunked equal)
    obj = json.loads(body_t)
    assert obj["n"] == len(specs)
    for spec, envelope in zip(specs, obj["results"]):
        status, single = _get(
            tport, f"/region/{spec}?minCadd=4.0&limit=6"
        )
        assert status == 200
        # byte-identical: the batch envelope is the single body verbatim
        assert json.dumps(envelope, separators=(",", ":")) \
            == json.dumps(json.loads(single), separators=(",", ":"))
        assert single in body_t


def test_http_regions_count_only_and_tokens(both_servers):
    _tport, aport = both_servers
    st, body = _post(aport, "/regions",
                     {"regions": ["8:1-10000"], "limit": 0,
                      "tokenize": True})
    assert st == 200
    obj = json.loads(body)
    assert obj["results"][0]["returned"] == 0
    assert obj["results"][0]["count"] == obj["tokens"]["count"][0] > 0


def test_http_regions_bad_bodies_are_400(both_servers):
    tport, aport = both_servers
    for port in (tport, aport):
        for bad in ({"regions": "x"}, {"regions": [1]}, {"nope": []},
                    {"regions": ["8:9-3"]}, {"regions": ["junk"]},
                    {"regions": ["8:1-2"], "limit": "ten"},
                    {"regions": ["8:1-2"], "tokenize": "yes"},
                    {"regions": ["8:1-2"], "minCadd": True}):
            st, body = _post(port, "/regions", bad)
            assert st == 400, (port, bad, st, body[:200])
        # the route answers normally afterwards
        st, _ = _post(port, "/regions", {"regions": ["8:1-2"]})
        assert st == 200


def test_http_regions_cap_is_400(served, monkeypatch):
    from annotatedvdb_tpu.serve.http import build_server

    monkeypatch.setenv("AVDB_SERVE_REGIONS_MAX", "2")
    store_dir, _truth, _manager, _engine = served
    httpd = build_server(store_dir=store_dir, port=0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        port = httpd.server_address[1]
        st, body = _post(port, "/regions",
                         {"regions": ["8:1-2", "8:1-2", "8:1-2"]})
        assert st == 400 and "cap" in body
        st, _ = _post(port, "/regions", {"regions": ["8:1-2", "8:3-4"]})
        assert st == 200
    finally:
        httpd.shutdown()
        httpd.server_close()
        httpd.ctx.batcher.close()


def test_http_regions_fault_fails_one_request_and_metrics(both_servers):
    from annotatedvdb_tpu.utils import faults

    tport, _aport = both_servers
    try:
        faults.reset("serve.regions:1:raise")
        st, body = _post(tport, "/regions", {"regions": ["8:1-100"]})
        assert st == 500 and "InjectedFault" in body
        st, _ = _post(tport, "/regions", {"regions": ["8:1-100"]})
        assert st == 200  # exactly one batch failed; serving continues
    finally:
        faults.reset("")
    st, metrics = _get(tport, "/metrics")
    assert st == 200
    assert 'avdb_query_requests_total{kind="regions"}' in metrics
    assert 'avdb_query_errors_total{kind="regions"}' in metrics


def test_http_regions_streaming_parity_with_buffered(served):
    """A panel whose total rows exceed the aio stream threshold must
    de-chunk to exactly the buffered (threaded) bytes."""
    from annotatedvdb_tpu.serve.aio import build_aio_server
    from annotatedvdb_tpu.serve.http import build_server

    store_dir, _truth, _manager, _engine = served
    httpd = build_server(store_dir=store_dir, port=0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    aio = build_aio_server(store_dir=store_dir, port=0, stream_threshold=4)
    aio.start_background()
    try:
        payload = {"regions": _specs()}
        st_a, body_a = _post(aio.server_address[1], "/regions", payload)
        st_t, body_t = _post(httpd.server_address[1], "/regions", payload)
        assert st_a == st_t == 200
        assert body_a == body_t
        assert json.loads(body_a)["n"] == len(PANEL)
    finally:
        httpd.shutdown()
        httpd.server_close()
        httpd.ctx.batcher.close()
        aio.shutdown()
        aio.ctx.batcher.close()
