"""Hard-crash recovery: SIGKILL a committing CLI load mid-stream, resume,
and require the recovered store to match an uninterrupted load exactly.

This exercises the durability ordering end-to-end through the real CLI
(persist-before-checkpoint: the saved store may run AHEAD of the ledger
cursor but never behind it), the reference's operational recovery story
(``--resumeAfter`` + log scan, ``variant_loader.py:440-455``) done as
idempotent batch replay instead.
"""

import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from annotatedvdb_tpu.store import VariantStore

pytestmark = pytest.mark.skipif(
    not os.environ.get("AVDB_CRASH_TEST"),
    reason="three CLI subprocess loads over 200k rows (~15-30s on CPU with "
           "the shared persistent compile cache): set AVDB_CRASH_TEST=1",
)

N_ROWS = 200_000  # large enough that a cache-warm victim is still mid-load
                  # when the kill lands at its first durable checkpoint


def _write_vcf(path):
    with open(path, "w") as f:
        f.write("##fileformat=VCFv4.2\n"
                "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\n")
        for i in range(N_ROWS):
            f.write(f"8\t{1000 + 3 * i}\trs{i}\tA\tG\t.\t.\tRS={i}\n")


def _cli(vcf, store, extra=()):
    return [sys.executable, "-m", "annotatedvdb_tpu.cli.load_vcf",
            "--fileName", vcf, "--storeDir", store,
            "--commitAfter", "2048", "--commit", *extra]


def test_sigkill_mid_load_then_resume(tmp_path):
    # the three subprocesses would each pay the full XLA compile of the
    # load kernels (the old gate's 14 min was almost all compile): share
    # one persistent compilation cache so only the first run compiles
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        JAX_COMPILATION_CACHE_DIR=str(tmp_path / "jaxcache"),
        JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES="0",
        JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS="0",
    )
    vcf = str(tmp_path / "d.vcf")
    _write_vcf(vcf)

    # reference run: uninterrupted load into its own store
    ref_store = str(tmp_path / "ref")
    r = subprocess.run(_cli(vcf, ref_store), env=env,
                       capture_output=True, text=True, timeout=480)
    assert r.returncode == 0, r.stderr[-2000:]

    # victim run: SIGKILL once the store directory shows a first checkpoint
    crash_store = str(tmp_path / "crash")
    p = subprocess.Popen(_cli(vcf, crash_store), env=env,
                         stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    deadline = time.time() + 300
    killed = False
    manifest = os.path.join(crash_store, "manifest.json")
    while time.time() < deadline:
        if p.poll() is not None:
            break  # finished before we could kill it — still a valid run
        if os.path.exists(manifest):
            # kill IMMEDIATELY at the first durable checkpoint: with the
            # shared compile cache the victim loads at full speed, so any
            # fixed grace period risks letting it finish
            if p.poll() is None:
                p.send_signal(signal.SIGKILL)
                killed = True
            break
        time.sleep(0.02)
    p.wait(timeout=60)
    if not killed:
        # the victim finishing on its own is fine — but only cleanly; a
        # crash for an unrelated reason must not be healed silently
        assert p.returncode == 0, f"victim exited {p.returncode} unkilled"
    if killed:
        partial = VariantStore.load(crash_store)
        assert partial.n < N_ROWS  # genuinely interrupted

    # recovery: rerun the same command; the ledger cursor + batch replay
    # must complete the load without duplicating committed rows
    r = subprocess.run(_cli(vcf, crash_store), env=env,
                       capture_output=True, text=True, timeout=480)
    assert r.returncode == 0, r.stderr[-2000:]

    got = VariantStore.load(crash_store)
    want = VariantStore.load(ref_store)
    assert got.n == want.n == N_ROWS
    gs, ws = got.shard(8), want.shard(8)
    gs.compact(), ws.compact()
    for col in ("pos", "h", "ref_snp", "ref_len", "alt_len",
                "bin_level", "leaf_bin"):
        np.testing.assert_array_equal(gs.cols[col], ws.cols[col], err_msg=col)
    np.testing.assert_array_equal(gs.ref, ws.ref)
    np.testing.assert_array_equal(gs.alt, ws.alt)
