"""Consequence ranking tests — modeled on the reference's manual
test_conseq_parser.py smoke flow (SURVEY.md §4.1), now with assertions."""

import numpy as np
import pytest

from annotatedvdb_tpu.conseq import (
    ConseqGroup,
    ConsequenceRanker,
    RankTable,
    is_coding_consequence,
)
from annotatedvdb_tpu.conseq.ranker import alphabetize_combo, int_to_alpha


def test_int_to_alpha():
    # base-26 digits, 0-based ('a' = 0): the encoding reconstructed from
    # the reference's published rank expectation (test_reference_rank_parity)
    assert int_to_alpha(0) == "a"
    assert int_to_alpha(25) == "z"
    assert int_to_alpha(26) == "ba"
    assert int_to_alpha(27) == "bb"


def test_group_membership_rules():
    combos = [
        "missense_variant",
        "missense_variant,NMD_transcript_variant",
        "intron_variant",
        "intron_variant,non_coding_transcript_variant",
        "splice_region_variant,non_coding_transcript_exon_variant",
    ]
    # HIGH_IMPACT excludes NMD/non-coding overlaps
    assert ConseqGroup.HIGH_IMPACT.members(combos) == ["missense_variant"]
    assert ConseqGroup.NMD.members(combos) == [
        "missense_variant,NMD_transcript_variant"
    ]
    assert ConseqGroup.NON_CODING_TRANSCRIPT.members(combos) == [
        "intron_variant,non_coding_transcript_variant",
        "splice_region_variant,non_coding_transcript_exon_variant",
    ]
    # MODIFIER requires full subset
    assert ConseqGroup.MODIFIER.members(combos, require_subset=True) == [
        "intron_variant",
        "intron_variant,non_coding_transcript_variant",
    ]
    with pytest.raises(IndexError, match="invalid consequence"):
        ConseqGroup.validate_terms(["fake_term"])


def test_ranker_seed_order_and_groups():
    r = ConsequenceRanker.from_vocabulary()
    ranks = r.rankings
    # every single-term combo is ranked; ranks are unique (gaps are expected:
    # combos in both the non-coding and MODIFIER groups occupy two slots in
    # the ordered list and the indexed dict keeps the later one, matching the
    # reference's list_to_indexed_dict behavior)
    assert len(set(ranks.values())) == len(ranks)
    # group ordering: any HIGH_IMPACT term outranks (smaller rank) any
    # NMD/non-coding/modifier-only combo
    assert ranks["missense_variant"] < ranks["NMD_transcript_variant"]
    assert ranks["NMD_transcript_variant"] < ranks["non_coding_transcript_variant"]
    assert ranks["stop_gained"] < ranks["intron_variant"]


def test_novel_combo_learned_and_reranked(tmp_path):
    r = ConsequenceRanker.from_vocabulary()
    before = dict(r.rankings)
    v0 = r.version
    rank = r.find_matching_consequence(["stop_gained", "missense_variant"])
    assert rank is not None and rank >= 0
    assert r.version == v0 + 1
    assert r.rank_of("stop_gained,missense_variant") == rank
    assert r.added == ["missense_variant,stop_gained"]
    # the stored key carries the internal rank order (stop_gained outranks
    # missense), matching the reference's re-rank output keys
    assert "stop_gained,missense_variant" in r.rankings
    # order-insensitive: same combo in any order hits the memo/known key
    assert r.find_matching_consequence(["missense_variant", "stop_gained"]) == rank
    assert r.version == v0 + 1  # no second re-rank
    # table renumbered consistently: one new combo, still unique ranks
    assert len(r.rankings) == len(before) + 1
    assert len(set(r.rankings.values())) == len(r.rankings)


def test_ranking_file_roundtrip(tmp_path):
    r = ConsequenceRanker.from_vocabulary()
    r.find_matching_consequence(["intron_variant", "downstream_gene_variant"])
    path = r.save(str(tmp_path / "ranks.txt"))
    canon = lambda rk: {alphabetize_combo(k): v for k, v in rk.rankings.items()}
    r2 = ConsequenceRanker(path)
    assert canon(r2) == canon(r)
    # rank_on_load reproduces the same ordering (idempotent re-rank)
    r3 = ConsequenceRanker(path, rank_on_load=True)
    assert canon(r3) == canon(r)


def test_rank_table_host_device_parity():
    r = ConsequenceRanker.from_vocabulary()
    r.find_matching_consequence(["stop_gained", "splice_region_variant"])
    t = RankTable(r)
    combos = list(r.rankings.keys()) + ["totally_unknown_combo"]
    masks = t.encode(combos)
    host = t.lookup_host(masks)
    hi = np.asarray((masks >> np.uint64(32)).astype(np.uint32))
    lo = np.asarray((masks & np.uint64(0xFFFFFFFF)).astype(np.uint32))
    dev = np.asarray(t.lookup_device(hi, lo))
    np.testing.assert_array_equal(host, dev)
    # known combos resolve to their ranks; unknown -> 0
    for combo, got in zip(combos[:-1], host[:-1]):
        assert got == r.rankings[combo]
    assert host[-1] == -1
    # order-insensitivity: shuffled term order gives the same mask
    a = t.encode(["missense_variant,stop_gained"])
    b = t.encode(["stop_gained,missense_variant"])
    assert a[0] == b[0]


def test_reference_rank_parity():
    """The published expectation (``Util/bin/test_conseq_parser.py:23-27``):
    re-ranking the reference's ranking table must give
    ``splice_acceptor_variant,splice_donor_variant,3_prime_UTR_variant,
    intron_variant`` rank 5.  The expectation predates the 2022
    GenomicsDB additions (rows flagged ``T`` in the shipped table), so the
    parity check runs on the original-row subset."""
    import csv
    import os

    from annotatedvdb_tpu.conseq.ranker import DEFAULT_RANKING_FILE

    with open(DEFAULT_RANKING_FILE, newline="") as fh:
        original = [
            row["consequence"] for row in csv.DictReader(fh, delimiter="\t")
            if row.get("genomicsdb_consequence", "").strip() != "T"
        ]
    assert len(original) == 228
    import tempfile

    with tempfile.NamedTemporaryFile("w", suffix=".txt", delete=False) as tf:
        tf.write("consequence\n")
        for c in original:
            tf.write(f'"{c}"\n' if "," in c else c + "\n")
        tmp = tf.name
    try:
        r = ConsequenceRanker(tmp, rank_on_load=True)
        combo = ("splice_acceptor_variant,splice_donor_variant,"
                 "3_prime_UTR_variant,intron_variant")
        assert r.find_matching_consequence(combo.split(",")) == 5
    finally:
        os.unlink(tmp)


def test_shipped_seed_loads_by_default():
    """ConsequenceRanker() loads the 294-row ADSP table (293 unique combos
    after alphabetization) and ranks it on first use."""
    r = ConsequenceRanker()
    assert len(r.rankings) == 293
    assert r.ranking_file.endswith("adsp_consequence_ranking.txt")
    # rank-on-load happened: 0-based re-rank output; gaps are expected where
    # a combo sits in both the non-coding and MODIFIER groups (last position
    # wins, list_to_indexed_dict semantics)
    values = sorted(r.rankings.values())
    assert len(set(values)) == 293
    assert values[0] == 0 and values[-1] < 300
    # known combos resolve regardless of term order (row 2 of the seed,
    # queried with its terms scrambled)
    combo = ("intron_variant,3_prime_UTR_variant,splice_donor_variant,"
             "splice_acceptor_variant")
    assert r.rank_of(combo) is not None
    assert r.rank_of("transcript_ablation") is not None


def test_fixture_flow_matches_reference_smoke():
    """The reference's manual smoke flow (``test_conseq_parser.py:7-48``)
    with its fixture file: load+rank, match, fail-on-missing raise, learn,
    versioned save."""
    import os

    fixture = os.path.join(os.path.dirname(__file__), "data",
                           "conseq_parser_test_data1.txt")
    r = ConsequenceRanker(fixture, rank_on_load=True)
    assert len(r.rankings) == 5
    novel = ["TFBS_amplification", "TF_binding_site_variant"]
    with pytest.raises(IndexError, match="not found in ADSP rankings"):
        r.find_matching_consequence(novel, fail_on_missing=True)
    rank = r.find_matching_consequence(novel)
    assert rank is not None and len(r.rankings) == 6
    # canonical (alphabetized) combo key: uppercase-prefix terms sort by
    # raw byte order, so TFBS_amplification precedes TF_binding_site_variant
    assert r.added == ["TFBS_amplification,TF_binding_site_variant"]


def test_prefetch_ranks_seeds_memo_and_matches_host_ranker():
    """The VEP batch path's rank prefetch (device table for large batches)
    agrees with the host ranker for known combos and leaves novel combos to
    the learn-on-miss path."""
    from annotatedvdb_tpu.io.vep import VepResultParser

    ranker = ConsequenceRanker()
    parser = VepResultParser(ranker)
    known = list(ranker.rankings)[:300]  # > DEVICE_RANK_MIN: device path
    anns = [
        {"transcript_consequences": [
            {"consequence_terms": c.split(","), "variant_allele": "A"}
        ]}
        for c in known
    ] + [
        {"transcript_consequences": [
            {"consequence_terms": ["TFBS_ablation", "intergenic_variant"],
             "variant_allele": "A"}
        ]}
    ]
    resolved = parser.prefetch_ranks(anns)
    assert resolved >= len(set(known)) - 1
    for c in known:
        memo = parser._rank_memo[",".join(c.split(","))]
        assert memo["rank"] == ranker.find_matching_consequence(c.split(","))
    # second prefetch is a no-op (memo hit)
    assert parser.prefetch_ranks(anns[:10]) == 0


def test_is_coding():
    assert is_coding_consequence("missense_variant,intron_variant")
    assert not is_coding_consequence(["intron_variant", "upstream_gene_variant"])


def test_ranking_save_six_column_roundtrip(tmp_path):
    """save() emits the seed's 6-column schema; save -> reload gives
    identical ranks, metadata columns survive, and novel combos appear with
    blank metadata (VERDICT r3 #6)."""
    from annotatedvdb_tpu.conseq.ranker import ConsequenceRanker

    r = ConsequenceRanker()  # shipped seed, rank_on_load
    novel = ["transcript_ablation", "intron_variant", "3_prime_UTR_variant"]
    r.find_matching_consequence(novel)
    assert len(r.added) == 1  # genuinely novel: learned via re-rank
    out = str(tmp_path / "saved.txt")
    r.save(out)
    with open(out) as fh:
        header = fh.readline().rstrip("\n").split("\t")
    assert header == ["consequence", "adsp_ranking", "adsp_impact",
                      "ensembl_ranking", "ensembl_impact",
                      "genomicsdb_consequence"]
    # reload (adsp_ranking recognized as the rank column) -> same ranks
    r2 = ConsequenceRanker(out, rank_on_load=False)
    for combo, rank in r.rankings.items():
        assert r2.rank_of(combo) == rank, combo
    # metadata preserved for seed combos, blank for the learned combo
    import csv as _csv

    with open(out, newline="") as fh:
        rows = {row["consequence"]: row
                for row in _csv.DictReader(fh, delimiter="\t")}
    assert rows["transcript_ablation"]["adsp_impact"] == "HIGH"
    assert rows["transcript_ablation"]["ensembl_ranking"] == "1"
    novel_row = next(
        row for key, row in rows.items()
        if sorted(key.split(",")) == sorted(novel)
    )
    assert novel_row["adsp_impact"] == ""


def test_ranking_save_diffable_against_seed(tmp_path):
    """Loading the seed WITHOUT re-ranking and saving reproduces the seed's
    content semantically: same combos (order-insensitive), same ranks
    (fractional legacy ranks like 2.5 kept exact), same metadata.  (A
    byte-diff is impossible even for the reference: its parser alphabetizes
    combo term order on load.)"""
    import csv as _csv

    from annotatedvdb_tpu.conseq.ranker import (
        DEFAULT_RANKING_FILE,
        ConsequenceRanker,
        alphabetize_combo,
    )

    r = ConsequenceRanker(DEFAULT_RANKING_FILE, rank_on_load=False)
    out = str(tmp_path / "seed_resave.txt")
    r.save(out)

    def read(path):
        with open(path, newline="") as fh:
            return {
                alphabetize_combo(row["consequence"]): (
                    row["adsp_ranking"], row["adsp_impact"],
                    row["ensembl_ranking"], row["ensembl_impact"],
                    row["genomicsdb_consequence"],
                )
                for row in _csv.DictReader(fh, delimiter="\t")
            }

    seed, saved = read(DEFAULT_RANKING_FILE), read(out)
    assert seed.keys() == saved.keys()
    for combo in seed:
        s_rank, *s_meta = seed[combo]
        o_rank, *o_meta = saved[combo]
        assert float(s_rank) == float(o_rank), combo
        assert s_meta == o_meta, combo
