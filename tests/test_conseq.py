"""Consequence ranking tests — modeled on the reference's manual
test_conseq_parser.py smoke flow (SURVEY.md §4.1), now with assertions."""

import numpy as np
import pytest

from annotatedvdb_tpu.conseq import (
    ConseqGroup,
    ConsequenceRanker,
    RankTable,
    is_coding_consequence,
)
from annotatedvdb_tpu.conseq.ranker import alphabetize_combo, int_to_alpha


def test_int_to_alpha():
    assert int_to_alpha(1) == "a"
    assert int_to_alpha(26) == "z"
    assert int_to_alpha(27) == "aa"
    assert int_to_alpha(28) == "ab"


def test_group_membership_rules():
    combos = [
        "missense_variant",
        "missense_variant,NMD_transcript_variant",
        "intron_variant",
        "intron_variant,non_coding_transcript_variant",
        "splice_region_variant,non_coding_transcript_exon_variant",
    ]
    # HIGH_IMPACT excludes NMD/non-coding overlaps
    assert ConseqGroup.HIGH_IMPACT.members(combos) == ["missense_variant"]
    assert ConseqGroup.NMD.members(combos) == [
        "missense_variant,NMD_transcript_variant"
    ]
    assert ConseqGroup.NON_CODING_TRANSCRIPT.members(combos) == [
        "intron_variant,non_coding_transcript_variant",
        "splice_region_variant,non_coding_transcript_exon_variant",
    ]
    # MODIFIER requires full subset
    assert ConseqGroup.MODIFIER.members(combos, require_subset=True) == [
        "intron_variant",
        "intron_variant,non_coding_transcript_variant",
    ]
    with pytest.raises(IndexError, match="invalid consequence"):
        ConseqGroup.validate_terms(["fake_term"])


def test_ranker_seed_order_and_groups():
    r = ConsequenceRanker()
    ranks = r.rankings
    # every single-term combo is ranked; ranks are unique (gaps are expected:
    # combos in both the non-coding and MODIFIER groups occupy two slots in
    # the ordered list and the indexed dict keeps the later one, matching the
    # reference's list_to_indexed_dict behavior)
    assert len(set(ranks.values())) == len(ranks)
    # group ordering: any HIGH_IMPACT term outranks (smaller rank) any
    # NMD/non-coding/modifier-only combo
    assert ranks["missense_variant"] < ranks["NMD_transcript_variant"]
    assert ranks["NMD_transcript_variant"] < ranks["non_coding_transcript_variant"]
    assert ranks["stop_gained"] < ranks["intron_variant"]


def test_novel_combo_learned_and_reranked(tmp_path):
    r = ConsequenceRanker()
    before = dict(r.rankings)
    v0 = r.version
    rank = r.find_matching_consequence(["stop_gained", "missense_variant"])
    assert rank is not None and rank >= 1
    assert r.version == v0 + 1
    assert r.rank_of("stop_gained,missense_variant") == rank
    assert r.added == ["missense_variant,stop_gained"]
    # the stored key carries the internal rank order (stop_gained outranks
    # missense), matching the reference's re-rank output keys
    assert "stop_gained,missense_variant" in r.rankings
    # order-insensitive: same combo in any order hits the memo/known key
    assert r.find_matching_consequence(["missense_variant", "stop_gained"]) == rank
    assert r.version == v0 + 1  # no second re-rank
    # table renumbered consistently: one new combo, still unique ranks
    assert len(r.rankings) == len(before) + 1
    assert len(set(r.rankings.values())) == len(r.rankings)


def test_ranking_file_roundtrip(tmp_path):
    r = ConsequenceRanker()
    r.find_matching_consequence(["intron_variant", "downstream_gene_variant"])
    path = r.save(str(tmp_path / "ranks.txt"))
    canon = lambda rk: {alphabetize_combo(k): v for k, v in rk.rankings.items()}
    r2 = ConsequenceRanker(path)
    assert canon(r2) == canon(r)
    # rank_on_load reproduces the same ordering (idempotent re-rank)
    r3 = ConsequenceRanker(path, rank_on_load=True)
    assert canon(r3) == canon(r)


def test_rank_table_host_device_parity():
    r = ConsequenceRanker()
    r.find_matching_consequence(["stop_gained", "splice_region_variant"])
    t = RankTable(r)
    combos = list(r.rankings.keys()) + ["totally_unknown_combo"]
    masks = t.encode(combos)
    host = t.lookup_host(masks)
    hi = np.asarray((masks >> np.uint64(32)).astype(np.uint32))
    lo = np.asarray((masks & np.uint64(0xFFFFFFFF)).astype(np.uint32))
    dev = np.asarray(t.lookup_device(hi, lo))
    np.testing.assert_array_equal(host, dev)
    # known combos resolve to their ranks; unknown -> 0
    for combo, got in zip(combos[:-1], host[:-1]):
        assert got == r.rankings[combo]
    assert host[-1] == 0
    # order-insensitivity: shuffled term order gives the same mask
    a = t.encode(["missense_variant,stop_gained"])
    b = t.encode(["stop_gained,missense_variant"])
    assert a[0] == b[0]


def test_is_coding():
    assert is_coding_consequence("missense_variant,intron_variant")
    assert not is_coding_consequence(["intron_variant", "upstream_gene_variant"])
