"""End-to-end VCF load slice tests (SURVEY.md §7.2 step 5)."""

import json
import subprocess
import sys

import numpy as np
import pytest

from annotatedvdb_tpu import oracle
from annotatedvdb_tpu.io.vcf import VcfBatchReader, parse_freq, parse_info
from annotatedvdb_tpu.loaders import TpuVcfLoader
from annotatedvdb_tpu.store import AlgorithmLedger, VariantStore

VCF = """##fileformat=VCFv4.2
#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO
1\t10019\trs775809821\tTA\tT\t.\t.\tRS=775809821;RSPOS=10020
1\t10039\trs978760828\tA\tC\t.\t.\tRS=978760828
1\t10051\trs1052373574\tA\tG,T\t.\t.\tRS=1052373574;FREQ=GnomAD:0.9986,0.001353,.|Korea1K:0.9814,0.01861,0.1
chr2\t20301\t.\tG\tGAA\t.\t.\t.
MT\t263\trs2853515\tA\tG\t.\t.\tRS=2853515
2\t30421\tsub1\tCCTT\tCATT\t.\t.\t.
1\t10039\trs978760828\tA\tC\t.\t.\tRS=978760828
3\t555\t.\tT\t.\t.\t.\t.
chr1_KI270706v1_random\t100\t.\tA\tC\t.\t.\t.
22\t11212877\t.\tTAAAATATCAAAGTACACCAAATACATATTATATACTGTACAC\tT\t.\t.\t.
"""


@pytest.fixture
def vcf_file(tmp_path):
    p = tmp_path / "sample.vcf"
    p.write_text(VCF)
    return str(p)


def make_loader(tmp_path, **kw):
    store = VariantStore(width=49)
    ledger = AlgorithmLedger(str(tmp_path / "ledger.jsonl"))
    return store, TpuVcfLoader(store, ledger, log=lambda *a: None, **kw)


def test_reader_row_expansion(vcf_file):
    chunks = list(VcfBatchReader(vcf_file, batch_size=100, width=49))
    assert len(chunks) == 1
    c = chunks[0]
    # 10 data lines: 1 multi-allelic (2 alts), 1 '.' alt skipped, 1 alt contig
    # skipped -> 7 lines with usable alts, multi-allelic adds 1 = 9 rows
    assert c.batch.n == 9
    assert c.counters["line"] == 10
    assert c.counters["skipped_alt"] == 1
    assert c.counters["skipped_contig"] == 1
    # refsnp extraction: from ID and from INFO RS
    assert c.ref_snp[0] == "rs775809821"
    # MT folded to M (code 25)
    assert 25 in c.batch.chrom
    # multi-allelic FREQ matched per alt with index offset; '.' dropped
    i_g = next(i for i in range(9) if c.variant_id[i] == "1:10051:A:G,T" and
               c.batch.alt[i, 0] == ord("G"))
    i_t = next(i for i in range(9) if c.variant_id[i] == "1:10051:A:G,T" and
               c.batch.alt[i, 0] == ord("T"))
    assert c.frequencies[i_g] == {"GnomAD": {"gmaf": 0.001353}, "Korea1K": {"gmaf": 0.01861}}
    assert c.frequencies[i_t] == {"Korea1K": {"gmaf": 0.1}}  # GnomAD '.' dropped
    assert c.is_multi_allelic[i_g] and c.is_multi_allelic[i_t]


def test_mapping_ids_and_pks_tricky_shapes(tmp_path):
    """Mapping sidecar fidelity across the id/rs shapes the vectorized
    assembly special-cases: verbatim ids, multi-allelic sites, weird and
    zero-padded rs ids — identical for both ingest engines."""
    vcf = tmp_path / "t.vcf"
    vcf.write_text(
        "##fileformat=VCFv4.2\n"
        "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\n"
        "1\t100\trs7\tA\tG\t.\t.\t.\n"          # rs id -> assembled vid
        "1\t200\tcustom_id\tC\tT\t.\t.\t.\n"    # verbatim id
        "1\t300\tweird_rs_x\tG\tA\t.\t.\t.\n"   # weird rs string in PK
        "1\t400\trs0042\tT\tC\t.\t.\t.\n"       # zero-padded rs
        "1\t500\t.\tA\tT,TA\t.\t.\tRS=9\n"      # multi-alt + INFO rs
        '1\t600\tq"uote\tA\tC\t.\t.\t.\n'       # id needing JSON escape
    )
    expected = {
        "1:100:A:G": "1:100:A:G:rs7",
        "custom_id": "1:200:C:T",
        "weird_rs_x": "1:300:G:A:weird_rs_x",
        "1:400:T:C": "1:400:T:C:rs0042",
        "1:500:A:T,TA": None,  # two rows share the site id
        'q"uote': "1:600:A:C",
    }
    for engine in ("python", "native"):
        store = VariantStore(width=16)
        ledger = AlgorithmLedger(str(tmp_path / f"l{engine}.jsonl"))
        loader = TpuVcfLoader(store, ledger, log=lambda *a: None)
        import annotatedvdb_tpu.io.vcf as iov

        mp = tmp_path / f"m.{engine}.jsonl"
        # force the engine through the reader the loader constructs
        orig = iov.VcfBatchReader._use_native
        iov.VcfBatchReader._use_native = lambda self: engine == "native"
        try:
            loader.load_file(str(vcf), commit=True, mapping_path=str(mp))
        finally:
            iov.VcfBatchReader._use_native = orig
        mapping = [json.loads(l) for l in open(mp)]
        flat: dict = {}
        for m in mapping:
            for k, v in m.items():
                flat.setdefault(k, []).extend(v)
        for vid, pk in expected.items():
            assert vid in flat, (engine, vid)
            if pk is not None:
                assert flat[vid][0]["primary_key"] == pk, (engine, vid)
        assert {e["primary_key"] for e in flat["1:500:A:T,TA"]} == {
            "1:500:A:T:rs9", "1:500:A:TA:rs9"
        }, engine


def test_loader_close_is_idempotent(tmp_path, vcf_file):
    """close() releases the prefetch worker and a closed loader can load
    again (the pool respawns lazily)."""
    store, loader = make_loader(tmp_path)
    loader.load_file(vcf_file, commit=True)
    loader.close()
    loader.close()  # idempotent
    n = store.n
    loader.load_file(vcf_file, commit=True, resume=False)
    assert store.n == n  # all duplicates on the second pass
    loader.close()


def test_info_escape_scrubbing():
    info = parse_info(r"NOTE=a\x2cb\x59c#d;FLAG")
    assert info["NOTE"] == "a,b/c:d"
    assert info["FLAG"] is True


def test_load_commit_and_dedupe(tmp_path, vcf_file):
    store, loader = make_loader(tmp_path)
    counters = loader.load_file(vcf_file, commit=True,
                                mapping_path=str(tmp_path / "m.jsonl"))
    # 9 rows, 1 exact duplicate line (rs978760828 repeated) -> 8 inserted
    assert counters["variant"] == 8
    assert counters["duplicates"] == 1
    assert store.n == 8
    # chromosome sharding: chr1 has 4 unique rows (TA>T, A>C, A>G, A>T)
    assert store.shard(1).n == 4
    assert store.shard(25).n == 1  # MT -> M
    # display attributes are not materialized by default; the egress
    # recompute must match the oracle row-for-row
    from annotatedvdb_tpu.io.pg_egress import computed_display_attributes

    s = store.shard(2)
    assert all(s.annotations["display_attributes"][i] is None for i in range(s.n))
    display = computed_display_attributes(s, np.arange(s.n))
    for i in range(s.n):
        ref = bytes(s.ref[i][: s.cols["ref_len"][i]]).decode()
        alt = bytes(s.alt[i][: s.cols["alt_len"][i]]).decode()
        want = oracle.display_attributes(ref, alt, "2", int(s.cols["pos"][i]))
        assert display[i] == want
    # mapping sidecar has PKs with refsnp suffixes
    mapping = [json.loads(l) for l in open(tmp_path / "m.jsonl")]
    flat = {k: v for m in mapping for k, v in m.items()}
    assert flat["1:10019:TA:T"][0]["primary_key"] == "1:10019:TA:T:rs775809821"
    assert flat["1:10019:TA:T"][0]["bin_index"].startswith("chr1.L1.B1")
    # loading the same file again: everything is a duplicate
    counters2 = loader.load_file(vcf_file, commit=True, resume=False)
    assert counters2["variant"] == counters["variant"]  # cumulative counter
    assert store.n == 8


def test_dry_run_mutates_nothing(tmp_path, vcf_file):
    store, loader = make_loader(tmp_path)
    counters = loader.load_file(vcf_file, commit=False)
    assert counters["variant"] == 8  # counted as would-insert
    assert store.n == 0


def test_resume_from_checkpoint(tmp_path, vcf_file):
    store, loader = make_loader(tmp_path, batch_size=4)
    # fail mid-load at a variant in the second batch
    with pytest.raises(RuntimeError, match="failAt"):
        loader.load_file(vcf_file, commit=True, fail_at="sub1")
    partial = store.n
    assert 0 < partial < 8
    # re-run: resumes after the last committed checkpoint, no double inserts
    store2_counters = loader.load_file(vcf_file, commit=True)
    assert store.n == 8
    uniq = {
        (int(c), int(p), int(h))
        for c, s in store.shards.items()
        for p, h in zip(s.cols["pos"], s.cols["h"])
    }
    assert len(uniq) == 8  # no double inserts from the replay


def test_undo(tmp_path, vcf_file):
    store, loader = make_loader(tmp_path)
    counters = loader.load_file(vcf_file, commit=True)
    alg = counters["alg_id"]
    assert store.delete_by_algorithm(alg) == 8
    assert store.n == 0


def test_long_allele_digest_pk(tmp_path, vcf_file):
    store, loader = make_loader(tmp_path)
    loader.load_file(vcf_file, commit=True)
    s = store.shard(22)
    assert s.n == 1
    # 43+1 <= 50: literal PK, no digest
    assert not s.cols["needs_digest"][0]
    # now a >50bp allele gets a digest PK stored on the host path
    vcf2 = tmp_path / "long.vcf"
    long_ref = "T" + "ACGT" * 15  # 61bp
    vcf2.write_text(f"#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\n4\t900\t.\t{long_ref}\tT\t.\t.\t.\n")
    loader.load_file(str(vcf2), commit=True)
    s4 = store.shard(4)
    assert s4.cols["needs_digest"][0]
    pk = s4.digest_pk[0]
    assert pk.startswith("4:900:") and len(pk.split(":")[2]) == 32  # sha512t24u


def test_long_alleles_not_conflated(tmp_path):
    """Two >width alleles sharing their first 49 bytes must stay distinct
    (identity is re-hashed from the full strings), and digest PKs must be
    computed over the full allele, not the device-truncated window."""
    from annotatedvdb_tpu.ops.vrs import VrsDigestGenerator

    a = "T" + "A" * 60
    b = "T" + "A" * 59 + "C"  # differs only at byte 61
    vcf = tmp_path / "twins.vcf"
    vcf.write_text(
        "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\n"
        f"5\t777\t.\t{a}\tT\t.\t.\t.\n"
        f"5\t777\t.\t{b}\tT\t.\t.\t.\n"
    )
    store, loader = make_loader(tmp_path)
    counters = loader.load_file(str(vcf), commit=True)
    assert counters["variant"] == 2
    assert counters["duplicates"] == 0
    assert store.shard(5).n == 2
    # VRS digests cover location + replacement sequence only, so both refs
    # (same length, same alt) digest identically — matching vrs-python, where
    # ref content is validated against the genome, not hashed.
    pks = set(store.shard(5).digest_pk)
    want = VrsDigestGenerator("GRCh38").compute_identifier("5", 777, a, "T")
    assert pks == {f"5:777:{want}"}
    # rows with different REF LENGTH digest differently (interval end moves)
    other = VrsDigestGenerator("GRCh38").compute_identifier("5", 777, a + "A", "T")
    assert other != want


def test_cli_roundtrip(tmp_path, vcf_file):
    env_script = (
        "import sys; sys.argv=['load_vcf','--fileName',%r,'--storeDir',%r,'--commit'];"
        "from annotatedvdb_tpu.cli.load_vcf import main; sys.exit(main())"
        % (vcf_file, str(tmp_path / "vdb"))
    )
    out = subprocess.run(
        [sys.executable, "-c", env_script],
        capture_output=True, text=True, cwd="/root/repo",
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"},
    )
    assert out.returncode == 0, out.stderr
    assert out.stdout.strip() == "1"  # first algorithm invocation id
    store = VariantStore.load(str(tmp_path / "vdb"))
    assert store.n == 8
    # undo CLI
    undo_script = (
        "import sys; sys.argv=['undo','--storeDir',%r,'--algId','1','--commit'];"
        "from annotatedvdb_tpu.cli.undo_load import main; sys.exit(main())"
        % (str(tmp_path / "vdb"),)
    )
    out = subprocess.run(
        [sys.executable, "-c", undo_script],
        capture_output=True, text=True, cwd="/root/repo",
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"},
    )
    assert out.returncode == 0, out.stderr
    assert VariantStore.load(str(tmp_path / "vdb")).n == 0


def test_packed_transport_forced_on_cpu(tmp_path, vcf_file, monkeypatch):
    """The packed-output/nibble-upload transport is gated OFF on CPU
    backends (transport_wanted); force it on and pin that a load through
    the packed path produces the identical store — keeping the TPU-side
    transport logic covered by the CPU suite."""
    from annotatedvdb_tpu.ops import pack

    monkeypatch.setattr(pack, "_TRANSPORT_WANTED", True)
    store_p, loader_p = make_loader(tmp_path)
    c_p = loader_p.load_file(vcf_file, commit=True)

    monkeypatch.setattr(pack, "_TRANSPORT_WANTED", False)
    store_u, loader_u = make_loader(tmp_path / "u")
    (tmp_path / "u").mkdir(exist_ok=True)
    c_u = loader_u.load_file(vcf_file, commit=True)

    assert c_p["variant"] == c_u["variant"] == 8
    assert c_p["duplicates"] == c_u["duplicates"] == 1
    for code in store_u.shards:
        a, b = store_p.shard(code), store_u.shard(code)
        a.compact(), b.compact()
        np.testing.assert_array_equal(a.cols["pos"], b.cols["pos"])
        np.testing.assert_array_equal(a.cols["h"], b.cols["h"])
        np.testing.assert_array_equal(a.cols["bin_level"], b.cols["bin_level"])
        np.testing.assert_array_equal(a.cols["leaf_bin"], b.cols["leaf_bin"])
        np.testing.assert_array_equal(a.ref, b.ref)
        np.testing.assert_array_equal(a.alt, b.alt)


def test_async_and_sync_store_paths_match(tmp_path, vcf_file, monkeypatch):
    """AVDB_ASYNC_STORE=0 (inline append+persist) and the default async
    writer produce identical stores, counters, and resumable checkpoints."""
    import os

    monkeypatch.setenv("AVDB_ASYNC_STORE", "0")
    store_s, loader_s = make_loader(tmp_path / "s")
    os.makedirs(tmp_path / "s", exist_ok=True)
    c_s = loader_s.load_file(vcf_file, commit=True,
                             persist=lambda: store_s.save(str(tmp_path / "s/vdb")))

    monkeypatch.setenv("AVDB_ASYNC_STORE", "1")
    store_a, loader_a = make_loader(tmp_path / "a")
    os.makedirs(tmp_path / "a", exist_ok=True)
    c_a = loader_a.load_file(vcf_file, commit=True,
                             persist=lambda: store_a.save(str(tmp_path / "a/vdb")))

    assert {k: c_s[k] for k in ("variant", "duplicates", "line")} == \
           {k: c_a[k] for k in ("variant", "duplicates", "line")}
    assert store_s.n == store_a.n
    # both persisted stores reload to the same content
    rs = VariantStore.load(str(tmp_path / "s/vdb"))
    ra = VariantStore.load(str(tmp_path / "a/vdb"))
    for code in rs.shards:
        a, b = rs.shard(code), ra.shard(code)
        a.compact(), b.compact()
        np.testing.assert_array_equal(a.cols["pos"], b.cols["pos"])
        np.testing.assert_array_equal(a.cols["h"], b.cols["h"])
        np.testing.assert_array_equal(a.ref, b.ref)
