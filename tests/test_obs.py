"""Unified telemetry subsystem (``annotatedvdb_tpu.obs``): metrics registry
semantics, Chrome-trace well-formedness, BoundedStage backpressure
accounting, and the per-load run ledger (append-on-abort included)."""

import collections
import json
import subprocess
import sys
import threading
import time

import pytest

from annotatedvdb_tpu.obs import MetricsRegistry, ObsSession, Tracer
from annotatedvdb_tpu.obs.session import config_hash, run_record


# ---------------------------------------------------------------- metrics


def test_counter_and_gauge_basics():
    reg = MetricsRegistry()
    c = reg.counter("avdb_test_total", "help text")
    c.inc()
    c.inc(4)
    assert c.value == 5
    with pytest.raises(ValueError):
        c.inc(-1)
    g = reg.gauge("avdb_depth")
    g.set(3)
    g.inc()
    g.dec(2)
    assert g.value == 2
    # get-or-create: same (name, labels) returns the same object
    assert reg.counter("avdb_test_total") is c
    # same name as a different type is a bug (the kind conflict is the
    # behavior under test here, mirroring static rule AVDB303)
    with pytest.raises(TypeError):
        reg.gauge("avdb_test_total")  # avdb: noqa[AVDB303] -- deliberate kind conflict asserting the registry raises


def test_histogram_fixed_bucket_edges():
    reg = MetricsRegistry()
    h = reg.histogram("avdb_h", edges=(1.0, 10.0, 100.0))
    for v in (0.5, 1.0, 5, 10, 99, 1000):
        h.observe(v)
    snap = h.snapshot()
    assert snap["edges"] == [1.0, 10.0, 100.0]
    # le semantics: observe(edge) falls INTO that edge's bucket
    # (bisect_left): 0.5,1.0 <= 1; 5,10 <= 10; 99 <= 100; 1000 -> +Inf
    assert snap["counts"] == [2, 2, 1, 1]
    assert snap["count"] == 6
    assert snap["sum"] == pytest.approx(1115.5)
    # edges are FIXED: re-registering with different edges is an error
    with pytest.raises(ValueError):
        reg.histogram("avdb_h", edges=(2.0, 20.0))
    # malformed edges rejected at creation
    with pytest.raises(ValueError):
        reg.histogram("avdb_bad", edges=(5.0, 5.0))
    with pytest.raises(ValueError):
        reg.histogram("avdb_empty", edges=())


def test_prometheus_rendering_and_snapshot():
    reg = MetricsRegistry()
    reg.counter("avdb_rows_total", "rows", {"loader": "x"}).inc(7)
    h = reg.histogram("avdb_lat", (0.1, 1.0), "latency")
    h.observe(0.05)
    h.observe(5.0)
    text = reg.render_prometheus()
    assert "# TYPE avdb_rows_total counter" in text
    assert 'avdb_rows_total{loader="x"} 7' in text
    # cumulative buckets + the implicit +Inf bucket + sum/count
    assert 'avdb_lat_bucket{le="0.1"} 1' in text
    assert 'avdb_lat_bucket{le="1"} 1' in text
    assert 'avdb_lat_bucket{le="+Inf"} 2' in text
    assert "avdb_lat_count 2" in text
    snap = reg.snapshot()
    assert snap["avdb_rows_total"][0]["value"] == 7
    assert snap["avdb_lat"][0]["count"] == 2
    with pytest.raises(ValueError):
        reg.counter("not a valid name!")


def test_registry_thread_safety():
    reg = MetricsRegistry()
    c = reg.counter("avdb_mt_total")
    h = reg.histogram("avdb_mt_h", (10.0, 100.0))

    def work():
        for i in range(1000):
            c.inc()
            h.observe(i % 150)

    threads = [threading.Thread(target=work) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 4000
    assert h.snapshot()["count"] == 4000
    assert sum(h.snapshot()["counts"]) == 4000


def test_metrics_files_roundtrip(tmp_path):
    reg = MetricsRegistry()
    reg.counter("avdb_x_total").inc(3)
    prom = tmp_path / "m.prom"
    reg.write_textfile(str(prom))
    reg.write_json(str(prom) + ".json")
    assert "avdb_x_total 3" in prom.read_text()
    snap = json.loads((tmp_path / "m.prom.json").read_text())
    assert snap["avdb_x_total"][0]["value"] == 3


# ------------------------------------------------------------------ trace


def _check_trace_events(evs):
    """The well-formedness contract: sorted ts, per-(pid,tid) matched B/E
    pairs, named thread tracks."""
    ts = [e["ts"] for e in evs]
    assert ts == sorted(ts), "trace events not sorted by ts"
    stacks = collections.defaultdict(list)
    for e in evs:
        key = (e["pid"], e["tid"])
        if e["ph"] == "B":
            stacks[key].append(e["name"])
        elif e["ph"] == "E":
            assert stacks[key], f"E without B on {key}: {e['name']}"
            assert stacks[key].pop() == e["name"], "interleaved B/E pair"
    assert all(not s for s in stacks.values()), "unclosed B span"


def test_tracer_spans_threads_and_save(tmp_path):
    tracer = Tracer(process_name="test-proc")

    def worker():
        with tracer.span("worker-stage", items=3):
            time.sleep(0.002)

    with tracer.span("outer"):
        with tracer.span("inner"):
            pass
        t = threading.Thread(target=worker, name="avdb-test-worker")
        t.start()
        t.join()
    tracer.counter("queue_depth", ingest=2, dispatch=0)
    evs = tracer.events()
    _check_trace_events(evs)
    names = {
        e["args"]["name"] for e in evs
        if e["ph"] == "M" and e["name"] == "thread_name"
    }
    assert "avdb-test-worker" in names
    assert any(e["ph"] == "C" and e["name"] == "queue_depth" for e in evs)
    out = tmp_path / "trace.json"
    tracer.save(str(out))
    doc = json.loads(out.read_text())
    assert doc["displayTimeUnit"] == "ms"
    _check_trace_events(doc["traceEvents"])


def test_tracer_ident_reuse_gets_own_track(monkeypatch):
    """OS thread idents are recycled: the lazily-spawned store-writer
    routinely inherits the exited ingest thread's ident, and keying
    tracks on the raw ident silently merged the two threads into one
    misnamed track (the CLI trace then showed no writer lane at all).
    A reused ident under a NEW thread name must open a fresh track."""
    tracer = Tracer(process_name="test-proc")
    monkeypatch.setattr(threading, "get_ident", lambda: 4242)
    names = iter(["avdb-vcf-ingest", "avdb-vcf-ingest", "avdb-store_0"])

    class _T:
        def __init__(self, name):
            self.name = name

    monkeypatch.setattr(
        threading, "current_thread", lambda: _T(next(names))
    )
    tracer.begin("ingest")
    tracer.end("ingest")  # same name: stays on the first track
    tracer.begin("append")  # same ident, new name: must NOT merge
    metas = {
        e["args"]["name"]: e["tid"] for e in tracer.events()
        if e["ph"] == "M" and e["name"] == "thread_name"
    }
    assert set(metas) >= {"avdb-vcf-ingest", "avdb-store_0"}
    assert metas["avdb-vcf-ingest"] != metas["avdb-store_0"]
    by_track = {
        e["tid"]: e["name"] for e in tracer.events() if e["ph"] == "B"
    }
    assert by_track[metas["avdb-store_0"]] == "append"


def test_stage_timer_mirrors_spans_to_tracer():
    from annotatedvdb_tpu.utils.profiling import StageTimer

    timer = StageTimer()
    timer.tracer = Tracer()
    with timer.wall():
        with timer.stage("annotate", items=10):
            pass
        with timer.stage("lookup"):
            pass
    evs = timer.tracer.events()
    _check_trace_events(evs)
    span_names = [e["name"] for e in evs if e["ph"] == "B"]
    assert span_names == ["load", "annotate", "lookup"]


# ----------------------------------------------------------- backpressure


def test_bounded_stage_stall_accounting_under_backpressure():
    """A fast producer against a slow consumer accumulates producer-block
    seconds; a slow producer starves its consumer into consumer-wait
    seconds.  Both live on the stage's StageStats."""
    from annotatedvdb_tpu.utils.pipeline import BoundedStage

    # fast producer, slow consumer -> producer blocks on the full queue
    stage = BoundedStage(iter(range(12)), depth=1, name="t-fast")
    got = []
    for item in stage:
        time.sleep(0.02)
        got.append(item)
    assert got == list(range(12))
    assert stage.stats.items == 12
    assert stage.stats.producer_block_s > 0.05
    assert stage.stats.max_depth >= 1
    d = stage.stats.as_dict()
    assert set(d) == {"items", "producer_block_s", "consumer_wait_s",
                      "max_depth"}

    # slow producer -> the consumer waits on an empty queue
    def slow():
        for i in range(4):
            time.sleep(0.02)
            yield i

    stage = BoundedStage(slow(), depth=2, name="t-slow")
    assert list(stage) == [0, 1, 2, 3]
    assert stage.stats.consumer_wait_s > 0.05
    assert stage.stats.producer_block_s < 0.05


def test_loader_queue_stalls_populated(tmp_path, monkeypatch):
    """An overlapped load fills the loader's queue_stalls table with one
    record per stage boundary."""
    monkeypatch.setenv("AVDB_PIPELINE", "overlapped")
    from annotatedvdb_tpu.loaders import TpuVcfLoader
    from annotatedvdb_tpu.store import AlgorithmLedger, VariantStore

    vcf = tmp_path / "s.vcf"
    lines = ["##fileformat=VCFv4.2",
             "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO"]
    for i in range(2000):
        lines.append(f"1\t{1000 + i * 3}\trs{i}\tA\tG\t.\t.\t.")
    vcf.write_text("\n".join(lines) + "\n")
    store = VariantStore(width=16)
    ledger = AlgorithmLedger(str(tmp_path / "l.jsonl"))
    loader = TpuVcfLoader(store, ledger, batch_size=128, log=lambda *a: None)
    loader.load_file(str(vcf), commit=True)
    loader.close()
    assert {"ingest", "dispatch", "store-writer"} <= set(loader.queue_stalls)
    for rec in loader.queue_stalls.values():
        assert rec["items"] > 0
        assert rec["producer_block_s"] >= 0
        assert rec["consumer_wait_s"] >= 0
    from annotatedvdb_tpu.utils.profiling import stall_summary

    line = stall_summary(loader.queue_stalls, loader.timer.wall_seconds)
    assert "ingest" in line and "dispatch" in line


# ------------------------------------------------------------- run ledger


def test_run_record_shape():
    rec = run_record(
        "load-vcf", "/x/in.vcf", {"commit": True}, {"variant": 100, "line": 120},
        wall_seconds=2.0, stages={"annotate": {"seconds": 1.0, "items": 100}},
        queue_stalls={"ingest": {"items": 1, "producer_block_s": 0.0,
                                 "consumer_wait_s": 0.1, "max_depth": 2}},
    )
    assert rec["status"] == "completed"
    assert rec["throughput_per_sec"] == 50.0
    assert rec["config_hash"] == config_hash({"commit": True})
    err = run_record(
        "load-vcf", "/x/in.vcf", {}, {"variant": 1}, 1.0,
        error=RuntimeError("boom"),
    )
    assert err["status"] == "aborted"
    assert err["error_class"] == "RuntimeError"


def test_config_hash_stable_and_order_independent():
    assert config_hash({"a": 1, "b": 2}) == config_hash({"b": 2, "a": 1})
    assert config_hash({"a": 1}) != config_hash({"a": 2})


def test_ledger_run_records_append_and_survive_reload(tmp_path):
    from annotatedvdb_tpu.store import AlgorithmLedger

    path = str(tmp_path / "ledger.jsonl")
    ledger = AlgorithmLedger(path)
    alg_id = ledger.begin("x", {"file": "a.vcf"}, True)
    ledger.run(run_record("load-vcf", "a.vcf", {}, {"variant": 5}, 1.0))
    ledger.finish(alg_id, {"variant": 5})
    # run records never disturb resume-cursor logic
    assert ledger.last_checkpoint("a.vcf") == 0
    reloaded = AlgorithmLedger(path)
    runs = reloaded.runs()
    assert len(runs) == 1
    assert runs[0]["script"] == "load-vcf" and runs[0]["type"] == "run"


def test_obs_session_appends_run_record_on_abort(tmp_path):
    """A load that dies mid-file still lands one ``type: "run"`` record
    with the error class — the CLIs' except-path contract."""
    from annotatedvdb_tpu.loaders import TpuVcfLoader
    from annotatedvdb_tpu.store import AlgorithmLedger, VariantStore

    vcf = tmp_path / "a.vcf"
    lines = ["##fileformat=VCFv4.2",
             "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO"]
    for i in range(600):
        vid = "failhere" if i == 300 else f"rs{i}"
        lines.append(f"1\t{1000 + i * 3}\t{vid}\tA\tG\t.\t.\t.")
    vcf.write_text("\n".join(lines) + "\n")
    store = VariantStore(width=16)
    ledger = AlgorithmLedger(str(tmp_path / "l.jsonl"))
    loader = TpuVcfLoader(store, ledger, batch_size=128, log=lambda *a: None)
    obs = ObsSession(
        "load-vcf", str(vcf), {"commit": True},
        metrics_out=str(tmp_path / "m.prom"),
        trace_out=str(tmp_path / "t.json"),
    )
    obs.attach(loader)
    with pytest.raises(RuntimeError, match="failAt"):
        try:
            loader.load_file(str(vcf), commit=True, fail_at="failhere")
        except BaseException as exc:
            obs.abort(ledger, exc, store=store)
            raise
    loader.close()
    runs = AlgorithmLedger(str(tmp_path / "l.jsonl")).runs()
    assert len(runs) == 1
    assert runs[0]["status"] == "aborted"
    assert runs[0]["error_class"] == "RuntimeError"
    assert runs[0]["counters"]["variant"] > 0  # pre-fault chunks committed
    # exports still happened (the abort path writes the same artifacts)
    assert (tmp_path / "m.prom").exists()
    doc = json.loads((tmp_path / "t.json").read_text())
    _check_trace_events(doc["traceEvents"])


def test_obs_session_finish_exports_everything(tmp_path):
    """Happy path: counters + stages + stalls land in the registry, both
    metric files and the trace are written, one run record appended."""
    from annotatedvdb_tpu.loaders import TpuVcfLoader
    from annotatedvdb_tpu.store import AlgorithmLedger, VariantStore

    vcf = tmp_path / "b.vcf"
    lines = ["##fileformat=VCFv4.2",
             "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO"]
    for i in range(500):
        lines.append(f"1\t{1000 + i * 3}\trs{i}\tA\tG\t.\t.\t.")
    vcf.write_text("\n".join(lines) + "\n")
    store = VariantStore(width=16)
    ledger = AlgorithmLedger(str(tmp_path / "l.jsonl"))
    loader = TpuVcfLoader(store, ledger, batch_size=128, log=lambda *a: None)
    obs = ObsSession(
        "load-vcf", str(vcf), {"commit": True},
        metrics_out=str(tmp_path / "m.prom"),
        trace_out=str(tmp_path / "t.json"),
    )
    obs.attach(loader)
    counters = loader.load_file(str(vcf), commit=True)
    loader.close()
    obs.finish(ledger, counters, store=store)
    text = (tmp_path / "m.prom").read_text()
    assert 'avdb_load_variant_total{loader="load-vcf"} 500' in text
    assert "avdb_stage_busy_seconds_total" in text
    assert "avdb_queue_producer_block_seconds_total" in text
    assert 'avdb_store_rows{chrom="1"} 500' in text
    doc = json.loads((tmp_path / "t.json").read_text())
    tracks = {
        e["args"]["name"] for e in doc["traceEvents"]
        if e["ph"] == "M" and e["name"] == "thread_name"
    }
    # host timeline covers every pipeline thread (>= 4 named tracks)
    assert len(tracks) >= 4, tracks
    runs = ledger.runs()
    assert len(runs) == 1 and runs[0]["status"] == "completed"
    assert runs[0]["queue_stalls"]


# -------------------------------------------------- satellites: logging


def test_progress_cadence_flushes_final_line():
    """A load ending between cadences (short file) still logs a terminal
    PARSED line; one that ended exactly on a cadence does not repeat it."""
    from annotatedvdb_tpu.utils.logging import ProgressCadence

    logs = []
    cad = ProgressCadence(lambda m: logs.append(m), 100)
    cad.maybe_log(40, {"variant": 40})   # below cadence: nothing yet
    assert logs == []
    cad.finish(40, {"variant": 40})
    assert len(logs) == 1 and "final" in logs[0] and "40" in logs[0]

    logs.clear()
    cad = ProgressCadence(lambda m: logs.append(m), 100)
    cad.maybe_log(100, {"variant": 100})
    assert len(logs) == 1
    cad.finish(100, {"variant": 100})    # already logged at exactly 100
    assert len(logs) == 1

    logs.clear()
    cad = ProgressCadence(lambda m: logs.append(m), None)  # cadence off
    cad.finish(40, {})
    assert logs == []


def test_load_logger_registry_is_bounded(tmp_path):
    import logging as _logging

    from annotatedvdb_tpu.utils import logging as avdb_logging

    before = {
        n for n in _logging.Logger.manager.loggerDict if n.startswith("avdb.")
    }
    n = avdb_logging.MAX_LIVE_LOGGERS + 8
    for i in range(n):
        inp = tmp_path / f"in{i}.vcf"
        inp.write_text("")
        log, _logger, _p = avdb_logging.load_logger(str(inp), "t")
        log("hello")
    after = {
        n for n in _logging.Logger.manager.loggerDict if n.startswith("avdb.")
    }
    # +1: the "avdb.t" ancestor placeholder logging interns per tag
    assert len(after - before) <= avdb_logging.MAX_LIVE_LOGGERS + 1
    # the most recent logger still works (file handler intact)
    log("still alive")
    assert "still alive" in (tmp_path / f"in{n-1}.vcf-t.log").read_text()


# ------------------------------------------------------------ CLI surface


def test_cli_metrics_and_trace_flags(tmp_path):
    """End-to-end through the real CLI: --metricsOut/--traceOut produce a
    Prometheus textfile, a JSON snapshot, a loadable Chrome trace, and a
    run record in the store ledger."""
    vcf = tmp_path / "in.vcf"
    body = ["##fileformat=VCFv4.2",
            "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO"]
    for i in range(300):
        body.append(f"1\t{100 + i * 5}\trs{i}\tA\tG\t.\t.\t.")
    vcf.write_text("\n".join(body) + "\n")
    res = subprocess.run(
        [sys.executable, "-m", "annotatedvdb_tpu.cli.load_vcf",
         "--fileName", str(vcf), "--storeDir", str(tmp_path / "vdb"),
         "--commit", "--commitAfter", "64",
         "--metricsOut", str(tmp_path / "m.prom"),
         "--traceOut", str(tmp_path / "t.json")],
        capture_output=True, text=True, timeout=600,
    )
    assert res.returncode == 0, res.stderr
    text = (tmp_path / "m.prom").read_text()
    assert "# TYPE avdb_chunk_rows histogram" in text
    assert "avdb_load_variant_total" in text
    assert json.loads((tmp_path / "m.prom.json").read_text())
    doc = json.loads((tmp_path / "t.json").read_text())
    _check_trace_events(doc["traceEvents"])
    tracks = {
        e["args"]["name"] for e in doc["traceEvents"]
        if e["ph"] == "M" and e["name"] == "thread_name"
    }
    assert len(tracks) >= 4, tracks
    runs = [
        json.loads(line)
        for line in (tmp_path / "vdb" / "ledger.jsonl").read_text().splitlines()
        if '"run"' in line
    ]
    runs = [r for r in runs if r.get("type") == "run"]
    assert len(runs) == 1 and runs[0]["status"] == "completed"
    assert runs[0]["config_hash"]
