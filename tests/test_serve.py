"""avdb-serve test battery: the query engine against a brute-force
reference scan, the batcher under real concurrency, snapshot isolation
against a committing loader, the HTTP front end end-to-end (including 429
admission), and the read-only store-open contract.

Parity discipline: the reference scan walks every row of every segment in
plain host Python (no hashing, no searchsorted, no bin pruning) and shares
only the final record renderer with the engine — so any divergence in the
engine's hash/probe/slice/dedup machinery shows up as a byte diff, while a
sample of records is additionally field-checked against the original input
data to pin the renderer itself.  Region envelopes are rebuilt in-test from
the scalar bin ORACLE (``oracle.binindex.closed_form_bin``), so the
device-kernel bin answer is cross-checked per query too.
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from annotatedvdb_tpu.loaders.lookup import identity_hashes
from annotatedvdb_tpu.oracle.binindex import closed_form_bin, closed_form_path
from annotatedvdb_tpu.serve import (
    QueryBatcher,
    QueryEngine,
    QueryError,
    QueueFull,
    SnapshotManager,
    StaticSnapshots,
    parse_region,
    parse_variant_id,
    render_variant,
)
from annotatedvdb_tpu.store import VariantStore
from annotatedvdb_tpu.store.variant_store import RawJson, Segment
from annotatedvdb_tpu.types import chromosome_label, encode_allele_array

WIDTH = 8
CHROMS = (1, 8, 23)  # "1", "8", "X"
BASES = ("A", "C", "G", "T")


# ---------------------------------------------------------------------------
# synthetic multi-chromosome store


def _rows_for(code: int, base_pos: int, n: int, salt: int):
    """Deterministic row set: SNVs + indels, sparse annotations (CADD on
    every 3rd row, ranked consequence on every 4th, RawJson vep_output on
    every 5th), positions spread across several level-13 bins."""
    rows = []
    for i in range(n):
        pos = base_pos + 977 * i
        k = (i + salt) % 4
        ref = BASES[k]
        alt = BASES[(k + 1) % 4] if i % 3 else ref + "TG"  # every 3rd: indel
        rows.append({
            "chrom": code, "pos": pos, "ref": ref, "alt": alt,
            "rs": (1000 * code + i) if i % 2 else -1,
            "cadd": round(0.5 * i + code, 2) if i % 3 == 0 else None,
            "rank": (i % 30) + 1 if i % 4 == 0 else None,
            "vep": i % 5 == 0,
        })
    return rows


def _append(shard, rows, direct: bool = False):
    refs = [r["ref"] for r in rows]
    alts = [r["alt"] for r in rows]
    ref, ref_len = encode_allele_array(refs, WIDTH)
    alt, alt_len = encode_allele_array(alts, WIDTH)
    h = identity_hashes(WIDTH, ref, alt, ref_len, alt_len, refs, alts)
    cols = {
        "pos": np.asarray([r["pos"] for r in rows], np.int32),
        "h": h, "ref_len": ref_len, "alt_len": alt_len,
        "ref_snp": np.asarray([r["rs"] for r in rows], np.int64),
    }
    ann = {
        "cadd_scores": [
            {"CADD_raw_score": r["cadd"] / 10, "CADD_phred": r["cadd"]}
            if r["cadd"] is not None else None for r in rows
        ],
        "adsp_most_severe_consequence": [
            {"conseq": "missense_variant", "rank": r["rank"]}
            if r["rank"] is not None else None for r in rows
        ],
        "vep_output": [
            RawJson(f'{{"input":"{r["chrom"]}:{r["pos"]}","n":{i}}}')
            if r["vep"] else None for i, r in enumerate(rows)
        ],
    }
    long_alleles = [
        (r["ref"], r["alt"])
        if len(r["ref"]) > WIDTH or len(r["alt"]) > WIDTH else None
        for r in rows
    ]
    if direct:  # overlapping segment: no cascade merge, stays separate
        shard.append_segment(Segment.build(
            cols, ref, alt, annotations=ann, long_alleles=long_alleles
        ))
        shard._starts_cache = None
    else:
        shard.append(cols, ref, alt, annotations=ann,
                     long_alleles=long_alleles)


def _build_store(store_dir: str):
    """Three chromosomes, three disjoint segments each, plus one OVERLAPPING
    extra segment on chr8 carrying a shadowed duplicate identity (the
    store's first-wins policy must hide it) and an over-width long-allele
    row (the host-string hash override path).  Returns the truth rows that
    must be visible (shadowed duplicates excluded)."""
    store = VariantStore(width=WIDTH)
    truth: list[dict] = []
    for code in CHROMS:
        shard = store.shard(code)
        for run, base in enumerate((500, 120_000, 2_000_000)):
            rows = _rows_for(code, base, 40, salt=run)
            _append(shard, rows)
            truth.extend(rows)
    # chr8 extra segment: one duplicate of an existing row (different
    # annotations — must stay shadowed), one fresh in-range row, one
    # over-width long-allele row
    shard = store.shard(8)
    dup_src = next(r for r in truth if r["chrom"] == 8 and r["pos"] == 500)
    shadowed = dict(dup_src, cadd=999.0, rank=1, vep=False)
    fresh = {"chrom": 8, "pos": 501, "ref": "T", "alt": "C", "rs": 77,
             "cadd": 33.3, "rank": 2, "vep": False}
    long_row = {"chrom": 8, "pos": 600, "ref": "A" * 20, "alt": "G",
                "rs": -1, "cadd": None, "rank": None, "vep": False}
    _append(shard, [shadowed, fresh, long_row], direct=True)
    truth.extend([fresh, long_row])
    store.save(store_dir)
    return truth


def _vid(row: dict) -> str:
    return (f"{chromosome_label(row['chrom'])}:{row['pos']}"
            f":{row['ref']}:{row['alt']}")


# ---------------------------------------------------------------------------
# brute-force reference scan (plain host Python, shares only the renderer)


def _brute_find(shard, pos: int, ref: str, alt: str):
    """First-wins global id by walking every row of every segment."""
    starts = shard._starts()
    for si, seg in enumerate(shard.segments):
        for j in range(seg.n):
            if int(seg.cols["pos"][j]) != pos:
                continue
            gid = int(starts[si]) + j
            if shard.alleles(gid) == (ref, alt):
                return gid
    return None


def _brute_region_rows(shard, start: int, end: int):
    """(segment, local) rows in engine order: (pos, hash, segment age),
    duplicates first-wins."""
    rows = []
    for si, seg in enumerate(shard.segments):
        for j in range(seg.n):
            p = int(seg.cols["pos"][j])
            if start <= p <= end:
                rows.append((p, int(seg.cols["h"][j]), si, j))
    rows.sort(key=lambda r: (r[0], r[1], r[2]))
    starts = shard._starts()
    kept, seen = [], set()
    for p, h, si, j in rows:
        ident = (p, h) + shard.alleles(int(starts[si]) + j)
        if ident in seen:
            continue
        seen.add(ident)
        kept.append((si, j))
    return kept


def _brute_region_text(store, generation: int, code: int, start: int,
                       end: int, min_cadd=None, max_rank=None, limit=None):
    """The full region response rebuilt from the brute scan + the scalar
    bin ORACLE (cross-checking the device kernel's bin answer)."""
    label = chromosome_label(code)
    level, leaf = closed_form_bin(start, end)
    shard = store.shards.get(code)
    kept = _brute_region_rows(shard, start, end) if shard is not None else []
    if min_cadd is not None or max_rank is not None:
        filtered = []
        for si, j in kept:
            seg = shard.segments[si]

            def field(col, name):
                v = seg.obj[col][j] if seg.obj[col] is not None else None
                return v.get(name) if v is not None else None

            if min_cadd is not None:
                phred = field("cadd_scores", "CADD_phred")
                if phred is None or phred < min_cadd:
                    continue
            if max_rank is not None:
                rank = field("adsp_most_severe_consequence", "rank")
                if rank is None or rank > max_rank:
                    continue
            filtered.append((si, j))
        kept = filtered
    shown = kept if limit is None else kept[:limit]
    starts = shard._starts() if shard is not None else None
    rendered = [
        render_variant(shard, code, int(starts[si]) + j) for si, j in shown
    ]
    return (
        f'{{"region":{json.dumps(f"{label}:{start}-{end}")}'
        f',"bin_level":{level}'
        f',"bin_index":{json.dumps(closed_form_path(label, level, leaf))}'
        f',"count":{len(kept)}'
        f',"returned":{len(rendered)}'
        f',"generation":{generation}'
        ',"variants":[' + ",".join(rendered) + "]}"
    )


# ---------------------------------------------------------------------------
# fixtures


@pytest.fixture(scope="module")
def served(tmp_path_factory):
    """(store_dir, truth rows, SnapshotManager, QueryEngine)."""
    store_dir = str(tmp_path_factory.mktemp("serve_store"))
    truth = _build_store(store_dir)
    manager = SnapshotManager(store_dir)
    engine = QueryEngine(manager, region_cache_size=8)
    return store_dir, truth, manager, engine


# ---------------------------------------------------------------------------
# grammar


def test_query_grammar():
    assert parse_variant_id("chr8:100:a:g") == (8, 100, "A", "G")
    assert parse_variant_id("X:5:AT:A") == (23, 5, "AT", "A")
    # the store's own primary keys round-trip (trailing rs field tolerated)
    assert parse_variant_id("8:100:A:G:rs55") == (8, 100, "A", "G")
    assert parse_region("chr8:100-2000") == (8, 100, 2000)
    for bad in ("8:100", "8:100:A", "banana:1:A:G", "8:zero:A:G",
                "8:0:A:G", "8:100:A!:G", "8:100:A:G:extra:junk"):
        with pytest.raises(QueryError):
            parse_variant_id(bad)
    for bad in ("8:100", "8:a-b", "nope:1-2", "8:9-3", "8:0-5",
                "8:1-999999999"):
        with pytest.raises(QueryError):
            parse_region(bad)


# ---------------------------------------------------------------------------
# engine parity vs the brute-force scan


def test_point_parity_all_rows(served):
    _dir, truth, manager, engine = served
    store = manager.current().store
    for row in truth:
        shard = store.shards[row["chrom"]]
        gid = _brute_find(shard, row["pos"], row["ref"], row["alt"])
        assert gid is not None, row
        want = render_variant(shard, row["chrom"], gid)
        got = engine.lookup(_vid(row))
        assert got == want, f"point mismatch for {_vid(row)}"


def test_point_renderer_fields_match_inputs(served):
    _dir, truth, _manager, engine = served
    for row in truth[::7]:  # renderer spot-check against the source data
        rec = json.loads(engine.lookup(_vid(row)))
        assert rec["chromosome"] == chromosome_label(row["chrom"])
        assert rec["position"] == row["pos"]
        assert (rec["ref"], rec["alt"]) == (row["ref"], row["alt"])
        assert rec["ref_snp"] == (
            f"rs{row['rs']}" if row["rs"] >= 0 else None
        )
        ann = rec["annotations"]
        if row["cadd"] is not None:
            assert ann["cadd_scores"]["CADD_phred"] == row["cadd"]
        else:
            assert "cadd_scores" not in ann
        if row["rank"] is not None:
            assert ann["adsp_most_severe_consequence"]["rank"] == row["rank"]
        if row["vep"]:  # RawJson splice survives as real JSON
            assert ann["vep_output"]["input"].startswith(str(row["chrom"]))


def test_point_misses_and_shadowed_duplicate(served):
    _dir, truth, manager, engine = served
    assert engine.lookup("8:499:A:G") is None          # absent position
    assert engine.lookup("2:500:A:G") is None          # unloaded chromosome
    assert engine.lookup("8:500:T:C") is None          # wrong alleles
    # the duplicate identity planted in the newer chr8 segment is shadowed:
    # the OLD row's annotations win (first-wins), never cadd=999
    dup = next(r for r in truth if r["chrom"] == 8 and r["pos"] == 500)
    rec = json.loads(engine.lookup(_vid(dup)))
    cadd = rec["annotations"].get("cadd_scores")
    assert cadd is None or cadd["CADD_phred"] != 999.0


def test_overwidth_long_allele_point(served):
    _dir, truth, _manager, engine = served
    long_row = next(r for r in truth if len(r["ref"]) > WIDTH)
    rec = json.loads(engine.lookup(_vid(long_row)))
    assert rec["ref"] == long_row["ref"]  # true string, not the truncation


def test_point_render_cache_byte_bounded(served):
    """The render LRU is bounded in BYTES as well as entries: records
    carrying large annotation blobs must not pin entries x record-size
    of RSS in a long-lived serving process.  The byte ledger stays exact
    under eviction."""
    store_dir, truth, _manager, _engine = served
    eng = QueryEngine(SnapshotManager(store_dir))
    rows = [r for r in truth if r["chrom"] == 8][:20]
    one = len(eng.lookup(_vid(rows[0])))
    eng.POINT_RENDER_CACHE_BYTES = int(one * 2.5)  # room for ~2 records
    for r in rows:
        assert eng.lookup(_vid(r)) is not None
    assert eng._render_cache_bytes <= eng.POINT_RENDER_CACHE_BYTES
    assert eng._render_cache_bytes == sum(
        len(v) for v in eng._render_cache.values()
    )
    assert len(eng._render_cache) >= 1  # the bound evicts, not disables


def test_bulk_parity_thousands(served):
    _dir, truth, _manager, engine = served
    ids = [_vid(r) for r in truth]
    misses = [f"8:{p}:A:G" for p in range(3, 3 + 60)]
    batch = (ids + misses) * 8  # ~3.5k ids through one vectorized call
    got = engine.lookup_many(batch)
    singles = {i: engine.lookup(i) for i in set(batch)}
    assert got == [singles[i] for i in batch]
    assert sum(1 for r in got if r is None) == len(misses) * 8
    with pytest.raises(QueryError):
        engine.lookup_many(["8:1:A:G", "garbage"])


REGIONS = [
    (8, 1, 10_000),            # spans the overlapping extra segment
    (8, 490, 600),             # duplicate + long-allele corner
    (8, 120_000, 160_000),     # interior of the second run
    (1, 1, 3_000_000),         # whole loaded range, crosses all segments
    (23, 2_000_000, 2_005_000),
    (8, 50_000, 60_000),       # gap: zero rows
    (11, 1, 5_000),            # unloaded chromosome: zero rows
]


@pytest.mark.parametrize("code,start,end", REGIONS)
def test_region_parity(served, code, start, end):
    _dir, _truth, manager, engine = served
    snap = manager.current()
    label = chromosome_label(code)
    got = engine.region(f"{label}:{start}-{end}")
    want = _brute_region_text(snap.store, snap.generation, code, start, end)
    assert got == want  # byte-identical, envelope included


def test_region_filters_and_limit(served):
    _dir, _truth, manager, engine = served
    snap = manager.current()
    for min_cadd, max_rank, limit in (
        (10.0, None, None), (None, 5, None), (4.0, 10, None),
        (None, None, 3), (1.0, 25, 2),
    ):
        got = engine.region("8:1-3000000", min_cadd=min_cadd,
                            max_conseq_rank=max_rank, limit=limit)
        want = _brute_region_text(
            snap.store, snap.generation, 8, 1, 3_000_000,
            min_cadd=min_cadd, max_rank=max_rank, limit=limit,
        )
        assert got == want
        rec = json.loads(got)
        assert rec["returned"] == len(rec["variants"])
        assert rec["returned"] <= rec["count"]


def test_region_lru_cache():
    store = VariantStore(width=WIDTH)
    shard = store.shard(8)
    _append(shard, _rows_for(8, 500, 10, salt=0))
    from annotatedvdb_tpu.obs.metrics import MetricsRegistry

    reg = MetricsRegistry()
    engine = QueryEngine(StaticSnapshots(store), registry=reg,
                         region_cache_size=2)
    first = engine.region("8:1-100000")
    assert engine.region("8:1-100000") == first          # hit
    engine.region("8:1-5")                               # fill
    engine.region("8:6-10")                              # evicts the first
    engine.region("8:1-100000")                          # miss again
    snap = reg.snapshot()
    assert snap["avdb_query_cache_hits_total"][0]["value"] == 1
    assert snap["avdb_query_cache_misses_total"][0]["value"] == 4


# ---------------------------------------------------------------------------
# batcher


def test_batcher_32_concurrent_clients(served):
    _dir, truth, _manager, engine = served
    ids = [_vid(r) for r in truth]
    expected = {i: engine.lookup(i) for i in ids}
    expected["8:499:A:G"] = None
    batcher = QueryBatcher(engine, max_batch=64, max_wait_s=0.005,
                           max_queue=10_000)
    n_threads, per_thread = 32, 25
    failures: list = []
    barrier = threading.Barrier(n_threads)

    def client(tid: int):
        try:
            barrier.wait(timeout=10)
            for k in range(per_thread):
                qid = ids[(tid * 7 + k * 13) % len(ids)] \
                    if (tid + k) % 5 else "8:499:A:G"
                got = batcher.submit(qid)
                if got != expected[qid]:
                    failures.append((tid, qid))
        except Exception as exc:
            failures.append((tid, repr(exc)))

    threads = [threading.Thread(target=client, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    try:
        assert not failures, failures[:5]
        stats = batcher.drain_stats()
        assert stats["queries"] == n_threads * per_thread
        # coalescing actually happened: far fewer drains than queries
        assert stats["batches"] < stats["queries"]
        assert 0.0 < stats["batch_fill"] <= 1.0
    finally:
        batcher.close()


def test_batcher_bad_grammar_stays_with_its_caller(served):
    _dir, truth, _manager, engine = served
    batcher = QueryBatcher(engine, max_batch=8, max_wait_s=0.001)
    try:
        with pytest.raises(QueryError):
            batcher.submit("not-a-variant")
        # the drain thread is unharmed and still answers real queries
        assert batcher.submit(_vid(truth[0])) is not None
    finally:
        batcher.close()


def test_batcher_admission_bound(served):
    _dir, truth, _manager, engine = served
    batcher = QueryBatcher(engine, max_batch=8, max_wait_s=0.001,
                           max_queue=0)
    try:
        with pytest.raises(QueueFull):
            batcher.submit(_vid(truth[0]))
    finally:
        batcher.close()


# ---------------------------------------------------------------------------
# snapshot isolation


def _commit_more_rows(store_dir: str) -> int:
    """A loader-shaped commit into the serving directory: load writable,
    append, save (atomic manifest swap)."""
    store = VariantStore.load(store_dir)
    rows = [{"chrom": 8, "pos": 5_000_000 + 11 * i, "ref": "A", "alt": "C",
             "rs": -1, "cadd": None, "rank": None, "vep": False}
            for i in range(25)]
    _append(store.shard(8), rows)
    store.save(store_dir)
    return len(rows)


def test_snapshot_isolation_across_commit(tmp_path):
    store_dir = str(tmp_path / "store")
    _build_store(store_dir)
    manager = SnapshotManager(store_dir)
    engine = QueryEngine(manager, region_cache_size=0)
    pinned = manager.current()
    rows_before = pinned.store.n
    before = engine.region("8:4999999-5001000")
    assert json.loads(before)["count"] == 0
    assert manager.refresh() is False  # nothing changed on disk

    added = _commit_more_rows(store_dir)

    # no refresh yet: in-flight readers keep the pinned generation
    assert json.loads(engine.region("8:4999999-5001000"))["count"] == 0
    assert manager.current() is pinned

    assert manager.refresh() is True
    snap = manager.current()
    assert snap.generation == pinned.generation + 1
    assert snap.store.n == rows_before + added
    got = json.loads(engine.region("8:4999999-5001000"))
    assert got["count"] > 0 and got["generation"] == snap.generation
    # the OLD snapshot object still answers exactly the old generation
    assert pinned.store.n == rows_before
    assert manager.refresh() is False


# ---------------------------------------------------------------------------
# HTTP front end


def _get(port: int, path: str):
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30
        ) as r:
            return r.status, r.read().decode(), dict(r.headers)
    except urllib.error.HTTPError as err:
        return err.code, err.read().decode(), dict(err.headers)


@pytest.fixture()
def http_server(served):
    from annotatedvdb_tpu.serve.http import build_server

    store_dir, truth, _manager, _engine = served
    httpd = build_server(store_dir=store_dir, port=0)
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    try:
        yield httpd, httpd.server_address[1], truth
    finally:
        httpd.shutdown()
        httpd.server_close()
        httpd.ctx.batcher.close()


def test_http_end_to_end(http_server):
    httpd, port, truth = http_server
    status, body, _ = _get(port, "/healthz")
    assert status == 200 and json.loads(body)["status"] == "ok"

    row = truth[0]
    status, body, _ = _get(port, f"/variant/{_vid(row)}")
    assert status == 200
    assert json.loads(body)["position"] == row["pos"]

    status, body, _ = _get(port, "/variant/8:499:A:G")
    assert status == 404
    status, body, _ = _get(port, "/variant/garbage")
    assert status == 400
    status, body, _ = _get(port, "/nope")
    assert status == 404

    status, body, _ = _get(port, "/region/8:1-10000?minCadd=5&limit=4")
    assert status == 200
    rec = json.loads(body)
    assert rec["returned"] <= 4
    assert all(
        v["annotations"]["cadd_scores"]["CADD_phred"] >= 5
        for v in rec["variants"]
    )
    status, body, _ = _get(port, "/region/8:9-3")
    assert status == 400

    ids = [_vid(r) for r in truth[:50]] + ["8:499:A:G"]
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/variants",
        data=json.dumps({"ids": ids}).encode(), method="POST",
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=30) as r:
        rec = json.loads(r.read().decode())
    assert rec["n"] == 51 and rec["found"] == 50
    assert rec["results"][-1] is None

    # explicit limit=0 is a count-only query, NOT the default page size
    status, body, _ = _get(port, "/region/8:1-10000?limit=0")
    rec = json.loads(body)
    assert status == 200 and rec["returned"] == 0 and rec["count"] > 0
    assert rec["variants"] == []

    # malformed bulk bodies are client errors (400), never a dead thread
    for bad in (b"[1,2]", b'{"ids": [1]}', b'{"ids": "x"}', b"{nope"):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/variants", data=bad, method="POST"
        )
        try:
            urllib.request.urlopen(req, timeout=30)
            raise AssertionError(f"bulk body {bad!r} was accepted")
        except urllib.error.HTTPError as err:
            assert err.code == 400, (bad, err.code)

    status, body, _ = _get(port, "/metrics")
    assert status == 200
    for metric in ("avdb_query_requests_total", "avdb_query_seconds",
                   "avdb_serve_batches_total"):
        assert metric in body, metric
    status, body, _ = _get(port, "/stats")
    assert status == 200 and json.loads(body)["batcher"]["queries"] >= 2


def test_http_429_under_forced_backpressure(served):
    from annotatedvdb_tpu.serve.http import build_server

    store_dir, truth, _manager, _engine = served
    httpd = build_server(store_dir=store_dir, port=0, max_queue=0)
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    try:
        port = httpd.server_address[1]
        status, _body, headers = _get(port, f"/variant/{_vid(truth[0])}")
        assert status == 429
        assert headers.get("Retry-After") == "1"
        status, _body, _ = _get(port, "/region/8:1-10000")
        assert status == 429
        status, body, _ = _get(port, "/metrics")
        assert "avdb_query_rejected_total" in body
    finally:
        httpd.shutdown()
        httpd.server_close()
        httpd.ctx.batcher.close()


# ---------------------------------------------------------------------------
# read-only store open


def test_readonly_open_contract(tmp_path):
    store_dir = str(tmp_path / "ro")
    _build_store(store_dir)
    store = VariantStore.load(store_dir, readonly=True)
    assert store.readonly
    with pytest.raises(RuntimeError, match="readonly"):
        store.save(store_dir)
    with pytest.raises(RuntimeError, match="readonly"):
        store.shard(2)  # missing shard must not be materialized
    assert store.shards.get(2) is None
    assert store.shard(8).n > 0  # existing shards stay accessible
    # the writable default is unchanged
    assert not VariantStore.load(store_dir).readonly


def test_readonly_storeconfig_never_creates(tmp_path):
    from annotatedvdb_tpu.config import StoreConfig

    missing = str(tmp_path / "absent")
    with pytest.raises(FileNotFoundError):
        StoreConfig(missing).open(readonly=True)
    import os

    assert not os.path.exists(missing)  # no directory side effect
    store_dir = str(tmp_path / "present")
    _build_store(store_dir)
    store, _ledger = StoreConfig(store_dir).open(readonly=True)
    assert store.readonly
