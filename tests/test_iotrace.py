"""Crash-consistency sanitizer (``AVDB_IO_TRACE=1``): the ``utils/io``
traced wrappers, the ``analysis/iotrace`` happens-before recorder, and
the real store writers driven end-to-end with tracing armed.

The armed legs are the regression net for the three ordering holes this
sanitizer caught when first pointed at the tree: the replication
bootstrap's manifest install and promote's epoch commit had no directory
fsync under ``AVDB_FSYNC=1``, and fsck's repair manifest rewrite had
neither a crash point nor a directory fsync — all three now route
through ``utils.io.replace_manifest``.
"""

import json
import os

import pytest

import test_serve as ts
import test_replication as tr
from annotatedvdb_tpu.analysis.iotrace import (
    RECORDER,
    IoTraceRecorder,
    _durable_class,
    _manifest_refs,
)
from annotatedvdb_tpu.store import compact_store
from annotatedvdb_tpu.store import replication as repl
from annotatedvdb_tpu.store.fsck import fsck
from annotatedvdb_tpu.utils import io as tio


@pytest.fixture()
def traced(monkeypatch):
    """Arm tracing around one test, with a clean recorder both sides."""
    monkeypatch.setenv("AVDB_IO_TRACE", "1")
    RECORDER.reset()
    yield RECORDER
    RECORDER.reset()


def _kinds(recorder) -> set:
    return {v["kind"] for v in recorder.violations()}


# -- wrapper semantics -------------------------------------------------------


def test_unarmed_wrappers_are_passthrough(tmp_path, monkeypatch):
    """Unarmed, tio.open returns the raw file (no proxy) and no wrapper
    call touches the recorder."""
    monkeypatch.delenv("AVDB_IO_TRACE", raising=False)
    RECORDER.reset()
    p = str(tmp_path / "a.txt")
    f = tio.open(p, "w")
    assert type(f).__name__ != "TracedFile"
    f.write("x")
    tio.fsync(f)
    f.close()
    tio.replace(p, str(tmp_path / "b.txt"))
    tio.unlink(str(tmp_path / "b.txt"))
    tio.fsync_dir(str(tmp_path))
    assert RECORDER.report()["events"] == 0
    assert RECORDER.violations() == []


def test_traced_file_api_parity(tmp_path, traced):
    """The proxy answers every file-API surface the writers use."""
    p = str(tmp_path / "a.txt")
    with tio.open(p, "w") as f:
        assert type(f).__name__ == "TracedFile"
        f.write("line1\n")
        f.flush()
        assert isinstance(f.fileno(), int)
        assert f.tell() > 0
        assert f.name == p
        assert not f.closed
    assert f.closed
    # read opens stay raw even when armed (only writes are judged)
    with tio.open(p) as rf:
        assert type(rf).__name__ != "TracedFile"
        assert list(rf) == ["line1\n"]
    assert traced.report()["events"] >= 2  # the open + at least one write


def test_manifest_refs_both_formats(tmp_path):
    m1 = tmp_path / "m1.json"
    m1.write_text(json.dumps({"shards": {"8": [0, 1]}}))
    assert _manifest_refs(str(m1)) == {
        "chr8.000000.npz", "chr8.000000.ann.jsonl",
        "chr8.000001.npz", "chr8.000001.ann.jsonl",
    }
    m2 = tmp_path / "m2.json"
    m2.write_text(json.dumps({"format": 2, "shards": {"X": [3]}}))
    assert _manifest_refs(str(m2)) == {
        "chrX.000003.npz", "chrX.000003.ann.jsonl",
    }
    assert _manifest_refs(str(tmp_path / "absent.json")) == set()


def test_durable_class_taxonomy():
    assert _durable_class("manifest.json") == "manifest"
    assert _durable_class("serve-w0.wal") == "wal"
    assert _durable_class("chr8.000000.npz") == "data"
    assert _durable_class(".manifest.json.tmp123") is None
    assert _durable_class("chr8.000000.flush.tmp.npz") is None


# -- recorder judgments ------------------------------------------------------


def test_clean_commit_protocol_records_no_violation(tmp_path, traced):
    mpath = str(tmp_path / "manifest.json")
    tmp = mpath + ".t"
    with tio.open(tmp, "w") as f:
        f.write(json.dumps({"shards": {}}))
        f.flush()
        tio.fsync(f)
    tio.replace(tmp, mpath)
    assert traced.violations() == []


def test_misordered_writer_detected(tmp_path, traced, monkeypatch):
    """A writer that renames before fsync and never dir-fsyncs trips
    both judgments — the shape AVDB1001 proves statically, seen live."""
    monkeypatch.setenv("AVDB_FSYNC", "1")
    mpath = str(tmp_path / "manifest.json")
    tmp = mpath + ".t"
    with tio.open(tmp, "w") as f:
        f.write(json.dumps({"shards": {}}))
    tio.replace(tmp, mpath)  # dirty source: no fsync ever happened
    assert _kinds(traced) == {
        "rename-before-fsync", "manifest-replace-without-dir-fsync",
    }


def test_data_class_judged_only_under_avdb_fsync(tmp_path, traced,
                                                 monkeypatch):
    """Segment-data durability is the AVDB_FSYNC opt-in; the recorder
    mirrors it instead of inventing a stricter contract."""
    seg = str(tmp_path / "chr8.000000.npz")
    monkeypatch.delenv("AVDB_FSYNC", raising=False)
    with tio.open(seg + ".t", "wb") as f:
        f.write(b"x")
    tio.replace(seg + ".t", seg)
    assert traced.violations() == []  # unarmed: page-cache durability ok
    monkeypatch.setenv("AVDB_FSYNC", "1")
    with tio.open(seg + ".t", "wb") as f:
        f.write(b"x")
    tio.replace(seg + ".t", seg)
    assert _kinds(traced) == {"rename-before-fsync"}


def test_unlink_of_manifest_referenced_file_detected(tmp_path, traced):
    store = tmp_path / "store"
    store.mkdir()
    live = store / "chr8.000000.npz"
    live.write_bytes(b"seg")
    stale = store / ".manifest.json.tmp999"
    stale.write_bytes(b"junk")
    tio.replace_manifest(str(store / "manifest.json"),
                         {"shards": {"8": [0]}})
    tio.unlink(str(stale))  # debris: not referenced, no violation
    assert traced.violations() == []
    tio.unlink(str(live))
    assert _kinds(traced) == {"unlink-live-file"}


def test_dir_fsync_discharges_manifest_obligation(tmp_path, traced,
                                                  monkeypatch):
    monkeypatch.setenv("AVDB_FSYNC", "1")
    mpath = str(tmp_path / "manifest.json")
    tmp = mpath + ".t"
    with tio.open(tmp, "w") as f:
        f.write(json.dumps({"shards": {}}))
        tio.fsync(f)
    tio.replace(tmp, mpath)
    assert _kinds(traced) == {"manifest-replace-without-dir-fsync"}
    tio.fsync_dir(str(tmp_path))
    assert traced.violations() == []


def test_replace_manifest_helper_is_clean_under_full_durability(
        tmp_path, traced, monkeypatch):
    """The blessed helper discharges every obligation it creates —
    including the directory fsync the fixed writers used to miss."""
    monkeypatch.setenv("AVDB_FSYNC", "1")
    tio.replace_manifest(str(tmp_path / "manifest.json"), {"shards": {}})
    assert traced.violations() == []
    # pre-serialized bytes land byte-identical (the repl mirror's format)
    blob = b'{"shards": {}}\n'
    tio.replace_manifest(str(tmp_path / "manifest.json"), blob)
    assert traced.violations() == []
    assert open(str(tmp_path / "manifest.json"), "rb").read() == blob


def test_recorder_reset_and_report_shape(traced):
    rec = IoTraceRecorder()
    rec.note_write("/x/a")
    rec.note_rename("/x/a", "/x/serve-w0.wal")
    assert len(rec.violations()) == 1
    report = rec.report()
    assert set(report) == {"events", "violations", "dirty",
                          "pending_dir_fsync"}
    rec.reset()
    assert rec.report() == {"events": 0, "violations": [], "dirty": [],
                            "pending_dir_fsync": []}


# -- the real writers, traced (slowish: full store builds) -------------------


def test_store_build_flush_compact_fsck_traced_clean(tmp_path, traced,
                                                     monkeypatch):
    """save() + memtable flush + WAL + compaction + fsck repair under
    AVDB_IO_TRACE=1 AVDB_FSYNC=1: zero ordering violations."""
    monkeypatch.setenv("AVDB_FSYNC", "1")
    store_dir = str(tmp_path / "vdb")
    ts._build_store(store_dir)  # fragmented multi-segment save()s

    from annotatedvdb_tpu.store import VariantStore
    from annotatedvdb_tpu.store.memtable import Memtable
    from annotatedvdb_tpu.store.wal import WriteAheadLog

    store = VariantStore.load(store_dir)
    mem = Memtable(
        width=8, store_dir=store_dir,
        wal=WriteAheadLog(store_dir, "trace-w0", log=lambda m: None),
        log=lambda m: None,
    )
    mem.upsert(store, [{"code": 3, "pos": 77, "ref": "A", "alt": "G"}],
               durable=True)
    assert mem.flush()["status"] == "flushed"
    mem.wal.close(remove_if_empty=True)

    assert compact_store(store_dir)["status"] == "compacted"

    # plant crash debris; repair unlinks it and rewrites the manifest
    with open(os.path.join(store_dir, ".manifest.json.tmp42"), "w") as f:
        f.write("junk")
    report = fsck(store_dir, repair=True, log=lambda m: None)
    assert report["status"] == "repaired" and report["repairs"]

    assert traced.violations() == [], traced.report()


def test_replication_ship_bootstrap_promote_traced_clean(tmp_path, traced,
                                                         monkeypatch):
    """The full replica lifecycle traced: leader upserts, snapshot-cut
    bootstrap, WAL tail, promote (epoch commit).  Regression for the
    bootstrap-install and promote dir-fsync holes."""
    monkeypatch.setenv("AVDB_FSYNC", "1")
    leader = tr._Leader(str(tmp_path / "leader"))
    try:
        leader.upsert([{"id": "3:15:A:G"},
                       {"id": "3:25:AT:A", "ref_snp": 9}])
        fdir = str(tmp_path / "follower")
        tailer = repl.ReplicaTailer(fdir, leader.url, log=lambda m: None)
        tailer.bootstrap()
        assert tailer.sync_once()["applied"] == 1
        out = repl.promote(fdir, log=lambda m: None)
        assert out["status"] == "promoted" and out["rows"] == 2
    finally:
        leader.close()
    assert traced.violations() == [], traced.report()
