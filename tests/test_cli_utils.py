"""Bin-index reference generation, VCF export, per-chromosome split, and
chromosome-map parsing (reference ``generate_bin_index_references.py``,
``export_variant2vcf.py``, ``split_vcf_by_chr.py``,
``chromosome_map_parser.py``)."""

import gzip
import subprocess
import sys

import pytest

from annotatedvdb_tpu.cli.generate_bin_index_references import (
    emit_rows, read_chr_map,
)
from annotatedvdb_tpu.cli.split_vcf_by_chr import split_file
from annotatedvdb_tpu.io.chromosome_map import ChromosomeMap
from annotatedvdb_tpu.loaders import TpuVcfLoader
from annotatedvdb_tpu.store import AlgorithmLedger, VariantStore


def test_bin_index_reference_rows(tmp_path):
    """Rows must match the reference recursion: depth-first order, (]
    intervals clamped at sequence length, labels chrN.L1.Bk..."""
    out = tmp_path / "bins.tsv"
    chr_map = {"chr21": 48_129_895}
    with open(out, "w") as fh:
        n = emit_rows(chr_map, fh)
    rows = [line.split("\t") for line in out.read_text().splitlines()]
    assert len(rows) == n
    # level 0: whole chromosome
    assert rows[0] == ["chr21", "0", "1", "chr21", "(0,48129895]"]
    # first level-1 bin: 64Mb clamped to sequence length
    assert rows[1] == ["chr21", "1", "2", "chr21.L1.B1", "(0,48129895]"]
    # first level-2 bin: 32Mb
    assert rows[2][3] == "chr21.L1.B1.L2.B1"
    assert rows[2][4] == "(0,32000000]"
    # depth-first: the second level-2 bin appears only after the entire
    # subtree of the first (levels 3..13)
    paths = [r[3] for r in rows]
    i2 = paths.index("chr21.L1.B1.L2.B2")
    assert all(p.startswith("chr21.L1.B1.L2.B1") for p in paths[2:i2])
    # leaf size 15625: first leaf ends at 15625
    leaves = [r for r in rows if r[1] == "13"]
    assert leaves[0][4] == "(0,15625]"
    # every interval is (lower, upper] with lower < upper
    for r in rows:
        assert r[4].startswith("(") and r[4].endswith("]")
        lower, upper = r[4][1:-1].split(",")
        assert int(lower) < int(upper)


def test_bin_index_cli_and_chr_map(tmp_path):
    chr_map_file = tmp_path / "map.txt"
    chr_map_file.write_text("chr21\t48129895\nchr22\t51304566\n")
    assert read_chr_map(str(chr_map_file)) == {
        "chr21": 48129895, "chr22": 51304566,
    }
    out = tmp_path / "bins.tsv"
    res = subprocess.run(
        [sys.executable, "-m",
         "annotatedvdb_tpu.cli.generate_bin_index_references",
         "-m", str(chr_map_file), "-o", str(out)],
        capture_output=True, text=True, check=True,
    )
    lines = out.read_text().splitlines()
    assert lines[0].startswith("chr21\t0\t1\tchr21\t")
    # global_bin numbering continues across chromosomes
    first_chr22 = next(l for l in lines if l.startswith("chr22"))
    assert int(first_chr22.split("\t")[2]) > 1
    assert "generated" in res.stderr


BASE_VCF = """##fileformat=VCFv4.2
#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO
1\t100\trs11\tA\tG\t.\t.\t.
1\t200\t.\tC\tT\t.\t.\t.
1\t300\t.\tA\tR\t.\t.\t.
2\t100\t.\tT\tA\t.\t.\t.
"""


def build_store(tmp_path):
    store = VariantStore(width=49)
    ledger = AlgorithmLedger(str(tmp_path / "ledger.jsonl"))
    vcf = tmp_path / "base.vcf"
    vcf.write_text(BASE_VCF)
    TpuVcfLoader(store, ledger, log=lambda *a: None).load_file(str(vcf), commit=True)
    return store, ledger


def test_export_variant2vcf(tmp_path):
    store, _ = build_store(tmp_path)
    store_dir = tmp_path / "vdb"
    store.save(str(store_dir))
    out_dir = tmp_path / "export"
    res = subprocess.run(
        [sys.executable, "-m", "annotatedvdb_tpu.cli.export_variant2vcf",
         "--storeDir", str(store_dir), "--outputDir", str(out_dir)],
        capture_output=True, text=True, check=True,
    )
    chr1 = (out_dir / "1_1.vcf").read_text().splitlines()
    assert chr1[0].startswith("#CHRM")
    assert chr1[1].split("\t") == ["1", "100", "1:100:A:G:rs11", "A", "G",
                                   ".", ".", "."]
    assert chr1[2].split("\t")[2] == "1:200:C:T"
    assert len(chr1) == 3  # invalid R allele diverted
    invalid = (out_dir / "1_invalid.txt").read_text().splitlines()
    assert invalid == ["1:300:A:R"]
    assert (out_dir / "2_1.vcf").exists()


def test_export_file_sharding(tmp_path):
    store, _ = build_store(tmp_path)
    store_dir = tmp_path / "vdb"
    store.save(str(store_dir))
    out_dir = tmp_path / "export"
    subprocess.run(
        [sys.executable, "-m", "annotatedvdb_tpu.cli.export_variant2vcf",
         "--storeDir", str(store_dir), "--outputDir", str(out_dir),
         "--variantsPerFile", "1", "--chr", "1"],
        capture_output=True, text=True, check=True,
    )
    assert (out_dir / "1_1.vcf").exists() and (out_dir / "1_2.vcf").exists()
    assert not (out_dir / "2_1.vcf").exists()  # --chr filter


def test_shard_primary_key_digest(tmp_path):
    """Long-allele rows export their retained digest PK, not the literal."""
    store = VariantStore(width=8)
    ledger = AlgorithmLedger(str(tmp_path / "ledger.jsonl"))
    vcf = tmp_path / "long.vcf"
    vcf.write_text(
        "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\n"
        "1\t100\t.\tA\t" + "ACGT" * 15 + "\t.\t.\t.\n"
    )
    TpuVcfLoader(store, ledger, log=lambda *a: None).load_file(str(vcf), commit=True)
    shard = store.shard(1)
    pk = shard.primary_key(0)
    assert pk.startswith("1:100:") and "ACGTACGT" not in pk  # digest form


SPLIT_VCF = """##fileformat=VCFv4.2
#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO
NC_000001.10\t100\t.\tA\tG\t.\t.\t.
NC_000001.10\t200\t.\tC\tT\t.\t.\t.
NC_000023.10\t50\t.\tG\tA\t.\t.\t.
NC_999999.1\t10\t.\tT\tC\t.\t.\t.
"""


def test_split_vcf_by_chr(tmp_path):
    src = tmp_path / "all.vcf.gz"
    with gzip.open(src, "wt") as fh:
        fh.write(SPLIT_VCF)
    map_file = tmp_path / "map.tsv"
    map_file.write_text(
        "source_id\tchromosome\tchromosome_order_num\tlength\n"
        "NC_000001.10\tchr1\t1\t249250621\n"
        "NC_000023.10\tchrX\t23\t155270560\n"
    )
    cm = ChromosomeMap(str(map_file))
    counters = split_file(
        str(src), str(tmp_path / "out"), cm.chromosome_map(),
        log=lambda *a: None,
    )
    assert counters == {"line": 4, "unmapped": 1}
    chr1 = (tmp_path / "out" / "chr1.vcf").read_text().splitlines()
    assert len(chr1) == 3 and chr1[1].startswith("NC_000001.10\t100")
    chrx = (tmp_path / "out" / "chrX.vcf").read_text().splitlines()
    assert len(chrx) == 2
    # every standard chromosome gets a file, even if empty
    chr9 = (tmp_path / "out" / "chr9.vcf").read_text().splitlines()
    assert chr9 == ["#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO"]


def test_chromosome_map_parser(tmp_path):
    map_file = tmp_path / "map.tsv"
    map_file.write_text(
        "source_id\tchromosome\tchromosome_order_num\tlength\n"
        "NC_000001.10\tchr1\t1\t249250621\n"
        "NC_000024.9\tchrY\t24\t59373566\n"
    )
    cm = ChromosomeMap(str(map_file))
    assert cm.get("NC_000001.10") == "1"  # 'chr' stripped
    assert cm.get_sequence_id("1") == "NC_000001.10"
    assert cm.get_sequence_id("Y") == "NC_000024.9"
    assert cm.get_sequence_id("7") is None
    assert "NC_000024.9" in cm

    # headerless two-column variant
    plain = tmp_path / "plain.tsv"
    plain.write_text("NC_000001.10\t1\nNC_000024.9\tY\n")
    cm2 = ChromosomeMap(str(plain))
    assert cm2.get("NC_000024.9") == "Y"


def test_chromosome_map_tolerates_short_lines(tmp_path):
    path = tmp_path / "map.txt"
    path.write_text(
        "source_id\tchromosome\tchromosome_order_num\tlength\n"
        "NC_000001.10\tchr1\t1\t249250621\n"
        "# a comment line\n"
        "NC_000002.11\n"          # short line: only a source id
        "NC_000003.11\tchr3\t3\t198022430\n"
    )
    cmap = ChromosomeMap(str(path))
    assert cmap.chromosome_map() == {"NC_000001.10": "1", "NC_000003.11": "3"}


def test_export_rejects_unknown_chromosome(tmp_path):
    from annotatedvdb_tpu.cli import export_variant2vcf as cli
    store_dir = tmp_path / "vdb"
    VariantStore(width=16).save(str(store_dir))
    with pytest.raises(SystemExit) as err:
        cli.main(["--storeDir", str(store_dir),
                  "--outputDir", str(tmp_path / "out"), "--chr", "23q"])
    assert err.value.code == 2
