"""Fault-injection harness semantics + the robustness plumbing it drives:
deterministic arming/counting, bounded retry, pipeline stage-error
preservation, and the ledger/egress fault points."""

import errno
import os

import numpy as np
import pytest

from annotatedvdb_tpu.store import AlgorithmLedger, VariantStore
from annotatedvdb_tpu.utils import faults
from annotatedvdb_tpu.utils import retry as retry_mod
from annotatedvdb_tpu.utils.faults import InjectedFault
from annotatedvdb_tpu.utils.pipeline import BoundedStage
from annotatedvdb_tpu.utils.retry import with_backoff


@pytest.fixture(autouse=True)
def _disarm():
    yield
    faults.reset("")


# ---------------------------------------------------------------------------
# harness semantics


def test_unarmed_fire_is_noop():
    faults.reset("")
    for _ in range(3):
        faults.fire("store.save.pre_manifest")
    assert faults.fired() == {}


def test_nth_hit_fires_exactly_once():
    faults.reset("ingest.chunk:3:raise")
    faults.fire("ingest.chunk")
    faults.fire("ledger.append")  # different point: not counted
    faults.fire("ingest.chunk")
    with pytest.raises(InjectedFault):
        faults.fire("ingest.chunk")
    faults.fire("ingest.chunk")  # past nth: no-op again
    assert faults.fired() == {"ingest.chunk": 1}


def test_eio_action_raises_oserror():
    faults.reset("egress.flush:1:eio")
    with pytest.raises(OSError) as exc:
        faults.fire("egress.flush")
    assert exc.value.errno == errno.EIO


def test_bad_specs_rejected():
    for spec in ("nope", "ingest.chunk:x", "ingest.chunk:0",
                 "ingest.chunk:1:explode",
                 # prob mode: missing/garbage/out-of-range probabilities
                 "ingest.chunk:prob", "ingest.chunk:prob:x",
                 "ingest.chunk:prob:0", "ingest.chunk:prob:1.5",
                 # delay action: missing/garbage/negative milliseconds
                 "ingest.chunk:1:delay", "ingest.chunk:1:delay:x",
                 "ingest.chunk:1:delay:-5",
                 # trailing junk after a complete spec
                 "ingest.chunk:1:raise:junk"):
        with pytest.raises(ValueError):
            faults.reset(spec)
        faults.reset("")


def test_delay_action_sleeps_and_continues():
    """``delay:<ms>`` is injected latency, not an abort: the fire sleeps
    on the calling thread, counts as fired, and execution continues."""
    import time

    faults.reset("ingest.chunk:2:delay:50")
    t0 = time.perf_counter()
    faults.fire("ingest.chunk")  # hit 1: no-op
    assert time.perf_counter() - t0 < 0.04
    t0 = time.perf_counter()
    faults.fire("ingest.chunk")  # hit 2: the 50ms sleep, then continue
    assert time.perf_counter() - t0 >= 0.045
    assert faults.fired() == {"ingest.chunk": 1}
    faults.fire("ingest.chunk")  # nth mode: past the hit, no-op again
    assert faults.fired() == {"ingest.chunk": 1}


def test_prob_mode_fires_repeatedly_and_deterministically(monkeypatch):
    """``prob:<p>`` flips a seeded coin per pass: the same seed replays
    the exact injection sequence; a different AVDB_FAULT_SEED moves it."""
    def sequence():
        faults.reset("ingest.chunk:prob:0.5:eio")
        out = []
        for _ in range(64):
            try:
                faults.fire("ingest.chunk")
                out.append(0)
            except OSError:
                out.append(1)
        return out

    first = sequence()
    assert 0 < sum(first) < 64  # fires repeatedly, not always
    assert sequence() == first  # same seed => identical replay
    monkeypatch.setenv("AVDB_FAULT_SEED", "12345")
    moved = sequence()
    assert moved != first
    # replayable under the explicit seed too
    assert sequence() == moved


def test_prob_mode_with_delay_action():
    """The chaos harness's injected-latency shape: probabilistic delays
    keep counting per fire."""
    faults.reset("serve.batch:prob:1.0:delay:1")
    for _ in range(5):
        faults.fire("serve.batch")
    assert faults.fired() == {"serve.batch": 5}


def test_unknown_point_rejected_at_arm_time():
    """A typo'd point must fail the arm, not arm silently and never fire —
    and the error must name the known points so the fix is obvious."""
    with pytest.raises(ValueError) as exc:
        faults.reset("store.save.pre_manifst:1:kill")  # typo'd
    msg = str(exc.value)
    assert "unknown injection point" in msg
    for point in sorted(faults.POINTS):
        assert point in msg
    faults.reset("")
    # every registered point arms cleanly
    for point in faults.POINTS:
        faults.reset(f"{point}:1:raise")
    faults.reset("")


# ---------------------------------------------------------------------------
# bounded retry


def test_with_backoff_retries_transient_then_succeeds():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError(errno.EIO, "blip")
        return "ok"

    before = retry_mod.stats["retries"]
    assert with_backoff(flaky, attempts=3, base_delay=0.001) == "ok"
    assert calls["n"] == 3
    assert retry_mod.stats["retries"] - before == 2


def test_with_backoff_propagates_nontransient_immediately():
    calls = {"n": 0}

    def broken():
        calls["n"] += 1
        raise ValueError("data error, not transient")

    with pytest.raises(ValueError):
        with_backoff(broken, attempts=5, base_delay=0.001)
    assert calls["n"] == 1


def test_with_backoff_gives_up_after_attempts():
    def always():
        raise OSError(errno.EIO, "persistent")

    with pytest.raises(OSError):
        with_backoff(always, attempts=2, base_delay=0.001)


# ---------------------------------------------------------------------------
# pipeline: first in-flight stage error survives teardown (issue satellite)


def test_stage_error_reaches_consumer_and_is_recorded():
    def boom(x):
        raise RuntimeError("root cause")

    st = BoundedStage(iter([1]), fn=boom, depth=2)
    with pytest.raises(RuntimeError, match="root cause"):
        next(iter(st))
    assert isinstance(st.error, RuntimeError)
    st.close()


def test_stage_error_survives_close_without_consumption():
    """close() drains pending items; a drained _StageError envelope (or an
    error raised while the stop flag was set) must not vanish with them."""
    import time

    def boom(x):
        raise RuntimeError("dropped root cause")

    st = BoundedStage(iter([1]), fn=boom, depth=2)
    # give the stage thread a beat to fail and enqueue its envelope
    for _ in range(100):
        if st.error is not None or st.depth():
            break
        time.sleep(0.01)
    st.close()
    assert st.error is not None
    assert "dropped root cause" in str(st.error)


def test_producer_gone_with_error_raises_not_stopiteration():
    """A stage thread that died on an error whose envelope was lost must
    surface the error at the consumer, never silently truncate."""
    import queue as _q
    import time

    def src():
        yield 1
        raise RuntimeError("late failure")

    st = BoundedStage(src(), depth=1)
    it = iter(st)
    assert next(it) == 1
    # wait for the thread to die, then simulate the envelope having been
    # lost (drain the queue directly, bypassing __next__)
    for _ in range(200):
        if not st._thread.is_alive():
            break
        time.sleep(0.01)
    try:
        while True:
            st._q.get_nowait()
    except _q.Empty:
        pass
    with pytest.raises(RuntimeError, match="late failure"):
        next(it)


# ---------------------------------------------------------------------------
# ledger fault point


def test_ledger_append_raise_leaves_previous_records_intact(tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    ledger = AlgorithmLedger(path)
    a1 = ledger.begin("load", {"file": "f.vcf"}, commit=True)
    ledger.checkpoint(a1, "f.vcf", 100, {})
    faults.reset("ledger.append:1:raise")
    with pytest.raises(InjectedFault):
        ledger.checkpoint(a1, "f.vcf", 200, {})
    faults.reset("")
    # the raise fires BEFORE the write: the aborted checkpoint never landed,
    # earlier records are untouched
    reopened = AlgorithmLedger(path)
    assert reopened.last_checkpoint("f.vcf") == 100


# ---------------------------------------------------------------------------
# egress: injected EIO at the flush point rides the bounded retry


def test_egress_flush_eio_is_retried(tmp_path):
    from annotatedvdb_tpu.io.pg_egress import export_store

    store = VariantStore(width=8)
    store.shard(1).append(
        {"pos": np.asarray([10, 20], np.int32),
         "h": np.asarray([7, 8], np.uint32),
         "ref_len": np.full(2, 1, np.int32),
         "alt_len": np.full(2, 1, np.int32)},
        np.full((2, 8), 65, np.uint8), np.full((2, 8), 67, np.uint8),
    )
    out = str(tmp_path / "export")
    faults.reset("egress.flush:1:eio")
    before = retry_mod.stats["retries"]
    counts = export_store(store, out)
    assert counts == {"1": 2}
    assert retry_mod.stats["retries"] - before >= 1
    data = open(os.path.join(out, "data", "variant_chr1.copy")).read()
    assert data.count("\n") == 2
    # no torn half-written tmp left behind
    leftovers = [f for f in os.listdir(os.path.join(out, "data"))
                 if ".tmp" in f]
    assert leftovers == []


# ---------------------------------------------------------------------------
# ledger thread-safety (lock-discipline rule AVDB201 surfaced this): the
# async store writer checkpoints from its own thread while the main thread
# appends run/finish records


def test_ledger_concurrent_appends_are_serialized(tmp_path):
    import json
    import threading

    path = str(tmp_path / "ledger.jsonl")
    ledger = AlgorithmLedger(path)
    alg = ledger.begin("load", {"file": "f.vcf"}, commit=True)
    N = 200

    def checkpoints():
        for i in range(N):
            ledger.checkpoint(alg, "f.vcf", i + 1, {})

    def runs():
        for i in range(N):
            ledger.run({"script": "t", "i": i})

    threads = [threading.Thread(target=checkpoints),
               threading.Thread(target=runs)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    # every line parses (no interleaved/torn writes), every record landed
    with open(path) as f:
        lines = [json.loads(line) for line in f if line.strip()]
    assert len(lines) == 1 + 2 * N
    assert len(ledger.runs()) == N
    reopened = AlgorithmLedger(path)
    assert reopened.skipped_lines == 0
    assert reopened.last_checkpoint("f.vcf") == N


def test_gave_up_counts_retry_exhaustion_only():
    """A non-retryable error after an earlier transient blip is a data
    failure, not an exhausted retry — it must not inflate
    avdb_io_retries_exhausted_total."""
    calls = {"n": 0}

    def transient_then_data_error():
        calls["n"] += 1
        if calls["n"] == 1:
            raise OSError(errno.EIO, "blip")
        raise ValueError("data error")

    before = dict(retry_mod.stats)
    with pytest.raises(ValueError):
        with_backoff(transient_then_data_error, attempts=5,
                     base_delay=0.001)
    assert retry_mod.stats["retries"] - before["retries"] == 1
    assert retry_mod.stats["gave_up"] == before["gave_up"]

    def always_transient():
        raise OSError(errno.EIO, "persistent")

    before = dict(retry_mod.stats)
    with pytest.raises(OSError):
        with_backoff(always_transient, attempts=2, base_delay=0.001)
    assert retry_mod.stats["gave_up"] - before["gave_up"] == 1


def test_death_actions_are_subprocess_only_in_process():
    """Explicitly arming kill/torn_write at a non-worker point would
    SIGKILL the test process itself — reset() must reject it with an
    error that names the valid in-process actions; worker points (the
    chaos harness's /_chaos lever) and environment arming stay allowed."""
    for spec in ("ingest.chunk:1:kill", "store.save.pre_manifest:1:kill",
                 "wal.append:1:torn_write", "memtable.flush:1:kill"):
        with pytest.raises(ValueError) as exc:
            faults.reset(spec)
        msg = str(exc.value)
        assert "subprocess-only" in msg
        assert "raise, eio, delay" in msg
        assert faults.armed_point() is None  # nothing stayed armed
    # worker points: an in-process arm of a death action is the chaos
    # harness's intended lever (the supervisor absorbs the death)
    faults.reset("serve.accept:1:kill")
    assert faults.armed_point() == "serve.accept"
    faults.reset("")


def test_death_actions_allowed_via_environment(monkeypatch):
    """Environment arming IS the subprocess path: reset() with no
    explicit spec must accept a death action at any point (the armed
    process is the child that will die, not the harness)."""
    monkeypatch.setenv("AVDB_FAULT", "store.save.pre_manifest:1:kill")
    faults.reset()  # parses the environment: no rejection
    assert faults.armed_point() == "store.save.pre_manifest"
    monkeypatch.delenv("AVDB_FAULT")
    faults.reset("")
