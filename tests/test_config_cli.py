"""Typed config + umbrella CLI (``annotatedvdb_tpu.config`` / __main__)."""

import subprocess
import sys

import pytest

from annotatedvdb_tpu.config import LoadConfig, StoreConfig


def test_load_config_log_cadence_semantics():
    assert LoadConfig(commit_after=500).effective_log_after == 500
    assert LoadConfig(commit_after=500, log_after=50).effective_log_after == 50
    assert LoadConfig(commit_after=500, log_after=0).effective_log_after is None


def test_store_config_open_roundtrip(tmp_path):
    cfg = StoreConfig(str(tmp_path / "vdb"), width=16)
    store, ledger = cfg.open()
    assert store.width == 16 and store.n == 0
    store.save(cfg.store_dir)
    store2, _ = cfg.open()
    assert store2.width == 16
    with pytest.raises(FileNotFoundError):
        StoreConfig(str(tmp_path / "missing")).open(create=False)


def test_umbrella_cli_lists_and_dispatches(tmp_path):
    res = subprocess.run(
        [sys.executable, "-m", "annotatedvdb_tpu", "--help"],
        capture_output=True, text=True, timeout=120,
    )
    assert res.returncode == 0
    for cmd in ("load-vcf", "load-vep", "load-cadd", "undo", "export-vcf",
                "bin-references", "install-schema"):
        assert cmd in res.stdout
    # unknown command fails cleanly
    res = subprocess.run(
        [sys.executable, "-m", "annotatedvdb_tpu", "frobnicate"],
        capture_output=True, text=True, timeout=120,
    )
    assert res.returncode == 2 and "unknown command" in res.stderr
    # dispatch: a real load through the umbrella entry point
    vcf = tmp_path / "u.vcf"
    vcf.write_text(
        "##fileformat=VCFv4.2\n"
        "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\n"
        "1\t100\t.\tA\tG\t.\t.\t.\n"
    )
    res = subprocess.run(
        [sys.executable, "-m", "annotatedvdb_tpu", "load-vcf",
         "--fileName", str(vcf), "--storeDir", str(tmp_path / "vdb"),
         "--commit"],
        capture_output=True, text=True, timeout=600,
    )
    assert res.returncode == 0, res.stderr[-1500:]
    assert (tmp_path / "vdb" / "manifest.json").exists()
