"""Reference-genome subsystem: 2-bit packing, fetch, device validation,
GA4GH digests, loader integration (SeqRepo-equivalent, SURVEY §2.4)."""

import gzip

import numpy as np
import pytest

from annotatedvdb_tpu.genome import ReferenceGenome
from annotatedvdb_tpu.genome.refgenome import validate_ref_batch
from annotatedvdb_tpu.loaders import TpuVcfLoader
from annotatedvdb_tpu.ops.vrs import VrsDigestGenerator, sha512t24u
from annotatedvdb_tpu.store import AlgorithmLedger, VariantStore
from annotatedvdb_tpu.types import VariantBatch

CHR1 = "ACGTACGTACGTNNNACGTACGTGGGCCCTTTAAA" * 3   # 105 bases, Ns at 12-14
CHR2 = "TTTTGGGGCCCCAAAA" * 2                      # 32 bases
FASTA = f">chr1 test\n{CHR1[:50]}\n{CHR1[50:]}\n>2\n{CHR2}\n>chrUn_gl000220\nACGT\n"


@pytest.fixture(scope="module")
def genome(tmp_path_factory):
    p = tmp_path_factory.mktemp("g") / "ref.fa.gz"
    with gzip.open(p, "wt") as f:
        f.write(FASTA)
    return ReferenceGenome.from_fasta(str(p))


def test_build_and_fetch(genome):
    assert genome.length == {1: len(CHR1), 2: len(CHR2)}  # chrUn skipped
    assert genome.fetch("1", 0, 12) == CHR1[:12]
    assert genome.fetch("chr1", 10, 20) == CHR1[10:20]    # crosses the Ns
    assert "NNN" in genome.fetch(1, 0, len(CHR1))
    assert genome.fetch("2", 0, len(CHR2)) == CHR2
    # clamped at bounds
    assert genome.fetch("2", len(CHR2) - 4, len(CHR2) + 10) == CHR2[-4:]
    with pytest.raises(KeyError):
        genome.fetch("X", 0, 5)


def test_save_load_roundtrip(genome, tmp_path):
    genome.save(str(tmp_path / "g.npz"))
    back = ReferenceGenome.load(str(tmp_path / "g"))
    assert back.length == genome.length
    assert back.fetch("1", 0, len(CHR1)) == genome.fetch("1", 0, len(CHR1))


def test_sequence_digest_is_seqrepo_scheme(genome):
    want = sha512t24u(CHR1.encode("ascii"))
    assert genome.sequence_digest("1") == want
    lazy = genome.lazy_digests()
    assert "1" in lazy and "X" not in lazy
    assert lazy["1"] == want


def test_device_validation_matches_fetch(genome):
    variants = [
        ("1", 1, CHR1[0], "G"),               # valid SNV at pos 1
        ("1", 5, CHR1[4:9], "A"),             # valid 5bp ref
        ("1", 5, "TTTTT", "A"),               # wrong ref
        ("1", 13, "N", "A"),                  # genome N, stated N -> ok
        ("1", 13, "A", "G"),                  # genome N, stated A -> fail
        ("2", 30, CHR2[29:32], "T"),          # runs to the chromosome end
        ("2", 31, CHR2[30:] + "AA", "T"),     # overruns the chromosome
        ("X", 5, "A", "G"),                   # chromosome absent
        ("1", 3, CHR1[2:7].lower(), "a"),     # case-insensitive ref
    ]
    batch = VariantBatch.from_tuples(variants, width=16)
    ok = validate_ref_batch(genome, batch)
    assert list(ok) == [True, True, False, True, False, True, False, False, True]


def test_over_width_rows_validate_on_host(genome):
    long_ref = CHR1[20:60]                    # 40bp > width 16
    variants = [("1", 21, long_ref, "A"), ("1", 21, "G" * 40, "A")]
    batch = VariantBatch.from_tuples(variants, width=16)
    ok = validate_ref_batch(genome, batch, refs=[v[2] for v in variants])
    assert list(ok) == [True, False]


def test_vrs_digests_canonical_with_genome(genome):
    gen = VrsDigestGenerator(
        sequence_digests=genome.lazy_digests(),
        reference_bases=genome.reference_bases,
    )
    assert gen.sequence_id("1") == "SQ." + genome.sequence_digest(1)
    pk = gen.compute_identifier("1", 5, CHR1[4:9], "A")
    assert len(pk) == 32  # base64url of 24 bytes
    with pytest.raises(ValueError, match="reference mismatch"):
        gen.compute_identifier("1", 5, "TTTTT", "A")


def test_loader_counts_ref_mismatches(genome, tmp_path):
    vcf = tmp_path / "t.vcf"
    vcf.write_text(
        "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\n"
        f"1\t1\t.\t{CHR1[0]}\tG\t.\t.\t.\n"
        f"1\t5\t.\tTTTTT\tA\t.\t.\t.\n"
    )
    store = VariantStore(width=16)
    ledger = AlgorithmLedger(str(tmp_path / "ledger.jsonl"))
    msgs = []
    loader = TpuVcfLoader(store, ledger, genome=genome, log=msgs.append)
    counters = loader.load_file(str(vcf), commit=True)
    assert counters["ref_mismatch"] == 1
    assert counters["variant"] == 2   # mismatches are counted, not dropped
    assert any("ref-allele mismatches" in m for m in msgs)


def test_digest_pk_allele_swap_and_unvalidated_fallback(genome, tmp_path):
    """A >50bp variant with a mismatched ref must not abort the load: the
    PK falls back to the swapped orientation, then to an unvalidated
    digest (``vcf_variant_loader.py:234-256`` behavior)."""
    good_long = CHR1[:30]
    vcf = tmp_path / "t.vcf"
    vcf.write_text(
        "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\n"
        f"1\t1\t.\t{good_long}\t{'G' * 30}\t.\t.\t.\n"     # valid long ref
        f"1\t1\t.\t{'G' * 30}\t{good_long}\t.\t.\t.\n"     # swap validates
        f"1\t2\t.\t{'G' * 30}\t{'C' * 30}\t.\t.\t.\n"      # nothing validates
    )
    store = VariantStore(width=16)
    ledger = AlgorithmLedger(str(tmp_path / "ledger.jsonl"))
    loader = TpuVcfLoader(store, ledger, genome=genome, log=lambda *a: None)
    counters = loader.load_file(str(vcf), commit=True)
    assert counters["variant"] == 3            # none aborted
    assert counters["ref_mismatch"] == 2       # rows 2 and 3
    shard = store.shards[1]
    assert sum(pk is not None for pk in shard.digest_pk) == 3


def test_lossy_chromosome_digests_not_canonical(tmp_path):
    p = tmp_path / "iupac.fa"
    p.write_text(">1\nACGTRYACGTNNAC\n>2\nACGTACGT\n")   # chr1 has R/Y codes
    g = ReferenceGenome.from_fasta(str(p))
    assert g.lossy[1] is True and g.lossy[2] is False
    lazy = g.lazy_digests()
    assert "1" not in lazy and "2" in lazy
    gen = VrsDigestGenerator(sequence_digests=lazy)
    assert gen.sequence_id("1").startswith("SQF.")   # non-canonical fallback
    assert gen.sequence_id("2").startswith("SQ.")
    # lossy flag survives persistence
    g.save(str(tmp_path / "g.npz"))
    assert ReferenceGenome.load(str(tmp_path / "g.npz")).lossy == g.lossy


def test_streamed_digest_matches_one_shot(genome):
    # module-scope genome caches digests; use a fresh instance
    import gzip as _gzip
    from annotatedvdb_tpu.ops.vrs import sha512t24u as _d
    assert genome.sequence_digest(2) == _d(CHR2.encode())
