"""Unit suite for the fused analytics kernels (``ops/stats``) and the
engine's stats path.

Every kernel answer is checked against a brute-force Python oracle (per
interval: scan the rows, filter the missing sentinel, sum/bucket in
plain ints), and the device kernel against its registered numpy twin
byte-for-byte — ``ops.stats.stats_panel_kernel_jit`` vs
``ops.stats.stats_panel_host`` and ``ops.stats.windowed_stats_kernel_jit``
vs ``ops.stats.windowed_stats_host`` (``assert_array_equal``, never
allclose: the AVDB9xx twin contract).  The engine half covers the cached
feature columns (decode-once), the filter rewire's byte parity against
the scalar ``_passes`` definition, memtable-overlay rows, and ``doctor
profile``.
"""

from __future__ import annotations

import json
import math

import numpy as np
import pytest
from numpy.testing import assert_array_equal

from annotatedvdb_tpu.loaders.lookup import identity_hashes
from annotatedvdb_tpu.ops import TWINS
from annotatedvdb_tpu.ops import stats as st
from annotatedvdb_tpu.store import VariantStore
from annotatedvdb_tpu.types import encode_allele_array


def _random_case(seed, n_rows, n_queries, span=400_000):
    rng = np.random.default_rng(seed)
    pos = np.sort(rng.integers(1, 5_000_000, n_rows).astype(np.int32))
    af = rng.integers(-1, st.AF_SCALE + 1, n_rows).astype(np.int32)
    cadd = rng.integers(-1, 100_001, n_rows).astype(np.int32)
    rank = rng.integers(-1, st.RANK_BUCKETS + 8, n_rows).astype(np.int32)
    starts = rng.integers(1, 5_000_000, n_queries).astype(np.int64)
    ends = starts + rng.integers(0, span, n_queries)
    return pos, af, cadd, rank, starts, ends


def _oracle_interval(pos, values, s, e, edges=None):
    """(present, exact_sum, hist|None) for one interval by linear scan."""
    sel = [v for p, v in zip(pos.tolist(), values.tolist())
           if s <= p <= e and v >= 0]
    hist = None
    if edges is not None:
        hist = [0] * (len(edges) - 1)
        for v in sel:
            b = int(np.searchsorted(edges, v, side="right")) - 1
            hist[min(max(b, 0), len(edges) - 2)] += 1
    return len(sel), sum(sel), hist


# -- kernel vs twin vs oracle ------------------------------------------------


@pytest.mark.parametrize("n_rows,n_queries", [
    (0, 5), (1, 3), (64, 17), (1000, 65), (4096, 9),
])
def test_panel_kernel_twin_byte_exact(n_rows, n_queries):
    """stats_panel (device, via stats_panel_kernel_jit) and
    stats_panel_host answer byte-identically on random columns."""
    pos, af, cadd, rank, starts, ends = _random_case(
        2209_8600 + n_rows, n_rows, n_queries
    )
    dev = st.stats_panel(pos, af, cadd, rank, starts, ends)
    host = st.stats_panel_host(pos, af, cadd, rank, starts, ends)
    assert len(dev) == len(host) == 7
    for d, h in zip(dev, host):
        assert_array_equal(np.asarray(d), np.asarray(h))


def test_panel_matches_brute_oracle():
    pos, af, cadd, rank, starts, ends = _random_case(99, 777, 29)
    lo, hi, af_l, af_h, c_l, c_h, rk = st.stats_panel_host(
        pos, af, cadd, rank, starts, ends
    )
    af_sums = st.lanes_to_sums(af_l)
    c_sums = st.lanes_to_sums(c_l)
    for i, (s, e) in enumerate(zip(starts.tolist(), ends.tolist())):
        count = sum(1 for p in pos.tolist() if s <= p <= e)
        assert int(hi[i] - lo[i]) == count
        p_af, s_af, h_af = _oracle_interval(pos, af, s, e, st.AF_EDGES_FP)
        assert int(np.asarray(af_h[i]).sum()) == p_af
        assert int(af_sums[i]) == s_af
        assert np.asarray(af_h[i]).tolist() == h_af
        p_c, s_c, h_c = _oracle_interval(pos, cadd, s, e, st.CADD_EDGES_FP)
        assert int(np.asarray(c_h[i]).sum()) == p_c
        assert int(c_sums[i]) == s_c
        assert np.asarray(c_h[i]).tolist() == h_c
        # rank rollup: clamped bucket counts
        want = [0] * st.RANK_BUCKETS
        for p, r in zip(pos.tolist(), rank.tolist()):
            if s <= p <= e and r >= 0:
                want[min(r, st.RANK_BUCKETS - 1)] += 1
        assert np.asarray(rk[i]).tolist() == want


@pytest.mark.parametrize("windows", [1, 3, 16])
def test_windowed_kernel_twin_byte_exact(windows):
    """windowed_stats (device, via windowed_stats_kernel_jit) and
    windowed_stats_host answer byte-identically."""
    pos, _af, cadd, _rank, starts, ends = _random_case(5, 513, 21)
    dev = st.windowed_stats(pos, cadd, starts, ends, windows)
    host = st.windowed_stats_host(pos, cadd, starts, ends, windows)
    for d, h in zip(dev, host):
        assert_array_equal(np.asarray(d), np.asarray(h))


def test_windowed_tiles_the_interval_exactly():
    """Windows partition [start, end]: per-window counts sum to the
    interval's row count and boundaries never double-count."""
    pos, _af, cadd, _rank, starts, ends = _random_case(11, 900, 40)
    for w in (1, 4, 7):
        counts, present, lanes = st.windowed_stats_host(
            pos, cadd, starts, ends, w
        )
        lo = np.searchsorted(pos, np.clip(starts, 0, None), side="left")
        hi = np.searchsorted(pos, ends, side="right")
        assert_array_equal(counts.sum(axis=1), (hi - lo).astype(np.int32))
        sums = st.lanes_to_sums(lanes)
        for i, (s, e) in enumerate(zip(starts.tolist(), ends.tolist())):
            p, total, _h = _oracle_interval(pos, cadd, s, e)
            assert int(present[i].sum()) == p
            assert int(sums[i].sum()) == total


def test_empty_intervals_and_all_missing():
    pos = np.asarray([100, 200, 300], np.int32)
    missing = np.full(3, st.STATS_MISSING, np.int32)
    lo, hi, af_l, af_h, c_l, c_h, rk = st.stats_panel_host(
        pos, missing, missing, missing, [1, 150, 400], [50, 250, 500]
    )
    assert (hi - lo).tolist() == [0, 1, 0]
    assert int(np.asarray(af_h).sum()) == 0
    assert int(np.asarray(c_h).sum()) == 0
    assert int(np.asarray(rk).sum()) == 0
    summary = st.interval_summary(1, af_l[1], af_h[1], c_l[1], c_h[1], rk[1])
    assert summary["count"] == 1
    assert summary["af"] == {"present": 0, "mean": None,
                             "spectrum": [0] * (len(st.AF_EDGES_FP) - 1)}
    assert summary["cadd"]["present"] == 0
    assert summary["cadd"]["quantiles"] == {"p50": None, "p90": None,
                                            "p99": None}
    assert summary["conseq"] == {"present": 0, "ranks": {}}


def test_registry_covers_the_stats_kernels():
    assert TWINS["ops.stats.stats_panel_kernel_jit"] == \
        "ops.stats.stats_panel_host"
    assert TWINS["ops.stats.windowed_stats_kernel_jit"] == \
        "ops.stats.windowed_stats_host"


# -- derivation helpers ------------------------------------------------------


def test_quantiles_from_histogram():
    hist = np.asarray([5, 0, 5], np.int64)
    edges = np.asarray([0, 10, 20, 30], np.int64)
    q = st.hist_quantiles(hist, edges, 1, qs=(50, 100))
    # target rank 5 lands exactly at the first bin's last row
    assert q["p50"] == 10.0
    assert q["p100"] == 30.0
    assert st.hist_quantiles(np.zeros(3, np.int64), edges, 1)["p50"] is None


def test_feature_values_decode_rules():
    nan = float("nan")
    # plain numerics decode; bools/strings/missing do not
    cf, rf, af, cfp, ri = st.feature_values(
        {"CADD_phred": 12.5}, {"g": {"af": 0.25}, "x": 0.5}, {"rank": 3}
    )
    assert cf == 12.5 and cfp == 12_500
    assert af == 500_000  # cohort-max: the larger leaf wins
    assert rf == 3.0 and ri == 3
    cf, rf, af, cfp, ri = st.feature_values(
        {"CADD_phred": True}, {"g": "high"}, {"rank": "7"}
    )
    assert math.isnan(cf) and math.isnan(rf)
    assert af == st.STATS_MISSING and cfp == st.STATS_MISSING \
        and ri == st.STATS_MISSING
    # RawJson duck-type: parses fresh, never caches onto the instance
    class Raw:
        def __init__(self, text):
            self.text = text
    cf, _rf, af, cfp, _ri = st.feature_values(
        Raw('{"CADD_phred": 3.25}'), Raw('{"TOPMED": {"af": 1e-4}}'), None
    )
    assert cf == 3.25 and cfp == 3250 and af == 100
    # out-of-range values clamp into the fixed-point domain
    cf, _rf, af, cfp, _ri = st.feature_values(
        {"CADD_phred": -4.0}, {"af": 7.5}, {"rank": -2}
    )
    assert cf == -4.0 and cfp == 0  # filter sees the raw value
    assert af == st.AF_SCALE  # AF clamps to [0, 1]


# -- engine: feature columns, stats_serve, overlay ---------------------------


def _annotated_store(n=64, width=8):
    store = VariantStore(width=width)
    refs = ["A", "C", "G", "T"] * (n // 4)
    alts = ["G", "T", "A", "C"] * (n // 4)
    ref, ref_len = encode_allele_array(refs, width)
    alt, alt_len = encode_allele_array(alts, width)
    h = identity_hashes(width, ref, alt, ref_len, alt_len, refs, alts)
    pos = np.arange(1000, 1000 + 97 * n, 97, dtype=np.int32)[:n]
    store.shard(8).append(
        {"pos": pos, "h": h, "ref_len": ref_len, "alt_len": alt_len},
        ref, alt,
        annotations={
            "cadd_scores": [
                {"CADD_phred": float(i % 40)} if i % 2 else None
                for i in range(n)
            ],
            "allele_frequencies": [
                {"gnomad": {"af": (i % 100) / 100.0}} if i % 3 else None
                for i in range(n)
            ],
            "adsp_most_severe_consequence": [
                {"rank": i % 7} if i % 4 else None for i in range(n)
            ],
        },
    )
    return store, pos


def test_engine_stats_matches_brute_reference():
    from annotatedvdb_tpu.serve.engine import QueryEngine
    from annotatedvdb_tpu.serve.snapshot import StaticSnapshots

    store, pos = _annotated_store()
    engine = QueryEngine(StaticSnapshots(store), region_cache_size=0,
                         stats_device_min=0)
    specs = ["8:1000-3000", "8:2500-2500", "8:1-999", "7:5-10"]
    result = engine.stats_serve(specs, windows=4)
    doc = json.loads(result.assemble())
    assert doc["n"] == 4 and doc["metrics"] == ["af", "cadd", "conseq"]
    shard = store.shards[8]
    for entry, spec in zip(doc["results"], specs):
        assert entry["region"] == spec
        code_s, rng = spec.split(":")
        s, e = (int(x) for x in rng.split("-"))
        if code_s != "8":
            assert entry["count"] == 0
            continue
        rows = [i for i, p in enumerate(pos.tolist()) if s <= p <= e]
        assert entry["count"] == len(rows)
        phreds = [
            shard.annotations["cadd_scores"][i]["CADD_phred"]
            for i in rows if shard.annotations["cadd_scores"][i]
        ]
        assert entry["cadd"]["present"] == len(phreds)
        if phreds:
            want = round(
                sum(int(round(p * st.CADD_SCALE)) for p in phreds)
                / (len(phreds) * st.CADD_SCALE), 9)
            assert entry["cadd"]["mean"] == want
        assert sum(entry["windows"]["counts"]) == len(rows)


def test_engine_stats_device_host_and_forced_twin_identical():
    from annotatedvdb_tpu.serve.engine import QueryEngine
    from annotatedvdb_tpu.serve.snapshot import StaticSnapshots

    store, _pos = _annotated_store()
    engine = QueryEngine(StaticSnapshots(store), region_cache_size=0,
                         stats_device_min=0)
    specs = [f"8:{1000 + 13 * i}-{1500 + 13 * i}" for i in range(40)]
    via_device = engine.stats_serve(specs, windows=3).assemble()
    via_host = engine.stats_serve(specs, windows=3,
                                  host_only=True).assemble()
    assert via_device == via_host


def test_engine_stats_covers_memtable_overlay_rows():
    """Upserted rows (memtable overlay segments) join the analytics the
    moment they are visible — first-wins with the stored rows, exactly
    like every other read path."""
    from annotatedvdb_tpu.serve.engine import QueryEngine
    from annotatedvdb_tpu.serve.snapshot import StaticSnapshots
    from annotatedvdb_tpu.serve.snapshot import MemtableSnapshots
    from annotatedvdb_tpu.store.memtable import Memtable

    store, _pos = _annotated_store(n=16)
    base = StaticSnapshots(store)
    memtable = Memtable(width=store.width)
    provider = MemtableSnapshots(base, memtable)
    engine = QueryEngine(provider, region_cache_size=0, stats_device_min=0)
    spec = "8:900000-990000"  # far above the stored rows
    before = json.loads(engine.stats_serve([spec]).assemble())
    assert before["results"][0]["count"] == 0
    memtable.upsert(store, [{
        "code": 8, "pos": 900_500, "ref": "A", "alt": "G",
        "ref_snp": None,
        "ann": {"cadd_scores": {"CADD_phred": 33.0}},
    }])
    after = json.loads(engine.stats_serve([spec]).assemble())
    assert after["generation"] > before["generation"]
    entry = after["results"][0]
    assert entry["count"] == 1
    assert entry["cadd"]["present"] == 1
    assert entry["cadd"]["mean"] == 33.0


def test_feature_columns_cached_per_generation():
    """The sidecar decodes ONCE per (generation, chromosome): repeated
    stats/filter calls reuse the cached columns."""
    from annotatedvdb_tpu.serve.engine import QueryEngine
    from annotatedvdb_tpu.serve.snapshot import StaticSnapshots

    store, _pos = _annotated_store()
    engine = QueryEngine(StaticSnapshots(store), region_cache_size=0)
    calls = {"n": 0}
    real = st.feature_values

    def counting(*a):
        calls["n"] += 1
        return real(*a)

    import annotatedvdb_tpu.serve.engine as engine_mod

    orig = engine_mod.stats_ops.feature_values
    engine_mod.stats_ops.feature_values = counting
    try:
        engine.stats_serve(["8:1000-2000"])
        first = calls["n"]
        assert first == store.n  # one decode per row, once
        engine.stats_serve(["8:1000-9000"])
        engine.region("8:1000-9000", min_cadd=5.0)
        assert calls["n"] == first  # cache hit: zero further decodes
    finally:
        engine_mod.stats_ops.feature_values = orig


# -- the filter rewire: byte parity with the scalar definition ---------------


def _tricky_filter_store(width=8):
    """Annotation shapes that exercise every _passes branch: missing
    column values, non-dict values, bool/str 'numbers', int vs float."""
    store = VariantStore(width=width)
    n = 12
    refs = ["A"] * n
    alts = ["G"] * n
    ref, ref_len = encode_allele_array(refs, width)
    alt, alt_len = encode_allele_array(alts, width)
    h = identity_hashes(width, ref, alt, ref_len, alt_len, refs, alts)
    pos = np.arange(100, 100 + 10 * n, 10, dtype=np.int32)
    cadd = [None, {"CADD_phred": 5}, {"CADD_phred": 5.0001},
            {"CADD_phred": True}, {"CADD_phred": "9"}, {"other": 1},
            {"CADD_phred": 4.9999}, {"CADD_phred": 0}, None,
            {"CADD_phred": 40}, {"CADD_phred": -1.5}, {"CADD_phred": 5}]
    ms = [{"rank": 2}, None, {"rank": 7}, {"rank": 2.5}, {"rank": False},
          {"rank": 0}, {"norank": 3}, {"rank": 3}, {"rank": 1},
          {"rank": 9}, {"rank": 2}, None]
    store.shard(8).append(
        {"pos": pos, "h": h, "ref_len": ref_len, "alt_len": alt_len},
        ref, alt,
        annotations={"cadd_scores": cadd,
                     "adsp_most_severe_consequence": ms},
    )
    return store


@pytest.mark.parametrize("min_cadd,max_rank", [
    (5.0, None), (None, 2), (5.0, 2), (0.0, 0), (4.9999, 7),
])
def test_filtered_region_bytes_unchanged(min_cadd, max_rank):
    """The vectorized feature-column filter path renders byte-identical
    envelopes to the scalar per-row ``_passes`` reference — the
    regression pin for the sidecar re-parse hot-spot fix."""
    from annotatedvdb_tpu.serve.engine import (
        QueryEngine,
        RegionPage,
        _region_bin,
        closed_form_path,
    )
    from annotatedvdb_tpu.serve.snapshot import StaticSnapshots

    store = _tricky_filter_store()
    engine = QueryEngine(StaticSnapshots(store), region_cache_size=0)
    got = engine.region("8:1-100000", min_cadd=min_cadd,
                        max_conseq_rank=max_rank)
    # reference: the scalar definition over the brute-force row walk
    shard = store.shards[8]
    kept = [
        (si, j) for si, j in engine._region_rows(shard, 1, 100_000)
        if QueryEngine._passes(shard.segments[si], j, min_cadd, max_rank)
    ]
    level, leaf = _region_bin(1, 100_000)
    want = RegionPage(
        shard, "8", level, closed_form_path("8", level, leaf),
        len(kept), 1, kept, "8:1-100000", None, paged=False,
    ).assemble()
    assert got == want
    # the cursor-paged walk rides the same filter path
    paged = engine.region("8:1-100000", min_cadd=min_cadd,
                          max_conseq_rank=max_rank, limit=3, cursor="")
    doc = json.loads(paged)
    assert doc["count"] == len(kept)
    assert doc["returned"] == min(3, len(kept))


def test_batch_regions_filter_parity_after_rewire():
    from annotatedvdb_tpu.serve.engine import QueryEngine
    from annotatedvdb_tpu.serve.snapshot import StaticSnapshots

    store = _tricky_filter_store()
    engine = QueryEngine(StaticSnapshots(store), region_cache_size=0)
    specs = ["8:1-100000", "8:100-150", "8:160-220"]
    singles = [engine.region(s, min_cadd=5.0, max_conseq_rank=7)
               for s in specs]
    batch = engine.regions_serve(specs, min_cadd=5.0, max_conseq_rank=7)
    assert [p.assemble() for p in batch.pages] == singles


# -- doctor profile ----------------------------------------------------------


def test_doctor_profile_cli_matches_stats_serve(tmp_path):
    """The offline whole-store profile renders the SAME summary shapes
    — over the SAME first-wins-deduplicated row view — the serving
    stats path computes: the chunk-streamed accumulation must agree
    exactly with one full-span panel, including across a planted
    shadowed duplicate (which must count ONCE, with the older row's
    annotation values)."""
    from annotatedvdb_tpu.cli.doctor import main
    from annotatedvdb_tpu.serve.engine import QueryEngine
    from annotatedvdb_tpu.serve.snapshot import StaticSnapshots
    from annotatedvdb_tpu.store.variant_store import Segment

    store, _pos = _annotated_store()
    # plant a shadowed duplicate of the first row in a NEWER segment
    # with a wildly different CADD value: first-wins must hide it from
    # the profile exactly as it hides it from serving
    shard = store.shards[8]
    width = store.width
    ref, ref_len = encode_allele_array(["A"], width)
    alt, alt_len = encode_allele_array(["G"], width)
    h = identity_hashes(width, ref, alt, ref_len, alt_len, ["A"], ["G"])
    shard.append_segment(Segment.build(
        {"pos": np.asarray([1000], np.int32), "h": h,
         "ref_len": ref_len, "alt_len": alt_len},
        ref, alt,
        annotations={"cadd_scores": [{"CADD_phred": 9999.0}]},
    ))
    shard._starts_cache = None
    store_dir = str(tmp_path / "profstore")
    store.save(store_dir)
    out_path = str(tmp_path / "report.json")
    rc = main(["profile", "--storeDir", store_dir, "--out", out_path,
               "--chunkRows", "13"])
    assert rc == 0
    with open(out_path) as f:
        report = json.load(f)
    assert report["rows"] == store.n  # stored rows, duplicate included
    group = report["groups"]["8"]
    assert group["segments"] >= 1 and group["read_amp"] == group["segments"]
    # the shadowed duplicate counted ONCE (and its 9999 phred never
    # reached any histogram — the older row's value won)
    assert group["count"] == store.n - 1
    # cross-check: a serving stats panel over the whole chromosome span
    # must report the identical aggregation (same decode, same dedup,
    # same shapes)
    engine = QueryEngine(StaticSnapshots(store), region_cache_size=0)
    entry = json.loads(
        engine.stats_serve(["8:1-64000000"]).assemble()
    )["results"][0]
    for key in ("count", "af", "cadd", "conseq"):
        assert group[key] == entry[key], key
    assert report["totals"]["count"] == store.n - 1
    assert report["bins"] == st.edges_payload()


def test_doctor_profile_cli_unreadable_store_exits_2(tmp_path, capsys):
    from annotatedvdb_tpu.cli.doctor import main

    rc = main(["profile", "--storeDir", str(tmp_path / "missing")])
    assert rc == 2
    assert "doctor profile" in capsys.readouterr().err


def test_stats_device_copies_join_the_device_byte_ledger():
    """The feature columns' retained HBM copies are accounted against
    INDEX_DEVICE_BYTES exactly like the interval index's position array
    — and a ledger eviction (or a failed kernel) actually drops them."""
    from annotatedvdb_tpu.serve.engine import QueryEngine
    from annotatedvdb_tpu.serve.snapshot import StaticSnapshots

    store, _pos = _annotated_store()
    engine = QueryEngine(StaticSnapshots(store), region_cache_size=0,
                         stats_device_min=0)
    specs = [f"8:{1000 + 7 * i}-{2000 + 7 * i}" for i in range(4)]
    engine.stats_serve(specs)
    snap = engine.snapshots.current()
    feats = engine._stats_cache[(snap.generation, 8)]
    assert feats.device_bytes() > 0
    ledgered = {id(obj) for obj, _b in engine._index_device.values()}
    assert id(feats) in ledgered
    total = sum(b for _o, b in engine._index_device.values())
    assert total >= feats.device_bytes()
    # a failed kernel drops BOTH the device copy and its ledger entry
    def boom(index, f, starts, ends):
        raise RuntimeError("injected")

    engine._device_stats = boom
    engine.stats_serve(specs)  # host fallback, byte-identical
    assert feats.device_bytes() == 0
    assert id(feats) not in {
        id(obj) for obj, _b in engine._index_device.values()
    }
