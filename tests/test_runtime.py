"""Platform-pin robustness: probe retries, cached-fallback override, and
probe-detail recording (VERDICT r3 #1 — the official bench record must show
a TPU backend or say exactly why not, inside the JSON).

All probes are mocked: nothing here touches a real accelerator tunnel."""

import pytest

from annotatedvdb_tpu.utils import runtime


@pytest.fixture(autouse=True)
def isolated_marker(tmp_path, monkeypatch):
    """Every test gets its own tunnel-down marker file: a probe failure in
    one test must not short-circuit probes in the next (or leave state in
    the real tempdir for a later bench run)."""
    monkeypatch.setenv("AVDB_TPU_MARKER", str(tmp_path / "marker.json"))


@pytest.fixture
def clean_pin(monkeypatch):
    """Isolate the pin cache env vars (conftest pins AVDB_JAX_PLATFORM=cpu
    for every other test — these tests manage it explicitly)."""
    monkeypatch.delenv("AVDB_JAX_PLATFORM", raising=False)
    monkeypatch.delenv("AVDB_JAX_PLATFORM_SOURCE", raising=False)
    yield monkeypatch


def _sequence_probe(monkeypatch, outcomes):
    """Replace the subprocess probe with a canned outcome sequence."""
    calls = []

    def fake(timeout):
        calls.append(timeout)
        return outcomes[min(len(calls), len(outcomes)) - 1]

    monkeypatch.setattr(runtime, "_probe_once", fake)
    return calls


def test_probe_retries_until_success(monkeypatch):
    calls = _sequence_probe(
        monkeypatch,
        [(None, "probe hung past 1s"), (None, "probe rc=1: boom"), ("tpu", None)],
    )
    platform = runtime.probe_accelerator(timeout=1, attempts=3, backoff=0)
    assert platform == "tpu"
    assert len(calls) == 3
    rec = runtime.LAST_PROBE.as_dict()
    assert rec["platform"] == "tpu"
    assert rec["attempts"] == 3
    assert len(rec["errors"]) == 2
    assert "hung" in rec["errors"][0]


def test_probe_records_every_failure(monkeypatch):
    _sequence_probe(monkeypatch, [(None, "probe hung past 1s")])
    assert runtime.probe_accelerator(timeout=1, attempts=3, backoff=0) is None
    rec = runtime.LAST_PROBE.as_dict()
    assert rec["platform"] is None
    assert rec["attempts"] == 3
    assert len(rec["errors"]) == 3


def test_pin_reprobes_cached_fallback(clean_pin, monkeypatch):
    # a prior pin_platform probe failed and cached cpu ...
    monkeypatch.setenv("AVDB_JAX_PLATFORM", "cpu")
    monkeypatch.setenv("AVDB_JAX_PLATFORM_SOURCE", "probe")
    calls = _sequence_probe(monkeypatch, [("axon", None)])
    # ... the bench ignores that cache and probes fresh
    choice = runtime.pin_platform(
        "auto", timeout=1, attempts=3, ignore_cached_fallback=True
    )
    assert choice == "axon"
    assert len(calls) == 1
    import os

    assert os.environ["AVDB_JAX_PLATFORM"] == "axon"
    assert os.environ["AVDB_JAX_PLATFORM_SOURCE"] == "probe"


def test_pin_honors_user_explicit_cpu(clean_pin, monkeypatch):
    # the user exported AVDB_JAX_PLATFORM=cpu themselves (no SOURCE marker):
    # never re-probed, even with ignore_cached_fallback
    monkeypatch.setenv("AVDB_JAX_PLATFORM", "cpu")
    calls = _sequence_probe(monkeypatch, [("axon", None)])
    choice = runtime.pin_platform(
        "auto", timeout=1, attempts=3, ignore_cached_fallback=True
    )
    assert choice == "cpu"
    assert calls == []


def test_pin_falls_back_to_cpu_and_marks_source(clean_pin, monkeypatch):
    _sequence_probe(monkeypatch, [(None, "probe rc=1: tunnel down")])
    choice = runtime.pin_platform("auto", timeout=1, attempts=2)
    assert choice == "cpu"
    import os

    assert os.environ["AVDB_JAX_PLATFORM"] == "cpu"
    # marked as probe-derived so a later bench may re-probe it
    assert os.environ["AVDB_JAX_PLATFORM_SOURCE"] == "probe"
    assert runtime.LAST_PROBE.attempts == 2


def test_down_marker_short_circuits_next_probe(monkeypatch):
    """One concluded tunnel-down probe writes the marker; later probes in
    the round return in ms instead of re-eating attempts x timeout
    (VERDICT r5 weak #6: the wedged probe cost 290s of every bench run)."""
    calls = _sequence_probe(monkeypatch, [(None, "probe hung past 1s")])
    assert runtime.probe_accelerator(timeout=1, attempts=3, backoff=0) is None
    assert len(calls) == 3
    assert runtime.read_down_marker() is not None
    # second probe: marker honored, NO subprocess probes run, and the
    # recorded reason says so (it lands in the bench JSON)
    assert runtime.probe_accelerator(timeout=1, attempts=3, backoff=0) is None
    assert len(calls) == 3
    assert "marker" in runtime.LAST_PROBE.as_dict()["errors"][0]


def test_single_attempt_probe_never_writes_marker(monkeypatch):
    """A casual CLI probe (attempts=1) hitting a transient blip must NOT
    cache a down verdict for every later process — only the bench's
    deliberate multi-attempt probes may."""
    _sequence_probe(monkeypatch, [(None, "probe rc=1: blip")])
    assert runtime.probe_accelerator(timeout=1, attempts=1) is None
    assert runtime.read_down_marker() is None


def test_forced_probe_bypasses_and_clears_marker(monkeypatch):
    """--tpu-only semantics: force_probe re-probes through a fresh marker,
    and a successful probe clears it for the rest of the round."""
    calls = _sequence_probe(monkeypatch, [(None, "probe hung past 1s")])
    assert runtime.probe_accelerator(timeout=1, attempts=2, backoff=0) is None
    assert runtime.read_down_marker() is not None
    _sequence_probe(monkeypatch, [("axon", None)])
    assert runtime.probe_accelerator(
        timeout=1, attempts=1, honor_marker=False
    ) == "axon"
    assert runtime.read_down_marker() is None  # cleared on success
    # with the marker gone, an honoring probe goes straight to subprocess
    calls = _sequence_probe(monkeypatch, [("axon", None)])
    assert runtime.probe_accelerator(timeout=1, attempts=1) == "axon"
    assert len(calls) == 1


def test_stale_marker_is_ignored(monkeypatch):
    _sequence_probe(monkeypatch, [(None, "probe hung past 1s")])
    assert runtime.probe_accelerator(timeout=1, attempts=2, backoff=0) is None
    assert runtime.read_down_marker() is not None
    monkeypatch.setenv("AVDB_TPU_MARKER_TTL_S", "0")
    assert runtime.read_down_marker() is None
    calls = _sequence_probe(monkeypatch, [("axon", None)])
    assert runtime.probe_accelerator(timeout=1, attempts=1) == "axon"
    assert len(calls) == 1
