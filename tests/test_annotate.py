"""Parity tests: annotate kernel vs the scalar oracle, plus golden cases from
the reference's manual smoke fixtures (SURVEY.md §4)."""

import numpy as np
import pytest

from annotatedvdb_tpu import oracle
from annotatedvdb_tpu.ops.annotate import annotate_kernel_jit
from annotatedvdb_tpu.types import VariantBatch, VariantClass

from conftest import random_variants

# Hard indel cases from the reference's manual smoke test
# (Util/bin/test_variant_annotator.py:5-8).
HARD_VARIANTS = [
    ("22", 11212877, "TAAAATATCAAAGTACACCAAATACATATTATATACTGTACAC", "T"),
    (
        "22",
        11212877,
        "TAAAATATCAAAGTACACCAAATACATATTATATACTGTACAC",
        "TAAAATATCAAAGTACACCAAATACATATTATATACTGTACACAAAATATCAAAGTACACCAAATACATATTATATACTGTACAC",
    ),
]

_CLASS_BY_NAME = {
    "single nucleotide variant": VariantClass.SNV,
    "substitution": VariantClass.MNV,
    "inversion": VariantClass.INVERSION,
    "insertion": VariantClass.INS,
    "duplication": VariantClass.DUP,
    "indel": VariantClass.INDEL,
    "deletion": VariantClass.DEL,
}


def run_kernel(variants, width=24):
    batch = VariantBatch.from_tuples(variants, width=width)
    out = annotate_kernel_jit(batch.pos, batch.ref, batch.alt, batch.ref_len, batch.alt_len)
    return batch, {k: np.asarray(v) for k, v in out.items()}


def check_parity(variants, width=24):
    batch, out = run_kernel(variants, width=width)
    for i, (chrom, pos, ref, alt) in enumerate(variants):
        if out["host_fallback"][i]:
            assert len(ref) > width or len(alt) > width
            continue
        nref, nalt = oracle.normalize_alleles(ref, alt)
        end = oracle.infer_end_location(ref, alt, pos)
        attrs = oracle.display_attributes(ref, alt, chrom, pos)
        ctx = f"variant {chrom}:{pos}:{ref}:{alt}"
        assert out["norm_ref_len"][i] == len(nref), ctx
        assert out["norm_alt_len"][i] == len(nalt), ctx
        assert out["end_location"][i] == end, ctx
        assert out["location_start"][i] == attrs["location_start"], ctx
        assert out["location_end"][i] == attrs["location_end"], ctx
        expected_cls = _CLASS_BY_NAME[attrs["variant_class"]]
        assert VariantClass(out["variant_class"][i]) == expected_cls, ctx
        assert out["needs_digest"][i] == (len(ref) + len(alt) > 50), ctx


def test_hard_variants_golden():
    """Expected values derived by executing the reference semantics by hand:
    case 1 is a 42bp deletion (pos+1 .. pos+42), case 2 a duplication."""
    batch, out = run_kernel(HARD_VARIANTS, width=96)
    # case 1: deletion of ref[1:], normalized ref len 42
    assert VariantClass(out["variant_class"][0]) == VariantClass.DEL
    assert out["prefix_len"][0] == 1
    assert out["norm_ref_len"][0] == 42
    assert out["norm_alt_len"][0] == 0
    assert out["end_location"][0] == 11212877 + 42
    assert out["location_start"][0] == 11212878
    assert not out["needs_digest"][0]  # 43+1 <= 50 -> literal PK
    # case 2: one extra copy of the 42bp motif inserted, but the event lands
    # downstream of the anchor (end = pos+42 != pos+1) -> INDEL with a "dup"
    # display prefix (variant_annotator.py:213-220)
    assert VariantClass(out["variant_class"][1]) == VariantClass.INDEL
    assert out["is_dup_motif"][1]
    assert out["norm_ref_len"][1] == 0
    assert out["norm_alt_len"][1] == 42
    assert out["end_location"][1] == 11212877 + 42
    assert out["location_start"][1] == 11212878
    assert out["needs_digest"][1]  # 43+85 > 50 -> VRS digest PK


def test_hard_variants_parity():
    check_parity(HARD_VARIANTS, width=96)


def test_random_parity(rng):
    check_parity(random_variants(rng, 500))


def test_long_allele_flags(rng):
    variants = [("1", 1000, "A" * 40, "A"), ("1", 1000, "A", "C" * 30)]
    batch, out = run_kernel(variants, width=24)
    assert out["host_fallback"].tolist() == [True, True]
    # oracle still handles them (host fallback path)
    attrs = oracle.display_attributes("A" * 40, "A", "1", 1000)
    assert attrs["variant_class"] == "deletion"


def test_oracle_golden_normalization():
    """Normalization behavior spot checks (docstring example
    variant_annotator.py:85 'CAGT/CG <-> AGT/G')."""
    assert oracle.normalize_alleles("CAGT", "CG") == ("AGT", "G")
    assert oracle.normalize_alleles("A", "C") == ("A", "C")        # SNV untouched
    assert oracle.normalize_alleles("CT", "CA") == ("T", "A")      # MNV prefix
    assert oracle.normalize_alleles("GAT", "TAC") == ("GAT", "TAC")  # no prefix
    assert oracle.normalize_alleles("CC", "C", True) == ("C", "-")
    assert oracle.normalize_alleles("C", "CA", True) == ("-", "A")


def test_oracle_inversion_and_dup():
    attrs = oracle.display_attributes("AACG", "GCAA", "1", 500)
    assert attrs["variant_class"] == "inversion"
    assert attrs["location_end"] == 503
    # pure duplication requires the event anchored at pos+1 (end == pos+1,
    # i.e. 2bp ref): single-base motif copy
    attrs = oracle.display_attributes("TA", "TAA", "1", 500)
    assert attrs["variant_class"] == "duplication"
    assert attrs["display_allele"] == "dupA"
    # longer dup-motif insertions land downstream -> indel with dup prefix
    attrs = oracle.display_attributes("CAG", "CAGAG", "1", 500)
    assert attrs["variant_class"] == "indel"
    assert "dup" in attrs["display_allele"]


def test_parity_snv_deletion_to_minus():
    """SNV-sized deletions/insertions after normalization."""
    check_parity(
        [
            ("1", 100, "CC", "C"),
            ("1", 100, "C", "CA"),
            ("1", 100, "CCTTAAT", "CCTTAATC"),  # docstring case variant_annotator.py:69
            ("1", 100, "CAGT", "CG"),
            ("1", 100, "AT", "TA"),  # MNV that is also an inversion
        ]
    )
