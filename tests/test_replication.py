"""Replica fleets: WAL/ledger shipping, bounded-staleness follower
reads, and kill-the-leader failover (``store/replication.py`` + the
``/repl/*`` ship surface + ``serve --follow`` + ``doctor promote``).

Covers the ship reader's torn-frame guarantee (stable prefixes only),
the snapshot-cut bootstrap (resumable, CRC-verified against the
manifest's own integrity records), the tail/apply loop (byte-identical
follower reads at the applied LSN), the staleness contract (lag gauge,
/readyz 503, upserts 403-with-leader-location), and promote failover
(WAL replay into segments, fencing epoch, deposed-leader flush abort).
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from annotatedvdb_tpu.loaders.lookup import identity_hashes
from annotatedvdb_tpu.obs.metrics import MetricsRegistry
from annotatedvdb_tpu.serve import MemtableSnapshots, SnapshotManager
from annotatedvdb_tpu.serve.http import build_server
from annotatedvdb_tpu.store import VariantStore
from annotatedvdb_tpu.store import replication as repl
from annotatedvdb_tpu.store.memtable import Memtable
from annotatedvdb_tpu.store.wal import WriteAheadLog, count_records
from annotatedvdb_tpu.types import encode_allele_array

WIDTH = 8


def _seed_store() -> VariantStore:
    store = VariantStore(width=WIDTH)
    ref, ref_len = encode_allele_array(["A"] * 3, WIDTH)
    alt, alt_len = encode_allele_array(["C"] * 3, WIDTH)
    store.shard(3).append(
        {"pos": np.asarray([10, 20, 30], np.int32),
         "h": identity_hashes(WIDTH, ref, alt, ref_len, alt_len),
         "ref_len": ref_len, "alt_len": alt_len},
        ref, alt,
        annotations={"cadd_scores": [None, {"CADD_phred": 22.5}, None]},
    )
    return store


def _request(port, method, path, body=None, timeout=15):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", method=method,
        data=json.dumps(body).encode() if body is not None else None,
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as err:
        return err.code, err.read()


class _Leader:
    """One in-process threaded leader: on-disk store + memtable + WAL."""

    def __init__(self, store_dir: str):
        self.store_dir = store_dir
        _seed_store().save(store_dir)
        self.registry = MetricsRegistry()
        self.mgr = SnapshotManager(store_dir, log=lambda m: None)
        self.mem = Memtable(
            width=WIDTH, store_dir=store_dir,
            wal=WriteAheadLog(store_dir, "serve-w0", log=lambda m: None),
            registry=self.registry, log=lambda m: None,
        )
        self.httpd = build_server(
            manager=MemtableSnapshots(self.mgr, self.mem), port=0,
            memtable=self.mem, registry=self.registry,
        )
        threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        ).start()
        self.port = self.httpd.server_address[1]
        self.url = f"http://127.0.0.1:{self.port}"

    def upsert(self, variants):
        status, body = _request(self.port, "POST", "/variants/upsert",
                                {"variants": variants})
        assert status == 200, body
        return json.loads(body)

    def close(self):
        self.httpd.shutdown()
        self.httpd.ctx.batcher.close()


@pytest.fixture()
def leader(tmp_path):
    led = _Leader(str(tmp_path / "leader"))
    yield led
    led.close()


def _follower_server(follower_dir, tailer):
    """A read-only follower front end over the mirrored store directory
    with the tailer's overlay — the serve --follow wiring, in-process."""
    registry = MetricsRegistry()
    mgr = SnapshotManager(follower_dir, log=lambda m: None)
    mem = Memtable(width=WIDTH, store_dir=None, wal=None,
                   flush_bytes=0, flush_age_s=0.0, log=lambda m: None)
    manager = MemtableSnapshots(mgr, mem)
    httpd = build_server(manager=manager, port=0, memtable=None,
                         registry=registry)
    httpd.ctx.repl = tailer
    httpd.ctx.follow_url = tailer.leader_url
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd, manager, mem, mgr


# -- satellite: WAL stable-prefix / count_records battery --------------------


def _wal_with_records(tmp_path, n=3, name="serve-w0"):
    wal = WriteAheadLog(str(tmp_path), name=name, log=lambda m: None)
    for i in range(n):
        wal.append({"rows": [{"id": f"3:{100 + i}:A:G"}]})
    return wal


def test_count_records_and_stable_prefix_intact(tmp_path):
    wal = _wal_with_records(tmp_path, n=3)
    wal.close()
    path = wal.pending_files()[0][1]
    assert count_records(path) == 3
    stable, records = repl.stable_wal_prefix(path)
    assert records == 3
    assert stable == os.path.getsize(path)


def test_torn_tail_mid_frame_returns_stable_prefix(tmp_path):
    """A torn tail (kill mid-append) never ships and never counts: both
    readers stop at the last intact frame boundary."""
    wal = _wal_with_records(tmp_path, n=3)
    wal.close()
    path = wal.pending_files()[0][1]
    full, _ = repl.stable_wal_prefix(path)
    for cut in (full - 1, full - 7, full - 20):
        with open(path, "r+b") as f:
            f.truncate(full)  # restore, then tear mid-3rd-frame
            f.truncate(cut)
        assert count_records(path) == 2
        stable, records = repl.stable_wal_prefix(path)
        assert records == 2
        # the stable prefix is a frame boundary: re-reading exactly those
        # bytes yields whole records, never a torn frame
        assert repl.read_wal_records(path, 0, stable) == [
            {"rows": [{"id": "3:100:A:G"}]},
            {"rows": [{"id": "3:101:A:G"}]},
        ]


def test_corrupt_frame_ends_prefix_not_file(tmp_path):
    wal = _wal_with_records(tmp_path, n=2)
    wal.close()
    path = wal.pending_files()[0][1]
    stable1, _ = repl.stable_wal_prefix(path)
    # flip one byte inside the SECOND frame's payload
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.seek(size - 2)
        b = f.read(1)
        f.seek(size - 2)
        f.write(bytes([b[0] ^ 0xFF]))
    stable, records = repl.stable_wal_prefix(path)
    assert records == 1
    assert 0 < stable < stable1


def test_empty_sealed_file_counts_zero(tmp_path):
    wal = _wal_with_records(tmp_path, n=1)
    sealed = wal.rotate()  # the new active file is header-only
    wal.close()
    paths = dict(wal.pending_files())
    active = paths[sealed + 1]
    assert count_records(active) == 0
    stable, records = repl.stable_wal_prefix(active)
    assert records == 0
    assert stable == os.path.getsize(active)  # header ships, no frames


def test_alien_and_missing_files_are_empty_prefix(tmp_path):
    alien = str(tmp_path / "serve-w0.000001.wal")
    with open(alien, "w") as f:
        f.write("this is not a wal header\n")
    assert repl.stable_wal_prefix(alien) == (0, 0)
    assert count_records(alien) == 0
    assert repl.stable_wal_prefix(str(tmp_path / "nope.wal")) == (0, 0)


def test_rotation_race_reader_sees_stable_prefix(tmp_path):
    """Reader vs appender race: every concurrently captured prefix must
    parse to whole records (the ship surface's no-torn-frame contract)."""
    wal = WriteAheadLog(str(tmp_path), name="serve-w0", log=lambda m: None)
    wal.append({"rows": [{"id": "3:1:A:G"}]})
    path = wal.pending_files()[0][1]
    stop = threading.Event()
    seen = []

    def reader():
        while not stop.is_set():
            p = path  # capture: rotation swaps the module-level name
            stable, records = repl.stable_wal_prefix(p)
            recs = repl.read_wal_records(p, 0, stable)
            seen.append((stable, records, len(recs)))

    t = threading.Thread(target=reader)
    t.start()
    for i in range(60):
        wal.append({"rows": [{"id": f"3:{i + 2}:A:G"}]})
        if i % 20 == 19:
            wal.rotate()
            path = wal.pending_files()[-1][1]
    stop.set()
    t.join()
    wal.close()
    assert seen
    for stable, records, parsed in seen:
        assert parsed == records  # every stable byte range parses fully


# -- ship surface ------------------------------------------------------------


def test_ship_manifest_document_shape(leader):
    leader.upsert([{"id": "3:15:A:G"}])
    doc = repl.ship_manifest(leader.store_dir)
    assert doc["repl"] == 1
    assert doc["epoch"] == 0
    assert isinstance(doc["manifest"], dict) and "shards" in doc["manifest"]
    assert len(doc["fingerprint"]) == 3
    (entry,) = doc["wal"]
    assert entry["records"] == 1
    assert entry["bytes"] == repl.stable_wal_prefix(
        os.path.join(leader.store_dir, entry["file"])
    )[0]


def test_ship_manifest_refuses_non_store(tmp_path):
    with pytest.raises(repl.ReplError):
        repl.ship_manifest(str(tmp_path))
    os.makedirs(tmp_path / "x")
    with open(tmp_path / "x" / "manifest.json", "w") as f:
        f.write("{\"not\": \"a store\"}")
    with pytest.raises(repl.ReplError):
        repl.ship_manifest(str(tmp_path / "x"))


def test_ship_file_range_namespace_and_clamps(leader):
    leader.upsert([{"id": "3:15:A:G"}])
    d = leader.store_dir
    # segments ship raw
    seg = sorted(f for f in os.listdir(d) if f.endswith(".npz"))[0]
    blob = repl.ship_file_range(d, seg, 0, 1 << 30)
    assert blob == open(os.path.join(d, seg), "rb").read()
    # offset/limit honored
    assert repl.ship_file_range(d, seg, 2, 3) == blob[2:5]
    # WAL clamps to the stable prefix even when the file is longer
    wname = repl.wal_files(d)[0]
    wpath = os.path.join(d, wname)
    stable, _ = repl.stable_wal_prefix(wpath)
    with open(wpath, "ab") as f:
        f.write(b"\x99" * 9)  # a torn tail beyond the stable prefix
    assert repl.ship_file_range(d, wname, 0, 1 << 30) == \
        open(wpath, "rb").read()[:stable]
    assert repl.ship_file_range(d, wname, stable, 100) == b""
    # outside the namespace: refused, not read
    for name in ("manifest.json", "../etc/passwd", ".hidden",
                 "repl.cursor.json", "serve-w0.000001.wal.tmp"):
        assert repl.ship_file_range(d, name, 0, 10) is None


def test_repl_routes_404_without_store_dir():
    """A StaticSnapshots front end (no on-disk store) has no ship
    surface: /repl/* answer 404, not a crash."""
    from annotatedvdb_tpu.serve import StaticSnapshots

    httpd = build_server(manager=StaticSnapshots(_seed_store()), port=0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        port = httpd.server_address[1]
        for path in ("/repl/manifest", "/repl/wal?name=x", "/repl/segment"):
            status, body = _request(port, "GET", path)
            assert status == 404, (path, body)
    finally:
        httpd.shutdown()
        httpd.ctx.batcher.close()


# -- bootstrap + tail --------------------------------------------------------


def test_bootstrap_then_tail_byte_identical_reads(leader, tmp_path):
    leader.upsert([
        {"id": "3:15:A:G", "ref_snp": 42,
         "annotations": {"cadd_scores": {"CADD_phred": 31.0}}},
        {"id": "3:25:AT:A"},
    ])
    fdir = str(tmp_path / "follower")
    tailer = repl.ReplicaTailer(fdir, leader.url, log=lambda m: None)
    applied = []
    tailer.apply_rows = applied.extend
    tailer.bootstrap()
    # the mirror is a loadable store from the first bootstrap on
    assert VariantStore.load(fdir, readonly=True).n == 3
    out = tailer.sync_once()
    assert out["applied"] == 1 and not out["resynced"]  # 1 record, 2 rows
    assert [r["pos"] for r in applied] == [15, 25]
    # WAL mirror is byte-identical to the leader's stable prefix
    wname = repl.wal_files(fdir)[0]
    assert open(os.path.join(fdir, wname), "rb").read() == \
        open(os.path.join(leader.store_dir, wname), "rb").read()
    # cursor ledger persisted (resumable)
    cur = json.load(open(os.path.join(fdir, repl.CURSOR_FILE)))
    assert cur["repl_cursor"] == 1 and cur["offsets"]

    # serve the mirror through the follower front end: every read is
    # byte-identical to the leader at the applied LSN
    httpd, _manager, mem, _mgr = _follower_server(fdir, tailer)
    try:
        for rec in tailer.local_records():
            mem.upsert(_mgr_store(_manager), rec["rows"], durable=False)
        fport = httpd.server_address[1]
        for path in ("/variant/3:15:A:G", "/variant/3:25:AT:A",
                     "/variant/3:20:A:C", "/region/3:1-1000"):
            ls, lb = _request(leader.port, "GET", path)
            fs, fb = _request(fport, "GET", path)
            assert (ls, lb) == (fs, fb), path
    finally:
        httpd.shutdown()
        httpd.ctx.batcher.close()


def _mgr_store(manager):
    return manager.base.current().store


def test_tail_is_incremental_and_idempotent(leader, tmp_path):
    fdir = str(tmp_path / "follower")
    tailer = repl.ReplicaTailer(fdir, leader.url, log=lambda m: None)
    applied = []
    tailer.apply_rows = applied.extend
    tailer.bootstrap()
    leader.upsert([{"id": "3:15:A:G"}])
    assert tailer.sync_once()["applied"] == 1
    assert tailer.sync_once()["applied"] == 0  # nothing new: no re-apply
    leader.upsert([{"id": "3:25:AT:A"}])
    assert tailer.sync_once()["applied"] == 1
    assert [r["pos"] for r in applied] == [15, 25]


def test_leader_flush_resyncs_cut_and_resets_overlay(leader, tmp_path):
    """A leader memtable flush commits a new manifest generation and
    discards sealed WAL files; the follower must re-sync the cut, drop
    vanished mirrors, and fire on_resync exactly once."""
    fdir = str(tmp_path / "follower")
    resyncs = []
    tailer = repl.ReplicaTailer(fdir, leader.url, log=lambda m: None,
                                on_resync=lambda: resyncs.append(1))
    tailer.bootstrap()
    leader.upsert([{"id": "3:15:A:G"}])
    tailer.sync_once()
    assert repl.wal_files(fdir)

    assert leader.mem.flush()["status"] == "flushed"
    leader.mgr.refresh()
    out = tailer.sync_once()
    assert out["resynced"] and resyncs == [1]
    # the flushed row is in the mirrored base cut now; the discarded
    # leader WAL vanished from the mirror too
    assert VariantStore.load(fdir, readonly=True).n == 4
    assert repl.wal_files(fdir) == repl.wal_files(leader.store_dir)


def test_restart_resume_recovers_lsn_and_records(leader, tmp_path):
    leader.upsert([{"id": "3:15:A:G"}, {"id": "3:25:AT:A"}])
    fdir = str(tmp_path / "follower")
    t1 = repl.ReplicaTailer(fdir, leader.url, log=lambda m: None)
    t1.bootstrap()
    t1.sync_once()
    offsets = dict(t1._offsets)

    # a fresh incarnation adopts the cursor and re-derives the LSN
    # vector from the mirrored bytes alone
    t2 = repl.ReplicaTailer(fdir, leader.url, log=lambda m: None)
    recovered = t2.resume()
    assert recovered == 1  # one record (two rows) durable locally
    assert t2._offsets == offsets
    rows = [r["pos"] for rec in t2.local_records() for r in rec["rows"]]
    assert rows == [15, 25]
    assert t2.sync_once()["applied"] == 0  # nothing re-applied


def test_restart_truncates_torn_mirror_tail(leader, tmp_path):
    """A kill mid-mirror leaves a torn tail; resume truncates back to
    the local stable prefix and the next cycle re-ships the difference —
    the follower lands on a consistent applied-LSN prefix, never a
    hybrid."""
    leader.upsert([{"id": "3:15:A:G"}])
    fdir = str(tmp_path / "follower")
    t1 = repl.ReplicaTailer(fdir, leader.url, log=lambda m: None)
    t1.bootstrap()
    t1.sync_once()
    wname = repl.wal_files(fdir)[0]
    wpath = os.path.join(fdir, wname)
    with open(wpath, "ab") as f:
        f.write(b"\x01\x02\x03")  # torn mid-frame tail

    leader.upsert([{"id": "3:25:AT:A"}])
    t2 = repl.ReplicaTailer(fdir, leader.url, log=lambda m: None)
    applied = []
    t2.apply_rows = applied.extend
    assert t2.resume() == 1
    t2.sync_once()
    # only the NEW record applies; the mirror is whole again
    assert [r["pos"] for r in applied] == [25]
    assert open(wpath, "rb").read() == \
        open(os.path.join(leader.store_dir, wname), "rb").read()


def test_nonpersist_worker_applies_without_touching_disk(leader, tmp_path):
    """Fleet follower workers 1..N (persist=False) apply shipped frames
    straight from memory: same applied rows, zero files mirrored."""
    leader.upsert([{"id": "3:15:A:G"}])
    fdir = str(tmp_path / "follower-w1")
    tailer = repl.ReplicaTailer(fdir, leader.url, log=lambda m: None,
                                persist=False)
    applied = []
    tailer.apply_rows = applied.extend
    tailer.bootstrap()
    tailer.sync_once()
    assert [r["pos"] for r in applied] == [15]
    assert not os.path.exists(fdir) or not os.listdir(fdir)


def test_deposed_leader_epoch_refused(leader, tmp_path):
    fdir = str(tmp_path / "follower")
    tailer = repl.ReplicaTailer(fdir, leader.url, log=lambda m: None)
    tailer.bootstrap()
    tailer._epoch = 7  # as if this follower already saw epoch 7
    with pytest.raises(repl.ReplError, match="deposed"):
        tailer.sync_once()


# -- staleness contract ------------------------------------------------------


def test_lag_gauge_readyz_and_follower_403(leader, tmp_path):
    leader.upsert([{"id": "3:15:A:G"}])
    fdir = str(tmp_path / "follower")
    registry = MetricsRegistry()
    tailer = repl.ReplicaTailer(fdir, leader.url, log=lambda m: None,
                                registry=registry, max_lag_s=0.2)
    tailer.bootstrap()
    tailer.sync_once()
    assert tailer.lag_s() < 0.2 and not tailer.lag_exceeded()

    httpd, _manager, _mem, _mgr = _follower_server(fdir, tailer)
    try:
        fport = httpd.server_address[1]
        status, _ = _request(fport, "GET", "/readyz")
        assert status == 200
        # upserts on a follower: 403 with the leader's location
        status, body = _request(fport, "POST", "/variants/upsert",
                                {"variants": [{"id": "3:77:A:G"}]})
        assert status == 403
        assert json.loads(body)["leader"] == leader.url
        # stall the ship stream: lag grows past the declared bound
        tailer._caught_up_t -= 10.0
        assert tailer.lag_exceeded()
        status, body = _request(fport, "GET", "/readyz")
        assert status == 503 and b"replication lag" in body
        # catch-up clears the gate
        tailer.sync_once()
        status, _ = _request(fport, "GET", "/readyz")
        assert status == 200
    finally:
        httpd.shutdown()
        httpd.ctx.batcher.close()


def test_background_tail_thread_tracks_leader(leader, tmp_path):
    fdir = str(tmp_path / "follower")
    registry = MetricsRegistry()
    applied = []
    tailer = repl.ReplicaTailer(fdir, leader.url, log=lambda m: None,
                                registry=registry, poll_s=0.05,
                                apply_rows=applied.extend)
    tailer.bootstrap()
    tailer.start()
    try:
        leader.upsert([{"id": "3:15:A:G"}])
        deadline = time.monotonic() + 10
        while not applied and time.monotonic() < deadline:
            time.sleep(0.02)
        assert [r["pos"] for r in applied] == [15]
    finally:
        tailer.stop()
    rendered = registry.render_prometheus()
    assert "avdb_replication_lag_seconds" in rendered
    assert "avdb_repl_records_applied_total" in rendered
    assert "avdb_repl_ship_bytes_total" in rendered


# -- env knobs ---------------------------------------------------------------


def test_repl_env_knobs(monkeypatch):
    assert repl.repl_max_lag_from_env() == 5.0
    assert repl.repl_poll_from_env() == 0.5
    assert repl.repl_chunk_from_env() == 4 << 20
    assert repl.repl_timeout_from_env() == 10.0
    monkeypatch.setenv("AVDB_REPL_MAX_LAG_S", "0")
    assert repl.repl_max_lag_from_env() == 0.0
    monkeypatch.setenv("AVDB_REPL_CHUNK_BYTES", "512k")
    assert repl.repl_chunk_from_env() == 512 << 10
    for var, fn in (
        ("AVDB_REPL_MAX_LAG_S", repl.repl_max_lag_from_env),
        ("AVDB_REPL_POLL_S", repl.repl_poll_from_env),
        ("AVDB_REPL_CHUNK_BYTES", repl.repl_chunk_from_env),
        ("AVDB_REPL_TIMEOUT_S", repl.repl_timeout_from_env),
    ):
        monkeypatch.setenv(var, "bogus")
        with pytest.raises(ValueError, match=var):
            fn()
        monkeypatch.delenv(var)


# -- promote (failover) ------------------------------------------------------


def test_promote_seals_tail_bumps_epoch_and_fences(leader, tmp_path):
    leader.upsert([{"id": "3:15:A:G"},
                   {"id": "3:25:AT:A", "ref_snp": 9}])
    fdir = str(tmp_path / "follower")
    tailer = repl.ReplicaTailer(fdir, leader.url, log=lambda m: None)
    tailer.bootstrap()
    tailer.sync_once()

    out = repl.promote(fdir, log=lambda m: None)
    assert out == {"status": "promoted", "epoch": 1, "rows": 2}
    # the tailed rows are ordinary committed segments now
    store = VariantStore.load(fdir, readonly=True)
    assert store.n == 5
    assert not repl.wal_files(fdir)
    assert not os.path.exists(os.path.join(fdir, repl.CURSOR_FILE))
    manifest = json.load(open(os.path.join(fdir, "manifest.json")))
    assert manifest["repl_epoch"] == 1

    # promote is idempotent: nothing left to replay, epoch moves on
    again = repl.promote(fdir, log=lambda m: None)
    assert again["status"] == "promoted" and again["rows"] == 0
    assert again["epoch"] == 2

    # fencing: a writer that opened the store before the promote cannot
    # commit a flush over the promoted lineage
    deposed = Memtable(width=WIDTH, store_dir=fdir, wal=None,
                       log=lambda m: None, fence_epoch=0)
    deposed.upsert(store, [{"code": 3, "pos": 99, "ref": "A", "alt": "G"}],
                   durable=False)
    result = deposed.flush()
    assert result["status"] == "aborted"
    assert "fenced" in result["reason"]
    # a writer opened AFTER the promote (fence_epoch = current) commits
    fresh = Memtable(width=WIDTH, store_dir=fdir, wal=None,
                     log=lambda m: None, fence_epoch=2)
    fresh.upsert(store, [{"code": 3, "pos": 99, "ref": "A", "alt": "G"}],
                 durable=False)
    assert fresh.flush()["status"] == "flushed"


def test_promoted_follower_refuses_old_leader(leader, tmp_path):
    """After promote, a tailer re-pointed at the deposed leader refuses
    it (its epoch is behind the promoted store's cursor-free epoch)."""
    fdir = str(tmp_path / "follower")
    tailer = repl.ReplicaTailer(fdir, leader.url, log=lambda m: None)
    tailer.bootstrap()
    tailer.sync_once()
    repl.promote(fdir, log=lambda m: None)
    t2 = repl.ReplicaTailer(fdir, leader.url, log=lambda m: None)
    t2._epoch = 1  # the promoted epoch
    with pytest.raises(repl.ReplError, match="deposed"):
        t2.sync_once()


def test_doctor_promote_cli(leader, tmp_path, capsys):
    from annotatedvdb_tpu.cli.doctor import main as doctor_main

    leader.upsert([{"id": "3:15:A:G"}])
    fdir = str(tmp_path / "follower")
    tailer = repl.ReplicaTailer(fdir, leader.url, log=lambda m: None)
    tailer.bootstrap()
    tailer.sync_once()
    rc = doctor_main(["promote", "--storeDir", fdir, "--json"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["status"] == "promoted" and out["rows"] == 1
    assert VariantStore.load(fdir, readonly=True).n == 4
    # not a store: exit 2
    assert doctor_main(
        ["promote", "--storeDir", str(tmp_path / "nope")]
    ) == 2
