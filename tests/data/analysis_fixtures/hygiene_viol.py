"""Fixture: hygiene violations (AVDB601/AVDB602/AVDB603).

``# EXPECT: <CODE>`` markers pin the expected findings.
"""


def swallow_everything():
    try:
        return 1 / 0
    except:                                   # EXPECT: AVDB601
        pass


def swallow_exception():
    try:
        return 1 / 0
    except Exception:                         # EXPECT: AVDB602
        pass


def swallow_with_log_ok(log=print):
    try:
        return 1 / 0
    except Exception as err:  # fine: the error is surfaced
        log(f"failed: {err}")
        return None


def narrow_pass_ok():
    try:
        return 1 / 0
    except ZeroDivisionError:  # fine: narrow type
        pass


def mutable_default(items=[]):                # EXPECT: AVDB603
    return items


def mutable_default_kw(*, mapping={}):        # EXPECT: AVDB603
    return mapping


def none_default_ok(items=None):
    return items or []
