"""Fixture: the "threaded front end" half of the parity pair (AVDB8xx).

Findings are reported against the aio twin (``serve/aio.py`` in this
tree), which carries the EXPECT markers; this file is the reference
side.  See tests/test_avdb_check.py.
"""
import os


MSG_SHED = "fixture: bulk reads shed (point reads keep serving)"


def parse_region_params(query):
    """The shared helper the aio twin fails to use (AVDB803 over there)."""
    return query


def handler():
    knob = os.environ.get("AVDB_SERVE_FIXTURE_KNOB", "1")
    body = "fixture response body shaped here exactly once"
    return parse_region_params(MSG_SHED + body + knob)
