# fixture aio front end (parity pair twin)       # EXPECT: AVDB803
# AVDB803 reports file-level at line 1: parse_region_params is used by
# the http twin but never referenced here.
import os


def handler():
    knob = os.environ.get("AVDB_SERVE_FIXTURE_KNOB", "1")  # EXPECT: AVDB802
    body = "fixture response body shaped here exactly once"  # EXPECT: AVDB801
    return body + knob
