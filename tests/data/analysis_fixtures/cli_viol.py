"""Fixture: CLI-contract violations (AVDB501/AVDB502).

tests/test_avdb_check.py runs the analyzer with ``loader_clis`` overridden
to point at THIS file, which hand-rolls its parser: two shared flags are
missing entirely and one is re-defined with a drifted default.
"""
import argparse


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)  # EXPECT: AVDB501, AVDB501
    ap.add_argument("--fileName", required=True)
    ap.add_argument("--commit", action="store_true")
    ap.add_argument("--test", action="store_true")
    ap.add_argument("--logAfter", type=int, default=None)
    ap.add_argument("--logFilePath", default=None)
    ap.add_argument("--maxErrors", type=int, default=0)  # EXPECT: AVDB502
    # --metricsOut / --traceOut are MISSING -> the two AVDB501s above
    return ap.parse_args(argv)
