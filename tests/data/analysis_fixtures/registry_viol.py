"""Fixture: registry-drift violations (AVDB301/AVDB303/AVDB304).

``# EXPECT: <CODE>`` markers pin the expected findings.  The fault-point
check resolves against the REAL ``faults.POINTS`` registry (the fixture
lives inside the repo), so ``ingest.chunk`` passes and a typo fails.
"""
from annotatedvdb_tpu.obs.metrics import MetricsRegistry
from annotatedvdb_tpu.utils import faults

reg = MetricsRegistry()


def fire_points():
    faults.fire("ingest.chunk")  # registered: clean
    faults.fire("ingest.chunkz")              # EXPECT: AVDB301


def register_metrics():
    reg.counter("avdb_fixture_rows_total", "rows", {"loader": "x"})
    reg.gauge("avdb_fixture_rows_total", "rows")  # EXPECT: AVDB303
    reg.counter("avdb_fixture_chunks_total", "c", {"loader": "x"})
    reg.counter("avdb_fixture_chunks_total", "c", {"stage": "y"})  # EXPECT: AVDB304
    # non-literal labels are skipped, not guessed: no finding
    labels = {"loader": "z"}
    reg.counter("avdb_fixture_chunks_total", "c", labels)
