"""Fixture parity "test" for the twins tree: references good_kernel_jit
together with good_kernel_np, so that pair (and only that pair) counts
as proven for AVDB903.  Not collected by pytest (no test_ prefix) — the
analyzer only needs the names to co-occur in a file under tests/."""

PAIR = ("good_kernel_jit", "good_kernel_np")
