"""Fixture ops registry for the AVDB9xx twin-contract rules."""

TWINS: dict = {
    # clean: jitted, twin resolves, pair referenced by tests/kernels_parity.py
    "ops.kernels.good_kernel_jit": "ops.kernels.good_kernel_np",
    # resolvable pair with NO test referencing both
    "ops.kernels.untested_kernel_jit":
        "ops.kernels.untested_kernel_np",     # EXPECT: AVDB903
    # stale: no such jitted function under ops/
    "ops.kernels.ghost_kernel_jit":
        "ops.kernels.ghost_kernel_np",        # EXPECT: AVDB902
    # stale the other way: kernel exists, twin target does not
    "ops.kernels.orphan_kernel_jit":
        "ops.kernels.no_such_twin",           # EXPECT: AVDB902
}
