"""Fixture kernels for the AVDB9xx twin-contract rules."""
import functools

import jax


def good_kernel(x):
    return x


def good_kernel_np(x):
    return x


good_kernel_jit = jax.jit(good_kernel)      # registered + tested: clean


def untested_kernel(x):
    return x


def untested_kernel_np(x):
    return x


untested_kernel_jit = jax.jit(untested_kernel)  # 903 fires at the registry


def orphan_kernel(x):
    return x


orphan_kernel_jit = jax.jit(orphan_kernel)  # its TWIN is stale (registry)


def rogue_kernel(x):
    return x


rogue_kernel_jit = jax.jit(rogue_kernel)    # EXPECT: AVDB901


@jax.jit
def decorated_rogue(x):                     # EXPECT: AVDB901
    return x


@functools.partial(jax.jit, static_argnames=("mode",))
def partial_rogue(x, mode):                 # EXPECT: AVDB901
    return x


def mesh_pjit(fn, pads):                    # stand-in for parallel.mesh's
    return fn                               # sharded-kernel factory


mesh_rogue = mesh_pjit(rogue_kernel_jit, ("zero",))  # EXPECT: AVDB901
