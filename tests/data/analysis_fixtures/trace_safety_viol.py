"""Fixture: trace-safety violations (AVDB101/AVDB102).

Violation lines carry a trailing ``# EXPECT: <CODE>`` marker;
tests/test_avdb_check.py asserts the analyzer reports exactly those
(code, line) pairs for this file.  This file is never imported — the
analyzer is purely static.
"""
import functools
import os

import jax
from annotatedvdb_tpu.utils import faults


@jax.jit
def decorated_kernel(x, y):
    print("tracing", x)                       # EXPECT: AVDB101
    faults.fire("ingest.chunk")               # EXPECT: AVDB101
    flag = os.environ.get("AVDB_PIPELINE")    # EXPECT: AVDB101
    del flag
    if x > 0:                                 # EXPECT: AVDB102
        return x + y
    return x - y


def wrapped_kernel(x):
    counter.inc(1)                            # EXPECT: AVDB101
    return x * 2


wrapped_kernel_jit = jax.jit(wrapped_kernel)


def sharded_step(block):
    if block:                                 # EXPECT: AVDB102
        return block
    return block * 0


sharded = jax.shard_map(sharded_step)


@functools.partial(jax.jit, static_argnames=("mode",))
def static_ok(x, mode):
    if mode:          # static param: allowed
        return x + 1
    if x:                                     # EXPECT: AVDB102
        return x
    return x - 1


def shape_read_ok(x):
    if x.shape[0] > 8:  # static under tracing: allowed
        return x
    return x * 2


shape_read_ok_jit = jax.jit(shape_read_ok)


def host_helper(x):   # NOT traced: none of this is flagged
    print("fine here")
    if x:
        return os.environ.get("AVDB_PIPELINE")
    return None


counter = None
