"""Stale-suppression fixture: one live noqa, one stale, one empty blanket.

The live one (``swallow``) suppresses an AVDB602 that genuinely fires —
AVDB604 must leave it alone.  The stale one names a code that fires
nowhere near it; the blanket one suppresses nothing at all (and must not
be able to self-suppress the audit that flags it).
"""


def swallow(probe):
    try:
        probe()
    except Exception:  # avdb: noqa[AVDB602] -- fixture: deliberately silent
        pass


def stale(probe):
    result = probe()  # avdb: noqa[AVDB602] -- nothing swallowed here  # EXPECT: AVDB604
    return result


TUNING = 7  # avdb: noqa  # EXPECT: AVDB604
