"""Arms the tests/-scanned half of the whole-tree gate (tree_scan)."""


def test_placeholder():
    assert True
