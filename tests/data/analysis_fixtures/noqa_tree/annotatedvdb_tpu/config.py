"""Miniature registry: scanning this path arms ``full_registry_scan``
(and, with the sibling ``tests/`` module, ``tree_scan``) so the AVDB604
stale-suppression audit runs over this fixture tree.  Empty registries —
the audits have nothing to cross-reference and stay silent."""

ENV_VARS = {}
