"""Fixture: env-var drift violations (AVDB401).

``# EXPECT: <CODE>`` markers pin the expected findings.  Resolution is
against the REAL ``config.ENV_VARS`` registry; writes are never flagged.
"""
import os


def read_vars():
    a = os.environ.get("AVDB_PIPELINE")  # declared: clean
    b = os.getenv("AVDB_TOTALLY_UNDECLARED")  # EXPECT: AVDB401
    c = os.environ["AVDB_ALSO_UNDECLARED"]    # EXPECT: AVDB401
    return a, b, c


def write_vars():
    # writes arm fixtures/tests — the variable's job, never a finding
    os.environ["AVDB_SOME_WRITE_ONLY"] = "1"
