"""Fixture: lock-discipline violations (AVDB201/AVDB202).

``# EXPECT: <CODE>`` markers pin the expected findings; see
tests/test_avdb_check.py.
"""
import threading


class GuardedCounter:
    def __init__(self):
        self._lock = threading.Lock()
        #: guarded by self._lock
        self._value = 0
        self._value = 0  # re-assignment in __init__ is exempt

    def inc(self):
        with self._lock:
            self._value += 1  # correctly guarded

    def racy_read(self):
        return self._value                    # EXPECT: AVDB201

    def racy_write(self):
        self._value = 0                       # EXPECT: AVDB201

    def suppressed_read(self):
        # lexical rule escape hatch: caller holds the lock
        return self._value  # avdb: noqa[AVDB201] -- caller holds _lock

    def guarded_then_not(self):
        with self._lock:
            v = self._value  # guarded
        self._value = v + 1                   # EXPECT: AVDB201


class StaleAnnotation:
    def __init__(self):
        #: guarded by self._lokc  # EXPECT: AVDB202
        self._events = []

    def read(self):
        return self._events                   # EXPECT: AVDB201


class AugAssignBinding:
    """The annotation binds to augmented assignments too."""

    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0

    def bump(self):
        with self._lock:
            #: guarded by self._lock
            self.count += 1  # the annotation binds HERE (augassign)

    def racy_bump(self):
        self.count += 1                       # EXPECT: AVDB201


class FloatingAnnotation:
    def __init__(self):
        self._lock = threading.Lock()
        #: guarded by self._lock  # EXPECT: AVDB202
        # (binds to nothing: no self.X assignment within 3 lines —
        #  a silently dropped annotation would disable the rule)
        x = 1
        del x


class Unannotated:
    """No guard annotations: nothing here is checked."""

    def __init__(self):
        self.value = 0

    def racy_but_unclaimed(self):
        self.value += 1  # fine: no annotation claims a lock
