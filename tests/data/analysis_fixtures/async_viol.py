"""Fixture: async-safety violations (AVDB701/AVDB702).

``# EXPECT: <CODE>`` markers pin the expected findings; see
tests/test_avdb_check.py.  Never imported — purely static analysis.
"""
import subprocess
import threading
import time


async def blocking_directly(loop, pool):
    time.sleep(0.5)                           # EXPECT: AVDB701
    data = open("/tmp/f").read()              # EXPECT: AVDB701
    subprocess.run(["ls"])                    # EXPECT: AVDB701
    await loop.run_in_executor(pool, slow_scan)   # routed: allowed
    return data


def slow_scan():
    # only referenced as an executor target, never CALLED from async:
    # nothing here is flagged
    time.sleep(1.0)
    return open("/tmp/g").read()


def helper_called_from_async():
    with open("/tmp/h") as f:                 # EXPECT: AVDB701
        return f.read()


def second_hop():
    return helper_called_from_async()


async def blocking_via_helpers():
    return helper_called_from_async() + second_hop()


class Server:
    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0

    def _sync_write(self):
        time.sleep(0.01)                      # EXPECT: AVDB701

    async def handle(self, fut):
        fut.result()                          # EXPECT: AVDB701
        with self._lock:                      # EXPECT: AVDB701
            self.value += 1
        self._sync_write()

    async def await_under_lock(self, fut):
        with self._lock:                      # EXPECT: AVDB701
            await fut                         # EXPECT: AVDB702
        return self.value

    async def suppressed(self):
        time.sleep(0)  # avdb: noqa[AVDB701] -- fixture: justified block

    async def callback_factory(self):
        def cb():
            # nested def: runs wherever its executor runs, not here
            time.sleep(1.0)
        return cb


def plain_sync_function():
    # no async reaches this: blocking is fine on a worker thread
    time.sleep(0.1)
    return open("/tmp/ok").read()
