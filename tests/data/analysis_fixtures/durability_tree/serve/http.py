"""Front-end ack-order fixture: a 200 built before the durable upsert.

Only this one front end exists in the tree, so the AVDB8xx parity
finalizer stays silent and the findings here are AVDB1005's alone.
"""


def handle_upsert(ctx, body):
    if body is None:
        return (400, {"error": "empty body"})
    if ctx.queue_full:
        return (200, {"status": "queued"})  # EXPECT: AVDB1005
    accepted = ctx.memtable.upsert(ctx.store, body["rows"])
    return (200, {"accepted": accepted})
