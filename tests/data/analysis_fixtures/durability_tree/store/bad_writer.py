"""Durability-protocol fixture: each AVDB10xx shape violated exactly once.

A miniature store writer that gets every step of the tmp -> fsync ->
rename -> manifest-commit protocol wrong in a different function, plus
one correct function per rule so the checker's negative space is pinned
too.  Scanned as a tree (``run_paths([tree], root=tree)``) together with
the sibling ``store/fsck.py`` so the AVDB1002/1003 cross-reference arms.
"""

import json
import os


def unsynced_rename(path):
    tmp = path + ".flush.tmp"
    with open(tmp, "w") as f:
        f.write("payload")
    os.replace(tmp, path)  # EXPECT: AVDB1001


def synced_rename(path):
    tmp = path + ".flush.tmp"
    with open(tmp, "w") as f:
        f.write("payload")
        os.fsync(f.fileno())
    os.replace(tmp, path)


def uninjectable_manifest_commit(store_dir, manifest):
    mpath = os.path.join(store_dir, "manifest.json")
    tmp = mpath + ".t"
    with open(tmp, "w") as f:
        json.dump(manifest, f)
        os.fsync(f.fileno())
    os.replace(tmp, mpath)  # EXPECT: AVDB1004


COMPACT_TMP = ".compact.tmp"  # EXPECT: AVDB1002, AVDB1003


class UnsyncedWriteAheadLog:
    def append(self, frame):  # EXPECT: AVDB1005
        self._f.write(frame)
        return len(frame)


class EagerAckWriteAheadLog:
    def append(self, frame):
        if not frame:
            return 0  # EXPECT: AVDB1005
        self._f.write(frame)
        os.fsync(self._f.fileno())
        return len(frame)
