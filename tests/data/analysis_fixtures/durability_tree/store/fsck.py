"""Miniature fsck: attributes only the ``flush`` tmp family.

Scanning this file arms the AVDB1002/1003 cross-reference (fsck_scan):
the ``flush-tmp`` code below attributes ``.flush.tmp`` debris, while the
``.compact.tmp`` literal in ``bad_writer.py`` stays unattributed and
must be flagged.
"""


def note(level, code, path):
    return {"level": level, "code": code, "path": path}


def scan_store(names):
    findings = []
    for name in names:
        if name.endswith(".flush.tmp"):
            findings.append(note("warn", "flush-tmp", name))
    return findings
