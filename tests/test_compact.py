"""Crash-safe online compaction (`store/compact.py` + `doctor compact`).

The byte-parity gate: a compacted store must answer point / bulk / region /
`/regions` BYTE-identically to the fragmented pre-compaction store — via
the engine, a brute-force per-row reference scan, and BOTH HTTP front ends
— while legacy (pre-compaction-era) stores keep loading unchanged.  Plus:
first-wins dedup, the v2 container (dictionary-coded alleles, compressed
JSONB sidecar), the out-of-core spill tier, online generation-swap
publication, cooperative preemption/cancellation, `doctor compact` CLI
contract (dry-run / --group / --maxBytes), fsck's compact-tmp handling,
and the compaction metrics registrations.
"""

import json
import os
import shutil
import subprocess
import sys
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

import test_serve as ts
from annotatedvdb_tpu.store import (
    AlgorithmLedger,
    VariantStore,
    compact_store,
    plan_compaction,
)
from annotatedvdb_tpu.store.compact import _metrics, segment_spans
from annotatedvdb_tpu.store.fsck import fsck
from annotatedvdb_tpu.store.variant_store import Segment
from annotatedvdb_tpu.serve import QueryEngine, SnapshotManager
from annotatedvdb_tpu.utils import faults


@pytest.fixture(autouse=True)
def _disarm():
    yield
    faults.reset("")


def _fragmented(store_dir: str):
    """The test_serve store (chr1/chr8/chrX): 3 disjoint segments each, one
    OVERLAPPING chr8 segment with a shadowed duplicate + an over-width
    long-allele row.  Saved segment-per-append, so the directory is a
    genuinely fragmented many-file store."""
    return ts._build_store(store_dir)


def _files(store_dir: str):
    return sorted(
        f for f in os.listdir(store_dir)
        if f.endswith(".npz") or f.endswith(".ann.jsonl")
    )


def _query_bytes(store_dir: str, truth: list) -> dict:
    """Every read surface's bytes from a FRESH engine on ``store_dir``:
    point (every truth row + misses), bulk, region (filters/limit), and a
    batched /regions panel."""
    manager = SnapshotManager(store_dir)
    engine = QueryEngine(manager, region_cache_size=0)
    out = {}
    out["points"] = [engine.lookup(ts._vid(r)) for r in truth]
    out["misses"] = [engine.lookup("8:499:A:G"), engine.lookup("9:1:A:C")]
    out["bulk"] = engine.lookup_many([ts._vid(r) for r in truth])
    out["regions_single"] = [
        engine.region(spec, min_cadd=mc, max_conseq_rank=mr, limit=lim)
        for spec, mc, mr, lim in (
            ("8:1-10000", None, None, None),
            ("8:1-3000000", 5.0, None, 64),
            ("1:100000-2500000", None, 10, None),
            ("X:1-999", None, None, 0),
        )
    ]
    batch = engine.regions_serve(
        ["8:1-10000", "8:400-700", "1:1-3000000"], limit=16
    )
    out["regions_batch"] = [p.assemble() for p in batch.pages]
    return out


def test_compaction_byte_parity_engine_and_brute(tmp_path):
    store_dir = str(tmp_path / "vdb")
    truth = _fragmented(store_dir)
    assert len(_files(store_dir)) > 6  # genuinely fragmented

    pre = _query_bytes(store_dir, truth)
    # brute-force reference scan of the PRE store (region text rebuilt row
    # by row, first-wins dedup applied by hand)
    pre_store = VariantStore.load(store_dir)
    brute_pre = ts._brute_region_text(pre_store, 1, 8, 1, 10000)

    report = compact_store(store_dir)
    assert report["status"] == "compacted"
    assert report["rows_dropped"] == 1  # the shadowed chr8 duplicate
    assert report["files_after"] == len(report["labels"]) == 3
    assert report["bytes_after"] < report["bytes_before"]

    post = _query_bytes(store_dir, truth)
    assert post == pre
    # the brute scan of the POST store reproduces the same region text
    post_store = VariantStore.load(store_dir)
    assert ts._brute_region_text(post_store, 1, 8, 1, 10000) == brute_pre
    # and the store is observably compact: one segment file pair per shard
    assert segment_spans(store_dir) == {"1": 1, "8": 1, "X": 1}
    assert fsck(store_dir, deep=True, log=lambda m: None)["exit_code"] == 0


def _collect_http(port: int, truth: list) -> list:
    """One response-bytes sample across every route of a front end."""
    out = []
    for r in truth[:25] + [truth[-1]]:
        out.append(ts._get(port, f"/variant/{ts._vid(r)}")[:2])
    out.append(ts._get(port, "/variant/8:499:A:G")[:2])
    out.append(ts._get(port, "/region/8:1-10000?minCadd=5&limit=8")[:2])
    out.append(ts._get(port, "/region/1:100000-2500000?limit=0")[:2])
    ids = [ts._vid(r) for r in truth[:40]] + ["8:499:A:G"]
    for path, payload in (
        ("/variants", {"ids": ids}),
        ("/regions", {"regions": ["8:1-10000", "8:400-700"], "limit": 8}),
    ):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}{path}",
            data=json.dumps(payload).encode(), method="POST",
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=30) as resp:
            out.append((resp.status, resp.read().decode()))
    return out


def test_compaction_byte_parity_both_front_ends(tmp_path):
    """Pre- vs post-compaction responses on the threaded AND aio front
    ends (fresh managers each side, so generation numbers agree)."""
    from annotatedvdb_tpu.serve.aio import build_aio_server
    from annotatedvdb_tpu.serve.http import build_server

    pre_dir = str(tmp_path / "pre")
    truth = _fragmented(pre_dir)
    post_dir = str(tmp_path / "post")
    shutil.copytree(pre_dir, post_dir)
    assert compact_store(post_dir)["status"] == "compacted"

    def threaded_sample(store_dir):
        httpd = build_server(store_dir=store_dir, port=0)
        thread = threading.Thread(target=httpd.serve_forever, daemon=True)
        thread.start()
        try:
            return _collect_http(httpd.server_address[1], truth)
        finally:
            httpd.shutdown()
            httpd.server_close()
            httpd.ctx.batcher.close()

    def aio_sample(store_dir):
        server = build_aio_server(store_dir=store_dir, port=0)
        server.start_background()
        try:
            return _collect_http(server.server_address[1], truth)
        finally:
            server.shutdown()
            server.ctx.batcher.close()

    pre_t = threaded_sample(pre_dir)
    post_t = threaded_sample(post_dir)
    assert post_t == pre_t
    pre_a = aio_sample(pre_dir)
    post_a = aio_sample(post_dir)
    assert post_a == pre_a
    assert pre_a == pre_t  # and the front ends agree with each other


def test_legacy_fragmented_store_loads_unchanged(tmp_path):
    """A store that is never compacted keeps its exact multi-segment
    layout and content across load/save round trips — compaction support
    must not disturb the v1 path."""
    store_dir = str(tmp_path / "vdb")
    _fragmented(store_dir)
    files = _files(store_dir)
    store = VariantStore.load(store_dir)
    n = store.n
    segs = {c: len(s.segments) for c, s in store.shards.items()}
    assert max(segs.values()) > 1
    again = VariantStore.load(store_dir)
    assert again.n == n
    assert {c: len(s.segments) for c, s in again.shards.items()} == segs
    assert _files(store_dir) == files  # loading never rewrites


def test_compacted_sidecar_is_compressed_and_alleles_dict_coded(tmp_path):
    """The v2 container: zlib sidecar (0x78 lead byte) and dictionary-coded
    allele matrices when that shrinks them — verified by content parity
    plus the on-disk artifacts."""
    store_dir = str(tmp_path / "vdb")
    store = VariantStore(width=8)
    sh = store.shard(5)
    n = 600
    for k in range(3):
        cols = {
            "pos": np.arange(1000 + 50_000 * k, 1000 + 50_000 * k + n,
                             dtype=np.int32),
            "h": np.arange(n, dtype=np.uint32) + 11,
            "ref_len": np.full(n, 4, np.int32),
            "alt_len": np.full(n, 4, np.int32),
        }
        ref = np.zeros((n, 8), np.uint8)
        alt = np.zeros((n, 8), np.uint8)
        ref[:, :4] = [65, 67, 71, 84]  # ACGT — 1 unique row
        alt[:, :4] = [84, 71, 67, 65]
        sh.append_segment(Segment.build(
            cols, ref, alt,
            annotations={"other_annotation":
                         [{"k": int(i)} for i in range(n)]},
        ))
        sh._starts_cache = None
        store.save(store_dir)
    pre = VariantStore.load(store_dir)
    pre.shard(5).compact()
    pre_sig = (pre.shard(5).cols["pos"].tobytes(), pre.shard(5).ref.tobytes(),
               [pre.shard(5).get_ann("other_annotation", i)
                for i in range(0, 3 * n, 97)])

    report = compact_store(store_dir)
    assert report["status"] == "compacted"
    npz = [f for f in _files(store_dir) if f.endswith(".npz")]
    jsonl = [f for f in _files(store_dir) if f.endswith(".ann.jsonl")]
    assert len(npz) == 1 and len(jsonl) == 1
    with open(os.path.join(store_dir, npz[0]), "rb") as f:
        hdr = json.loads(f.readline())
    assert hdr["seg"] == 2
    assert "ref_dict" in hdr["names"] and "alt_dict" in hdr["names"]
    with open(os.path.join(store_dir, jsonl[0]), "rb") as f:
        assert f.read(1) == b"\x78"  # zlib magic, not '{'

    post = VariantStore.load(store_dir)
    post.shard(5).compact()
    post_sig = (post.shard(5).cols["pos"].tobytes(),
                post.shard(5).ref.tobytes(),
                [post.shard(5).get_ann("other_annotation", i)
                 for i in range(0, 3 * n, 97)])
    assert post_sig == pre_sig
    # deep-verify agrees with the compressed/coded integrity records
    assert fsck(store_dir, deep=True, log=lambda m: None)["exit_code"] == 0


def test_online_publication_through_snapshot_swap(tmp_path):
    """Compaction against a LIVE pinned generation: the pre-compaction
    snapshot keeps answering (its segment set is in memory; GC'd files
    don't matter), the swap publishes the compacted generation, and
    point/bulk answers are byte-identical across the swap."""
    store_dir = str(tmp_path / "vdb")
    truth = _fragmented(store_dir)
    manager = SnapshotManager(store_dir)
    engine = QueryEngine(manager, region_cache_size=0)
    vids = [ts._vid(r) for r in truth]
    pre_points = [engine.lookup(v) for v in vids]
    pre_gen = manager.current().generation

    report = compact_store(store_dir)
    assert report["status"] == "compacted"
    # the pinned (pre-compaction) generation still answers: its files are
    # gone from disk but the loaded segment set is immune to the GC
    assert [engine.lookup(v) for v in vids] == pre_points
    assert manager.current().generation == pre_gen

    assert manager.refresh() is True
    assert manager.current().generation == pre_gen + 1
    assert [engine.lookup(v) for v in vids] == pre_points


def test_cancel_aborts_cleanly(tmp_path):
    store_dir = str(tmp_path / "vdb")
    _fragmented(store_dir)
    files = _files(store_dir)
    report = compact_store(store_dir, cancel=lambda: True)
    assert report["status"] == "aborted"
    assert "cancel" in report["reason"]
    assert _files(store_dir) == files
    assert not [f for f in os.listdir(store_dir) if ".compact.tmp" in f]


def test_loader_commit_mid_pass_preempts(tmp_path, monkeypatch):
    """A loader commit between merge and swap must abort the pass (temps
    removed, the LOADER's generation intact) — the cooperative-preemption
    half of the online contract."""
    store_dir = str(tmp_path / "vdb")
    _fragmented(store_dir)
    committed = {"n": 0}
    real_fire = faults.fire

    def commit_at_swap(point, *args, **kwargs):
        if point == "compact.swap" and not committed["n"]:
            committed["n"] = 1
            store = VariantStore.load(store_dir)
            ts._append(store.shard(8), [
                {"chrom": 8, "pos": 7_777_777, "ref": "A", "alt": "G",
                 "rs": -1, "cadd": None, "rank": None, "vep": False},
            ])
            store.save(store_dir)
        return real_fire(point, *args, **kwargs)

    monkeypatch.setattr(
        "annotatedvdb_tpu.store.compact.faults.fire", commit_at_swap
    )
    report = compact_store(store_dir)
    assert report["status"] == "aborted"
    assert "loader committed" in report["reason"]
    assert not [f for f in os.listdir(store_dir) if ".compact.tmp" in f]
    store = VariantStore.load(store_dir)  # loader's row survived the abort
    found, _ = store.shard(8).lookup(
        *_identity_arrays("A", "G", 7_777_777)
    )
    assert bool(found[0])
    # an unarmed retry compacts to a clean store that keeps the row
    monkeypatch.setattr("annotatedvdb_tpu.store.compact.faults.fire",
                        real_fire)
    assert compact_store(store_dir)["status"] == "compacted"
    store = VariantStore.load(store_dir)
    found, _ = store.shard(8).lookup(
        *_identity_arrays("A", "G", 7_777_777)
    )
    assert bool(found[0])
    assert fsck(store_dir, deep=True, log=lambda m: None)["exit_code"] == 0


def _identity_arrays(ref: str, alt: str, pos: int):
    from annotatedvdb_tpu.loaders.lookup import identity_hashes
    from annotatedvdb_tpu.types import encode_allele_array

    r, rl = encode_allele_array([ref], ts.WIDTH)
    a, al = encode_allele_array([alt], ts.WIDTH)
    h = identity_hashes(ts.WIDTH, r, a, rl, al, [ref], [alt])
    return np.asarray([pos], np.int32), h, r, a, rl, al


# ---------------------------------------------------------------------------
# out-of-core spill tier


def test_spill_tier_loads_memmapped_and_byte_identical(tmp_path, monkeypatch):
    store_dir = str(tmp_path / "vdb")
    truth = _fragmented(store_dir)
    pre = _query_bytes(store_dir, truth)

    monkeypatch.setenv("AVDB_STORE_SPILL_BYTES", "1")  # spill everything
    store = VariantStore.load(store_dir)
    assert any(
        isinstance(seg.cols["pos"], np.memmap)
        for s in store.shards.values() for seg in s.segments
    )
    assert _query_bytes(store_dir, truth) == pre  # engine over spilled store

    # mutation lands in copy-on-write pages (update loaders keep working)
    sh = store.shard(8)
    sh.set_col("ref_snp", [0], [424242])
    assert int(sh.get_col("ref_snp", [0])[0]) == 424242

    # and a compaction pass over a spilled store still round-trips
    assert compact_store(store_dir)["status"] == "compacted"
    monkeypatch.delenv("AVDB_STORE_SPILL_BYTES")
    assert _query_bytes(store_dir, truth) == pre


def test_spill_threshold_gates_by_file_size(tmp_path, monkeypatch):
    store_dir = str(tmp_path / "vdb")
    _fragmented(store_dir)
    monkeypatch.setenv("AVDB_STORE_SPILL_BYTES", "1g")  # nothing that big
    store = VariantStore.load(store_dir)
    assert not any(
        isinstance(seg.cols["pos"], np.memmap)
        for s in store.shards.values() for seg in s.segments
    )


# ---------------------------------------------------------------------------
# doctor compact CLI contract


def _doctor(args):
    from annotatedvdb_tpu.cli import doctor

    return doctor.main(args)


def test_dry_run_prints_plan_without_touching(tmp_path, capsys):
    store_dir = str(tmp_path / "vdb")
    _fragmented(store_dir)
    before = {
        f: os.path.getmtime(os.path.join(store_dir, f))
        for f in os.listdir(store_dir)
    }
    rc = _doctor(["compact", "--storeDir", store_dir, "--dry-run", "--json"])
    assert rc == 0
    plan = json.loads(capsys.readouterr().out)
    assert {e["label"] for e in plan["eligible"]} == {"1", "8", "X"}
    for e in plan["eligible"]:
        assert e["stems"] >= 3 and e["bytes_before"] > 0
    after = {
        f: os.path.getmtime(os.path.join(store_dir, f))
        for f in os.listdir(store_dir)
    }
    assert after == before  # nothing touched, nothing created


def test_group_and_max_bytes_scoping(tmp_path, capsys):
    store_dir = str(tmp_path / "vdb")
    _fragmented(store_dir)
    # --group compacts exactly that chromosome
    rc = _doctor(["compact", "--storeDir", store_dir,
                  "--group", "chrX", "--json"])
    assert rc == 0
    report = json.loads(capsys.readouterr().out)
    assert report["status"] == "compacted" and report["labels"] == ["X"]
    spans = segment_spans(store_dir)
    assert spans["X"] == 1 and spans["8"] > 1 and spans["1"] > 1
    # --maxBytes 0: every remaining group is over budget -> noop
    rc = _doctor(["compact", "--storeDir", store_dir,
                  "--maxBytes", "0", "--json"])
    assert rc == 0
    assert json.loads(capsys.readouterr().out)["status"] == "noop"
    # unscoped pass finishes the rest
    rc = _doctor(["compact", "--storeDir", store_dir, "--json"])
    assert rc == 0
    assert set(segment_spans(store_dir).values()) == {1}


def test_cli_missing_store_is_exit_2(tmp_path, capsys):
    rc = _doctor(["compact", "--storeDir", str(tmp_path / "nope")])
    assert rc == 2


def test_cli_hard_failure_is_exit_2(tmp_path):
    """A real I/O failure mid-merge (injected EIO) is the documented exit
    2 — never the benign 'aborted cleanly' 1 an ops retry loop would
    treat as preemption and spin on."""
    store_dir = str(tmp_path / "vdb")
    _fragmented(store_dir)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               AVDB_FAULT="compact.merge:1:eio")
    p = subprocess.run(
        [sys.executable, "-m", "annotatedvdb_tpu", "doctor", "compact",
         "--storeDir", store_dir],
        env=env, capture_output=True, text=True, timeout=240,
    )
    assert p.returncode == 2, (p.returncode, p.stderr[-500:])
    assert "EIO" in p.stderr
    store = VariantStore.load(store_dir)  # store untouched
    assert store.n > 0


def test_compact_metrics_registered_and_counted(tmp_path):
    from annotatedvdb_tpu.obs import MetricsRegistry

    store_dir = str(tmp_path / "vdb")
    _fragmented(store_dir)
    reg = MetricsRegistry()
    compact_store(store_dir, registry=reg, cancel=lambda: True)  # abort
    compact_store(store_dir, registry=reg)                       # pass
    snap = reg.snapshot()
    assert snap["avdb_compact_passes_total"][0]["value"] == 1
    assert snap["avdb_compact_aborts_total"][0]["value"] == 1
    assert snap["avdb_compact_segments_merged_total"][0]["value"] > 0
    assert snap["avdb_compact_bytes_reclaimed_total"][0]["value"] > 0
    assert snap["avdb_compact_seconds"][0]["count"] == 1
    # the module default registry exists and exposes the same names
    handles = _metrics(None)
    assert set(handles) == {
        "passes", "segments_merged", "bytes_reclaimed", "aborts", "seconds"
    }


def test_compact_ledger_record(tmp_path):
    store_dir = str(tmp_path / "vdb")
    _fragmented(store_dir)
    compact_store(store_dir)
    led = AlgorithmLedger(os.path.join(store_dir, "ledger.jsonl"),
                          log=lambda m: None)
    recs = led.compactions()
    assert len(recs) == 1
    rec = recs[0]
    assert rec["type"] == "compact"
    assert set(rec) >= {"labels", "files_before", "files_after",
                        "bytes_before", "bytes_after", "bytes_reclaimed",
                        "rows", "rows_dropped", "seconds", "ts"}
    # compact records are invisible to resume/undo logic
    assert led.last_checkpoint("whatever.vcf") == 0
    assert led.pending_undo_intents() == []


# ---------------------------------------------------------------------------
# fsck: abandoned compaction temps


def test_fsck_flags_and_prunes_compact_tmp(tmp_path):
    store_dir = str(tmp_path / "vdb")
    _fragmented(store_dir)
    stray_npz = os.path.join(store_dir, "chr8.000042.compact.tmp.npz")
    stray_jsonl = os.path.join(store_dir,
                               "chr8.000042.compact.tmp.ann.jsonl")
    open(stray_npz, "wb").write(b"half-written garbage")
    open(stray_jsonl, "wb").write(b"\x78\x9cxx")
    report = fsck(store_dir, log=lambda m: None)
    codes = [f["code"] for f in report["findings"]]
    assert codes.count("compact-tmp") == 2
    assert "foreign-file" not in codes  # the satellite bug: was foreign
    assert report["exit_code"] == 1
    report = fsck(store_dir, repair=True, log=lambda m: None)
    assert not os.path.exists(stray_npz)
    assert not os.path.exists(stray_jsonl)
    assert fsck(store_dir, log=lambda m: None)["status"] == "clean"


def test_stale_plan_label_preempts_instead_of_keyerror(tmp_path, monkeypatch):
    """A plan naming a label the (separately read, fingerprinted) manifest
    no longer carries must preempt cleanly, never KeyError mid-pass."""
    import annotatedvdb_tpu.store.compact as C

    store_dir = str(tmp_path / "vdb")
    _fragmented(store_dir)
    real_plan = C.plan_compaction

    def stale_plan(*args, **kwargs):
        plan = real_plan(*args, **kwargs)
        plan["eligible"].append({
            "label": "22", "stems": 3, "groups": 3, "rows": 0,
            "bytes_before": 10, "est_bytes_after": 10,
        })
        return plan

    monkeypatch.setattr(C, "plan_compaction", stale_plan)
    report = compact_store(store_dir)
    assert report["status"] == "aborted"
    assert "no longer present" in report["reason"]
    assert not [f for f in os.listdir(store_dir) if ".compact.tmp" in f]
    VariantStore.load(store_dir)  # untouched


def test_corrupt_compressed_sidecar_is_store_corrupt_error(tmp_path):
    """A same-size bit flip in a compacted (zlib) sidecar passes the free
    size check but must still surface as StoreCorruptError naming the
    doctor — never a bare zlib.error."""
    from annotatedvdb_tpu.store import StoreCorruptError

    store_dir = str(tmp_path / "vdb")
    _fragmented(store_dir)
    compact_store(store_dir)
    victim = [f for f in _files(store_dir)
              if f.startswith("chr8.") and f.endswith(".ann.jsonl")][0]
    fp = os.path.join(store_dir, victim)
    blob = bytearray(open(fp, "rb").read())
    assert blob[0] == 0x78  # the compressed format is what's under test
    blob[len(blob) // 2] ^= 0xFF
    open(fp, "wb").write(bytes(blob))
    with pytest.raises(StoreCorruptError, match="store_fsck"):
        VariantStore.load(store_dir)


def test_malformed_spill_knob_raises(tmp_path, monkeypatch):
    """A typo'd AVDB_STORE_SPILL_BYTES errors loudly (shared parse_bytes
    grammar) instead of silently disabling the out-of-core tier."""
    store_dir = str(tmp_path / "vdb")
    _fragmented(store_dir)
    monkeypatch.setenv("AVDB_STORE_SPILL_BYTES", "512mb")
    with pytest.raises(ValueError, match="AVDB_STORE_SPILL_BYTES"):
        VariantStore.load(store_dir)


def test_plan_skips_damaged_groups(tmp_path):
    """A group with a missing segment file is skipped (doctor --repair
    first), never half-compacted."""
    store_dir = str(tmp_path / "vdb")
    _fragmented(store_dir)
    victim = [f for f in _files(store_dir)
              if f.startswith("chr1.") and f.endswith(".npz")][0]
    os.remove(os.path.join(store_dir, victim))
    plan = plan_compaction(store_dir)
    assert "1" not in {e["label"] for e in plan["eligible"]}
    assert any(e["label"] == "1" and "missing" in e["reason"]
               for e in plan["skipped"])


def test_compact_survives_sigterm_via_cli(tmp_path):
    """SIGTERM mid-pass aborts cleanly: rc=1, temps pruned, store intact
    (the cooperative shutdown half of the preemption contract)."""
    import signal
    import time

    store_dir = str(tmp_path / "vdb")
    _fragmented(store_dir)
    files = _files(store_dir)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               AVDB_COMPACT_CHUNK_ROWS="1024",
               # park the pass long enough to land the signal mid-merge
               AVDB_FAULT="compact.plan:1:delay:8000")
    proc = subprocess.Popen(
        [sys.executable, "-m", "annotatedvdb_tpu", "doctor", "compact",
         "--storeDir", store_dir],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    # wait for the handler-is-live announcement — signaling during
    # interpreter startup would hit the DEFAULT handler and just die
    line = proc.stderr.readline()
    assert "pass starting" in line, line
    time.sleep(0.5)
    proc.send_signal(signal.SIGTERM)
    rc = proc.wait(timeout=60)
    assert rc == 1, proc.stderr.read()[-1000:]
    assert _files(store_dir) == files
    assert not [f for f in os.listdir(store_dir) if ".compact.tmp" in f]
    store = VariantStore.load(store_dir)
    assert store.n > 0
