"""CADD score-join subsystem tests (SURVEY.md §7.2 step 6).

Fixture mirrors the CADD distribution format: the SNV table carries 3 rows
(alt bases) per position, the indel table a variable run; evidence columns
are (RawScore, PHRED).  Expectations follow the reference's matching rules
(``cadd_updater.py:187-221``): table choice by allele length, allele-set
membership, first match wins, ``{}`` placeholder for unmatched, skip rows
already scored."""

import gzip
import json
import subprocess
import sys

import numpy as np

from annotatedvdb_tpu.io.cadd import CaddFileReader
from annotatedvdb_tpu.loaders import TpuVcfLoader
from annotatedvdb_tpu.loaders.cadd_loader import TpuCaddUpdater
from annotatedvdb_tpu.ops.cadd_join import cadd_join_kernel
from annotatedvdb_tpu.store import AlgorithmLedger, VariantStore
from annotatedvdb_tpu.types import VariantBatch

VCF = """##fileformat=VCFv4.2
#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO
1\t100\t.\tA\tG\t.\t.\t.
1\t200\t.\tC\tT\t.\t.\t.
1\t300\t.\tG\tGA\t.\t.\t.
1\t400\t.\tTC\tT\t.\t.\t.
2\t100\t.\tT\tA\t.\t.\t.
M\t263\t.\tA\tG\t.\t.\t.
"""

SNV_TSV = """## CADD GRCh38-v1.7
#Chrom\tPos\tRef\tAlt\tRawScore\tPHRED
1\t100\tA\tC\t0.1\t1.0
1\t100\tA\tG\t0.2\t2.0
1\t100\tA\tT\t0.3\t3.0
1\t200\tC\tA\t0.4\t4.0
1\t200\tC\tG\t0.5\t5.0
1\t200\tC\tT\t0.6\t6.0
2\t100\tT\tA\t0.7\t7.0
2\t100\tT\tC\t0.8\t8.0
2\t100\tT\tG\t0.9\t9.0
MT\t263\tA\tG\t1.1\t11.0
MT\t263\tA\tC\t1.2\t12.0
MT\t263\tA\tT\t1.3\t13.0
"""

INDEL_TSV = """## CADD GRCh38-v1.7 indels
#Chrom\tPos\tRef\tAlt\tRawScore\tPHRED
1\t300\tG\tGA\t2.0\t20.0
1\t300\tG\tGAA\t2.1\t21.0
1\t500\tAT\tA\t2.2\t22.0
"""


def build_store(tmp_path):
    store = VariantStore(width=49)
    ledger = AlgorithmLedger(str(tmp_path / "ledger.jsonl"))
    vcf = tmp_path / "v.vcf"
    vcf.write_text(VCF)
    TpuVcfLoader(store, ledger, log=lambda *a: None).load_file(str(vcf), commit=True)
    return store, ledger


def write_cadd_db(tmp_path):
    db = tmp_path / "cadd"
    db.mkdir(exist_ok=True)
    with gzip.open(db / "whole_genome_SNVs.tsv.gz", "wt") as f:
        f.write(SNV_TSV)
    with gzip.open(db / "gnomad.genomes.r3.0.indel.tsv.gz", "wt") as f:
        f.write(INDEL_TSV)
    return str(db)


def scores_by_metaseq(store):
    out = {}
    for code, shard in store.shards.items():
        for i in range(shard.n):
            batch = VariantBatch(
                np.array([code], np.int8), shard.cols["pos"][i : i + 1],
                shard.ref[i : i + 1], shard.alt[i : i + 1],
                shard.cols["ref_len"][i : i + 1], shard.cols["alt_len"][i : i + 1],
            )
            out[batch.metaseq_id(0)] = shard.annotations["cadd_scores"][i]
    return out


def test_reader_blocks_and_runs(tmp_path):
    db = write_cadd_db(tmp_path)
    reader = CaddFileReader(db + "/whole_genome_SNVs.tsv.gz", width=8)
    blocks = list(reader.blocks(1))
    assert len(blocks) == 1
    b = blocks[0]
    assert b.n == 6 and b.max_run == 3
    assert b.min_pos == 100 and b.max_pos == 200
    # chromosome 2 stream stops after leaving chr2 (sorted-file early exit)
    b2 = list(reader.blocks(2))[0]
    assert b2.n == 3
    # MT folds to M (code 25)
    bm = list(reader.blocks(25))[0]
    assert bm.n == 3 and bm.min_pos == 263


def test_join_kernel_membership_and_first_match():
    # variants: matching, swapped-orientation matching, non-matching
    batch = VariantBatch.from_tuples(
        [("1", 100, "A", "G"), ("1", 200, "T", "C"), ("1", 100, "A", "A")], width=8
    )
    spos = np.array([100, 100, 200, np.iinfo(np.int32).max], np.int32)
    from annotatedvdb_tpu.types import encode_allele_array

    sref, _ = encode_allele_array(["A", "A", "C", ""], 8)
    salt, _ = encode_allele_array(["G", "T", "T", ""], 8)
    m, midx = cadd_join_kernel(
        batch.pos, batch.ref, batch.alt, spos, sref, salt, probe=4
    )
    m, midx = np.asarray(m), np.asarray(midx)
    assert m.tolist() == [True, True, True]
    # row 1: ref/alt swapped vs table (C->T) still matches by set membership
    assert midx[1] == 2
    # row 2: A/A matches first row at pos 100 whose allele set contains A
    assert midx[2] == 0
    assert midx[0] == 0


def test_updater_end_to_end(tmp_path):
    store, ledger = build_store(tmp_path)
    db = write_cadd_db(tmp_path)
    upd = TpuCaddUpdater(store, ledger, db, log=lambda *a: None)
    counters = upd.update_all(commit=True)
    # SNVs: 1:100 A>G, 1:200 C>T, 2:100 T>A, M:263 A>G all match
    assert counters["snv"] == 4
    # indels: 1:300 G>GA matches; 1:400 TC>T does not
    assert counters["indel"] == 1
    assert counters["not_matched"] == 1
    assert counters["update"] == 5
    scores = scores_by_metaseq(store)
    assert scores["1:100:A:G"] == {"CADD_raw_score": 0.2, "CADD_phred": 2.0}
    assert scores["1:300:G:GA"] == {"CADD_raw_score": 2.0, "CADD_phred": 20.0}
    assert scores["M:263:A:G"] == {"CADD_raw_score": 1.1, "CADD_phred": 11.0}
    assert scores["1:400:TC:T"] == {}  # unmatched placeholder

    # second pass: everything (matched or placeholder) is skipped
    upd2 = TpuCaddUpdater(store, ledger, db, log=lambda *a: None)
    counters2 = upd2.update_all(commit=True)
    assert counters2["update"] == 0 and counters2["skipped"] == 6


def test_updater_dry_run_mutates_nothing(tmp_path):
    store, ledger = build_store(tmp_path)
    db = write_cadd_db(tmp_path)
    TpuCaddUpdater(store, ledger, db, log=lambda *a: None).update_all(commit=False)
    assert all(v is None for v in scores_by_metaseq(store).values())


def test_long_allele_host_replay(tmp_path):
    """Over-width alleles must match on full strings, never truncated bytes."""
    import pytest

    long_a = "A" * 60
    long_b = "A" * 59 + "T"  # same 49-byte prefix as long_a
    store = VariantStore(width=49)
    ledger = AlgorithmLedger(str(tmp_path / "ledger.jsonl"))
    vcf = tmp_path / "long.vcf"
    vcf.write_text(
        "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\n"
        f"1\t700\t.\tG\t{long_b}\t.\t.\t.\n"
    )
    TpuVcfLoader(store, ledger, log=lambda *a: None).load_file(str(vcf), commit=True)
    db = tmp_path / "cadd"
    db.mkdir()
    with gzip.open(db / "gnomad.genomes.r3.0.indel.tsv.gz", "wt") as f:
        f.write(
            "#Chrom\tPos\tRef\tAlt\tRawScore\tPHRED\n"
            f"1\t700\tG\t{long_a}\t3.0\t30.0\n"
            f"1\t700\tG\t{long_b}\t3.5\t35.0\n"
        )
    upd = TpuCaddUpdater(store, ledger, str(db), log=lambda *a: None)
    counters = upd.update_all(commit=True)
    # the 49-byte prefix shared with long_a must NOT match; full-string
    # comparison picks the second row
    assert counters["indel"] == 1 and counters["not_matched"] == 0
    scores = [v for v in store.shard(1).annotations["cadd_scores"] if v]
    assert scores == [{"CADD_raw_score": 3.5, "CADD_phred": 35.0}]

    with pytest.raises(ValueError):
        upd.update_all(chromosomes=["nonsense"], commit=False)


def test_test_mode_does_not_poison_unexamined_rows(tmp_path):
    """--test stops after one block; rows beyond it must stay unset, not {}."""
    store, ledger = build_store(tmp_path)
    db = write_cadd_db(tmp_path)
    upd = TpuCaddUpdater(store, ledger, db, log=lambda *a: None)
    # block_rows=4 forces multiple blocks for the chr1 SNV table; patch the
    # reader capacity through a tiny subclass of the updater's file pass
    import annotatedvdb_tpu.loaders.cadd_loader as mod

    orig = mod.CaddFileReader

    class SmallReader(orig):
        def __init__(self, path, width, block_rows=4, **kw):
            super().__init__(path, width, block_rows=4, **kw)

    mod.CaddFileReader = SmallReader
    try:
        upd.update_all(commit=True, test=True)
    finally:
        mod.CaddFileReader = orig
    # full run afterwards must still score everything the test run skipped
    upd2 = TpuCaddUpdater(store, ledger, db, log=lambda *a: None)
    upd2.update_all(commit=True)
    scores = scores_by_metaseq(store)
    assert scores["1:200:C:T"] == {"CADD_raw_score": 0.6, "CADD_phred": 6.0}
    assert scores["2:100:T:A"] == {"CADD_raw_score": 0.7, "CADD_phred": 7.0}
    assert scores["M:263:A:G"] == {"CADD_raw_score": 1.1, "CADD_phred": 11.0}


def test_cli_vcf_restricted(tmp_path):
    store, ledger = build_store(tmp_path)
    store_dir = tmp_path / "vdb"
    store.save(str(store_dir))
    # restrict to a VCF naming only two of the variants
    sub = tmp_path / "subset.vcf"
    sub.write_text(
        "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\n"
        "1\t100\t.\tA\tG\t.\t.\t.\n1\t400\t.\tTC\tT\t.\t.\t.\n"
    )
    db = write_cadd_db(tmp_path)
    res = subprocess.run(
        [sys.executable, "-m", "annotatedvdb_tpu.cli.load_cadd",
         "--databaseDir", db, "--storeDir", str(store_dir),
         "--fileName", str(sub), "--commit"],
        capture_output=True, text=True, check=True,
    )
    counters = json.loads(res.stdout.splitlines()[0])
    assert counters["snv"] == 1 and counters["not_matched"] == 1
    reloaded = VariantStore.load(str(store_dir))
    scores = scores_by_metaseq(reloaded)
    assert scores["1:100:A:G"] == {"CADD_raw_score": 0.2, "CADD_phred": 2.0}
    assert scores["1:400:TC:T"] == {}
    assert scores["1:200:C:T"] is None  # untouched: not in the subset VCF


def test_native_cadd_blocks_parity(tmp_path, monkeypatch):
    """The C++ table tokenizer must produce the exact block stream the
    Python parse loop produces: same codes, same device rows, same
    host-row side tables, across chromosome changes, capacity splits with
    trailing-run peels, long alleles, and malformed lines."""
    import gzip as _gzip

    from annotatedvdb_tpu.io.cadd import CaddFileReader
    from annotatedvdb_tpu.native import cadd as native_cadd

    if not native_cadd.available():
        pytest.skip("no C++ toolchain")

    path = str(tmp_path / "t.tsv.gz")
    with _gzip.open(path, "wt") as f:
        f.write("## CADD v1.6\n#Chrom\tPos\tRef\tAlt\tRawScore\tPHRED\n")
        # chr1: runs of 3 per position, crossing the capacity boundary
        for p in range(100, 160):
            for a in "CGT":
                f.write(f"1\t{p}\tA\t{a}\t{p / 7:.4f}\t{p % 13}.5\n")
        # malformed rows: bad pos, short line, unknown contig, bad score
        f.write("1\tnotanum\tA\tC\t0.1\t1\n")
        f.write("1\t200\n")
        f.write("GL000\t300\tA\tC\t0.1\t1\n")
        f.write("1\t201\tA\tC\tx\t1\n")
        # long alleles at one position (host rows) + short row at same pos
        f.write(f"2\t500\t{'A' * 40}\tG\t0.9\t9\n")
        f.write("2\t500\tA\tG\t0.8\t8\n")
        f.write("chrX\t700\tT\tC\t1e-3\t2.5\n")

    def collect(native: bool):
        monkeypatch.setenv("AVDB_NATIVE_CADD", "1" if native else "0")
        reader = CaddFileReader(path, width=16, block_rows=64)
        out = []
        for code, block in reader.blocks_all():
            n = block.n
            out.append((
                code, n,
                block.pos[:n].tolist(),
                block.ref[:n].tolist(), block.alt[:n].tolist(),
                block.raw[:n].tolist(), block.phred[:n].tolist(),
                block.max_run,
                {k: sorted(v) for k, v in block.host_rows.items()},
            ))
        return out

    a, b = collect(False), collect(True)
    assert a == b
