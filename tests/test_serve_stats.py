"""``POST /stats/region`` serving battery.

The contract under test: the analytics surface answers **byte-identically
on both front ends**, under the device path, the ``host_only`` twin, a
breaker-forced host fallback, and across a live snapshot swap — with the
full admission shape (grammar 400s, brownout shed, deadline 504s, the
interval cap) and the engine's answers pinned against an independent
brute-force reference that shares only the decode/summary helpers
(``ops.stats.feature_values`` / ``summary_from_totals``).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from annotatedvdb_tpu.loaders.lookup import identity_hashes
from annotatedvdb_tpu.ops import stats as st
from annotatedvdb_tpu.serve import (
    DeviceBreaker,
    QueryEngine,
    QueryError,
    SnapshotManager,
)
from annotatedvdb_tpu.store import VariantStore
from annotatedvdb_tpu.store.variant_store import RawJson
from annotatedvdb_tpu.types import chromosome_label, encode_allele_array
from annotatedvdb_tpu.utils import faults

WIDTH = 8
CHROMS = (1, 8, 23)
BASES = ("A", "C", "G", "T")


def _rows_for(code: int, base_pos: int, n: int, salt: int):
    rows = []
    for i in range(n):
        k = (i + salt) % 4
        rows.append({
            "chrom": code, "pos": base_pos + 977 * i,
            "ref": BASES[k], "alt": BASES[(k + 1) % 4],
            "cadd": round(0.5 * i + code, 2) if i % 3 == 0 else None,
            "rank": (i % 30) + 1 if i % 4 == 0 else None,
            "af": round((i % 50) / 50.0, 4) if i % 2 == 0 else None,
        })
    return rows


def _append(shard, rows):
    refs = [r["ref"] for r in rows]
    alts = [r["alt"] for r in rows]
    ref, ref_len = encode_allele_array(refs, WIDTH)
    alt, alt_len = encode_allele_array(alts, WIDTH)
    h = identity_hashes(WIDTH, ref, alt, ref_len, alt_len, refs, alts)
    shard.append(
        {"pos": np.asarray([r["pos"] for r in rows], np.int32),
         "h": h, "ref_len": ref_len, "alt_len": alt_len},
        ref, alt,
        annotations={
            "cadd_scores": [
                {"CADD_phred": r["cadd"]} if r["cadd"] is not None
                else None for r in rows
            ],
            "adsp_most_severe_consequence": [
                {"conseq": "missense_variant", "rank": r["rank"]}
                if r["rank"] is not None else None for r in rows
            ],
            "allele_frequencies": [
                RawJson(json.dumps(
                    {"GnomAD": {"af": r["af"]}, "1000Genomes": r["af"] / 2}
                )) if i % 5 == 0 and r["af"] is not None
                else ({"GnomAD": {"af": r["af"]}}
                      if r["af"] is not None else None)
                for i, r in enumerate(rows)
            ],
        },
    )


def _build_store(store_dir: str | None):
    store = VariantStore(width=WIDTH)
    truth: list[dict] = []
    for code in CHROMS:
        shard = store.shard(code)
        for run, base in enumerate((500, 120_000, 2_000_000)):
            rows = _rows_for(code, base, 40, salt=run)
            _append(shard, rows)
            truth.extend(rows)
    if store_dir is not None:
        store.save(store_dir)
    return store, truth


PANEL = [
    (8, 1, 10_000), (8, 490, 600), (8, 120_000, 160_000),
    (1, 1, 3_000_000), (23, 2_000_000, 2_005_000), (11, 1, 5_000),
    (1, 500, 500), (8, 1, 5_000_000), (23, 1, 4_000_000),
]


def _specs():
    return [f"{chromosome_label(c)}:{s}-{e}" for c, s, e in PANEL]


def _brute_entry(truth, code, start, end, metrics=st.STATS_METRICS,
                 windows=None):
    """Independent reference: accumulate one interval's totals in plain
    Python from the truth rows (no dedup needed: the store is
    loader-deduplicated), render through the shared summary shape."""
    rows = [r for r in truth
            if r["chrom"] == code and start <= r["pos"] <= end]
    af_sum = cadd_sum = 0
    af_hist = np.zeros(len(st.AF_EDGES_FP) - 1, np.int64)
    cadd_hist = np.zeros(len(st.CADD_EDGES_FP) - 1, np.int64)
    ranks = np.zeros(st.RANK_BUCKETS, np.int64)
    afs, cadds = [], []
    for r in sorted(rows, key=lambda r: r["pos"]):
        _cf, _rf, af_fp, cadd_fp, rank_i = st.feature_values(
            {"CADD_phred": r["cadd"]} if r["cadd"] is not None else None,
            {"GnomAD": {"af": r["af"]}} if r["af"] is not None else None,
            {"rank": r["rank"]} if r["rank"] is not None else None,
        )
        afs.append(af_fp)
        cadds.append(cadd_fp)
        if af_fp >= 0:
            af_sum += af_fp
        if cadd_fp >= 0:
            cadd_sum += cadd_fp
        if rank_i >= 0:
            ranks[rank_i] += 1
    _p, _s, af_hist = st.column_totals(np.asarray(afs or [-1], np.int64),
                                       st.AF_EDGES_FP) if afs else \
        (0, 0, af_hist)
    if cadds:
        _p, _s, cadd_hist = st.column_totals(
            np.asarray(cadds, np.int64), st.CADD_EDGES_FP
        )
    block = None
    if windows:
        pos = np.asarray(sorted(r["pos"] for r in rows), np.int64)
        counts, present, means = [], [], []
        span = end - start + 1
        q, rem = divmod(span, windows)
        bounds = [start + q * w + (rem * w) // windows
                  for w in range(windows + 1)]
        by_pos = {}
        for r in rows:
            by_pos.setdefault(r["pos"], r)
        for w in range(windows):
            in_w = [p for p in pos.tolist()
                    if bounds[w] <= p < bounds[w + 1]] \
                if w < windows - 1 else [
                    p for p in pos.tolist() if bounds[w] <= p <= end]
            counts.append(len(in_w))
            fps = []
            for p in in_w:
                r = by_pos[p]
                if r["cadd"] is not None:
                    fps.append(int(round(r["cadd"] * st.CADD_SCALE)))
            present.append(len(fps))
            means.append(
                round(sum(fps) / (len(fps) * st.CADD_SCALE), 9)
                if fps else None
            )
        block = {"n": windows, "counts": counts,
                 "cadd_present": present, "cadd_mean": means}
    return {
        "region": f"{chromosome_label(code)}:{start}-{end}",
        **st.summary_from_totals(len(rows), af_sum, af_hist, cadd_sum,
                                 cadd_hist, ranks, list(metrics), block),
    }


@pytest.fixture(scope="module")
def served(tmp_path_factory):
    store_dir = str(tmp_path_factory.mktemp("stats_store"))
    _store, truth = _build_store(store_dir)
    manager = SnapshotManager(store_dir)
    engine = QueryEngine(manager, region_cache_size=8)
    return store_dir, truth, manager, engine


# ---------------------------------------------------------------------------
# engine parity


def test_stats_parity_vs_brute_reference(served):
    _dir, truth, _manager, engine = served
    doc = json.loads(engine.stats_serve(_specs(), windows=4).assemble())
    assert doc["n"] == len(PANEL)
    assert doc["bins"] == st.edges_payload()
    for (code, start, end), entry in zip(PANEL, doc["results"]):
        assert entry == _brute_entry(truth, code, start, end, windows=4), \
            entry["region"]


def test_stats_metrics_subset_renders_only_selected(served):
    _dir, truth, _manager, engine = served
    doc = json.loads(
        engine.stats_serve(["8:1-10000"], metrics=["cadd"]).assemble()
    )
    entry = doc["results"][0]
    assert "cadd" in entry and "af" not in entry and "conseq" not in entry
    assert doc["metrics"] == ["cadd"]
    assert entry == _brute_entry(truth, 8, 1, 10_000, metrics=["cadd"])


def test_stats_device_host_and_breaker_fallback_identical(served):
    store_dir, _truth, _manager, engine = served
    specs = _specs()
    want = engine.stats_serve(specs, windows=3).assemble()
    assert engine.stats_serve(specs, windows=3,
                              host_only=True).assemble() == want
    # forced device: every group through the jitted kernels
    dev_engine = QueryEngine(SnapshotManager(store_dir),
                             region_cache_size=0, stats_device_min=0)
    assert dev_engine.stats_serve(specs, windows=3).assemble() == want
    # breaker-forced host fallback: a failing device kernel feeds the
    # breaker, answers stay byte-identical, and an open group stops
    # paying device attempts
    breaker = DeviceBreaker(cooldown_s=30.0)
    sick = QueryEngine(SnapshotManager(store_dir), region_cache_size=0,
                       stats_device_min=0, breaker=breaker)
    calls = {"n": 0}

    def boom(index, feats, starts, ends):
        calls["n"] += 1
        raise RuntimeError("injected stats kernel failure")

    sick._device_stats = boom
    sick._device_windows = lambda *a, **k: boom(*a[:4])
    for _ in range(breaker.failure_threshold):
        assert sick.stats_serve(specs, windows=3).assemble() == want
    codes = sorted({c for c, _s, _e in PANEL
                    if sick.snapshots.current().store.shards.get(c)})
    assert all(breaker.state(c) == "open" for c in codes)
    before = calls["n"]
    assert sick.stats_serve(specs, windows=3).assemble() == want
    assert calls["n"] == before  # open breaker: no device attempt


def test_stats_grammar_and_cap(served):
    store_dir, _truth, _manager, engine = served
    with pytest.raises(QueryError):
        engine.stats_serve(["8:1-100", "not-a-region"])
    with pytest.raises(QueryError):
        engine.stats_serve(["8:9-3"])
    with pytest.raises(QueryError, match="metrics"):
        engine.stats_serve(["8:1-100"], metrics=["af", "nope"])
    with pytest.raises(QueryError, match="metrics"):
        engine.stats_serve(["8:1-100"], metrics=[])
    with pytest.raises(QueryError, match="windows"):
        engine.stats_serve(["8:1-100"], windows=0)
    with pytest.raises(QueryError, match="windows"):
        engine.stats_serve(["8:1-100"], windows=st.MAX_WINDOWS + 1)
    capped = QueryEngine(SnapshotManager(store_dir), region_cache_size=0,
                         stats_max=2)
    with pytest.raises(QueryError, match="cap"):
        capped.stats_serve(["8:1-10", "8:1-10", "8:1-10"])


def test_stats_fault_fails_only_its_request(served):
    """serve.stats raise/eio fail exactly the armed request; the next
    panel answers byte-identically (the serve.regions contract)."""
    from annotatedvdb_tpu.utils.faults import InjectedFault

    _dir, _truth, _manager, engine = served
    specs = ["8:1-10000", "1:1-3000000"]
    want = engine.stats_serve(specs).assemble()
    try:
        faults.reset("serve.stats:1:raise")
        with pytest.raises(InjectedFault):
            engine.stats_serve(specs)
        faults.reset("serve.stats:1:eio")
        with pytest.raises(OSError):
            engine.stats_serve(specs)
    finally:
        faults.reset("")
    assert engine.stats_serve(specs).assemble() == want


def test_stats_snapshot_swap_invalidates(served, tmp_path):
    """A loader commit swaps the generation and the analytics reflect
    the new rows — generation-keyed feature columns age out exactly like
    every other generation-keyed cache."""
    store_dir = str(tmp_path / "swap_store")
    _build_store(store_dir)
    manager = SnapshotManager(store_dir, ttl_s=0.0)
    engine = QueryEngine(manager, region_cache_size=0)
    spec = "8:9000000-9000100"
    before = json.loads(engine.stats_serve([spec]).assemble())
    assert before["results"][0]["count"] == 0
    store = VariantStore.load(store_dir)
    _append(store.shard(8), [{
        "chrom": 8, "pos": 9_000_050, "ref": "A", "alt": "T",
        "cadd": 12.0, "rank": 3, "af": 0.25,
    }])
    store.save(store_dir)
    assert manager.refresh()
    after = json.loads(engine.stats_serve([spec]).assemble())
    assert after["generation"] == before["generation"] + 1
    entry = after["results"][0]
    assert entry["count"] == 1
    assert entry["cadd"]["present"] == 1 and entry["cadd"]["mean"] == 12.0
    assert entry["af"]["present"] == 1 and entry["af"]["mean"] == 0.25


# ---------------------------------------------------------------------------
# HTTP: both front ends


def _get(port: int, path: str, headers=None):
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}",
                                 headers=headers or {})
    try:
        with urllib.request.urlopen(req, timeout=20) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as err:
        return err.code, err.read().decode()


def _post(port: int, path: str, payload, headers=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(), method="POST",
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    try:
        with urllib.request.urlopen(req, timeout=20) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as err:
        return err.code, err.read().decode()


@pytest.fixture()
def both_servers(served):
    from annotatedvdb_tpu.serve.aio import build_aio_server
    from annotatedvdb_tpu.serve.http import build_server

    store_dir, _truth, _manager, _engine = served
    httpd = build_server(store_dir=store_dir, port=0)
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    aio = build_aio_server(store_dir=store_dir, port=0)
    aio.start_background()
    try:
        yield httpd, aio
    finally:
        httpd.shutdown()
        httpd.server_close()
        httpd.ctx.batcher.close()
        aio.shutdown()
        aio.ctx.batcher.close()


def test_http_stats_cross_frontend_byte_parity(both_servers, served):
    _dir, _truth, _manager, engine = served
    httpd, aio = both_servers
    tport, aport = httpd.server_address[1], aio.server_address[1]
    bodies = [
        {"regions": _specs()},
        {"regions": _specs(), "metrics": ["af", "conseq"]},
        {"regions": ["8:1-10000"], "windows": 8},
        {"regions": []},
    ]
    for body in bodies:
        st1, b1 = _post(tport, "/stats/region", body)
        st2, b2 = _post(aport, "/stats/region", body)
        assert (st1, b1) == (st2, b2), body
        assert st1 == 200
        # and both match the engine's own rendering
        want = engine.stats_serve(
            body["regions"], metrics=body.get("metrics"),
            windows=body.get("windows"),
        ).assemble()
        assert b1 == want
    # kind=stats metrics counted on both front ends
    for port in (tport, aport):
        _st, metrics = _get(port, "/metrics")
        assert 'avdb_query_requests_total{kind="stats"}' in metrics


def test_http_stats_grammar_400_parity(both_servers):
    httpd, aio = both_servers
    tport, aport = httpd.server_address[1], aio.server_address[1]
    for body in (
        {"regions": "x"},
        {"regions": [3]},
        {"regions": ["8:9-3"]},
        {"regions": ["8:1-10"], "metrics": "af"},
        {"regions": ["8:1-10"], "metrics": ["af", "nope"]},
        {"regions": ["8:1-10"], "windows": True},
        {"regions": ["8:1-10"], "windows": 0},
        ["not", "an", "object"],
    ):
        st1, b1 = _post(tport, "/stats/region", body)
        st2, b2 = _post(aport, "/stats/region", body)
        assert st1 == 400 and (st1, b1) == (st2, b2), body


def test_http_stats_brownout_and_deadline_parity(both_servers):
    from annotatedvdb_tpu.serve.http import (
        MSG_BROWNOUT_STATS,
        MSG_DEADLINE_ADMISSION,
    )

    httpd, aio = both_servers
    body = {"regions": ["8:1-10000"]}
    for ctx, port in ((httpd.ctx, httpd.server_address[1]),
                      (aio.ctx, aio.server_address[1])):
        # a sub-microsecond budget is dead by the admission check: 504
        status, text = _post(port, "/stats/region", body,
                             headers={"X-Deadline-Ms": "0.0001"})
        assert status == 504 and MSG_DEADLINE_ADMISSION in text
        # brownout level 3 sheds analytics while point reads keep serving
        ctx.governor.force_level(3)
        try:
            status, text = _post(port, "/stats/region", body)
            assert status == 503 and MSG_BROWNOUT_STATS in text
        finally:
            ctx.governor.force_level(0)
        status, _text = _post(port, "/stats/region", body)
        assert status == 200


def test_http_stats_cap_is_400(served, monkeypatch):
    from annotatedvdb_tpu.serve.http import build_server

    monkeypatch.setenv("AVDB_SERVE_STATS_MAX", "2")
    store_dir, _truth, _manager, _engine = served
    httpd = build_server(store_dir=store_dir, port=0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        port = httpd.server_address[1]
        status, text = _post(port, "/stats/region",
                             {"regions": ["8:1-10", "8:1-10", "8:1-10"]})
        assert status == 400 and "cap" in text
        status, _ = _post(port, "/stats/region", {"regions": ["8:1-10"]})
        assert status == 200
    finally:
        httpd.shutdown()
        httpd.server_close()
        httpd.ctx.batcher.close()


def test_http_stats_fault_500_once_then_serves(both_servers):
    """An armed serve.stats fault surfaces as ONE 500 to the one caller;
    the next request answers normally on the same front end."""
    httpd, aio = both_servers
    body = {"regions": ["8:1-10000"]}
    for port in (httpd.server_address[1], aio.server_address[1]):
        _st, want = _post(port, "/stats/region", body)
        try:
            faults.reset("serve.stats:1:raise")
            status, text = _post(port, "/stats/region", body)
            assert status == 500 and "InjectedFault" in text
        finally:
            faults.reset("")
        status, text = _post(port, "/stats/region", body)
        assert status == 200 and text == want
