"""The overlapped ingest spine (``io/prefetch.py``): knob validation,
shuffled chunk scheduling, zero-copy FREQ sidecars, and mesh-placement-
ordered segment writes.

The load-bearing contract: a load whose chunks were scheduled in a seeded
RANDOM order (``AVDB_INGEST_SHUFFLE_SEED``) must persist a store
byte-identical to the strict-source-order load — the Resequencer restores
chunk order before any order-bearing work, so identity first-wins, counters
and checkpoint cursors cannot tell the schedules apart.  Same story one
layer down: ``save()`` reordering physical segment writes by mesh placement
(``AVDB_MESH_SHAPE``) must leave manifest and segment bytes untouched.
"""

import json
import math
import os

import numpy as np
import pytest

from annotatedvdb_tpu.io.prefetch import (
    ChunkPrefetcher,
    ingest_chunk_rows,
    ingest_prefetch_depth,
    ingest_shuffle_seed,
)
from annotatedvdb_tpu.io.vcf import freq_sidecar, parse_freq, parse_info
from annotatedvdb_tpu.loaders import TpuVcfLoader
from annotatedvdb_tpu.store import AlgorithmLedger, VariantStore
from annotatedvdb_tpu.utils.pipeline import Resequencer

from tests.test_pipeline_modes import (
    COUNTER_KEYS,
    _persisted_bytes,
    _run_load,
    _write_vcf,
)


# ---------------------------------------------------------------------------
# knob validation (the parse_bytes precedent: loud, never a silent fallback)


def test_ingest_knobs_default_when_unset(monkeypatch):
    for name in ("AVDB_INGEST_CHUNK_ROWS", "AVDB_INGEST_PREFETCH_DEPTH",
                 "AVDB_INGEST_SHUFFLE_SEED"):
        monkeypatch.delenv(name, raising=False)
    assert ingest_chunk_rows(4096) == 4096
    assert ingest_chunk_rows() is None
    assert ingest_prefetch_depth() == 2
    assert ingest_shuffle_seed() is None
    # empty string == unset (a cleared shell export must not explode)
    monkeypatch.setenv("AVDB_INGEST_PREFETCH_DEPTH", "  ")
    assert ingest_prefetch_depth(3) == 3


def test_ingest_knobs_parse_and_reject_loudly(monkeypatch):
    monkeypatch.setenv("AVDB_INGEST_CHUNK_ROWS", "8192")
    monkeypatch.setenv("AVDB_INGEST_PREFETCH_DEPTH", "5")
    monkeypatch.setenv("AVDB_INGEST_SHUFFLE_SEED", "0")
    assert ingest_chunk_rows(1) == 8192
    assert ingest_prefetch_depth() == 5
    assert ingest_shuffle_seed() == 0  # seed 0 is a real seed, not "unset"

    monkeypatch.setenv("AVDB_INGEST_CHUNK_ROWS", "lots")
    with pytest.raises(ValueError, match="AVDB_INGEST_CHUNK_ROWS"):
        ingest_chunk_rows(1)
    monkeypatch.setenv("AVDB_INGEST_CHUNK_ROWS", "0")
    with pytest.raises(ValueError, match=">= 1"):
        ingest_chunk_rows(1)
    monkeypatch.setenv("AVDB_INGEST_PREFETCH_DEPTH", "-2")
    with pytest.raises(ValueError, match="AVDB_INGEST_PREFETCH_DEPTH"):
        ingest_prefetch_depth()
    monkeypatch.setenv("AVDB_INGEST_SHUFFLE_SEED", "1.5")
    with pytest.raises(ValueError, match="AVDB_INGEST_SHUFFLE_SEED"):
        ingest_shuffle_seed()


# ---------------------------------------------------------------------------
# ChunkPrefetcher / Resequencer mechanics


def test_prefetcher_untagged_preserves_order():
    src = list(range(57))
    pre = ChunkPrefetcher(iter(src), depth=3)
    assert list(pre) == src


def test_prefetcher_shuffle_requires_tagging():
    with pytest.raises(ValueError, match="tagged"):
        ChunkPrefetcher(iter([1, 2]), depth=2, shuffle_seed=7)


def test_prefetcher_shuffled_schedule_is_seeded_and_complete():
    src = list(range(101))
    runs = []
    for _ in range(2):
        pre = ChunkPrefetcher(iter(src), depth=4, shuffle_seed=123,
                              tagged=True)
        runs.append(list(pre))
    # deterministic replay of the SAME shuffled schedule...
    assert runs[0] == runs[1]
    # ...that is a true permutation (nothing lost, nothing duplicated),
    # tags matching payloads
    assert sorted(runs[0]) == [(i, i) for i in src]
    assert [seq for seq, _ in runs[0]] != src  # it actually shuffled
    # and the Resequencer restores source order exactly
    pre = ChunkPrefetcher(iter(src), depth=4, shuffle_seed=123, tagged=True)
    assert list(Resequencer(pre)) == src


def test_prefetcher_block_shuffle_bounds_resequencer_held():
    """Shuffling permutes disjoint bounded blocks, so the resequencer's
    held dict — the memory cost of out-of-order arrival — is HARD-bounded
    at block−1 chunks, never an unbounded pile."""
    depth = 3
    pre = ChunkPrefetcher(iter(range(200)), depth=depth, shuffle_seed=9,
                          tagged=True)
    rs = Resequencer(pre)
    assert list(rs) == list(range(200))
    assert rs.max_held <= max(2, depth) - 1


def test_prefetcher_propagates_source_error_and_closes():
    def boom():
        yield 1
        yield 2
        raise RuntimeError("scan exploded")

    pre = ChunkPrefetcher(boom(), depth=2)
    got = []
    with pytest.raises(RuntimeError, match="scan exploded"):
        for x in pre:
            got.append(x)
    assert got == [1, 2]
    assert pre.close()


# ---------------------------------------------------------------------------
# zero-copy FREQ sidecars


FREQ_CASES = [
    ("RS=1;FREQ=GnomAD:0.9,0.001", 1),
    ("FREQ=GnomAD:0.5,0.25", 2),  # n_alts > provided freqs
    ("FREQ=TOPMED:.,0.1|GnomAD:0.5,0.25", 1),  # '.' -> None
    ("FREQ=TOPMED:0,0.1", 1),  # "0" excluded by string-compare
    ("FREQ=TOPMED:0.0,0.1", 1),  # "0.0" NOT excluded
    ("FREQ=A B:0.1|dbGaP\\x2cX:0.2", 1),  # space + scrubbed comma in name
    ("FREQ=Ké:0.25", 1),  # non-ASCII population name -> \u escapes
    ("FREQ=X:1e400", 1),  # overflows to inf; json allow_nan renders it
    ("FREQ=X:3", 1),  # integer-form frequency
    ("FREQ=X:0.1;FREQ=Y:0.2", 1),  # duplicate FREQ key: LAST wins
    ("FREQ=X:0.1|X:0.2", 1),  # duplicate population: last wins, first slot
    ("FREQ=1000Genomes:0.993611,0.006389|Chileans:0.925926,0.074074", 2),
    ("FREQ=bad", 1),  # no ':' -> no pops at all
    ("RS=5", 1),  # no FREQ
    ("", 1),
    ("FREQ=GnomAD#0.3", 1),  # '#' scrubs to ':'
]


@pytest.mark.parametrize("info,n_alts", FREQ_CASES)
def test_freq_sidecar_matches_dict_path_bytes(info, n_alts):
    """freq_sidecar's RawJson text must be byte-identical to what
    sidecar_line would have serialized for the parse_freq dict — the whole
    zero-copy discipline rests on this equality."""
    want = parse_freq(parse_info(info), n_alts)
    got = freq_sidecar(info, n_alts)
    assert len(got) == len(want) == n_alts
    for g, w in zip(got, want):
        if w is None:
            assert g is None
        else:
            assert g.text == json.dumps(w)
            assert g == w  # RawJson mapping equality with the dict


def test_freq_sidecar_lazy_equivalence_roundtrip():
    # FREQ slot 0 is the REF frequency; alts take slots 1..n
    got = freq_sidecar("FREQ=GnomAD:0.9,0.25,0.001", 2)
    # RawJson parses lazily but reads like the dict
    assert got[0]["GnomAD"] == {"gmaf": 0.25}
    assert math.isclose(got[1]["GnomAD"]["gmaf"], 0.001)


# ---------------------------------------------------------------------------
# shuffled scheduling end-to-end: byte-identical stores


def test_shuffled_load_store_byte_identical(tmp_path, monkeypatch):
    vcf = str(tmp_path / "multi.vcf")
    _write_vcf(vcf)
    monkeypatch.delenv("AVDB_INGEST_SHUFFLE_SEED", raising=False)
    c_seq, _, store_seq, loader_seq, dir_seq = _run_load(
        tmp_path, vcf, "overlapped", monkeypatch, "seq"
    )
    monkeypatch.setenv("AVDB_INGEST_SHUFFLE_SEED", "1234")
    c_sh, _, store_sh, loader_sh, dir_sh = _run_load(
        tmp_path, vcf, "overlapped", monkeypatch, "sh"
    )
    loader_seq.close(), loader_sh.close()
    assert {k: c_seq.get(k) for k in COUNTER_KEYS} == \
           {k: c_sh.get(k) for k in COUNTER_KEYS}
    assert c_seq["duplicates"] > 0 and c_seq["malformed"] > 0
    assert store_seq.n == store_sh.n
    files_seq, files_sh = _persisted_bytes(dir_seq), _persisted_bytes(dir_sh)
    assert list(files_seq) == list(files_sh)
    for name in files_seq:
        assert files_seq[name] == files_sh[name], f"{name} bytes diverge"
    # the idle-fraction headline is recorded and sane
    assert 0.0 <= loader_sh.device_idle_fraction <= 1.0


def test_shuffled_load_identical_under_mesh_write_order(tmp_path,
                                                        monkeypatch):
    """Same identity with mesh-placement-ordered segment writes active:
    AVDB_MESH_SHAPE reorders save()'s physical writes AND the prefetcher
    shuffles the schedule, yet bytes match a strict-order load saved under
    the same placement."""
    vcf = str(tmp_path / "mesh.vcf")
    _write_vcf(vcf, n_lines=1200)
    monkeypatch.setenv("AVDB_MESH_SHAPE", "2")
    monkeypatch.delenv("AVDB_INGEST_SHUFFLE_SEED", raising=False)
    c_seq, _, _, loader_seq, dir_seq = _run_load(
        tmp_path, vcf, "overlapped", monkeypatch, "mseq"
    )
    monkeypatch.setenv("AVDB_INGEST_SHUFFLE_SEED", "42")
    c_sh, _, _, loader_sh, dir_sh = _run_load(
        tmp_path, vcf, "overlapped", monkeypatch, "msh"
    )
    loader_seq.close(), loader_sh.close()
    assert {k: c_seq.get(k) for k in COUNTER_KEYS} == \
           {k: c_sh.get(k) for k in COUNTER_KEYS}
    files_seq, files_sh = _persisted_bytes(dir_seq), _persisted_bytes(dir_sh)
    assert list(files_seq) == list(files_sh)
    for name in files_seq:
        assert files_seq[name] == files_sh[name], f"{name} bytes diverge"
    # the advisory placement actually landed in the manifest
    manifest = json.loads(files_sh["manifest.json"])
    assert manifest.get("mesh_placement", {}).get("devices") == 2


def test_max_errors_exact_under_shuffled_decode(tmp_path, monkeypatch):
    """--maxErrors must trip at the same rejected-row count no matter how
    the prefetcher scheduled the chunks: the budget check runs on the
    consumer in resequenced chunk order."""
    from annotatedvdb_tpu.utils.quarantine import ErrorBudgetExceeded

    vcf = str(tmp_path / "bad.vcf")
    with open(vcf, "w") as fh:
        fh.write("##fileformat=VCFv4.2\n"
                 "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\n")
        for k in range(3000):
            if k % 500 == 250:  # 6 malformed lines, spread across chunks
                fh.write(f"1\tnot_a_pos_{k}\t.\tA\tC\t.\t.\t.\n")
            else:
                fh.write(f"1\t{1000 + 3 * k}\trs{k}\tA\tC\t.\t.\tRS={k}\n")

    counts = {}
    for tag, seed in (("seq", None), ("sh", "77")):
        monkeypatch.setenv("AVDB_PIPELINE", "overlapped")
        if seed is None:
            monkeypatch.delenv("AVDB_INGEST_SHUFFLE_SEED", raising=False)
        else:
            monkeypatch.setenv("AVDB_INGEST_SHUFFLE_SEED", seed)
        store = VariantStore(width=49)
        ledger = AlgorithmLedger(str(tmp_path / f"led.{tag}.jsonl"))
        loader = TpuVcfLoader(store, ledger, batch_size=256,
                              log=lambda *a: None, max_errors=3)
        with pytest.raises(ErrorBudgetExceeded):
            loader.load_file(vcf, commit=False)
        loader.close()
        counts[tag] = loader._budget.count
    assert counts["seq"] == counts["sh"] == 4  # trips on the 4th reject


# ---------------------------------------------------------------------------
# mesh-placement segment write order


def _multi_chrom_store(codes=(1, 2, 3, 10, 23)):
    from annotatedvdb_tpu.loaders.lookup import identity_hashes
    from annotatedvdb_tpu.types import encode_allele_array

    store = VariantStore(width=8)
    ref, ref_len = encode_allele_array(["A", "A"], 8)
    alt, alt_len = encode_allele_array(["C", "C"], 8)
    for code in codes:
        store.shard(code).append(
            {"pos": np.asarray([10, 20], np.int32),
             "h": identity_hashes(8, ref, alt, ref_len, alt_len),
             "ref_len": ref_len, "alt_len": alt_len},
            ref, alt,
        )
    return store


def test_save_writes_segments_in_placement_order(tmp_path, monkeypatch):
    from annotatedvdb_tpu.parallel.mesh import placement_hint
    from annotatedvdb_tpu.store.variant_store import chromosome_label

    monkeypatch.setenv("AVDB_MESH_SHAPE", "2")
    placement = placement_hint()
    assert placement is not None and placement["devices"] == 2

    orig = VariantStore._write_segment
    order: list[str] = []

    def spy(path, stem, seg):
        order.append(stem)
        return orig(path, stem, seg)

    monkeypatch.setattr(VariantStore, "_write_segment", staticmethod(spy))
    codes = (1, 2, 3, 10, 23)
    store = _multi_chrom_store(codes)
    d = str(tmp_path / "placed")
    store.save(d)

    assert len(order) == len(codes)
    devs = [placement["groups"][stem.split(".")[0][3:]] for stem in order]
    # grouped by owning device, never interleaved
    assert devs == sorted(devs), f"write order not placement-grouped: " \
                                 f"{list(zip(order, devs))}"
    assert len(set(devs)) == 2  # the fixture really spans both devices

    # and the manifest's LOGICAL layout is the legacy sorted-code order —
    # identical (mesh_placement block aside) to a save with no mesh at all
    with open(os.path.join(d, "manifest.json")) as f:
        placed = json.load(f)
    monkeypatch.delenv("AVDB_MESH_SHAPE")
    store2 = _multi_chrom_store(codes)
    d2 = str(tmp_path / "flat")
    store2.save(d2)
    with open(os.path.join(d2, "manifest.json")) as f:
        flat = json.load(f)
    placed.pop("mesh_placement")
    for m in (placed, flat):
        m.pop("store_uid")
    assert placed == flat
    # flat save writes in sorted-code order (the legacy invariant)
    labels = [chromosome_label(c) for c in codes]
    assert [s.split(".")[0][3:] for s in order[len(codes):]] == labels
