"""Bench-record schema gate: every committed ``BENCH_*.json`` must validate
against the documented schema (README "Bench JSON schema"), and the checker
itself must catch the drift classes it exists for."""

import copy
import glob
import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(ROOT, "tools"))

from check_bench_schema import validate_file, validate_record  # noqa: E402

GOOD = {
    "metric": "end_to_end_vcf_to_store_variants_per_sec",
    "value": 1000000.0,
    "unit": "variants/sec",
    "vs_baseline": 6.7,
    "kernel_variants_per_sec": 4.5e6,
    "kernel_vs_target": 4.5,
    "kernel": "jnp",
    "backend": "cpu",
    "end_to_end": {
        "variants_per_sec": 1000000.0,
        "variants": 2092068,
        "duplicates": 5084,
        "seconds": 2.1,
        "vcf_mb": 67.3,
        "mb_per_sec": 32.0,
        "pipeline": "overlapped",
        "stages": {
            "ingest": {"seconds": 0.9, "items": 0},
            "annotate": {"seconds": 0.01, "items": 2092068},
        },
        "stage_wall": {
            "wall_seconds": 2.1, "busy_seconds": 3.2, "overlap": 1.52,
        },
        "queue_stalls": {
            "ingest": {"items": 16, "producer_block_s": 0.4,
                       "consumer_wait_s": 0.1, "max_depth": 2},
            "store-writer": {"items": 16, "producer_block_s": 0.0,
                             "consumer_wait_s": 0.0, "max_depth": 1},
        },
        "vep_update": {
            "results_per_sec": 200000.0, "updated": 200000,
            "seconds": 1.0, "runs": [199000.0, 200000.0, 201000.0],
        },
    },
    "cadd_join": {"table_rows_per_sec": 2.0e6, "matched": 49778,
                  "variants": 100000, "seconds": 0.43},
    "qc_update": {"rows_per_sec": 120000.0, "updated": 100000,
                  "seconds": 0.82},
    "serving": {
        "qps": 3200.0, "p50_ms": 4.9, "p99_ms": 6.3, "requests": 4000,
        "clients": 16, "errors": 0, "batch_fill": 0.06, "batches": 250,
        "seconds": 1.2, "store_rows": 50000,
        "region": {"qps": 110.0, "requests": 200, "seconds": 1.8},
        "regions": {
            "intervals": 2048, "window_bp": 30, "limit": 10,
            "batch_size": 256, "byte_identical": True, "mismatches": 0,
            "sequential": {"intervals_per_sec": 850.0, "p50_ms": 1.1,
                           "p99_ms": 3.2, "seconds": 2.41},
            "batched": {"intervals_per_sec": 7400.0, "calls": 8,
                        "p50_ms": 33.0, "p99_ms": 41.0, "seconds": 0.28},
            "speedup": 8.7,
            "count_only": {"intervals_per_sec": 52000.0, "seconds": 0.04,
                           "speedup": 61.2},
        },
        "stats": {
            "intervals": 1024, "window_bp": 4000, "batch_size": 256,
            "store_rows": 60000, "byte_identical": True, "mismatches": 0,
            "sequential": {"intervals_per_sec": 133.1, "p50_ms": 6.4,
                           "p99_ms": 20.5, "seconds": 7.69},
            "batched": {"intervals_per_sec": 2204.3, "calls": 4,
                        "p50_ms": 106.2, "p99_ms": 132.2,
                        "seconds": 0.47},
            "speedup": 16.56,
            "point_read": {"p99_ms_before": 19.8, "p99_ms_after": 16.0,
                           "ratio": 0.81, "parity_ok": True},
        },
        "open_loop": {
            "slo_p99_ms": 25.0, "conns": 8, "duration_s": 2.5,
            "max_sustainable_qps": 11800.0,
            "fleets": [
                {"workers": 1, "max_sustainable_qps": 9900.0,
                 "steps": [
                     {"offered_qps": 8000.0, "achieved_qps": 7950.0,
                      "p50_ms": 12.0, "p99_ms": 21.5, "errors": 0,
                      "transport_errors": 0,
                      "status_counts": {"200": 19875, "429": 125},
                      "requests": 20000, "seconds": 2.5},
                 ]},
                {"workers": 2, "max_sustainable_qps": 11800.0,
                 "steps": [
                     {"offered_qps": 12000.0, "achieved_qps": 11800.0,
                      "p50_ms": 14.0, "p99_ms": 24.0, "errors": 0,
                      "requests": 30000, "seconds": 2.5},
                 ]},
            ],
        },
        "observability": {
            "offered_qps": 3600.0, "probe_achieved_qps": 7980.0,
            "duration_s": 2.5, "conns": 8, "rounds": 5,
            "armed": {"achieved_qps": 3590.0, "p99_ms": 12.4,
                      "samples": [{"achieved_qps": 3591.0,
                                   "p99_ms": 12.9}]},
            "unarmed": {"achieved_qps": 3594.0, "p99_ms": 12.2,
                        "samples": [{"achieved_qps": 3596.0,
                                     "p99_ms": 12.0}]},
            "overhead_qps": 0.0011, "overhead_p99": 0.0164,
            "overhead_p99_ms": 0.2, "p99_abs_floor_ms": 2.0,
            "max_overhead": 0.03, "within_bound": True,
        },
        "slo": {
            "offered_qps": 3600.0, "probe_achieved_qps": 7973.0,
            "duration_s": 2.5, "conns": 8, "rounds": 5,
            "armed": {"achieved_qps": 3582.0, "p99_ms": 9.3,
                      "samples": [{"achieved_qps": 3582.0,
                                   "p99_ms": 9.3}]},
            "unarmed": {"achieved_qps": 3589.0, "p99_ms": 7.9,
                        "samples": [{"achieved_qps": 3589.0,
                                     "p99_ms": 7.9}]},
            "overhead_qps": 0.0019, "overhead_p99": 0.0182,
            "overhead_p99_ms": 1.46, "p99_abs_floor_ms": 2.0,
            "max_overhead": 0.03, "within_bound": True,
            "alerts_sample": {
                "enabled": True, "worker": 0, "state": "ok",
                "firing": 0, "burn_threshold": 2.0,
                "windows": {"fast_s": 60.0, "slow_s": 300.0},
                "alerts": [
                    {"slo": "availability", "kind": "availability",
                     "state": "ok", "burn_fast": 0.0, "burn_slow": 0.0,
                     "threshold": 2.0, "since": None, "fired_total": 0,
                     "target": 0.999},
                    {"slo": "point_read_p99", "kind": "latency",
                     "state": "ok", "burn_fast": 0.0, "burn_slow": 0.0,
                     "threshold": 2.0, "since": None, "fired_total": 0,
                     "target_ms": 250.0, "objective": 0.99},
                ],
            },
        },
        "mixed_workload": {
            "read_qps_target": 2000.0, "upserts_per_sec_target": 150.0,
            "duration_s": 6.0, "slo_p99_ms": 25.0, "conns": 8,
            "read": {"offered_qps": 2000.0, "achieved_qps": 1988.0,
                     "p50_ms": 8.2, "p99_ms": 19.4, "errors": 0,
                     "transport_errors": 0,
                     "status_counts": {"200": 11928},
                     "requests": 11928, "seconds": 6.0},
            "read_slo_met": True,
            "upserts": {"acked": 894, "errors": 0,
                        "achieved_per_sec": 148.8,
                        "ack_p50_ms": 2.4, "ack_p99_ms": 9.7},
            "acked_verified": 894, "acked_missing": 0,
        },
        "chaos": {
            "mode": "full", "workers": 2, "duration_s": 40.0,
            "offered_qps": 600.0, "requests": 24734, "ok": 23359,
            "errors": 0, "hard_errors": 0, "shed": 12,
            "transport_errors": 1375,
            "status_counts": {"200": 23359, "503": 12},
            "wrong_bytes": 0, "p99_ms": 813.5, "p99_budget_ms": 2500.0,
            "error_rate": 0.0, "error_budget": 0.05,
            "transport_rate": 0.056, "transport_budget": 0.25,
            "faults": ["serve.batch:prob:0.2:delay:20",
                       "serve.wedge:1:delay:30000"],
            "breaker_trips": 1,
            "recovered": True, "recovered_s": 19.1,
            "recovery_window_s": 30.0, "violations": [],
            "compact": {"status": "compacted", "files_before": 2,
                        "files_after": 1, "bytes_reclaimed": 120034,
                        "seconds": 0.8},
            "upserts": {"acked": 360, "errors": 2, "missing": 0,
                        "verify_s": 3.1},
            "maintain": {"high": 3, "low": 2, "passes": 20, "paused": 2,
                         "preempted": 1, "read_amp_end": 1,
                         "converged": True},
            "flight": {"harvested_files": 2, "parse_failures": 0,
                       "harvested_requests": 57, "breaker_events": 3,
                       "brownout_events": 4},
        },
        "replication": {
            "max_lag_s": 3.0, "lag_p50_s": 0.0, "lag_p99_s": 0.16,
            "ship_bytes": 104013, "ship_mb_per_s": 0.008,
            "records_applied": 379, "resyncs": 1, "stale_503_s": 3.03,
            "failover_s": 1.64, "acked": 380, "acked_missing": 0,
            "promote_epoch": 1, "promote_rows": 138,
            "post_promote_write_ok": True, "wrong_bytes": 0,
            "violations": [],
        },
    },
    "storage": {
        "autonomy": {
            "high": 3, "low": 2, "segments_written": 12, "passes": 5,
            "preemptions": 0, "paused": 0, "read_amp_peak": 3,
            "read_amp_bound": 6, "read_amp_bounded": True,
            "read_amp_end": 2, "read_amp_samples": [2, 3, 2, 3, 2],
            "converged": True, "seconds": 8.4,
        },
    },
    "compaction": {
        "rows": 40000, "rows_dropped": 0,
        "files_before": 12, "files_after": 2,
        "bytes_before": 2804211, "bytes_after": 1517804,
        "bytes_reclaimed": 2804211, "seconds": 1.92,
        "segments_per_sec": 6.25,
        "read_amp_before": 6.0, "read_amp_after": 1.0,
        "byte_identical": True, "mismatches": 0,
        "serve": {"offered_qps": 400.0, "achieved_qps": 396.0,
                  "p50_ms": 6.1, "p99_ms": 38.0, "errors": 0,
                  "transport_errors": 0, "requests": 3200},
    },
}


def test_committed_bench_records_validate():
    paths = sorted(glob.glob(os.path.join(ROOT, "BENCH_*.json")))
    assert paths, "no committed BENCH records found"
    for path in paths:
        errors = validate_file(path)
        assert not errors, f"{os.path.basename(path)}: {errors}"


def test_good_record_passes_including_new_blocks():
    assert validate_record(GOOD) == []


def test_missing_core_field_fails():
    bad = copy.deepcopy(GOOD)
    del bad["value"]
    errors = validate_record(bad)
    assert any("value" in e for e in errors)


def test_bad_stage_shape_fails():
    bad = copy.deepcopy(GOOD)
    bad["end_to_end"]["stages"]["ingest"] = {"items": 0}  # no seconds
    errors = validate_record(bad)
    assert any("ingest" in e and "seconds" in e for e in errors)


def test_serving_block_is_validated_strictly():
    bad = copy.deepcopy(GOOD)
    del bad["serving"]["p99_ms"]
    assert any("p99_ms" in e for e in validate_record(bad))
    bad = copy.deepcopy(GOOD)
    bad["serving"]["batch_fill"] = 1.5  # a ratio, not a count
    assert any("batch_fill" in e for e in validate_record(bad))
    bad = copy.deepcopy(GOOD)
    bad["serving"]["p99_ms"] = 1.0  # below p50: impossible percentiles
    assert any("p99_ms below p50_ms" in e for e in validate_record(bad))
    bad = copy.deepcopy(GOOD)
    bad["serving"]["region"] = {"requests": 200}  # qps/seconds required
    assert any("region" in e for e in validate_record(bad))


def test_regions_block_is_validated_strictly():
    bad = copy.deepcopy(GOOD)
    del bad["serving"]["regions"]["speedup"]
    assert any("speedup" in e for e in validate_record(bad))

    bad = copy.deepcopy(GOOD)
    del bad["serving"]["regions"]["batched"]["intervals_per_sec"]
    assert any("intervals_per_sec" in e for e in validate_record(bad))

    bad = copy.deepcopy(GOOD)
    bad["serving"]["regions"]["byte_identical"] = "yes"  # bool, not str
    assert any("byte_identical" in e for e in validate_record(bad))

    bad = copy.deepcopy(GOOD)
    bad["serving"]["regions"]["sequential"]["p99_ms"] = 0.5  # below p50
    assert any("p99_ms below p50_ms" in e for e in validate_record(bad))

    bad = copy.deepcopy(GOOD)
    bad["serving"]["regions"]["intervals"] = 0
    assert any("positive" in e for e in validate_record(bad))

    # a serving block WITHOUT regions stays valid (r05-r07-era records)
    old = copy.deepcopy(GOOD)
    del old["serving"]["regions"]
    assert validate_record(old) == []

    # a failed leg records its error and stays loadable
    failed = copy.deepcopy(GOOD)
    failed["serving"]["regions"] = {"error": "server did not start"}
    assert validate_record(failed) == []


def test_stats_block_is_validated_strictly():
    bad = copy.deepcopy(GOOD)
    del bad["serving"]["stats"]["speedup"]
    assert any("speedup" in e for e in validate_record(bad))

    bad = copy.deepcopy(GOOD)
    del bad["serving"]["stats"]["batched"]["intervals_per_sec"]
    assert any("intervals_per_sec" in e for e in validate_record(bad))

    bad = copy.deepcopy(GOOD)
    bad["serving"]["stats"]["byte_identical"] = "yes"  # bool, not str
    assert any("byte_identical" in e for e in validate_record(bad))

    # byte identity is a correctness contract, REQUIRED true: summaries
    # are deterministic integer aggregations, a divergence is wrong
    # answers (the acked_missing precedent), never measurement noise
    bad = copy.deepcopy(GOOD)
    bad["serving"]["stats"]["byte_identical"] = False
    assert any("wrong answers" in e for e in validate_record(bad))

    bad = copy.deepcopy(GOOD)
    bad["serving"]["stats"]["sequential"]["p99_ms"] = 0.5  # below p50
    assert any("p99_ms below p50_ms" in e for e in validate_record(bad))

    bad = copy.deepcopy(GOOD)
    bad["serving"]["stats"]["intervals"] = 0
    assert any("positive" in e for e in validate_record(bad))

    bad = copy.deepcopy(GOOD)
    del bad["serving"]["stats"]["point_read"]["parity_ok"]
    assert any("parity_ok" in e for e in validate_record(bad))

    # a serving block WITHOUT stats stays valid (r01-r10-era records)
    old = copy.deepcopy(GOOD)
    del old["serving"]["stats"]
    assert validate_record(old) == []

    # a failed leg records its error and stays loadable
    failed = copy.deepcopy(GOOD)
    failed["serving"]["stats"] = {"error": "server did not start"}
    assert validate_record(failed) == []


def test_open_loop_block_is_validated_strictly():
    bad = copy.deepcopy(GOOD)
    del bad["serving"]["open_loop"]["max_sustainable_qps"]
    assert any("max_sustainable_qps" in e for e in validate_record(bad))
    bad = copy.deepcopy(GOOD)
    bad["serving"]["open_loop"]["fleets"] = []  # at least one fleet size
    assert any("fleets" in e for e in validate_record(bad))
    bad = copy.deepcopy(GOOD)
    del bad["serving"]["open_loop"]["fleets"][0]["workers"]
    assert any("workers" in e for e in validate_record(bad))
    bad = copy.deepcopy(GOOD)
    step = bad["serving"]["open_loop"]["fleets"][0]["steps"][0]
    del step["achieved_qps"]
    assert any("achieved_qps" in e for e in validate_record(bad))
    bad = copy.deepcopy(GOOD)
    step = bad["serving"]["open_loop"]["fleets"][1]["steps"][0]
    step["p99_ms"] = 1.0  # below p50: impossible percentiles
    assert any("p99_ms below p50_ms" in e for e in validate_record(bad))
    # a serving block WITHOUT open_loop stays valid (r05-era records)
    old = copy.deepcopy(GOOD)
    del old["serving"]["open_loop"]
    assert validate_record(old) == []


def test_chaos_block_is_validated_strictly():
    bad = copy.deepcopy(GOOD)
    del bad["serving"]["chaos"]["wrong_bytes"]
    assert any("wrong_bytes" in e for e in validate_record(bad))
    bad = copy.deepcopy(GOOD)
    del bad["serving"]["chaos"]["recovered"]
    assert any("recovered" in e for e in validate_record(bad))
    bad = copy.deepcopy(GOOD)
    bad["serving"]["chaos"]["error_rate"] = 1.7  # a ratio, not a count
    assert any("error_rate" in e for e in validate_record(bad))
    bad = copy.deepcopy(GOOD)
    bad["serving"]["chaos"]["faults"] = "serve.wedge"  # a list of specs
    assert any("faults" in e for e in validate_record(bad))
    bad = copy.deepcopy(GOOD)
    bad["serving"]["chaos"]["recovered"] = "yes"  # bool, not string
    assert any("recovered" in e for e in validate_record(bad))
    # a serving block WITHOUT chaos stays valid (r05/r06-era records)
    old = copy.deepcopy(GOOD)
    del old["serving"]["chaos"]
    assert validate_record(old) == []
    # a failed chaos leg records {"error": ...} and stays loadable
    failed = copy.deepcopy(GOOD)
    failed["serving"]["chaos"] = {"error": "chaos soak timed out"}
    assert validate_record(failed) == []


def test_compaction_block_is_validated_strictly():
    bad = copy.deepcopy(GOOD)
    del bad["compaction"]["byte_identical"]
    assert any("byte_identical" in e for e in validate_record(bad))
    bad = copy.deepcopy(GOOD)
    del bad["compaction"]["files_after"]
    assert any("files_after" in e for e in validate_record(bad))
    bad = copy.deepcopy(GOOD)
    bad["compaction"]["byte_identical"] = "yes"  # bool, not string
    assert any("byte_identical" in e for e in validate_record(bad))
    bad = copy.deepcopy(GOOD)
    bad["compaction"]["files_after"] = 99  # compaction cannot grow files
    assert any("files_after above files_before" in e
               for e in validate_record(bad))
    bad = copy.deepcopy(GOOD)
    bad["compaction"]["bytes_before"] = -1
    assert any("bytes_before" in e and "negative" in e
               for e in validate_record(bad))
    bad = copy.deepcopy(GOOD)
    bad["compaction"]["serve"]["p99_ms"] = 1.0  # below p50: impossible
    assert any("p99_ms below p50_ms" in e for e in validate_record(bad))
    bad = copy.deepcopy(GOOD)
    del bad["compaction"]["serve"]["p99_ms"]
    assert any("serve" in e and "p99_ms" in e for e in validate_record(bad))
    # a record WITHOUT the block stays valid (pre-r09 records)
    old = copy.deepcopy(GOOD)
    del old["compaction"]
    assert validate_record(old) == []
    # a failed leg records {"error": ...} and stays loadable
    failed = copy.deepcopy(GOOD)
    failed["compaction"] = {"error": "doctor compact rc=2"}
    assert validate_record(failed) == []
    # the chaos sub-block: compact summary validated when present
    bad = copy.deepcopy(GOOD)
    bad["serving"]["chaos"]["compact"] = {"files_before": 2}  # no status
    assert any("compact" in e and "status" in e
               for e in validate_record(bad))
    bad = copy.deepcopy(GOOD)
    bad["serving"]["chaos"]["compact"]["seconds"] = "fast"
    assert any("compact" in e and "seconds" in e
               for e in validate_record(bad))


def test_open_loop_step_transport_errors_validated():
    bad = copy.deepcopy(GOOD)
    step = bad["serving"]["open_loop"]["fleets"][0]["steps"][0]
    step["transport_errors"] = 1.5  # a count, not a ratio
    assert any("transport_errors" in e for e in validate_record(bad))
    bad = copy.deepcopy(GOOD)
    step = bad["serving"]["open_loop"]["fleets"][0]["steps"][0]
    step["status_counts"] = {"200": "many"}  # counts are integers
    assert any("status_counts" in e for e in validate_record(bad))


def test_queue_stalls_block_is_validated_strictly():
    bad = copy.deepcopy(GOOD)
    del bad["end_to_end"]["queue_stalls"]["ingest"]["consumer_wait_s"]
    errors = validate_record(bad)
    assert any("consumer_wait_s" in e for e in errors)
    neg = copy.deepcopy(GOOD)
    neg["end_to_end"]["queue_stalls"]["ingest"]["producer_block_s"] = -1.0
    errors = validate_record(neg)
    assert any("negative" in e for e in errors)


def test_wrapper_with_failed_rc_is_tolerated(tmp_path):
    # rc != 0 with no parsed record is a legitimate historical record
    path = tmp_path / "BENCH_rX.json"
    path.write_text(json.dumps(
        {"n": 1, "cmd": "python bench.py", "rc": 1, "tail": "boom",
         "parsed": None}
    ))
    assert validate_file(str(path)) == []
    # but rc == 0 with no parsed record is drift
    path.write_text(json.dumps(
        {"n": 1, "cmd": "python bench.py", "rc": 0, "tail": "", "parsed": None}
    ))
    assert validate_file(str(path))


def test_checker_cli_over_committed_records():
    import subprocess

    res = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "check_bench_schema.py")],
        capture_output=True, text=True, timeout=120,
    )
    assert res.returncode == 0, res.stderr


def test_mixed_workload_block_is_validated_strictly():
    mx = GOOD["serving"]["mixed_workload"]
    bad = copy.deepcopy(GOOD)
    del bad["serving"]["mixed_workload"]["acked_missing"]
    assert any("acked_missing" in e for e in validate_record(bad))

    bad = copy.deepcopy(GOOD)
    bad["serving"]["mixed_workload"]["acked_missing"] = 3
    assert any("acknowledged upsert" in e for e in validate_record(bad))

    bad = copy.deepcopy(GOOD)
    bad["serving"]["mixed_workload"]["upserts"]["ack_p99_ms"] = 0.1
    assert any("ack_p99_ms below ack_p50_ms" in e
               for e in validate_record(bad))

    bad = copy.deepcopy(GOOD)
    del bad["serving"]["mixed_workload"]["upserts"]["achieved_per_sec"]
    assert any("achieved_per_sec" in e for e in validate_record(bad))

    bad = copy.deepcopy(GOOD)
    bad["serving"]["mixed_workload"]["read"]["achieved_qps"] = "fast"
    assert any("achieved_qps" in e for e in validate_record(bad))

    # a failed leg records {"error": ...} and must not fail validation
    failed = copy.deepcopy(GOOD)
    failed["serving"]["mixed_workload"] = {"error": "TimeoutError: x"}
    assert validate_record(failed) == []

    # historic records (no mixed_workload at all) keep validating
    old = copy.deepcopy(GOOD)
    del old["serving"]["mixed_workload"]
    assert validate_record(old) == []
    assert isinstance(mx, dict)


def test_chaos_upserts_subblock_is_validated():
    bad = copy.deepcopy(GOOD)
    bad["serving"]["chaos"]["upserts"]["missing"] = 4
    assert any("acknowledged-write loss" in e for e in validate_record(bad))

    bad = copy.deepcopy(GOOD)
    del bad["serving"]["chaos"]["upserts"]["acked"]
    assert any("acked" in e for e in validate_record(bad))

    old = copy.deepcopy(GOOD)
    del old["serving"]["chaos"]["upserts"]
    assert validate_record(old) == []


def test_autonomy_block_is_validated_strictly():
    bad = copy.deepcopy(GOOD)
    del bad["storage"]["autonomy"]["converged"]
    assert any("converged" in e for e in validate_record(bad))

    bad = copy.deepcopy(GOOD)
    bad["storage"]["autonomy"]["converged"] = False
    assert any("never converged" in e for e in validate_record(bad))

    bad = copy.deepcopy(GOOD)
    bad["storage"]["autonomy"]["passes"] = 0
    assert any("proves nothing" in e for e in validate_record(bad))

    bad = copy.deepcopy(GOOD)
    bad["storage"]["autonomy"]["read_amp_end"] = 4  # above low=2
    assert any("above the low watermark" in e for e in validate_record(bad))

    bad = copy.deepcopy(GOOD)
    bad["storage"]["autonomy"]["read_amp_bounded"] = False
    assert any("escaped" in e for e in validate_record(bad))

    bad = copy.deepcopy(GOOD)
    bad["storage"]["autonomy"]["read_amp_samples"] = [2, "x"]
    assert any("read_amp_samples" in e for e in validate_record(bad))

    # a failed leg records its error without poisoning the file
    failed = copy.deepcopy(GOOD)
    failed["storage"]["autonomy"] = {"error": "OSError: boom"}
    assert validate_record(failed) == []

    # historic records (no storage block at all) keep validating
    old = copy.deepcopy(GOOD)
    del old["storage"]
    assert validate_record(old) == []


def test_chaos_maintain_subblock_is_validated():
    bad = copy.deepcopy(GOOD)
    bad["serving"]["chaos"]["maintain"]["converged"] = False
    assert any("autonomy is broken" in e for e in validate_record(bad))

    bad = copy.deepcopy(GOOD)
    del bad["serving"]["chaos"]["maintain"]["passes"]
    assert any("passes" in e for e in validate_record(bad))

    bad = copy.deepcopy(GOOD)
    bad["serving"]["chaos"]["maintain"]["paused"] = "two"
    assert any("paused" in e for e in validate_record(bad))

    old = copy.deepcopy(GOOD)
    del old["serving"]["chaos"]["maintain"]
    assert validate_record(old) == []


GOOD_MULTICHIP = {
    "mode": "multichip",
    "metric": "multichip_annotate_speedup_8dev",
    "value": 1.9,
    "unit": "x_vs_1dev",
    "vs_baseline": 0.95,
    "backend": "cpu",
    "platform_pin": "cpu",
    "multichip": {
        "devices": [1, 2, 4, 8],
        "cores": 2,
        "label": "virtual-cpu host mesh (shared cores)",
        "annotate": {
            "rows": 524288, "width": 16, "speedup_at_max": 1.9,
            "per_device": [
                {"devices": d, "rows_per_sec": 1e6 * d, "seconds": 0.5,
                 "speedup": float(d), "efficiency": 1.0,
                 "byte_identical": True}
                for d in (1, 2, 4, 8)
            ],
        },
        "bulk_lookup": {
            "store_rows": 2097152, "queries": 65536,
            "speedup_at_max": 1.4,
            "per_device": [
                {"devices": d, "lookups_per_sec": 1e5 * d,
                 "seconds": 0.4, "speedup": float(d),
                 "efficiency": 1.0, "byte_identical": True}
                for d in (1, 2, 4, 8)
            ],
        },
    },
}


def test_multichip_record_validates():
    assert validate_record(GOOD_MULTICHIP) == []


def test_multichip_block_is_validated_strictly():
    # byte_identical=false is a hard failure at ANY device count
    rec = copy.deepcopy(GOOD_MULTICHIP)
    rec["multichip"]["annotate"]["per_device"][2]["byte_identical"] = False
    assert any("byte_identical" in e for e in validate_record(rec))
    # a missing per-device throughput is a failure
    rec = copy.deepcopy(GOOD_MULTICHIP)
    del rec["multichip"]["bulk_lookup"]["per_device"][0]["lookups_per_sec"]
    assert any("lookups_per_sec" in e for e in validate_record(rec))
    # the honesty fields are required: cores + label + device list
    for field in ("cores", "label", "devices"):
        rec = copy.deepcopy(GOOD_MULTICHIP)
        del rec["multichip"][field]
        assert any(field in e for e in validate_record(rec)), field
    # missing speedup_at_max fails
    rec = copy.deepcopy(GOOD_MULTICHIP)
    del rec["multichip"]["annotate"]["speedup_at_max"]
    assert any("speedup_at_max" in e for e in validate_record(rec))
    # a multichip-mode record with no block (and no error) fails
    rec = copy.deepcopy(GOOD_MULTICHIP)
    del rec["multichip"]
    assert any("no" in e and "multichip" in e for e in validate_record(rec))
    # ... unless it recorded an error (a failed run stays loadable)
    rec["error"] = "RuntimeError: backend died"
    assert validate_record(rec) == []
    # a skipped curve (too few devices) is a legitimate record
    rec = copy.deepcopy(GOOD_MULTICHIP)
    rec["multichip"] = {"skipped": "only 1 CPU device"}
    assert validate_record(rec) == []


def test_multichip_block_inside_full_record_validates():
    rec = copy.deepcopy(GOOD)
    rec["multichip"] = copy.deepcopy(GOOD_MULTICHIP["multichip"])
    assert validate_record(rec) == []
    rec["multichip"]["bulk_lookup"]["per_device"][3]["byte_identical"] = False
    assert any("byte_identical" in e for e in validate_record(rec))


def test_multichip_dryrun_wrappers_validate(tmp_path):
    # the historic MULTICHIP_r01–r05 shape stays loadable
    wrapper = {"n_devices": 8, "rc": 0, "ok": True, "skipped": False,
               "tail": "dryrun_multichip(8): ok\n"}
    p = tmp_path / "MULTICHIP_r99.json"
    p.write_text(json.dumps(wrapper))
    assert validate_file(str(p)) == []
    bad = dict(wrapper, ok="yes")
    p.write_text(json.dumps(bad))
    assert any("ok" in e for e in validate_file(str(p)))


def test_checker_cli_covers_committed_multichip_records():
    paths = sorted(glob.glob(os.path.join(ROOT, "MULTICHIP_*.json")))
    assert len(paths) >= 5  # r01–r05 are committed history
    for path in paths:
        assert validate_file(path) == [], path


def test_observability_block_is_validated_strictly():
    """The tracing-overhead gate: overhead over the bound (or a false
    within_bound) is a schema ERROR — the layer's cost is pinned by the
    record, not by hope."""
    bad = copy.deepcopy(GOOD)
    del bad["serving"]["observability"]["overhead_qps"]
    assert any("overhead_qps" in e for e in validate_record(bad))
    bad = copy.deepcopy(GOOD)
    del bad["serving"]["observability"]["armed"]
    assert any("armed" in e for e in validate_record(bad))
    bad = copy.deepcopy(GOOD)
    bad["serving"]["observability"]["overhead_qps"] = 0.08  # > 3%
    assert any("overhead bound" in e for e in validate_record(bad))
    # p99 over the RATIO but under the absolute noise floor: tolerated
    # (on a 10-40ms baseline 3% measures the container, not the code)
    noisy = copy.deepcopy(GOOD)
    noisy["serving"]["observability"]["overhead_p99"] = 0.08
    noisy["serving"]["observability"]["overhead_p99_ms"] = 0.9
    assert validate_record(noisy) == []
    # p99 over the ratio AND over the floor: rejected
    bad = copy.deepcopy(GOOD)
    bad["serving"]["observability"]["overhead_p99"] = 0.31
    bad["serving"]["observability"]["overhead_p99_ms"] = 8.2
    assert any("noise floor" in e for e in validate_record(bad))
    bad = copy.deepcopy(GOOD)
    bad["serving"]["observability"]["within_bound"] = False
    assert any("within_bound" in e for e in validate_record(bad))
    bad = copy.deepcopy(GOOD)
    bad["serving"]["observability"]["armed"] = {"p99_ms": 1.0}
    assert any("achieved_qps" in e for e in validate_record(bad))
    # historic records carry no observability block: still valid
    old = copy.deepcopy(GOOD)
    del old["serving"]["observability"]
    assert validate_record(old) == []
    # a failed leg records {"error": ...} and stays loadable
    failed = copy.deepcopy(GOOD)
    failed["serving"]["observability"] = {"error": "worker died"}
    assert validate_record(failed) == []


def test_slo_block_is_validated_strictly():
    """The health-plane overhead gate rides the same armed/unarmed
    contract as tracing, PLUS the alerts_sample proof: a record claiming
    the gate ran without showing a live /alerts body is rejected."""
    bad = copy.deepcopy(GOOD)
    del bad["serving"]["slo"]["overhead_qps"]
    assert any("slo" in e and "overhead_qps" in e
               for e in validate_record(bad))
    bad = copy.deepcopy(GOOD)
    bad["serving"]["slo"]["overhead_qps"] = 0.08  # > 3%
    assert any("health plane is too expensive" in e
               for e in validate_record(bad))
    bad = copy.deepcopy(GOOD)
    bad["serving"]["slo"]["within_bound"] = False
    assert any("failed its own overhead gate" in e
               for e in validate_record(bad))
    # the liveness proof: sample required, must be enabled, must carry
    # well-formed SLO rows
    bad = copy.deepcopy(GOOD)
    del bad["serving"]["slo"]["alerts_sample"]
    assert any("alerts_sample" in e for e in validate_record(bad))
    bad = copy.deepcopy(GOOD)
    bad["serving"]["slo"]["alerts_sample"]["enabled"] = False
    assert any("health plane was off" in e for e in validate_record(bad))
    bad = copy.deepcopy(GOOD)
    bad["serving"]["slo"]["alerts_sample"]["alerts"] = []
    assert any("at least one declared SLO row" in e
               for e in validate_record(bad))
    bad = copy.deepcopy(GOOD)
    bad["serving"]["slo"]["alerts_sample"]["alerts"][0]["state"] = "broken"
    assert any("valid state" in e for e in validate_record(bad))
    # p99 over the ratio but under the absolute floor: tolerated, same
    # container-noise escape the tracing gate carries
    noisy = copy.deepcopy(GOOD)
    noisy["serving"]["slo"]["overhead_p99"] = 0.08
    noisy["serving"]["slo"]["overhead_p99_ms"] = 0.9
    assert validate_record(noisy) == []
    # pre-PR-17 records carry no slo block: still valid; a failed leg
    # records {"error": ...} and stays loadable
    old = copy.deepcopy(GOOD)
    del old["serving"]["slo"]
    assert validate_record(old) == []
    failed = copy.deepcopy(GOOD)
    failed["serving"]["slo"] = {"error": "worker died"}
    assert validate_record(failed) == []


def test_bench_regress_watchdog_verdicts(tmp_path):
    """The regression watchdog: newest-vs-trailing-median on every
    tracked headline, with the thin-history escape and the exit-code
    contract (1 = regression, 0 = clean or insufficient history)."""
    import subprocess

    from check_bench_regress import evaluate_history, load_records

    def rec(n, qps, p99, value=250000.0):
        return {
            "n": n,
            "parsed": {
                "metric": "end_to_end", "unit": "variants/sec",
                "value": value,
                "serving": {"qps": qps, "p99_ms": p99},
            },
        }

    history = [rec(i, 3000.0 + 10 * i, 10.0) for i in range(1, 6)]
    ok = evaluate_history(history + [rec(6, 2900.0, 11.0)])
    assert ok["regressions"] == 0
    by_name = {c["series"]: c for c in ok["checks"]}
    assert by_name["serving.qps"]["verdict"] == "ok"
    assert by_name["serving.p99_ms"]["verdict"] == "ok"
    # a halved qps and a >2x p99 both trip
    regressed = evaluate_history(history + [rec(6, 100.0, 99.0)])
    names = {c["series"]: c["verdict"] for c in regressed["checks"]}
    assert names["serving.qps"] == "regression"
    assert names["serving.p99_ms"] == "regression"
    assert regressed["regressions"] >= 2
    # single-point series: thin, never a regression
    thin = evaluate_history([rec(1, 3000.0, 10.0)])
    assert thin["regressions"] == 0
    assert thin["thin"] == len(thin["checks"])
    # a serving error row carries no benchmark fact
    errored = [rec(1, 3000.0, 10.0)]
    errored[0]["parsed"]["serving"]["error"] = "died"
    assert all(not c["series"].startswith("serving.")
               for c in evaluate_history(errored)["checks"])
    # CLI contract: regression -> 1, thin/empty history -> 0
    bench_dir = tmp_path / "hist"
    bench_dir.mkdir()
    tool = os.path.join(ROOT, "tools", "check_bench_regress.py")
    for i, doc in enumerate(history + [rec(6, 100.0, 10.0)], start=1):
        (bench_dir / f"BENCH_r{i:02d}.json").write_text(json.dumps(doc))
    assert subprocess.run(
        [sys.executable, tool, "--dir", str(bench_dir)],
        capture_output=True,
    ).returncode == 1
    (bench_dir / "BENCH_r06.json").write_text(
        json.dumps(rec(6, 2900.0, 11.0))
    )
    assert subprocess.run(
        [sys.executable, tool, "--dir", str(bench_dir)],
        capture_output=True,
    ).returncode == 0
    # unreadable + parsed-null records are skipped, not fatal
    (bench_dir / "BENCH_r00.json").write_text("{not json")
    (bench_dir / "BENCH_r07.json").write_text(json.dumps(
        {"n": 7, "parsed": None}
    ))
    assert len(load_records(str(bench_dir))) == 6


def test_chaos_flight_subblock_is_validated():
    """The black-box gates ride the chaos record: a missing harvest or a
    parse failure is a schema error, and pre-PR-14 records (no flight
    sub-block) stay valid."""
    bad = copy.deepcopy(GOOD)
    bad["serving"]["chaos"]["flight"]["harvested_files"] = 0
    assert any("no black box was harvested" in e
               for e in validate_record(bad))
    bad = copy.deepcopy(GOOD)
    bad["serving"]["chaos"]["flight"]["parse_failures"] = 1
    assert any("failed to parse" in e for e in validate_record(bad))
    bad = copy.deepcopy(GOOD)
    del bad["serving"]["chaos"]["flight"]["harvested_requests"]
    assert any("harvested_requests" in e for e in validate_record(bad))
    bad = copy.deepcopy(GOOD)
    bad["serving"]["chaos"]["flight"] = "yes"
    assert any("flight: must be an object" in e
               for e in validate_record(bad))
    old = copy.deepcopy(GOOD)
    del old["serving"]["chaos"]["flight"]
    assert validate_record(old) == []


def test_replication_block_is_validated_strictly():
    # the hard verdict: acknowledged writes lost across the failover
    bad = copy.deepcopy(GOOD)
    bad["serving"]["replication"]["acked_missing"] = 3
    assert any("acked_missing" in e for e in validate_record(bad))

    # write availability never restored after promote
    bad = copy.deepcopy(GOOD)
    bad["serving"]["replication"]["post_promote_write_ok"] = False
    assert any("post_promote_write_ok" in e for e in validate_record(bad))

    # the lag distribution must be a distribution
    bad = copy.deepcopy(GOOD)
    bad["serving"]["replication"]["lag_p99_s"] = 0.0
    bad["serving"]["replication"]["lag_p50_s"] = 1.0
    assert any("lag_p99_s below lag_p50_s" in e
               for e in validate_record(bad))

    # follower reads that diverged from the leader's bytes
    bad = copy.deepcopy(GOOD)
    bad["serving"]["replication"]["wrong_bytes"] = 2
    assert any("wrong_bytes" in e for e in validate_record(bad))

    # required evidence fields
    for field in ("ship_mb_per_s", "lag_p50_s", "lag_p99_s",
                  "failover_s", "acked_missing"):
        bad = copy.deepcopy(GOOD)
        del bad["serving"]["replication"][field]
        assert any(field in e for e in validate_record(bad)), field

    # historic records (r01-r11) carry no replication block: still valid
    old = copy.deepcopy(GOOD)
    del old["serving"]["replication"]
    assert validate_record(old) == []
    # a failed leg records {"error": ...} and stays loadable
    failed = copy.deepcopy(GOOD)
    failed["serving"]["replication"] = {"error": "replication timed out"}
    assert validate_record(failed) == []


def test_chaos_repl_subblock_and_committed_repl_records():
    # the --repl chaos record's repl sub-block shares the contract
    bad = copy.deepcopy(GOOD)
    bad["serving"]["chaos"]["repl"] = {
        "max_lag_s": 3.0, "lag_p50_s": 0.0, "lag_p99_s": 0.2,
        "ship_mb_per_s": 0.01, "failover_s": 2.0, "acked_missing": 1,
    }
    assert any("acked_missing" in e for e in validate_record(bad))
    bad["serving"]["chaos"]["repl"]["acked_missing"] = 0
    assert validate_record(bad) == []

    # every committed REPL_r*.json must validate (recovered true, zero
    # violations, acked_missing 0)
    paths = sorted(glob.glob(os.path.join(ROOT, "REPL_*.json")))
    assert paths, "no committed REPL_r*.json failover certification"
    for path in paths:
        assert validate_file(path) == [], path


def test_committed_repl_record_rejects_loss(tmp_path):
    # a doctored record with failover loss must NOT validate
    with open(sorted(glob.glob(os.path.join(ROOT, "REPL_*.json")))[0]) as f:
        rec = json.load(f)
    rec["repl"]["acked_missing"] = 5
    rec["violations"] = ["acked-upsert loss across failover"]
    p = tmp_path / "REPL_r99.json"
    p.write_text(json.dumps(rec))
    errors = validate_file(str(p))
    assert any("acked_missing" in e for e in errors)
    assert any("violations" in e for e in errors)


def test_bench_regress_insufficient_history_cases(tmp_path):
    """A 0-, 1-, or 2-record history is 'insufficient history': the
    watchdog says so and exits 0 — a fresh checkout or a young repo must
    never fail the check chain, and a single prior is not a median worth
    judging against (even when that prior would scream regression)."""
    import subprocess

    from check_bench_regress import MIN_HISTORY

    def rec(n, qps, p99):
        return {
            "n": n,
            "parsed": {
                "metric": "end_to_end", "unit": "variants/sec",
                "value": 250000.0,
                "serving": {"qps": qps, "p99_ms": p99},
            },
        }

    assert MIN_HISTORY == 3
    tool = os.path.join(ROOT, "tools", "check_bench_regress.py")
    bench_dir = tmp_path / "hist"
    bench_dir.mkdir()
    # the 2-record case is the sharp edge: the newest point HALVES qps
    # against its single prior, which a premature judge would flag
    docs = [rec(1, 3000.0, 10.0), rec(2, 100.0, 99.0)]
    for count in (0, 1, 2):
        for i in range(count):
            (bench_dir / f"BENCH_r{i + 1:02d}.json").write_text(
                json.dumps(docs[i]))
        p = subprocess.run(
            [sys.executable, tool, "--dir", str(bench_dir), "--json"],
            capture_output=True, text=True,
        )
        assert p.returncode == 0, (count, p.stderr)
        assert "insufficient history" in p.stderr, (count, p.stderr)
        report = json.loads(p.stdout)
        assert report["checks"] == [] and report["regressions"] == 0
        assert report["insufficient_history"] == count
    # unparseable files do not count toward the minimum
    (bench_dir / "BENCH_r03.json").write_text("{not json")
    (bench_dir / "BENCH_r04.json").write_text(json.dumps(
        {"n": 4, "parsed": None}))
    p = subprocess.run(
        [sys.executable, tool, "--dir", str(bench_dir)],
        capture_output=True, text=True,
    )
    assert p.returncode == 0 and "insufficient history" in p.stderr
    # the third parseable record crosses the threshold: judged for real
    (bench_dir / "BENCH_r05.json").write_text(json.dumps(
        rec(5, 90.0, 99.0)))
    p = subprocess.run(
        [sys.executable, tool, "--dir", str(bench_dir)],
        capture_output=True, text=True,
    )
    assert "insufficient history" not in p.stderr
