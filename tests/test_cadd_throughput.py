"""Gated CADD join throughput pin: the sequential whole-table pass must
stay table-parse bound (>50k table rows/sec even on a busy CI core), not
regress to per-row Python.

The reference's equivalent is a server-side cursor + per-variant tabix
fetch (``load_cadd_scores.py:98-141``); this pass streams the scored table
once and joins on device-shaped columns.
"""

import gzip
import os
import random
import time

import numpy as np
import pytest

from annotatedvdb_tpu.loaders.cadd_loader import TpuCaddUpdater
from annotatedvdb_tpu.ops.hashing import allele_hash_jit
from annotatedvdb_tpu.store import AlgorithmLedger, VariantStore

pytestmark = pytest.mark.skipif(
    not os.environ.get("AVDB_SCALE_TEST"),
    reason="~1 min: set AVDB_SCALE_TEST=1",
)

N_VARIANTS = 100_000
TABLE_POSITIONS = 500_000  # x3 alt rows = 1.5M table rows


def test_cadd_sequential_join_throughput(tmp_path):
    rng = random.Random(7)
    store = VariantStore(width=16)
    sh = store.shard(1)
    pos = np.sort(np.array(
        rng.sample(range(10_000, 10_000 + TABLE_POSITIONS), N_VARIANTS),
        np.int32,
    ))
    ref = np.zeros((N_VARIANTS, 16), np.uint8)
    alt = np.zeros((N_VARIANTS, 16), np.uint8)
    bases = np.frombuffer(b"ACGT", np.uint8)
    ri = np.array([rng.randrange(4) for _ in range(N_VARIANTS)])
    off = np.array([rng.randrange(1, 4) for _ in range(N_VARIANTS)])
    rr = bases[ri]
    aa = bases[(ri + off) % 4]  # always a REAL base distinct from ref
    ref[:, 0] = rr
    alt[:, 0] = aa
    ones = np.ones(N_VARIANTS, np.int32)
    h = np.asarray(allele_hash_jit(ref, alt, ones, ones))
    sh.append({"pos": pos, "h": h, "ref_len": ones, "alt_len": ones},
              ref, alt)

    cadd_dir = str(tmp_path / "cadd")
    os.makedirs(cadd_dir)
    with gzip.open(os.path.join(cadd_dir, "whole_genome_SNVs.tsv.gz"),
                   "wt", compresslevel=1) as f:
        f.write("## CADD\n#Chrom\tPos\tRef\tAlt\tRawScore\tPHRED\n")
        lines = []
        for p in range(10_000, 10_000 + TABLE_POSITIONS):
            b = "ACGT"[p % 4]
            for a in "ACGT":
                if a != b:
                    lines.append(f"1\t{p}\t{b}\t{a}\t0.5\t10.0")
            if len(lines) > 200_000:
                f.write("\n".join(lines) + "\n")
                lines = []
        if lines:
            f.write("\n".join(lines) + "\n")
    with gzip.open(os.path.join(cadd_dir, "gnomad.genomes.r3.0.indel.tsv.gz"),
                   "wt") as f:
        f.write("## CADD\n#Chrom\tPos\tRef\tAlt\tRawScore\tPHRED\n")

    up = TpuCaddUpdater(store, AlgorithmLedger(str(tmp_path / "l.jsonl")),
                        cadd_dir, log=lambda *a: None)
    t0 = time.perf_counter()
    counters = up.update_all(commit=True)
    dt = time.perf_counter() - t0
    n_rows = 3 * TABLE_POSITIONS
    rate = n_rows / dt
    # exact match accounting: matching is by unordered allele set (the
    # reference's allele-set compare, cadd_updater.py:200-217), and the
    # table at each position carries (base, x) for every x != base — so a
    # variant matches iff the position's cycling base is one of its two
    # alleles
    table_base = np.frombuffer(b"ACGT", np.uint8)[pos % 4]
    expected = int(((rr == table_base) | (aa == table_base)).sum())
    assert counters["snv"] == expected
    assert counters["snv"] + counters["not_matched"] == N_VARIANTS
    assert rate > 50_000, f"CADD join regressed to {rate:,.0f} rows/s"
