"""Gated CADD join throughput pin: the sequential whole-table pass must
stay table-parse bound (>50k table rows/sec even on a busy CI core), not
regress to per-row Python.

The reference's equivalent is a server-side cursor + per-variant tabix
fetch (``load_cadd_scores.py:98-141``); this pass streams the scored table
once and joins on device-shaped columns.
"""

import os
import time

import pytest

from annotatedvdb_tpu.loaders.cadd_loader import TpuCaddUpdater
from annotatedvdb_tpu.store import AlgorithmLedger

pytestmark = pytest.mark.skipif(
    not os.environ.get("AVDB_SCALE_TEST"),
    reason="~1 min: set AVDB_SCALE_TEST=1",
)

N_VARIANTS = 100_000
TABLE_POSITIONS = 500_000  # x3 alt rows = 1.5M table rows


def test_cadd_sequential_join_throughput(tmp_path):
    from annotatedvdb_tpu.io.synth import synthetic_cadd_setup

    cadd_dir = str(tmp_path / "cadd")
    # shared fixture builder: the bench's cadd_join leg uses the SAME
    # setup, so the bench always measures exactly what this gate pins
    store, expected = synthetic_cadd_setup(
        cadd_dir, N_VARIANTS, TABLE_POSITIONS
    )

    up = TpuCaddUpdater(store, AlgorithmLedger(str(tmp_path / "l.jsonl")),
                        cadd_dir, log=lambda *a: None)
    t0 = time.perf_counter()
    counters = up.update_all(commit=True)
    dt = time.perf_counter() - t0
    n_rows = 3 * TABLE_POSITIONS
    rate = n_rows / dt
    assert counters["snv"] == expected
    assert counters["snv"] + counters["not_matched"] == N_VARIANTS
    assert rate > 50_000, f"CADD join regressed to {rate:,.0f} rows/s"
