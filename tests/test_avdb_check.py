"""Analyzer unit tests: every rule family pinned by checked-in fixture
files with expected (code, line) pairs, plus suppression semantics, the
project-audit codes (driven through synthetic registries), the --json
schema, and a self-hosting smoke test.

Fixture convention (``tests/data/analysis_fixtures/``): a violation line
carries a trailing ``# EXPECT: <CODE>[, <CODE>...]`` marker; the test
asserts the analyzer reports EXACTLY those (line, code) pairs for the
file — so a rule that stops firing (or starts over-firing) fails here
before it silently stops guarding the tree.  A new rule family lands with
a fixture file the same way a new fault point lands with a matrix case.
"""

import json
import os
import re
import subprocess
import sys

import pytest

from annotatedvdb_tpu.analysis import run_paths
from annotatedvdb_tpu.analysis.core import (
    FileContext,
    Project,
    ProjectFacts,
    find_repo_root,
)

REPO = find_repo_root(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "data", "analysis_fixtures")
_EXPECT_RE = re.compile(r"#\s*EXPECT:\s*([A-Z0-9,\s]+)")


def expected_pairs(path):
    """{(line, code)} parsed from the fixture's EXPECT markers."""
    out = set()
    with open(path) as f:
        for i, line in enumerate(f, start=1):
            m = _EXPECT_RE.search(line)
            if not m:
                continue
            for code in m.group(1).split(","):
                code = code.strip()
                if code:
                    out.add((i, code))
    return out


def found_pairs(path, **kwargs):
    findings, n_files = run_paths([path], **kwargs)
    assert n_files == 1
    return {(f.line, f.code) for f in findings}, findings


FIXTURE_FILES = [
    "trace_safety_viol.py",
    "lock_viol.py",
    "registry_viol.py",
    "env_viol.py",
    "hygiene_viol.py",
    "async_viol.py",
]


@pytest.mark.parametrize("name", FIXTURE_FILES)
def test_fixture_findings_match_markers_exactly(name):
    path = os.path.join(FIXTURES, name)
    want = expected_pairs(path)
    assert want, f"{name}: fixture has no EXPECT markers"
    got, findings = found_pairs(path)
    assert got == want, (
        f"{name}: findings != markers\n  extra: {sorted(got - want)}\n"
        f"  missing: {sorted(want - got)}\n  raw: "
        + "\n  ".join(f.render() for f in findings)
    )


def test_cli_contract_fixture():
    """AVDB501/502 need the loader-CLI list pointed at the fixture."""
    path = os.path.join(FIXTURES, "cli_viol.py")
    want = expected_pairs(path)
    got, findings = found_pairs(
        path, loader_clis=("tests/data/analysis_fixtures/cli_viol.py",)
    )
    assert got == want, (got, want)


def test_fixtures_fail_via_cli_entrypoint():
    """Acceptance: the CLI exits non-zero on each checked-in fixture."""
    for name in FIXTURE_FILES + ["cli_viol.py"]:
        cmd = [sys.executable, os.path.join(REPO, "tools", "avdb_check.py"),
               os.path.join(FIXTURES, name)]
        if name == "cli_viol.py":
            cmd += ["--loaderCli", "tests/data/analysis_fixtures/cli_viol.py"]
        p = subprocess.run(cmd, capture_output=True, text=True, cwd=REPO)
        assert p.returncode == 1, (name, p.returncode, p.stdout, p.stderr)


def test_every_rule_family_covered_by_fixtures():
    """One fixture-backed assertion per family, by construction."""
    families = set()
    tree_fixtures = [
        os.path.join("parity_tree", "serve", "aio.py"),
        os.path.join("twins_tree", "annotatedvdb_tpu", "ops",
                     "__init__.py"),
        os.path.join("twins_tree", "annotatedvdb_tpu", "ops",
                     "kernels.py"),
        os.path.join("durability_tree", "store", "bad_writer.py"),
        os.path.join("durability_tree", "serve", "http.py"),
        os.path.join("noqa_tree", "pipeline.py"),
    ]
    for name in FIXTURE_FILES + ["cli_viol.py"] + tree_fixtures:
        for _line, code in expected_pairs(os.path.join(FIXTURES, name)):
            families.add(code[:-2])  # AVDB101 -> AVDB1, AVDB1001 -> AVDB10
    assert families == {"AVDB1", "AVDB2", "AVDB3", "AVDB4", "AVDB5",
                        "AVDB6", "AVDB7", "AVDB8", "AVDB9", "AVDB10"}


# ---------------------------------------------------------------------------
# tree fixtures: the parity pair (AVDB8xx) and the twins registry (AVDB9xx)
# are cross-file rules, so their fixtures are little trees, scanned whole


def _tree_pairs(tree, files):
    want = {}
    for rel in files:
        path = os.path.join(tree, rel)
        for line, code in expected_pairs(path):
            want.setdefault(rel.replace(os.sep, "/"), set()).add(
                (line, code)
            )
    return want


def test_parity_tree_fixture():
    tree = os.path.join(FIXTURES, "parity_tree")
    findings, n = run_paths([tree], root=tree)
    assert n == 2
    got = {}
    for f in findings:
        rel = f.path.replace("\\", "/").split("parity_tree/")[-1]
        got.setdefault(rel, set()).add((f.line, f.code))
    want = _tree_pairs(tree, [
        os.path.join("serve", "http.py"), os.path.join("serve", "aio.py"),
    ])
    assert got == want, (got, want)


def test_parity_silent_on_single_front_end():
    """A scan holding only one front-end file cannot judge parity."""
    tree = os.path.join(FIXTURES, "parity_tree")
    findings, n = run_paths(
        [os.path.join(tree, "serve", "aio.py")], root=tree
    )
    assert n == 1
    assert [f for f in findings if f.code.startswith("AVDB8")] == []


def test_twins_tree_fixture():
    tree = os.path.join(FIXTURES, "twins_tree")
    findings, n = run_paths([tree], root=tree)
    assert n == 3
    got = {}
    for f in findings:
        rel = f.path.replace("\\", "/").split("twins_tree/")[-1]
        got.setdefault(rel, set()).add((f.line, f.code))
    want = _tree_pairs(tree, [
        os.path.join("annotatedvdb_tpu", "ops", "__init__.py"),
        os.path.join("annotatedvdb_tpu", "ops", "kernels.py"),
    ])
    assert got == want, (got, want)


def test_twins_silent_without_registry_scan():
    """Scanning one ops module alone (the registry not in the scan) must
    not fire the twin audits — AVDB9xx needs ops/__init__.py."""
    tree = os.path.join(FIXTURES, "twins_tree")
    findings, _n = run_paths(
        [os.path.join(tree, "annotatedvdb_tpu", "ops", "kernels.py")],
        root=tree,
    )
    assert [f for f in findings if f.code.startswith("AVDB9")] == []


# ---------------------------------------------------------------------------
# durability tree (AVDB10xx) and the stale-noqa tree (AVDB604)


def test_durability_tree_fixture():
    tree = os.path.join(FIXTURES, "durability_tree")
    findings, n = run_paths([tree], root=tree)
    assert n == 3
    got = {}
    for f in findings:
        rel = f.path.replace("\\", "/").split("durability_tree/")[-1]
        got.setdefault(rel, set()).add((f.line, f.code))
    want = _tree_pairs(tree, [
        os.path.join("store", "bad_writer.py"),
        os.path.join("serve", "http.py"),
    ])
    assert got == want, (got, want)


def test_durability_fsck_xref_silent_without_fsck_scan():
    """AVDB1002/1003 cross-reference fsck's attribution codes; a scan
    that does not include store/fsck.py cannot decide them."""
    tree = os.path.join(FIXTURES, "durability_tree")
    findings, _n = run_paths(
        [os.path.join(tree, "store", "bad_writer.py")], root=tree
    )
    codes = {f.code for f in findings}
    assert "AVDB1002" not in codes and "AVDB1003" not in codes
    # the per-function durability codes stay live on the partial scan
    assert {"AVDB1001", "AVDB1004", "AVDB1005"} <= codes


def test_durability_fsck_xref_silent_in_diff_mode():
    """audit=False (--diff) force-disables the fsck cross-reference even
    when store/fsck.py happens to be in the scan set."""
    tree = os.path.join(FIXTURES, "durability_tree")
    findings, _n = run_paths([tree], root=tree, audit=False)
    codes = {f.code for f in findings}
    assert "AVDB1002" not in codes and "AVDB1003" not in codes
    assert "AVDB1001" in codes


def test_noqa_tree_fixture():
    """The stale and blanket suppressions are flagged AVDB604; the live
    AVDB602 suppression is honored (no AVDB602 in the output)."""
    tree = os.path.join(FIXTURES, "noqa_tree")
    findings, n = run_paths([tree], root=tree)
    assert n == 3
    got = {}
    for f in findings:
        rel = f.path.replace("\\", "/").split("noqa_tree/")[-1]
        got.setdefault(rel, set()).add((f.line, f.code))
    want = _tree_pairs(tree, ["pipeline.py"])
    assert got == want, (got, want)


def test_noqa_audit_gated_to_tree_scans():
    """A partial scan (no config.py / no tests/) must not judge
    staleness — the suppressed code might fire only on a full scan."""
    tree = os.path.join(FIXTURES, "noqa_tree")
    findings, _n = run_paths(
        [os.path.join(tree, "pipeline.py")], root=tree
    )
    assert [f for f in findings if f.code == "AVDB604"] == []


def test_blanket_noqa_cannot_self_suppress_avdb604(tmp_path):
    """A blanket noqa covers every code EXCEPT AVDB604 — a suppression
    must not certify itself; silencing the audit takes an explicit
    [AVDB604] list."""
    ctx = FileContext(
        str(tmp_path / "f.py"),
        "x = 1  # avdb: noqa\n"
        "y = 2  # avdb: noqa[AVDB604] -- deliberate fixture\n",
    )
    assert not ctx.suppressed(1, "AVDB604")
    assert ctx.suppressed(1, "AVDB999")
    assert ctx.suppressed(2, "AVDB604")


# ---------------------------------------------------------------------------
# suppression semantics


def test_noqa_parsing_forms(tmp_path):
    src = (
        "x = 1  # avdb: noqa[AVDB601]\n"
        "y = 2  # avdb: noqa[AVDB101, AVDB102] -- reason here\n"
        "z = 3  # avdb: noqa\n"
        "w = 4\n"
    )
    ctx = FileContext(str(tmp_path / "f.py"), src)
    assert ctx.suppressed(1, "AVDB601")
    assert not ctx.suppressed(1, "AVDB602")
    assert ctx.suppressed(2, "AVDB101") and ctx.suppressed(2, "AVDB102")
    assert ctx.suppressed(3, "AVDB999")  # blanket
    assert not ctx.suppressed(4, "AVDB601")


def test_noqa_honored_identically_for_relative_and_absolute_scans(tmp_path,
                                                                  monkeypatch):
    """Suppression is keyed by absolute path on both sides: a noqa must
    work the same under `avdb_check .` and `avdb_check /abs/tree` (it was
    once silently ignored for absolute scans of project-level findings)."""
    bad = tmp_path / "bad.py"
    bad.write_text(
        "def f(x=[]):  # avdb: noqa[AVDB603] -- fixture\n    return x\n"
    )
    abs_findings, _ = run_paths([str(bad)])
    monkeypatch.chdir(tmp_path)
    rel_findings, _ = run_paths(["bad.py"])
    assert abs_findings == [] and rel_findings == []


def test_fixture_data_skipped_only_under_tests(tmp_path):
    """Only tests/data is exempt from scanning — a package dir that merely
    happens to be NAMED `data` must still be analyzed."""
    from annotatedvdb_tpu.analysis import iter_python_files

    (tmp_path / "tests" / "data").mkdir(parents=True)
    (tmp_path / "tests" / "data" / "fixture.py").write_text("x = 1\n")
    (tmp_path / "pkg" / "data").mkdir(parents=True)
    (tmp_path / "pkg" / "data" / "module.py").write_text("x = 1\n")
    files = [os.path.relpath(f, tmp_path)
             for f in iter_python_files([str(tmp_path)])]
    assert os.path.join("pkg", "data", "module.py") in files
    assert os.path.join("tests", "data", "fixture.py") not in files


def test_noqa_suppresses_finding_end_to_end(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "try:\n    pass\n"
        "except Exception:  # avdb: noqa[AVDB602] -- fixture\n    pass\n"
    )
    findings, _ = run_paths([str(bad)])
    assert findings == []


# ---------------------------------------------------------------------------
# project-audit codes (AVDB302/305/402/403) — driven through synthetic
# registries so the shipped tree (which is clean) still proves they fire


def _project(**over):
    base = dict(
        root=REPO, readme="", fault_points=frozenset(),
        fault_matrix_src="", env_declared={}, loader_clis=(),
        flag_registrars={},
    )
    base.update(over)
    return Project(**base)


def _audit_facts():
    facts = ProjectFacts()
    facts.full_registry_scan = True
    facts.tree_scan = True
    return facts


def test_avdb302_uncovered_fault_point():
    from annotatedvdb_tpu.analysis import rules_registry

    project = _project(
        fault_points=frozenset({"a.b", "c.d"}),
        fault_matrix_src="only a.b is exercised here",
    )
    findings = rules_registry.finalize(_audit_facts(), project)
    assert [f.code for f in findings] == ["AVDB302"]
    assert "c.d" in findings[0].message


def test_avdb305_readme_metric_reference():
    from annotatedvdb_tpu.analysis import rules_registry
    from annotatedvdb_tpu.analysis.rules_registry import MetricReg

    facts = _audit_facts()
    facts.metric_regs = {
        "avdb_real_rows_total": [MetricReg(
            "avdb_real_rows_total", False, "counter", (), "m.py", 1
        )],
    }
    project = _project(
        readme="`avdb_real_rows_total` exists; `avdb_ghost_total` not; "
               "`avdb_check` is a tool, not a metric",
    )
    findings = rules_registry.finalize(facts, project)
    assert [f.code for f in findings] == ["AVDB305"]
    assert "avdb_ghost_total" in findings[0].message


def test_avdb402_403_env_audit():
    from annotatedvdb_tpu.analysis import rules_env

    facts = _audit_facts()
    facts.env_reads = [("x.py", 1, "AVDB_USED")]
    project = _project(
        env_declared={
            "AVDB_USED": "doc", "AVDB_UNDOCUMENTED": "doc",
            "AVDB_STALE": "doc",
        },
        readme="AVDB_USED and AVDB_STALE are in the readme",
    )
    findings = rules_env.finalize(facts, project)
    by_code = {}
    for f in findings:
        by_code.setdefault(f.code, []).append(f.message)
    assert sorted(by_code) == ["AVDB402", "AVDB403"]
    assert any("AVDB_UNDOCUMENTED" in m for m in by_code["AVDB402"])
    # AVDB_STALE: documented but never read; AVDB_UNDOCUMENTED is also
    # unread (bench.py supplements reads, neither appears there)
    assert any("AVDB_STALE" in m for m in by_code["AVDB403"])


def test_audit_codes_gated_off_on_partial_scans():
    """Scanning a fixture subtree must not audit the whole project."""
    from annotatedvdb_tpu.analysis import rules_env, rules_registry

    facts = ProjectFacts()  # full_registry_scan stays False
    project = _project(
        fault_points=frozenset({"never.tested"}),
        fault_matrix_src="no coverage here",
        env_declared={"AVDB_NEVER_READ": "doc"},
        readme="nothing",
    )
    codes = [f.code for f in rules_registry.finalize(facts, project)]
    codes += [f.code for f in rules_env.finalize(facts, project)]
    assert "AVDB302" not in codes
    assert "AVDB402" not in codes and "AVDB403" not in codes


# ---------------------------------------------------------------------------
# --diff mode: the fast pre-commit scan


def test_diff_mode_is_clean_and_audit_free():
    """``--diff HEAD`` analyzes only changed files and must stay clean on
    a tree the full gate accepts: the whole-project audit codes
    (AVDB302/305/402/403/9xx) gate OFF — a partial scan that happens to
    include config.py must not judge the files it did not scan."""
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "avdb_check.py"),
         "--diff", "HEAD", "--json"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert p.returncode == 0, p.stdout + p.stderr
    report = json.loads(p.stdout)
    assert report["findings"] == []


def test_diff_mode_rejects_bad_rev_and_path_mix():
    tool = os.path.join(REPO, "tools", "avdb_check.py")
    p = subprocess.run(
        [sys.executable, tool, "--diff", "no-such-rev-zzz"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert p.returncode == 2
    assert "failed" in p.stderr
    p = subprocess.run(
        [sys.executable, tool, "--diff", "HEAD", "somepath"],
        capture_output=True, text=True, cwd=REPO,
    )
    assert p.returncode == 2
    assert "exclusive" in p.stderr


def test_diff_mode_audit_gating_via_api():
    """audit=False keeps call-site codes firing but silences the
    project audits even when config.py is in the scan set."""
    config = os.path.join(REPO, "annotatedvdb_tpu", "config.py")
    bad = os.path.join(FIXTURES, "hygiene_viol.py")
    findings, _n = run_paths([config, bad], audit=False)
    codes = {f.code for f in findings}
    assert any(c.startswith("AVDB6") for c in codes)  # per-file still on
    assert not any(
        c in {"AVDB302", "AVDB305", "AVDB402", "AVDB403"} or
        c.startswith("AVDB9") for c in codes
    ), codes


# ---------------------------------------------------------------------------
# --json schema (alongside tools/check_bench_schema.py conventions)


def test_json_output_schema():
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "avdb_check.py"),
         "--json", os.path.join(FIXTURES, "hygiene_viol.py")],
        capture_output=True, text=True, cwd=REPO,
    )
    assert p.returncode == 1
    report = json.loads(p.stdout)
    assert report["version"] == 1
    assert report["exit_code"] == 1
    assert isinstance(report["files_scanned"], int)
    assert report["files_scanned"] == 1
    assert isinstance(report["findings"], list) and report["findings"]
    for f in report["findings"]:
        assert set(f) == {"code", "path", "line", "message", "hint"}
        assert re.fullmatch(r"AVDB\d{3,4}", f["code"])
        assert isinstance(f["line"], int) and f["line"] >= 1
        assert f["message"] and f["hint"]


def test_json_clean_tree_shape():
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "avdb_check.py"),
         "--json", os.path.join(REPO, "annotatedvdb_tpu", "analysis")],
        capture_output=True, text=True, cwd=REPO,
    )
    assert p.returncode == 0, p.stdout + p.stderr
    report = json.loads(p.stdout)
    assert report["findings"] == [] and report["exit_code"] == 0


# ---------------------------------------------------------------------------
# self-hosting smoke: the analyzer over the package is clean via the API
# (the full-tree CLI gate lives in tests/test_static_checks.py)


def test_self_hosting_package_clean():
    findings, n_files = run_paths([os.path.join(REPO, "annotatedvdb_tpu")])
    assert n_files > 50
    assert findings == [], "\n".join(f.render() for f in findings)


def test_syntax_error_is_a_finding_not_a_crash(tmp_path):
    bad = tmp_path / "broken.py"
    bad.write_text("def f(:\n")
    findings, _ = run_paths([str(bad)])
    assert [f.code for f in findings] == ["AVDB001"]
