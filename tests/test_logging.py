"""Operational-logging parity: per-input log files + --logAfter cadence
(reference ``load_vcf_file.py:29-47``)."""

import logging
import subprocess
import sys

import pytest

from annotatedvdb_tpu.utils.logging import (
    ExitOnCriticalHandler,
    load_logger,
)


def test_load_logger_writes_per_input_file(tmp_path):
    inp = tmp_path / "x.vcf"
    inp.write_text("")
    log, logger, path = load_logger(str(inp), "load-vcf")
    assert path == str(inp) + "-load-vcf.log"
    log("hello", 42)
    log("world")
    content = (tmp_path / "x.vcf-load-vcf.log").read_text()
    assert "hello 42" in content and "world" in content
    # re-opening for the same input must not duplicate handlers
    log2, logger2, _ = load_logger(str(inp), "load-vcf")
    log2("once")
    assert (tmp_path / "x.vcf-load-vcf.log").read_text().count("once") == 1


def test_critical_exits(tmp_path, capsys):
    _, logger, _ = load_logger(str(tmp_path / "y.vcf"), "t")
    with pytest.raises(SystemExit):
        logger.critical("fatal parse state")


def test_log_after_cadence(tmp_path):
    """The loader emits counter lines every logAfter input lines."""
    from annotatedvdb_tpu.loaders import TpuVcfLoader
    from annotatedvdb_tpu.store import AlgorithmLedger, VariantStore

    lines = ["##fileformat=VCFv4.2",
             "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO"]
    for i in range(100):
        lines.append(f"1\t{1000 + i * 10}\t.\tA\tG\t.\t.\t.")
    vcf = tmp_path / "c.vcf"
    vcf.write_text("\n".join(lines) + "\n")

    logs = []
    store = VariantStore(width=16)
    ledger = AlgorithmLedger(str(tmp_path / "l.jsonl"))
    loader = TpuVcfLoader(
        store, ledger, batch_size=20, log=lambda *a: logs.append(" ".join(map(str, a))),
        log_after=20,
    )
    loader.load_file(str(vcf), commit=True)
    progress = [m for m in logs if m.startswith("PARSED")]
    # 100 lines / cadence 20 -> ~5 progress lines with counters + stage rates
    assert 4 <= len(progress) <= 6
    assert "counters" in progress[0] and "annotate" in progress[0]


def test_cli_writes_log_file(tmp_path):
    vcf = tmp_path / "in.vcf"
    vcf.write_text(
        "##fileformat=VCFv4.2\n"
        "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\n"
        "1\t100\t.\tA\tG\t.\t.\t.\n"
    )
    res = subprocess.run(
        [sys.executable, "-m", "annotatedvdb_tpu.cli.load_vcf",
         "--fileName", str(vcf), "--storeDir", str(tmp_path / "vdb"),
         "--commit", "--logAfter", "1"],
        capture_output=True, text=True, timeout=600,
    )
    assert res.returncode == 0, res.stderr
    log_file = tmp_path / "in.vcf-load-vcf.log"
    assert log_file.exists()
    content = log_file.read_text()
    assert "COMMITTED" in content and "stage breakdown" in content
