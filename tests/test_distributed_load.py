"""Distributed end-to-end load: the mesh path through the production loader.

VERDICT round 2 item 1's done-criterion: a multi-device CPU test loads a
sorted single-chromosome VCF end-to-end through the same code path the CLI
uses (``TpuVcfLoader(mesh=...)``), asserting zero drops and store parity
with the single-device load.  Chromosome-sorted input is the adversarial
case for resharding — every row routes to one owner — which the lossless
default capacity must absorb.
"""

import random

import numpy as np
import pytest

from annotatedvdb_tpu.loaders import TpuVcfLoader
from annotatedvdb_tpu.store import AlgorithmLedger, VariantStore

BASES = "ACGT"


def write_sorted_vcf(path, n=1000, chrom="22", seed=5):
    rng = random.Random(seed)
    lines = ["##fileformat=VCFv4.2",
             "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO"]
    pos = 10_000
    for i in range(n):
        pos += rng.randint(1, 40)
        kind = rng.randrange(4)
        if kind == 0:
            ref = rng.choice(BASES)
            alt = rng.choice(BASES.replace(ref, ""))
        elif kind == 1:
            ref = rng.choice(BASES)
            alt = ref + "".join(rng.choice(BASES) for _ in range(rng.randint(1, 6)))
        elif kind == 2:
            alt = rng.choice(BASES)
            ref = alt + "".join(rng.choice(BASES) for _ in range(rng.randint(1, 6)))
        else:
            ref = "".join(rng.choice(BASES) for _ in range(3))
            alt = "".join(rng.choice(BASES) for _ in range(3))
        lines.append(f"{chrom}\t{pos}\trs{i}\t{ref}\t{alt}\t.\t.\tRS={i}")
    # long-allele tail exercises the host-fallback path through the exchange
    lines.append(f"{chrom}\t{pos + 50}\t.\t{'A' * 60}\tG\t.\t.\t.")
    path.write_text("\n".join(lines) + "\n")
    return str(path)


def load_with(tmp_path, vcf, tag, mesh):
    store = VariantStore(width=49)
    ledger = AlgorithmLedger(str(tmp_path / f"ledger_{tag}.jsonl"))
    loader = TpuVcfLoader(store, ledger, mesh=mesh, batch_size=256,
                          log=lambda *a: None)
    counters = loader.load_file(vcf, commit=True)
    return store, counters


def test_mesh_load_matches_single_device(tmp_path):
    """Sorted single-chromosome VCF: mesh load == single-device load."""
    from annotatedvdb_tpu.parallel import make_mesh

    vcf = write_sorted_vcf(tmp_path / "chr22.vcf")
    s1, c1 = load_with(tmp_path, vcf, "single", mesh=None)
    s8, c8 = load_with(tmp_path, vcf, "mesh", mesh=make_mesh(8))

    for key in ("line", "variant", "skipped", "duplicates"):
        assert c1[key] == c8[key], f"counter {key}: {c1[key]} != {c8[key]}"
    assert s1.n == s8.n == c1["variant"]

    sh1, sh8 = s1.shard(22), s8.shard(22)
    sh1.compact(), sh8.compact()
    for col in ("pos", "h", "ref_len", "alt_len", "ref_snp", "bin_level",
                "leaf_bin", "needs_digest"):
        np.testing.assert_array_equal(sh1.cols[col], sh8.cols[col], err_msg=col)
    np.testing.assert_array_equal(sh1.ref, sh8.ref)
    np.testing.assert_array_equal(sh1.alt, sh8.alt)
    # record PKs (including the digest-tail row) agree row-for-row
    for i in range(0, sh1.n, 97):
        assert sh1.primary_key(i) == sh8.primary_key(i)
    digest1 = [pk for pk in sh1.digest_pk if pk is not None]
    digest8 = [pk for pk in sh8.digest_pk if pk is not None]
    assert digest1 == digest8 and len(digest1) == 1


def test_mesh_load_multi_chromosome(tmp_path):
    """Interleaved chromosomes route across owners without loss."""
    from annotatedvdb_tpu.parallel import make_mesh

    rng = random.Random(11)
    lines = ["##fileformat=VCFv4.2",
             "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO"]
    per_chrom = {}
    for i in range(800):
        chrom = rng.choice([str(c) for c in range(1, 23)] + ["X", "Y", "M"])
        pos = per_chrom.get(chrom, 1000) + rng.randint(1, 50)
        per_chrom[chrom] = pos
        ref = rng.choice(BASES)
        alt = rng.choice(BASES.replace(ref, ""))
        lines.append(f"{chrom}\t{pos}\t.\t{ref}\t{alt}\t.\t.\t.")
    vcf = tmp_path / "multi.vcf"
    vcf.write_text("\n".join(lines) + "\n")

    s1, c1 = load_with(tmp_path, str(vcf), "single", mesh=None)
    s4, c4 = load_with(tmp_path, str(vcf), "mesh", mesh=make_mesh(4))
    assert c1["variant"] == c4["variant"]
    assert sorted(s1.shards) == sorted(s4.shards)
    for code in s1.shards:
        a, b = s1.shard(code), s4.shard(code)
        a.compact(), b.compact()
        np.testing.assert_array_equal(a.cols["pos"], b.cols["pos"])
        np.testing.assert_array_equal(a.cols["h"], b.cols["h"])


def _write_vep_json(path, vcf_path, n):
    import json as _json

    written = 0
    with open(vcf_path) as src, open(path, "w") as out:
        for line in src:
            if line.startswith("#"):
                continue
            chrom, pos, vid, ref, alt = line.split("\t")[:5]
            alt0 = alt.split(",")[0]
            p = 0
            while p < min(len(ref), len(alt0)) and ref[p] == alt0[p]:
                p += 1
            norm = alt0[p:] or "-"
            out.write(_json.dumps({
                "input": f"{chrom}\t{pos}\t{vid}\t{ref}\t{alt0}",
                "most_severe_consequence": "missense_variant",
                "transcript_consequences": [
                    {"consequence_terms": ["missense_variant"],
                     "variant_allele": norm, "gene_id": "ENSG1"}],
                "colocated_variants": [
                    {"id": vid, "allele_string": f"{ref}/{alt0}",
                     "frequencies": {norm: {"gnomad": 0.25}}}],
            }) + "\n")
            written += 1
            if written >= n:
                break
    # two results for variants NOT in the store (not_found accounting)
    with open(path, "a") as out:
        for k, (c, p) in enumerate((("1", 999_000_111), ("2", 999_000_222))):
            out.write(_json.dumps({
                "input": f"{c}\t{p}\tnovel{k}\tA\tG",
                "most_severe_consequence": "intron_variant",
                "transcript_consequences": [
                    {"consequence_terms": ["intron_variant"],
                     "variant_allele": "G"}],
            }) + "\n")
    return written + 2


def test_mesh_vep_update_matches_single_device(tmp_path):
    """VEP update via the sharded identity step == host-side updates:
    same counters, same stored annotation values row for row (VERDICT r4
    item 3 — the update legs' distributed path)."""
    from annotatedvdb_tpu.conseq import ConsequenceRanker
    from annotatedvdb_tpu.loaders.vep_loader import TpuVepLoader
    from annotatedvdb_tpu.parallel import make_mesh

    rng = random.Random(31)
    lines = ["##fileformat=VCFv4.2",
             "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO"]
    per_chrom = {}
    for i in range(600):
        chrom = rng.choice([str(c) for c in range(1, 23)] + ["X"])
        pos = per_chrom.get(chrom, 1000) + rng.randint(1, 50)
        per_chrom[chrom] = pos
        ref = rng.choice(BASES)
        alt = rng.choice(BASES.replace(ref, ""))
        lines.append(f"{chrom}\t{pos}\trs{i}\t{ref}\t{alt}\t.\t.\tRS={i}")
    # over-width row: exercises the mesh path's host re-resolve tail
    lines.append(f"22\t{per_chrom.get('22', 1000) + 60}\t.\t{'A' * 60}\tG\t.\t.\t.")
    vcf = tmp_path / "m.vcf"
    vcf.write_text("\n".join(lines) + "\n")

    vep_json = str(tmp_path / "m.vep.json")
    n_results = _write_vep_json(vep_json, str(vcf), 400)

    results = {}
    for tag, mesh in (("single", None), ("mesh", make_mesh(8))):
        store = VariantStore(width=49)
        ledger = AlgorithmLedger(str(tmp_path / f"vl_{tag}.jsonl"))
        TpuVcfLoader(store, ledger, batch_size=256,
                     log=lambda *a: None).load_file(str(vcf), commit=True)
        vl = TpuVepLoader(store, ledger, ConsequenceRanker(),
                          datasource="dbSNP", mesh=mesh, log=lambda *a: None)
        counters = vl.load_file(vep_json, commit=True)
        results[tag] = (store, counters)

    (s1, c1), (s8, c8) = results["single"], results["mesh"]
    for key in ("line", "variant", "update", "not_found", "skipped"):
        assert c1[key] == c8[key], f"counter {key}: {c1[key]} != {c8[key]}"
    assert c1["update"] == n_results - 2  # both novels miss
    assert sorted(s1.shards) == sorted(s8.shards)
    for code in s1.shards:
        a, b = s1.shard(code), s8.shard(code)
        assert a.n == b.n
        for i in range(a.n):
            for col in ("adsp_most_severe_consequence",
                        "adsp_ranked_consequences", "allele_frequencies",
                        "vep_output"):
                va, vb = a.get_ann(col, i), b.get_ann(col, i)
                assert va == vb, (code, i, col)


def test_mesh_cadd_join_matches_sequential(tmp_path):
    """CADD table pass via the sharded identity step (both allele
    orientations) == the sequential per-block join kernel: same counters,
    same stored cadd_scores row for row (VERDICT r4 item 3, CADD half)."""
    from annotatedvdb_tpu.io.synth import synthetic_cadd_setup
    from annotatedvdb_tpu.loaders.cadd_loader import TpuCaddUpdater
    from annotatedvdb_tpu.parallel import make_mesh

    results = {}
    for tag, mesh in (("seq", None), ("mesh", make_mesh(8))):
        cadd_dir = str(tmp_path / f"cadd_{tag}")
        store, expected = synthetic_cadd_setup(cadd_dir, 3000, 9000)
        up = TpuCaddUpdater(
            store, AlgorithmLedger(str(tmp_path / f"cl_{tag}.jsonl")),
            cadd_dir, mesh=mesh, log=lambda *a: None,
        )
        counters = up.update_all(commit=True)
        results[tag] = (store, counters, expected)

    (s1, c1, exp), (s8, c8, _) = results["seq"], results["mesh"]
    for key in ("snv", "indel", "update", "not_matched", "skipped"):
        assert c1[key] == c8[key], f"counter {key}: {c1[key]} != {c8[key]}"
    assert c1["snv"] == exp  # the synthetic ground truth
    a, b = s1.shard(1), s8.shard(1)
    assert a.n == b.n
    for i in range(a.n):
        va, vb = a.get_ann("cadd_scores", i), b.get_ann("cadd_scores", i)
        assert va == vb, (i, va, vb)


def test_mesh_cadd_join_edge_cases(tmp_path, monkeypatch):
    """Mesh CADD parity under the risky branches: multiple flushes
    (cross-flush first-wins dedup), multiple chromosomes (the chrom-keyed
    dedup key), an indel-table pass, long TABLE alleles (host_rows /
    host_excl suppression) and an over-width STORE variant."""
    import gzip

    from annotatedvdb_tpu.loaders.cadd_loader import TpuCaddUpdater
    from annotatedvdb_tpu.ops.hashing import allele_hash_np
    from annotatedvdb_tpu.parallel import make_mesh

    monkeypatch.setattr(TpuCaddUpdater, "MESH_FLUSH_ROWS", 256)
    width = 8
    bases = "ACGT"

    def build_store():
        store = VariantStore(width=width)
        for code, start, n in ((1, 1000, 600), (2, 5000, 400)):
            pos = np.arange(start, start + n, dtype=np.int32)
            ref = np.zeros((n, width), np.uint8)
            alt = np.zeros((n, width), np.uint8)
            for j in range(n):
                ref[j, 0] = ord(bases[j % 4])
                alt[j, 0] = ord(bases[(j + 1 + j % 3) % 4])
            ones = np.ones(n, np.int32)
            h = allele_hash_np(ref, alt, ones, ones)
            store.shard(code).append(
                {"pos": pos, "h": h, "ref_len": ones, "alt_len": ones},
                ref, alt,
            )
        # chr2 indel + an over-width variant (host-matching paths)
        long_ref = "A" * 20
        extra = [("AC", "A", 6000), (long_ref, "G", 6100)]
        n = len(extra)
        ref = np.zeros((n, width), np.uint8)
        alt = np.zeros((n, width), np.uint8)
        rl = np.zeros(n, np.int32)
        al = np.zeros(n, np.int32)
        las = []
        from annotatedvdb_tpu.loaders.vcf_loader import _fnv32_str

        h = np.zeros(n, np.uint32)
        for j, (r, a, _p) in enumerate(extra):
            rb, ab = r.encode(), a.encode()
            ref[j, :min(len(rb), width)] = list(rb[:width])
            alt[j, :min(len(ab), width)] = list(ab[:width])
            rl[j], al[j] = len(rb), len(ab)
            if len(rb) > width or len(ab) > width:
                h[j] = _fnv32_str(r, a)
                las.append((r, a))
            else:
                h[j] = allele_hash_np(
                    ref[j:j + 1], alt[j:j + 1], rl[j:j + 1], al[j:j + 1]
                )[0]
                las.append(None)
        store.shard(2).append(
            {"pos": np.array([p for _, _, p in extra], np.int32),
             "h": h, "ref_len": rl, "alt_len": al},
            ref, alt, long_alleles=las,
        )
        return store

    cadd_dir = str(tmp_path / "cadd")
    import os as _os

    _os.makedirs(cadd_dir)
    with gzip.open(_os.path.join(cadd_dir, "whole_genome_SNVs.tsv.gz"),
                   "wt") as f:
        f.write("## CADD\n#Chrom\tPos\tRef\tAlt\tRawScore\tPHRED\n")
        for code, start, n in ((1, 1000, 600), (2, 5000, 400)):
            for p in range(start, start + n):
                b = bases[p % 4]
                for a in bases:
                    if a != b:
                        f.write(f"{code}\t{p}\t{b}\t{a}\t0.25\t5.0\n")
    with gzip.open(
            _os.path.join(cadd_dir, "gnomad.genomes.r3.0.indel.tsv.gz"),
            "wt") as f:
        f.write("## CADD\n#Chrom\tPos\tRef\tAlt\tRawScore\tPHRED\n")
        # short indel row (device path) + long-allele rows (host_rows)
        f.write("2\t6000\tAC\tA\t0.75\t15.0\n")
        f.write(f"2\t6100\t{'A' * 20}\tG\t0.9\t20.0\n")
        f.write(f"2\t6100\t{'C' * 30}\tG\t0.1\t1.0\n")

    results = {}
    for tag, mesh in (("seq", None), ("mesh", make_mesh(8))):
        store = build_store()
        up = TpuCaddUpdater(
            store, AlgorithmLedger(str(tmp_path / f"ce_{tag}.jsonl")),
            cadd_dir, mesh=mesh, log=lambda *a: None,
        )
        counters = up.update_all(commit=True)
        results[tag] = (store, counters)

    (s1, c1), (s8, c8) = results["seq"], results["mesh"]
    for key in ("snv", "indel", "update", "not_matched", "skipped"):
        assert c1[key] == c8[key], f"counter {key}: {c1[key]} != {c8[key]}"
    assert c1["indel"] >= 2  # the indel + the long-allele host match landed
    for code in (1, 2):
        a, b = s1.shard(code), s8.shard(code)
        assert a.n == b.n
        for i in range(a.n):
            va, vb = a.get_ann("cadd_scores", i), b.get_ann("cadd_scores", i)
            assert va == vb, (code, i, va, vb)
