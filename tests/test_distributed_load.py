"""Distributed end-to-end load: the mesh path through the production loader.

VERDICT round 2 item 1's done-criterion: a multi-device CPU test loads a
sorted single-chromosome VCF end-to-end through the same code path the CLI
uses (``TpuVcfLoader(mesh=...)``), asserting zero drops and store parity
with the single-device load.  Chromosome-sorted input is the adversarial
case for resharding — every row routes to one owner — which the lossless
default capacity must absorb.
"""

import random

import numpy as np
import pytest

from annotatedvdb_tpu.loaders import TpuVcfLoader
from annotatedvdb_tpu.store import AlgorithmLedger, VariantStore

BASES = "ACGT"


def write_sorted_vcf(path, n=1000, chrom="22", seed=5):
    rng = random.Random(seed)
    lines = ["##fileformat=VCFv4.2",
             "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO"]
    pos = 10_000
    for i in range(n):
        pos += rng.randint(1, 40)
        kind = rng.randrange(4)
        if kind == 0:
            ref = rng.choice(BASES)
            alt = rng.choice(BASES.replace(ref, ""))
        elif kind == 1:
            ref = rng.choice(BASES)
            alt = ref + "".join(rng.choice(BASES) for _ in range(rng.randint(1, 6)))
        elif kind == 2:
            alt = rng.choice(BASES)
            ref = alt + "".join(rng.choice(BASES) for _ in range(rng.randint(1, 6)))
        else:
            ref = "".join(rng.choice(BASES) for _ in range(3))
            alt = "".join(rng.choice(BASES) for _ in range(3))
        lines.append(f"{chrom}\t{pos}\trs{i}\t{ref}\t{alt}\t.\t.\tRS={i}")
    # long-allele tail exercises the host-fallback path through the exchange
    lines.append(f"{chrom}\t{pos + 50}\t.\t{'A' * 60}\tG\t.\t.\t.")
    path.write_text("\n".join(lines) + "\n")
    return str(path)


def load_with(tmp_path, vcf, tag, mesh):
    store = VariantStore(width=49)
    ledger = AlgorithmLedger(str(tmp_path / f"ledger_{tag}.jsonl"))
    loader = TpuVcfLoader(store, ledger, mesh=mesh, batch_size=256,
                          log=lambda *a: None)
    counters = loader.load_file(vcf, commit=True)
    return store, counters


def test_mesh_load_matches_single_device(tmp_path):
    """Sorted single-chromosome VCF: mesh load == single-device load."""
    from annotatedvdb_tpu.parallel import make_mesh

    vcf = write_sorted_vcf(tmp_path / "chr22.vcf")
    s1, c1 = load_with(tmp_path, vcf, "single", mesh=None)
    s8, c8 = load_with(tmp_path, vcf, "mesh", mesh=make_mesh(8))

    for key in ("line", "variant", "skipped", "duplicates"):
        assert c1[key] == c8[key], f"counter {key}: {c1[key]} != {c8[key]}"
    assert s1.n == s8.n == c1["variant"]

    sh1, sh8 = s1.shard(22), s8.shard(22)
    sh1.compact(), sh8.compact()
    for col in ("pos", "h", "ref_len", "alt_len", "ref_snp", "bin_level",
                "leaf_bin", "needs_digest"):
        np.testing.assert_array_equal(sh1.cols[col], sh8.cols[col], err_msg=col)
    np.testing.assert_array_equal(sh1.ref, sh8.ref)
    np.testing.assert_array_equal(sh1.alt, sh8.alt)
    # record PKs (including the digest-tail row) agree row-for-row
    for i in range(0, sh1.n, 97):
        assert sh1.primary_key(i) == sh8.primary_key(i)
    digest1 = [pk for pk in sh1.digest_pk if pk is not None]
    digest8 = [pk for pk in sh8.digest_pk if pk is not None]
    assert digest1 == digest8 and len(digest1) == 1


def test_mesh_load_multi_chromosome(tmp_path):
    """Interleaved chromosomes route across owners without loss."""
    from annotatedvdb_tpu.parallel import make_mesh

    rng = random.Random(11)
    lines = ["##fileformat=VCFv4.2",
             "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO"]
    per_chrom = {}
    for i in range(800):
        chrom = rng.choice([str(c) for c in range(1, 23)] + ["X", "Y", "M"])
        pos = per_chrom.get(chrom, 1000) + rng.randint(1, 50)
        per_chrom[chrom] = pos
        ref = rng.choice(BASES)
        alt = rng.choice(BASES.replace(ref, ""))
        lines.append(f"{chrom}\t{pos}\t.\t{ref}\t{alt}\t.\t.\t.")
    vcf = tmp_path / "multi.vcf"
    vcf.write_text("\n".join(lines) + "\n")

    s1, c1 = load_with(tmp_path, str(vcf), "single", mesh=None)
    s4, c4 = load_with(tmp_path, str(vcf), "mesh", mesh=make_mesh(4))
    assert c1["variant"] == c4["variant"]
    assert sorted(s1.shards) == sorted(s4.shards)
    for code in s1.shards:
        a, b = s1.shard(code), s4.shard(code)
        a.compact(), b.compact()
        np.testing.assert_array_equal(a.cols["pos"], b.cols["pos"])
        np.testing.assert_array_equal(a.cols["h"], b.cols["h"])
