"""Span-ring lifecycle + crash flight recorder unit battery.

The request tracer's ring must wrap, survive concurrent writers without
a lock, and export Chrome-trace events; the flight recorder's mmap'd
ring must round-trip, wrap, tolerate torn slots on harvest (the
ledger's torn-tail discipline at slot granularity), survive a simulated
process death (reopen + decode), and absorb injected write failures —
observability never takes down what it observes.
"""

from __future__ import annotations

import json
import os
import threading

import pytest

from annotatedvdb_tpu.obs import flight as flight_mod
from annotatedvdb_tpu.obs import reqtrace
from annotatedvdb_tpu.obs.flight import (
    HEADER,
    SLOT,
    FlightRecorder,
    decode_ring,
    harvest,
    load_harvest,
)
from annotatedvdb_tpu.obs.metrics import MetricsRegistry
from annotatedvdb_tpu.obs.reqtrace import TraceRecorder
from annotatedvdb_tpu.utils import faults


@pytest.fixture(autouse=True)
def _unarmed():
    faults.reset("")
    yield
    faults.reset("")


# ---------------------------------------------------------------------------
# span ring


def test_ring_records_stages_and_wraps():
    rec = TraceRecorder(slots=4, sample=1.0)
    for i in range(10):
        t = rec.begin(f"id{i}", "point")
        t.add("queue", 0.001 * i)
        t.add("device", 0.002)
        rec.finish(t, 200)
    records = rec.records()
    assert len(records) == 4  # wrapped: only the last four survive
    ids = {r[0] for r in records}
    assert ids == {"id6", "id7", "id8", "id9"}
    trace_id, kind, status, _t0, total, stages, _spans = records[-1]
    assert kind == "point" and status == 200 and total >= 0
    assert dict(stages)["device"] == 0.002


def test_ring_concurrent_writers_never_tear():
    rec = TraceRecorder(slots=64, sample=1.0)
    errors: list = []

    def writer(wid: int):
        try:
            for i in range(200):
                t = rec.begin(f"w{wid}-{i}", "bulk")
                t.add("device", 0.001)
                rec.finish(t, 200)
        except Exception as err:  # pragma: no cover
            errors.append(err)

    threads = [threading.Thread(target=writer, args=(w,)) for w in range(8)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert not errors
    records = rec.records()
    assert len(records) == 64
    # every surviving slot is a complete immutable record, never a hybrid
    for r in records:
        assert len(r) == 7 and r[1] == "bulk" and r[2] == 200
        assert dict(r[5]) == {"device": 0.001}


def test_sampling_zero_disarms_and_fraction_samples():
    rec = TraceRecorder(sample=0.0)
    assert rec.begin("x", "point") is None
    rec.finish(None, 200)  # a disarmed finish is a no-op, never a crash
    assert rec.records() == []
    frac = TraceRecorder(sample=0.5)
    got = sum(1 for i in range(400)
              if frac.begin(str(i), "point") is not None)
    assert 100 < got < 300  # seeded RNG: comfortably inside


def test_stage_histograms_and_slow_log():
    reg = MetricsRegistry()
    lines: list[str] = []
    rec = TraceRecorder(registry=reg, slow_ms=5.0, sample=1.0,
                        log=lines.append)
    t = rec.begin("fast", "point")
    rec.finish(t, 200)
    t = rec.begin("slowone", "region")
    t.add("device", 0.02)
    t.t0_ns -= int(20e6)  # backdate 20ms: over the 5ms threshold
    rec.finish(t, 200)
    slow = [ln for ln in lines if "slow request" in ln]
    assert len(slow) == 1
    assert "trace=slowone" in slow[0] and "device=" in slow[0]
    text = reg.render_prometheus()
    assert 'avdb_stage_seconds_count{stage="device"} 1' in text
    assert 'avdb_stage_seconds_count{stage="total"} 2' in text
    assert "avdb_trace_slow_requests_total 1" in text


def test_span_cap_bounds_subspans():
    rec = TraceRecorder(sample=1.0)
    t = rec.begin("panel", "regions")
    for i in range(200):
        t.span(f"regions.chr{i}", 0.001)
    assert len(t.spans) == t.MAX_SPANS


def test_chrome_events_merge_with_tracer_timebase():
    from annotatedvdb_tpu.obs.trace import Tracer

    tracer = Tracer(process_name="t")
    rec = TraceRecorder(sample=1.0)
    t = rec.begin("abc", "point")
    t.add("queue", 0.001)
    rec.finish(t, 200)
    with tracer.span("serve.batch", n=3):
        pass
    events = rec.chrome_events(base_ns=tracer._t0) + tracer.events()
    # both sources parse as one trace-event list
    doc = json.loads(json.dumps(
        {"traceEvents": events, "displayTimeUnit": "ms"}
    ))
    names = {e["name"] for e in doc["traceEvents"]}
    assert "point" in names and "serve.batch" in names
    req = [e for e in doc["traceEvents"]
           if e.get("name") == "point" and e.get("ph") == "X"]
    assert req and req[0]["args"]["trace_id"] == "abc"
    stage = [e for e in doc["traceEvents"] if e.get("name") == "queue"]
    assert stage and stage[0]["dur"] == pytest.approx(1000.0)


def test_active_trace_attaches_engine_subspans():
    rec = TraceRecorder(sample=1.0)
    t = rec.begin("x", "regions")
    reqtrace.span_active("orphan", 1.0)  # no active trace: no-op
    with reqtrace.activate(t):
        reqtrace.span_active("regions.chr8", 0.003)
    reqtrace.span_active("late", 1.0)  # deactivated again
    assert t.spans == [("regions.chr8", 0.003)]
    with reqtrace.activate(None):  # None trace: transparent
        reqtrace.span_active("nope", 1.0)
    assert t.spans == [("regions.chr8", 0.003)]


def test_background_sink_records_span_and_event():
    rec = TraceRecorder(sample=1.0)
    events: list = []
    reqtrace.set_background_sink(
        rec.background, lambda name, detail: events.append((name, detail))
    )
    try:
        with reqtrace.background_span("memtable.flush", groups=2):
            pass
        reqtrace.lifecycle_event("wal", "rotated")
    finally:
        reqtrace.set_background_sink(None, None)
    records = [r for r in rec.records() if r[1] == "background"]
    assert len(records) == 1
    assert records[0][6][0][0] == "memtable.flush"
    assert events == [("wal", "rotated")]
    # cleared sink: everything is a no-op again
    with reqtrace.background_span("x"):
        pass
    reqtrace.lifecycle_event("y", "z")
    assert len([r for r in rec.records() if r[1] == "background"]) == 1


# ---------------------------------------------------------------------------
# flight recorder


def test_flight_roundtrip_requests_and_events(tmp_path):
    path = str(tmp_path / "w0.ring")
    fr = FlightRecorder(path, slots=16)
    fr.request("abc", "point", 200, 0.0042,
               [("queue", 0.001), ("device", 0.002)])
    fr.event("brownout", "level 0->1 (limit)")
    fr.close()
    decoded = decode_ring(path)
    assert decoded["slots"] == 16
    req, ev = decoded["events"]
    assert req["type"] == "request" and req["trace"] == "abc"
    assert req["kind"] == "point" and req["status"] == 200
    assert req["ms"] == pytest.approx(4.2)
    assert req["stages"]["device"] == pytest.approx(2.0)
    assert ev["type"] == "event" and ev["name"] == "brownout"
    assert "level 0->1" in ev["detail"]


def test_flight_ring_wraps_keeping_newest(tmp_path):
    path = str(tmp_path / "w0.ring")
    fr = FlightRecorder(path, slots=8, event_slots=8)
    for i in range(20):
        fr.event("tick", f"n={i}")
    fr.close()
    events = decode_ring(path)["events"]
    assert len(events) == 8
    assert [e["detail"] for e in events] == [
        f"n={i}" for i in range(12, 20)
    ]


def test_flight_request_flood_cannot_wash_out_lifecycle_events(tmp_path):
    """The incident timeline survives serving QPS: lifecycle events live
    in their own ring region, so thousands of request summaries wrap the
    request ring without touching the breaker trip that explains them —
    the full-chaos harvest found the single-ring version losing exactly
    this evidence."""
    path = str(tmp_path / "w0.ring")
    fr = FlightRecorder(path, slots=8, event_slots=16)
    fr.event("breaker", "group 8 tripped open")
    for i in range(5000):  # the flood
        fr.request(f"t{i}", "point", 200, 0.001, [])
    fr.close()
    events = decode_ring(path)["events"]
    reqs = [e for e in events if e["type"] == "request"]
    life = [e for e in events if e["type"] == "event"]
    assert len(reqs) == 8  # request ring wrapped as designed
    assert [e["name"] for e in life] == ["breaker"]  # still aboard


def test_flight_survives_simulated_kill_and_tolerates_torn_slot(tmp_path):
    path = str(tmp_path / "w0.ring")
    fr = FlightRecorder(path, slots=8)
    for i in range(5):
        fr.request(f"t{i}", "point", 200, 0.001, [])
    fr.flush()  # the serving tick's cadence; summaries are mmap-durable
    # no close(): a SIGKILL never runs destructors — the mmap'd bytes
    # are already in the page cache, a fresh reader must decode them
    events = decode_ring(path)["events"]
    assert [e["trace"] for e in events] == [f"t{i}" for i in range(5)]
    # tear one slot (flip a payload byte mid-record): the CRC drops
    # exactly that slot and keeps the rest.  The payload field starts
    # after seq/t/kind/status/crc/plen/trace = 62 bytes into the slot.
    with open(path, "r+b") as f:
        off = HEADER.size + 2 * SLOT.size + 64
        f.seek(off)
        b = f.read(1)
        f.seek(off)
        f.write(bytes([b[0] ^ 0xFF]))
    survivors = decode_ring(path)["events"]
    assert [e["trace"] for e in survivors] == ["t0", "t1", "t3", "t4"]
    fr.close()


def test_flight_write_failure_is_absorbed(tmp_path):
    lines: list[str] = []
    fr = FlightRecorder(str(tmp_path / "w0.ring"), slots=4,
                        log=lines.append)
    faults.reset("obs.flight:1:raise")
    fr.event("breaker", "boom window")  # injected failure: absorbed
    fr.event("breaker", "after")        # recording continues
    fr.close()
    assert fr.errors == 1
    assert any("ring write failed" in ln for ln in lines)
    events = decode_ring(str(tmp_path / "w0.ring"))["events"]
    assert [e["detail"] for e in events] == ["after"]


def test_harvest_writes_jsonl_and_loads_back(tmp_path):
    store = tmp_path / "store"
    store.mkdir()
    ring = flight_mod.ring_path(str(store), 1)
    fr = FlightRecorder(ring, slots=8)
    fr.request("abc", "upsert", 200, 0.01, [("wal_fsync", 0.004)])
    fr.event("maintain", "pass starting")
    fr.close()
    out = harvest(ring, str(store), 1, "died rc=-9", log=lambda m: None)
    assert out is not None and out.endswith("-w1.jsonl")
    data = load_harvest(out)
    assert data["meta"]["reason"] == "died rc=-9"
    assert data["meta"]["worker"] == 1
    kinds = [(e["type"], e.get("kind") or e.get("name"))
             for e in data["events"]]
    assert kinds == [("request", "upsert"), ("event", "maintain")]
    boxes = flight_mod.list_blackboxes(str(store))
    assert boxes["harvested"] == [out]
    assert boxes["rings"] == [ring]


def test_harvest_of_missing_or_empty_ring_is_none(tmp_path):
    store = tmp_path / "store"
    store.mkdir()
    assert harvest(str(store / "nope.ring"), str(store), 0, "died") is None
    ring = flight_mod.ring_path(str(store), 0)
    FlightRecorder(ring, slots=4).close()  # created, never written
    assert harvest(ring, str(store), 0, "died") is None
    assert flight_mod.list_blackboxes(str(store))["harvested"] == []


def test_decode_rejects_foreign_files(tmp_path):
    p = tmp_path / "junk.ring"
    p.write_bytes(b"not a ring at all" * 10)
    with pytest.raises(ValueError):
        decode_ring(str(p))
    short = tmp_path / "short.ring"
    short.write_bytes(b"ab")
    with pytest.raises(ValueError):
        decode_ring(str(short))


def test_respawn_truncates_the_previous_incarnation(tmp_path):
    path = str(tmp_path / "w0.ring")
    fr = FlightRecorder(path, slots=8)
    fr.event("old", "before death")
    fr.close()
    fr2 = FlightRecorder(path, slots=8)  # the respawned worker's fresh box
    fr2.event("new", "after respawn")
    fr2.close()
    events = decode_ring(path)["events"]
    assert [e["name"] for e in events] == ["new"]


def test_oversized_event_detail_truncates_to_valid_json(tmp_path):
    """A long (or escape-heavy) lifecycle detail SHRINKS until the
    encoded payload fits — byte-slicing encoded JSON used to cut
    mid-string, and the CRC-valid-but-unparseable slot was silently
    dropped on decode (losing exactly the events the box exists for)."""
    path = str(tmp_path / "w0.ring")
    fr = FlightRecorder(path, slots=4, event_slots=8)
    fr.event("breaker", "x" * 500)
    fr.event("brownout", "é" * 80)  # escapes inflate 6x when encoded
    fr.close()
    events = decode_ring(path)["events"]
    assert [e["name"] for e in events] == ["breaker", "brownout"]
    assert events[0]["detail"].startswith("xxx")
    assert events[1]["detail"].startswith("é")


def test_concurrent_flush_and_events_never_collide_slots(tmp_path):
    """Two threads flushing (the threaded front end's inline time-gated
    flushes can race) plus write-through events must never interleave a
    seq reservation and overwrite each other's slot."""
    path = str(tmp_path / "w0.ring")
    fr = FlightRecorder(path, slots=256, event_slots=64)
    for i in range(200):
        fr.request(f"t{i}", "point", 200, 0.001, [])

    def drain():
        fr.flush(limit=10)

    threads = [threading.Thread(target=drain) for _ in range(8)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    fr.close()
    reqs = [e for e in decode_ring(path)["events"]
            if e["type"] == "request"]
    # every drained record landed in its own slot: seqs are unique and
    # the full set survived (80 capped-flush + the close() drain = 200)
    seqs = [e["seq"] for e in reqs]
    assert len(seqs) == len(set(seqs)) == 200


def test_oversized_payload_drops_stages_not_the_headline(tmp_path):
    path = str(tmp_path / "w0.ring")
    fr = FlightRecorder(path, slots=4)
    stages = [(f"stage_with_a_long_name_{i}", 0.001) for i in range(30)]
    fr.request("big", "regions", 200, 1.5, stages)
    fr.close()
    ev = decode_ring(path)["events"][0]
    assert ev["trace"] == "big" and ev["ms"] == pytest.approx(1500.0)
    assert "stages" not in ev  # trimmed to fit the fixed slot


# ---------------------------------------------------------------------------
# fleet metric-snapshot merging (the ?fleet=1 math)


def test_merge_snapshots_sums_counters_maxes_gauges():
    from annotatedvdb_tpu.obs.metrics import merge_snapshots, render_snapshot

    def snap(n):
        reg = MetricsRegistry()
        reg.counter("avdb_query_requests_total", labels={"kind": "point"}) \
            .inc(n)
        reg.gauge("avdb_serve_queue_depth").set(n)
        h = reg.histogram("avdb_query_seconds", (0.1, 1.0),
                          labels={"kind": "point"})
        h.observe(0.05)
        h.observe(0.5 * n)
        return reg.snapshot()

    merged = merge_snapshots([snap(2), snap(5)])
    by = {(name, tuple(sorted(e["labels"].items()))): e
          for name, entries in merged.items() for e in entries}
    c = by[("avdb_query_requests_total", (("kind", "point"),))]
    assert c["value"] == 7  # counters sum
    g = by[("avdb_serve_queue_depth", ())]
    assert g["value"] == 5  # gauges take the max
    h = by[("avdb_query_seconds", (("kind", "point"),))]
    assert h["count"] == 4 and h["counts"][0] == 2  # bucket-wise sum
    text = render_snapshot(merged)
    assert 'avdb_query_requests_total{kind="point"} 7' in text
    assert 'avdb_query_seconds_bucket{kind="point",le="+Inf"} 4' in text
    assert "# TYPE avdb_query_seconds histogram" in text


def test_merge_snapshots_skips_mismatched_edges():
    from annotatedvdb_tpu.obs.metrics import merge_snapshots

    a = MetricsRegistry()
    a.histogram("avdb_query_seconds", (0.1, 1.0),
                labels={"kind": "point"}).observe(0.05)
    b = MetricsRegistry()
    b.histogram("avdb_query_seconds", (0.2, 2.0),
                labels={"kind": "point"}).observe(0.05)
    merged = merge_snapshots([a.snapshot(), b.snapshot()])
    entry = merged["avdb_query_seconds"][0]
    assert entry["count"] == 1  # the mismatched sibling was dropped
    assert entry["edges"] == [0.1, 1.0]
