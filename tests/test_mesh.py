"""Mesh-native AVDB battery: the multiprocess-CPU mesh suite.

``tests/conftest.py`` forces ``--xla_force_host_platform_device_count=8``,
so every test here runs against a REAL 8-device host mesh — the same
device topology a v5e-8 slice presents, minus the silicon.  The contract
under test is byte-identity: the mesh-sharded answers (load, point, bulk,
region, regions, the annotate kernel) must equal the single-device
answers bit for bit, because the mesh only moves WHERE rows compute —
never what they compute.  Placement, knob grammar, per-device residency
budgets, the manifest's advisory placement block, and the doctor/status
surfaces ride along.
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from annotatedvdb_tpu.loaders.lookup import identity_hashes
from annotatedvdb_tpu.parallel import mesh as meshlib
from annotatedvdb_tpu.serve import (
    DeviceBreaker,
    MeshExecutor,
    QueryEngine,
    SnapshotManager,
    StaticSnapshots,
    serve_mesh_executor,
)
from annotatedvdb_tpu.store import VariantStore
from annotatedvdb_tpu.store.variant_store import RawJson
from annotatedvdb_tpu.types import (
    NUM_CHROMOSOMES,
    chromosome_label,
    encode_allele_array,
)

WIDTH = 8
CHROMS = (1, 8, 23)
BASES = ("A", "C", "G", "T")


@pytest.fixture(autouse=True)
def _fresh_mesh_cache():
    meshlib.reset_global_mesh()
    yield
    meshlib.reset_global_mesh()


# ---------------------------------------------------------------------------
# synthetic multi-chromosome store (shadowed duplicate + long-allele tail)


def _append(shard, rows):
    refs = [r["ref"] for r in rows]
    alts = [r["alt"] for r in rows]
    ref, ref_len = encode_allele_array(refs, WIDTH)
    alt, alt_len = encode_allele_array(alts, WIDTH)
    h = identity_hashes(WIDTH, ref, alt, ref_len, alt_len, refs, alts)
    cols = {
        "pos": np.asarray([r["pos"] for r in rows], np.int32),
        "h": h, "ref_len": ref_len, "alt_len": alt_len,
    }
    ann = {
        "cadd_scores": [
            {"CADD_phred": float(3 + (r["pos"] % 17))}
            if r["pos"] % 3 == 0 else None for r in rows
        ],
        "vep_output": [
            RawJson(f'{{"p":{r["pos"]}}}') if r["pos"] % 5 == 0 else None
            for r in rows
        ],
    }
    long_alleles = [
        (r["ref"], r["alt"])
        if len(r["ref"]) > WIDTH or len(r["alt"]) > WIDTH else None
        for r in rows
    ]
    shard.append(cols, ref, alt, annotations=ann,
                 long_alleles=long_alleles)


def _build_store():
    store = VariantStore(width=WIDTH)
    truth = []
    for code in CHROMS:
        shard = store.shard(code)
        for run, base in enumerate((500, 60_000)):
            rows = []
            for i in range(25):
                pos = base + 977 * i
                k = (i + run) % 4
                ref = BASES[k]
                alt = BASES[(k + 1) % 4] if i % 4 else ref + "TTG"
                if i == 20:  # long-allele tail: full-string identity
                    ref = "A" * (WIDTH + 4)
                    alt = "G"
                rows.append({"chrom": code, "pos": pos, "ref": ref,
                             "alt": alt})
            _append(shard, rows)
            truth.extend(rows)
    # a shadowed duplicate: same identity in a NEWER chr8 segment —
    # first-wins must keep the older row on every path
    dup = dict(truth[0], chrom=8)
    dup = next(r for r in truth if r["chrom"] == 8)
    _append(store.shard(8), [dict(dup)])
    return store, truth


def _ids(truth):
    ids = [
        f"{chromosome_label(r['chrom'])}:{r['pos']}:{r['ref']}:{r['alt']}"
        for r in truth
    ]
    ids += ["1:999999999:A:T", "11:50:G:C", "8:505:T:G"]  # misses
    return ids


SPECS = ["8:1-100000", "1:400-2000", "X:59000-90000", "11:1-5000",
         "8:490-600", "1:1-60000000", "8:60000-60000"]


@pytest.fixture(scope="module")
def served():
    store, truth = _build_store()
    snaps = StaticSnapshots(store)
    plain = QueryEngine(snaps, region_cache_size=0)
    breaker = DeviceBreaker()
    meshed = QueryEngine(
        snaps, region_cache_size=0, breaker=breaker,
        mesh=MeshExecutor(meshlib.global_mesh(), breaker=breaker,
                          bulk_min=0),
    )
    return store, truth, plain, meshed


# ---------------------------------------------------------------------------
# mesh authority: shape grammar, sizing, placement


def test_mesh_shape_env_grammar(monkeypatch):
    monkeypatch.setenv("AVDB_MESH_SHAPE", "2x4")
    with pytest.raises(ValueError, match="device count"):
        meshlib.mesh_shape_from_env()
    monkeypatch.setenv("AVDB_MESH_SHAPE", "0")
    with pytest.raises(ValueError, match=">= 1"):
        meshlib.mesh_shape_from_env()
    monkeypatch.setenv("AVDB_MESH_SHAPE", "64")
    with pytest.raises(ValueError, match="exceeds"):
        meshlib.global_mesh()
    monkeypatch.delenv("AVDB_MESH_SHAPE")
    assert meshlib.mesh_shape_from_env() is None


def test_global_mesh_sizing(monkeypatch):
    import jax

    mesh = meshlib.global_mesh()
    assert mesh is not None and mesh.devices.size == len(jax.devices())
    monkeypatch.setenv("AVDB_MESH_SHAPE", "4")
    meshlib.reset_global_mesh()
    assert meshlib.global_mesh().devices.size == 4
    # --maxWorkers-style limit clamps further
    assert meshlib.global_mesh(limit=2).devices.size == 2
    monkeypatch.setenv("AVDB_MESH_SHAPE", "1")
    meshlib.reset_global_mesh()
    assert meshlib.global_mesh() is None  # single device = no mesh


def test_chromosome_placement_covers_every_code():
    from annotatedvdb_tpu.parallel.distributed import chromosome_owner_table

    placement = meshlib.chromosome_placement(8)
    assert set(placement) == set(range(1, NUM_CHROMOSOMES + 1))
    assert set(placement.values()) == set(range(8))
    # serving placement and loader routing MUST be the same table
    table = chromosome_owner_table(8)
    for code, dev in placement.items():
        assert table[code] == dev
    per_dev = meshlib.groups_per_device(placement, placement.keys())
    assert sum(len(v) for v in per_dev.values()) == NUM_CHROMOSOMES


def test_placement_hint_single_device_is_none(monkeypatch):
    monkeypatch.delenv("AVDB_MESH_SHAPE", raising=False)
    assert meshlib.placement_hint() is None
    monkeypatch.setenv("AVDB_MESH_SHAPE", "1")
    assert meshlib.placement_hint() is None
    monkeypatch.setenv("AVDB_MESH_SHAPE", "4")
    hint = meshlib.placement_hint()
    assert hint["devices"] == 4
    assert set(hint["groups"].values()) <= set(range(4))


# ---------------------------------------------------------------------------
# manifest placement block + snapshot + doctor status


def test_manifest_placement_roundtrip(tmp_path, monkeypatch):
    store, _truth = _build_store()
    plain_dir = str(tmp_path / "plain")
    store.save(plain_dir)
    with open(plain_dir + "/manifest.json") as f:
        assert "mesh_placement" not in json.load(f)

    monkeypatch.setenv("AVDB_MESH_SHAPE", "4")
    mesh_dir = str(tmp_path / "meshed")
    store.save(mesh_dir)
    with open(mesh_dir + "/manifest.json") as f:
        block = json.load(f)["mesh_placement"]
    assert block["devices"] == 4
    assert set(block["groups"]) == {
        chromosome_label(c) for c in range(1, NUM_CHROMOSOMES + 1)
    }
    loaded = VariantStore.load(mesh_dir, readonly=True)
    assert loaded.mesh_placement == block
    # the snapshot carries the placement map
    manager = SnapshotManager(mesh_dir)
    assert manager.current().placement == block
    # and the single-device store's snapshot carries none
    assert SnapshotManager(plain_dir).current().placement is None


def test_doctor_status_mesh_block(tmp_path, monkeypatch):
    from annotatedvdb_tpu.store.maintenance import store_status

    store, _truth = _build_store()
    monkeypatch.setenv("AVDB_MESH_SHAPE", "4")
    monkeypatch.setenv("AVDB_SERVE_HBM_BUDGET", "64m")
    store_dir = str(tmp_path / "status_store")
    store.save(store_dir)
    report = store_status(store_dir)
    mesh = report["mesh"]
    assert mesh["devices"] == 4
    assert sum(mesh["groups_per_device"].values()) == len(CHROMS)
    assert mesh["per_device_budget_bytes"] == (64 << 20) // 4
    assert all(v > 0 for v in
               mesh["est_resident_bytes_per_device"].values())
    # single-device resolution: no mesh block
    monkeypatch.delenv("AVDB_MESH_SHAPE")
    plain_dir = str(tmp_path / "status_plain")
    store.save(plain_dir)
    assert store_status(plain_dir)["mesh"] is None


# ---------------------------------------------------------------------------
# knob grammar + executor gating


def test_serve_mesh_knob_grammar(monkeypatch):
    from annotatedvdb_tpu.serve import mesh_exec

    monkeypatch.setenv("AVDB_SERVE_MESH", "yes")
    with pytest.raises(ValueError, match="AVDB_SERVE_MESH"):
        mesh_exec.resolve_serve_mesh()
    monkeypatch.setenv("AVDB_MESH_BULK_MIN", "many")
    with pytest.raises(ValueError, match="AVDB_MESH_BULK_MIN"):
        mesh_exec.resolve_mesh_bulk_min()
    monkeypatch.setenv("AVDB_SERVE_MESH", "0")
    assert serve_mesh_executor() is None
    # auto on a CPU backend: the per-segment host path stays production
    monkeypatch.setenv("AVDB_SERVE_MESH", "auto")
    assert serve_mesh_executor() is None
    # forced: the executor engages on the virtual mesh
    monkeypatch.setenv("AVDB_SERVE_MESH", "1")
    monkeypatch.setenv("AVDB_MESH_BULK_MIN", "16")
    ex = serve_mesh_executor()
    assert ex is not None and ex.n_devices == 8 and ex.bulk_min == 16


# ---------------------------------------------------------------------------
# byte-identity: point / bulk


def test_bulk_and_point_parity(served):
    _store, truth, plain, meshed = served
    ids = _ids(truth)
    want = plain.lookup_many(ids)
    got = meshed.lookup_many(ids)
    assert got == want
    assert sum(1 for v in want if v is not None) == len(truth)
    # the sharded call actually ran (not a silent fallback)
    assert meshed.mesh._bulk is not None
    # single point rides the same path
    assert meshed.lookup(ids[0]) == plain.lookup(ids[0])
    assert meshed.lookup("11:50:G:C") is None


def test_bulk_min_gates_small_batches(served):
    store, truth, plain, _meshed = served
    breaker = DeviceBreaker()
    engine = QueryEngine(
        StaticSnapshots(store), region_cache_size=0, breaker=breaker,
        mesh=MeshExecutor(meshlib.global_mesh(), breaker=breaker,
                          bulk_min=10_000),
    )
    ids = _ids(truth)[:8]
    assert engine.lookup_many(ids) == plain.lookup_many(ids)
    assert engine.mesh._bulk is None  # never dispatched


def test_budget_tombstone_falls_back(served):
    store, truth, plain, _meshed = served
    engine = QueryEngine(
        StaticSnapshots(store), region_cache_size=0,
        mesh=MeshExecutor(meshlib.global_mesh(), bulk_min=0,
                          budget_bytes=16),  # nothing fits
    )
    ids = _ids(truth)
    assert engine.lookup_many(ids) == plain.lookup_many(ids)
    assert engine.mesh._bulk.store is None  # tombstoned, not resident


# ---------------------------------------------------------------------------
# byte-identity: region / regions


def test_regions_parity(served):
    _store, _truth, plain, meshed = served
    for kwargs in (
        {},
        {"min_cadd": 5.0},
        {"limit": 3},
        {"limit": 0},                      # count-only
        {"tokenize": True},
        {"min_cadd": 4.0, "limit": 2, "tokenize": True},
    ):
        want = plain.regions_serve(SPECS, **kwargs).assemble()
        got = meshed.regions_serve(SPECS, **kwargs).assemble()
        assert got == want, kwargs
    for spec in SPECS:
        assert meshed.region(spec) == plain.region(spec)


def test_parity_across_generation_swap(tmp_path, monkeypatch):
    """The mesh state is generation-keyed: a loader commit must rebuild
    it, and post-swap answers stay byte-identical to the single-device
    path (stale resident state would serve pre-commit bytes)."""
    store, truth = _build_store()
    store_dir = str(tmp_path / "swap_store")
    store.save(store_dir)
    manager = SnapshotManager(store_dir)
    plain = QueryEngine(manager, region_cache_size=0)
    meshed = QueryEngine(
        manager, region_cache_size=0,
        mesh=MeshExecutor(meshlib.global_mesh(), bulk_min=0,
                          rebuild_min_s=0.0),
    )
    ids = _ids(truth) + ["8:777777:T:A"]
    assert meshed.lookup_many(ids) == plain.lookup_many(ids)
    gen1 = meshed.mesh._bulk.generation

    # a loader commit adds a row
    writer = VariantStore.load(store_dir)
    _append(writer.shard(8), [{"chrom": 8, "pos": 777_777, "ref": "T",
                               "alt": "A"}])
    writer.save(store_dir)
    assert manager.refresh() is True

    want = plain.lookup_many(ids)
    got = meshed.lookup_many(ids)
    assert got == want
    assert want[-1] is not None  # the new row resolved on both paths
    assert meshed.mesh._bulk.generation > gen1
    assert plain.regions_serve(SPECS).assemble() \
        == meshed.regions_serve(SPECS).assemble()


def test_rebuild_rate_limit_declines_churning_generations(served):
    """A generation churning faster than ``rebuild_min_s`` (the live
    write path mints one per memtable epoch) must NOT re-sort and
    re-upload the store per epoch: the executor declines and the
    byte-identical single-device path serves until the window lapses."""
    store, truth, plain, _m = served
    snaps = StaticSnapshots(store)
    engine = QueryEngine(
        snaps, region_cache_size=0,
        mesh=MeshExecutor(meshlib.global_mesh(), bulk_min=0,
                          rebuild_min_s=3600.0),
    )
    ids = _ids(truth)
    want = plain.lookup_many(ids)
    assert engine.lookup_many(ids) == want
    built = engine.mesh._bulk
    assert built is not None and built.generation == 1
    # the "commit": a new generation over the same rows
    engine.snapshots = StaticSnapshots(store, generation=2)
    assert engine.lookup_many(ids) == want  # correct bytes, no rebuild
    assert engine.mesh._bulk is built       # state untouched (declined)


def test_builders_hand_mesh_the_per_device_budget(tmp_path, monkeypatch):
    """The mesh state budget rides the residency manager's already-split
    per-device share — never the raw AVDB_SERVE_HBM_BUDGET env (a fleet
    worker reading the env whole would overcommit HBM N-fold)."""
    from annotatedvdb_tpu.serve import ResidencyManager
    from annotatedvdb_tpu.serve.http import build_server

    store, _truth = _build_store()
    store_dir = str(tmp_path / "budget_store")
    store.save(store_dir)
    monkeypatch.setenv("AVDB_SERVE_MESH", "1")
    monkeypatch.setenv("AVDB_SERVE_HBM_BUDGET", "8g")  # must be ignored
    residency = ResidencyManager(1 << 20)  # the worker's split share
    httpd = build_server(store_dir=store_dir, port=0, residency=residency)
    try:
        assert httpd.ctx.engine.mesh is not None
        assert httpd.ctx.engine.mesh.budget == 1 << 20
    finally:
        httpd.server_close()
        httpd.ctx.batcher.close()
    # no residency manager = unmanaged mesh state, not env-budgeted
    httpd = build_server(store_dir=store_dir, port=0)
    try:
        assert httpd.ctx.engine.mesh.budget == 0
    finally:
        httpd.server_close()
        httpd.ctx.batcher.close()


def test_mesh_bulk_keeps_residency_warm(served):
    """Mesh bulk traffic must keep feeding residency heat scores — the
    per-segment caches are what the single-device FALLBACK serves from
    (a decayed plan would evict them exactly when a tripped mesh needs
    them)."""
    from annotatedvdb_tpu.serve import ResidencyManager

    store, truth, _plain, _m = served
    residency = ResidencyManager(
        1 << 30, upload=False, min_rows=0, plan_interval_s=0.0,
    )
    engine = QueryEngine(
        StaticSnapshots(store), region_cache_size=0, residency=residency,
        mesh=MeshExecutor(meshlib.global_mesh(), bulk_min=0),
    )
    engine.lookup_many(_ids(truth))
    assert engine.mesh._bulk is not None  # the mesh path really ran
    stats = residency.stats()
    assert stats["resident"] > 0  # touches fed the plan


# ---------------------------------------------------------------------------
# byte-identity over BOTH HTTP front ends


def _get(port, path):
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30
        ) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as err:
        return err.code, err.read().decode()


def _post(port, path, payload):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(), method="POST",
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as err:
        return err.code, err.read().decode()


def test_front_end_parity_mesh_vs_single_device(tmp_path, monkeypatch):
    """Each front end with the mesh FORCED answers byte-identically to
    itself without the mesh, across point/bulk/region/regions — the
    serving acceptance gate."""
    from annotatedvdb_tpu.serve.aio import build_aio_server
    from annotatedvdb_tpu.serve.http import build_server

    store, truth = _build_store()
    store_dir = str(tmp_path / "http_store")
    store.save(store_dir)
    ids = _ids(truth)[:40]
    paths = (
        [f"/variant/{ids[0]}", f"/variant/{ids[-1]}"]
        + [f"/region/{s}" for s in SPECS[:4]]
        + ["/region/8:490-600?minCadd=4.0&limit=3"]
    )
    bodies = {}
    for mesh_mode in ("0", "1"):
        monkeypatch.setenv("AVDB_SERVE_MESH", mesh_mode)
        monkeypatch.setenv("AVDB_MESH_BULK_MIN", "0")
        httpd = build_server(store_dir=store_dir, port=0)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        aio = build_aio_server(store_dir=store_dir, port=0)
        aio.start_background()
        try:
            assert (httpd.ctx.engine.mesh is not None) \
                == (mesh_mode == "1")
            for name, port in (("threaded", httpd.server_address[1]),
                               ("aio", aio.server_address[1])):
                out = [body for _s, body in (
                    _get(port, p) for p in paths
                )]
                st, bulk = _post(port, "/variants", {"ids": ids})
                assert st == 200
                out.append(bulk)
                st, regions = _post(port, "/regions",
                                    {"regions": SPECS, "limit": 5})
                assert st == 200
                out.append(regions)
                bodies[(name, mesh_mode)] = out
        finally:
            httpd.shutdown()
            httpd.server_close()
            httpd.ctx.batcher.close()
            aio.shutdown()
            aio.ctx.batcher.close()
    for name in ("threaded", "aio"):
        assert bodies[(name, "1")] == bodies[(name, "0")], name
    # and cross-front-end parity holds on the mesh path too
    assert bodies[("threaded", "1")] == bodies[("aio", "1")]


# ---------------------------------------------------------------------------
# sharded load == single-device load (the mesh authority wired through
# the loader path; the deep parity battery lives in test_distributed_load)


def test_load_parity_via_global_mesh(tmp_path, monkeypatch):
    from annotatedvdb_tpu.loaders.vcf_loader import TpuVcfLoader
    from annotatedvdb_tpu.store import AlgorithmLedger

    lines = ["##fileformat=VCFv4.2",
             "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO"]
    rng = np.random.default_rng(5)
    pos = 1000
    for i in range(300):
        pos += int(rng.integers(1, 40))
        ref = BASES[int(rng.integers(0, 4))]
        alt = BASES[(BASES.index(ref) + 1 + int(rng.integers(0, 3))) % 4]
        if alt == ref:
            alt = BASES[(BASES.index(ref) + 1) % 4]
        lines.append(f"7\t{pos}\trs{i}\t{ref}\t{alt}\t.\t.\tRS={i}")
    vcf = tmp_path / "chr7.vcf"
    vcf.write_text("\n".join(lines) + "\n")

    def load(tag, mesh):
        store = VariantStore(width=16)
        ledger = AlgorithmLedger(str(tmp_path / f"ledger_{tag}.jsonl"))
        loader = TpuVcfLoader(store, ledger, mesh=mesh, batch_size=128,
                              log=lambda *a: None)
        loader.load_file(str(vcf), commit=True)
        return store

    s1 = load("single", mesh=None)
    monkeypatch.setenv("AVDB_MESH_SHAPE", "4")
    meshlib.reset_global_mesh()
    mesh = meshlib.global_mesh()
    assert mesh is not None and mesh.devices.size == 4
    s4 = load("mesh", mesh=mesh)
    sh1, sh4 = s1.shard(7), s4.shard(7)
    sh1.compact(), sh4.compact()
    assert sh1.n == sh4.n > 0
    for col in ("pos", "h", "ref_len", "alt_len", "bin_level", "leaf_bin"):
        np.testing.assert_array_equal(sh1.cols[col], sh4.cols[col],
                                      err_msg=col)
    np.testing.assert_array_equal(sh1.ref, sh4.ref)
    np.testing.assert_array_equal(sh1.alt, sh4.alt)


# ---------------------------------------------------------------------------
# residency: per-device budgets + placed uploads


def test_residency_places_uploads_per_device_budget():
    import jax

    from annotatedvdb_tpu.serve import ResidencyManager

    store, _truth = _build_store()
    snaps = StaticSnapshots(store)
    placement = meshlib.chromosome_placement(8)
    from annotatedvdb_tpu.serve.residency import device_cache_bytes

    seg_bytes = max(
        device_cache_bytes(seg, WIDTH)
        for shard in store.shards.values() for seg in shard.segments
    )
    manager = ResidencyManager(
        seg_bytes,  # per-device: exactly ONE segment fits per device
        upload=True, async_upload=False, min_rows=0, plan_interval_s=0.0,
        placement=placement, devices=jax.devices(),
    )
    manager.govern(snaps.current())
    # touch every chromosome: each group's hottest segment becomes
    # resident ON ITS PLACED DEVICE; per-device bytes never exceed budget
    for code, shard in store.shards.items():
        key = shard.segments[0].key
        manager.touch_window(shard, key[0], key[-1], 100)
    stats = manager.stats()
    assert stats["resident"] >= len(CHROMS) - 1
    per_dev = stats["per_device_bytes"]
    assert per_dev and all(v <= seg_bytes for v in per_dev.values())
    for code, shard in store.shards.items():
        for seg in shard.segments:
            if seg._device is not None:
                dev = next(iter(seg._device[0].devices()))
                assert dev == jax.devices()[placement[code]], code


# ---------------------------------------------------------------------------
# metrics + stats surfaces


def test_mesh_metrics_registered(served):
    from annotatedvdb_tpu.obs.metrics import MetricsRegistry

    store, truth, plain, _m = served
    registry = MetricsRegistry()
    breaker = DeviceBreaker(registry=registry)
    engine = QueryEngine(
        StaticSnapshots(store), region_cache_size=0, breaker=breaker,
        mesh=MeshExecutor(meshlib.global_mesh(), registry=registry,
                          breaker=breaker, bulk_min=0),
    )
    ids = _ids(truth)
    assert engine.lookup_many(ids) == plain.lookup_many(ids)
    engine.regions_serve(SPECS)
    text = registry.render_prometheus()
    assert 'avdb_mesh_devices 8' in text
    assert 'avdb_mesh_dispatch_total{kind="bulk"} 1' in text
    assert 'avdb_mesh_dispatch_total{kind="spans"} 1' in text
    assert "avdb_mesh_resident_bytes" in text
    assert "avdb_mesh_groups_placed" in text
    stats = engine.mesh.stats()
    assert stats["devices"] == 8
    assert stats["resident_bytes"] > 0
    assert sum(stats["groups_per_device"].values()) == NUM_CHROMOSOMES
