"""Serial vs overlapped executor parity (the AVDB_PIPELINE modes).

The overlapped streaming executor (``loaders/vcf_loader.py``) runs ingest /
dispatch / process / store-writer as concurrent bounded stages; the serial
loop is the debugging escape hatch.  The two must be indistinguishable from
the outside: identical counters (inserts, duplicates, skip totals, lines),
identical resume semantics after a mid-file fault, and bit-identical
persisted store bytes.  These tests pin that contract, plus the stage
accounting that keeps the overlapped stage table honest (busy seconds are
measured per stage thread, so with real overlap they sum past wall)."""

import json
import os

import numpy as np
import pytest

from annotatedvdb_tpu.loaders import TpuVcfLoader
from annotatedvdb_tpu.store import AlgorithmLedger, VariantStore


def _write_vcf(path, n_lines: int = 3000) -> None:
    """Multi-chunk synthetic VCF with every counter-bearing shape: exact
    duplicate lines, multi-allelic sites, '.' alts, unplaceable contigs,
    a malformed line, FREQ annotations, rs ids."""
    rng = np.random.default_rng(11)
    bases = "ACGT"
    with open(path, "w") as fh:
        fh.write("##fileformat=VCFv4.2\n")
        fh.write("#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\n")
        pos = 500
        for k in range(n_lines):
            pos += int(rng.integers(1, 6))
            ref = bases[int(rng.integers(4))]
            alt = bases[(bases.index(ref) + 1 + int(rng.integers(3))) % 4]
            if k % 97 == 0:
                alt = alt + ",."  # skipped '.' alt
            elif k % 53 == 0:
                alt = alt + "," + bases[int(rng.integers(4))]
            info = (
                f"RS={k};FREQ=GnomAD:0.9,{0.001 * (k % 9 + 1):.4f}"
                if k % 31 == 0 else f"RS={k}" if k % 3 == 0 else "."
            )
            chrom = "1" if k % 7 else "2"
            # one verbatim id mid-file: the failAt fault-injection target
            # (rs ids assemble metaseq-style variant ids instead)
            vid = "failhere" if k == 1500 else f"rs{k}"
            fh.write(f"{chrom}\t{pos}\t{vid}\t{ref}\t{alt}\t.\t.\t{info}\n")
            if k % 211 == 0:  # exact duplicate of the line just written
                fh.write(
                    f"{chrom}\t{pos}\t{vid}\t{ref}\t{alt}\t.\t.\t{info}\n"
                )
        fh.write("weird_contig\t100\t.\tA\tC\t.\t.\t.\n")
        fh.write("1\tnot_a_pos\t.\tA\tC\t.\t.\t.\n")  # malformed


def _run_load(tmp_path, vcf, mode, monkeypatch, tag, fail_at=None,
              reuse=None):
    """One committed load in the given pipeline mode; returns
    (counters_or_exception, store, loader, save_dir)."""
    monkeypatch.setenv("AVDB_PIPELINE", mode)
    if reuse is None:
        store = VariantStore(width=49)
        ledger = AlgorithmLedger(str(tmp_path / f"ledger.{tag}.jsonl"))
        loader = TpuVcfLoader(store, ledger, batch_size=256,
                              log=lambda *a: None)
    else:
        store, loader = reuse
    save_dir = str(tmp_path / f"vdb.{tag}")
    err = None
    try:
        counters = loader.load_file(
            vcf, commit=True, fail_at=fail_at,
            persist=lambda: store.save(save_dir),
        )
    except RuntimeError as exc:
        counters, err = None, exc
    store.save(save_dir)
    return counters, err, store, loader, save_dir


def _persisted_bytes(save_dir) -> dict:
    """Every persisted file's bytes, with the manifest normalized for the
    per-store uid (the only legitimately differing byte)."""
    out = {}
    for name in sorted(os.listdir(save_dir)):
        with open(os.path.join(save_dir, name), "rb") as f:
            data = f.read()
        if name == "manifest.json":
            m = json.loads(data)
            m.pop("store_uid", None)
            data = json.dumps(m, sort_keys=True).encode()
        out[name] = data
    return out


COUNTER_KEYS = ("variant", "duplicates", "line", "skipped", "malformed")


def test_pipeline_modes_parity(tmp_path, monkeypatch):
    vcf = str(tmp_path / "multi.vcf")
    _write_vcf(vcf)
    c_s, _, store_s, loader_s, dir_s = _run_load(
        tmp_path, vcf, "serial", monkeypatch, "s"
    )
    c_o, _, store_o, loader_o, dir_o = _run_load(
        tmp_path, vcf, "overlapped", monkeypatch, "o"
    )
    loader_s.close(), loader_o.close()
    assert {k: c_s.get(k) for k in COUNTER_KEYS} == \
           {k: c_o.get(k) for k in COUNTER_KEYS}
    assert c_s["duplicates"] > 0  # the fixture actually exercises dedup
    assert c_s["skipped"] > 0 and c_s["malformed"] > 0
    assert store_s.n == store_o.n
    # the persisted stores must be BIT-identical, segment files included
    files_s, files_o = _persisted_bytes(dir_s), _persisted_bytes(dir_o)
    assert list(files_s) == list(files_o)
    for name in files_s:
        assert files_s[name] == files_o[name], f"{name} bytes diverge"


def test_pipeline_modes_parity_through_resume(tmp_path, monkeypatch):
    """A mid-file fault + resumed re-run lands both modes on identical
    stores and resume cursors (failAt fires at PROCESS time in both)."""
    vcf = str(tmp_path / "multi.vcf")
    _write_vcf(vcf)
    results = {}
    for mode, tag in (("serial", "s"), ("overlapped", "o")):
        c1, err, store, loader, save_dir = _run_load(
            tmp_path, vcf, mode, monkeypatch, tag, fail_at="failhere"
        )
        assert c1 is None and "failAt" in str(err)
        partial = store.n
        assert 0 < partial < 3000
        # earlier chunks committed before the fault — exactly like serial
        resume_line = loader.ledger.last_checkpoint(vcf)
        assert resume_line > 0
        c2, err2, store, loader, save_dir = _run_load(
            tmp_path, vcf, mode, monkeypatch, tag,
            reuse=(store, loader),
        )
        assert err2 is None
        loader.close()
        results[mode] = (partial, resume_line, dict(c2), save_dir, store.n)
    p_s, r_s, c_s, dir_s, n_s = results["serial"]
    p_o, r_o, c_o, dir_o, n_o = results["overlapped"]
    assert (p_s, r_s, n_s) == (p_o, r_o, n_o)
    assert {k: c_s.get(k) for k in COUNTER_KEYS} == \
           {k: c_o.get(k) for k in COUNTER_KEYS}
    files_s, files_o = _persisted_bytes(dir_s), _persisted_bytes(dir_o)
    assert list(files_s) == list(files_o)
    for name in files_s:
        assert files_s[name] == files_o[name], f"{name} bytes diverge"
    # no row exists twice despite the replayed chunk
    for store_dir in (dir_s,):
        reloaded = VariantStore.load(store_dir)
        for code, shard in reloaded.shards.items():
            keys = {
                (int(p), int(h))
                for p, h in zip(shard.cols["pos"], shard.cols["h"])
            }
            assert len(keys) == shard.n


def test_overlapped_stage_accounting(tmp_path, monkeypatch):
    """The overlapped stage table measures busy time per stage THREAD:
    with real overlap the per-stage sum exceeds the load's wall-clock —
    proving concurrency is measured rather than hidden inside one
    stage's clock (the honesty property the bench's stage_wall reports)."""
    vcf = str(tmp_path / "multi.vcf")
    _write_vcf(vcf, n_lines=6000)
    monkeypatch.setenv("AVDB_PIPELINE", "overlapped")
    store = VariantStore(width=49)
    ledger = AlgorithmLedger(str(tmp_path / "ledger.jsonl"))
    loader = TpuVcfLoader(store, ledger, batch_size=256, log=lambda *a: None)
    loader.load_file(
        vcf, commit=True,
        persist=lambda: store.save(str(tmp_path / "vdb")),
    )
    loader.close()
    t = loader.timer
    assert t.wall_seconds > 0
    busy = t.total()
    # >= wall: ingest/dispatch run on their own threads and the writer
    # persists concurrently, so their busy seconds stack on top of the
    # process thread's — a serial-measured table could never reach this
    assert busy >= t.wall_seconds, (busy, t.wall_seconds)
    assert t.overlap() >= 1.0
    wd = t.wall_dict()
    assert wd["busy_seconds"] >= wd["wall_seconds"] > 0
    assert wd["overlap"] >= 1.0
    # every pipeline stage is represented in the table
    for stage in ("ingest", "dispatch", "annotate", "lookup", "gather",
                  "build", "append", "persist"):
        assert stage in t.seconds, stage


def test_bounded_stage_propagates_errors_and_closes(monkeypatch):
    """utils.pipeline.BoundedStage: in-order delivery, upstream exception
    re-raised at the consumer, prompt close with a blocked producer."""
    from annotatedvdb_tpu.utils.pipeline import BoundedStage

    # in-order mapping
    stage = BoundedStage(iter(range(8)), fn=lambda x: x * 2, depth=2)
    assert list(stage) == [0, 2, 4, 6, 8, 10, 12, 14]

    # exception in fn surfaces at next()
    def boom(x):
        if x == 3:
            raise ValueError("boom at 3")
        return x

    stage = BoundedStage(iter(range(8)), fn=boom, depth=2)
    got = []
    with pytest.raises(ValueError, match="boom at 3"):
        for item in stage:
            got.append(item)
    assert got == [0, 1, 2]

    # close() unblocks a producer stuck on a full queue and joins it
    import itertools

    stage = BoundedStage(itertools.count(), depth=2)
    assert next(stage) == 0
    stage.close()
    assert not stage._thread.is_alive()
    with pytest.raises(StopIteration):
        next(stage)


def test_chained_stage_teardown_is_prompt_any_order():
    """Aborting a CHAINED pipeline (consumer stops mid-stream) must tear
    both stage threads down promptly in either close order — a downstream
    thread blocked pulling from a closed upstream may never hang on an
    unsignaled queue (the failAt/test-mode abort path)."""
    import itertools
    import time

    from annotatedvdb_tpu.utils.pipeline import BoundedStage

    for upstream_first in (True, False):
        ingest = BoundedStage(itertools.count(), depth=2, name="t-ingest")
        dispatch = BoundedStage(ingest, fn=lambda x: x, depth=2,
                                name="t-dispatch")
        assert next(dispatch) == 0  # pipeline is flowing
        t0 = time.perf_counter()
        if upstream_first:
            ingest.close(), dispatch.close()
        else:
            dispatch.close(), ingest.close()
        dt = time.perf_counter() - t0
        assert dt < 2.0, f"teardown stalled {dt:.1f}s (order={upstream_first})"
        assert not ingest._thread.is_alive()
        assert not dispatch._thread.is_alive(), "dispatch thread leaked"


def test_reader_prefetch_matches_inline_iteration(tmp_path):
    """iter_prefetched hands over the same chunk stream the inline
    iterator produces (same batches, counters, sidecar columns)."""
    from annotatedvdb_tpu.io.vcf import VcfBatchReader

    vcf = str(tmp_path / "m.vcf")
    _write_vcf(vcf, n_lines=700)
    inline = list(VcfBatchReader(vcf, batch_size=128, width=49))
    pre = list(VcfBatchReader(vcf, batch_size=128, width=49)
               .iter_prefetched(depth=2))
    assert len(inline) == len(pre)
    for a, b in zip(inline, pre):
        np.testing.assert_array_equal(a.batch.pos, b.batch.pos)
        np.testing.assert_array_equal(a.batch.ref, b.batch.ref)
        np.testing.assert_array_equal(a.line_number, b.line_number)
        assert a.counters == b.counters
        assert list(a.variant_id) == list(b.variant_id)
