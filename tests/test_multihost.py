"""Multi-host wiring (``parallel/multihost.py``): env contract + a real
single-process ``jax.distributed`` world running the sharded load step.

True multi-host needs multiple machines; a num_processes=1 world exercises
the same initialization path, and the distributed step's collectives are
already covered on the virtual 8-device mesh (``test_distributed.py``)."""

import socket
import subprocess
import sys

from annotatedvdb_tpu.parallel.multihost import multihost_env


def test_multihost_env_contract(monkeypatch):
    for var in ("AVDB_COORDINATOR", "AVDB_NUM_PROCESSES", "AVDB_PROCESS_ID",
                "JAX_COORDINATOR_ADDRESS"):
        monkeypatch.delenv(var, raising=False)
    assert multihost_env() is None  # plain single-host run
    monkeypatch.setenv("AVDB_COORDINATOR", "10.0.0.1:8476")
    monkeypatch.setenv("AVDB_NUM_PROCESSES", "4")
    monkeypatch.setenv("AVDB_PROCESS_ID", "2")
    assert multihost_env() == {
        "coordinator_address": "10.0.0.1:8476",
        "num_processes": 4,
        "process_id": 2,
    }
    # the standard JAX variable also works
    monkeypatch.delenv("AVDB_COORDINATOR")
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.0.0.9:8476")
    env = multihost_env()
    assert env["coordinator_address"] == "10.0.0.9:8476"
    assert env["num_processes"] == 4 and env["process_id"] == 2


def test_single_process_distributed_world(tmp_path):
    """init_multihost joins a real (1-process) jax.distributed world and the
    sharded annotate step runs over it — in a subprocess, because the
    distributed runtime binds the process's backend for good."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    src = f"""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["AVDB_JAX_PLATFORM"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=4"
)
os.environ["AVDB_COORDINATOR"] = "127.0.0.1:{port}"
os.environ["AVDB_NUM_PROCESSES"] = "1"
os.environ["AVDB_PROCESS_ID"] = "0"
import jax
jax.config.update("jax_platforms", "cpu")
from annotatedvdb_tpu.parallel import (
    distributed_annotate_step, init_multihost, make_mesh, process_info,
)
assert init_multihost()
assert process_info() == (0, 1)
from annotatedvdb_tpu.io.synth import synthetic_batch
import numpy as np
mesh = make_mesh(4)
batch = synthetic_batch(256, width=16)
ann, rid, counts, dropped, n_fb = distributed_annotate_step(mesh, batch)
assert int(np.asarray(dropped)) == 0
total = int(np.asarray(counts).sum()) + int(np.asarray(n_fb))
assert total == batch.n, (total, batch.n)
print("DISTRIBUTED_WORLD_OK")
"""
    res = subprocess.run(
        [sys.executable, "-c", src], capture_output=True, text=True,
        timeout=600,
    )
    assert res.returncode == 0, res.stderr[-2000:]
    assert "DISTRIBUTED_WORLD_OK" in res.stdout
