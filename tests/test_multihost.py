"""Multi-host wiring (``parallel/multihost.py``): env contract + a real
single-process ``jax.distributed`` world running the sharded load step.

True multi-host needs multiple machines; a num_processes=1 world exercises
the same initialization path, and the distributed step's collectives are
already covered on the virtual 8-device mesh (``test_distributed.py``)."""

import os
import socket
import subprocess
import sys

import pytest

from annotatedvdb_tpu.parallel.multihost import multihost_env


def test_multihost_env_contract(monkeypatch):
    for var in ("AVDB_COORDINATOR", "AVDB_NUM_PROCESSES", "AVDB_PROCESS_ID",
                "JAX_COORDINATOR_ADDRESS"):
        monkeypatch.delenv(var, raising=False)
    assert multihost_env() is None  # plain single-host run
    monkeypatch.setenv("AVDB_COORDINATOR", "10.0.0.1:8476")
    monkeypatch.setenv("AVDB_NUM_PROCESSES", "4")
    monkeypatch.setenv("AVDB_PROCESS_ID", "2")
    assert multihost_env() == {
        "coordinator_address": "10.0.0.1:8476",
        "num_processes": 4,
        "process_id": 2,
    }
    # the standard JAX variable also works
    monkeypatch.delenv("AVDB_COORDINATOR")
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.0.0.9:8476")
    env = multihost_env()
    assert env["coordinator_address"] == "10.0.0.9:8476"
    assert env["num_processes"] == 4 and env["process_id"] == 2


def test_single_process_distributed_world(tmp_path):
    """init_multihost joins a real (1-process) jax.distributed world and the
    sharded annotate step runs over it — in a subprocess, because the
    distributed runtime binds the process's backend for good."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    src = f"""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["AVDB_JAX_PLATFORM"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=4"
)
os.environ["AVDB_COORDINATOR"] = "127.0.0.1:{port}"
os.environ["AVDB_NUM_PROCESSES"] = "1"
os.environ["AVDB_PROCESS_ID"] = "0"
import jax
jax.config.update("jax_platforms", "cpu")
from annotatedvdb_tpu.parallel import (
    distributed_annotate_step, init_multihost, make_mesh, process_info,
)
assert init_multihost()
assert process_info() == (0, 1)
from annotatedvdb_tpu.io.synth import synthetic_batch
import numpy as np
mesh = make_mesh(4)
batch = synthetic_batch(256, width=16)
ann, rid, counts, dropped, n_fb = distributed_annotate_step(mesh, batch)
assert int(np.asarray(dropped)) == 0
total = int(np.asarray(counts).sum()) + int(np.asarray(n_fb))
assert total == batch.n, (total, batch.n)
print("DISTRIBUTED_WORLD_OK")
"""
    res = subprocess.run(
        [sys.executable, "-c", src], capture_output=True, text=True,
        timeout=600,
    )
    assert res.returncode == 0, res.stderr[-2000:]
    assert "DISTRIBUTED_WORLD_OK" in res.stdout


_WORKER_SRC = """
import os, sys
port, pid, n_procs, local_dev = sys.argv[1:5]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["AVDB_JAX_PLATFORM"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=" + local_dev
)
os.environ["AVDB_COORDINATOR"] = "127.0.0.1:" + port
os.environ["AVDB_NUM_PROCESSES"] = n_procs
os.environ["AVDB_PROCESS_ID"] = pid
import jax
jax.config.update("jax_platforms", "cpu")
from annotatedvdb_tpu.parallel import init_multihost, make_mesh, process_info
from annotatedvdb_tpu.parallel.distributed import (
    distributed_annotate_step, position_block_owner,
)
assert init_multihost()
assert process_info() == (int(pid), int(n_procs))
assert len(jax.devices()) == int(n_procs) * int(local_dev), jax.devices()
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P
from annotatedvdb_tpu.io.synth import synthetic_batch
from annotatedvdb_tpu.parallel.mesh import SHARD_AXIS
from annotatedvdb_tpu.types import VariantBatch

n_global = int(n_procs) * int(local_dev)
mesh = make_mesh(n_global)
batch = synthetic_batch(256, width=16)  # same seed in every process
owner = position_block_owner(batch.chrom, batch.pos, n_global)
sharding = NamedSharding(mesh, P(SHARD_AXIS))
dev = VariantBatch(*(jax.device_put(x, sharding) for x in batch))
ann, rid, counts, dropped, n_fb = distributed_annotate_step(
    mesh, dev, owner=owner
)
jax.block_until_ready(counts)
print("COUNTS", np.asarray(counts).tolist(), int(np.asarray(dropped)),
      int(np.asarray(n_fb)), flush=True)
"""


def _run_world(n_procs: int, local_dev: int) -> list[str]:
    """Spawn a real jax.distributed world on an ephemeral loopback
    coordinator and return each process's COUNTS line (asserting they all
    agree)."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _WORKER_SRC, str(port), str(pid),
             str(n_procs), str(local_dev)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for pid in range(n_procs)
    ]
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=540)
        if p.returncode != 0 and (
                "Multiprocess computations aren't implemented" in err):
            # this jax/XLA build has no cross-process CPU collectives —
            # an environment limit, not a wiring bug (the 1-process world
            # and the virtual 8-device mesh still cover the step)
            for q in procs:
                q.kill()
            pytest.skip(
                "jax build lacks multiprocess CPU collectives"
            )
        assert p.returncode == 0, (out[-1000:], err[-3000:])
        outs.append(out)
    lines = [
        next(l for l in out.splitlines() if l.startswith("COUNTS"))
        for out in outs
    ]
    assert len(set(lines)) == 1, ("processes disagree", lines)
    return lines


def _ground_truth_counts() -> str:
    """Single-process 8-device run of the same seeded batch."""
    import numpy as np

    from annotatedvdb_tpu.io.synth import synthetic_batch
    from annotatedvdb_tpu.parallel import make_mesh
    from annotatedvdb_tpu.parallel.distributed import (
        distributed_annotate_step,
        position_block_owner,
    )

    mesh = make_mesh(8)
    batch = synthetic_batch(256, width=16)
    owner = position_block_owner(batch.chrom, batch.pos, 8)
    _ann, _rid, counts, dropped, n_fb = distributed_annotate_step(
        mesh, batch, owner=owner
    )
    return (
        f"COUNTS {np.asarray(counts).tolist()} "
        f"{int(np.asarray(dropped))} {int(np.asarray(n_fb))}"
    )


def test_two_process_distributed_world():
    """Two REAL jax.distributed processes (loopback coordinator, 4 virtual
    CPU devices each) run the sharded annotate step over the global
    8-device mesh; psum'd counters must agree across processes AND match a
    single-process 8-device run of the same batch (the reference's only
    concurrency analog is its 10-process worker pool,
    load_vcf_file.py:307-313 — this is the first >1-process exercise of
    ours)."""
    lines = _run_world(n_procs=2, local_dev=4)
    want = _ground_truth_counts()
    assert lines[0] == want, (lines[0], want)


@pytest.mark.skipif(
    not os.environ.get("AVDB_SCALE_TEST"),
    reason="4-process world: set AVDB_SCALE_TEST=1 (4 concurrent compiles "
           "on a 1-core host run ~minutes)",
)
def test_four_process_distributed_world():
    """Four REAL jax.distributed processes (2 virtual devices each, global
    8-device mesh) agree with the single-process ground truth — the
    >2-process exercise of SURVEY §5.8's comm backend (the reference fans
    10 OS processes; collectives here cross process boundaries 4 ways)."""
    lines = _run_world(n_procs=4, local_dev=2)
    want = _ground_truth_counts()
    assert lines[0] == want, (lines[0], want)
