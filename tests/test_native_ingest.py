"""Native C++ VCF tokenizer vs the pure Python reader: chunk-level parity.

The native engine (``native/avdb_native.cpp`` via
``annotatedvdb_tpu/native``) must emit byte-identical chunks so the two
engines are freely interchangeable behind ``VcfBatchReader(engine=...)``.
"""

import gzip

import numpy as np
import pytest

from annotatedvdb_tpu import native
from annotatedvdb_tpu.io.vcf import VcfBatchReader

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library unavailable (no g++)"
)

# exercises: multi-allelic expansion, '.' alt skipping, unplaceable contigs,
# chr prefixes, MT folding, rs ids in ID and INFO, FREQ parsing, missing
# trailing columns, '.' QUAL/FILTER, over-width alleles, malformed POS,
# blank lines, no trailing newline
TRICKY_VCF = """\
##fileformat=VCFv4.2
#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\tFORMAT
1\t100\trs1\tA\tG\t50\tPASS\tRS=1;RSPOS=100;FREQ=GnomAD:0.5,0.25|TOPMED:.,0.1\tGT
chr2\t200\t.\tC\tT,CA,.\t.\t.\tRS=2
MT\t300\tweird_rs_id\tG\tA\t.\tLOWQ\t.
GL000219.1\t400\t.\tA\tC\t.\t.\t.
3\t500\tcustomid\tACGT\tA
4\tnotanumber\t.\tA\tC\t.\t.\t.
5\t600\t.\t{LONG}\tT\t.\t.\tAF=0.1

X\t700\trs7\tT\t.\t.\t.\t.
Y\t800\t.\tAT\tATAT\t9.5\tq10;s50\tDP=100
""".replace("{LONG}", "A" * 80)


def write_vcf(tmp_path, text, gz=False):
    if gz:
        p = tmp_path / "t.vcf.gz"
        with gzip.open(p, "wt") as f:
            f.write(text)
    else:
        p = tmp_path / "t.vcf"
        p.write_text(text)
    return str(p)


def read_all(path, **kw):
    return list(VcfBatchReader(path, **kw))


def assert_chunks_equal(a_chunks, b_chunks):
    def flat(chunks, attr):
        out = []
        for c in chunks:
            out.extend(getattr(c, attr))
        return out

    for attr in ("refs", "alts", "ref_snp", "variant_id", "qual", "filter",
                 "format", "rs_position", "frequencies", "info"):
        assert flat(a_chunks, attr) == flat(b_chunks, attr), attr
    for arr in ("chrom", "pos", "ref_len", "alt_len", "ref", "alt"):
        pa = np.concatenate([np.asarray(getattr(c.batch, arr)) for c in a_chunks])
        na = np.concatenate([np.asarray(getattr(c.batch, arr)) for c in b_chunks])
        assert (pa == na).all(), arr
    for attr in ("is_multi_allelic", "line_number", "rs_number"):
        pa = np.concatenate([np.asarray(getattr(c, attr)) for c in a_chunks])
        na = np.concatenate([np.asarray(getattr(c, attr)) for c in b_chunks])
        assert (pa == na).all(), attr
    # the int rs column must agree with the loaders' parse of the string one
    from annotatedvdb_tpu.loaders.vcf_loader import _rs_number

    # strict-digit rule: engines must agree on pathological IDs too
    assert _rs_number("rs1_2") == -1
    assert _rs_number("rs+12") == -1
    assert _rs_number("rs 12") == -1
    assert _rs_number("rs0012") == 12
    # wider than int64: 'weird' (-1), never an overflow crash or wrap
    assert _rs_number("rs99999999999999999999") == -1

    for chunks in (a_chunks, b_chunks):
        for c in chunks:
            for i in range(c.batch.n):
                assert c.rs_number[i] == _rs_number(c.ref_snp[i]), (
                    c.ref_snp[i]
                )
    for key in ("line", "skipped_contig", "skipped_alt"):
        assert (
            sum(c.counters.get(key, 0) for c in a_chunks)
            == sum(c.counters.get(key, 0) for c in b_chunks)
        ), key


@pytest.mark.parametrize("identity_only", [False, True])
@pytest.mark.parametrize("gz", [False, True])
def test_native_python_parity(tmp_path, identity_only, gz):
    path = write_vcf(tmp_path, TRICKY_VCF, gz=gz)
    py = read_all(path, engine="python", identity_only=identity_only, width=16)
    nat = read_all(path, engine="native", identity_only=identity_only, width=16)
    assert sum(c.batch.n for c in py) == sum(c.batch.n for c in nat)
    assert_chunks_equal(py, nat)


def test_native_batch_boundaries(tmp_path):
    """Tiny batch_size forces capacity re-feeds; a multi-allelic line must
    never straddle chunks, and nothing is double-counted."""
    path = write_vcf(tmp_path, TRICKY_VCF)
    py = read_all(path, engine="python", batch_size=2, width=16)
    nat = read_all(path, engine="native", batch_size=2, width=16)
    assert_chunks_equal(py, nat)
    # rows of one source line (multi-allelic expansion) share a chunk
    seen = {}
    for ci, c in enumerate(nat):
        for ln in np.asarray(c.line_number):
            seen.setdefault(int(ln), set()).add(ci)
    assert all(len(v) == 1 for v in seen.values())


def test_native_over_width_fallback(tmp_path):
    path = write_vcf(tmp_path, TRICKY_VCF)
    (chunk,) = read_all(path, engine="native", width=16)
    long_rows = np.where(np.asarray(chunk.batch.ref_len) > 16)[0]
    assert long_rows.size == 1
    i = int(long_rows[0])
    assert chunk.refs[i] == "A" * 80          # original string via lazy span
    assert chunk.batch.ref_len[i] == 80       # true length beyond the width


def test_rs_info_fallback_parity(tmp_path):
    """Pathological INFO RS= forms: the native scan must mirror the Python
    chain (to_numeric/int() coercion then re-print), per-engine and
    cross-engine."""
    vcf = "\n".join([
        "##fileformat=VCFv4.2",
        "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO",
        "1\t100\t.\tA\tG\t.\t.\tRS=+12",       # int('+12') == 12
        "1\t200\t.\tA\tG\t.\t.\tRS=1_2",       # int('1_2') == 12
        "1\t300\t.\tA\tG\t.\t.\tRS=1;RS=2",    # last key wins
        "1\t400\t.\tA\tG\t.\t.\tRS=-5",        # 'rs-5' -> -1
        "1\t500\t.\tA\tG\t.\t.\tRS=1.5",       # float -> 'rs1.5' -> -1
        "1\t600\t.\tA\tG\t.\t.\tRS=_1",        # int() rejects -> -1
        "1\t700\t.\tA\tG\t.\t.\tRS=1__2",      # int() rejects -> -1
        "1\t800\t.\tA\tG\t.\t.\tRS=",          # empty -> -1
        "1\t900\t.\tA\tG\t.\t.\tRS= 12",       # int() strips whitespace
        "1\t950\trs99999999999999999999\tA\tG\t.\t.\t.\n"
        "1\t960\t.\tA\tG\t.\t.\tRS=99999999999999999999",  # > int64
        # int64 boundary: both engines share the pre-multiply bound
        # ((2^63-10)//10), so the largest accepted id is ...799 and ids
        # within 8 of INT64_MAX are rejected by BOTH (they diverged here
        # once: Python post-add accepted ...800-807, C++ rejected)
        "1\t970\trs9223372036854775799\tA\tG\t.\t.\t.",   # max accepted
        "1\t980\trs9223372036854775807\tA\tG\t.\t.\t.",   # INT64_MAX -> -1
        "1\t990\t.\tA\tG\t.\t.\tRS=9223372036854775799",  # max accepted
        "1\t995\t.\tA\tG\t.\t.\tRS=9223372036854775800",  # in-window -> -1
    ]) + "\n"
    path = write_vcf(tmp_path, vcf)
    py = read_all(path, engine="python", width=16)
    nat = read_all(path, engine="native", width=16)
    assert_chunks_equal(py, nat)
    got = np.concatenate([c.rs_number for c in nat]).tolist()
    assert got == [12, 12, 2, -1, -1, -1, -1, -1, 12, -1, -1,
                   9223372036854775799, -1, 9223372036854775799, -1]


def test_native_prepacked_alleles_match_host_encoder(tmp_path):
    """The tokenizer's inline nibble pack == ops.pack.encode_alleles_nibble
    over the same byte matrices; chunks with symbolic alleles ship none."""
    from annotatedvdb_tpu.ops.pack import encode_alleles_nibble

    path = write_vcf(tmp_path, TRICKY_VCF)
    for chunk in read_all(path, engine="native", width=16):
        enc = encode_alleles_nibble(
            np.asarray(chunk.batch.ref), np.asarray(chunk.batch.alt)
        )
        # both directions: the C++ and Python alphabets must agree on
        # WHETHER the chunk packs, not just on the packed bytes
        assert (chunk.ref_packed is None) == (enc is None)
        if enc is not None:
            assert (chunk.ref_packed == enc[0]).all()
            assert (chunk.alt_packed == enc[1]).all()

    sym = (
        "##fileformat=VCFv4.2\n#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\n"
        "1\t100\t.\tA\t<DEL>\t.\t.\t.\n"
    )
    (tmp_path / "s").mkdir()
    p2 = write_vcf(tmp_path / "s", sym)
    (chunk,) = read_all(p2, engine="native", width=16)
    assert chunk.ref_packed is None  # symbolic allele blocks chunk packing
    assert chunk.alleles_packable is False


def test_native_counters(tmp_path):
    path = write_vcf(tmp_path, TRICKY_VCF)
    (chunk,) = read_all(path, engine="native", width=16)
    assert chunk.counters["skipped_contig"] == 1   # GL000219.1
    assert chunk.counters["skipped_alt"] == 2      # '.' in multi-allelic + X's '.'
    assert chunk.counters["malformed"] == 1        # POS 'notanumber'


def test_native_engine_forced_errors_without_library(monkeypatch, tmp_path):
    import annotatedvdb_tpu.native as nat_mod

    monkeypatch.setattr(nat_mod, "available", lambda: False)
    path = write_vcf(tmp_path, TRICKY_VCF)
    with pytest.raises(RuntimeError, match="native ingest engine unavailable"):
        list(VcfBatchReader(path, engine="native"))
    # auto falls back silently
    assert list(VcfBatchReader(path, engine="auto", width=16))


# trailing filtered lines + an out-of-int32-range position: both engines must
# count them identically even though no data row follows
TRAILING_SKIP_VCF = """\
#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO
1\t100\trs1\tA\tG\t.\t.\t.
2\t3000000000\t.\tA\tC\t.\t.\t.
GL000219.1\t400\t.\tA\tC\t.\t.\t.
"""


@pytest.mark.parametrize("engine", ["python", "native"])
def test_trailing_skip_counters_survive(tmp_path, engine):
    p = tmp_path / "t.vcf"
    p.write_text(TRAILING_SKIP_VCF)
    chunks = list(VcfBatchReader(str(p), engine=engine, width=16, batch_size=1))
    totals = {}
    for c in chunks:
        for k, v in c.counters.items():
            totals[k] = totals.get(k, 0) + v
    assert totals["line"] == 3
    assert totals["malformed"] == 1       # pos > 2^31
    assert totals["skipped_contig"] == 1
    assert sum(c.batch.n for c in chunks) == 1


def test_loader_tolerates_trailing_counter_chunk(tmp_path):
    from annotatedvdb_tpu.loaders import TpuVcfLoader
    from annotatedvdb_tpu.store import AlgorithmLedger, VariantStore

    p = tmp_path / "t.vcf"
    p.write_text(TRAILING_SKIP_VCF)
    store = VariantStore(width=16)
    ledger = AlgorithmLedger(str(tmp_path / "ledger.jsonl"))
    counters = TpuVcfLoader(store, ledger, log=lambda *a: None).load_file(
        str(p), commit=True
    )
    assert counters["variant"] == 1
    assert counters["malformed"] == 1
    assert counters["skipped"] == 1       # the contig line
    assert counters["line"] == 3


def test_native_forced_with_chromosome_map_raises(tmp_path):
    p = tmp_path / "t.vcf"
    p.write_text(TRAILING_SKIP_VCF)
    with pytest.raises(RuntimeError, match="chromosome_map"):
        list(VcfBatchReader(str(p), engine="native",
                            chromosome_map={"NC_1": "1"}))


def test_native_hash_matches_kernel(tmp_path):
    """The tokenizer's in-scan FNV hash is the bit-exact twin of
    ops.hashing.allele_hash over the width-bounded arrays (membership and
    dedup compare the two, so they must never diverge)."""
    from annotatedvdb_tpu.ops.hashing import allele_hash_np

    path = write_vcf(tmp_path, TRICKY_VCF)
    for chunk in VcfBatchReader(path, batch_size=4, width=16,
                                engine="native"):
        if chunk.batch.n == 0:
            continue
        assert chunk.h_native is not None
        want = allele_hash_np(
            chunk.batch.ref, chunk.batch.alt,
            chunk.batch.ref_len, chunk.batch.alt_len,
        )
        np.testing.assert_array_equal(chunk.h_native, want)


def test_subset_chunk_subsets_all_sidecars(tmp_path):
    """_subset_chunk must subset every per-row numpy sidecar: a stale
    full-length rs_number column made novel-row inserts store the WRONG
    rs ids (regression)."""
    from annotatedvdb_tpu.loaders.update_loader import _subset_chunk

    path = write_vcf(tmp_path, TRICKY_VCF)
    [chunk] = [
        c for c in VcfBatchReader(path, batch_size=64, width=16,
                                  engine="native")
        if c.batch.n
    ]
    rows = [2, 4]
    sub = _subset_chunk(chunk, rows)
    assert sub.batch.n == 2
    np.testing.assert_array_equal(sub.rs_number, chunk.rs_number[rows])
    np.testing.assert_array_equal(sub.h_native, chunk.h_native[rows])
    np.testing.assert_array_equal(sub.rs_weird, chunk.rs_weird[rows])
    np.testing.assert_array_equal(sub.id_verbatim, chunk.id_verbatim[rows])
    np.testing.assert_array_equal(sub.has_freq, chunk.has_freq[rows])
