"""TSV annotation-load tests (reference ``txt_variant_loader.py`` +
``update_variant_annotation.py``)."""

import json
import subprocess
import sys

import numpy as np
import pytest

from annotatedvdb_tpu.loaders import TpuTextLoader, TpuVcfLoader
from annotatedvdb_tpu.loaders.txt_loader import (
    coerce_update_value, parse_variant_id,
)
from annotatedvdb_tpu.store import AlgorithmLedger, VariantStore

BASE_VCF = """##fileformat=VCFv4.2
#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO
1\t100\trs11\tA\tG\t.\t.\t.
1\t200\t.\tC\tT\t.\t.\t.
2\t100\trs22\tT\tA\t.\t.\t.
"""


def build_store(tmp_path):
    store = VariantStore(width=49)
    ledger = AlgorithmLedger(str(tmp_path / "ledger.jsonl"))
    vcf = tmp_path / "base.vcf"
    vcf.write_text(BASE_VCF)
    TpuVcfLoader(store, ledger, log=lambda *a: None).load_file(str(vcf), commit=True)
    return store, ledger


def find_row(store, code, pos):
    shard = store.shard(code)
    i = int(np.searchsorted(shard.cols["pos"], pos))
    assert shard.cols["pos"][i] == pos
    return shard, i


def write_tsv(path, header, rows):
    lines = ["\t".join(header)] + ["\t".join(r) for r in rows]
    path.write_text("\n".join(lines) + "\n")


def test_parse_variant_id():
    assert parse_variant_id("1:100:A:G", "METASEQ") == (1, 100, "A", "G", None)
    assert parse_variant_id("X:5:AC:-", "METASEQ") == (23, 5, "AC", "-", None)
    assert parse_variant_id("1:100:A:G:rs11", "PRIMARY_KEY") == (
        1, 100, "A", "G", "rs11"
    )
    # digest-form PK: alleles unknown
    code, pos, ref, alt, rs = parse_variant_id(
        "1:100:GnDKL2Ax6uVVmPPDKEC17BsPB4ACKEHx:rs99", "PRIMARY_KEY"
    )
    assert (code, pos, ref, alt, rs) == (1, 100, None, None, "rs99")
    assert parse_variant_id("rs22", "REFSNP")[4] == "rs22"
    with pytest.raises(ValueError):
        parse_variant_id("1:100:GnDKL2Ax6uVVmPPDKEC17BsPB4ACKEHx", "METASEQ")


def test_coerce_update_value():
    assert coerce_update_value("gwas_flags", '{"AD": true}') == {"AD": True}
    assert coerce_update_value("gwas_flags", "NULL") is None
    assert coerce_update_value("is_adsp_variant", "true") == 1
    assert coerce_update_value("is_adsp_variant", "False") == 0
    assert coerce_update_value("ref_snp_id", "rs123") == "rs123"
    with pytest.raises(ValueError, match="invalid JSON"):
        coerce_update_value("gwas_flags", "{notjson")


def test_tsv_update_known_variants(tmp_path):
    store, ledger = build_store(tmp_path)
    tsv = tmp_path / "ann.tsv"
    write_tsv(
        tsv,
        ["variant", "gwas_flags", "ref_snp_id"],
        [
            ["1:100:A:G", '{"ADGC": {"pvalue": 1e-8}}', "NULL"],
            ["1:200:C:T", '{"IGAP": {"pvalue": 0.5}}', "rs33"],
        ],
    )
    loader = TpuTextLoader(store, ledger, log=lambda *a: None)
    counters = loader.load_file(str(tsv), commit=True)
    assert counters["update"] == 2
    assert counters["inserted"] == 0
    assert store.n == 3

    shard, i = find_row(store, 1, 100)
    assert shard.annotations["gwas_flags"][i] == {"ADGC": {"pvalue": 1e-8}}
    shard, i = find_row(store, 1, 200)
    assert shard.cols["ref_snp"][i] == 33  # ref_snp_id column applied

    # second file merges (jsonb_merge), not replaces
    tsv2 = tmp_path / "ann2.tsv"
    write_tsv(tsv2, ["variant", "gwas_flags"],
              [["1:100:A:G", '{"IGAP": {"pvalue": 0.01}}']])
    TpuTextLoader(store, ledger, log=lambda *a: None).load_file(
        str(tsv2), commit=True
    )
    shard, i = find_row(store, 1, 100)
    assert set(shard.annotations["gwas_flags"][i]) == {"ADGC", "IGAP"}


def test_tsv_insert_novel_metaseq(tmp_path):
    store, ledger = build_store(tmp_path)
    tsv = tmp_path / "ann.tsv"
    write_tsv(tsv, ["variant", "other_annotation"],
              [["2:900:G:GAT", '{"src": "x"}']])
    counters = TpuTextLoader(store, ledger, log=lambda *a: None).load_file(
        str(tsv), commit=True
    )
    assert counters["inserted"] == 1
    shard, i = find_row(store, 2, 900)
    assert shard.annotations["other_annotation"][i] == {"src": "x"}
    # full insert path ran: identity hash assigned
    assert shard.cols["h"][i] != 0


def test_tsv_refsnp_lookup_and_not_found(tmp_path):
    store, ledger = build_store(tmp_path)
    tsv = tmp_path / "ann.tsv"
    write_tsv(tsv, ["variant", "gwas_flags"],
              [["rs22", '{"hit": 1}'], ["rs404", '{"miss": 1}']])
    counters = TpuTextLoader(
        store, ledger, variant_id_type="REFSNP", log=lambda *a: None
    ).load_file(str(tsv), commit=True)
    assert counters["update"] == 1
    assert counters["not_found"] == 1  # refSNP ids can't insert (no alleles)
    shard, i = find_row(store, 2, 100)
    assert shard.annotations["gwas_flags"][i] == {"hit": 1}


def test_tsv_skip_existing(tmp_path):
    store, ledger = build_store(tmp_path)
    tsv = tmp_path / "ann.tsv"
    write_tsv(tsv, ["variant", "gwas_flags"], [["1:100:A:G", '{"x": 1}']])
    counters = TpuTextLoader(
        store, ledger, update_existing=False, skip_existing=True,
        log=lambda *a: None,
    ).load_file(str(tsv), commit=True)
    assert counters["skipped"] == 1 and counters["update"] == 0
    shard, i = find_row(store, 1, 100)
    assert shard.annotations["gwas_flags"][i] is None


def test_tsv_dry_run(tmp_path):
    store, ledger = build_store(tmp_path)
    tsv = tmp_path / "ann.tsv"
    write_tsv(tsv, ["variant", "gwas_flags"],
              [["1:100:A:G", '{"x": 1}'], ["2:900:G:GAT", '{"y": 2}']])
    counters = TpuTextLoader(store, ledger, log=lambda *a: None).load_file(
        str(tsv), commit=False
    )
    assert counters["update"] >= 1
    assert store.n == 3  # nothing inserted
    shard, i = find_row(store, 1, 100)
    assert shard.annotations["gwas_flags"][i] is None


def test_tsv_cli(tmp_path):
    store, ledger = build_store(tmp_path)
    store_dir = tmp_path / "vdb"
    store.save(str(store_dir))
    tsv = tmp_path / "ann.tsv"
    write_tsv(tsv, ["variant", "gwas_flags"], [["1:100:A:G", '{"AD": true}']])
    res = subprocess.run(
        [sys.executable, "-m", "annotatedvdb_tpu.cli.update_variant_annotation",
         "--fileName", str(tsv), "--storeDir", str(store_dir), "--commit"],
        capture_output=True, text=True, check=True,
    )
    counters = json.loads(res.stdout.splitlines()[0])
    assert counters["update"] == 1
    reloaded = VariantStore.load(str(store_dir))
    shard, i = find_row(reloaded, 1, 100)
    assert shard.annotations["gwas_flags"][i] == {"AD": True}


def test_parse_variant_id_malformed_and_contigs():
    # 2-part id: valid digest-less PK prefix is NOT acceptable as metaseq
    with pytest.raises(ValueError, match="without alleles"):
        parse_variant_id("1:100", "METASEQ")
    # non-standard contig: skipped like VCF ingest's skipped_contig
    with pytest.raises(ValueError, match="unplaceable"):
        parse_variant_id("GL000219.1:100:A:G", "METASEQ")
    # 2-part PRIMARY_KEY parses (digest unknown) and resolves to not-found
    assert parse_variant_id("1:100", "PRIMARY_KEY") == (1, 100, None, None, None)


def test_tsv_malformed_ids_are_skipped_not_fatal(tmp_path):
    store, ledger = build_store(tmp_path)
    tsv = tmp_path / "ann.tsv"
    write_tsv(tsv, ["variant", "gwas_flags"],
              [["1:100", '{"x": 1}'],                  # metaseq without alleles
               ["GL000219.1:100:A:G", '{"x": 1}'],     # unplaceable contig
               ["1:100:A:G", '{"x": 2}']])             # valid
    counters = TpuTextLoader(store, ledger, log=lambda *a: None).load_file(
        str(tsv), commit=True
    )
    assert counters["skipped"] == 2
    assert counters["update"] == 1
    shard, i = find_row(store, 1, 100)
    assert shard.annotations["gwas_flags"][i] == {"x": 2}


def test_tsv_short_primary_key_counts_not_found(tmp_path):
    store, ledger = build_store(tmp_path)
    tsv = tmp_path / "ann.tsv"
    write_tsv(tsv, ["variant", "gwas_flags"], [["1:100", '{"x": 1}']])
    loader = TpuTextLoader(store, ledger, variant_id_type="PRIMARY_KEY",
                           log=lambda *a: None)
    counters = loader.load_file(str(tsv), commit=True)
    assert counters["not_found"] == 1
    assert counters["update"] == 0


def test_tsv_dry_run_counts_novel_once(tmp_path):
    """Dry-run and commit runs must agree: novel rows count as inserted,
    never additionally as update."""
    store, ledger = build_store(tmp_path)
    tsv = tmp_path / "ann.tsv"
    rows = [["5:777:T:TG", '{"n": 1}'], ["5:778:C:A", '{"n": 2}']]
    write_tsv(tsv, ["variant", "gwas_flags"], rows)
    dry = TpuTextLoader(store, ledger, log=lambda *a: None).load_file(
        str(tsv), commit=False, resume=False
    )
    assert dry["inserted"] == 2 and dry["update"] == 0
    wet = TpuTextLoader(store, ledger, log=lambda *a: None).load_file(
        str(tsv), commit=True, resume=False
    )
    assert wet["inserted"] == 2 and wet["update"] == 0
    shard, i = find_row(store, 5, 777)
    assert shard.annotations["gwas_flags"][i] == {"n": 1}
