"""Autonomous storage management: watermark semantics, load-aware
pausing, the shared preemption-retry policy, disk-pressure degradation
(507 on both front ends, SIGKILL-safe, recovery pinned), the extended
heartbeat health slots, and `doctor status`."""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from annotatedvdb_tpu.obs.metrics import MetricsRegistry
from annotatedvdb_tpu.store import VariantStore
from annotatedvdb_tpu.store.maintenance import (
    DiskReserveGuard,
    MaintenanceDaemon,
    store_status,
)
from annotatedvdb_tpu.store.variant_store import Segment
from annotatedvdb_tpu.utils import faults
from annotatedvdb_tpu.utils.retry import retry_preempted

WIDTH = 8


@pytest.fixture(autouse=True)
def _disarm():
    yield
    faults.reset("")


def _fragment(store_dir: str, nseg: int, n: int = 120,
              code: int = 6) -> None:
    """``nseg`` disjoint checkpoint segments on one chromosome — each
    save is a real loader checkpoint, so the store's manifest carries
    ``nseg`` on-disk segment files for the group."""
    store = VariantStore(width=WIDTH)
    shard = store.shard(code)
    for k in range(nseg):
        cols = {
            "pos": np.arange(500 + 50_000 * k, 500 + 50_000 * k + n,
                             dtype=np.int32),
            "h": np.arange(n, dtype=np.uint32) + 1,
            "ref_len": np.full(n, 1, np.int32),
            "alt_len": np.full(n, 1, np.int32),
        }
        shard.append_segment(Segment.build(
            cols, np.full((n, WIDTH), 65, np.uint8),
            np.full((n, WIDTH), 71, np.uint8),
        ))
        shard._starts_cache = None
        store.save(store_dir)


def _amp(daemon: MaintenanceDaemon) -> int:
    return max(daemon.read_amp().values(), default=0)


def _daemon(store_dir, **kw):
    kw.setdefault("high", 4)
    kw.setdefault("low", 2)
    kw.setdefault("tick_s", 0.05)
    kw.setdefault("cooldown_s", 0.0)
    kw.setdefault("log", lambda m: None)
    return MaintenanceDaemon(store_dir, **kw)


def _resume_now(daemon) -> None:
    """Collapse a pending backoff so the next tick evaluates again."""
    with daemon._lock:
        daemon._resume_at = 0.0


# ---------------------------------------------------------------------------
# watermark edge semantics


def test_exactly_at_high_watermark_trips(tmp_path):
    """>= trips: a group holding EXACTLY the high watermark's segment
    count engages the daemon and gets compacted."""
    store_dir = str(tmp_path / "s")
    _fragment(store_dir, nseg=4)
    d = _daemon(store_dir, high=4, low=2)
    assert d.tick() == "pass"
    assert _amp(d) == 1
    assert d.stats()["passes"] == 1


def test_below_high_watermark_stays_idle(tmp_path):
    store_dir = str(tmp_path / "s")
    _fragment(store_dir, nseg=3)
    d = _daemon(store_dir, high=4, low=2)
    assert d.tick() == "idle"
    assert _amp(d) == 3  # byte-untouched: no pass ran
    assert d.stats()["passes"] == 0


def test_hysteresis_exit_below_low_watermark(tmp_path):
    """Engaged state ends only once every group is at/below LOW — and a
    store sitting BETWEEN low and high never re-engages (that is the
    hysteresis: entry and exit are different lines)."""
    store_dir = str(tmp_path / "s")
    _fragment(store_dir, nseg=5)
    d = _daemon(store_dir, high=4, low=2)
    assert d.tick() == "pass"
    assert d.stats()["engaged"] is False  # converged: amp 1 <= low 2
    # grow the store back to BETWEEN low and high: 3 segments
    store = VariantStore.load(store_dir)
    shard = store.shard(6)
    for k in range(2):
        n = 50
        cols = {
            "pos": np.arange(9_000_000 + 50_000 * k,
                             9_000_000 + 50_000 * k + n, dtype=np.int32),
            "h": np.arange(n, dtype=np.uint32) + 7,
            "ref_len": np.full(n, 1, np.int32),
            "alt_len": np.full(n, 1, np.int32),
        }
        shard.append_segment(Segment.build(
            cols, np.full((n, WIDTH), 65, np.uint8),
            np.full((n, WIDTH), 84, np.uint8),
        ))
        shard._starts_cache = None
        store.save(store_dir)
    assert _amp(d) == 3  # low < 3 < high
    assert d.tick() == "idle"  # engaged only at >= high, never between
    assert d.stats()["passes"] == 1


def test_compact_min_segments_floor_wins_over_watermark(tmp_path,
                                                        monkeypatch):
    """A compactor floor ABOVE the watermark makes every pass a no-op;
    the daemon must disengage instead of spinning no-op passes."""
    store_dir = str(tmp_path / "s")
    _fragment(store_dir, nseg=5)
    monkeypatch.setenv("AVDB_COMPACT_MIN_SEGMENTS", "99")
    d = _daemon(store_dir, high=4, low=2, cooldown_s=5.0)
    assert d.tick() == "noop"
    assert _amp(d) == 5  # floor won: nothing was merged
    st = d.stats()
    assert st["engaged"] is False and st["passes"] == 0
    # the watermark condition persists, so without a cooldown the next
    # tick would re-engage/re-plan/re-log the same pair forever — the
    # noop installed a backoff instead of a hammering loop
    assert d.tick() == "cooldown"
    assert st["backoff_s"] >= 0.0
    _resume_now(d)
    assert d.tick() == "noop"  # re-evaluates after the backoff only


def test_backoff_doubles_on_repeated_preemptions(tmp_path, monkeypatch):
    """Repeated clean preemptions back the daemon off exponentially —
    never a tight retry loop against a busy writer."""
    store_dir = str(tmp_path / "s")
    _fragment(store_dir, nseg=5)
    d = _daemon(store_dir, high=4, low=2, cooldown_s=10.0, retries=0)
    monkeypatch.setattr(
        d, "_compact_once",
        lambda: {"status": "aborted", "reason": "test writer"},
    )
    assert d.tick() == "preempted"
    st1 = d.stats()
    assert st1["preemptions"] == 1
    assert 9.0 < st1["backoff_s"] <= 10.0
    assert d.tick() == "cooldown"  # the backoff actually holds
    _resume_now(d)
    assert d.tick() == "preempted"
    st2 = d.stats()
    assert st2["preemptions"] == 2
    assert 19.0 < st2["backoff_s"] <= 20.0  # doubled
    assert st2["engaged"] is True  # still committed to converging


def test_retry_preempted_is_used_before_backoff(tmp_path, monkeypatch):
    """The shared preemption-retry policy: one clean preemption retries
    in-pass (the chaos-soak behavior, hoisted); only a pass that stays
    preempted after the retries becomes a setback."""
    store_dir = str(tmp_path / "s")
    _fragment(store_dir, nseg=5)
    d = _daemon(store_dir, high=4, low=2, retries=1)
    calls = {"n": 0}
    real = d._compact_once

    def flaky():
        calls["n"] += 1
        if calls["n"] == 1:
            return {"status": "aborted", "reason": "racing writer"}
        return real()

    monkeypatch.setattr(d, "_compact_once", flaky)
    assert d.tick() == "pass"
    assert calls["n"] == 2  # aborted once, retried, landed
    assert d.stats()["preemptions"] == 0


def test_paused_when_worker_health_hot_resumes_when_calm(tmp_path):
    """Load-awareness: brownout >= 1 (or a breached p99 target) on any
    live worker pauses the daemon BEFORE it opens a segment; calm health
    resumes it after the cool-down."""
    store_dir = str(tmp_path / "s")
    _fragment(store_dir, nseg=5)
    health = {"brownout_max": 1, "exceed_max": 0.0}
    d = _daemon(store_dir, high=4, low=2, cooldown_s=5.0,
                health=lambda: dict(health))
    assert d.tick() == "paused"
    assert _amp(d) == 5  # the pass never started
    assert d.stats()["paused"] == 1
    # p99-exceedance alone is also hot
    health.update(brownout_max=0, exceed_max=0.2)
    _resume_now(d)
    d._hot_check_at = 0.0  # drop the health cache
    assert d.tick() == "paused"
    # calm again: the pass runs
    health.update(exceed_max=0.0)
    _resume_now(d)
    d._hot_check_at = 0.0
    assert d.tick() == "pass"
    assert _amp(d) == 1


def test_mid_pass_health_abort_counts_as_paused(tmp_path, monkeypatch):
    """A pass our own health cancel aborted mid-run reports as a PAUSE
    (the brownout-paused-compaction observable the soak asserts on)."""
    store_dir = str(tmp_path / "s")
    _fragment(store_dir, nseg=5)
    calls = {"n": 0}

    def health():
        calls["n"] += 1
        # calm at the pre-pass gate, hot at the post-abort check
        return {"brownout_max": 0 if calls["n"] == 1 else 1,
                "exceed_max": 0.0}

    d = _daemon(store_dir, high=4, low=2, cooldown_s=1.0, retries=0,
                health=health)
    monkeypatch.setattr(
        d, "_compact_once",
        lambda: {"status": "aborted", "reason": "cancelled mid-merge"},
    )
    assert d.tick() == "paused"
    st = d.stats()
    assert st["paused"] == 1 and st["preemptions"] == 1


def test_daemon_disables_after_consecutive_hard_failures(tmp_path,
                                                         monkeypatch):
    """Hard failures back off and, after MAX_CONSEC_FAILURES, disable
    the daemon loudly — never a compact-crash loop."""
    store_dir = str(tmp_path / "s")
    _fragment(store_dir, nseg=5)
    logs: list = []
    d = _daemon(store_dir, high=4, low=2, cooldown_s=0.0, retries=0,
                log=logs.append)

    def boom():
        raise OSError("disk on fire")

    monkeypatch.setattr(d, "_compact_once", boom)
    for _ in range(MaintenanceDaemon.MAX_CONSEC_FAILURES):
        _resume_now(d)
        assert d.tick() == "failed"
    st = d.stats()
    assert st["disabled"] is True
    assert st["failures"] == MaintenanceDaemon.MAX_CONSEC_FAILURES
    assert d.tick() == "disabled"  # permanently out, no more passes
    assert any("DISABLED" in m for m in logs)


def test_daemon_metrics_registered(tmp_path):
    store_dir = str(tmp_path / "s")
    _fragment(store_dir, nseg=4)
    registry = MetricsRegistry()
    d = _daemon(store_dir, high=4, low=2, registry=registry)
    assert d.tick() == "pass"
    text = registry.render_prometheus()
    assert "avdb_maintain_passes_total 1" in text
    assert "avdb_maintain_preemptions_total 0" in text
    assert "avdb_maintain_paused_total 0" in text


def test_bad_watermark_knob_fails_fleet_startup(tmp_path, monkeypatch):
    """A typo'd AVDB_MAINTAIN_* must fail startup loudly (the ServeFleet
    resolves knobs at __init__), never silently disable autonomy."""
    from annotatedvdb_tpu.serve.fleet import ServeFleet

    monkeypatch.setenv("AVDB_MAINTAIN_SEGMENTS_HIGH", "banana")
    with pytest.raises(ValueError, match="AVDB_MAINTAIN_SEGMENTS_HIGH"):
        ServeFleet(str(tmp_path), port=0, workers=1, maintain=True)


def test_maintain_requires_aio_front_end(tmp_path, capsys):
    from annotatedvdb_tpu.cli.serve import main as serve_main

    rc = serve_main(["--storeDir", str(tmp_path), "--frontend",
                     "threaded", "--maintain"])
    assert rc == 2
    assert "--maintain requires the aio front end" in \
        capsys.readouterr().err


# ---------------------------------------------------------------------------
# retry_preempted (the shared policy itself)


def test_retry_preempted_passes_through_success():
    calls = {"n": 0}

    def run():
        calls["n"] += 1
        return {"status": "compacted"}

    assert retry_preempted(run, retries=3)["status"] == "compacted"
    assert calls["n"] == 1


def test_retry_preempted_bounded_and_returns_last_report():
    calls = {"n": 0}

    def run():
        calls["n"] += 1
        return {"status": "aborted", "reason": "busy"}

    report = retry_preempted(run, retries=2, base_delay=0.0)
    assert report["status"] == "aborted"
    assert calls["n"] == 3  # initial + 2 retries, then give up


def test_retry_preempted_never_retries_hard_failures():
    calls = {"n": 0}

    def run():
        calls["n"] += 1
        raise OSError("hard")

    with pytest.raises(OSError):
        retry_preempted(run, retries=5)
    assert calls["n"] == 1


def test_retry_preempted_stops_on_success_mid_sequence():
    reports = [{"status": "aborted"}, {"status": "compacted"}]
    calls = {"n": 0}

    def run():
        calls["n"] += 1
        return reports[calls["n"] - 1]

    assert retry_preempted(run, retries=5,
                           base_delay=0.0)["status"] == "compacted"
    assert calls["n"] == 2


# ---------------------------------------------------------------------------
# heartbeat health slots + fleet aggregation


def test_hb_slot_roundtrip_and_worker_health_aggregation(tmp_path):
    import mmap as mmap_mod
    import struct

    from annotatedvdb_tpu.serve.fleet import HB_SLOT, ServeFleet

    fleet = ServeFleet(str(tmp_path), port=0, workers=3)
    try:
        class _Live:
            def poll(self):
                return None

        class _Dead:
            def poll(self):
                return 0

        fleet._procs = {0: _Live(), 1: _Live(), 2: _Dead()}
        now = time.time()
        HB_SLOT.pack_into(fleet._hb_mm, 0, now, 0.01, 0, 5)
        HB_SLOT.pack_into(fleet._hb_mm, HB_SLOT.size, now, 0.30, 2, 9)
        # worker 2 is dead: its (stale, hot) slot must not count
        HB_SLOT.pack_into(fleet._hb_mm, 2 * HB_SLOT.size, now, 1.0, 3, 99)
        h = fleet.worker_health()
        assert h["workers"] == 2
        assert h["brownout_max"] == 2
        assert h["exceed_max"] == pytest.approx(0.30)
        assert h["queue_depth_max"] == 9
        # a live worker that has not ticked yet (beat 0) contributes
        # nothing — startup reads as calm, not as brownout
        HB_SLOT.pack_into(fleet._hb_mm, HB_SLOT.size, 0.0, 0.9, 3, 1)
        h = fleet.worker_health()
        assert h["workers"] == 1 and h["brownout_max"] == 0
        # the wedge watchdog still reads the beat as the first field
        beat = struct.unpack_from("<d", fleet._hb_mm, 0)[0]
        assert beat == pytest.approx(now)
        assert isinstance(fleet._hb_mm, mmap_mod.mmap)
    finally:
        fleet._reserve.close()
        fleet._hb_mm.close()
        os.unlink(fleet._hb_path)


def test_aio_tick_publishes_health_fields(tmp_path):
    """The worker side of the health contract: the maintenance tick
    writes (beat, exceedance, brownout level, queue depth) into its
    slot."""
    from annotatedvdb_tpu.serve.aio import build_aio_server
    from annotatedvdb_tpu.serve.fleet import HB_SLOT

    store_dir = str(tmp_path / "s")
    _fragment(store_dir, nseg=1)
    hb = tmp_path / "hb"
    hb.write_bytes(b"\x00" * HB_SLOT.size)
    server = build_aio_server(
        store_dir=store_dir, port=0, heartbeat_file=str(hb),
        heartbeat_index=0,
    )
    try:
        server.ctx.governor.force_level(2)
        server.start_background()
        deadline = time.monotonic() + 10
        beat = level = 0
        while time.monotonic() < deadline:
            beat, _exceed, level, _depth = HB_SLOT.unpack_from(
                server._hb_mm, 0
            )
            if beat > 0.0 and level == 2:
                break
            time.sleep(0.05)
        assert beat > 0.0
        assert level == 2
    finally:
        server.shutdown()
        server.ctx.batcher.close()


def test_governor_exposes_exceedance():
    from annotatedvdb_tpu.serve.resilience import OverloadGovernor

    gov = OverloadGovernor(depth_fn=lambda: 0, max_queue=100,
                           p99_target_s=0.001)
    assert gov.exceedance == 0.0
    for _ in range(50):
        gov.note_latency(1.0)  # way over target
    assert gov.exceedance > 0.0


# ---------------------------------------------------------------------------
# disk-pressure degradation (507 contract)


def _seed_serve_store():
    from annotatedvdb_tpu.loaders.lookup import identity_hashes
    from annotatedvdb_tpu.types import encode_allele_array

    store = VariantStore(width=WIDTH)
    ref, ref_len = encode_allele_array(["A"] * 3, WIDTH)
    alt, alt_len = encode_allele_array(["C"] * 3, WIDTH)
    store.shard(3).append(
        {"pos": np.asarray([10, 20, 30], np.int32),
         "h": identity_hashes(WIDTH, ref, alt, ref_len, alt_len),
         "ref_len": ref_len, "alt_len": alt_len},
        ref, alt,
    )
    return store


def _request(port, method, path, body=None, timeout=15):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", method=method,
        data=json.dumps(body).encode() if body is not None else None,
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as err:
        return err.code, err.read()


@pytest.fixture()
def pair(tmp_path):
    """Both front ends over ONE on-disk store, each with its own
    memtable + WAL (the test_upsert fleet shape)."""
    from annotatedvdb_tpu.serve import MemtableSnapshots, SnapshotManager
    from annotatedvdb_tpu.serve.aio import build_aio_server
    from annotatedvdb_tpu.serve.http import build_server
    from annotatedvdb_tpu.store.memtable import Memtable
    from annotatedvdb_tpu.store.wal import WriteAheadLog

    store_dir = str(tmp_path / "store")
    _seed_serve_store().save(store_dir)
    built = []

    def one(tag, build):
        registry = MetricsRegistry()
        mgr = SnapshotManager(store_dir, log=lambda m: None)
        mem = Memtable(
            width=WIDTH, store_dir=store_dir,
            wal=WriteAheadLog(store_dir, f"serve-{tag}",
                              log=lambda m: None),
            registry=registry, log=lambda m: None,
        )
        server = build(manager=MemtableSnapshots(mgr, mem), port=0,
                       memtable=mem, registry=registry)
        built.append((server, mem))
        return server, mem

    httpd, mem_t = one("t", build_server)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    aio, mem_a = one("a", build_aio_server)
    aio.start_background()
    yield {
        "store_dir": store_dir,
        "pt": httpd.server_address[1], "pa": aio.server_address[1],
        "ctx_t": httpd.ctx, "ctx_a": aio.ctx,
    }
    aio.shutdown()
    aio.ctx.batcher.close()
    httpd.shutdown()
    httpd.server_close()
    httpd.ctx.batcher.close()
    for _server, mem in built:
        if mem.wal is not None:
            mem.wal.close(remove_if_empty=True)


def test_disk_reserve_507_parity_reads_survive_and_recovery(pair):
    """The disk-pressure contract end to end: with the reserve breached
    both front ends 507 upserts BYTE-IDENTICALLY while point/bulk reads
    keep serving; freeing space (reserve cleared) resumes upserts."""
    store_dir = pair["store_dir"]
    for ctx in (pair["ctx_t"], pair["ctx_a"]):
        ctx.disk_guard = DiskReserveGuard(
            store_dir, reserve=1 << 60, ttl_s=0.0, log=lambda m: None
        )
    up = {"variants": [{"id": "3:70:A:G"}]}
    st_t, body_t = _request(pair["pt"], "POST", "/variants/upsert", up)
    st_a, body_a = _request(pair["pa"], "POST", "/variants/upsert", up)
    assert st_t == st_a == 507
    assert body_t == body_a  # single-source message constant
    from annotatedvdb_tpu.serve.http import MSG_DISK_RESERVE

    assert json.loads(body_t)["error"] == MSG_DISK_RESERVE
    # reads keep serving through the degraded window, on both fronts
    for port in (pair["pt"], pair["pa"]):
        status, body = _request(port, "GET", "/variant/3:10:A:C")
        assert status == 200 and b'"3:10:A:C"' in body
        status, body = _request(port, "POST", "/variants",
                                {"ids": ["3:10:A:C", "3:20:A:C"]})
        assert status == 200 and json.loads(body)["found"] == 2
    # the shed is visible in metrics
    assert "avdb_upsert_disk_shed_total 1" in \
        pair["ctx_t"].registry.render_prometheus()
    # space freed -> upserts resume (recovery), identically on both
    for ctx in (pair["ctx_t"], pair["ctx_a"]):
        ctx.disk_guard = DiskReserveGuard(
            store_dir, reserve=1, ttl_s=0.0, log=lambda m: None
        )
    st_t, body_t = _request(pair["pt"], "POST", "/variants/upsert", up)
    assert st_t == 200 and json.loads(body_t)["accepted"] == 1
    st_a, body_a = _request(pair["pa"], "POST", "/variants/upsert",
                            {"variants": [{"id": "3:77:A:G"}]})
    assert st_a == 200 and json.loads(body_a)["accepted"] == 1


def test_flush_of_acked_rows_runs_under_disk_guard(pair):
    """The guard sheds NEW writes only: a memtable flush of rows acked
    before the window commits to segments (it is what drains the WAL)."""
    store_dir = pair["store_dir"]
    ctx = pair["ctx_t"]
    st, _ = _request(pair["pt"], "POST", "/variants/upsert",
                     {"variants": [{"id": "3:90:A:G"}]})
    assert st == 200
    ctx.disk_guard = DiskReserveGuard(
        store_dir, reserve=1 << 60, ttl_s=0.0, log=lambda m: None
    )
    st, _ = _request(pair["pt"], "POST", "/variants/upsert",
                     {"variants": [{"id": "3:91:A:G"}]})
    assert st == 507
    result = ctx.memtable.flush(base_manager=ctx.manager.base)
    assert result["status"] == "flushed"
    assert ctx.memtable.rows == 0
    rows = json.load(open(os.path.join(store_dir, "manifest.json")))[
        "stats"]["rows"]
    assert int(rows["3"]) == 4  # 3 loaded + the acked upsert


def test_flush_retries_transient_io(pair):
    """ENOSPC/EIO on a flush gets the bounded backoff-retry: one
    injected blip and the flush still lands (nothing wedges)."""
    ctx = pair["ctx_t"]
    st, _ = _request(pair["pt"], "POST", "/variants/upsert",
                     {"variants": [{"id": "3:95:A:G"}]})
    assert st == 200
    assert ctx.memtable.rows == 1
    faults.reset("memtable.flush:1:eio")
    ctx._flush_memtable(ctx.manager.base)
    assert ctx.memtable.rows == 0  # retried past the blip and flushed


def test_upsert_sigkill_in_degraded_window_loses_nothing_acked(tmp_path):
    """Through the REAL serve CLI: rows acked before the reserve breach
    survive a SIGKILL DURING the degraded window (WAL replay), new
    upserts 507 inside it, and clearing the reserve restores full
    service with every acked row present."""
    store_dir = str(tmp_path / "store")
    _seed_serve_store().save(store_dir)

    def spawn(env_extra):
        import re

        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   AVDB_MEMTABLE_FLUSH_S="0", AVDB_MEMTABLE_BYTES="0")
        env.pop("AVDB_FAULT", None)
        env.pop("AVDB_STORE_DISK_RESERVE_BYTES", None)
        env.update(env_extra)
        proc = subprocess.Popen(
            [sys.executable, "-m", "annotatedvdb_tpu", "serve",
             "--storeDir", store_dir, "--port", "0", "--upserts"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for _ in range(50):
            line = proc.stdout.readline()
            if not line:
                break
            m = re.search(r"http://([\d.]+):(\d+)", line)
            if m:
                return proc, m.group(1), int(m.group(2))
        raise AssertionError("no serve address line")

    def post(host, port, vid):
        return _request(port, "POST", "/variants/upsert",
                        {"variants": [{"id": vid}]})

    # phase 1: healthy disk — ack two rows, then SIGKILL (unflushed:
    # flush triggers are disabled, so the WAL is their only durability)
    proc, host, port = spawn({})
    try:
        st, body = post(host, port, "3:40:A:G")
        assert st == 200 and json.loads(body)["accepted"] == 1
        st, body = post(host, port, "3:50:A:G")
        assert st == 200 and json.loads(body)["accepted"] == 1
    finally:
        proc.kill()
        proc.wait(timeout=30)

    # phase 2: the degraded window — reserve breached from startup.
    # WAL replay restores the acked rows; reads serve them; new writes
    # 507; a SIGKILL here loses nothing acked.
    proc, host, port = spawn({"AVDB_STORE_DISK_RESERVE_BYTES": "1000g"})
    try:
        for vid in ("3:40:A:G", "3:50:A:G"):
            st, body = _request(port, "GET", f"/variant/{vid}")
            assert st == 200, (vid, body)
        st, body = post(host, port, "3:60:A:G")
        assert st == 507
        from annotatedvdb_tpu.serve.http import MSG_DISK_RESERVE

        assert json.loads(body)["error"] == MSG_DISK_RESERVE
    finally:
        proc.kill()  # SIGKILL mid-degraded-window
        proc.wait(timeout=30)

    # phase 3: space freed — acked rows still present, upserts resume
    proc, host, port = spawn({})
    try:
        for vid in ("3:40:A:G", "3:50:A:G"):
            st, _body = _request(port, "GET", f"/variant/{vid}")
            assert st == 200
        st, _body = _request(port, "GET", "/variant/3:60:A:G")
        assert st == 404  # the 507'd write was never acknowledged
        st, body = post(host, port, "3:60:A:G")
        assert st == 200 and json.loads(body)["accepted"] == 1
    finally:
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=60) == 0


# ---------------------------------------------------------------------------
# doctor status


def test_store_status_report_and_cli(tmp_path, monkeypatch):
    from annotatedvdb_tpu.store.memtable import Memtable
    from annotatedvdb_tpu.store.wal import WriteAheadLog

    store_dir = str(tmp_path / "s")
    _fragment(store_dir, nseg=5)
    # pending WAL records + assorted debris
    wal = WriteAheadLog(store_dir, "serve-w0", log=lambda m: None)
    mem = Memtable(width=WIDTH, store_dir=store_dir, wal=wal,
                   log=lambda m: None)
    mem.upsert(None, [{"code": 6, "pos": 42, "ref": "A", "alt": "G",
                       "ref_snp": None, "ann": None}])
    wal.close()
    open(os.path.join(store_dir, "chr6.000099.flush.tmp.npz"), "wb").close()
    open(os.path.join(store_dir, "chr6.000098.compact.tmp.npz"),
         "wb").close()
    open(os.path.join(store_dir, "serve-w1.000001.wal.tmp"), "wb").close()

    monkeypatch.setenv("AVDB_MAINTAIN_SEGMENTS_HIGH", "4")
    monkeypatch.setenv("AVDB_STORE_DISK_RESERVE_BYTES", "1000g")
    report = store_status(store_dir)
    assert report["groups"]["6"]["segments"] == 5
    assert report["read_amp"]["max"] == 5
    assert report["watermarks"]["high"] == 4
    assert report["watermarks"]["over_high"] == ["6"]
    assert report["wal"]["files"] == 1
    assert report["wal"]["records_pending_replay"] == 1
    assert report["debris"] == {"flush_tmp": 1, "compact_tmp": 1,
                                "wal_tmp": 1, "stale_tmp": 0}
    assert report["disk"]["breached"] is True  # 1000g reserve

    from annotatedvdb_tpu.cli.doctor import main as doctor_main

    rc = doctor_main(["status", "--storeDir", store_dir, "--json"])
    assert rc == 0


def test_store_status_includes_last_ledger_records(tmp_path):
    from annotatedvdb_tpu.store.compact import compact_store

    store_dir = str(tmp_path / "s")
    _fragment(store_dir, nseg=4)
    report = compact_store(store_dir)
    assert report["status"] == "compacted"
    status = store_status(store_dir)
    assert status["ledger"]["last_compact"] is not None
    assert status["ledger"]["last_compact"]["files_before"] == 4
    assert status["read_amp"]["max"] == 1


def test_store_status_missing_store_exits_2(tmp_path):
    from annotatedvdb_tpu.cli.doctor import main as doctor_main

    rc = doctor_main(["status", "--storeDir",
                      str(tmp_path / "nothing"), "--json"])
    assert rc == 2


def test_doctor_compact_retries_flag(tmp_path, monkeypatch):
    """`doctor compact --retries N` rides the shared retry_preempted
    policy: a pass cleanly preempted once (a racing commit between plan
    and swap) lands on the retry instead of exiting 1."""
    from annotatedvdb_tpu.cli import doctor as doctor_mod
    from annotatedvdb_tpu.store import compact as compact_mod

    store_dir = str(tmp_path / "s")
    _fragment(store_dir, nseg=4)
    calls = {"n": 0}
    real = compact_mod.compact_store

    def flaky(store, **kw):
        calls["n"] += 1
        if calls["n"] == 1:
            return {"status": "aborted", "reason": "test race",
                    "labels": [], "files_before": 0, "files_after": 0,
                    "bytes_before": 0, "bytes_after": 0,
                    "bytes_reclaimed": 0, "rows": 0, "rows_dropped": 0,
                    "seconds": 0.0}
        return real(store, **kw)

    monkeypatch.setattr(compact_mod, "compact_store", flaky)
    rc = doctor_mod.main(["compact", "--storeDir", store_dir,
                          "--retries", "1", "--json"])
    assert rc == 0
    assert calls["n"] == 2


# ---------------------------------------------------------------------------
# review-round regressions


def test_retry_preempted_never_retries_callers_own_cancel():
    """A pass the CALLER itself cancelled (SIGTERM, daemon stop, hot
    health) is not a preemption to retry — re-running would only delay
    the shutdown behind backoff sleeps."""
    calls = {"n": 0}

    def run():
        calls["n"] += 1
        return {"status": "aborted", "reason": "cancelled before merge"}

    report = retry_preempted(run, retries=5, base_delay=0.0,
                             cancel=lambda: True)
    assert report["status"] == "aborted"
    assert calls["n"] == 1  # no retries against our own cancel


def test_bad_disk_reserve_knob_fails_fleet_startup(tmp_path, monkeypatch):
    """A typo'd AVDB_STORE_DISK_RESERVE_BYTES must fail the fleet at
    startup (rc 1 via the cli), not be discovered inside every spawned
    worker as a rapid-death respawn loop."""
    from annotatedvdb_tpu.serve.fleet import ServeFleet

    monkeypatch.setenv("AVDB_STORE_DISK_RESERVE_BYTES", "512mb")
    with pytest.raises(ValueError,
                       match="AVDB_STORE_DISK_RESERVE_BYTES"):
        ServeFleet(str(tmp_path), port=0, workers=1)


def test_store_status_unreadable_free_space_reports_breached(
        tmp_path, monkeypatch):
    """An unreadable free-space reading reports breached, matching the
    serving guard's fail-toward-refusing-writes semantics — the health
    report must never say 'ok' while workers shed 507."""
    import annotatedvdb_tpu.store.maintenance as maintenance

    store_dir = str(tmp_path / "s")
    _fragment(store_dir, nseg=1)
    monkeypatch.setenv("AVDB_STORE_DISK_RESERVE_BYTES", "1k")

    def boom(path):
        raise OSError("statvfs failed")

    monkeypatch.setattr(maintenance, "free_disk_bytes", boom)
    report = store_status(store_dir)
    assert report["disk"]["free_bytes"] == -1
    assert report["disk"]["breached"] is True
