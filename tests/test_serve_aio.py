"""The asyncio serving front end: byte-parity against the threaded
reference server (point/bulk/region, hits and errors), weighted
per-client fairness under a hog, chunked region streaming, continuation
paging, the coalesced snapshot TTL, and the batcher's non-blocking
submission path it rides on."""

from __future__ import annotations

import http.client
import json
import socket
import threading
import time
import urllib.error
import urllib.request

import pytest

from annotatedvdb_tpu.serve import QueryBatcher, QueryEngine, SnapshotManager
from annotatedvdb_tpu.serve import snapshot as snapshot_mod
from test_serve import _build_store, _commit_more_rows, _vid


# ---------------------------------------------------------------------------
# fixtures: one store, both front ends


@pytest.fixture(scope="module")
def store(tmp_path_factory):
    store_dir = str(tmp_path_factory.mktemp("aio_store"))
    truth = _build_store(store_dir)
    return store_dir, truth


@pytest.fixture(scope="module")
def aio_server(store):
    from annotatedvdb_tpu.serve.aio import build_aio_server

    store_dir, _truth = store
    server = build_aio_server(store_dir=store_dir, port=0)
    server.start_background()
    try:
        yield server
    finally:
        server.shutdown()
        server.ctx.batcher.close()


@pytest.fixture(scope="module")
def threaded_server(store):
    from annotatedvdb_tpu.serve.http import build_server

    store_dir, _truth = store
    httpd = build_server(store_dir=store_dir, port=0)
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    try:
        yield httpd
    finally:
        httpd.shutdown()
        httpd.server_close()
        httpd.ctx.batcher.close()


def _get(port: int, path: str, headers=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", headers=headers or {}
    )
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, r.read().decode(), dict(r.headers)
    except urllib.error.HTTPError as err:
        return err.code, err.read().decode(), dict(err.headers)


def _post(port: int, path: str, payload: bytes):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=payload, method="POST"
    )
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as err:
        return err.code, err.read().decode()


# ---------------------------------------------------------------------------
# byte parity vs the threaded reference front end


def test_point_parity_hits_misses_errors(store, aio_server, threaded_server):
    _dir, truth = store
    a_port = aio_server.server_address[1]
    t_port = threaded_server.server_address[1]
    paths = [f"/variant/{_vid(r)}" for r in truth[::5]]
    paths += ["/variant/8:499:A:G",       # miss -> 404
              "/variant/garbage",          # grammar -> 400
              "/variant/2:500:A:G"]        # unloaded chromosome -> 404
    for path in paths:
        astatus, abody, _ = _get(a_port, path)
        tstatus, tbody, _ = _get(t_port, path)
        assert (astatus, abody) == (tstatus, tbody), path


def test_bulk_parity_including_bad_bodies(store, aio_server, threaded_server):
    _dir, truth = store
    a_port = aio_server.server_address[1]
    t_port = threaded_server.server_address[1]
    ids = [_vid(r) for r in truth[:40]] + ["8:499:A:G"]
    payload = json.dumps({"ids": ids}).encode()
    assert _post(a_port, "/variants", payload) \
        == _post(t_port, "/variants", payload)
    for bad in (b"[1,2]", b'{"ids": [1]}', b'{"ids": "x"}', b"{nope"):
        assert _post(a_port, "/variants", bad) \
            == _post(t_port, "/variants", bad), bad


def test_region_parity_with_filters(store, aio_server, threaded_server):
    a_port = aio_server.server_address[1]
    t_port = threaded_server.server_address[1]
    for path in (
        "/region/8:1-10000",
        "/region/8:1-10000?minCadd=5&limit=4",
        "/region/8:1-3000000?maxConseqRank=10",
        "/region/8:1-10000?limit=0",          # count-only
        "/region/11:1-5000",                   # unloaded chromosome
        "/region/8:9-3",                       # bad range -> 400
        "/region/8:1-10000?limit=zebra",       # bad param -> 400
    ):
        astatus, abody, _ = _get(a_port, path)
        tstatus, tbody, _ = _get(t_port, path)
        assert (astatus, abody) == (tstatus, tbody), path


def test_aio_routes_and_metrics(aio_server):
    port = aio_server.server_address[1]
    status, body, _ = _get(port, "/healthz")
    assert status == 200 and json.loads(body)["status"] == "ok"
    status, body, _ = _get(port, "/nope")
    assert status == 404
    status, body, _ = _get(port, "/metrics")
    assert status == 200
    for metric in ("avdb_query_requests_total", "avdb_query_seconds",
                   "avdb_serve_batches_total"):
        assert metric in body, metric
    status, body, _ = _get(port, "/stats")
    assert status == 200 and json.loads(body)["batcher"]["queries"] >= 1


def test_aio_429_at_queue_bound(store):
    from annotatedvdb_tpu.serve.aio import build_aio_server

    store_dir, truth = store
    server = build_aio_server(store_dir=store_dir, port=0, max_queue=0)
    server.start_background()
    try:
        port = server.server_address[1]
        status, _body, headers = _get(port, f"/variant/{_vid(truth[0])}")
        assert status == 429
        assert headers.get("Retry-After") == "1"
    finally:
        server.shutdown()
        server.ctx.batcher.close()


# ---------------------------------------------------------------------------
# pipelining: many requests in flight on ONE connection, answers in order


def _pipeline_point_gets(port: int, vids: list) -> list:
    """Send every GET on one socket up front; return the bodies in
    arrival order."""
    import socket

    req = b"".join(
        f"GET /variant/{v} HTTP/1.1\r\nHost: t\r\n\r\n".encode()
        for v in vids
    )
    with socket.create_connection(("127.0.0.1", port), timeout=30) as sock:
        sock.sendall(req)
        buf = b""
        bodies = []
        while len(bodies) < len(vids):
            chunk = sock.recv(1 << 16)
            assert chunk, "server closed mid-pipeline"
            buf += chunk
            while True:
                he = buf.find(b"\r\n\r\n")
                if he < 0:
                    break
                cl = buf.find(b"Content-Length: ")
                blen = int(buf[cl + 16:he])
                if len(buf) < he + 4 + blen:
                    break
                bodies.append(buf[he + 4:he + 4 + blen].decode())
                buf = buf[he + 4 + blen:]
    return bodies


def test_pipelined_connection_answers_in_order(store, aio_server):
    _dir, truth = store
    port = aio_server.server_address[1]
    vids = [_vid(r) for r in truth[:30]]
    bodies = _pipeline_point_gets(port, vids)
    for vid, body in zip(vids, bodies):
        rec = json.loads(body)
        assert rec["metaseq_id"].split(":")[1] == vid.split(":")[1], vid


def test_writer_flushes_mid_batch_above_high_water(store, aio_server,
                                                   monkeypatch):
    """The coalescing writer flushes once the buffer crosses
    _WRITE_HIGH_WATER instead of accumulating the whole pipelined batch
    (batch-count x response-size RSS); bodies must stay complete and in
    request order across the forced mid-batch flushes."""
    from annotatedvdb_tpu.serve import aio as aio_mod

    monkeypatch.setattr(aio_mod, "_WRITE_HIGH_WATER", 8)
    _dir, truth = store
    port = aio_server.server_address[1]
    vids = [_vid(r) for r in truth[:20]]
    bodies = _pipeline_point_gets(port, vids)
    for vid, body in zip(vids, bodies):
        rec = json.loads(body)
        assert rec["metaseq_id"].split(":")[1] == vid.split(":")[1], vid


# ---------------------------------------------------------------------------
# weighted per-client fairness


def test_hog_cannot_starve_polite_client(store):
    """A hog blasting unpaced traffic gets throttled to its bucket; a
    polite client under its share sees zero rejections and bounded
    latency — the weighted-share contract of the ISSUE."""
    from annotatedvdb_tpu.serve.aio import build_aio_server

    store_dir, truth = store
    server = build_aio_server(
        store_dir=store_dir, port=0, client_rate=5.0,
    )
    server.start_background()
    try:
        port = server.server_address[1]
        vid = _vid(truth[0])
        results = {}

        def hog():
            # weight 1 -> 5 req/s share; blasts unpaced
            ok = rejected = 0
            lat = []
            deadline = time.monotonic() + 2.0
            while time.monotonic() < deadline:
                t0 = time.perf_counter()
                status, _b, _h = _get(
                    port, f"/variant/{vid}",
                    headers={"X-Client-Id": "hog"},
                )
                lat.append(time.perf_counter() - t0)
                if status == 200:
                    ok += 1
                elif status == 429:
                    rejected += 1
            results["hog"] = (ok, rejected, lat)

        def polite():
            # weight 4 -> 20 req/s share; paces at ~8 req/s, well under
            ok = rejected = 0
            lat = []
            for _ in range(16):
                t0 = time.perf_counter()
                status, _b, _h = _get(
                    port, f"/variant/{vid}",
                    headers={"X-Client-Id": "polite",
                             "X-Client-Weight": "4"},
                )
                lat.append(time.perf_counter() - t0)
                if status == 200:
                    ok += 1
                elif status == 429:
                    rejected += 1
                time.sleep(0.12)
            results["polite"] = (ok, rejected, lat)

        threads = [threading.Thread(target=hog),
                   threading.Thread(target=polite)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        hog_ok, hog_rej, hog_lat = results["hog"]
        pol_ok, pol_rej, pol_lat = results["polite"]
        # the hog was actually throttled...
        assert hog_rej > 0
        # ...to roughly its bucket (rate*duration + burst, with slack)
        assert hog_ok <= 5 * 2.0 + 4 + 20
        # the polite client never starved: no rejects, every call answered
        assert pol_rej == 0 and pol_ok == 16
        # p99 ratio bound: the polite client's tail latency stays within
        # an order of magnitude of the hog's (it is NOT queued behind it)
        pol_lat.sort()
        hog_lat.sort()
        pol_p99 = pol_lat[int(0.99 * (len(pol_lat) - 1))]
        hog_p99 = hog_lat[int(0.99 * (len(hog_lat) - 1))]
        assert pol_p99 <= max(hog_p99 * 10, 0.5)
    finally:
        server.shutdown()
        server.ctx.batcher.close()


def test_weighted_client_gets_larger_share(store):
    from annotatedvdb_tpu.serve.aio import ClientGovernor

    governor = ClientGovernor(10.0)
    heavy = sum(
        1 for _ in range(200) if governor.admit("heavy", 4) == 0.0
    )
    light = sum(
        1 for _ in range(200) if governor.admit("light", 1) == 0.0
    )
    # burst capacity scales with weight: 4x the weight, ~4x the admitted
    assert heavy >= 2 * light
    retry = governor.admit("light", 1)
    assert retry > 0.0  # a drained bucket reports a concrete wait


def test_region_blank_params_mean_absent():
    """`?minCadd=&limit=` (an unfilled client template) means 'no filter',
    exactly as before keep_blank_values — only a blank cursor is
    meaningful (it starts a paged walk)."""
    from annotatedvdb_tpu.serve.http import parse_region_params

    min_cadd, max_rank, limit, cursor = parse_region_params(
        "minCadd=&maxConseqRank=&limit=&cursor="
    )
    assert min_cadd is None and max_rank is None
    assert limit == 10_000
    assert cursor == ""
    assert parse_region_params("minCadd=2.5&limit=7")[:1] == (2.5,)
    with pytest.raises(Exception):
        parse_region_params("minCadd=abc")


def test_bind_failure_raises_cleanly(store):
    """A taken port must surface the real OSError immediately, not a 30s
    startup-timeout hang with the cause buried in a daemon thread."""
    import socket as socket_mod

    from annotatedvdb_tpu.serve.aio import build_aio_server

    store_dir, _truth = store
    blocker = socket_mod.socket()
    blocker.bind(("127.0.0.1", 0))
    blocker.listen(1)
    try:
        server = build_aio_server(
            store_dir=store_dir, port=blocker.getsockname()[1]
        )
        t0 = time.monotonic()
        with pytest.raises(OSError):
            server.start_background()
        assert time.monotonic() - t0 < 10
        server.ctx.batcher.close()
    finally:
        blocker.close()


def test_healthz_stats_and_bad_content_length_parity(
        store, aio_server, threaded_server):
    """The ops routes and the malformed-Content-Length POST answer
    identically on both front ends (the payload builders are shared in
    http.py for exactly this reason)."""
    aport = aio_server.server_address[1]
    tport = threaded_server.server_address[1]
    sa, ba, _h = _get(aport, "/healthz")
    st, bt, _h = _get(tport, "/healthz")
    assert (sa, ba) == (st, bt)
    sa, ba, _h = _get(aport, "/stats")
    st, bt, _h = _get(tport, "/stats")
    # drain counters differ across the shared fixtures; the surface
    # (status + key set) must not fork
    assert sa == st
    assert json.loads(ba).keys() == json.loads(bt).keys()

    def bad_cl(port):
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        try:
            conn.putrequest("POST", "/variants")
            conn.putheader("Content-Length", "abc")
            conn.endheaders()
            r = conn.getresponse()
            return r.status, r.read()
        finally:
            conn.close()

    assert bad_cl(aport) == bad_cl(tport)
    assert bad_cl(aport)[0] == 400


def test_bulk_charges_per_id_against_bucket(store):
    """Batching must not bypass per-client fairness: a /variants POST
    debits one token per id (with bounded debt), so after one big bulk
    the same client's next request is throttled while strangers are
    unaffected — and a bulk too large for the bucket to ever repay is
    rejected outright instead of served-then-forgiven."""
    from annotatedvdb_tpu.serve.aio import (
        MAX_DEBT_S,
        ClientGovernor,
        build_aio_server,
    )

    # governor unit: the debt lands, is bounded, and unknown keys no-op
    gov = ClientGovernor(10.0)
    assert gov.admit("hog", 1) == 0.0
    gov.charge("hog", 9999.0)
    retry = gov.admit("hog", 1)
    assert retry > 0.0
    assert retry <= MAX_DEBT_S + 1.0
    gov.charge("stranger", 5.0)  # LRU-evicted key: forfeits, no crash
    # the refillable budget scales with weight and floors at 1
    assert gov.bulk_budget(1) == int(10.0 * MAX_DEBT_S)
    assert gov.bulk_budget(4) == int(40.0 * MAX_DEBT_S)
    assert gov.bulk_budget(999) == gov.bulk_budget(16)  # weight clamp
    assert ClientGovernor(0.001).bulk_budget(1) == 1

    # end to end: a within-budget 100-id bulk indebts the bucket (the
    # charge lands on the loop just after the executor parses), so the
    # same client's point GET goes 429 while a fresh client stays
    # admitted
    store_dir, truth = store
    server = build_aio_server(store_dir=store_dir, port=0, client_rate=5.0)
    server.start_background()
    try:
        port = server.server_address[1]
        vid = _vid(truth[0])
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/variants",
            data=json.dumps({"ids": [vid] * 100}).encode(),
            headers={"X-Client-Id": "bulkhog"}, method="POST",
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            assert r.status == 200
        deadline = time.monotonic() + 5.0
        throttled = False
        while time.monotonic() < deadline and not throttled:
            status, _b, hdrs = _get(
                port, f"/variant/{vid}", headers={"X-Client-Id": "bulkhog"}
            )
            throttled = status == 429
        assert throttled, "bulk ids never debited the client bucket"
        assert int(hdrs["Retry-After"]) >= 1
        status, _b, _h = _get(
            port, f"/variant/{vid}", headers={"X-Client-Id": "fresh"}
        )
        assert status == 200
        # a bulk beyond the refillable budget (rate 5 * 30s = 150 ids)
        # is rejected BEFORE any lookup runs — the debt clamp must not
        # forgive work already done
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/variants",
            data=json.dumps({"ids": [vid] * 200}).encode(),
            headers={"X-Client-Id": "jumbo"}, method="POST",
        )
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req, timeout=30)
        assert exc.value.code == 429
        body = json.loads(exc.value.read().decode())
        assert "rate budget" in body["error"]
        assert int(exc.value.headers["Retry-After"]) >= 1
        # ...and the rejection did not wedge the jumbo client's bucket:
        # only the admit token was spent, so its next point GET is fine
        status, _b, _h = _get(
            port, f"/variant/{vid}", headers={"X-Client-Id": "jumbo"}
        )
        assert status == 200
    finally:
        server.shutdown()
        server.ctx.batcher.close()


@pytest.mark.parametrize("frontend", ["aio", "threaded"])
def test_bad_env_knob_exits_cleanly(store, frontend):
    """An unparseable ``AVDB_SERVE_*`` knob must exit ``serve: cannot
    start`` rc=1 on BOTH front ends, not a traceback — a fleet worker
    dying with a traceback would respawn into a crash loop."""
    import os
    import subprocess
    import sys

    store_dir, _truth = store
    env = dict(os.environ, AVDB_SERVE_BATCH_MAX="abc")
    p = subprocess.run(
        [sys.executable, "-m", "annotatedvdb_tpu", "serve",
         "--storeDir", store_dir, "--port", "0", "--frontend", frontend],
        env=env, capture_output=True, text=True, timeout=120,
    )
    assert p.returncode == 1, p.stderr[-2000:]
    assert "serve: cannot start" in p.stderr
    assert "Traceback" not in p.stderr


def test_threaded_frontend_warns_on_aio_only_knobs(tmp_path, capsys,
                                                   monkeypatch):
    """--clientRate/--streamThreshold have no wiring on the threaded
    front end: starting silently would let an operator believe hogs are
    throttled while nothing limits them."""
    from annotatedvdb_tpu.cli.serve import main

    monkeypatch.delenv("AVDB_SERVE_CLIENT_RATE", raising=False)
    monkeypatch.delenv("AVDB_SERVE_STREAM_THRESHOLD", raising=False)
    missing = str(tmp_path / "no_store")
    rc = main(["--storeDir", missing, "--frontend", "threaded",
               "--clientRate", "10", "--streamThreshold", "5"])
    assert rc == 1  # missing store still fails cleanly after the warning
    err = capsys.readouterr().err
    assert "--clientRate" in err and "--streamThreshold" in err
    assert "ignored with --frontend threaded" in err
    # the same knobs on the default (aio) front end must NOT warn
    rc = main(["--storeDir", missing, "--clientRate", "10"])
    assert rc == 1
    assert "ignored" not in capsys.readouterr().err


def test_abandoned_stream_items_release_admission_slots(store):
    """Exec items a cancelled writer abandons must still release their
    bulk/region admission slots (regression: a pipelining client that
    stopped reading streamed regions permanently burned
    ``ctx.max_inflight`` slots on an otherwise healthy server)."""
    import asyncio

    from annotatedvdb_tpu.serve.aio import build_aio_server

    store_dir, _truth = store
    server = build_aio_server(store_dir=store_dir, port=0)
    ctx = server.ctx

    async def scenario():
        loop = asyncio.get_running_loop()
        # mid-await cancellation path: the settle rides a done callback
        assert ctx.admit()
        fut = loop.create_future()
        fut.set_result(("stream", object()))
        server._settle_when_done(fut)
        await asyncio.sleep(0)
        assert ctx._inflight == 0
        # teardown-drain path: a queued exec item that never reached _emit
        assert ctx.admit()
        fut2 = loop.create_future()
        fut2.set_result(("stream", object()))
        await server._settle(("exec", fut2, "region", 0.0, None, None))
        assert ctx._inflight == 0
        # buffered results (bytes) released on the executor side: no-op
        fut3 = loop.create_future()
        fut3.set_result(b"HTTP/1.1 200 OK\r\n\r\n")
        await server._settle(("exec", fut3, "bulk", 0.0, None, None))
        assert ctx._inflight == 0

    asyncio.run(scenario())
    server.ctx.batcher.close()


def test_client_weight_applies_per_request():
    """The declared weight binds per request, not per bucket lifetime: a
    client whose first request omitted X-Client-Weight must ride its real
    share once it declares one (and drop back when it stops)."""
    from annotatedvdb_tpu.serve.aio import ClientGovernor

    governor = ClientGovernor(10.0)
    governor.admit("c", 1)
    bucket = governor._buckets["c"]
    assert bucket.rate == 10.0
    governor.admit("c", 8)
    assert bucket.rate == 80.0 and bucket.burst == 20.0
    governor.admit("c", 1)
    assert bucket.rate == 10.0


# ---------------------------------------------------------------------------
# chunked region streaming + paging


def test_region_streams_chunked_above_threshold(store, threaded_server):
    from annotatedvdb_tpu.serve.aio import build_aio_server

    store_dir, _truth = store
    server = build_aio_server(
        store_dir=store_dir, port=0, stream_threshold=5,
    )
    server.start_background()
    try:
        port = server.server_address[1]
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        conn.request("GET", "/region/8:1-3000000")
        resp = conn.getresponse()
        assert resp.status == 200
        assert resp.getheader("Transfer-Encoding") == "chunked"
        assert resp.getheader("Content-Length") is None
        streamed = resp.read().decode()
        conn.close()
        # de-chunked bytes identical to the buffered reference server
        t_port = threaded_server.server_address[1]
        _status, buffered, _ = _get(t_port, "/region/8:1-3000000")
        assert streamed == buffered
        rec = json.loads(streamed)
        assert rec["returned"] > 5
        # small regions stay buffered (Content-Length, not chunked)
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        conn.request("GET", "/region/8:1-10000?limit=3")
        resp = conn.getresponse()
        assert resp.getheader("Transfer-Encoding") is None
        assert resp.getheader("Content-Length") is not None
        resp.read()
        conn.close()
    finally:
        server.shutdown()
        server.ctx.batcher.close()


def test_region_paging_walk_matches_unpaged(store, aio_server):
    port = aio_server.server_address[1]
    _status, full, _ = _get(port, "/region/8:1-3000000?minCadd=3")
    want = [v["primary_key"] for v in json.loads(full)["variants"]]
    got = []
    cursor = ""
    pages = 0
    while cursor is not None:
        _s, body, _ = _get(
            port, f"/region/8:1-3000000?minCadd=3&limit=7&cursor={cursor}"
        )
        rec = json.loads(body)
        assert rec["returned"] <= 7
        got.extend(v["primary_key"] for v in rec["variants"])
        cursor = rec["next"]
        pages += 1
        assert pages < 100
    assert got == want
    assert pages == (len(want) + 6) // 7


def test_region_paging_rejects_foreign_and_junk_cursors(store, aio_server):
    port = aio_server.server_address[1]
    status, _b, _ = _get(port, "/region/8:1-10000?cursor=junk!!")
    assert status == 400
    _s, body, _ = _get(port, "/region/8:1-3000000?limit=3&cursor=")
    token = json.loads(body)["next"]
    assert token
    # replaying the token against DIFFERENT bounds is a client error
    status, body, _ = _get(port, f"/region/8:1-20000?limit=3&cursor={token}")
    assert status == 400 and "cursor" in json.loads(body)["error"]


def test_client_id_rotation_cannot_bypass_rate_limit():
    """A hog rotating X-Client-Id per request must not mint a fresh
    burst every time: ids are scoped to the peer and capped at
    PEER_KEY_CAP distinct buckets, beyond which the sprayer shares the
    peer's aggregate bucket — and the spray cannot evict another peer's
    bucket."""
    from annotatedvdb_tpu.serve.aio import ClientGovernor

    gov = ClientGovernor(base_rate=1.0)
    victim = gov.resolve_key("10.0.0.2", "steady")
    assert gov.admit(victim, 1) == 0.0
    admitted = 0
    for i in range(1000):
        key = gov.resolve_key("10.0.0.9", f"spray-{i}")
        if gov.admit(key, 1) == 0.0:
            admitted += 1
    # bounded by cap buckets' bursts plus the aggregate bucket's burst
    # (each burst is max(rate*0.25, 4) = 4 tokens), nowhere near 1000
    assert admitted <= (gov.PEER_KEY_CAP + 1) * 4 + 8, admitted
    assert victim in gov._buckets  # spray never evicted the other peer


def test_paged_walk_scans_region_once(store, monkeypatch):
    """A cursor walk must reuse its match list across pages: without the
    walk cache every page re-runs the full interval search + filter pass
    (O(pages x region)).  The scan unit is one ``_interval_spans`` call
    (the BITS search against the generation's interval index)."""
    from annotatedvdb_tpu.serve import QueryEngine, SnapshotManager

    store_dir, _truth = store
    engine = QueryEngine(SnapshotManager(store_dir), region_cache_size=0)
    calls = {"n": 0}
    real = engine._interval_spans

    def counting(index, code, starts, ends, host_only=False):
        calls["n"] += 1
        return real(index, code, starts, ends, host_only)

    monkeypatch.setattr(engine, "_interval_spans", counting)
    body = json.loads(engine.region("8:1-3000000", limit=5, cursor=""))
    pages = [body]
    while body.get("next"):
        body = json.loads(
            engine.region("8:1-3000000", limit=5, cursor=body["next"])
        )
        pages.append(body)
    assert len(pages) > 2
    assert calls["n"] == 1, calls["n"]
    # and the walk still matches the unpaged body row-for-row
    unpaged = json.loads(engine.region("8:1-3000000"))
    walked = [v for p in pages for v in p["variants"]]
    assert walked == unpaged["variants"]


def test_cursor_schema_requires_generation_field():
    """The token schema is the full (g, o, k) triple: a hand-built token
    missing ``g`` is malformed, while a well-formed token from ANY
    generation stays replayable (best-effort continuation contract)."""
    import base64

    from annotatedvdb_tpu.serve.engine import (
        QueryError, decode_cursor, encode_cursor,
    )

    token = encode_cursor(3, 7, 42)
    assert decode_cursor(token, 42) == 7
    truncated = base64.urlsafe_b64encode(
        b'{"o":7,"k":42}'
    ).decode().rstrip("=")
    with pytest.raises(QueryError):
        decode_cursor(truncated, 42)


# ---------------------------------------------------------------------------
# coalesced snapshot freshness (AVDB_SERVE_SNAPSHOT_TTL_MS)


def test_snapshot_ttl_coalesces_stats(tmp_path, monkeypatch):
    store_dir = str(tmp_path / "ttl_store")
    _build_store(store_dir)
    calls = {"n": 0}
    real = snapshot_mod._manifest_fingerprint

    def counting(path):
        calls["n"] += 1
        return real(path)

    monkeypatch.setattr(snapshot_mod, "_manifest_fingerprint", counting)
    manager = SnapshotManager(store_dir, ttl_s=60.0)
    base = calls["n"]
    for _ in range(100):
        assert manager.maybe_refresh() is False
    assert calls["n"] == base + 1  # one stat for the whole TTL window
    # refresh() keeps its always-stat semantics
    assert manager.refresh() is False
    assert calls["n"] == base + 2
    # ttl 0: every maybe_refresh stats (the uncoalesced PR-5 behavior)
    manager0 = SnapshotManager(store_dir, ttl_s=0.0)
    base = calls["n"]
    for _ in range(5):
        manager0.maybe_refresh()
    assert calls["n"] == base + 5


def test_snapshot_ttl_commit_visible_within_window(tmp_path):
    store_dir = str(tmp_path / "ttl_live")
    _build_store(store_dir)
    manager = SnapshotManager(store_dir, ttl_s=0.05)
    engine = QueryEngine(manager, region_cache_size=0)
    assert json.loads(engine.region("8:4999999-5001000"))["count"] == 0
    manager.maybe_refresh()  # arm the window
    _commit_more_rows(store_dir)
    # within the window: stale is acceptable and expected...
    deadline = time.monotonic() + 5.0
    while manager.current().generation == 1:
        manager.maybe_refresh()
        if time.monotonic() > deadline:
            raise AssertionError("commit never became visible via TTL path")
        time.sleep(0.01)
    # ...and after it lapses the commit is visible with no forced refresh
    assert json.loads(engine.region("8:4999999-5001000"))["count"] > 0


# ---------------------------------------------------------------------------
# batcher non-blocking submission (the aio front end's primitive)


def test_submit_nowait_callback_completes_off_thread(store):
    store_dir, truth = store
    manager = SnapshotManager(store_dir)
    engine = QueryEngine(manager, region_cache_size=0)
    batcher = QueryBatcher(engine, max_batch=16, max_wait_s=0.001)
    try:
        done = threading.Event()
        got = {}

        def cb(pending):
            got["result"] = pending.result
            got["error"] = pending.error
            done.set()

        pending = batcher.submit_nowait(
            _vid(truth[0]), cb, want_event=False
        )
        assert pending.done is None  # no Event allocated on this path
        assert done.wait(10)
        assert got["error"] is None
        assert json.loads(got["result"])["position"] == truth[0]["pos"]
        # blocking submit still works on the same batcher
        assert batcher.submit(_vid(truth[1])) is not None
    finally:
        batcher.close()


def test_loop_batcher_burst_leaves_no_orphan_drain():
    """A submit burst past max_batch schedules exactly one follow-up
    drain.  The old path queued one ``call_soon`` per submit at full
    depth and dropped the backlog timer handle without cancelling it, so
    a request arriving in the same loop slice as the burst's drains was
    left behind a stale armed timer (and could be drained by an orphan
    handle before its coalescing window)."""
    import asyncio

    from annotatedvdb_tpu.serve.aio import LoopBatcher

    class _Engine:
        def lookup_many(self, ids, parsed=None):
            return [None] * len(ids)

    async def scenario():
        b = LoopBatcher(_Engine(), max_batch=4, max_wait_s=30.0,
                        max_queue=64)
        loop = asyncio.get_running_loop()
        burst = [b.submit_future(f"1:{100 + i}:A:T") for i in range(5)]
        lone = []
        # lands in the same loop pass as the burst's drain — the window
        # where the old code's duplicate/orphan handles did damage
        loop.call_soon(lambda: lone.append(b.submit_future("1:900:A:T")))
        for _ in range(4):
            await asyncio.sleep(0)
        # the single follow-up drain coalesced the backlog AND the fresh
        # arrival (max_wait is 30s: a timer could not have done this) in
        # exactly TWO microbatches; the old path's duplicate call_soon
        # plus the orphaned backlog handle executed three, the last a
        # premature single-query batch
        assert all(f.done() for f in burst)
        assert lone and lone[0].done()
        assert b._batches == 2
        assert b.depth() == 0
        # nothing may survive the burst: a stale timer or queued drain
        # here is exactly the orphan that fired into later lone queues
        assert b._timer is None and not b._drain_soon
        b.close()

    asyncio.run(scenario())


def test_heartbeat_mmap_preopened_at_worker_start(store, tmp_path):
    """The heartbeat file is opened + mmap'd ONCE at construction (worker
    start) — never on the event loop (AVDB701: the maintenance tick only
    pack_intos the established mapping).  Pinned by unlinking the file
    before the loop starts: a per-tick reopen would fail and stop the
    beats, while the preopened mapping keeps advancing."""
    import os
    import struct

    from annotatedvdb_tpu.serve.aio import build_aio_server

    store_dir, _truth = store
    from annotatedvdb_tpu.serve.fleet import HB_SLOT

    hb = tmp_path / "hb"
    hb.write_bytes(b"\x00" * HB_SLOT.size)
    server = build_aio_server(
        store_dir=store_dir, port=0, heartbeat_file=str(hb),
        heartbeat_index=0,
    )
    try:
        # the mapping exists BEFORE any loop does
        assert server._hb_mm is not None
        os.unlink(hb)  # a reopen from here on is impossible
        server.start_background()
        deadline = time.monotonic() + 10
        beat1 = 0.0
        while beat1 == 0.0 and time.monotonic() < deadline:
            beat1 = struct.unpack_from("<d", server._hb_mm, 0)[0]
            time.sleep(0.05)
        assert beat1 > 0.0, "first heartbeat never landed"
        beat2 = beat1
        while beat2 <= beat1 and time.monotonic() < deadline:
            beat2 = struct.unpack_from("<d", server._hb_mm, 0)[0]
            time.sleep(0.05)
        assert beat2 > beat1, "heartbeat stopped advancing after unlink"
    finally:
        server.shutdown()
        server.ctx.batcher.close()


def test_heartbeat_unusable_file_logs_and_serves(store, tmp_path):
    """A missing/unopenable heartbeat file degrades exactly as before:
    the worker logs, serves, and the watchdog just never sees it."""
    from annotatedvdb_tpu.serve.aio import build_aio_server

    store_dir, _truth = store
    logs: list = []
    server = build_aio_server(
        store_dir=store_dir, port=0,
        heartbeat_file=str(tmp_path / "missing_hb"),
        log=logs.append,
    )
    try:
        assert server._hb_mm is None
        assert any("heartbeat file unusable" in m for m in logs)
        server.start_background()
        port = server.server_address[1]
        status, body, _hdrs = _get(port, "/healthz")
        assert status == 200 and json.loads(body)["status"] == "ok"
    finally:
        server.shutdown()
        server.ctx.batcher.close()
