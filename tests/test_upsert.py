"""Live write path: WAL durability, memtable semantics, and the
read-your-writes contract — an upserted row is immediately visible
through every read path (point/bulk/region/regions), byte-identical
across BOTH front ends, merged under the store's first-wins dedup policy,
and byte-identical before vs after the memtable flushes it to ordinary
store segments."""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from annotatedvdb_tpu.loaders.lookup import identity_hashes
from annotatedvdb_tpu.obs.metrics import MetricsRegistry
from annotatedvdb_tpu.serve import (
    MemtableSnapshots,
    QueryEngine,
    QueryError,
    SnapshotManager,
    StaticSnapshots,
)
from annotatedvdb_tpu.serve.aio import build_aio_server
from annotatedvdb_tpu.serve.http import (
    UPSERT_MAX_ROWS,
    build_server,
    parse_upsert_body,
)
from annotatedvdb_tpu.store import VariantStore
from annotatedvdb_tpu.store.memtable import (
    Memtable,
    flush_age_from_env,
    flush_bytes_from_env,
)
from annotatedvdb_tpu.store.wal import WriteAheadLog
from annotatedvdb_tpu.types import encode_allele_array

WIDTH = 8


def _seed_store() -> VariantStore:
    """Three chr3 A->C SNVs (pos 10/20/30) with real identity hashes and
    a CADD annotation on the middle one (filter paths have work to do)."""
    store = VariantStore(width=WIDTH)
    ref, ref_len = encode_allele_array(["A"] * 3, WIDTH)
    alt, alt_len = encode_allele_array(["C"] * 3, WIDTH)
    store.shard(3).append(
        {"pos": np.asarray([10, 20, 30], np.int32),
         "h": identity_hashes(WIDTH, ref, alt, ref_len, alt_len),
         "ref_len": ref_len, "alt_len": alt_len},
        ref, alt,
        annotations={"cadd_scores": [None, {"CADD_phred": 22.5}, None]},
    )
    return store


def _request(port, method, path, body=None, timeout=15):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", method=method,
        data=json.dumps(body).encode() if body is not None else None,
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as err:
        return err.code, err.read()


@pytest.fixture()
def pair(tmp_path):
    """(threaded port, aio port, store_dir, contexts): both front ends
    over ONE on-disk store, each with its own memtable + WAL (the fleet
    shape: per-worker write state, shared read generation)."""
    store_dir = str(tmp_path / "store")
    _seed_store().save(store_dir)
    servers = []

    def one(tag, build):
        registry = MetricsRegistry()
        mgr = SnapshotManager(store_dir, log=lambda m: None)
        mem = Memtable(
            width=WIDTH, store_dir=store_dir,
            wal=WriteAheadLog(store_dir, f"serve-{tag}",
                              log=lambda m: None),
            registry=registry, log=lambda m: None,
        )
        return build(manager=MemtableSnapshots(mgr, mem), port=0,
                     memtable=mem, registry=registry), mem, mgr

    httpd, mem_t, mgr_t = one("t", build_server)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    aio, mem_a, mgr_a = one("a", build_aio_server)
    aio.start_background()
    servers = [(httpd, "threaded"), (aio, "aio")]
    yield {
        "pt": httpd.server_address[1], "pa": aio.server_address[1],
        "store_dir": store_dir,
        "ctx_t": httpd.ctx, "ctx_a": aio.ctx,
        "mem_t": mem_t, "mem_a": mem_a,
        "mgr_t": mgr_t, "mgr_a": mgr_a,
    }
    aio.shutdown()
    aio.ctx.batcher.close()
    httpd.shutdown()
    httpd.ctx.batcher.close()
    del servers


UPSERT_BODY = {"variants": [
    {"id": "3:15:A:G", "ref_snp": 42,
     "annotations": {"cadd_scores": {"CADD_phred": 31.0},
                     "other_annotation": {"src": "live"}}},
    {"id": "3:25:AT:A"},
]}


# ---------------------------------------------------------------------------
# WAL unit contract


def test_wal_roundtrip_and_rotation(tmp_path):
    d = str(tmp_path)
    wal = WriteAheadLog(d, "serve-w0", log=lambda m: None)
    wal.append({"rows": [{"a": 1}]})
    wal.append({"rows": [{"b": 2}]})
    sealed = wal.rotate()
    assert sealed == 1
    wal.append({"rows": [{"c": 3}]})
    fresh = WriteAheadLog(d, "serve-w0", log=lambda m: None)
    got = list(fresh.replay_records())
    assert got == [{"rows": [{"a": 1}]}, {"rows": [{"b": 2}]},
                   {"rows": [{"c": 3}]}]
    # discard covers exactly the sealed interval
    assert wal.discard_sealed() == 1
    fresh = WriteAheadLog(d, "serve-w0", log=lambda m: None)
    assert list(fresh.replay_records()) == [{"rows": [{"c": 3}]}]
    wal.close()


def test_wal_torn_tail_dropped_earlier_records_survive(tmp_path):
    d = str(tmp_path)
    wal = WriteAheadLog(d, "serve-w0", log=lambda m: None)
    wal.append({"k": 1})
    wal.append({"k": 2})
    wal.close()
    path = wal.pending_files()[0][1]
    with open(path, "r+b") as f:
        f.truncate(os.path.getsize(path) - 5)  # tear the 2nd frame
    got = list(WriteAheadLog(d, "serve-w0",
                             log=lambda m: None).replay_records())
    assert got == [{"k": 1}]


def test_wal_corrupt_frame_stops_that_file(tmp_path):
    d = str(tmp_path)
    wal = WriteAheadLog(d, "serve-w0", log=lambda m: None)
    wal.append({"k": 1})
    wal.append({"k": 2})
    wal.close()
    path = wal.pending_files()[0][1]
    blob = bytearray(open(path, "rb").read())
    blob[-3] ^= 0xFF  # flip a byte inside the LAST record's payload
    open(path, "wb").write(bytes(blob))
    got = list(WriteAheadLog(d, "serve-w0",
                             log=lambda m: None).replay_records())
    assert got == [{"k": 1}]  # crc catches the flip; earlier record fine


def test_wal_close_removes_record_free_files_only(tmp_path):
    d = str(tmp_path)
    wal = WriteAheadLog(d, "serve-w0", log=lambda m: None)
    wal.append({"k": 1})
    wal.rotate()  # active file now header-only
    wal.close(remove_if_empty=True)
    files = wal.pending_files()
    assert len(files) == 1  # the record-bearing file stayed
    assert list(WriteAheadLog(d, "serve-w0",
                              log=lambda m: None).replay_records()) \
        == [{"k": 1}]


def test_wal_files_are_per_worker(tmp_path):
    d = str(tmp_path)
    WriteAheadLog(d, "serve-w0", log=lambda m: None).append({"w": 0})
    WriteAheadLog(d, "serve-w1", log=lambda m: None).append({"w": 1})
    assert list(WriteAheadLog(d, "serve-w0",
                              log=lambda m: None).replay_records()) \
        == [{"w": 0}]


# ---------------------------------------------------------------------------
# body grammar (single source, shared by both front ends)


def test_parse_upsert_body_accepts_canonical_shape():
    entries = parse_upsert_body(json.dumps(UPSERT_BODY).encode())
    assert entries[0]["id"] == "3:15:A:G"
    assert entries[0]["ref_snp"] == 42
    assert entries[1]["annotations"] is None


@pytest.mark.parametrize("body", [
    b"not json",
    b"[]",
    b"{}",
    b'{"variants": []}',
    b'{"variants": ["3:15:A:G"]}',
    b'{"variants": [{"id": 7}]}',
    b'{"variants": [{"id": "3:15:A:G", "ref_snp": -1}]}',
    b'{"variants": [{"id": "3:15:A:G", "ref_snp": true}]}',
    b'{"variants": [{"id": "3:15:A:G", "annotations": ["x"]}]}',
    b'{"variants": [{"id": "3:15:A:G", "annotations": {"nope": 1}}]}',
])
def test_parse_upsert_body_rejects_malformed(body):
    with pytest.raises(QueryError):
        parse_upsert_body(body)


def test_parse_upsert_body_row_cap():
    body = json.dumps({"variants": [
        {"id": "3:10:A:C"}] * (UPSERT_MAX_ROWS + 1)}).encode()
    with pytest.raises(QueryError, match="cap"):
        parse_upsert_body(body)


# ---------------------------------------------------------------------------
# read-your-writes: both front ends, every read path, byte-identical


def test_upsert_read_your_writes_parity_both_front_ends(pair):
    pt, pa = pair["pt"], pair["pa"]
    # ack on both (per-worker memtables: each accepts the new rows)
    for port in (pt, pa):
        status, body = _request(port, "POST", "/variants/upsert",
                                UPSERT_BODY)
        assert status == 200, body
        assert json.loads(body) == {
            "n": 2, "accepted": 2, "shadowed": 0,
            "generation": json.loads(body)["generation"],
        }
    # IMMEDIATE visibility through every read path, byte-identical
    # across the two front ends
    reads = [
        ("GET", "/variant/3:15:A:G", None),
        ("GET", "/variant/3:25:AT:A", None),
        ("GET", "/variant/3:20:A:C", None),          # loaded row untouched
        ("POST", "/variants",
         {"ids": ["3:15:A:G", "3:25:AT:A", "3:10:A:C", "3:99:A:C"]}),
        ("GET", "/region/3:1-100", None),
        ("GET", "/region/3:1-100?minCadd=30", None),  # filter sees upsert
        ("POST", "/regions", {"regions": ["3:1-100", "3:14-16"]}),
    ]
    for method, path, body in reads:
        s1, b1 = _request(pt, method, path, body)
        s2, b2 = _request(pa, method, path, body)
        assert s1 == s2 == 200, (path, s1, s2, b1, b2)
        assert b1 == b2, (path, b1, b2)
    # and the content is right: the region count grew, the upserted row
    # renders with its annotations, the filter finds the new CADD row
    _s, region = _request(pt, "GET", "/region/3:1-100")
    env = json.loads(region)
    assert env["count"] == 5 and env["returned"] == 5
    _s, rec = _request(pt, "GET", "/variant/3:15:A:G")
    assert b'"rs42"' in rec and b'"src": "live"' in rec
    _s, filtered = _request(pt, "GET", "/region/3:1-100?minCadd=30")
    assert json.loads(filtered)["count"] == 1


def test_upsert_shadowed_by_loaded_row_first_wins(pair):
    """An upsert whose identity the store already holds is SHADOWED: the
    stored row keeps answering byte-identically, the response reports
    the shadow, and the rejected-rows counter moves."""
    pt = pair["pt"]
    _s, before = _request(pt, "GET", "/variant/3:20:A:C")
    status, body = _request(pt, "POST", "/variants/upsert", {"variants": [
        {"id": "3:20:A:C",
         "annotations": {"other_annotation": {"hijack": True}}},
    ]})
    assert status == 200
    assert json.loads(body)["shadowed"] == 1
    assert json.loads(body)["accepted"] == 0
    _s, after = _request(pt, "GET", "/variant/3:20:A:C")
    assert after == before  # first-wins: the loaded row still answers
    # the same identity upserted twice in ONE batch: first occurrence wins
    status, body = _request(pt, "POST", "/variants/upsert", {"variants": [
        {"id": "3:40:A:G", "ref_snp": 1},
        {"id": "3:40:A:G", "ref_snp": 2},
    ]})
    assert json.loads(body) == {
        "n": 2, "accepted": 1, "shadowed": 1,
        "generation": json.loads(body)["generation"],
    }
    _s, rec = _request(pt, "GET", "/variant/3:40:A:G")
    assert b'"rs1"' in rec


def test_upsert_visible_through_concurrent_cursor_walk(pair):
    """A paged region walk started BEFORE an upsert picks the new row up
    on pages rendered after it: cursor offsets re-apply against the new
    generation (the best-effort continuation contract cursors already
    have across loader commits)."""
    pt = pair["pt"]
    s, page1 = _request(pt, "GET", "/region/3:1-100?limit=1&cursor=")
    assert s == 200
    env1 = json.loads(page1)
    assert env1["count"] == 3 and env1["next"]
    status, _b = _request(pt, "POST", "/variants/upsert", {"variants": [
        {"id": "3:25:AT:A"},
    ]})
    assert status == 200
    seen = [v["position"] for v in env1["variants"]]
    cursor = env1["next"]
    for _ in range(8):
        s, page = _request(
            pt, "GET", f"/region/3:1-100?limit=1&cursor={cursor}"
        )
        assert s == 200
        env = json.loads(page)
        seen += [v["position"] for v in env["variants"]]
        assert env["count"] == 4  # the walk now sees the upserted row
        cursor = env["next"]
        if not cursor:
            break
    assert seen == [10, 20, 25, 30]


def test_upserts_disabled_route_403_parity(tmp_path):
    store_dir = str(tmp_path / "ro")
    _seed_store().save(store_dir)
    httpd = build_server(store_dir=store_dir, port=0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    aio = build_aio_server(store_dir=store_dir, port=0)
    aio.start_background()
    try:
        s1, b1 = _request(httpd.server_address[1], "POST",
                          "/variants/upsert", UPSERT_BODY)
        s2, b2 = _request(aio.server_address[1], "POST",
                          "/variants/upsert", UPSERT_BODY)
        assert s1 == s2 == 403 and b1 == b2
        assert b"not enabled" in b1
    finally:
        aio.shutdown()
        aio.ctx.batcher.close()
        httpd.shutdown()
        httpd.ctx.batcher.close()


def test_upsert_grammar_errors_are_parity_400s(pair):
    cases = [
        {"nope": 1},
        {"variants": [{"id": "3:15:A:G", "annotations": {"bogus": 1}}]},
        {"variants": [{"id": "not-an-id"}]},
        {"variants": [{"id": "3:15:" + "A" * 20 + ":G"}]},  # over-width
    ]
    for body in cases:
        s1, b1 = _request(pair["pt"], "POST", "/variants/upsert", body)
        s2, b2 = _request(pair["pa"], "POST", "/variants/upsert", body)
        assert s1 == s2 == 400, (body, s1, s2)
        assert b1 == b2, (body, b1, b2)


# ---------------------------------------------------------------------------
# flush: pre/post byte identity, WAL truncation, ledger record


def test_flush_preserves_read_bytes_and_truncates_wal(pair):
    pt, mem, mgr = pair["pt"], pair["mem_t"], pair["mgr_t"]
    store_dir = pair["store_dir"]
    status, _b = _request(pt, "POST", "/variants/upsert", UPSERT_BODY)
    assert status == 200
    reads = [
        ("GET", "/variant/3:15:A:G", None),
        ("GET", "/variant/3:25:AT:A", None),
        ("POST", "/variants", {"ids": ["3:15:A:G", "3:10:A:C"]}),
        ("GET", "/region/3:1-100", None),
        ("POST", "/regions", {"regions": ["3:1-100"]}),
    ]
    before = [_request(pt, m, p, b) for m, p, b in reads]
    result = mem.flush(base_manager=mgr)
    assert result["status"] == "flushed" and result["finalized"], result
    assert mem.rows == 0
    after = [_request(pt, m, p, b) for m, p, b in reads]
    # region envelopes carry the generation, which a flush advances (the
    # view handed over from memtable to store segments) — everything
    # else must be byte-identical
    import re as _re

    def _scrub(pairs):
        return [
            (s, _re.sub(rb'"generation":\d+', b'"generation":G', b))
            for s, b in pairs
        ]

    assert _scrub(before) == _scrub(after)
    # the rows are ordinary store segments now
    store = VariantStore.load(store_dir)
    assert store.shard(3).n == 5
    # the flushed interval's WAL files are gone; a fresh worker replays
    # nothing (the store already holds everything)
    fresh = Memtable(
        width=WIDTH, store_dir=store_dir,
        wal=WriteAheadLog(store_dir, "serve-t", log=lambda m: None),
        log=lambda m: None,
    )
    assert fresh.replay(VariantStore.load(store_dir, readonly=True)) == 0
    # ledger carries the {"type": "flush"} record
    from annotatedvdb_tpu.store import AlgorithmLedger

    ledger = AlgorithmLedger(os.path.join(store_dir, "ledger.jsonl"),
                             log=lambda m: None)
    flushes = ledger.flushes()
    assert flushes and flushes[-1]["rows"] == 2 \
        and flushes[-1]["labels"] == ["3"]


def test_generation_strictly_increases_across_upserts_and_flush(pair):
    pt, mem, mgr = pair["pt"], pair["mem_t"], pair["mgr_t"]
    gens = []

    def healthz_gen():
        _s, b = _request(pt, "GET", "/healthz")
        return json.loads(b)["generation"]

    gens.append(healthz_gen())
    for k in range(3):
        _request(pt, "POST", "/variants/upsert",
                 {"variants": [{"id": f"3:{50 + k}:A:G"}]})
        gens.append(healthz_gen())
    assert mem.flush(base_manager=mgr)["status"] == "flushed"
    gens.append(healthz_gen())
    assert gens == sorted(gens) and len(set(gens)) == len(gens), gens


def test_flush_triggers_and_env_knobs(tmp_path, monkeypatch):
    store_dir = str(tmp_path / "store")
    _seed_store().save(store_dir)
    base = VariantStore.load(store_dir, readonly=True)
    mem = Memtable(width=WIDTH, store_dir=store_dir, flush_bytes=1,
                   flush_age_s=0, log=lambda m: None)
    assert not mem.should_flush()  # empty
    mem.upsert(base, [{"code": 3, "pos": 15, "ref": "A", "alt": "G",
                       "ref_snp": None, "ann": None}])
    assert mem.should_flush()  # one row trips a 1-byte bound
    mem2 = Memtable(width=WIDTH, store_dir=store_dir, flush_bytes=0,
                    flush_age_s=0.05, log=lambda m: None)
    mem2.upsert(base, [{"code": 3, "pos": 16, "ref": "A", "alt": "G",
                        "ref_snp": None, "ann": None}])
    assert not mem2.should_flush()
    time.sleep(0.08)
    assert mem2.should_flush()  # the age trigger
    # env parsing: shared grammar, loud failures
    monkeypatch.setenv("AVDB_MEMTABLE_BYTES", "64m")
    assert flush_bytes_from_env() == 64 << 20
    monkeypatch.setenv("AVDB_MEMTABLE_BYTES", "64mb")
    with pytest.raises(ValueError, match="AVDB_MEMTABLE_BYTES"):
        flush_bytes_from_env()
    monkeypatch.setenv("AVDB_MEMTABLE_FLUSH_S", "2.5")
    assert flush_age_from_env() == 2.5
    monkeypatch.setenv("AVDB_MEMTABLE_FLUSH_S", "soon")
    with pytest.raises(ValueError, match="AVDB_MEMTABLE_FLUSH_S"):
        flush_age_from_env()


def test_upsert_metrics_move(pair):
    ctx, mem = pair["ctx_a"], pair["mem_a"]
    reg: MetricsRegistry = ctx.registry
    _request(pair["pa"], "POST", "/variants/upsert", {"variants": [
        {"id": "3:60:A:G"},
        {"id": "3:10:A:C"},   # shadowed
    ]})
    snap = reg.snapshot()
    assert snap["avdb_upsert_requests_total"][0]["value"] == 1
    assert snap["avdb_upsert_rows_total"][0]["value"] == 1
    assert snap["avdb_upsert_rejected_total"][0]["value"] == 1
    assert snap["avdb_upsert_wal_bytes_total"][0]["value"] > 0
    assert snap["avdb_memtable_bytes"][0]["value"] > 0
    assert snap["avdb_upsert_ack_seconds"][0]["count"] == 1
    kinds = {tuple(sorted(e["labels"].items())): e["value"]
             for e in snap["avdb_query_requests_total"]}
    assert kinds[(("kind", "upsert"),)] == 1
    assert mem.flush(base_manager=pair["mgr_a"])["status"] == "flushed"
    snap = reg.snapshot()
    assert snap["avdb_upsert_flushes_total"][0]["value"] == 1
    assert snap["avdb_memtable_bytes"][0]["value"] == 0


def test_overlay_is_passthrough_until_first_upsert(tmp_path):
    store_dir = str(tmp_path / "store")
    _seed_store().save(store_dir)
    mgr = SnapshotManager(store_dir, log=lambda m: None)
    mem = Memtable(width=WIDTH, store_dir=store_dir, log=lambda m: None)
    prov = MemtableSnapshots(mgr, mem)
    snap = prov.current()
    assert snap is mgr.current()  # the very same object: zero overhead
    base = VariantStore.load(store_dir, readonly=True)
    mem.upsert(base, [{"code": 3, "pos": 15, "ref": "A", "alt": "G",
                       "ref_snp": None, "ann": None}])
    over = prov.current()
    assert over is not snap
    assert over.generation > snap.generation
    assert over.store.n == 4
    # stable while nothing changes (cached overlay, not rebuilt per read)
    assert prov.current() is over


def test_replayed_worker_serves_acked_rows_byte_identical(tmp_path):
    """The respawn story in-process: worker A acks rows and dies
    (abandoned memtable); worker B replays the WAL and serves the exact
    same bytes."""
    store_dir = str(tmp_path / "store")
    _seed_store().save(store_dir)
    base = VariantStore.load(store_dir, readonly=True)
    mem_a = Memtable(
        width=WIDTH, store_dir=store_dir,
        wal=WriteAheadLog(store_dir, "serve-w0", log=lambda m: None),
        log=lambda m: None,
    )
    rows = [
        {"code": 3, "pos": 15, "ref": "A", "alt": "G", "ref_snp": 42,
         "ann": {"other_annotation": {"k": [1, 2]}}},
        {"code": 3, "pos": 25, "ref": "AT", "alt": "A", "ref_snp": None,
         "ann": None},
    ]
    accepted, _s, _b = mem_a.upsert(base, rows)
    assert accepted == 2
    engine_a = QueryEngine(
        MemtableSnapshots(StaticSnapshots(base), mem_a),
        region_cache_size=0,
    )
    want = [engine_a.lookup("3:15:A:G"), engine_a.lookup("3:25:AT:A"),
            engine_a.region("3:1-100")]
    # worker A dies; worker B replays
    mem_b = Memtable(
        width=WIDTH, store_dir=store_dir,
        wal=WriteAheadLog(store_dir, "serve-w0", log=lambda m: None),
        log=lambda m: None,
    )
    assert mem_b.replay(base) == 2
    engine_b = QueryEngine(
        MemtableSnapshots(StaticSnapshots(base), mem_b),
        region_cache_size=0,
    )
    got = [engine_b.lookup("3:15:A:G"), engine_b.lookup("3:25:AT:A"),
           engine_b.region("3:1-100")]
    assert got == want


def test_loader_save_adopts_concurrent_flush_groups(tmp_path):
    """The third-writer hole closed: a loader that loaded the store
    BEFORE a memtable flush committed (and whose WAL was then truncated)
    must not clobber or orphan the flushed segments when it saves —
    save() re-syncs next_seg_id from the live manifest and carries the
    flush's groups forward, on every subsequent checkpoint save too."""
    store_dir = str(tmp_path / "store")
    _seed_store().save(store_dir)

    # the "loader": holds the pre-flush manifest in memory
    loader_store = VariantStore.load(store_dir)

    # a serve worker acks + flushes an upsert meanwhile; the WAL is
    # truncated — the flushed segment is now the ONLY copy of the row
    mem = Memtable(
        width=WIDTH, store_dir=store_dir,
        wal=WriteAheadLog(store_dir, "serve-w0", log=lambda m: None),
        log=lambda m: None,
    )
    base = VariantStore.load(store_dir, readonly=True)
    accepted, _s, _b = mem.upsert(base, [
        {"code": 3, "pos": 15, "ref": "A", "alt": "G", "ref_snp": 7,
         "ann": {"other_annotation": {"live": True}}},
    ])
    assert accepted == 1
    assert mem.flush(base_manager=None)["status"] == "flushed"
    assert not [f for f in os.listdir(store_dir) if f.endswith(".wal")
                and os.path.getsize(os.path.join(store_dir, f)) > 60]

    # the loader commits on top of its STALE view
    import numpy as np_

    from annotatedvdb_tpu.loaders.lookup import identity_hashes as ih

    ref, ref_len = encode_allele_array(["A"], WIDTH)
    alt, alt_len = encode_allele_array(["G"], WIDTH)
    loader_store.shard(3).append(
        {"pos": np_.asarray([40], np_.int32),
         "h": ih(WIDTH, ref, alt, ref_len, alt_len),
         "ref_len": ref_len, "alt_len": alt_len},
        ref, alt,
    )
    loader_store.save(store_dir)

    final = VariantStore.load(store_dir)
    assert final.shard(3).n == 5, "flushed row lost to the loader save"
    engine = QueryEngine(StaticSnapshots(final), region_cache_size=0)
    rec = engine.lookup("3:15:A:G")
    assert rec is not None and '"live": true' in rec
    assert engine.lookup("3:40:A:G") is not None

    # a SECOND checkpoint save must keep re-adopting (not a one-shot)
    loader_store.shard(3).append(
        {"pos": np_.asarray([50], np_.int32),
         "h": ih(WIDTH, ref, alt, ref_len, alt_len),
         "ref_len": ref_len, "alt_len": alt_len},
        ref, alt,
    )
    loader_store.save(store_dir)
    final = VariantStore.load(store_dir)
    assert final.shard(3).n == 6
    assert QueryEngine(StaticSnapshots(final),
                       region_cache_size=0).lookup("3:15:A:G") == rec

    from annotatedvdb_tpu.store.fsck import fsck

    report = fsck(store_dir, deep=True, log=lambda m: None)
    # only the loader's own record-free wal-less debris may warn; the
    # data findings must be absent
    assert report["exit_code"] in (0, 1), report
    assert not any(f["code"].startswith("segment-")
                   for f in report["findings"]), report


def test_undo_still_drops_rows_despite_adoption(tmp_path):
    """Adoption must never resurrect rows an undo deleted: groups below
    the load-time floor are this store's own to manage."""
    store_dir = str(tmp_path / "store")
    store = VariantStore(width=WIDTH)
    import numpy as np_

    ref, ref_len = encode_allele_array(["A"] * 2, WIDTH)
    alt, alt_len = encode_allele_array(["C"] * 2, WIDTH)
    store.shard(3).append(
        {"pos": np_.asarray([10, 20], np_.int32),
         "h": identity_hashes(WIDTH, ref, alt, ref_len, alt_len),
         "ref_len": ref_len, "alt_len": alt_len,
         "row_algorithm_id": np_.asarray([9, 9], np_.int32)},
        ref, alt,
    )
    store.save(store_dir)
    undoer = VariantStore.load(store_dir)
    assert undoer.delete_by_algorithm(9) == 2
    undoer.save(store_dir)
    assert VariantStore.load(store_dir).n == 0
