"""Store scale-wall regression: flush cost must stay flat as the shard grows.

The round-2 store rewrote its full sorted arrays on every flush
(``np.insert`` per batch — O(n) per flush, O(n^2/batch) per load), which
cannot reach the BASELINE 90M-row gate.  The segmented store appends one
sorted segment per flush with an amortized-logarithmic cascade merge, so a
load's per-batch cost must not grow with store size.  These tests guard that
property at a size where the quadratic behavior is unmistakable (the
10M-row full-scale run lives in ``bench.py --scale``, not in CI).
"""

import os
import time

import numpy as np
import pytest

from annotatedvdb_tpu.store import VariantStore

WIDTH = 16
BATCH = 1 << 14
N_BATCHES = 64  # 1M rows: quadratic flush cost would show a >10x drift


def _batches(n_batches: int, batch: int, seed: int = 11):
    """Pre-sorted unique-identity batches for one chromosome (chr1), shaped
    like the loader's append input (hash column = low bits of a counter, so
    identities are unique and spread)."""
    rng = np.random.default_rng(seed)
    for b in range(n_batches):
        pos = np.sort(rng.integers(1, 248_000_000, batch)).astype(np.int32)
        h = (np.arange(batch, dtype=np.uint32) + np.uint32(b * batch)) * np.uint32(
            2654435761
        )
        order = np.argsort(
            (pos.astype(np.uint64) << np.uint64(32)) | h, kind="stable"
        )
        ref = np.zeros((batch, WIDTH), np.uint8)
        alt = np.zeros((batch, WIDTH), np.uint8)
        ref[:, 0] = 65
        alt[:, 0] = 71
        rows = {
            "pos": pos[order],
            "h": h[order],
            "ref_len": np.ones(batch, np.int32),
            "alt_len": np.ones(batch, np.int32),
            "row_algorithm_id": np.full(batch, 1, np.int32),
        }
        yield rows, ref, alt


def test_flush_cost_stays_flat(monkeypatch):
    """The scale-wall gate is DETERMINISTIC: total rows moved by cascade
    merges must stay O(n log(n/batch)) — the old np.insert store rewrote
    the whole shard per flush (~n^2/(2*batch) rows moved), which this bound
    rejects by orders of magnitude.  Wall-clock is only a loose smoke check
    (CI timers share a core with the rest of the suite)."""
    from annotatedvdb_tpu.store import variant_store as vs

    merged_rows = [0]
    real_merge = vs.Segment.merge.__func__

    def counting_merge(cls, older, newer):
        merged_rows[0] += older.n + newer.n
        return real_merge(cls, older, newer)

    monkeypatch.setattr(vs.Segment, "merge", classmethod(counting_merge))

    store = VariantStore(width=WIDTH)
    shard = store.shard(1)
    times = []
    for rows, ref, alt in _batches(N_BATCHES, BATCH):
        t0 = time.perf_counter()
        shard.append(rows, ref, alt)
        times.append(time.perf_counter() - t0)
    n = N_BATCHES * BATCH
    assert shard.n == n

    # the deterministic amortization bound
    assert merged_rows[0] <= n * (np.log2(N_BATCHES) + 2), (
        f"cascade merges moved {merged_rows[0]:,} rows for a {n:,}-row load "
        f"— amortization regressed (np.insert regime is ~{n * N_BATCHES // 2:,})"
    )
    # segment count stays logarithmic, so lookup cost is bounded
    assert len(shard.segments) <= 2 + int(np.log2(N_BATCHES))
    # loose wall-clock smoke: the second half must not blow up outright
    first = max(float(np.median(times[: N_BATCHES // 2])), 5e-4)
    second = float(np.median(times[N_BATCHES // 2:]))
    assert second < 10.0 * first + 5e-3, (
        f"per-flush cost grew {second / first:.1f}x over the load"
    )


@pytest.mark.skipif(
    not os.environ.get("AVDB_SCALE_TEST"),
    reason="10M-row scale gate: set AVDB_SCALE_TEST=1 (runs ~1-2 min)",
)
def test_flush_cost_flat_at_10m():
    """Full-scale gate: 10M rows into one chr1 shard, flat flush cost and
    bounded memory (RSS growth ~ data size, not O(n^2) temporaries)."""
    import resource

    n_batches, batch = 160, 1 << 16  # 10.5M rows
    store = VariantStore(width=WIDTH)
    shard = store.shard(1)
    rss0 = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    times = []
    for rows, ref, alt in _batches(n_batches, batch, seed=29):
        t0 = time.perf_counter()
        shard.append(rows, ref, alt)
        times.append(time.perf_counter() - t0)
    assert shard.n == n_batches * batch
    first = float(np.median(times[: n_batches // 2]))
    second = float(np.median(times[n_batches // 2:]))
    assert second < 3.0 * first + 1e-3, (
        f"per-flush cost grew {second / first:.1f}x at 10M rows"
    )
    import sys

    rss_unit = 1 if sys.platform == "darwin" else 1024  # bytes vs KB
    rss_growth_mb = (
        resource.getrusage(resource.RUSAGE_SELF).ru_maxrss - rss0
    ) * rss_unit / (1024 * 1024)
    # ~76B/row numeric+allele data = ~800MB; allow transient merge doubling
    assert rss_growth_mb < 4096, f"memory not bounded: +{rss_growth_mb:.0f}MB"


def test_incremental_save_is_flat(tmp_path):
    """Per-checkpoint persistence writes only new/dirty segments.

    Asserted by MECHANISM, not wall-clock (a 1ms-slack timing comparison
    flaked ~half the time on the drifting shared host): persisted files
    are never rewritten in place (append-only — a re-persisted segment
    takes a fresh id, orphans are removed), and a no-op re-save must not
    touch any segment file at all."""
    store = VariantStore(width=WIDTH)
    shard = store.shard(1)
    out = str(tmp_path / "vdb")

    def seg_files():
        return {
            f: os.stat(os.path.join(out, f)).st_mtime_ns
            for f in os.listdir(out)
            if f.endswith((".npz", ".ann.jsonl"))
        }

    prev: dict = {}
    for rows, ref, alt in _batches(12, BATCH, seed=13):
        shard.append(rows, ref, alt)
        store.save(out)
        # every file a save leaves behind belongs to a CLEAN segment, and
        # surviving files are never rewritten IN PLACE (append-only:
        # cascade-merged segments persist under fresh ids; their
        # constituents' files are orphan-removed, not mutated)
        assert all(not s.dirty for s in shard.segments)
        cur = seg_files()
        rewritten = {f for f in prev if f in cur and cur[f] != prev[f]}
        assert not rewritten, f"save rewrote files in place: {rewritten}"
        prev = cur
    # after a save everything is clean: an immediate re-save writes NO
    # segment files (new or rewritten, byte-for-byte the same directory)
    store.save(out)
    assert seg_files() == prev
    loaded = VariantStore.load(out)
    assert loaded.n == store.n
    np.testing.assert_array_equal(
        loaded.shard(1).column("pos"), shard.column("pos")
    )


def test_frozen_segments_not_remerged_or_rewritten(tmp_path, monkeypatch):
    """Segments past MERGE_SEGMENT_CAP freeze: later flushes never re-merge
    them (bounding merge traffic at whole-genome scale) and later saves
    never rewrite their files (bounding persist IO)."""
    from annotatedvdb_tpu.store import variant_store as vs

    monkeypatch.setattr(vs, "MERGE_SEGMENT_CAP", 3 * BATCH)
    store = VariantStore(width=WIDTH)
    shard = store.shard(1)
    out = str(tmp_path / "vdb")
    frozen_mtime = {}
    for bi, (rows, ref, alt) in enumerate(_batches(16, BATCH, seed=31)):
        shard.append(rows, ref, alt)
        store.save(out)
        for seg in shard.segments:
            if seg.n > 3 * BATCH and seg.backing:
                for sid in seg.backing:
                    f = [x for x in os.listdir(out)
                         if x.endswith(".npz") and f"{sid:06d}" in x]
                    assert f, "frozen segment must be on disk"
                    mt = os.path.getmtime(os.path.join(out, f[0]))
                    if sid in frozen_mtime:
                        assert mt == frozen_mtime[sid], (
                            "frozen segment rewritten by a later save"
                        )
                    frozen_mtime[sid] = mt
    assert frozen_mtime, "load never produced a frozen segment"
    assert len(shard.segments) > 1  # cap actually prevented full compaction
    # membership still correct across frozen + live segments
    rows, ref, alt = next(iter(_batches(1, BATCH, seed=31)))
    found, idx = shard.lookup(
        rows["pos"], rows["h"], ref, alt, rows["ref_len"], rows["alt_len"]
    )
    assert found.all()
    # and lookups against absent rows stay absent
    found2, _ = shard.lookup(
        rows["pos"] + 1, rows["h"], ref, alt, rows["ref_len"], rows["alt_len"]
    )
    assert not found2.any()


def test_segment_device_probe_matches_numpy(monkeypatch):
    """The device membership kernel path gives identical answers to the
    numpy probe (forced on despite the CPU backend/thresholds)."""
    from annotatedvdb_tpu.store import variant_store as vs

    monkeypatch.setattr(vs, "DEVICE_SEGMENT_MIN", 1)
    monkeypatch.setattr(vs, "DEVICE_QUERY_MIN", 1)
    monkeypatch.setattr(vs, "_DEVICE_LOOKUP_OK", True)

    store = VariantStore(width=WIDTH)
    shard = store.shard(1)
    for rows, ref, alt in _batches(2, 4096, seed=17):
        shard.append(rows, ref, alt)
    seg = shard.segments[0]
    pos, h = seg.cols["pos"][::3], seg.cols["h"][::3]
    ref, alt = seg.ref[::3], seg.alt[::3]
    rl, al = seg.cols["ref_len"][::3], seg.cols["alt_len"][::3]
    qkey = vs.combined_key(pos, h)
    f_dev, i_dev = seg.probe(qkey, pos, h, ref, alt, rl, al)
    monkeypatch.setattr(vs, "_DEVICE_LOOKUP_OK", False)
    f_np, i_np = seg.probe(qkey, pos, h, ref, alt, rl, al)
    np.testing.assert_array_equal(f_dev, f_np)
    np.testing.assert_array_equal(i_dev, i_np)
    assert f_np.all()


def test_pin_device_lookup_builds_reachable_cache(monkeypatch):
    """pin_device_lookup uploads segment caches that subsequent small-query
    probes actually use (the sunk-cost disjunct in Segment.probe)."""
    from annotatedvdb_tpu.store import variant_store as vs

    monkeypatch.setattr(vs, "_DEVICE_LOOKUP_OK", True)
    monkeypatch.setattr(vs, "DEVICE_SEGMENT_MIN", 1)

    store = VariantStore(width=WIDTH)
    shard = store.shard(1)
    for rows, ref, alt in _batches(2, 4096, seed=23):
        shard.append(rows, ref, alt)
    assert shard.pin_device_lookup() == len(
        [s for s in shard.segments if s.n]
    )
    assert all(s._device is not None for s in shard.segments if s.n)
    seg = shard.segments[0]
    # a query far too small to amortize an upload still rides the cache;
    # answers match the numpy path exactly
    pos, h = seg.cols["pos"][:16], seg.cols["h"][:16]
    ref, alt = seg.ref[:16], seg.alt[:16]
    rl, al = seg.cols["ref_len"][:16], seg.cols["alt_len"][:16]
    qkey = vs.combined_key(pos, h)
    f_dev, i_dev = seg.probe(qkey, pos, h, ref, alt, rl, al)
    monkeypatch.setattr(vs, "_DEVICE_LOOKUP_OK", False)
    f_np, i_np = seg.probe(qkey, pos, h, ref, alt, rl, al)
    np.testing.assert_array_equal(f_dev, f_np)
    np.testing.assert_array_equal(i_dev, i_np)
    assert f_np.all()


def test_pin_for_updates_respects_link_speed(monkeypatch):
    """store.pin_for_updates pins every eligible segment when the backend
    and link qualify, and is a no-op on slow links."""
    from annotatedvdb_tpu.store import variant_store as vs

    monkeypatch.setattr(vs, "_DEVICE_LOOKUP_OK", True)
    monkeypatch.setattr(vs, "DEVICE_SEGMENT_MIN", 1)
    store = VariantStore(width=WIDTH)
    shard = store.shard(1)
    for rows, ref, alt in _batches(1, 4096, seed=41):
        shard.append(rows, ref, alt)
    monkeypatch.setattr(vs, "_TRANSFER_FAST", False)
    assert store.pin_for_updates() == 0  # slow link: no-op
    monkeypatch.setattr(vs, "_TRANSFER_FAST", True)
    assert store.pin_for_updates() == 1
    assert shard.segments[0]._device is not None


def test_append_interleaved_with_lookup(rng):
    """Membership answers stay exact across segment cascades."""
    from annotatedvdb_tpu.ops.hashing import allele_hash_jit
    from annotatedvdb_tpu.types import VariantBatch

    from conftest import random_variants

    store = VariantStore(width=24)
    shard = store.shard(1)
    seen = []
    for step in range(8):
        variants = [("1", v[1], v[2], v[3])
                    for v in random_variants(rng, 64, max_len=10)]
        batch = VariantBatch.from_tuples(variants, width=24)
        h = np.asarray(
            allele_hash_jit(batch.ref, batch.alt, batch.ref_len, batch.alt_len)
        )
        found, _ = shard.lookup(
            batch.pos, h, batch.ref, batch.alt, batch.ref_len, batch.alt_len
        )
        fresh = ~found
        # in-batch dedup so appended identities are unique
        key = (batch.pos.astype(np.uint64) << np.uint64(32)) | h
        _, first = np.unique(key, return_index=True)
        keep = np.zeros(batch.n, bool)
        keep[first] = True
        sel = np.where(fresh & keep)[0]
        shard.append(
            {"pos": batch.pos[sel], "h": h[sel],
             "ref_len": batch.ref_len[sel], "alt_len": batch.alt_len[sel]},
            batch.ref[sel], batch.alt[sel],
        )
        seen.extend(variants[int(i)] for i in sel)
    # every row ever appended is found afterwards
    all_b = VariantBatch.from_tuples(seen, width=24)
    all_h = np.asarray(
        allele_hash_jit(all_b.ref, all_b.alt, all_b.ref_len, all_b.alt_len)
    )
    found, idx = shard.lookup(
        all_b.pos, all_h, all_b.ref, all_b.alt, all_b.ref_len, all_b.alt_len
    )
    assert found.all()
    assert shard.n == len(seen)
    np.testing.assert_array_equal(shard.get_col("pos", idx), all_b.pos)


def test_fast_link_auto_device_lookup(monkeypatch):
    """AVDB_DEVICE_LOOKUP=auto on a fast link: large-segment probes take
    the device kernel path by POLICY (ski-rental crossover), not only by
    env override — and return numpy-identical results (VERDICT r3 #8: the
    fast-link branch was dead code off TPU-local deployments)."""
    from annotatedvdb_tpu.store import variant_store as vs

    # simulate a locally-attached accelerator on the CPU test backend:
    # kernels run, transfers are fast, mode is plain auto
    monkeypatch.setattr(vs, "_TRANSFER_FAST", True)
    monkeypatch.setattr(vs, "_DEVICE_LOOKUP_OK", True)
    monkeypatch.setattr(vs, "_DEVICE_LOOKUP_MODE", "auto")

    n = vs.DEVICE_SEGMENT_MIN  # smallest segment the policy uploads
    (rows, ref, alt), = _batches(1, n, seed=41)
    store = VariantStore(width=WIDTH)
    shard = store.shard(1)
    shard.append(rows, ref, alt)
    seg = shard.segments[0]
    assert seg._device is None

    # query volume large enough that the ski-rental accumulator crosses on
    # the first probe: nq * AMORTIZE >= n
    nq = n // vs.DEVICE_UPLOAD_AMORTIZE
    q = slice(0, nq)
    found, idx = shard.lookup(
        rows["pos"][q], rows["h"][q], ref[q], alt[q],
        rows["ref_len"][q], rows["alt_len"][q],
    )
    assert seg._device is not None, "policy did not take the device path"
    assert found.all()

    # device answers == numpy answers on hits AND misses
    f_dev, i_dev = seg._probe_device(
        rows["pos"][q], rows["h"][q], ref[q], alt[q],
        rows["ref_len"][q], rows["alt_len"][q],
    )
    assert f_dev.all() and (i_dev >= 0).all()
    miss_pos = rows["pos"][q] + 1
    f_miss, i_miss = seg._probe_device(
        miss_pos, rows["h"][q], ref[q], alt[q],
        rows["ref_len"][q], rows["alt_len"][q],
    )
    assert not f_miss.any() and (i_miss == -1).all()


def test_slow_link_auto_stays_numpy(monkeypatch):
    """auto mode on a slow link never uploads (the r3-tuned behavior)."""
    from annotatedvdb_tpu.store import variant_store as vs

    monkeypatch.setattr(vs, "_TRANSFER_FAST", False)
    monkeypatch.setattr(vs, "_DEVICE_LOOKUP_OK", True)
    monkeypatch.setattr(vs, "_DEVICE_LOOKUP_MODE", "auto")
    n = vs.DEVICE_SEGMENT_MIN
    (rows, ref, alt), = _batches(1, n, seed=43)
    store = VariantStore(width=WIDTH)
    shard = store.shard(1)
    shard.append(rows, ref, alt)
    found, _ = shard.lookup(
        rows["pos"][:8192], rows["h"][:8192], ref[:8192], alt[:8192],
        rows["ref_len"][:8192], rows["alt_len"][:8192],
    )
    assert found.all()
    assert shard.segments[0]._device is None
