"""Crash/recovery matrix: for every fault-injection kill point in the VCF
load path, abort a committing load mid-flight, then require that

1. the on-disk store loads cleanly, at most one checkpoint behind, OR is
   restored by ``store_fsck --repair``; and
2. ledger-driven resume completes the load to a store whose CONTENT is
   identical to an uninterrupted run (provenance columns — seg ids,
   ``row_algorithm_id`` — necessarily differ: they encode how many
   invocations it took, which is the one thing a crash changes).

The in-process matrix uses the ``raise`` action: an exception abandons the
in-memory store exactly where a crash would, and the durable state is
whatever the persist path had already renamed into place — the same
atomic-swap guarantees a SIGKILL exercises, minus page-cache effects no
in-tree test can simulate.  ``test_sigkill_*`` drives two points through a
real subprocess SIGKILL for the no-finally-runs guarantee.
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from annotatedvdb_tpu.config import StoreConfig
from annotatedvdb_tpu.store import VariantStore
from annotatedvdb_tpu.store.fsck import fsck
from annotatedvdb_tpu.utils import faults

N_ROWS = 2600
BATCH = 512  # ~6 chunks => ~6 checkpoints per committed load


@pytest.fixture(autouse=True)
def _disarm():
    yield
    faults.reset("")


def _write_vcf(path, n=N_ROWS):
    with open(path, "w") as f:
        f.write("##fileformat=VCFv4.2\n"
                "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\n")
        for i in range(n):
            f.write(f"8\t{1000 + 3 * i}\trs{i}\tA\tG\t.\t.\tRS={i}\n")


def _run_load(store_dir, vcf, fault=""):
    """One committing CLI-shaped load (persist-before-checkpoint).  Returns
    (counters, exception): with a fault armed, the in-memory store is
    abandoned like a crashed process's heap and only disk state survives."""
    from annotatedvdb_tpu.loaders import TpuVcfLoader

    faults.reset(fault)
    store, ledger = StoreConfig(store_dir).open()
    loader = TpuVcfLoader(
        store, ledger, batch_size=BATCH, log=lambda *a: None,
    )
    try:
        counters = loader.load_file(
            vcf, commit=True, resume=True,
            persist=lambda: store.save(store_dir),
        )
        loader.close()
        store.save(store_dir)
        return counters, None
    except BaseException as exc:
        # a real crash stops every thread instantly: cancel the "dead"
        # loader's queued writer jobs so it cannot keep committing into
        # the directory while the recovery run is underway (an artifact
        # only an in-process crash simulation has)
        try:
            if loader._writer_pool is not None:
                loader._writer_pool.shutdown(wait=True, cancel_futures=True)
            if loader._prefetch_pool is not None:
                loader._prefetch_pool.shutdown(wait=False)
        except Exception:  # avdb: noqa[AVDB602] -- best-effort teardown of a simulated-dead loader; the armed fault is the exception under test
            pass
        return None, exc
    finally:
        faults.reset("")


def _content(store_dir):
    """Content signature: every column except provenance (alg ids)."""
    store = VariantStore.load(store_dir)
    shard = store.shard(8)
    shard.compact()
    cols = {
        c: shard.cols[c]
        for c in ("pos", "h", "ref_snp", "ref_len", "alt_len",
                  "bin_level", "leaf_bin")
    }
    return cols, shard.ref.copy(), shard.alt.copy(), store.n


@pytest.fixture(scope="module")
def reference(tmp_path_factory):
    """One uninterrupted load: the content every recovery must reproduce."""
    d = tmp_path_factory.mktemp("ref")
    vcf = str(d / "d.vcf")
    _write_vcf(vcf)
    ref_store = str(d / "store")
    counters, exc = _run_load(ref_store, vcf)
    assert exc is None, exc
    assert counters["variant"] == N_ROWS
    return vcf, _content(ref_store)


# every kill point of the load path; nth chosen so at least one checkpoint
# is durable before the fault lands (the "<= 1 checkpoint behind" clause)
MATRIX = [
    ("store.save.pre_manifest:2:raise", False),
    ("store.save.pre_manifest:2:raise", True),   # + fsck --repair pass
    ("store.save.mid_segment:3:raise", False),
    ("ledger.append:4:raise", False),
    ("ingest.chunk:4:raise", False),
    # the prefetch spine (io/prefetch.py): death ON the prefetch thread —
    # the stage envelope must surface it on the consumer, and the durable
    # store stays <= 1 checkpoint behind like any other ingest death
    ("ingest.prefetch:3:raise", False),
    ("ingest.prefetch:2:eio", False),
]


@pytest.mark.parametrize("fault,run_fsck", MATRIX)
def test_crash_matrix(tmp_path, reference, fault, run_fsck):
    vcf, want = reference
    store_dir = str(tmp_path / "crash")

    counters, exc = _run_load(store_dir, vcf, fault=fault)
    assert exc is not None, f"{fault}: fault never fired"

    # 1. the durable store must load cleanly (possibly behind) ...
    partial = VariantStore.load(store_dir)
    assert partial.n <= N_ROWS
    # ... at most one checkpoint behind the ledger cursor: resume replays
    # idempotently, so the cursor may lag the store but never lead it
    from annotatedvdb_tpu.store import AlgorithmLedger

    cursor = AlgorithmLedger(
        os.path.join(store_dir, "ledger.jsonl")
    ).last_checkpoint(vcf)
    committed_rows = partial.n
    assert cursor <= 2 + committed_rows  # lines = header(2) + one per row

    if run_fsck:  # repair between crash and resume must stay recoverable
        report = fsck(store_dir, repair=True, log=lambda m: None)
        assert report["exit_code"] in (0, 1), report
        VariantStore.load(store_dir)

    # 2. resume completes to reference content
    counters, exc = _run_load(store_dir, vcf)
    assert exc is None, f"{fault}: resume failed: {exc}"
    got = _content(store_dir)
    want_cols, want_ref, want_alt, want_n = want
    got_cols, got_ref, got_alt, got_n = got
    assert got_n == want_n == N_ROWS
    for c, arr in want_cols.items():
        np.testing.assert_array_equal(got_cols[c], arr, err_msg=f"{fault}:{c}")
    np.testing.assert_array_equal(got_ref, want_ref)
    np.testing.assert_array_equal(got_alt, want_alt)

    # 3. post-recovery store passes fsck cleanly (orphans at worst)
    report = fsck(store_dir, deep=True, repair=True, log=lambda m: None)
    assert report["exit_code"] in (0, 1), report


def _cli(vcf, store, extra=()):
    return [sys.executable, "-m", "annotatedvdb_tpu.cli.load_vcf",
            "--fileName", vcf, "--storeDir", store,
            "--commitAfter", str(BATCH), "--commit", *extra]


@pytest.mark.parametrize("fault", [
    "store.save.pre_manifest:2:kill",
    "ledger.append:4:torn_write",
    # SIGKILL delivered ON the ingest-prefetch thread, mid-scan: the whole
    # process dies with chunks queued ahead of the consumer, and resume
    # must still land exactly on the reference content
    "ingest.prefetch:3:kill",
])
def test_sigkill_matrix(tmp_path, reference, fault):
    """True process death (no finally/atexit) at the juiciest points:
    before a manifest swap, tearing a ledger append in half, and mid-scan
    on the prefetch thread."""
    vcf, want = reference
    store_dir = str(tmp_path / "crash")
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        AVDB_FAULT=fault,
        JAX_COMPILATION_CACHE_DIR=str(tmp_path / "jaxcache"),
        JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES="0",
        JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS="0",
    )
    p = subprocess.run(_cli(vcf, store_dir), env=env,
                       capture_output=True, text=True, timeout=480)
    assert p.returncode == -signal.SIGKILL, (
        f"expected SIGKILL death, got rc={p.returncode}\n{p.stderr[-2000:]}"
    )

    # store loads (possibly behind); fsck prunes crash debris
    try:
        n_partial = VariantStore.load(store_dir).n
    except FileNotFoundError:
        # the prefetch thread runs AHEAD of the consumer: its kill can
        # land before the very first checkpoint persisted, leaving no
        # manifest at all — "zero checkpoints behind nothing" is a legal
        # durable state for that point, and resume starts from scratch
        assert fault.startswith("ingest.prefetch"), fault
        n_partial = 0
    assert n_partial <= N_ROWS
    if n_partial:
        report = fsck(store_dir, repair=True, log=lambda m: None)
        assert report["exit_code"] in (0, 1), report

    # resume (no fault armed) completes to reference content
    env.pop("AVDB_FAULT")
    p = subprocess.run(_cli(vcf, store_dir), env=env,
                       capture_output=True, text=True, timeout=480)
    assert p.returncode == 0, p.stderr[-2000:]
    got_cols, got_ref, got_alt, got_n = _content(store_dir)
    want_cols, want_ref, want_alt, want_n = want
    assert got_n == want_n
    for c, arr in want_cols.items():
        np.testing.assert_array_equal(got_cols[c], arr, err_msg=c)
    np.testing.assert_array_equal(got_ref, want_ref)
    np.testing.assert_array_equal(got_alt, want_alt)


# ---------------------------------------------------------------------------
# egress.flush — the export leg's injection point.  Not part of the VCF
# load matrix above (egress runs offline), but every faults.POINTS entry
# must be crash-tested here (static rule AVDB302): a raise mid-export must
# abort without leaving a torn COPY tmp, and a rerun must complete.


def _tiny_store(width=8):
    """Three chr3 A->C SNVs with REAL identity hashes (the serve legs probe
    them back by ``chr:pos:ref:alt``, so the stored hash must match what
    the engine computes)."""
    from annotatedvdb_tpu.loaders.lookup import identity_hashes
    from annotatedvdb_tpu.types import encode_allele_array

    store = VariantStore(width=width)
    ref, ref_len = encode_allele_array(["A"] * 3, width)
    alt, alt_len = encode_allele_array(["C"] * 3, width)
    store.shard(3).append(
        {"pos": np.asarray([10, 20, 30], np.int32),
         "h": identity_hashes(width, ref, alt, ref_len, alt_len),
         "ref_len": ref_len, "alt_len": alt_len},
        ref, alt,
    )
    return store


def test_egress_flush_raise_aborts_clean_and_rerun_completes(tmp_path):
    from annotatedvdb_tpu.io.pg_egress import export_store
    from annotatedvdb_tpu.utils.faults import InjectedFault

    store = _tiny_store()
    out = str(tmp_path / "export")
    faults.reset("egress.flush:1:raise")
    with pytest.raises(InjectedFault):
        export_store(store, out)
    # the aborted export left no torn half-written COPY tmp behind
    data_dir = os.path.join(out, "data")
    if os.path.isdir(data_dir):
        assert [f for f in os.listdir(data_dir) if ".tmp" in f] == []
    # rerun unarmed completes to full content
    faults.reset("")
    counts = export_store(store, out)
    assert counts == {"3": 3}
    data = open(os.path.join(data_dir, "variant_chr3.copy")).read()
    assert data.count("\n") == 3


# ---------------------------------------------------------------------------
# serve.batch / snapshot.swap — the serving subsystem's injection points
# (AVDB302: every faults.POINTS entry must be crash-tested in this file).
# Both use the raise action: serving is in-memory, so the contract is
# fail-the-unit-of-work-and-keep-running, not crash-and-recover-from-disk.


def test_serve_batch_raise_fails_only_that_batch_and_recovers():
    """An injected fault mid-drain (serve.batch:1:raise) must surface the
    root cause to every caller of THAT microbatch and leave the drain
    thread serving the next one."""
    from annotatedvdb_tpu.serve import QueryBatcher, QueryEngine, StaticSnapshots
    from annotatedvdb_tpu.utils.faults import InjectedFault

    engine = QueryEngine(StaticSnapshots(_tiny_store()), region_cache_size=0)
    batcher = QueryBatcher(engine, max_batch=4, max_wait_s=0.001)
    try:
        faults.reset("serve.batch:1:raise")
        with pytest.raises(InjectedFault):
            batcher.submit("3:10:A:C")
        faults.reset("")
        # the batcher survived its failed drain: same query now answers
        assert batcher.submit("3:10:A:C") is not None
        stats = batcher.drain_stats()
        assert stats["batches"] == 1  # only the clean drain counted
    finally:
        faults.reset("")
        batcher.close()


def test_serve_regions_raise_fails_only_that_batch_and_recovers():
    """An injected fault in the batch-region drain (serve.regions:1:raise)
    must fail exactly that batch's caller — the front ends map it to one
    500 — and leave the engine answering the next batch byte-identically
    to the untouched single-region path."""
    from annotatedvdb_tpu.serve import QueryEngine, StaticSnapshots
    from annotatedvdb_tpu.utils.faults import InjectedFault

    engine = QueryEngine(StaticSnapshots(_tiny_store()), region_cache_size=0)
    specs = ["3:1-100", "3:5-25"]
    want = [engine.region(s) for s in specs]
    faults.reset("serve.regions:1:raise")
    with pytest.raises(InjectedFault):
        engine.regions_serve(specs)
    # the engine survived its failed batch: the same panel now answers,
    # byte-identical per interval to the single-region calls
    got = engine.regions_serve(specs)
    assert [p.assemble() for p in got.pages] == want


def test_serve_stats_raise_and_eio_fail_only_that_request_and_recover():
    """An injected fault in the analytics drain (serve.stats raise/eio)
    must fail exactly that panel's caller — the front ends map it to one
    500 — and leave the engine answering the next panel byte-identically
    (incl. after an EIO, the transient-device shape the stats breaker
    fallback also absorbs)."""
    from annotatedvdb_tpu.serve import QueryEngine, StaticSnapshots
    from annotatedvdb_tpu.utils.faults import InjectedFault

    engine = QueryEngine(StaticSnapshots(_tiny_store()), region_cache_size=0)
    specs = ["3:1-100", "3:5-25"]
    want = engine.stats_serve(specs).assemble()
    try:
        faults.reset("serve.stats:1:raise")
        with pytest.raises(InjectedFault):
            engine.stats_serve(specs)
        faults.reset("serve.stats:1:eio")
        with pytest.raises(OSError):
            engine.stats_serve(specs)
    finally:
        faults.reset("")
    # the engine survived both failed panels: same panel, same bytes
    assert engine.stats_serve(specs).assemble() == want


def test_snapshot_swap_raise_keeps_old_generation_serving(tmp_path):
    """A fault between loading the new generation and swapping the pin
    (snapshot.swap:1:raise) must leave the OLD generation serving; an
    unarmed retry completes the swap."""
    from annotatedvdb_tpu.serve import SnapshotManager
    from annotatedvdb_tpu.utils.faults import InjectedFault

    store_dir = str(tmp_path / "store")
    _tiny_store().save(store_dir)
    manager = SnapshotManager(store_dir)
    rows_v1 = manager.current().store.n

    # a loader commit lands a second generation on disk
    store = VariantStore.load(store_dir)
    store.shard(3).append(
        {"pos": np.asarray([40], np.int32),
         "h": np.asarray([11], np.uint32),
         "ref_len": np.full(1, 1, np.int32),
         "alt_len": np.full(1, 1, np.int32)},
        np.full((1, 8), 65, np.uint8), np.full((1, 8), 71, np.uint8),
    )
    store.save(store_dir)

    faults.reset("snapshot.swap:1:raise")
    with pytest.raises(InjectedFault):
        manager.refresh()
    # the pin never moved: generation 1, old row count
    snap = manager.current()
    assert snap.generation == 1 and snap.store.n == rows_v1

    faults.reset("")
    assert manager.refresh() is True
    snap = manager.current()
    assert snap.generation == 2 and snap.store.n == rows_v1 + 1


# ---------------------------------------------------------------------------
# serve.accept / serve.worker — the fleet's injection points.  An accept
# fault must cost exactly one connection (raise) while the server keeps
# serving; a killed worker must be restarted by the supervisor with the
# fleet serving cleanly after the restart window.


def test_serve_accept_raise_fails_only_that_connection():
    import urllib.error
    import urllib.request

    from annotatedvdb_tpu.serve import StaticSnapshots
    from annotatedvdb_tpu.serve.aio import build_aio_server

    server = build_aio_server(
        manager=StaticSnapshots(_tiny_store()), port=0
    )
    server.start_background()
    try:
        port = server.server_address[1]

        def get():
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/variant/3:10:A:C", timeout=30
            ) as r:
                return r.status

        assert get() == 200
        # arm: the NEXT accepted connection dies before parsing anything
        # (the client sees a reset/empty response, never a served reply)
        faults.reset("serve.accept:1:raise")
        with pytest.raises((urllib.error.URLError, ConnectionResetError)):
            get()
        # exactly that connection failed; the server keeps serving
        faults.reset("")
        assert get() == 200
    finally:
        faults.reset("")
        server.shutdown()
        server.ctx.batcher.close()


def test_engine_device_probe_eio_trips_breaker_then_half_open_recloses():
    """engine.device_probe (eio): repeated injected device-probe failures
    must (1) never change answer bytes — the breaker retries the
    byte-identical host path — and (2) trip the per-group breaker after
    the threshold, then re-close it through a half-open probe once the
    cooldown lapses and the fault is gone."""
    from annotatedvdb_tpu.serve import (
        DeviceBreaker,
        QueryEngine,
        StaticSnapshots,
    )

    clock = {"t": 0.0}
    breaker = DeviceBreaker(cooldown_s=5.0, clock=lambda: clock["t"])
    engine = QueryEngine(
        StaticSnapshots(_tiny_store()), region_cache_size=0,
        breaker=breaker,
    )
    want = engine.lookup("3:10:A:C")
    assert want is not None
    faults.reset("engine.device_probe:prob:1.0:eio")
    for _ in range(breaker.failure_threshold):
        # every failing probe still answers, byte-identical (host retry)
        assert engine.lookup("3:10:A:C") == want
    assert breaker.state(3) == "open"
    # while tripped the device path is never attempted: the armed fault
    # cannot fire (host-only path), answers stay correct
    fired_before = faults.fired().get("engine.device_probe", 0)
    assert engine.lookup("3:10:A:C") == want
    assert faults.fired().get("engine.device_probe", 0) == fired_before
    # cooldown lapses, fault cleared: ONE half-open probe re-closes
    faults.reset("")
    clock["t"] = 10.0
    assert engine.lookup("3:10:A:C") == want
    assert breaker.state(3) == "closed"


def test_serve_wedge_watchdog_kills_and_respawns(tmp_path):
    """serve.wedge (delay): a long delay on the event-loop maintenance
    tick parks the LOOP — the worker process stays alive but stops
    heartbeating and serving.  The fleet watchdog must SIGKILL it
    (logged as wedged) and the respawned workers (fault stripped) must
    bring the fleet back to clean serving."""
    import re
    import subprocess
    import threading
    import time
    import urllib.request

    store_dir = str(tmp_path / "wedge_store")
    _tiny_store().save(store_dir)
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        # 3rd tick (~0.5s after accept starts): both workers come up,
        # serve briefly, then park their loops for 60s
        AVDB_FAULT="serve.wedge:3:delay:60000",
        AVDB_SERVE_WEDGE_TIMEOUT_S="2",
    )
    proc = subprocess.Popen(
        [sys.executable, "-m", "annotatedvdb_tpu", "serve",
         "--storeDir", store_dir, "--port", "0", "--workers", "2"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True,
    )
    lines: list[str] = []
    try:
        first = proc.stdout.readline()
        lines.append(first)
        reader = threading.Thread(
            target=lambda: lines.extend(proc.stdout), daemon=True
        )
        reader.start()
        m = re.search(r"http://([\d.]+):(\d+)", first)
        assert m, f"no fleet address line: {first!r}"
        host, port = m.group(1), int(m.group(2))

        def get(path):
            with urllib.request.urlopen(
                f"http://{host}:{port}{path}", timeout=5
            ) as r:
                return r.status

        # the watchdog must detect the parked loops and the respawned
        # (clean) workers must serve again
        deadline = time.monotonic() + 120
        recovered = False
        while time.monotonic() < deadline:
            if any("wedged" in ln for ln in lines):
                try:
                    if get("/variant/3:10:A:C") == 200:
                        recovered = True
                        break
                except OSError:
                    pass
            time.sleep(0.3)
        assert any("wedged" in ln for ln in lines), (
            "watchdog never detected the wedged workers:\n"
            + "".join(lines)[-2000:]
        )
        assert recovered, (
            "fleet never recovered after the wedge kills:\n"
            + "".join(lines)[-2000:]
        )
        # recovered means RELIABLY serving, not one lucky hit
        failures = sum(
            1 for _ in range(20)
            if _get_status_or_none(get) != 200
        )
        assert failures == 0
    finally:
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=60)
    assert rc == 0, "".join(lines)[-2000:]


def _get_status_or_none(get):
    try:
        return get("/variant/3:10:A:C")
    except OSError:
        return None


# ---------------------------------------------------------------------------
# compact.plan / compact.merge / compact.swap / compact.gc — the online
# compactor's kill points (store/compact.py).  Contract: a death at ANY of
# them leaves a store byte-identical to either the PRE- or the
# POST-compaction reference — never a third state — and fsck --repair
# prunes whatever debris (compact temps, orphaned segments) the death left.


def _fragmented_store(store_dir: str) -> None:
    """Four disjoint chr6 segments saved one checkpoint apart, with sparse
    annotations — enough files that every compact kill point has real work
    in flight when it fires."""
    store = VariantStore(width=8)
    shard = store.shard(6)
    from annotatedvdb_tpu.store.variant_store import Segment

    for k in range(4):
        n = 250
        cols = {
            "pos": np.arange(500 + 20_000 * k, 500 + 20_000 * k + n,
                             dtype=np.int32),
            "h": np.arange(n, dtype=np.uint32) + 1,
            "ref_len": np.full(n, 1, np.int32),
            "alt_len": np.full(n, 1, np.int32),
        }
        shard.append_segment(Segment.build(
            cols, np.full((n, 8), 65, np.uint8),
            np.full((n, 8), 71, np.uint8),
            annotations={"other_annotation":
                         [{"k": int(i)} if i % 3 else None
                          for i in range(n)]},
        ))
        shard._starts_cache = None
        store.save(store_dir)


def _store_signature(store_dir: str):
    """Full content signature: every numeric column + alleles + a sample of
    annotations, in position-sorted order (compaction-invariant)."""
    from annotatedvdb_tpu.store.variant_store import _NUMERIC_COLUMNS

    store = VariantStore.load(store_dir)
    shard = store.shard(6)
    shard.compact()
    return (
        tuple(shard.cols[c].tobytes() for c, _ in _NUMERIC_COLUMNS),
        shard.ref.tobytes(), shard.alt.tobytes(),
        tuple(json.dumps(shard.get_ann("other_annotation", i))
              for i in range(0, store.n, 83)),
        store.n,
    )


@pytest.fixture()
def compact_refs(tmp_path):
    """(store_dir, pre signature, post signature): the two states every
    crashed compact pass must land on."""
    import shutil

    store_dir = str(tmp_path / "cstore")
    _fragmented_store(store_dir)
    pre = _store_signature(store_dir)
    ref_dir = str(tmp_path / "cref")
    shutil.copytree(store_dir, ref_dir)
    from annotatedvdb_tpu.store import compact_store

    report = compact_store(ref_dir)
    assert report["status"] == "compacted"
    post = _store_signature(ref_dir)
    assert post == pre  # no duplicates here: content identical either way
    return store_dir, pre, post


@pytest.mark.parametrize("fault,expect_state", [
    ("compact.plan:1:raise", "pre"),
    ("compact.plan:1:eio", "pre"),
    ("compact.merge:1:raise", "pre"),
    ("compact.merge:1:eio", "pre"),
    ("compact.swap:1:raise", "pre"),
    ("compact.gc:1:eio", "post"),   # gc absorbs eio: committed, orphans
])
def test_compact_crash_matrix_in_process(compact_refs, fault, expect_state):
    from annotatedvdb_tpu.store import compact_store
    from annotatedvdb_tpu.store.fsck import fsck as run_fsck

    store_dir, pre, post = compact_refs
    faults.reset(fault)
    try:
        report = compact_store(store_dir)
        fired = faults.fired()
        assert expect_state == "post", f"{fault}: fault never surfaced"
        assert report["status"] == "compacted" and fired
    except (faults.InjectedFault, OSError):
        assert expect_state == "pre"
    finally:
        faults.reset("")

    got = _store_signature(store_dir)
    assert got == (pre if expect_state == "pre" else post)
    # in-process aborts clean their own temps; repair handles the rest
    report = run_fsck(store_dir, repair=True, log=lambda m: None)
    assert report["exit_code"] in (0, 1), report
    assert _store_signature(store_dir) == got
    # an unarmed pass completes to the post state
    final = compact_store(store_dir)
    assert final["status"] in ("compacted", "noop")
    assert _store_signature(store_dir) == post


@pytest.mark.parametrize("fault", [
    "compact.merge:1:kill",
    "compact.merge:1:torn_write",
    "compact.swap:1:kill",
    "compact.gc:1:kill",
])
def test_compact_sigkill_matrix(compact_refs, fault):
    """True process death through the CLI (`doctor compact` subprocess):
    the durable store must equal pre OR post — never a hybrid — and the
    repair + rerun path must converge on post."""
    from annotatedvdb_tpu.store import compact_store
    from annotatedvdb_tpu.store.fsck import fsck as run_fsck

    store_dir, pre, post = compact_refs
    env = dict(os.environ, JAX_PLATFORMS="cpu", AVDB_FAULT=fault)
    p = subprocess.run(
        [sys.executable, "-m", "annotatedvdb_tpu", "doctor", "compact",
         "--storeDir", store_dir],
        env=env, capture_output=True, text=True, timeout=240,
    )
    assert p.returncode == -signal.SIGKILL, (
        f"{fault}: expected SIGKILL death, rc={p.returncode}\n"
        f"{p.stderr[-2000:]}"
    )
    got = _store_signature(store_dir)
    assert got in (pre, post), f"{fault}: store is a third state"
    # gc kill dies AFTER the commit point; everything earlier dies before
    expect_committed = fault.startswith("compact.gc")
    spans = json.load(open(os.path.join(store_dir, "manifest.json")))
    n_stems = sum(len(g) for g in spans["shards"]["6"])
    assert (n_stems == 1) == expect_committed

    report = run_fsck(store_dir, repair=True, log=lambda m: None)
    assert report["exit_code"] in (0, 1), report
    assert not [f for f in os.listdir(store_dir) if ".compact.tmp" in f]
    assert _store_signature(store_dir) == got

    final = compact_store(store_dir)
    assert final["status"] in ("compacted", "noop")
    assert _store_signature(store_dir) == post
    assert run_fsck(store_dir, deep=True,
                    log=lambda m: None)["exit_code"] == 0


def test_serve_worker_kill_fleet_restarts_and_keeps_serving(tmp_path):
    """SIGKILLed workers (serve.worker:1:kill fires in each initial worker
    right after it starts accepting) are restarted by the supervisor —
    with the serve-side fault stripped from the respawn env — and after
    the restart window the fleet serves with zero failed responses."""
    import re
    import subprocess
    import time
    import urllib.request

    store_dir = str(tmp_path / "fleet_store")
    _tiny_store().save(store_dir)
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        AVDB_FAULT="serve.worker:1:kill",
    )
    proc = subprocess.Popen(
        [sys.executable, "-m", "annotatedvdb_tpu", "serve",
         "--storeDir", store_dir, "--port", "0", "--workers", "2"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True,
    )
    try:
        line = proc.stdout.readline()
        m = re.search(r"http://([\d.]+):(\d+)", line)
        assert m, f"no fleet address line: {line!r}"
        host, port = m.group(1), int(m.group(2))

        def get(path):
            with urllib.request.urlopen(
                f"http://{host}:{port}{path}", timeout=5
            ) as r:
                return r.status

        # both initial workers die at the fire point; the supervisor
        # respawns them clean — wait out the restart window
        deadline = time.monotonic() + 120
        up = False
        while time.monotonic() < deadline:
            try:
                if get("/healthz") == 200:
                    up = True
                    break
            except OSError:
                time.sleep(0.3)
        assert up, "fleet never recovered from the injected worker kills"
        # zero failed responses after the restart window
        failures = 0
        for _ in range(30):
            try:
                if get("/variant/3:10:A:C") != 200:
                    failures += 1
            except OSError:
                failures += 1
        assert failures == 0
    finally:
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=60)
    assert rc == 0, proc.stdout.read()[-2000:]


# ---------------------------------------------------------------------------
# wal.append / wal.fsync / wal.replay / memtable.flush — the live write
# path's kill points (store/wal.py + store/memtable.py).  Contract: an
# ACKNOWLEDGED upsert (Memtable.upsert returned) is present after
# recovery; an unacknowledged one is applied in full or not at all —
# never a hybrid, never a torn store.


_UPSERT_ROW = {
    "code": 3, "pos": 15, "ref": "A", "alt": "G", "ref_snp": 7,
    "ann": {"other_annotation": {"k": 1}},
}


def _upsert_env(tmp_path):
    """(store_dir, base readonly store, memtable-with-wal) over the tiny
    chr3 store — the in-process write-path fixture."""
    from annotatedvdb_tpu.store.memtable import Memtable
    from annotatedvdb_tpu.store.wal import WriteAheadLog

    store_dir = str(tmp_path / "ustore")
    _tiny_store().save(store_dir)
    base = VariantStore.load(store_dir, readonly=True)
    wal = WriteAheadLog(store_dir, "serve-w0", log=lambda m: None)
    mem = Memtable(width=8, store_dir=store_dir, wal=wal,
                   log=lambda m: None)
    return store_dir, base, mem


def _fresh_replayed(store_dir, base):
    """A brand-new memtable rebuilt from the on-disk WAL — the respawned
    worker's view."""
    from annotatedvdb_tpu.store.memtable import Memtable
    from annotatedvdb_tpu.store.wal import WriteAheadLog

    mem = Memtable(width=8, store_dir=store_dir,
                   wal=WriteAheadLog(store_dir, "serve-w0",
                                     log=lambda m: None),
                   log=lambda m: None)
    applied = mem.replay(base)
    return mem, applied


@pytest.mark.parametrize("fault", [
    "wal.append:1:raise",
    "wal.append:1:eio",
])
def test_wal_append_fault_leaves_prestate(tmp_path, fault):
    """A failure BEFORE the WAL frame lands must fail the request with
    nothing visible, nothing durable, and nothing to replay — the
    consistent pre-state (the request was never acknowledged)."""
    store_dir, base, mem = _upsert_env(tmp_path)
    faults.reset(fault)
    try:
        with pytest.raises((faults.InjectedFault, OSError)):
            mem.upsert(base, [dict(_UPSERT_ROW)])
    finally:
        faults.reset("")
    assert mem.rows == 0
    replayed, applied = _fresh_replayed(store_dir, base)
    assert applied == 0 and replayed.rows == 0
    # unarmed retry succeeds and IS durable
    accepted, shadowed, _b = mem.upsert(base, [dict(_UPSERT_ROW)])
    assert (accepted, shadowed) == (1, 0)
    _, applied = _fresh_replayed(store_dir, base)
    assert applied == 1


def test_wal_fsync_fault_is_all_or_nothing(tmp_path):
    """A failure between the frame write and its fsync: the request was
    NOT acknowledged, but the frame is complete — replay applies it in
    full (never a torn half-row), which the contract allows for un-acked
    writes.  The failing request itself left nothing visible."""
    store_dir, base, mem = _upsert_env(tmp_path)
    faults.reset("wal.fsync:1:raise")
    try:
        with pytest.raises(faults.InjectedFault):
            mem.upsert(base, [dict(_UPSERT_ROW)])
    finally:
        faults.reset("")
    assert mem.rows == 0  # nothing became visible in the failing worker
    replayed, applied = _fresh_replayed(store_dir, base)
    assert applied in (0, 1)
    if applied:
        # applied IN FULL: the row answers with its exact content
        from annotatedvdb_tpu.serve import QueryEngine, StaticSnapshots
        from annotatedvdb_tpu.serve.snapshot import MemtableSnapshots

        engine = QueryEngine(
            MemtableSnapshots(StaticSnapshots(base), replayed),
            region_cache_size=0,
        )
        rec = engine.lookup("3:15:A:G")
        assert rec is not None and '"rs7"' in rec \
            and '"other_annotation":{"k": 1}' in rec


def test_wal_replay_fault_then_retry_recovers(tmp_path):
    """A death mid-replay (wal.replay) is recovered by replaying again on
    the next respawn — replay mutates nothing durable, and the first-wins
    check makes double-application impossible."""
    store_dir, base, mem = _upsert_env(tmp_path)
    accepted, _s, _b = mem.upsert(base, [dict(_UPSERT_ROW)])
    assert accepted == 1
    faults.reset("wal.replay:1:raise")
    try:
        with pytest.raises(faults.InjectedFault):
            _fresh_replayed(store_dir, base)
    finally:
        faults.reset("")
    # the respawn replays clean; a second replay pass over the same WAL
    # (the crash-during-replay recovery) changes nothing
    replayed, applied = _fresh_replayed(store_dir, base)
    assert applied == 1 and replayed.rows == 1
    accepted, shadowed, _b = replayed.upsert(
        base, [dict(_UPSERT_ROW)], durable=False
    )
    assert (accepted, shadowed) == (0, 1)


@pytest.mark.parametrize("fault", [
    "memtable.flush:1:raise",   # before anything is written
    "memtable.flush:1:eio",
    "memtable.flush:2:raise",   # mid-manifest-commit (segments renamed)
    "memtable.flush:2:eio",
])
def test_memtable_flush_crash_matrix_in_process(tmp_path, fault):
    """A flush failure at either kill point leaves the on-disk store
    byte-identical to its pre-flush state, the memtable + WAL keeping
    every acknowledged row (reads unaffected); fsck prunes any debris
    and an unarmed retry completes."""
    from annotatedvdb_tpu.store.fsck import fsck as run_fsck

    store_dir, base, mem = _upsert_env(tmp_path)
    pre = _store_signature_chr3(store_dir)
    accepted, _s, _b = mem.upsert(base, [dict(_UPSERT_ROW)])
    assert accepted == 1
    faults.reset(fault)
    try:
        with pytest.raises((faults.InjectedFault, OSError)):
            mem.flush(base_manager=None)
    finally:
        faults.reset("")
    # store untouched; the acknowledged row is still served (memtable)
    assert _store_signature_chr3(store_dir) == pre
    assert mem.rows == 1
    report = run_fsck(store_dir, repair=True, log=lambda m: None)
    assert report["exit_code"] in (0, 1), report
    # repair prunes WAL debris too in this mode — but the MEMTABLE still
    # holds the row, so the retry flush makes it durable regardless
    result = mem.flush(base_manager=None)
    assert result["status"] == "flushed" and result["rows"] == 1
    assert mem.rows == 0
    store = VariantStore.load(store_dir)
    assert store.shard(3).n == 4
    final = run_fsck(store_dir, repair=True, log=lambda m: None)
    assert final["exit_code"] in (0, 1), final


def _store_signature_chr3(store_dir: str):
    store = VariantStore.load(store_dir)
    shard = store.shard(3)
    shard.compact()
    return (
        shard.cols["pos"].tobytes(), shard.cols["h"].tobytes(),
        shard.ref.tobytes(), shard.alt.tobytes(), store.n,
    )


def test_memtable_flush_preempted_by_loader_commit(tmp_path):
    """The three-writer coordination contract: a loader committing a new
    generation between the flush's plan and its commit point PREEMPTS the
    flush (status aborted, temps cleaned, memtable untouched) — and the
    retry lands the rows on top of the loader's generation."""
    store_dir, base, mem = _upsert_env(tmp_path)
    accepted, _s, _b = mem.upsert(base, [dict(_UPSERT_ROW)])
    assert accepted == 1

    from annotatedvdb_tpu.store import memtable as memtable_mod

    real_write = VariantStore._write_segment
    fired = {"n": 0}

    def racing_write(path, stem, seg):
        rec = real_write(path, stem, seg)
        if fired["n"] == 0:
            fired["n"] = 1
            # a loader commits a new generation AFTER our temp is written,
            # BEFORE the flush's rename step re-checks the fingerprint
            loader = VariantStore.load(store_dir)
            loader.shard(3).append(
                {"pos": np.asarray([40], np.int32),
                 "h": np.asarray([99], np.uint32),
                 "ref_len": np.full(1, 1, np.int32),
                 "alt_len": np.full(1, 1, np.int32)},
                np.full((1, 8), 65, np.uint8),
                np.full((1, 8), 71, np.uint8),
            )
            loader.save(store_dir)
        return rec

    import unittest.mock as mock

    with mock.patch.object(VariantStore, "_write_segment",
                           staticmethod(racing_write)):
        result = mem.flush(base_manager=None)
    assert result["status"] == "aborted", result
    assert mem.rows == 1  # nothing acknowledged was lost
    assert not [f for f in os.listdir(store_dir) if ".flush.tmp" in f]
    # the retry flushes onto the loader's generation
    result = mem.flush(base_manager=None)
    assert result["status"] == "flushed"
    store = VariantStore.load(store_dir)
    assert store.shard(3).n == 5  # 3 loaded + 1 loader row + 1 upsert


def _spawn_upsert_server(store_dir, env_extra=None, timeout=60):
    """One real `serve --upserts` worker process on an ephemeral port;
    returns (proc, host, port) once the address line printed."""
    import re

    env = dict(os.environ, JAX_PLATFORMS="cpu",
               AVDB_MEMTABLE_FLUSH_S="0", AVDB_MEMTABLE_BYTES="0")
    env.pop("AVDB_FAULT", None)
    env.update(env_extra or {})
    proc = subprocess.Popen(
        [sys.executable, "-m", "annotatedvdb_tpu", "serve",
         "--storeDir", store_dir, "--port", "0", "--upserts"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True,
    )
    lines = []
    for _ in range(50):  # replay/log lines may precede the address line
        line = proc.stdout.readline()
        if not line:
            break
        lines.append(line)
        m = re.search(r"http://([\d.]+):(\d+)", line)
        if m:
            return proc, m.group(1), int(m.group(2))
    raise AssertionError(f"no serve address line: {lines!r}")


def _post_upsert(host, port, vid, timeout=10):
    import urllib.request

    body = json.dumps({"variants": [{"id": vid}]}).encode()
    req = urllib.request.Request(
        f"http://{host}:{port}/variants/upsert", data=body, method="POST"
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, r.read()


def _get_variant(host, port, vid, timeout=10):
    import urllib.error
    import urllib.request

    try:
        with urllib.request.urlopen(
            f"http://{host}:{port}/variant/{vid}", timeout=timeout
        ) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as err:
        return err.code, err.read()


def test_upsert_sigkill_unacked_never_appears_acked_survives(tmp_path):
    """The ack contract through the REAL serve CLI:

    1. a worker armed ``wal.append:1:torn_write`` dies mid-frame on the
       first upsert — the client never got a 200, and after a clean
       respawn the row is ABSENT (the torn tail was dropped);
    2. the respawned worker ACKs the same upsert (200) and is then
       SIGKILLed outright — after another respawn the acknowledged row
       is PRESENT, byte-identical, served from the replayed WAL."""
    import urllib.error

    store_dir = str(tmp_path / "sstore")
    _tiny_store().save(store_dir)

    # -- stage 1: death mid-WAL-append => un-acked, absent ---------------
    proc, host, port = _spawn_upsert_server(
        store_dir, env_extra={"AVDB_FAULT": "wal.append:1:torn_write"}
    )
    try:
        with pytest.raises((urllib.error.URLError, ConnectionError,
                            TimeoutError)):
            _post_upsert(host, port, "3:15:A:G")
    finally:
        rc = proc.wait(timeout=60)
    assert rc == -signal.SIGKILL, f"expected SIGKILL death, rc={rc}"

    proc, host, port = _spawn_upsert_server(store_dir)
    try:
        status, _body = _get_variant(host, port, "3:15:A:G")
        assert status == 404, "un-acked upsert must not appear"

        # -- stage 2: acked upsert survives a SIGKILL --------------------
        status, body = _post_upsert(host, port, "3:15:A:G")
        assert status == 200 and b'"accepted":1' in body
        status, want = _get_variant(host, port, "3:15:A:G")
        assert status == 200
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)

    proc, host, port = _spawn_upsert_server(store_dir)
    try:
        status, got = _get_variant(host, port, "3:15:A:G")
        assert status == 200 and got == want, \
            "acknowledged upsert lost or changed across SIGKILL"
    finally:
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=60) == 0


def test_memtable_flush_sigkill_through_cli_recovers_to_post(tmp_path):
    """memtable.flush:2:kill through the REAL serve CLI: the worker acks
    an upsert, its flush dies AT THE MANIFEST COMMIT POINT (segments
    renamed, manifest not swapped) — the durable store is byte-identical
    pre-state with fsck-attributable debris, the acknowledged row
    survives in the WAL, and a clean respawn replays it, flushes it, and
    converges on the post state."""
    import shutil as _shutil
    import time

    store_dir = str(tmp_path / "fstore")
    _tiny_store().save(store_dir)

    pre_manifest = json.load(open(os.path.join(store_dir,
                                               "manifest.json")))

    # stage 1: ack a row with flush triggers off, drain cleanly (the
    # WAL keeps the row: the memtable never flushed)
    proc, host, port = _spawn_upsert_server(store_dir)
    try:
        status, body = _post_upsert(host, port, "3:15:A:G")
        assert status == 200 and b'"accepted":1' in body
        status, want = _get_variant(host, port, "3:15:A:G")
        assert status == 200
    finally:
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=60) == 0

    # stage 2: respawn with the commit-point kill armed and a 1-byte
    # bound — replay crosses the bound, the maintenance tick fires the
    # flush, and the armed kill lands at the manifest commit (no request
    # in flight: the ack already happened, a restart ago)
    proc, host, port = _spawn_upsert_server(
        store_dir,
        env_extra={"AVDB_FAULT": "memtable.flush:2:kill",
                   "AVDB_MEMTABLE_BYTES": "1"},
    )
    try:
        rc = proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
    assert rc == -signal.SIGKILL, f"expected flush kill, rc={rc}"

    # pre-state: the manifest never swapped (same shard groups), the
    # renamed segments are orphan debris, the WAL survives
    now_manifest = json.load(open(os.path.join(store_dir,
                                               "manifest.json")))
    assert now_manifest["shards"] == pre_manifest["shards"]
    assert any(f.endswith(".wal") for f in os.listdir(store_dir))
    from annotatedvdb_tpu.store.fsck import fsck as run_fsck

    # repair on a COPY first: pruning must yield a clean pre-state store
    audit = str(tmp_path / "audit")
    _shutil.copytree(store_dir, audit)
    report = run_fsck(audit, repair=True, log=lambda m: None)
    assert report["exit_code"] in (0, 1), report

    # a clean respawn replays the acked row and completes the flush
    proc, host, port = _spawn_upsert_server(
        store_dir, env_extra={"AVDB_MEMTABLE_BYTES": "1"}
    )
    try:
        status, got = _get_variant(host, port, "3:15:A:G")
        assert status == 200 and got == want
        deadline = time.time() + 60
        flushed = False
        while time.time() < deadline:
            rows = json.load(open(os.path.join(
                store_dir, "manifest.json"
            ))).get("stats", {}).get("rows", {})
            if int(rows.get("3", 0)) >= 4:
                flushed = True
                break
            time.sleep(0.25)
        assert flushed, "respawned worker never completed the flush"
        status, got = _get_variant(host, port, "3:15:A:G")
        assert status == 200 and got == want
    finally:
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=60) == 0
    store = VariantStore.load(store_dir)
    assert store.shard(3).n == 4
    # the dead flush's stale .manifest.tmp is the one prescribed repair
    # (the per-kill-point table); after it the store deep-fscks clean
    report = run_fsck(store_dir, repair=True, log=lambda m: None)
    assert report["exit_code"] in (0, 1), report
    assert run_fsck(store_dir, deep=True,
                    log=lambda m: None)["exit_code"] == 0
    assert VariantStore.load(store_dir).shard(3).n == 4


# ---------------------------------------------------------------------------
# maintain.tick / maintain.disk_guard — the autonomy layer's fault points
# (store/maintenance.py).  Contract: a dying daemon tick is absorbed
# (logged + backed off) and never propagates to the hosting fleet
# supervisor; an injected low-disk reading flips upserts to 507 on both
# front ends (through the ONE shared upsert_execute gate) and clears
# cleanly on the next reading.


@pytest.mark.parametrize("fault", [
    "maintain.tick:1:raise",
    "maintain.tick:1:eio",
])
def test_maintain_tick_fault_absorbed_next_tick_compacts(tmp_path, fault):
    """A dying tick must never kill the daemon (and therefore never the
    supervisor or the fleet hosting it): the fault is logged, the daemon
    backs off, and the NEXT tick runs the watermark evaluation normally
    — the fragmented store still gets compacted."""
    from annotatedvdb_tpu.store.maintenance import MaintenanceDaemon

    store_dir = str(tmp_path / "mstore")
    _fragmented_store(store_dir)
    pre = _store_signature(store_dir)
    logs: list = []
    daemon = MaintenanceDaemon(
        store_dir, high=4, low=2, tick_s=0.05, cooldown_s=0.0,
        log=logs.append,
    )
    faults.reset(fault)
    assert daemon.tick() == "error"  # absorbed, not raised
    assert any("tick failed" in m for m in logs), logs
    # nth=1 consumed: the next tick trips the watermark and compacts
    assert daemon.tick() == "pass"
    assert max(daemon.read_amp().values()) == 1
    assert _store_signature(store_dir) == pre
    assert daemon.stats()["disabled"] is False


def test_maintain_tick_fault_daemon_thread_survives(tmp_path):
    """Same point through the REAL daemon thread (what the supervisor
    hosts): with the fault armed the thread keeps ticking — it neither
    dies nor wedges, which is exactly what keeps the fleet alive."""
    from annotatedvdb_tpu.store.maintenance import MaintenanceDaemon

    store_dir = str(tmp_path / "mstore2")
    _fragmented_store(store_dir)
    daemon = MaintenanceDaemon(
        store_dir, high=4, low=2, tick_s=0.05, cooldown_s=0.0,
        log=lambda m: None,
    )
    faults.reset("maintain.tick:1:raise")
    daemon.start()
    try:
        deadline = time.time() + 20
        while time.time() < deadline:
            if daemon.stats()["passes"] >= 1:
                break
            time.sleep(0.05)
        stats = daemon.stats()
        assert daemon._thread.is_alive()
        assert stats["passes"] >= 1, stats
        assert stats["ticks"] >= 2, stats
    finally:
        daemon.stop()


def test_maintain_disk_guard_fault_flips_507_both_front_ends_and_clears(
        tmp_path):
    """maintain.disk_guard (raise/eio): an injected free-space reading
    failure IS a low-disk observation — the guard reports breached, the
    shared upsert gate answers 507 with the single-source body on BOTH
    front ends, nothing becomes durable, and the next (clean) reading
    clears the degradation."""
    from annotatedvdb_tpu.obs.metrics import MetricsRegistry
    from annotatedvdb_tpu.serve.http import (
        MSG_DISK_RESERVE,
        build_server,
    )
    from annotatedvdb_tpu.serve.snapshot import (
        MemtableSnapshots,
        SnapshotManager,
    )
    from annotatedvdb_tpu.store.maintenance import DiskReserveGuard
    from annotatedvdb_tpu.store.memtable import Memtable
    from annotatedvdb_tpu.store.wal import WriteAheadLog

    store_dir = str(tmp_path / "dstore")
    _fragmented_store(store_dir)

    # guard level: injected failure = breached; clean reading = clear
    guard = DiskReserveGuard(store_dir, reserve=1, ttl_s=0.0,
                             log=lambda m: None)
    faults.reset("maintain.disk_guard:1:eio")
    breached, free = guard.state(force=True)
    assert breached is True and free == -1
    breached, free = guard.state(force=True)  # nth=1 consumed: clean now
    assert breached is False and free > 0
    faults.reset("")

    # route level: the ONE shared gate (ServeContext.upsert_execute)
    # renders the 507 for both front ends, so asserting it per-context
    # IS the parity proof at the decision layer (the HTTP-level parity
    # battery lives in tests/test_maintenance.py)
    registry = MetricsRegistry()
    mgr = SnapshotManager(store_dir, log=lambda m: None)
    mem = Memtable(
        width=8, store_dir=store_dir,
        wal=WriteAheadLog(store_dir, "serve-dg", log=lambda m: None),
        registry=registry, log=lambda m: None,
    )
    httpd = build_server(manager=MemtableSnapshots(mgr, mem), port=0,
                        memtable=mem, registry=registry)
    ctx = httpd.ctx
    try:
        ctx.disk_guard = DiskReserveGuard(store_dir, reserve=1,
                                          ttl_s=0.0, log=lambda m: None)
        body = json.dumps(
            {"variants": [{"id": "6:999999:A:G"}]}
        ).encode()
        faults.reset("maintain.disk_guard:1:raise")
        status, text, _rows = ctx.upsert_execute(body)
        assert status == 507
        assert json.loads(text)["error"] == MSG_DISK_RESERVE
        assert mem.rows == 0  # nothing durable, nothing visible
        # the degraded window clears on the next clean reading: the
        # SAME request now acks durably
        status, text, _rows = ctx.upsert_execute(body)
        assert status == 200
        assert json.loads(text)["accepted"] == 1
        assert mem.rows == 1
    finally:
        faults.reset("")
        httpd.server_close()
        ctx.batcher.close()
        mem.wal.close(remove_if_empty=True)


# ---------------------------------------------------------------------------
# mesh.dispatch — a device failure inside the sharded mesh gather
# (serve/mesh_exec).  The contract: the mesh breaker group absorbs it on
# the byte-identical single-device path (never wrong bytes), repeated
# failures trip the group open so the sharded attempt stops being paid,
# and a half-open probe re-closes it once the device heals.


def test_mesh_dispatch_raise_bulk_falls_back_byte_identical():
    """mesh.dispatch (raise) during a bulk lookup: the answer bytes are
    the single-device path's, the breaker's mesh group trips after the
    threshold (no further sharded attempt fires while open), and the
    cooled-down half-open probe re-closes it."""
    from annotatedvdb_tpu.parallel.mesh import global_mesh
    from annotatedvdb_tpu.serve import (
        DeviceBreaker,
        MeshExecutor,
        QueryEngine,
        StaticSnapshots,
    )
    from annotatedvdb_tpu.serve.mesh_exec import MESH_GROUP

    mesh = global_mesh()
    assert mesh is not None  # conftest forces the 8-device host platform
    snaps = StaticSnapshots(_tiny_store())
    plain = QueryEngine(snaps, region_cache_size=0)
    clock = {"t": 0.0}
    breaker = DeviceBreaker(cooldown_s=5.0, clock=lambda: clock["t"])
    engine = QueryEngine(
        snaps, region_cache_size=0, breaker=breaker,
        mesh=MeshExecutor(mesh, breaker=breaker, bulk_min=0),
    )
    ids = ["3:10:A:C", "3:20:A:C", "3:30:A:C", "3:99:A:C"]
    want = plain.lookup_many(ids)
    assert engine.lookup_many(ids) == want  # mesh path agrees unarmed
    faults.reset("mesh.dispatch:prob:1.0:raise")
    try:
        for _ in range(breaker.failure_threshold):
            # every failing dispatch still answers, byte-identical
            # (single-device fallback)
            assert engine.lookup_many(ids) == want
        assert breaker.state(MESH_GROUP) == "open"
        # while tripped the sharded call is never attempted: the armed
        # fault cannot fire
        fired_before = faults.fired().get("mesh.dispatch", 0)
        assert engine.lookup_many(ids) == want
        assert faults.fired().get("mesh.dispatch", 0) == fired_before
    finally:
        faults.reset("")
    # cooldown lapses, fault cleared: the half-open probe re-closes
    clock["t"] = 10.0
    assert engine.lookup_many(ids) == want
    assert breaker.state(MESH_GROUP) == "closed"


def test_mesh_dispatch_eio_panel_falls_back_byte_identical():
    """mesh.dispatch (eio) during a region panel: the batch answers
    byte-identically through the single-device spans path, and the
    engine keeps serving mesh panels once the fault clears."""
    from annotatedvdb_tpu.parallel.mesh import global_mesh
    from annotatedvdb_tpu.serve import (
        DeviceBreaker,
        MeshExecutor,
        QueryEngine,
        StaticSnapshots,
    )

    mesh = global_mesh()
    assert mesh is not None
    snaps = StaticSnapshots(_tiny_store())
    plain = QueryEngine(snaps, region_cache_size=0)
    breaker = DeviceBreaker(cooldown_s=0.0)
    engine = QueryEngine(
        snaps, region_cache_size=0, breaker=breaker,
        mesh=MeshExecutor(mesh, breaker=breaker, bulk_min=0),
    )
    specs = ["3:1-100", "3:5-25", "7:1-50"]
    want = plain.regions_serve(specs).assemble()
    assert engine.regions_serve(specs).assemble() == want
    faults.reset("mesh.dispatch:1:eio")
    try:
        assert engine.regions_serve(specs).assemble() == want
    finally:
        faults.reset("")
    # unarmed: the mesh panel path serves again, same bytes
    assert engine.regions_serve(specs).assemble() == want


# ---------------------------------------------------------------------------
# obs.flight — the crash flight recorder (obs/flight.py).  Contract:
# observability must NEVER take down serving — an injected failure inside
# a ring write costs exactly that record, a failure inside the
# supervisor's harvest costs exactly that harvest, and a REAL SIGKILL
# through the serve CLI leaves a harvested black box holding the killed
# worker's final requests.


def test_obs_flight_ring_write_failure_absorbed_while_serving(tmp_path):
    """obs.flight (raise) inside a request-summary write: the request
    still answers 200, the failure is counted, recording continues."""
    import threading
    import urllib.request

    from annotatedvdb_tpu.obs.flight import FlightRecorder, decode_ring
    from annotatedvdb_tpu.serve.http import build_server

    store_dir = str(tmp_path / "fstore")
    _tiny_store().save(store_dir)
    ring = str(tmp_path / "w0.ring")
    flight = FlightRecorder(ring, slots=16, log=lambda m: None)
    httpd = build_server(store_dir=store_dir, port=0, flight=flight)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        port = httpd.server_address[1]

        def get(path):
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10
            ) as r:
                return r.status

        faults.reset("obs.flight:1:raise")
        assert get("/variant/3:10:A:C") == 200  # the write failure is silent
        faults.reset("")
        assert get("/variant/3:20:A:C") == 200
        assert flight.errors == 1
        flight.flush()
        reqs = [e for e in decode_ring(ring)["events"]
                if e["type"] == "request"]
        # exactly the injected record is missing; recording resumed
        assert len(reqs) == 1
    finally:
        faults.reset("")
        httpd.shutdown()
        httpd.server_close()
        httpd.ctx.batcher.close()
        flight.close()


def test_obs_flight_harvest_failure_absorbed_by_supervisor(tmp_path):
    """obs.flight (eio) inside the supervisor's harvest: the fleet's
    absorb wrapper logs and continues — a broken black box must never
    stall the respawn loop."""
    from annotatedvdb_tpu.obs import flight as flight_mod
    from annotatedvdb_tpu.serve.fleet import ServeFleet

    store_dir = str(tmp_path / "hstore")
    _tiny_store().save(store_dir)
    ring = flight_mod.ring_path(store_dir, 0)
    fr = flight_mod.FlightRecorder(ring, slots=8)
    fr.request("abc", "point", 200, 0.001, [])
    fr.close()
    fleet = ServeFleet(store_dir, port=0, workers=1, log=lambda m: None)
    try:
        faults.reset("obs.flight:1:eio")
        fleet._harvest_flight(0, "died rc=-9")  # absorbed, never raises
        faults.reset("")
        assert flight_mod.list_blackboxes(store_dir)["harvested"] == []
        # unarmed: the same harvest lands
        fleet._harvest_flight(0, "died rc=-9")
        assert len(
            flight_mod.list_blackboxes(store_dir)["harvested"]
        ) == 1
    finally:
        faults.reset("")
        fleet._reserve.close()
        if fleet._sup_flight is not None:
            fleet._sup_flight.close()
        import shutil

        from annotatedvdb_tpu.obs import reqtrace as _rt

        _rt.set_background_sink(None, None)
        shutil.rmtree(fleet._telemetry_dir, ignore_errors=True)
        fleet._hb_mm.close()
        os.unlink(fleet._hb_path)


def test_obs_flight_sigkill_harvest_holds_final_requests(tmp_path):
    """A REAL worker SIGKILL through the serve CLI: requests land on the
    worker's mmap'd ring, the chaos route kills it mid-accept, and the
    supervisor's harvest under <store>/flight/ holds the killed worker's
    final request summaries — the black-box acceptance contract."""
    import re
    import subprocess
    import urllib.request

    from annotatedvdb_tpu.obs import flight as flight_mod

    store_dir = str(tmp_path / "kstore")
    _tiny_store().save(store_dir)
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        AVDB_SERVE_CHAOS="1",
    )
    env.pop("AVDB_FAULT", None)
    proc = subprocess.Popen(
        [sys.executable, "-m", "annotatedvdb_tpu", "serve",
         "--storeDir", store_dir, "--port", "0", "--workers", "2"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True,
    )
    try:
        line = proc.stdout.readline()
        m = re.search(r"http://([\d.]+):(\d+)", line)
        assert m, f"no fleet address line: {line!r}"
        host, port = m.group(1), int(m.group(2))

        def get(path, timeout=5):
            with urllib.request.urlopen(
                f"http://{host}:{port}{path}", timeout=timeout
            ) as r:
                return r.status, r.read()

        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            try:
                if get("/healthz")[0] == 200:
                    break
            except OSError:
                time.sleep(0.3)
        # traffic both workers record (kernel round-robins accepts)
        for i in range(40):
            try:
                get(f"/variant/3:{(i % 3 + 1) * 10}:A:C")
            except OSError:
                pass
        # arm a kill in whichever worker answers: it dies mid-accept
        body = json.dumps({"spec": "serve.accept:1:kill"}).encode()
        req = urllib.request.Request(
            f"http://{host}:{port}/_chaos", data=body, method="POST",
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=5):
            pass
        # trip it + wait for the supervisor to harvest and respawn
        for _ in range(10):
            try:
                get("/variant/3:10:A:C", timeout=2)
            except OSError:
                pass
            time.sleep(0.2)
        harvested = []
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            harvested = flight_mod.list_blackboxes(store_dir)["harvested"]
            if harvested:
                break
            time.sleep(0.5)
        assert harvested, "the supervisor never harvested the killed " \
                          "worker's flight ring"
        data = flight_mod.load_harvest(harvested[0])
        assert "died rc=-9" in data["meta"]["reason"]
        reqs = [e for e in data["events"] if e["type"] == "request"]
        assert reqs, "the harvested black box holds no request summaries"
        assert any(e["kind"] == "point" and e["status"] == 200
                   and e.get("stages") for e in reqs)
        # the fleet telemetry plane on the REAL fleet: any worker's
        # ?fleet=1 answers for the whole fleet, incl. the supervisor's
        # respawn counter the kill just incremented (workers publish
        # snapshots ~1 Hz; give the plane a moment to converge)
        deadline = time.monotonic() + 30
        fleet_ok = False
        while time.monotonic() < deadline and not fleet_ok:
            try:
                _s, body = get("/metrics?fleet=1")
                text = body.decode()
                fleet_ok = ("avdb_fleet_workers_live 2" in text
                            and "avdb_fleet_respawns_total 1" in text)
            except OSError:
                pass
            if not fleet_ok:
                time.sleep(0.5)
        assert fleet_ok, "?fleet=1 never showed 2 live workers and the " \
                         "respawn the kill caused"
    finally:
        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=60)
    assert rc == 0, proc.stdout.read()[-2000:]


# ---------------------------------------------------------------------------
# obs.tick — the health plane's fault point (obs/timeseries.py,
# obs/slo.py).  Same contract as obs.flight: observability must NEVER
# take down serving — a failing snapshot costs one tick, a failing
# mirror write costs one persist (the previous file survives tmp+rename),
# a failing supervisor harvest costs exactly that harvest.


@pytest.mark.parametrize("fault", [
    "obs.tick:1:raise",
    "obs.tick:1:eio",
])
def test_obs_tick_sample_fault_absorbed_ring_continues(tmp_path, fault):
    """An injected failure inside the snapshot costs one tick: absorbed,
    logged once, counted — and the NEXT tick samples normally."""
    from annotatedvdb_tpu.obs.metrics import MetricsRegistry
    from annotatedvdb_tpu.obs.timeseries import TimeSeriesRing

    logs: list = []
    ring = TimeSeriesRing(
        MetricsRegistry(), worker=0,
        path=str(tmp_path / "w0.ts.json"),
        tick_s=0.01, history_s=60.0, log=logs.append,
    )
    faults.reset(fault)
    try:
        assert ring.tick() is False  # absorbed, not raised
        assert ring.errors == 1
        assert any("tick failed" in m for m in logs), logs
        assert ring.samples() == []
        # nth=1 consumed: the next tick runs normally
        assert ring.tick() is True
        assert len(ring.samples()) == 1
    finally:
        faults.reset("")


def test_obs_tick_persist_fault_keeps_previous_mirror(tmp_path):
    """A failing mirror write costs one persist: the sample still lands
    in the ring and the previously persisted file stays readable (the
    write is tmp+rename)."""
    from annotatedvdb_tpu.obs.metrics import MetricsRegistry
    from annotatedvdb_tpu.obs.timeseries import (
        TimeSeriesRing,
        load_history,
    )

    ring = TimeSeriesRing(
        MetricsRegistry(), worker=0,
        path=str(tmp_path / "w0.ts.json"),
        tick_s=0.01, history_s=60.0, log=lambda m: None,
    )
    ring.sample()
    ring.persist(force=True)
    assert len(load_history(ring.path)["samples"]) == 1
    # fire #1 passes the sample, fire #2 dies inside the persist
    # (re-open the PERSIST_S gate so the tick actually attempts it)
    ring._last_persist = -1e9
    faults.reset("obs.tick:2:eio")
    try:
        assert ring.tick() is False
        assert ring.errors == 1
        assert len(ring.samples()) == 2  # the sample half landed
        # the previous mirror is intact — no torn document
        assert len(load_history(ring.path)["samples"]) == 1
    finally:
        faults.reset("")
    ring.persist(force=True)  # unarmed: the mirror catches up
    assert len(load_history(ring.path)["samples"]) == 2


def test_obs_tick_fault_while_serving_requests_still_answer(tmp_path):
    """obs.tick (raise) under the threaded front end's inline driver:
    the request that carried the dying tick still answers 200, the
    failure is counted, and the next due tick samples normally."""
    import threading
    import urllib.request

    from annotatedvdb_tpu.obs.metrics import MetricsRegistry
    from annotatedvdb_tpu.obs.slo import HealthPlane
    from annotatedvdb_tpu.serve.http import build_server

    store_dir = str(tmp_path / "hstore")
    _tiny_store().save(store_dir)
    registry = MetricsRegistry()
    health = HealthPlane(registry, store_dir=store_dir, worker=0,
                         tick_s=0.01, history_s=60.0)
    httpd = build_server(store_dir=store_dir, port=0, registry=registry,
                        health=health)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        port = httpd.server_address[1]

        def get(path):
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10
            ) as r:
                return r.status

        faults.reset("obs.tick:1:raise")
        assert get("/variant/3:10:A:C") == 200  # the tick died silently
        faults.reset("")
        assert health.errors == 1
        time.sleep(0.02)  # past the tick gate
        assert get("/variant/3:20:A:C") == 200
        assert len(health.ring.samples()) >= 1  # recording resumed
    finally:
        faults.reset("")
        httpd.shutdown()
        httpd.server_close()
        httpd.ctx.batcher.close()


def test_obs_tick_harvest_failure_absorbed_by_supervisor(tmp_path):
    """obs.tick (eio) inside the supervisor's history harvest: the
    fleet's absorb wrapper logs and continues — a broken history file
    must never stall the respawn loop."""
    from annotatedvdb_tpu.obs.metrics import MetricsRegistry
    from annotatedvdb_tpu.obs.timeseries import (
        TimeSeriesRing,
        history_path,
        list_history,
    )
    from annotatedvdb_tpu.serve.fleet import ServeFleet

    store_dir = str(tmp_path / "hstore2")
    _tiny_store().save(store_dir)
    ring = TimeSeriesRing(
        MetricsRegistry(), worker=0, path=history_path(store_dir, 0),
        tick_s=1.0, history_s=60.0,
    )
    ring.sample()
    ring.persist(force=True)
    fleet = ServeFleet(store_dir, port=0, workers=1, log=lambda m: None)
    try:
        faults.reset("obs.tick:1:eio")
        fleet._harvest_history(0, "died rc=-9")  # absorbed, never raises
        faults.reset("")
        assert list_history(store_dir)["harvested"] == []
        # unarmed: the same harvest lands, reason stamped in
        fleet._harvest_history(0, "died rc=-9")
        assert len(list_history(store_dir)["harvested"]) == 1
    finally:
        faults.reset("")
        fleet._reserve.close()
        if fleet._sup_flight is not None:
            fleet._sup_flight.close()
        import shutil

        from annotatedvdb_tpu.obs import reqtrace as _rt

        _rt.set_background_sink(None, None)
        shutil.rmtree(fleet._telemetry_dir, ignore_errors=True)
        fleet._hb_mm.close()
        os.unlink(fleet._hb_path)


# -- replication kill points (store/replication.py) ---------------------------


def _repl_leader(tmp_path, rows):
    """One in-process leader (store + memtable + WAL + threaded front
    end) with ``rows`` upserted — the replication matrix's write source."""
    import threading

    from annotatedvdb_tpu.obs.metrics import MetricsRegistry
    from annotatedvdb_tpu.serve.http import build_server
    from annotatedvdb_tpu.serve.snapshot import (
        MemtableSnapshots,
        SnapshotManager,
    )
    from annotatedvdb_tpu.store.memtable import Memtable
    from annotatedvdb_tpu.store.wal import WriteAheadLog

    store_dir = str(tmp_path / "repl-leader")
    _tiny_store().save(store_dir)
    mem = Memtable(
        width=8, store_dir=store_dir,
        wal=WriteAheadLog(store_dir, "serve-w0", log=lambda m: None),
        log=lambda m: None,
    )
    store = VariantStore.load(store_dir, readonly=True)
    for row in rows:
        mem.upsert(store, [row], durable=True)
    httpd = build_server(
        manager=MemtableSnapshots(
            SnapshotManager(store_dir, log=lambda m: None), mem
        ),
        port=0, memtable=mem,
    )
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{httpd.server_address[1]}"
    return store_dir, url, httpd


_REPL_ROWS = [
    {"code": 3, "pos": 15, "ref": "A", "alt": "G"},
    {"code": 3, "pos": 25, "ref": "AT", "alt": "A"},
]


@pytest.mark.parametrize("fault", ["repl.ship:1:raise", "repl.ship:1:eio"])
def test_repl_ship_fault_cycle_retries_to_identical_state(tmp_path, fault):
    """repl.ship fires on the leader's ship surface: the poisoned cycle
    fails whole (ReplError — nothing half-applied), and the NEXT cycle
    lands the follower on the leader's exact applied-LSN state."""
    from annotatedvdb_tpu.store import replication as repl

    store_dir, url, httpd = _repl_leader(tmp_path, _REPL_ROWS)
    fdir = str(tmp_path / "repl-follower")
    applied: list = []
    tailer = repl.ReplicaTailer(fdir, url, log=lambda m: None,
                                apply_rows=applied.extend)
    try:
        faults.reset(fault)
        with pytest.raises(repl.ReplError):
            tailer.sync_once()
        assert applied == []  # the failed cycle applied NOTHING
        faults.reset("")
        tailer.sync_once()
        assert [r["pos"] for r in applied] == [15, 25]
        # the mirror is byte-identical to the leader's stable stream
        for fname in repl.wal_files(store_dir):
            with open(os.path.join(store_dir, fname), "rb") as f:
                leader_bytes = f.read()
            with open(os.path.join(fdir, fname), "rb") as f:
                assert f.read() == leader_bytes
    finally:
        faults.reset("")
        httpd.shutdown()
        httpd.ctx.batcher.close()


def test_repl_apply_fault_restart_lands_on_applied_lsn_prefix(tmp_path):
    """repl.apply dies AFTER the shipped bytes are durable on the
    follower but BEFORE the overlay applied them: a restarted tailer
    recovers the records from its own mirror and the live stream applies
    each acked row exactly once — a consistent applied-LSN prefix, never
    a hybrid."""
    from annotatedvdb_tpu.store import replication as repl

    store_dir, url, httpd = _repl_leader(tmp_path, _REPL_ROWS)
    fdir = str(tmp_path / "repl-follower")
    try:
        t1 = repl.ReplicaTailer(fdir, url, log=lambda m: None)
        t1.bootstrap()  # cut installed; WAL tail not mirrored yet
        applied: list = []
        t1.apply_rows = applied.extend
        faults.reset("repl.apply:1:raise")
        with pytest.raises(faults.InjectedFault):
            t1.sync_once()
        faults.reset("")
        assert applied == []  # durable locally, applied nowhere

        # restart: a fresh incarnation resumes from the mirror alone
        t2 = repl.ReplicaTailer(fdir, url, log=lambda m: None)
        recovered = t2.resume()
        replayed = [r["pos"] for rec in t2.local_records()
                    for r in rec["rows"]]
        live: list = []
        t2.apply_rows = live.extend
        t2.sync_once()
        total = replayed + [r["pos"] for r in live]
        # every acked row exactly once, in WAL order — no loss, no dupes
        assert sorted(total) == [15, 25]
        assert recovered + len(live) >= 1
    finally:
        faults.reset("")
        httpd.shutdown()
        httpd.ctx.batcher.close()


def test_repl_promote_fault_leaves_promotable_follower(tmp_path):
    """repl.promote (raise, hit #1 — before any mutation): the follower
    is byte-untouched and promotes cleanly on re-run; the deposed
    leader's flush is fenced afterwards."""
    from annotatedvdb_tpu.store import replication as repl
    from annotatedvdb_tpu.store.memtable import Memtable

    store_dir, url, httpd = _repl_leader(tmp_path, _REPL_ROWS)
    fdir = str(tmp_path / "repl-follower")
    try:
        tailer = repl.ReplicaTailer(fdir, url, log=lambda m: None)
        tailer.bootstrap()
        tailer.sync_once()
        before = sorted(os.listdir(fdir))

        faults.reset("repl.promote:1:raise")
        with pytest.raises(faults.InjectedFault):
            repl.promote(fdir, log=lambda m: None)
        faults.reset("")
        assert sorted(os.listdir(fdir)) == before  # byte-untouched
        with open(os.path.join(fdir, "manifest.json")) as f:
            assert json.load(f).get("repl_epoch", 0) == 0

        out = repl.promote(fdir, log=lambda m: None)
        assert out["status"] == "promoted" and out["epoch"] == 1
        promoted = VariantStore.load(fdir, readonly=True)
        assert promoted.n == 5  # 3 seed + 2 tailed rows sealed

        # deposed-leader write fenced: a writer that opened the store
        # under the old epoch cannot commit a flush over the new lineage
        deposed = Memtable(width=8, store_dir=fdir, wal=None,
                           log=lambda m: None, fence_epoch=0)
        deposed.upsert(
            promoted, [{"code": 3, "pos": 99, "ref": "A", "alt": "G"}],
            durable=False,
        )
        result = deposed.flush()
        assert result["status"] == "aborted"
        assert "fenced" in result["reason"]
    finally:
        faults.reset("")
        httpd.shutdown()
        httpd.ctx.batcher.close()


# ---------------------------------------------------------------------------
# fsck.repair — the repair pass's own manifest commit is a crash point too


@pytest.mark.parametrize("fault", [
    "fsck.repair:1:raise",
    "fsck.repair:1:eio",
])
def test_fsck_repair_commit_fault_leaves_diagnosable_store(tmp_path, fault):
    """``fsck.repair`` fires while the rolled-back manifest is staged (tmp
    written, atomic replace not yet done): a death there must leave the
    damaged-but-diagnosed store byte-identical — the OLD manifest still
    serving — so the next repair run diagnoses the same damage and
    converges.  Repair is idempotent; its commit is one atomic replace."""
    vcf = str(tmp_path / "d.vcf")
    _write_vcf(vcf, n=300)
    store_dir = str(tmp_path / "store")
    counters, exc = _run_load(store_dir, vcf)
    assert exc is None, exc
    # tear one referenced segment: size mismatch vs its integrity record
    seg = next(f for f in sorted(os.listdir(store_dir))
               if f.endswith(".npz"))
    with open(os.path.join(store_dir, seg), "r+b") as f:
        f.truncate(16)
    mpath = os.path.join(store_dir, "manifest.json")
    with open(mpath, "rb") as f:
        manifest_before = f.read()

    faults.reset(fault)
    try:
        with pytest.raises((faults.InjectedFault, OSError)):
            fsck(store_dir, repair=True, log=lambda m: None)
    finally:
        faults.reset("")
    # the commit never happened: the old manifest is byte-identical and
    # the damage is still on disk for the next run to diagnose
    with open(mpath, "rb") as f:
        assert f.read() == manifest_before

    # unarmed re-run converges: the damaged group rolls back, debris is
    # pruned, and the store then deep-fscks clean
    report = fsck(store_dir, repair=True, log=lambda m: None)
    assert report["exit_code"] in (0, 1), report
    assert fsck(store_dir, deep=True,
                log=lambda m: None)["exit_code"] == 0


# ---------------------------------------------------------------------------
# export.plan / export.pack / export.commit — the training-corpus export
# subsystem's kill points (export/core.py + export/writer.py).  Contract:
# a death at ANY of them leaves the output directory a committed-part
# PREFIX of the reference corpus (possibly empty, possibly plus prunable
# ``*.export.tmp*`` debris — never a torn part), and ``--resume``
# completes to bytes IDENTICAL to the uninterrupted run.


def _corpus_bytes(out_dir):
    if not os.path.isdir(out_dir):
        return {}
    out = {}
    for fname in sorted(os.listdir(out_dir)):
        if fname.endswith(".npz") or fname == "corpus.manifest.json":
            with open(os.path.join(out_dir, fname), "rb") as f:
                out[fname] = f.read()
    return out


@pytest.fixture()
def export_refs(tmp_path):
    """(store, ledger, store_dir, reference corpus bytes): a tiny store
    whose whole-store export makes 2 one-batch parts — enough that every
    export kill point has a real committed prefix to land on."""
    from annotatedvdb_tpu.export.core import run_export

    store_dir = str(tmp_path / "estore")
    _tiny_store().save(store_dir)
    store, ledger = StoreConfig(store_dir).open(create=False,
                                                readonly=True)
    ref_dir = str(tmp_path / "eref")
    summary = run_export(store, ledger, store_dir, ref_dir, seed=5,
                         batch_rows=2, part_bytes=1)
    assert summary["parts_written"] == 2 and summary["complete"]
    return store, ledger, store_dir, _corpus_bytes(ref_dir)


@pytest.mark.parametrize("fault", [
    "export.plan:1:raise",
    "export.plan:1:eio",
])
def test_export_plan_fault_leaves_out_dir_untouched(export_refs, tmp_path,
                                                    fault):
    """export.plan fires after the plan exists in memory, before anything
    touches the output directory: a death there must leave NO output
    directory at all, and an unarmed re-run (no resume needed — nothing
    was committed) produces the reference corpus."""
    from annotatedvdb_tpu.export.core import run_export

    store, ledger, store_dir, want = export_refs
    out_dir = str(tmp_path / "out")
    faults.reset(fault)
    try:
        with pytest.raises((faults.InjectedFault, OSError)):
            run_export(store, ledger, store_dir, out_dir, seed=5,
                       batch_rows=2, part_bytes=1)
    finally:
        faults.reset("")
    assert not os.path.exists(out_dir)  # byte-untouched means ABSENT
    run_export(store, ledger, store_dir, out_dir, seed=5,
               batch_rows=2, part_bytes=1)
    assert _corpus_bytes(out_dir) == want


@pytest.mark.parametrize("fault", [
    "export.pack:2:raise",
    "export.pack:2:eio",
])
def test_export_pack_fault_lands_on_prefix_resume_completes(export_refs,
                                                            tmp_path,
                                                            fault):
    """export.pack fires per tokenized batch, before staging: nth=2 dies
    with part 0 already committed.  The durable state must be exactly the
    reference's part-0 prefix (no manifest — it commits last), and
    ``resume=True`` must complete to reference bytes without repacking
    the committed part."""
    from annotatedvdb_tpu.export.core import run_export

    store, ledger, store_dir, want = export_refs
    out_dir = str(tmp_path / "out")
    faults.reset(fault)
    try:
        with pytest.raises((faults.InjectedFault, OSError)):
            run_export(store, ledger, store_dir, out_dir, seed=5,
                       batch_rows=2, part_bytes=1)
    finally:
        faults.reset("")
    got = _corpus_bytes(out_dir)
    assert set(got) == {"part-000000.npz"}  # committed prefix, no manifest
    assert got["part-000000.npz"] == want["part-000000.npz"]
    summary = run_export(store, ledger, store_dir, out_dir, seed=5,
                         batch_rows=2, part_bytes=1, resume=True)
    assert summary["resumed_parts"] == 1 and summary["parts_written"] == 1
    assert _corpus_bytes(out_dir) == want


@pytest.mark.parametrize("fault,n_committed", [
    ("export.commit:1:raise", 0),   # dies staging part 0
    ("export.commit:2:raise", 1),   # dies staging part 1 (part 0 durable)
    ("export.commit:2:eio", 1),
    ("export.commit:3:raise", 2),   # dies on the manifest temp, parts done
])
def test_export_commit_fault_strands_only_debris_resume_identical(
        export_refs, tmp_path, fault, n_committed):
    """export.commit fires on every staged temp (each part's, then the
    manifest's) after the body is written, before its fsync/rename: a
    death there strands exactly one ``*.export.tmp*`` temp next to the
    committed prefix — never a torn part — and resume prunes the debris
    and completes to reference bytes."""
    from annotatedvdb_tpu.export.core import run_export
    from annotatedvdb_tpu.export.writer import is_export_tmp

    store, ledger, store_dir, want = export_refs
    out_dir = str(tmp_path / "out")
    faults.reset(fault)
    try:
        with pytest.raises((faults.InjectedFault, OSError)):
            run_export(store, ledger, store_dir, out_dir, seed=5,
                       batch_rows=2, part_bytes=1)
    finally:
        faults.reset("")
    debris = [f for f in os.listdir(out_dir) if is_export_tmp(f)]
    assert len(debris) == 1, debris
    got = _corpus_bytes(out_dir)
    assert set(got) == {f"part-{n:06d}.npz" for n in range(n_committed)}
    for fname, body in got.items():
        assert body == want[fname]
    summary = run_export(store, ledger, store_dir, out_dir, seed=5,
                         batch_rows=2, part_bytes=1, resume=True)
    assert summary["resumed_parts"] == n_committed
    assert _corpus_bytes(out_dir) == want
    assert [f for f in os.listdir(out_dir) if is_export_tmp(f)] == []
