"""Store, dedup-kernel, and ledger tests."""

import numpy as np
import pytest

from annotatedvdb_tpu.io.synth import synthetic_batch
from annotatedvdb_tpu.store import VariantStore, AlgorithmLedger
from annotatedvdb_tpu.types import VariantBatch

from conftest import random_variants


def hashes(batch):
    from annotatedvdb_tpu.ops.hashing import allele_hash_jit

    return np.asarray(allele_hash_jit(batch.ref, batch.alt, batch.ref_len, batch.alt_len))


def test_mark_batch_duplicates(rng):
    from annotatedvdb_tpu.ops.dedup import mark_batch_duplicates_jit

    variants = random_variants(rng, 100)
    # duplicate some rows explicitly (identity ignores chromosome: batch-level
    # dedup runs per chromosome shard)
    variants = variants + [variants[3], variants[7], variants[7]]
    batch = VariantBatch.from_tuples(variants, width=24)
    h = hashes(batch)
    dup = np.asarray(
        mark_batch_duplicates_jit(batch.pos, h, batch.ref, batch.alt, batch.ref_len, batch.alt_len)
    )
    # each injected copy flagged, originals kept
    assert dup[100] and dup[101] and dup[102]
    assert not dup[3] and not dup[7]
    # python-oracle dedup over identity tuples must agree
    seen, want = set(), []
    for chrom, pos, ref, alt in variants:
        key = (pos, ref, alt)
        want.append(key in seen)
        seen.add(key)
    # rows at identical (pos, ref, alt) across different chromosomes would
    # collide here; random_variants makes that vanishingly unlikely
    np.testing.assert_array_equal(dup, want)


def test_store_append_lookup_roundtrip(rng):
    variants = random_variants(rng, 200)
    batch = VariantBatch.from_tuples(variants, width=24)
    h = hashes(batch)
    store = VariantStore(width=24)
    # split rows by chromosome into shards
    for code in np.unique(batch.chrom):
        m = batch.chrom == code
        # dedup within shard first (store expects unique identities)
        key = (batch.pos[m].astype(np.uint64) << np.uint64(32)) | h[m]
        _, first = np.unique(key, return_index=True)
        sel = np.where(m)[0][np.sort(first)]
        store.shard(code).append(
            {"pos": batch.pos[sel], "h": h[sel],
             "ref_len": batch.ref_len[sel], "alt_len": batch.alt_len[sel],
             "row_algorithm_id": np.full(len(sel), 1)},
            batch.ref[sel], batch.alt[sel],
        )
    # every stored row must be found; identity fields must round-trip
    for code in np.unique(batch.chrom):
        m = batch.chrom == code
        found, idx = store.shard(code).lookup(
            batch.pos[m], h[m], batch.ref[m], batch.alt[m],
            batch.ref_len[m], batch.alt_len[m],
        )
        assert found.all()
        s = store.shard(code)
        np.testing.assert_array_equal(s.cols["pos"][idx], batch.pos[m])
    # absent rows must not be found
    other = VariantBatch.from_tuples([("1", 42, "A", "TTT")], width=24)
    oh = hashes(other)
    found, idx = store.shard(int(batch.chrom[0])).lookup(
        other.pos, oh, other.ref, other.alt, other.ref_len, other.alt_len
    )
    assert not found.any() and (idx == -1).all()


def test_device_lookup_matches_host(rng):
    """lookup_in_sorted kernel == host searchsorted membership."""
    from annotatedvdb_tpu.ops.dedup import lookup_in_sorted_jit

    batch = synthetic_batch(512, width=16, seed=3)
    h = hashes(batch)
    # store = even rows (sorted); queries = all rows
    key = (batch.pos.astype(np.uint64) << np.uint64(32)) | h
    order = np.argsort(key[::2], kind="stable") * 2
    s_pos, s_h = batch.pos[order], h[order]
    s_ref, s_alt = batch.ref[order], batch.alt[order]
    s_rl, s_al = batch.ref_len[order], batch.alt_len[order]
    found, idx = lookup_in_sorted_jit(
        s_pos, s_h, s_ref, s_alt, s_rl, s_al,
        batch.pos, h, batch.ref, batch.alt, batch.ref_len, batch.alt_len,
    )
    found = np.asarray(found)
    # every even row finds itself; odd rows almost surely absent
    assert found[::2].all()
    stored = {tuple(k) for k in np.stack([batch.pos[::2], h[::2]], 1)}
    want_odd = np.array([(p, hh) in stored for p, hh in zip(batch.pos[1::2], h[1::2])])
    np.testing.assert_array_equal(found[1::2], want_odd)


def test_update_merge_semantics():
    store = VariantStore(width=16)
    b = synthetic_batch(4, width=16, seed=5)
    h = hashes(b)
    s = store.shard(1)
    order = np.argsort((b.pos.astype(np.uint64) << np.uint64(32)) | h)
    s.append(
        {"pos": b.pos[order], "h": h[order], "ref_len": b.ref_len[order],
         "alt_len": b.alt_len[order]},
        b.ref[order], b.alt[order],
        annotations={"allele_frequencies": [{"gnomad": {"af": 0.1}}, None, None, None]},
    )
    # jsonb_merge deep-merge: new source merges in, existing keys survive
    n_up = s.update_annotation(
        np.array([0, 1]), "allele_frequencies",
        [{"gnomad": {"af_afr": 0.2}}, {"topmed": {"af": 0.5}}],
    )
    assert n_up == 2
    assert s.annotations["allele_frequencies"][0] == {
        "gnomad": {"af": 0.1, "af_afr": 0.2}
    }
    assert s.annotations["allele_frequencies"][1] == {"topmed": {"af": 0.5}}
    # index -1 (not found) rows are skipped
    assert s.update_annotation(np.array([-1]), "cadd_scores", [{"x": 1}]) == 0


def test_undo_and_persistence(tmp_path, rng):
    store = VariantStore(width=24)
    batch = VariantBatch.from_tuples(random_variants(rng, 50), width=24)
    h = hashes(batch)
    for code in np.unique(batch.chrom):
        m = np.where(batch.chrom == code)[0]
        key = (batch.pos[m].astype(np.uint64) << np.uint64(32)) | h[m]
        m = m[np.argsort(key)]
        store.shard(code).append(
            {"pos": batch.pos[m], "h": h[m], "ref_len": batch.ref_len[m],
             "alt_len": batch.alt_len[m],
             "row_algorithm_id": np.full(len(m), 7)},
            batch.ref[m], batch.alt[m],
        )
    assert store.n == 50
    # persistence round-trip
    store.save(str(tmp_path / "vdb"))
    loaded = VariantStore.load(str(tmp_path / "vdb"))
    assert loaded.n == 50
    code = int(batch.chrom[0])
    np.testing.assert_array_equal(
        loaded.shard(code).cols["pos"], store.shard(code).cols["pos"]
    )
    # undo drops everything stamped with alg 7
    assert loaded.delete_by_algorithm(7) == 50
    assert loaded.n == 0
    assert loaded.delete_by_algorithm(7) == 0


def test_ledger(tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    ledger = AlgorithmLedger(path)
    a1 = ledger.begin("load_vcf", {"file": "x.vcf"}, commit=True)
    a2 = ledger.begin("load_vep", {"file": "y.json"}, commit=False)
    assert (a1, a2) == (1, 2)
    ledger.checkpoint(a1, "x.vcf", 500, {"variant": 480})
    ledger.checkpoint(a1, "x.vcf", 1000, {"variant": 970})
    # mid-load (crash recovery window): checkpoints drive resume
    assert ledger.last_checkpoint("x.vcf") == 1000
    ledger.finish(a1, {"variant": 970})
    # finished loads don't resume: re-submitting the file is a new load
    assert ledger.last_checkpoint("x.vcf") == 0
    assert ledger.last_checkpoint("unseen.vcf") == 0
    # reload from disk: serial ids continue, unfinished checkpoints survive
    ledger2 = AlgorithmLedger(path)
    assert ledger2.begin("load_cadd", {}, True) == 3
    a4 = ledger2.begin("load_vcf", {"file": "x.vcf"}, commit=True)
    ledger2.checkpoint(a4, "x.vcf", 200, {})
    assert AlgorithmLedger(path).last_checkpoint("x.vcf") == 200


def test_ledger_crashed_invocation_superseded_by_later_finish(tmp_path):
    """A checkpoint left by a crashed load must not resurrect as a resume
    point after a later invocation completes the same file."""
    ledger = AlgorithmLedger(str(tmp_path / "ledger.jsonl"))
    a1 = ledger.begin("load_qc", {"file": "f.vcf"}, commit=True)
    ledger.checkpoint(a1, "f.vcf", 1000, {})  # crash: a1 never finishes
    a2 = ledger.begin("load_qc", {"file": "f.vcf"}, commit=True)
    assert ledger.last_checkpoint("f.vcf") == 1000  # a2 resumes from a1
    ledger.checkpoint(a2, "f.vcf", 5000, {})
    ledger.finish(a2, {})
    # file fully loaded: a fresh submission starts at line 0, not 1000
    assert ledger.last_checkpoint("f.vcf") == 0


def test_ledger_resume_run_with_no_checkpoints_still_supersedes(tmp_path):
    """If the crash happened after the final chunk's checkpoint, the resume
    run replays everything as no-ops and writes no checkpoints of its own —
    its finish must still clear the crashed cursor."""
    ledger = AlgorithmLedger(str(tmp_path / "ledger.jsonl"))
    a1 = ledger.begin("load_qc", {"file": "f.vcf"}, commit=True)
    ledger.checkpoint(a1, "f.vcf", 1000, {})  # final chunk; crash before finish
    a2 = ledger.begin("load_qc", {"file": "f.vcf"}, commit=True)
    ledger.finish(a2, {})  # all chunks were covered; no new checkpoints
    assert ledger.last_checkpoint("f.vcf") == 0


def test_ledger_dry_run_and_test_runs_do_not_supersede(tmp_path):
    """A dry run (commit=False) or --test run finishing after a crashed
    commit load must NOT erase its resume cursor — neither completes the
    file."""
    ledger = AlgorithmLedger(str(tmp_path / "ledger.jsonl"))
    a1 = ledger.begin("load_qc", {"file": "f.vcf"}, commit=True)
    ledger.checkpoint(a1, "f.vcf", 1000, {})  # crash
    a2 = ledger.begin("load_qc", {"file": "f.vcf"}, commit=False)
    ledger.finish(a2, {})  # dry run
    assert ledger.last_checkpoint("f.vcf") == 1000
    a3 = ledger.begin("load_qc", {"file": "f.vcf", "test": True}, commit=True)
    ledger.finish(a3, {})  # --test run: stopped after one batch
    assert ledger.last_checkpoint("f.vcf") == 1000


def test_ledger_test_run_own_checkpoint_stays_live(tmp_path):
    """A --test --commit run that persisted its first batch leaves a LIVE
    resume cursor: its own finish does not mark the file complete, so the
    later full run must not replay (and duplicate) that batch."""
    ledger = AlgorithmLedger(str(tmp_path / "ledger.jsonl"))
    a1 = ledger.begin("load_tsv", {"file": "f.tsv", "test": True}, commit=True)
    ledger.checkpoint(a1, "f.tsv", 32768, {})
    ledger.finish(a1, {})  # test run "finishes" after one batch
    assert ledger.last_checkpoint("f.tsv") == 32768
    # the full run then resumes past the committed batch and completes
    a2 = ledger.begin("load_tsv", {"file": "f.tsv"}, commit=True)
    ledger.checkpoint(a2, "f.tsv", 100_000, {})
    ledger.finish(a2, {})
    assert ledger.last_checkpoint("f.tsv") == 0


def test_ledger_undone_checkpoint_is_dead(tmp_path):
    """Undoing an invocation (rows deleted) must kill its resume cursor —
    otherwise a later full run would skip the undone batch forever."""
    ledger = AlgorithmLedger(str(tmp_path / "ledger.jsonl"))
    a1 = ledger.begin("load_tsv", {"file": "f.tsv", "test": True}, commit=True)
    ledger.checkpoint(a1, "f.tsv", 32768, {})
    ledger.finish(a1, {})
    assert ledger.last_checkpoint("f.tsv") == 32768  # test-run cursor live
    ledger.undo(a1, removed=32768)
    assert ledger.last_checkpoint("f.tsv") == 0      # dead after undo


def test_ledger_undone_superseder_revives_older_cursor(tmp_path):
    """Undoing the run that completed a file revives an older crashed run's
    live checkpoint — the undone run no longer covers those lines."""
    ledger = AlgorithmLedger(str(tmp_path / "ledger.jsonl"))
    a1 = ledger.begin("load_qc", {"file": "f.vcf"}, commit=True)
    ledger.checkpoint(a1, "f.vcf", 500, {})  # crash: a1 never finishes
    a2 = ledger.begin("load_qc", {"file": "f.vcf"}, commit=True)
    ledger.checkpoint(a2, "f.vcf", 900, {})
    ledger.finish(a2, {})
    assert ledger.last_checkpoint("f.vcf") == 0  # a2 completed the file
    ledger.undo(a2, removed=900)
    # a2's coverage is gone; a1's crashed cursor is live again
    assert ledger.last_checkpoint("f.vcf") == 500


def test_ledger_tolerates_torn_final_line(tmp_path):
    """A SIGKILL mid-append leaves a truncated trailing JSONL line; reopen
    must drop it (that checkpoint never became durable), heal the file, and
    keep accepting appends."""
    path = str(tmp_path / "ledger.jsonl")
    ledger = AlgorithmLedger(path)
    a1 = ledger.begin("load", {"file": "f.vcf"}, commit=True)
    ledger.checkpoint(a1, "f.vcf", 1000, {})
    with open(path, "a") as f:
        f.write('{"type": "checkpoint", "alg_id": 1, "file": "f.v')  # torn
    reopened = AlgorithmLedger(path)
    assert reopened.last_checkpoint("f.vcf") == 1000  # torn line ignored
    a2 = reopened.begin("load", {"file": "f.vcf"}, commit=True)
    reopened.checkpoint(a2, "f.vcf", 2000, {})
    # healed: every line in the file parses again
    again = AlgorithmLedger(path)
    assert again.last_checkpoint("f.vcf") == 2000
    # a torn line in the MIDDLE (crash mid-append interleaved with another
    # writer, or byte damage) skips with a warning too: one bad line must
    # never poison runs()/last_checkpoint() for the whole store — fsck
    # reports the skipped count, the next append heals the file
    lines = open(path).read().splitlines()
    lines.insert(1, '{"type": "checkpoi')
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
    tolerant = AlgorithmLedger(path)
    assert tolerant.skipped_lines == 1
    assert tolerant.last_checkpoint("f.vcf") == 2000  # good lines intact
    tolerant.checkpoint(2, "f.vcf", 3000, {})  # heal-on-append
    healed = AlgorithmLedger(path)
    assert healed.skipped_lines == 0
    assert healed.last_checkpoint("f.vcf") == 3000


def test_save_is_atomic_against_kill(tmp_path, monkeypatch):
    """A crash mid-save must leave the previous on-disk state loadable:
    manifest and segment files swap in via tmp+rename, never truncate in
    place."""
    import os

    import annotatedvdb_tpu.store.variant_store as vs

    store = VariantStore(width=8)
    shard = store.shard(1)
    rows = {
        "pos": np.arange(100, 200, dtype=np.int32),
        "h": np.arange(100, dtype=np.uint32),
        "ref_len": np.ones(100, np.int32),
        "alt_len": np.ones(100, np.int32),
    }
    ref = np.zeros((100, 8), np.uint8); ref[:, 0] = 65
    alt = np.zeros((100, 8), np.uint8); alt[:, 0] = 71
    shard.append(dict(rows), ref.copy(), alt.copy())
    out = str(tmp_path / "vdb")
    store.save(out)
    before = VariantStore.load(out).n

    # second save dies midway: the segment write completes but the process
    # "dies" before the manifest swap
    rows2 = dict(rows); rows2["pos"] = rows["pos"] + 1000
    shard.append(rows2, ref.copy(), alt.copy())

    real_replace = os.replace
    def dying_replace(src, dst):
        if dst.endswith("manifest.json"):
            raise KeyboardInterrupt("simulated kill before manifest swap")
        return real_replace(src, dst)
    monkeypatch.setattr(vs.os, "replace", dying_replace)
    try:
        store.save(out)
    except KeyboardInterrupt:
        pass
    monkeypatch.setattr(vs.os, "replace", real_replace)
    # the previous state must still load intact
    assert VariantStore.load(out).n == before
    # and a clean retry completes the save
    store.save(out)
    assert VariantStore.load(out).n == 200


def _tiny_store(pos_list, width=8):
    store = VariantStore(width=width)
    n = len(pos_list)
    store.shard(1).append(
        {"pos": np.asarray(pos_list, np.int32),
         "h": np.arange(n, dtype=np.uint32)},
        np.full((n, width), 65, np.uint8),
        np.full((n, width), 67, np.uint8),
    )
    return store


def test_save_rejects_stale_files_from_other_store(tmp_path):
    """A same-stem segment file written by a DIFFERENT store must be
    rewritten, not adopted — including after the directory is overwritten
    BETWEEN two saves of the same store (the uid check re-reads the
    manifest every save; no trust cache)."""
    d = str(tmp_path / "vdb")
    a = _tiny_store([100, 200, 300])
    a.save(d)
    b = _tiny_store([111, 222, 333])
    b.save(d)  # same stems, different lineage: must not adopt a's files
    got = VariantStore.load(d).shards[1].column("pos").tolist()
    assert got == [111, 222, 333]
    # store A saves again into the (now foreign) directory: must rewrite,
    # not reference b's same-stem files
    a.save(d)
    got = VariantStore.load(d).shards[1].column("pos").tolist()
    assert got == [100, 200, 300]


def test_save_requires_both_segment_files(tmp_path):
    """A clean segment whose .ann.jsonl sibling vanished is rewritten on
    the next save (both files are the segment's on-disk identity)."""
    import os

    d = str(tmp_path / "vdb")
    store = _tiny_store([5, 6, 7])
    store.save(d)
    [ann] = [f for f in os.listdir(d) if f.endswith(".ann.jsonl")]
    os.remove(os.path.join(d, ann))
    store.save(d)
    got = VariantStore.load(d).shards[1].column("pos").tolist()
    assert got == [5, 6, 7]


def test_lookup_empty_query(rng):
    """Empty query batches return empty results (public-API contract)."""
    store = _tiny_store([10, 20])
    shard = store.shards[1]
    found, idx = shard.lookup(
        np.zeros(0, np.int32), np.zeros(0, np.uint32),
        np.zeros((0, 8), np.uint8), np.zeros((0, 8), np.uint8),
        np.zeros(0, np.int32), np.zeros(0, np.int32),
    )
    assert found.size == 0 and idx.size == 0


def test_disjoint_segments_not_merged_and_collapse(rng):
    """Monotonic appends stay one-segment-per-flush (no merge copies);
    overlapping appends still cascade; the MAX_SEGMENTS bound collapses
    runs back into capped segments."""
    from annotatedvdb_tpu.store import variant_store as vs

    store = _tiny_store([10, 20, 30])
    shard = store.shards[1]
    n0 = len(shard.segments)
    # disjoint (all-later keys): appended as a new segment, not merged
    shard.append(
        {"pos": np.asarray([40, 50], np.int32),
         "h": np.arange(2, dtype=np.uint32),
         "ref_len": np.full(2, 8, np.int32),
         "alt_len": np.full(2, 8, np.int32)},
        np.full((2, 8), 65, np.uint8), np.full((2, 8), 67, np.uint8),
    )
    assert len(shard.segments) == n0 + 1
    # overlapping append (key range intersects): cascade merges
    shard.append(
        {"pos": np.asarray([45, 60], np.int32),
         "h": np.asarray([9, 9], np.uint32),
         "ref_len": np.full(2, 8, np.int32),
         "alt_len": np.full(2, 8, np.int32)},
        np.full((2, 8), 65, np.uint8), np.full((2, 8), 67, np.uint8),
    )
    assert len(shard.segments) == n0 + 1  # merged into the tail segment
    # lookup still finds everything across segments
    h = np.arange(2, dtype=np.uint32)
    found, _ = shard.lookup(
        np.asarray([40, 50], np.int32), h,
        np.full((2, 8), 65, np.uint8), np.full((2, 8), 67, np.uint8),
        np.full(2, 8, np.int32), np.full(2, 8, np.int32),
    )
    assert found.all()


def test_collapse_bounds_segment_count(monkeypatch):
    from annotatedvdb_tpu.store import variant_store as vs

    monkeypatch.setattr(vs, "MAX_SEGMENTS", 8)
    store = VariantStore(width=8)
    shard = store.shard(1)
    for k in range(40):
        base = k * 100
        shard.append(
            {"pos": np.asarray([base + 1, base + 2], np.int32),
             "h": np.arange(2, dtype=np.uint32),
             "ref_len": np.full(2, 8, np.int32),
             "alt_len": np.full(2, 8, np.int32)},
            np.full((2, 8), 65, np.uint8), np.full((2, 8), 67, np.uint8),
        )
    assert len(shard.segments) <= 9
    assert shard.n == 80
    # every row still reachable
    found, _ = shard.lookup(
        np.asarray([1, 1901, 3902], np.int32),
        np.asarray([0, 0, 1], np.uint32),
        np.full((3, 8), 65, np.uint8), np.full((3, 8), 67, np.uint8),
        np.full(3, 8, np.int32), np.full(3, 8, np.int32),
    )
    assert found.all()


def test_legacy_npz_segments_still_load(tmp_path):
    """Stores persisted by older builds carry zip-backed npz segment files;
    the flat-container reader must sniff and load them unchanged."""
    import json
    import os

    from annotatedvdb_tpu.store.variant_store import _NUMERIC_COLUMNS

    store = VariantStore(width=8)
    store.shard(1).append(
        {"pos": np.asarray([10, 20, 30], np.int32),
         "h": np.asarray([7, 8, 9], np.uint32),
         "ref_len": np.full(3, 1, np.int32),
         "alt_len": np.full(3, 1, np.int32)},
        np.full((3, 8), 65, np.uint8), np.full((3, 8), 67, np.uint8),
    )
    d = str(tmp_path / "vdb")
    store.save(d)
    # rewrite every segment file in the LEGACY np.savez layout
    for name in os.listdir(d):
        if not name.endswith(".npz"):
            continue
        fp = os.path.join(d, name)
        with open(fp, "rb") as f:
            assert f.read(1) == b"{"  # current flat container
            f.seek(0)
            names = json.loads(f.readline())["names"]
            data = {
                n_: np.lib.format.read_array(f, allow_pickle=False)
                for n_ in names
            }
        with open(fp, "wb") as f:
            np.savez(f, **data)
        with open(fp, "rb") as f:
            assert f.read(1) == b"P"  # genuinely zip-backed now
    # legacy manifests predate integrity records: drop them so the emulated
    # store is faithful (otherwise the size check correctly flags the
    # out-of-band rewrite as tampering)
    mpath = os.path.join(d, "manifest.json")
    manifest = json.load(open(mpath))
    manifest.pop("integrity", None)
    with open(mpath, "w") as f:
        json.dump(manifest, f)
    loaded = VariantStore.load(d)
    assert loaded.n == 3
    s = loaded.shard(1)
    np.testing.assert_array_equal(np.sort(s.cols["pos"]), [10, 20, 30])
    np.testing.assert_array_equal(np.sort(s.cols["h"]), [7, 8, 9])
    for col, _ in _NUMERIC_COLUMNS:
        assert col in s.cols
