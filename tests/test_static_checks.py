"""Tier-1 static-analysis gate: the shipped tree stays clean under
``avdb_check`` (and the chained check script), and the analyzer stays fast
enough to run on every PR.

This is the enforcement half of the suite — the analyzer's own behavior
is pinned fixture-by-fixture in ``tests/test_avdb_check.py``.  A finding
here means new code violated a project invariant (trace-safety,
lock-discipline, registry-drift, env-drift, CLI-contract, hygiene,
async-safety, cross-front-end parity, device/host twin contract): fix
it or suppress with ``# avdb: noqa[CODE] -- reason`` per README "Static
analysis & code health".  The chained script additionally runs the serve
smoke under ``AVDB_LOCK_TRACE=1`` — the dynamic lock-order/deadlock
detector — and fails on any acquisition-order cycle.
"""

import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCAN = ["annotatedvdb_tpu", "tools", "tests", "bench.py"]


def test_tree_is_clean_and_fast():
    """Acceptance gate: zero findings over the whole tree, bounded wall.

    The budget is a guardrail against the analyzer going quadratic, not
    a latency SLO: it was 10s when the tree held 136 files, and at 182
    files on this 2-3x-swinging container a clean run measures 9-11s —
    20s keeps the quadratic-blowup alarm while surviving a slow
    scheduling window."""
    t0 = time.monotonic()
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "avdb_check.py"),
         *SCAN],
        capture_output=True, text=True, cwd=REPO,
    )
    wall = time.monotonic() - t0
    assert p.returncode == 0, (
        "avdb_check found violations (fix or noqa-with-reason; "
        "see README 'Static analysis & code health'):\n" + p.stdout
    )
    assert wall < 20.0, f"analyzer took {wall:.1f}s (budget 20s)"


def test_run_checks_script_clean():
    """The chained entry point (avdb_check + ruff-if-present + bench
    schema + lock-order-traced serve smoke + chaos smoke) gates every
    future PR from one script."""
    p = subprocess.run(
        ["bash", os.path.join(REPO, "tools", "run_checks.sh")],
        capture_output=True, text=True, cwd=REPO,
    )
    assert p.returncode == 0, p.stdout + "\n" + p.stderr


def test_fault_point_registry_matches_call_sites():
    """Every faults.POINTS entry is reachable: the analyzer's AVDB301/302
    guard the call sites and the matrix; this pins the registry itself
    against the live fire() sites (a deleted call site should delete its
    registry entry too)."""
    import re

    from annotatedvdb_tpu.utils import faults

    fired = set()
    pkg = os.path.join(REPO, "annotatedvdb_tpu")
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            with open(os.path.join(dirpath, fn), encoding="utf-8") as f:
                # only real point names (docstrings discussing the
                # `faults.fire("<point>")` pattern don't count)
                fired.update(
                    re.findall(r'faults\.fire\(\s*"([a-z][a-z0-9_.]*)"',
                               f.read())
                )
    assert fired == set(faults.POINTS), (
        f"faults.POINTS drift: registered-but-never-fired "
        f"{sorted(set(faults.POINTS) - fired)}, "
        f"fired-but-unregistered {sorted(fired - set(faults.POINTS))}"
    )
