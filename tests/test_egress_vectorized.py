"""Vectorized egress string assembly == scalar oracles, row for row."""

import numpy as np

from annotatedvdb_tpu.io import egress
from annotatedvdb_tpu.models.pipeline import annotate_batch
from annotatedvdb_tpu.oracle.binindex import closed_form_path
from annotatedvdb_tpu.types import AnnotatedBatch, VariantBatch, chromosome_label

from conftest import random_variants


def _annotated(batch):
    ann = annotate_batch(batch)
    return AnnotatedBatch(*(np.asarray(x) for x in ann))


def test_decode_alleles_roundtrip(rng):
    variants = random_variants(rng, 300)
    batch = VariantBatch.from_tuples(variants, width=24)
    refs, alts = egress.decode_alleles(batch)
    for i, (_, _, ref, alt) in enumerate(variants):
        assert refs[i] == ref and alts[i] == alt


def test_metaseq_and_bin_paths_match_scalar(rng):
    variants = random_variants(rng, 500)
    batch = VariantBatch.from_tuples(variants, width=24)
    ann = _annotated(batch)
    mseq = egress.metaseq_ids(batch)
    paths = egress.bin_paths(batch, ann)
    for i, (chrom, pos, ref, alt) in enumerate(variants):
        label = chromosome_label(batch.chrom[i])
        assert mseq[i] == f"{label}:{pos}:{ref}:{alt}"
        want = closed_form_path(
            "chr" + label, int(ann.bin_level[i]), int(ann.leaf_bin[i])
        )
        assert paths[i] == want, (i, paths[i], want)


def test_primary_keys_literal_and_rs_suffix(rng):
    variants = [("1", 100, "A", "G"), ("X", 5_000, "AT", "A"),
                ("M", 263, "A", "G")]
    batch = VariantBatch.from_tuples(variants, width=24)
    ann = _annotated(batch)
    pks = egress.primary_keys(batch, ann, ["rs1", None, "rs3"])
    assert pks[0] == "1:100:A:G:rs1"
    assert pks[1] == "X:5000:AT:A"
    assert pks[2] == "M:263:A:G:rs3"
    # no rs ids at all: scalar-suffix fast path
    pks2 = egress.primary_keys(batch, ann, [None, None, None])
    assert list(pks2) == ["1:100:A:G", "X:5000:AT:A", "M:263:A:G"]
