"""Vectorized egress string assembly == scalar oracles, row for row."""

import numpy as np

from annotatedvdb_tpu.io import egress
from annotatedvdb_tpu.models.pipeline import annotate_batch
from annotatedvdb_tpu.oracle.binindex import closed_form_path
from annotatedvdb_tpu.types import AnnotatedBatch, VariantBatch, chromosome_label

from conftest import random_variants


def _annotated(batch):
    ann = annotate_batch(batch)
    return AnnotatedBatch(*(np.asarray(x) for x in ann))


def test_decode_alleles_roundtrip(rng):
    variants = random_variants(rng, 300)
    batch = VariantBatch.from_tuples(variants, width=24)
    refs, alts = egress.decode_alleles(batch)
    for i, (_, _, ref, alt) in enumerate(variants):
        assert refs[i] == ref and alts[i] == alt


def test_metaseq_and_bin_paths_match_scalar(rng):
    variants = random_variants(rng, 500)
    batch = VariantBatch.from_tuples(variants, width=24)
    ann = _annotated(batch)
    mseq = egress.metaseq_ids(batch)
    paths = egress.bin_paths(batch, ann)
    for i, (chrom, pos, ref, alt) in enumerate(variants):
        label = chromosome_label(batch.chrom[i])
        assert mseq[i] == f"{label}:{pos}:{ref}:{alt}"
        want = closed_form_path(
            "chr" + label, int(ann.bin_level[i]), int(ann.leaf_bin[i])
        )
        assert paths[i] == want, (i, paths[i], want)


def test_shard_strings_matches_per_row(rng, tmp_path):
    """The vectorized whole-shard string assembly == the scalar
    ChromosomeShard accessors, row for row — the parity contract that lets
    both PK definitions exist."""
    from annotatedvdb_tpu.loaders import TpuVcfLoader
    from annotatedvdb_tpu.store import AlgorithmLedger, VariantStore

    lines = ["##fileformat=VCFv4.2",
             "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO"]
    pos = 1000
    for i, (_, _, ref, alt) in enumerate(random_variants(rng, 200, max_len=8)):
        pos += 7
        vid = f"rs{i}" if i % 3 == 0 else "."
        lines.append(f"7\t{pos}\t{vid}\t{ref}\t{alt}\t.\t.\t.")
    lines.append(f"7\t{pos + 50}\t.\t{'A' * 60}\tG\t.\t.\t.")  # digest tail
    vcf = tmp_path / "p.vcf"
    vcf.write_text("\n".join(lines) + "\n")
    store = VariantStore(width=49)
    TpuVcfLoader(store, AlgorithmLedger(str(tmp_path / "l.jsonl")),
                 log=lambda *a: None).load_file(str(vcf), commit=True)
    shard = store.shard(7)
    refs, alts, mseq, pks = egress.shard_strings(shard)
    assert sum(1 for i in range(shard.n)
               if len(refs[i]) > 49 or len(alts[i]) > 49) == 1
    for i in range(shard.n):
        assert (refs[i], alts[i]) == shard.alleles(i)
        assert pks[i] == shard.primary_key(i), i
    # windowed assembly (the streaming-egress access pattern) must agree
    # with the whole-shard call, including across the digest-tail row
    w = 64
    for lo in range(0, shard.n, w):
        wr, wa, wm, wp = egress.shard_strings(shard, lo, lo + w)
        hi = min(lo + w, shard.n)
        assert list(wr) == list(refs[lo:hi])
        assert list(wa) == list(alts[lo:hi])
        assert list(wm) == list(mseq[lo:hi])
        assert list(wp) == list(pks[lo:hi])


def test_primary_keys_literal_and_rs_suffix(rng):
    variants = [("1", 100, "A", "G"), ("X", 5_000, "AT", "A"),
                ("M", 263, "A", "G")]
    batch = VariantBatch.from_tuples(variants, width=24)
    ann = _annotated(batch)
    pks = egress.primary_keys(batch, ann, ["rs1", None, "rs3"])
    # the int-column assembly the loaders use must agree with the
    # string-input variant byte-for-byte
    pks_ints = egress.primary_keys_from_ints(
        batch, ann, np.array([1, -1, 3], np.int64)
    )
    assert list(pks_ints) == list(pks)
    assert pks[0] == "1:100:A:G:rs1"
    assert pks[1] == "X:5000:AT:A"
    assert pks[2] == "M:263:A:G:rs3"
    # no rs ids at all: scalar-suffix fast path
    pks2 = egress.primary_keys(batch, ann, [None, None, None])
    assert list(pks2) == ["1:100:A:G", "X:5000:AT:A", "M:263:A:G"]
