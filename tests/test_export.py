"""Training-corpus export battery (``annotatedvdb_tpu/export``).

The contract under test: one ``(store, plan, seed)`` triple maps to ONE
byte-exact corpus — same seed ⇒ byte-identical parts and manifest, across
re-runs, the ``host_only`` numpy twin, and a resume after a real SIGKILL
mid-part-commit — with the shuffled emission order a pure permutation of
the ``--ordered`` plan order, the ragged tail explicitly masked, the
per-chromosome allele dictionaries round-tripping to the rendered
strings, and ``GET /export/stream`` answering byte-identically on both
front ends.  The device/twin pin names and calls BOTH
``export_pack_kernel_jit`` and ``export_pack_host`` (the ops.TWINS
contract), and the ``bench.py --export`` record schema is exercised
against the strict checker.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from annotatedvdb_tpu.config import StoreConfig
from annotatedvdb_tpu.export import core as export_core
from annotatedvdb_tpu.export.core import run_export
from annotatedvdb_tpu.export.stream import emission_order
from annotatedvdb_tpu.export.writer import read_manifest, read_part
from annotatedvdb_tpu.loaders.lookup import identity_hashes
from annotatedvdb_tpu.store import VariantStore
from annotatedvdb_tpu.types import chromosome_label, encode_allele_array

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(ROOT, "tools"))

from check_bench_schema import validate_record  # noqa: E402

WIDTH = 8
CHROMS = (1, 8)
BASES = ("A", "C", "G", "T")
SEED = 3
BATCH_ROWS = 16
PART_BYTES = "2k"  # 16-row batches -> 2 batches/part -> 8 parts


def _rows_for(code: int, base_pos: int, n: int, salt: int):
    rows = []
    for i in range(n):
        k = (i + salt) % 4
        rows.append({
            "chrom": code, "pos": base_pos + 977 * i,
            "ref": BASES[k], "alt": BASES[(k + 1) % 4],
            "cadd": round(0.5 * i + code, 2) if i % 3 == 0 else None,
            "rank": (i % 30) + 1 if i % 4 == 0 else None,
            "af": round((i % 50) / 50.0, 4) if i % 2 == 0 else None,
        })
    return rows


def _build_store(store_dir: str):
    store = VariantStore(width=WIDTH)
    truth: list[dict] = []
    for code in CHROMS:
        shard = store.shard(code)
        for run, base in enumerate((500, 120_000, 2_000_000)):
            rows = _rows_for(code, base, 40, salt=run)
            refs = [r["ref"] for r in rows]
            alts = [r["alt"] for r in rows]
            ref, ref_len = encode_allele_array(refs, WIDTH)
            alt, alt_len = encode_allele_array(alts, WIDTH)
            h = identity_hashes(WIDTH, ref, alt, ref_len, alt_len,
                                refs, alts)
            shard.append(
                {"pos": np.asarray([r["pos"] for r in rows], np.int32),
                 "h": h, "ref_len": ref_len, "alt_len": alt_len},
                ref, alt,
                annotations={
                    "cadd_scores": [
                        {"CADD_phred": r["cadd"]} if r["cadd"] is not None
                        else None for r in rows
                    ],
                    "adsp_most_severe_consequence": [
                        {"conseq": "missense_variant", "rank": r["rank"]}
                        if r["rank"] is not None else None for r in rows
                    ],
                    "allele_frequencies": [
                        {"GnomAD": {"af": r["af"]}}
                        if r["af"] is not None else None for r in rows
                    ],
                },
            )
            truth.extend(rows)
    store.save(store_dir)
    return truth


def _corpus_bytes(out_dir: str) -> dict:
    out = {}
    for name in sorted(os.listdir(out_dir)):
        if name.endswith(".npz") or name == "corpus.manifest.json":
            with open(os.path.join(out_dir, name), "rb") as f:
                out[name] = f.read()
    return out


def _all_batches(out_dir: str) -> list[dict]:
    """Every committed batch across parts, in file order: one dict of
    per-batch scalars + row arrays each."""
    manifest = read_manifest(out_dir)
    batches = []
    for part in manifest["parts"]:
        arrays = read_part(os.path.join(out_dir, part["file"]))
        for b in range(arrays["n_valid"].shape[0]):
            batches.append({
                "chrom_code": int(arrays["chrom_code"][b]),
                "n_valid": int(arrays["n_valid"][b]),
                "seq": int(arrays["seq"][b]),
                **{name: arrays[name][b]
                   for name in export_core.ROW_FIELDS},
            })
    return batches


@pytest.fixture(scope="module")
def exported(tmp_path_factory):
    """(store_dir, truth, store, ledger, ref_dir): the uninterrupted
    whole-store reference export every determinism test compares to."""
    store_dir = str(tmp_path_factory.mktemp("export_store"))
    truth = _build_store(store_dir)
    store, ledger = StoreConfig(store_dir).open(create=False,
                                                readonly=True)
    ref_dir = str(tmp_path_factory.mktemp("export_ref"))
    summary = run_export(store, ledger, store_dir, ref_dir, seed=SEED,
                         batch_rows=BATCH_ROWS, part_bytes=PART_BYTES)
    assert summary["complete"] and summary["rows"] == len(truth)
    return store_dir, truth, store, ledger, ref_dir


# ---------------------------------------------------------------------------
# determinism: seed replay, host twin, shuffle-vs-ordered


def test_same_seed_rerun_byte_identical(exported, tmp_path):
    """Same (store, plan, seed) ⇒ byte-identical corpus; a different
    seed permutes emission and must change part bytes."""
    store_dir, _truth, store, ledger, ref_dir = exported
    want = _corpus_bytes(ref_dir)
    replay = str(tmp_path / "replay")
    run_export(store, ledger, store_dir, replay, seed=SEED,
               batch_rows=BATCH_ROWS, part_bytes=PART_BYTES)
    assert _corpus_bytes(replay) == want

    other = str(tmp_path / "other_seed")
    run_export(store, ledger, store_dir, other, seed=SEED + 1,
               batch_rows=BATCH_ROWS, part_bytes=PART_BYTES)
    got = _corpus_bytes(other)
    assert set(got) == set(want)  # same shape: same parts, same names
    assert any(got[n] != want[n] for n in want if n.endswith(".npz"))


def test_host_twin_corpus_byte_identical(exported, tmp_path):
    """``host_only=True`` routes every batch through the numpy twin and
    the corpus bytes must not move — the kernel/twin contract at the
    whole-subsystem level."""
    store_dir, _truth, store, ledger, ref_dir = exported
    twin = str(tmp_path / "twin")
    run_export(store, ledger, store_dir, twin, seed=SEED,
               batch_rows=BATCH_ROWS, part_bytes=PART_BYTES,
               host_only=True)
    assert _corpus_bytes(twin) == _corpus_bytes(ref_dir)


def test_export_pack_device_and_host_twins_byte_equal():
    """The ops.TWINS pin: ``export_pack_kernel_jit`` (device) and
    ``export_pack_host`` (numpy) produce byte-identical outputs, dtype
    for dtype, on a batch with a ragged tail and missing features."""
    from annotatedvdb_tpu.ops.export_pack import (
        export_pack_host,
        export_pack_kernel_jit,
    )

    B, n_valid = 32, 21
    rng = np.random.RandomState(7)
    pos = np.full(B, 1, np.int32)
    pos[:n_valid] = rng.randint(1, 2_000_000, n_valid)
    end = pos + np.where(rng.rand(B) < 0.3, 40, 0).astype(np.int32)
    ref_code = np.full(B, -1, np.int32)
    ref_code[:n_valid] = rng.randint(0, 4, n_valid)
    alt_code = np.full(B, -1, np.int32)
    alt_code[:n_valid] = rng.randint(0, 4, n_valid)
    feats = []
    for _ in range(3):
        col = np.full(B, -1, np.int32)
        present = rng.rand(n_valid) < 0.6
        col[:n_valid] = np.where(present,
                                 rng.randint(0, 10_000, n_valid), -1)
        feats.append(col)
    args = (pos, end, ref_code, alt_code, *feats, np.int32(n_valid))
    dev = [np.asarray(a) for a in export_pack_kernel_jit(*args)]
    host = [np.asarray(a) for a in export_pack_host(*args)]
    assert len(dev) == len(host) == 9
    for d, h in zip(dev, host):
        assert d.dtype == h.dtype and d.tobytes() == h.tobytes()
    # padded lanes uniformly masked: False / -1 beyond n_valid
    mask = dev[0]
    assert mask[:n_valid].all() and not mask[n_valid:].any()
    for col in dev[1:]:
        assert (col[n_valid:] == -1).all()


def test_shuffle_is_permutation_of_ordered_plan(exported, tmp_path):
    """The shuffled corpus is a pure permutation: its ``seq`` tags are
    the prefetcher's disjoint-block order (``emission_order`` replays it
    exactly), non-identity, and reordering its batches by ``seq``
    reproduces the ``--ordered`` corpus batch for batch."""
    store_dir, _truth, store, ledger, ref_dir = exported
    ordered_dir = str(tmp_path / "ordered")
    run_export(store, ledger, store_dir, ordered_dir, seed=SEED,
               batch_rows=BATCH_ROWS, part_bytes=PART_BYTES, ordered=True)
    shuffled = _all_batches(ref_dir)
    ordered = _all_batches(ordered_dir)
    assert len(shuffled) == len(ordered)
    seqs = [b["seq"] for b in shuffled]
    assert sorted(seqs) == list(range(len(ordered)))
    assert seqs != list(range(len(ordered)))  # seed 3 really permutes
    assert seqs == emission_order(len(ordered), SEED)
    assert [b["seq"] for b in ordered] == list(range(len(ordered)))
    by_seq = sorted(shuffled, key=lambda b: b["seq"])
    for got, want in zip(by_seq, ordered):
        assert got["chrom_code"] == want["chrom_code"]
        assert got["n_valid"] == want["n_valid"]
        for name in export_core.ROW_FIELDS:
            np.testing.assert_array_equal(got[name], want[name], err_msg=name)


# ---------------------------------------------------------------------------
# batch shape: ragged tail, allele dictionary


def test_ragged_tail_mask_and_padding(exported):
    """Each chromosome's last batch is ragged (120 rows into 16-row
    batches): the validity mask covers exactly ``n_valid`` rows and every
    padded lane is the -1 sentinel (empty string on the ltree path)."""
    _dir, truth, _store, _ledger, ref_dir = exported
    per_chrom = len(truth) // len(CHROMS)
    tail = per_chrom % BATCH_ROWS
    assert 0 < tail < BATCH_ROWS  # the fixture really has a ragged tail
    ragged = [b for b in _all_batches(ref_dir) if b["n_valid"] == tail]
    assert len(ragged) == len(CHROMS)
    for b in ragged:
        n = b["n_valid"]
        assert b["mask"][:n].all() and not b["mask"][n:].any()
        assert b["bin_level"][:n].min() >= 0
        for name in ("bin_level", "leaf_bin", "pos", "ref_code",
                     "alt_code", "af_fp", "cadd_fp", "rank_i"):
            assert (b[name][n:] == -1).all(), name
        assert (b["bin_index"][:n] != "").all()
        assert (b["bin_index"][n:] == "").all()


def test_allele_dict_round_trip_equals_rendered_alleles(exported):
    """Decoding every valid row's ``ref_code``/``alt_code`` through the
    manifest's per-chromosome dictionary reproduces the exact allele
    strings loaded into the store — and every truth row is present."""
    _dir, truth, _store, _ledger, ref_dir = exported
    manifest = read_manifest(ref_dir)
    want = {(r["chrom"], r["pos"]): (r["ref"], r["alt"]) for r in truth}
    seen = set()
    for b in _all_batches(ref_dir):
        alleles = manifest["alleles"][chromosome_label(b["chrom_code"])]
        for i in range(b["n_valid"]):
            key = (b["chrom_code"], int(b["pos"][i]))
            decoded = (alleles[int(b["ref_code"][i])],
                       alleles[int(b["alt_code"][i])])
            assert decoded == want[key], key
            seen.add(key)
    assert seen == set(want)


# ---------------------------------------------------------------------------
# resume after a real SIGKILL (the CLI, a subprocess, no finally blocks)


def test_resume_after_sigkill_via_cli_byte_identical(exported, tmp_path):
    """The real ``avdb export`` CLI armed ``export.commit:3:kill`` dies
    mid-part-commit (true SIGKILL: no cleanup ran), stranding a
    committed-part prefix plus tmp debris; ``--resume`` prunes the
    debris, skips the committed parts, and the final corpus — manifest
    included — is byte-identical to the uninterrupted reference."""
    store_dir, _truth, _store, _ledger, ref_dir = exported
    out_dir = str(tmp_path / "out")
    argv = [
        sys.executable, "-m", "annotatedvdb_tpu", "export",
        "--storeDir", store_dir, "--out", out_dir, "--commit",
        "--seed", str(SEED), "--batchRows", str(BATCH_ROWS),
        "--partBytes", PART_BYTES,
    ]
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               AVDB_FAULT="export.commit:3:kill")
    p = subprocess.run(argv, env=env, capture_output=True, text=True,
                       timeout=480)
    assert p.returncode == -signal.SIGKILL, (
        f"expected SIGKILL death, rc={p.returncode}\n{p.stderr[-2000:]}"
    )
    names = os.listdir(out_dir)
    assert any(".export.tmp" in f for f in names)
    assert "corpus.manifest.json" not in names  # manifest commits LAST

    env.pop("AVDB_FAULT")
    p = subprocess.run(argv + ["--resume"], env=env, capture_output=True,
                       text=True, timeout=480)
    assert p.returncode == 0, p.stderr[-2000:]
    summary = json.loads(p.stdout.strip().splitlines()[-1])
    assert summary["complete"] and summary["resumed_parts"] >= 1
    assert _corpus_bytes(out_dir) == _corpus_bytes(ref_dir)


# ---------------------------------------------------------------------------
# GET /export/stream: both front ends, byte parity


def _get(port: int, path: str):
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=20
        ) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as err:
        return err.code, err.read().decode()


@pytest.fixture()
def both_servers(exported):
    from annotatedvdb_tpu.serve.aio import build_aio_server
    from annotatedvdb_tpu.serve.http import build_server

    store_dir, _truth, _store, _ledger, _ref = exported
    httpd = build_server(store_dir=store_dir, port=0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    aio = build_aio_server(store_dir=store_dir, port=0)
    aio.start_background()
    try:
        yield httpd, aio
    finally:
        httpd.shutdown()
        httpd.server_close()
        httpd.ctx.batcher.close()
        aio.shutdown()
        aio.ctx.batcher.close()


def test_export_stream_cross_frontend_byte_parity(both_servers):
    httpd, aio = both_servers
    tport, aport = httpd.server_address[1], aio.server_address[1]
    queries = [
        "region=1:1-3000000&batch_rows=16&seed=3",           # shuffled
        "region=1:1-3000000&batch_rows=16&seed=3&batch=5",
        "region=1:1-3000000&batch_rows=16&ordered=1&batch=7",
        "region=chr8:100000-150000&batch_rows=8",
        "region=1:1-3000000&batch_rows=16&seed=4",           # reseeded
    ]
    for q in queries:
        st1, b1 = _get(tport, f"/export/stream?{q}")
        st2, b2 = _get(aport, f"/export/stream?{q}")
        assert (st1, b1) == (st2, b2), q
        assert st1 == 200, (q, b1)
        doc = json.loads(b1)
        n = doc["n_valid"]
        mask = doc["arrays"]["mask"]
        assert sum(mask) == n and all(mask[:n])
        assert doc["tokens_per_row"] == export_core.TOKENS_PER_ROW
    # kind=export counted on both front ends
    for port in (tport, aport):
        _st, metrics = _get(port, "/metrics")
        assert 'avdb_query_requests_total{kind="export"}' in metrics


def test_export_stream_shuffled_batch_matches_emission_order(both_servers):
    """The route's "seed S, batch K" is the SAME permutation the bulk
    exporter would emit: fetching shuffled slot K equals fetching plan
    batch ``emission_order(n, S)[K]`` in ordered mode, byte for byte in
    the arrays."""
    httpd, _aio = both_servers
    port = httpd.server_address[1]
    base = "region=1:1-3000000&batch_rows=16"
    _st, first = _get(port, f"/export/stream?{base}&seed=3")
    n_batches = json.loads(first)["n_batches"]
    order = emission_order(n_batches, 3)
    for k in (0, 3, n_batches - 1):
        _s1, shuffled = _get(port, f"/export/stream?{base}&seed=3&batch={k}")
        _s2, ordered = _get(
            port, f"/export/stream?{base}&ordered=1&batch={order[k]}")
        sdoc, odoc = json.loads(shuffled), json.loads(ordered)
        assert sdoc["seq"] == order[k] == odoc["batch"]
        assert sdoc["arrays"] == odoc["arrays"]
        assert sdoc["alleles"] == odoc["alleles"]


def test_export_stream_error_parity(both_servers):
    httpd, aio = both_servers
    tport, aport = httpd.server_address[1], aio.server_address[1]
    for q in (
        "",                                        # missing region
        "region=nope",                             # bad grammar
        "region=1:9-3",                            # inverted span
        "region=1:1-100&batch_rows=4",             # below the floor
        "region=1:1-100&batch_rows=99999",         # above the cap
        "region=21:1-100",                         # chromosome not in store
        "region=1:1-3000000&batch_rows=16&batch=500",  # batch out of range
    ):
        st1, b1 = _get(tport, f"/export/stream?{q}")
        st2, b2 = _get(aport, f"/export/stream?{q}")
        assert st1 == 400 and (st1, b1) == (st2, b2), q


# ---------------------------------------------------------------------------
# bench --export record schema (tools/check_bench_schema.py, strict)


GOOD_EXPORT = {
    "metric": "export_tokens_per_sec",
    "value": 612000.0,
    "unit": "tokens/sec",
    "vs_baseline": 0.612,
    "backend": "cpu",
    "platform_pin": "cpu",
    "mode": "export",
    "export": {
        "rows": 120_000,
        "seed": 11,
        "batch_rows": 4096,
        "one_shot": {
            "tokens_per_sec": 612000.0, "device_idle_frac": 0.08,
            "rows": 120_000, "tokens": 960_000, "parts": 3,
            "seconds": 1.57, "complete": True,
        },
        "replay_identical": True,
        "host_twin_identical": True,
        "resume": {"killed_rc": -9, "resume_rc": 0, "identical": True},
    },
}


def test_bench_export_schema_good_record_passes():
    assert validate_record(GOOD_EXPORT) == []


@pytest.mark.parametrize("mutate,needle", [
    (lambda r: r["export"].update(replay_identical=False),
     "replay_identical"),
    (lambda r: r["export"].update(host_twin_identical=False),
     "host_twin_identical"),
    (lambda r: r["export"]["resume"].update(resume_rc=1), "resume_rc"),
    (lambda r: r["export"]["resume"].update(identical=False), "identical"),
    (lambda r: r["export"]["resume"].update(killed_rc=0),
     "SIGKILL never landed"),
    (lambda r: r.pop("export"), "export block"),
    (lambda r: r["export"]["one_shot"].update(device_idle_frac=1.4),
     "device_idle_frac"),
    (lambda r: r.update(unit="rows/sec"), "unit"),
    (lambda r: r["export"].pop("one_shot"), "one_shot"),
])
def test_bench_export_schema_catches_drift(mutate, needle):
    import copy

    bad = copy.deepcopy(GOOD_EXPORT)
    mutate(bad)
    errors = validate_record(bad)
    assert any(needle in e for e in errors), (needle, errors)


def test_bench_export_schema_errored_record_still_validates():
    """A failed bench leg records {"error": ...} instead of the export
    block — that is a VALID record (the run is evidence), not drift."""
    failed = {
        "metric": "export_tokens_per_sec", "value": 0.0,
        "unit": "tokens/sec", "vs_baseline": 0.0, "backend": "cpu",
        "mode": "export", "error": "RuntimeError: device lost",
    }
    assert validate_record(failed) == []
