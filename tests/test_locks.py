"""Lock-order / deadlock detector battery (``utils.locks`` +
``analysis.lockorder``).

Three layers:

- **wrapper mechanics** — ``make_lock`` is a plain stdlib lock unarmed
  (the production path pays nothing) and a recording ``TracedLock`` under
  ``AVDB_LOCK_TRACE=1``;
- **detector semantics** — an ABBA inversion across two threads is
  reported as a cycle, consistent orderings and reentrant re-acquires are
  not, held durations land in the ``avdb_lock_held_seconds`` histogram;
- **serve battery under trace** — the real serve stack (engine + batcher
  + ServeContext admission + snapshot pin) driven concurrently with
  tracing armed must produce ZERO cycles: the tier-1 half of the
  acceptance gate (``tools/run_checks.sh`` arms the serve smoke the same
  way for the full-HTTP version).
"""

from __future__ import annotations

import threading

import numpy as np
import pytest

from annotatedvdb_tpu.analysis.lockorder import RECORDER, LockOrderRecorder
from annotatedvdb_tpu.utils.locks import TracedLock, make_lock


# ---------------------------------------------------------------------------
# wrapper mechanics


def test_make_lock_unarmed_is_plain_stdlib_lock(monkeypatch):
    monkeypatch.delenv("AVDB_LOCK_TRACE", raising=False)
    lock = make_lock("x")
    assert type(lock) is type(threading.Lock())
    rlock = make_lock("x", reentrant=True)
    assert type(rlock) is type(threading.RLock())


def test_make_lock_armed_returns_traced(monkeypatch):
    monkeypatch.setenv("AVDB_LOCK_TRACE", "1")
    lock = make_lock("test.armed")
    assert isinstance(lock, TracedLock)
    with lock:
        assert lock.locked()
    assert not lock.locked()


def test_traced_lock_api_matches_stdlib():
    rec = LockOrderRecorder()
    lock = TracedLock("test.api", recorder=rec)
    assert lock.acquire()
    assert not lock.acquire(blocking=False)  # held: non-blocking fails
    lock.release()
    assert lock.acquire(timeout=1.0)
    lock.release()
    assert rec.held_stats()["test.api"]["count"] == 2


def test_failed_acquire_records_nothing():
    rec = LockOrderRecorder()
    a = TracedLock("test.a", recorder=rec)
    b = TracedLock("test.b", recorder=rec)
    with a:
        done = threading.Event()

        def contender():
            # a is held by the main thread: this acquire must fail and
            # leave no (b -> a) ordering edge behind
            with b:
                assert not a.acquire(blocking=False)
            done.set()

        t = threading.Thread(target=contender)
        t.start()
        assert done.wait(5)
        t.join()
    assert ("test.b", "test.a") not in rec.snapshot_edges()


# ---------------------------------------------------------------------------
# detector semantics


def _run_threads(*fns):
    threads = [threading.Thread(target=fn) for fn in fns]
    for t in threads:
        t.start()
    for t in threads:
        t.join(10)


def test_abba_inversion_is_a_cycle():
    rec = LockOrderRecorder()
    a = TracedLock("order.a", recorder=rec)
    b = TracedLock("order.b", recorder=rec)
    gate = threading.Event()

    def t1():
        with a:
            with b:
                pass
        gate.set()

    def t2():
        gate.wait(5)  # sequential: records the inverted ORDER, no hang
        with b:
            with a:
                pass

    _run_threads(t1, t2)
    cycles = rec.cycles()
    assert len(cycles) == 1
    assert set(cycles[0]) == {"order.a", "order.b"}


def test_consistent_order_is_clean():
    rec = LockOrderRecorder()
    a = TracedLock("order.a", recorder=rec)
    b = TracedLock("order.b", recorder=rec)

    def worker():
        for _ in range(50):
            with a:
                with b:
                    pass

    _run_threads(worker, worker, worker)
    assert rec.cycles() == []
    assert rec.snapshot_edges() == {("order.a", "order.b"): 150}


def test_three_lock_cycle_detected():
    rec = LockOrderRecorder()
    locks = {n: TracedLock(f"tri.{n}", recorder=rec) for n in "abc"}

    def pair(x, y):
        with locks[x]:
            with locks[y]:
                pass

    pair("a", "b")
    pair("b", "c")
    pair("c", "a")
    cycles = rec.cycles()
    assert len(cycles) == 1
    assert set(cycles[0]) == {"tri.a", "tri.b", "tri.c"}


def test_reentrant_acquire_no_self_edge():
    rec = LockOrderRecorder()
    r = TracedLock("re.lock", reentrant=True, recorder=rec)
    with r:
        with r:
            pass
    assert rec.cycles() == []
    assert rec.snapshot_edges() == {}
    # both nesting levels accounted as holds
    assert rec.held_stats()["re.lock"]["count"] == 2


def test_hand_over_hand_release_order():
    rec = LockOrderRecorder()
    a = TracedLock("hoh.a", recorder=rec)
    b = TracedLock("hoh.b", recorder=rec)
    a.acquire()
    b.acquire()
    a.release()  # release order != acquire order
    b.release()
    assert rec.cycles() == []
    stats = rec.held_stats()
    assert stats["hoh.a"]["count"] == 1 and stats["hoh.b"]["count"] == 1


def test_held_histogram_exported_through_obs_registry():
    rec = LockOrderRecorder()
    lock = TracedLock("hist.lock", recorder=rec)
    for _ in range(5):
        with lock:
            pass
    snap = rec.registry.snapshot()
    series = snap["avdb_lock_held_seconds"]
    (entry,) = [e for e in series if e["labels"] == {"lock": "hist.lock"}]
    assert entry["count"] == 5
    assert "avdb_lock_held_seconds_bucket" in rec.render_prometheus()


def test_report_shape_and_reset():
    rec = LockOrderRecorder()
    a = TracedLock("rep.a", recorder=rec)
    with a:
        pass
    rep = rec.report()
    assert rep["locks"] == ["rep.a"]
    assert rep["cycles"] == []
    assert rep["held"]["rep.a"]["count"] == 1
    rec.reset()
    assert rec.report() == {
        "locks": [], "edges": {}, "cycles": [], "held": {},
    }


# ---------------------------------------------------------------------------
# serve battery under AVDB_LOCK_TRACE=1


def _tiny_store(store_dir: str) -> int:
    from annotatedvdb_tpu.loaders.lookup import identity_hashes
    from annotatedvdb_tpu.store import VariantStore
    from annotatedvdb_tpu.types import encode_allele_array

    width = 8
    store = VariantStore(width=width)
    n = 64
    refs = ["A", "C", "G", "T"] * (n // 4)
    alts = ["G", "T", "A", "C"] * (n // 4)
    ref, ref_len = encode_allele_array(refs, width)
    alt, alt_len = encode_allele_array(alts, width)
    h = identity_hashes(width, ref, alt, ref_len, alt_len, refs, alts)
    store.shard(8).append(
        {"pos": np.arange(1000, 1000 + 97 * n, 97, dtype=np.int32)[:n],
         "h": h, "ref_len": ref_len, "alt_len": alt_len},
        ref, alt,
        annotations={"cadd_scores": [
            {"CADD_phred": float(i)} if i % 2 else None for i in range(n)
        ]},
    )
    store.save(store_dir)
    return n


@pytest.fixture()
def traced_recorder(monkeypatch):
    """Arm tracing on the GLOBAL recorder for a serve-stack build."""
    monkeypatch.setenv("AVDB_LOCK_TRACE", "1")
    RECORDER.reset()
    yield RECORDER
    RECORDER.reset()


def test_serve_battery_traces_clean(tmp_path, traced_recorder):
    """The real serve stack's hot paths — point batching, bulk lookup,
    region reads (index build + LRU), admission accounting, snapshot
    refresh — driven concurrently under tracing: the acquisition-order
    graph must be acyclic, and the stack's named locks must actually
    show up (an empty graph would mean the battery proved nothing)."""
    from annotatedvdb_tpu.obs.metrics import MetricsRegistry
    from annotatedvdb_tpu.serve.batcher import QueryBatcher
    from annotatedvdb_tpu.serve.engine import QueryEngine
    from annotatedvdb_tpu.serve.http import ServeContext
    from annotatedvdb_tpu.serve.snapshot import SnapshotManager

    store_dir = str(tmp_path / "store")
    _tiny_store(store_dir)
    manager = SnapshotManager(store_dir)
    registry = MetricsRegistry()
    engine = QueryEngine(manager, registry=registry, region_cache_size=8)
    batcher = QueryBatcher(engine, max_batch=16, max_wait_s=0.001,
                           registry=registry)
    ctx = ServeContext(manager, engine, batcher, registry)
    try:
        errors: list = []

        def hammer(salt: int):
            try:
                for i in range(20):
                    pos = 1000 + 97 * ((i + salt) % 64)
                    ref = ["A", "C", "G", "T"][(i + salt) % 4]
                    alt = ["G", "T", "A", "C"][(i + salt) % 4]
                    batcher.submit(f"8:{pos}:{ref}:{alt}")
                    engine.lookup_many(
                        [f"8:{1000 + 97 * j}:A:G" for j in range(4)]
                    )
                    engine.region("8:1-100000", limit=5,
                                  min_cadd=1.0 if i % 2 else None)
                    assert ctx.admit()
                    ctx.observe("point", 0.001, rows=1)
                    ctx.release()
                    ctx.refresh_snapshot()
            except Exception as err:  # surfaced below, not swallowed
                errors.append(err)

        _run_threads(*(lambda s=s: hammer(s) for s in range(4)))
        assert not errors, errors
    finally:
        batcher.close()
    rep = traced_recorder.report()
    assert rep["cycles"] == [], rep
    seen = set(rep["locks"])
    assert {"serve.engine.cache", "serve.batcher.stats",
            "serve.ctx.inflight", "serve.snapshot.pin"} <= seen, seen
    assert rep["held"]["serve.ctx.inflight"]["count"] >= 80
