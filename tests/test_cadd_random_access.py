"""BGZF random access + indexed CADD subset joins (the tabix equivalent,
``cadd_updater.py:167-184``)."""

import gzip
import os
import random

import numpy as np
import pytest

from annotatedvdb_tpu.io.bgzf import (
    BgzfReader,
    BgzfWriter,
    compress_to_bgzf,
    is_bgzf,
)
from annotatedvdb_tpu.io.cadd import CaddIndex, open_random
from annotatedvdb_tpu.loaders import TpuVcfLoader
from annotatedvdb_tpu.loaders.cadd_loader import TpuCaddUpdater
from annotatedvdb_tpu.store import AlgorithmLedger, VariantStore

BASES = "ACGT"


def make_snv_table(n_pos=20000, seed=3):
    """Sorted SNV rows (3 alts per position) across chr1 + chr2."""
    rng = random.Random(seed)
    lines = ["## CADD GRCh38-v1.7", "#Chrom\tPos\tRef\tAlt\tRawScore\tPHRED"]
    rows = {}
    for chrom in ("1", "2"):
        pos = 50
        for _ in range(n_pos // 2):
            pos += rng.randint(1, 9)
            ref = BASES[rng.randrange(4)]
            for k, alt in enumerate(b for b in BASES if b != ref):
                raw = round(rng.random() * 5, 3)
                lines.append(
                    f"{chrom}\t{pos}\t{ref}\t{alt}\t{raw}\t{raw * 10:.2f}"
                )
                rows[(chrom, pos, ref, alt)] = (raw, round(raw * 10, 2))
    return "\n".join(lines) + "\n", rows


def test_bgzf_roundtrip_and_seek(tmp_path):
    text, _ = make_snv_table(4000)
    path = str(tmp_path / "t.tsv.bgz")
    with BgzfWriter(path) as w:
        w.write(text.encode())
    assert is_bgzf(path)
    # full streaming read reproduces the text
    with BgzfReader(path) as r:
        r.seek(0)
        got = []
        while True:
            line = r.readline()
            if not line:
                break
            got.append(line)
    assert b"".join(got).decode() == text
    # virtual-offset seek resumes mid-file exactly
    with BgzfReader(path) as r:
        r.seek(0)
        for _ in range(100):
            r.readline()
        voff = r.tell()
        want = r.readline()
        r2_bytes_before = r.bytes_read
        r.seek(voff)
        assert r.readline() == want
        # the re-read came from the block cache: no extra compressed bytes
        assert r.bytes_read == r2_bytes_before


def test_plain_gzip_rejected(tmp_path):
    p = tmp_path / "plain.tsv.gz"
    with gzip.open(p, "wt") as f:
        f.write("1\t100\tA\tC\t0.1\t1.0\n")
    assert not is_bgzf(str(p))
    with pytest.raises(ValueError, match="not seekable"):
        open_random(str(p))


def test_compress_to_bgzf_and_index_fetch(tmp_path):
    text, rows = make_snv_table(20000)
    plain = tmp_path / "snv.tsv"
    plain.write_text(text)
    bgz = compress_to_bgzf(str(plain))
    index = CaddIndex.build(bgz, stride=256)
    assert CaddIndex.load(bgz) is not None
    # fetch returns exactly the file's rows for a position, in file order
    some = [k for k in rows if k[0] == "2"][:50] + [k for k in rows][:50]
    with open_random(bgz) as reader:
        for chrom, pos, ref, alt in some:
            got = index.fetch(reader, int(chrom), pos)
            assert (ref, alt, *rows[(chrom, pos, ref, alt)]) in [
                (r, a, raw, ph) for r, a, raw, ph in got
            ]
            assert all(gr[0] == ref for gr in got)  # same site, same ref
        # absent position -> no rows
        assert index.fetch(reader, 1, 49) == []
    # stale index detection: table rewritten -> load refuses
    plain.write_text(text + "1\t999999\tA\tC\t0.1\t1.0\n")
    compress_to_bgzf(str(plain), bgz)
    assert CaddIndex.load(bgz) is None


def test_random_access_subset_matches_sequential_and_reads_less(tmp_path):
    text, rows = make_snv_table(20000)
    db = tmp_path / "cadd"
    db.mkdir()
    plain = db / "snv.tsv"
    plain.write_text(text)
    bgz_path = str(db / "whole_genome_SNVs.tsv.gz")
    with BgzfWriter(bgz_path) as w:  # .gz name, BGZF content (like CADD)
        w.write(text.encode())
    CaddIndex.build(bgz_path, stride=512)
    table_size = os.path.getsize(bgz_path)

    # store with 100 variants drawn from the table (plus 5 unmatched)
    picks = [k for i, k in enumerate(rows) if i % 117 == 0][:100]
    vcf_lines = ["##fileformat=VCFv4.2",
                 "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO"]
    entries = sorted(picks) + [("1", 13, "A", "C"), ("2", 17, "G", "T")]
    entries.sort(key=lambda e: (e[0], e[1]))
    seen = set()
    for chrom, pos, ref, alt in entries:
        if (chrom, pos) in seen:
            continue  # one alt per site keeps expected counts simple
        seen.add((chrom, pos))
        vcf_lines.append(f"{chrom}\t{pos}\t.\t{ref}\t{alt}\t.\t.\t.")
    vcf = tmp_path / "v.vcf"
    vcf.write_text("\n".join(vcf_lines) + "\n")

    def load_store():
        store = VariantStore(width=16)
        ledger = AlgorithmLedger(str(tmp_path / f"l{load_store.n}.jsonl"))
        load_store.n += 1
        TpuVcfLoader(store, ledger, log=lambda *a: None).load_file(
            str(vcf), commit=True
        )
        return store, ledger

    load_store.n = 0

    # sequential whole-table pass (ground truth)
    s1, l1 = load_store()
    subsets1 = {c: np.arange(s1.shard(c).n) for c in s1.shards}
    for c in s1.shards:
        s1.shard(c).compact()
    TpuCaddUpdater(s1, l1, str(db), log=lambda *a: None).update_all(
        commit=True, subsets=subsets1, random_access=False
    )

    # random-access subset join
    s2, l2 = load_store()
    for c in s2.shards:
        s2.shard(c).compact()
    subsets2 = {c: np.arange(s2.shard(c).n) for c in s2.shards}
    u2 = TpuCaddUpdater(s2, l2, str(db), log=lambda *a: None)
    counters = u2.update_all(commit=True, subsets=subsets2, random_access=True)

    # identical evidence row-for-row
    for c in s1.shards:
        a, b = s1.shard(c), s2.shard(c)
        for i in range(a.n):
            assert a.get_ann("cadd_scores", i) == b.get_ann("cadd_scores", i)
    assert counters["update"] > 50
    assert counters["not_matched"] >= 2
    # the point of the index: a 100-variant update reads a small fraction
    # of the table
    assert counters["bytes_read"] < table_size / 2, (
        f"read {counters['bytes_read']} of {table_size}"
    )


def test_random_access_requires_index(tmp_path):
    db = tmp_path / "cadd"
    db.mkdir()
    with BgzfWriter(str(db / "whole_genome_SNVs.tsv.gz")) as w:
        w.write(b"#Chrom\tPos\tRef\tAlt\tRawScore\tPHRED\n1\t100\tA\tC\t1\t10\n")
    store = VariantStore(width=16)
    ledger = AlgorithmLedger(str(tmp_path / "l.jsonl"))
    u = TpuCaddUpdater(store, ledger, str(db), log=lambda *a: None)
    with pytest.raises(ValueError, match="block-offset index"):
        u.update_all(commit=False, subsets={}, random_access=True)
