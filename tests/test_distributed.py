"""Multi-device sharding tests on the virtual 8-device CPU mesh."""

import numpy as np
import pytest


def test_dryrun_multichip_8():
    import __graft_entry__

    __graft_entry__.dryrun_multichip(8)


def test_entry_compiles():
    import jax

    import __graft_entry__

    fn, args = __graft_entry__.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    assert np.asarray(out.end_location).shape == args[1].shape


def test_distributed_counts_match_single_device(rng):
    """Global psum class counts equal a single-device run (lossless capacity)."""
    import jax
    from annotatedvdb_tpu.parallel import make_mesh, distributed_annotate_step
    from annotatedvdb_tpu.types import VariantBatch
    from conftest import random_variants

    mesh = make_mesh(4)
    batch = VariantBatch.from_tuples(random_variants(rng, 256), width=24)
    # default capacity is lossless: no drops, exact count parity required
    ann, row_id, counts, dropped, n_fallback = distributed_annotate_step(
        mesh, batch
    )
    assert int(np.asarray(dropped)) == 0
    assert int(np.asarray(n_fallback)) == 0
    assert int(np.asarray(counts).sum()) == batch.n
    from annotatedvdb_tpu.models.pipeline import annotate_batch

    single = annotate_batch(batch)
    want = np.bincount(np.asarray(single.variant_class), minlength=8)
    np.testing.assert_array_equal(np.asarray(counts), want)


def test_reshard_routes_to_owner(rng):
    """After the all_to_all, every valid row sits on its owning shard."""
    import jax
    import jax.numpy as jnp
    from annotatedvdb_tpu.parallel.distributed import shard_map
    from jax.sharding import PartitionSpec as P
    from annotatedvdb_tpu.parallel import make_mesh, reshard_by_owner
    from annotatedvdb_tpu.parallel.distributed import chromosome_owner
    from annotatedvdb_tpu.types import VariantBatch
    from conftest import random_variants

    n_shards, capacity = 4, 64
    mesh = make_mesh(n_shards)
    batch = VariantBatch.from_tuples(random_variants(rng, 256), width=24)

    @lambda f: shard_map(
        f, mesh=mesh, in_specs=(P("shard"),), out_specs=(P("shard"), P("shard"), P()),
        check_vma=False,
    )
    def route(chrom):
        owner = chromosome_owner(chrom, n_shards)
        (received,), valid, dropped = reshard_by_owner(
            owner, (chrom,), n_shards, capacity
        )
        return received, valid, dropped

    received, valid, dropped = route(batch.chrom)
    assert int(np.asarray(dropped)) == 0
    received = np.asarray(received).reshape(n_shards, n_shards * capacity)
    valid = np.asarray(valid).reshape(n_shards, n_shards * capacity)
    from annotatedvdb_tpu.parallel.distributed import chromosome_owner_table

    table = np.asarray(chromosome_owner_table(n_shards))
    for shard in range(n_shards):
        chroms = received[shard][valid[shard]]
        assert len(chroms) > 0
        np.testing.assert_array_equal(table[chroms.astype(np.int32)], shard)
    # every input row arrived somewhere
    assert valid.sum() == batch.n


def test_position_block_owner_spreads_sorted_input():
    """Chromosome-sorted input (the adversarial case for chromosome routing)
    spreads across all shards with near-minimal exchange capacity."""
    from annotatedvdb_tpu.parallel.distributed import (
        exact_capacity,
        position_block_owner,
    )

    n_shards, n = 8, 1 << 13
    chrom = np.full(n, 22, np.int8)
    pos = np.sort(np.random.default_rng(3).integers(1, 50_000_000, n)).astype(
        np.int32
    )
    owner = position_block_owner(chrom, pos, n_shards)
    # all shards participate, and no shard owns more than ~2x its fair share
    counts = np.bincount(owner, minlength=n_shards)
    assert (counts > 0).all()
    assert counts.max() <= 2 * n / n_shards
    # exchange slots stay near fair share, not the lossless worst case
    assert exact_capacity(owner, n_shards) <= 2 * (n // n_shards) // n_shards * 4


def test_balanced_owner_assignment():
    """Chromosome->shard packing stays within 1.5x genome-length imbalance
    (replacing the contiguous-block layout's ~5x chr1+chr2 skew; the
    reference shuffles chromosome order for the same reason,
    load_cadd_scores.py:306)."""
    from annotatedvdb_tpu.genome.assemblies import chromosome_lengths
    from annotatedvdb_tpu.parallel.distributed import chromosome_owner_table

    lengths = chromosome_lengths("GRCh38")
    for n_shards in (2, 4, 8):
        table = chromosome_owner_table(n_shards)
        load = [0] * n_shards
        for code, length in lengths.items():
            load[table[code]] += length
        assert max(load) <= 1.5 * (sum(load) / n_shards), (
            f"{n_shards} shards: imbalance {max(load) * n_shards / sum(load):.2f}x"
        )
        # every chromosome assigned within range
        assert all(0 <= table[c] < n_shards for c in lengths)


def test_insert_step_verdicts_match_single_device_loader(tmp_path):
    """The mesh insert step's dedup + membership verdicts equal the
    single-device loader's host-side counts on the same input (VERDICT r3
    #4: duplicate detection and store probes previously serialized on the
    host after device fan-in)."""
    from annotatedvdb_tpu.io.synth import synthetic_batch
    from annotatedvdb_tpu.loaders import TpuVcfLoader
    from annotatedvdb_tpu.ops.hashing import allele_hash_jit
    from annotatedvdb_tpu.parallel import make_mesh
    from annotatedvdb_tpu.parallel.device_store import build_device_shard_store
    from annotatedvdb_tpu.parallel.distributed import distributed_insert_step
    from annotatedvdb_tpu.store import VariantStore

    n_devices, n = 8, 256
    batch = synthetic_batch(n, width=16, seed=11)
    # in-batch duplicates: 6 rows repeated; store duplicates: 10 preloaded
    for f in batch._fields:
        getattr(batch, f)[10:16] = getattr(batch, f)[0:6]
    store = VariantStore(width=16)
    h = np.asarray(allele_hash_jit(
        batch.ref[20:30], batch.alt[20:30],
        batch.ref_len[20:30], batch.alt_len[20:30],
    ))
    for code in np.unique(batch.chrom[20:30]):
        rows = np.where(batch.chrom[20:30] == code)[0] + 20
        store.shard(int(code)).append(
            {"pos": batch.pos[rows], "h": h[rows - 20],
             "ref_len": batch.ref_len[rows], "alt_len": batch.alt_len[rows]},
            batch.ref[rows], batch.alt[rows],
        )

    mesh = make_mesh(n_devices)
    dev_store = build_device_shard_store(store, n_devices)
    ann, rid, flags, counters = distributed_insert_step(
        mesh, batch, dev_store=dev_store
    )
    n_batch_dup = int(np.asarray(counters["n_batch_dup"]))
    n_store_dup = int(np.asarray(counters["n_store_dup"]))
    n_new = int(np.asarray(counters["class_counts"]).sum())
    n_fb = int(np.asarray(counters["n_fallback"]))
    assert n_batch_dup == 6
    assert n_store_dup == 10
    assert n_new + n_batch_dup + n_store_dup + n_fb == n
    assert int(np.asarray(counters["n_dropped"])) == 0

    # single-device ground truth: run the host loader's dedup+membership
    # over the same batch against the same (pre-mesh) store
    from annotatedvdb_tpu.io.synth import batch_chunk
    from annotatedvdb_tpu.store import AlgorithmLedger

    ledger = AlgorithmLedger(str(tmp_path / "l.jsonl"))
    loader = TpuVcfLoader(store, ledger, log=lambda *a: None)
    chunk = batch_chunk(batch)
    loader._load_chunk(chunk, alg_id=1, commit=True, resume_line=0,
                       mapping_fh=None)
    assert loader.counters["duplicates"] == n_batch_dup + n_store_dup
    assert loader.counters["variant"] == n_new + n_fb  # host inserts
    # fallback rows too (width-16 synth has none over width)
    assert n_fb == 0


def test_insert_step_without_store_snapshot():
    """No dev_store: membership flags all-false, dedup still runs."""
    from annotatedvdb_tpu.io.synth import synthetic_batch
    from annotatedvdb_tpu.parallel import make_mesh
    from annotatedvdb_tpu.parallel.distributed import distributed_insert_step

    batch = synthetic_batch(128, width=16, seed=3)
    for f in batch._fields:
        getattr(batch, f)[4:8] = getattr(batch, f)[0:4]
    mesh = make_mesh(8)
    _ann, _rid, flags, counters = distributed_insert_step(mesh, batch)
    assert int(np.asarray(counters["n_batch_dup"])) == 4
    assert int(np.asarray(counters["n_store_dup"])) == 0
    assert not np.asarray(flags["in_store"]).any()


def test_insert_step_overlapping_verdicts_stay_disjoint(tmp_path):
    """A row that is BOTH an in-batch duplicate and present in the store
    counts once (as the in-batch dup, matching host-loader order), so the
    conservation identity holds on overlapping data."""
    from annotatedvdb_tpu.io.synth import synthetic_batch
    from annotatedvdb_tpu.ops.hashing import allele_hash_jit
    from annotatedvdb_tpu.parallel import make_mesh
    from annotatedvdb_tpu.parallel.device_store import build_device_shard_store
    from annotatedvdb_tpu.parallel.distributed import distributed_insert_step
    from annotatedvdb_tpu.store import VariantStore

    n = 128
    batch = synthetic_batch(n, width=16, seed=17)
    # rows [0:4) duplicated at [4:8); rows [0:8) ALSO preloaded in store
    for f in batch._fields:
        getattr(batch, f)[4:8] = getattr(batch, f)[0:4]
    store = VariantStore(width=16)
    h = np.asarray(allele_hash_jit(
        batch.ref[:8], batch.alt[:8], batch.ref_len[:8], batch.alt_len[:8]
    ))
    for code in np.unique(batch.chrom[:8]):
        rows = np.where(batch.chrom[:8] == code)[0]
        store.shard(int(code)).append(
            {"pos": batch.pos[rows], "h": h[rows],
             "ref_len": batch.ref_len[rows], "alt_len": batch.alt_len[rows]},
            batch.ref[rows], batch.alt[rows],
        )
    mesh = make_mesh(8)
    _ann, _rid, flags, c = distributed_insert_step(
        mesh, batch, dev_store=build_device_shard_store(store, 8)
    )
    n_batch_dup = int(np.asarray(c["n_batch_dup"]))
    n_store_dup = int(np.asarray(c["n_store_dup"]))
    n_new = int(np.asarray(c["class_counts"]).sum())
    # the 4 later copies are in-batch dups (even though they are ALSO in
    # the store — counted once); the 4 first copies are store dups
    assert n_batch_dup == 4
    assert n_store_dup == 4
    assert n_new + n_batch_dup + n_store_dup == n
    assert not (np.asarray(flags["dup_batch"]) & np.asarray(flags["in_store"])).any()
