"""Multi-device sharding tests on the virtual 8-device CPU mesh."""

import numpy as np
import pytest


def test_dryrun_multichip_8():
    import __graft_entry__

    __graft_entry__.dryrun_multichip(8)


def test_entry_compiles():
    import jax

    import __graft_entry__

    fn, args = __graft_entry__.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    assert np.asarray(out.end_location).shape == args[1].shape


def test_distributed_counts_match_single_device(rng):
    """Global psum class counts equal a single-device run (lossless capacity)."""
    import jax
    from annotatedvdb_tpu.parallel import make_mesh, distributed_annotate_step
    from annotatedvdb_tpu.types import VariantBatch
    from conftest import random_variants

    mesh = make_mesh(4)
    batch = VariantBatch.from_tuples(random_variants(rng, 256), width=24)
    # lossless capacity: no drops, exact count parity required
    ann, valid, counts, dropped, n_fallback = distributed_annotate_step(
        mesh, batch, capacity=batch.n // 4
    )
    assert int(np.asarray(dropped)) == 0
    assert int(np.asarray(n_fallback)) == 0
    assert int(np.asarray(counts).sum()) == batch.n
    from annotatedvdb_tpu.models.pipeline import AnnotationPipeline

    single = AnnotationPipeline().run(batch)
    want = np.bincount(np.asarray(single.variant_class), minlength=8)
    np.testing.assert_array_equal(np.asarray(counts), want)


def test_reshard_routes_to_owner(rng):
    """After the all_to_all, every valid row sits on its owning shard."""
    import jax
    import jax.numpy as jnp
    from jax import shard_map
    from jax.sharding import PartitionSpec as P
    from annotatedvdb_tpu.parallel import make_mesh, reshard_by_owner
    from annotatedvdb_tpu.parallel.distributed import chromosome_owner
    from annotatedvdb_tpu.types import VariantBatch
    from conftest import random_variants

    n_shards, capacity = 4, 64
    mesh = make_mesh(n_shards)
    batch = VariantBatch.from_tuples(random_variants(rng, 256), width=24)

    @lambda f: shard_map(
        f, mesh=mesh, in_specs=(P("shard"),), out_specs=(P("shard"), P("shard"), P()),
        check_vma=False,
    )
    def route(chrom):
        owner = chromosome_owner(chrom, n_shards)
        (received,), valid, dropped = reshard_by_owner(
            owner, (chrom,), n_shards, capacity
        )
        return received, valid, dropped

    received, valid, dropped = route(batch.chrom)
    assert int(np.asarray(dropped)) == 0
    received = np.asarray(received).reshape(n_shards, n_shards * capacity)
    valid = np.asarray(valid).reshape(n_shards, n_shards * capacity)
    per = -(-25 // n_shards)
    for shard in range(n_shards):
        chroms = received[shard][valid[shard]]
        assert len(chroms) > 0
        np.testing.assert_array_equal((chroms.astype(np.int32) - 1) // per, shard)
    # every input row arrived somewhere
    assert valid.sum() == batch.n
