"""Multi-device sharding tests on the virtual 8-device CPU mesh."""

import numpy as np
import pytest


def test_dryrun_multichip_8():
    import __graft_entry__

    __graft_entry__.dryrun_multichip(8)


def test_entry_compiles():
    import jax

    import __graft_entry__

    fn, args = __graft_entry__.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    assert np.asarray(out.end_location).shape == args[1].shape


def test_distributed_counts_match_single_device(rng):
    """Global psum class counts equal a single-device run (lossless capacity)."""
    import jax
    from annotatedvdb_tpu.parallel import make_mesh, distributed_annotate_step
    from annotatedvdb_tpu.types import VariantBatch
    from conftest import random_variants

    mesh = make_mesh(4)
    batch = VariantBatch.from_tuples(random_variants(rng, 256), width=24)
    # default capacity is lossless: no drops, exact count parity required
    ann, row_id, counts, dropped, n_fallback = distributed_annotate_step(
        mesh, batch
    )
    assert int(np.asarray(dropped)) == 0
    assert int(np.asarray(n_fallback)) == 0
    assert int(np.asarray(counts).sum()) == batch.n
    from annotatedvdb_tpu.models.pipeline import annotate_batch

    single = annotate_batch(batch)
    want = np.bincount(np.asarray(single.variant_class), minlength=8)
    np.testing.assert_array_equal(np.asarray(counts), want)


def test_reshard_routes_to_owner(rng):
    """After the all_to_all, every valid row sits on its owning shard."""
    import jax
    import jax.numpy as jnp
    from jax import shard_map
    from jax.sharding import PartitionSpec as P
    from annotatedvdb_tpu.parallel import make_mesh, reshard_by_owner
    from annotatedvdb_tpu.parallel.distributed import chromosome_owner
    from annotatedvdb_tpu.types import VariantBatch
    from conftest import random_variants

    n_shards, capacity = 4, 64
    mesh = make_mesh(n_shards)
    batch = VariantBatch.from_tuples(random_variants(rng, 256), width=24)

    @lambda f: shard_map(
        f, mesh=mesh, in_specs=(P("shard"),), out_specs=(P("shard"), P("shard"), P()),
        check_vma=False,
    )
    def route(chrom):
        owner = chromosome_owner(chrom, n_shards)
        (received,), valid, dropped = reshard_by_owner(
            owner, (chrom,), n_shards, capacity
        )
        return received, valid, dropped

    received, valid, dropped = route(batch.chrom)
    assert int(np.asarray(dropped)) == 0
    received = np.asarray(received).reshape(n_shards, n_shards * capacity)
    valid = np.asarray(valid).reshape(n_shards, n_shards * capacity)
    from annotatedvdb_tpu.parallel.distributed import chromosome_owner_table

    table = np.asarray(chromosome_owner_table(n_shards))
    for shard in range(n_shards):
        chroms = received[shard][valid[shard]]
        assert len(chroms) > 0
        np.testing.assert_array_equal(table[chroms.astype(np.int32)], shard)
    # every input row arrived somewhere
    assert valid.sum() == batch.n


def test_position_block_owner_spreads_sorted_input():
    """Chromosome-sorted input (the adversarial case for chromosome routing)
    spreads across all shards with near-minimal exchange capacity."""
    from annotatedvdb_tpu.parallel.distributed import (
        exact_capacity,
        position_block_owner,
    )

    n_shards, n = 8, 1 << 13
    chrom = np.full(n, 22, np.int8)
    pos = np.sort(np.random.default_rng(3).integers(1, 50_000_000, n)).astype(
        np.int32
    )
    owner = position_block_owner(chrom, pos, n_shards)
    # all shards participate, and no shard owns more than ~2x its fair share
    counts = np.bincount(owner, minlength=n_shards)
    assert (counts > 0).all()
    assert counts.max() <= 2 * n / n_shards
    # exchange slots stay near fair share, not the lossless worst case
    assert exact_capacity(owner, n_shards) <= 2 * (n // n_shards) // n_shards * 4


def test_balanced_owner_assignment():
    """Chromosome->shard packing stays within 1.5x genome-length imbalance
    (replacing the contiguous-block layout's ~5x chr1+chr2 skew; the
    reference shuffles chromosome order for the same reason,
    load_cadd_scores.py:306)."""
    from annotatedvdb_tpu.genome.assemblies import chromosome_lengths
    from annotatedvdb_tpu.parallel.distributed import chromosome_owner_table

    lengths = chromosome_lengths("GRCh38")
    for n_shards in (2, 4, 8):
        table = chromosome_owner_table(n_shards)
        load = [0] * n_shards
        for code, length in lengths.items():
            load[table[code]] += length
        assert max(load) <= 1.5 * (sum(load) / n_shards), (
            f"{n_shards} shards: imbalance {max(load) * n_shards / sum(load):.2f}x"
        )
        # every chromosome assigned within range
        assert all(0 <= table[c] < n_shards for c in lengths)
