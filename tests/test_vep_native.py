"""Native VEP transformer parity: the C++ fast path must produce the exact
store the pure-Python path produces — values compared after materializing
RawJson text back to Python objects."""

import json

import numpy as np
import pytest

from annotatedvdb_tpu.conseq import ConsequenceRanker
from annotatedvdb_tpu.loaders import TpuVcfLoader
from annotatedvdb_tpu.loaders.vep_loader import TpuVepLoader
from annotatedvdb_tpu.native import vep as native_vep
from annotatedvdb_tpu.store import AlgorithmLedger, VariantStore
from annotatedvdb_tpu.store.variant_store import JSONB_COLUMNS, RawJson

pytestmark = pytest.mark.skipif(
    not native_vep.available(), reason="no C++ toolchain for the native lib"
)

VCF = """##fileformat=VCFv4.2
#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO
1\t1000\trs1\tA\tG\t.\t.\tRS=1
1\t2000\trs2\tCA\tC\t.\t.\tRS=2
1\t3000\trs3\tT\tTA,TG\t.\t.\tRS=3
2\t4000\trs4\tG\tC\t.\t.\tRS=4
2\t5000\trs5\tGT\tG,GTT\t.\t.\tRS=5
X\t6000\trs6\tA\tT\t.\t.\tRS=6
"""

DOCS = [
    # plain SNV: consequences + multi-source frequencies
    {"input": "1\t1000\trs1\tA\tG", "most_severe_consequence": "missense_variant",
     "assembly_name": "GRCh38", "strand": 1,
     "transcript_consequences": [
         {"consequence_terms": ["missense_variant"], "variant_allele": "G",
          "impact": "MODERATE", "cadd_phred": 22.5,
          "domains": [{"db": "Pfam", "name": "PF0001"}]},
         {"consequence_terms": ["intron_variant"], "variant_allele": "G"},
         {"consequence_terms": ["missense_variant", "splice_region_variant"],
          "variant_allele": "G"}],
     "colocated_variants": [
         {"id": "rs1", "allele_string": "A/G",
          "frequencies": {"G": {"gnomad": 0.01, "af": 0.5, "aa": 0.125,
                                "gnomad_afr": 0.25, "ea": 0.0625}}}]},
    # deletion: '-'-keyed consequence + frequency
    {"input": "1\t2000\trs2\tCA\tC", "most_severe_consequence": "intron_variant",
     "transcript_consequences": [
         {"consequence_terms": ["intron_variant"], "variant_allele": "-"}],
     "regulatory_feature_consequences": [
         {"consequence_terms": ["regulatory_region_variant"],
          "variant_allele": "-"}],
     "colocated_variants": [
         {"id": "rs2", "allele_string": "CA/C",
          "frequencies": {"-": {"af": 0.25}}}]},
    # multi-allelic site: per-alt consequence split
    {"input": "1\t3000\trs3\tT\tTA,TG", "most_severe_consequence": "intron_variant",
     "transcript_consequences": [
         {"consequence_terms": ["intron_variant"], "variant_allele": "A"},
         {"consequence_terms": ["downstream_gene_variant"],
          "variant_allele": "G"}]},
    # COSMIC filter + id disambiguation across colocated variants
    {"input": "2\t4000\trs4\tG\tC", "most_severe_consequence": "intron_variant",
     "transcript_consequences": [
         {"consequence_terms": ["intron_variant"], "variant_allele": "C"}],
     "colocated_variants": [
         {"id": "COSV1", "allele_string": "COSMIC_MUTATION",
          "frequencies": {"C": {"af": 0.9}}},
         {"id": "rsOTHER", "allele_string": "G/C",
          "frequencies": {"C": {"af": 0.1}}},
         {"id": "rs4", "allele_string": "G/C",
          "frequencies": {"C": {"af": 0.2, "gnomad": 0.3}}}]},
    # multi-alt indels; one alt '.'-skipped in VEP output form
    {"input": "2\t5000\trs5\tGT\tG,GTT", "most_severe_consequence": "intron_variant",
     "transcript_consequences": [
         {"consequence_terms": ["intron_variant"], "variant_allele": "-"},
         {"consequence_terms": ["downstream_gene_variant"],
          "variant_allele": "T"}]},
    # doc with NO consequences for its allele and no frequencies
    {"input": "X\t6000\trs6\tA\tT", "most_severe_consequence": "intergenic_variant",
     "intergenic_consequences": [
         {"consequence_terms": ["intergenic_variant"], "variant_allele": "T"}]},
    # novel combo -> native fallback -> host learn-on-miss (both paths)
    {"input": "1\t1000\trs1\tA\tG",
     "most_severe_consequence": "splice_region_variant",
     "custom_key": {"from": "fallback_doc"},
     "motif_feature_consequences": [
         {"consequence_terms": ["splice_region_variant",
                                "non_coding_transcript_variant"],
          "variant_allele": "G"}]},
    # a NATIVE doc after the fallback doc, updating the SAME store row with
    # a conflicting vep_output key: deep-merge 'patch wins' makes the final
    # value order-sensitive, pinning the interleaved apply order
    {"input": "1\t1000\trs1\tA\tG",
     "most_severe_consequence": "intron_variant",
     "custom_key": {"from": "late_native_doc"},
     "transcript_consequences": [
         {"consequence_terms": ["intron_variant"], "variant_allele": "G"}]},
]


def _load(tmp_path, tag, native: bool, monkeypatch):
    monkeypatch.setenv("AVDB_NATIVE_VEP", "1" if native else "0")
    work = tmp_path / tag
    work.mkdir()
    vcf = work / "t.vcf"
    vcf.write_text(VCF)
    vep = work / "t.vep.json"
    vep.write_text("".join(json.dumps(d) + "\n" for d in DOCS))
    store = VariantStore(width=16)
    ledger = AlgorithmLedger(str(work / "l.jsonl"))
    TpuVcfLoader(store, ledger, log=lambda *a: None).load_file(
        str(vcf), commit=True
    )
    loader = TpuVepLoader(
        store, ledger, ConsequenceRanker(), datasource="dbSNP",
        log=lambda *a: None,
    )
    counters = loader.load_file(str(vep), commit=True)
    return store, counters


def _materialize(v):
    if isinstance(v, RawJson):
        return v.fresh()
    return v


def test_native_python_store_parity(tmp_path, monkeypatch):
    s_py, c_py = _load(tmp_path, "py", native=False, monkeypatch=monkeypatch)
    s_nat, c_nat = _load(tmp_path, "nat", native=True, monkeypatch=monkeypatch)
    for k in ("variant", "skipped", "update", "not_found", "line"):
        assert c_py[k] == c_nat[k], (k, c_py[k], c_nat[k])
    assert set(s_py.shards) == set(s_nat.shards)
    for code in s_py.shards:
        a, b = s_py.shard(code), s_nat.shard(code)
        a.compact(), b.compact()
        np.testing.assert_array_equal(a.cols["pos"], b.cols["pos"])
        for col in JSONB_COLUMNS:
            av, bv = a.annotations[col], b.annotations[col]
            for i in range(a.n):
                assert _materialize(av[i]) == _materialize(bv[i]), (
                    code, col, i, av[i], bv[i]
                )


def test_native_store_persists_and_reloads(tmp_path, monkeypatch):
    """RawJson values round-trip through save/load as plain dicts."""
    store, _ = _load(tmp_path, "persist", native=True, monkeypatch=monkeypatch)
    out = str(tmp_path / "persist" / "vdb")
    store.save(out)
    reloaded = VariantStore.load(out)
    for code in store.shards:
        a, b = store.shard(code), reloaded.shard(code)
        a.compact(), b.compact()
        for col in JSONB_COLUMNS:
            av, bv = a.annotations[col], b.annotations[col]
            for i in range(a.n):
                assert _materialize(av[i]) == bv[i], (code, col, i)


def test_pg_egress_splices_rawjson(tmp_path, monkeypatch):
    """COPY egress emits identical JSONB text content for both paths."""
    from annotatedvdb_tpu.io.pg_egress import export_store

    s_py, _ = _load(tmp_path, "epy", native=False, monkeypatch=monkeypatch)
    s_nat, _ = _load(tmp_path, "enat", native=True, monkeypatch=monkeypatch)
    d_py = tmp_path / "copy_py"
    d_nat = tmp_path / "copy_nat"
    export_store(s_py, str(d_py))
    export_store(s_nat, str(d_nat))
    for f in sorted(
        str(p.relative_to(d_py)) for p in d_py.rglob("*") if p.is_file()
    ):
        py_text = (d_py / f).read_text().splitlines()
        nat_text = (d_nat / f).read_text().splitlines()
        assert len(py_text) == len(nat_text), f
        for lp, ln in zip(py_text, nat_text):
            if lp == ln:
                continue
            # JSONB fields may differ in key order/whitespace only:
            # compare parsed per-field
            fp, fn = lp.split("\t"), ln.split("\t")
            assert len(fp) == len(fn), f
            for vp, vn in zip(fp, fn):
                if vp == vn:
                    continue
                assert json.loads(vp) == json.loads(vn), (f, vp, vn)

@pytest.mark.parametrize("seed", [20260730, 7, 991])
def test_fuzz_parity(tmp_path, monkeypatch, seed):
    """Seeded random docs — odd keys, unicode, escapes, numbers in exotic
    formats, missing blocks — through both paths; stores must match (docs
    the native parser rejects fall back, which is also parity)."""
    import random

    rng = random.Random(seed)
    terms_pool = ["missense_variant", "intron_variant", "stop_gained",
                  "synonymous_variant", "downstream_gene_variant",
                  "3_prime_UTR_variant", "NMD_transcript_variant"]
    bases = "ACGT"

    def rand_value(depth=0):
        r = rng.random()
        if depth > 2 or r < 0.3:
            return rng.choice([
                1, -2.5, 1e-7, 0.30000000000000004, True, False, None,
                "plain", "esc\taped", "uniécode", "q\"uote", 12345678901234,
            ])
        if r < 0.6:
            return {rng.choice(["a", "b", "weird key", "x\ty"]):
                    rand_value(depth + 1) for _ in range(rng.randint(0, 3))}
        return [rand_value(depth + 1) for _ in range(rng.randint(0, 3))]

    docs, vcf_rows = [], []
    for i in range(200):
        pos = 1000 + i * 10
        ref = rng.choice(bases) if rng.random() < 0.7 else "".join(
            rng.choice(bases) for _ in range(rng.randint(2, 5)))
        n_alts = rng.randint(1, 3)
        alts = []
        for _ in range(n_alts):
            a = rng.choice(bases) if rng.random() < 0.7 else "".join(
                rng.choice(bases) for _ in range(rng.randint(2, 5)))
            alts.append(a)
        alt_col = ",".join(alts)
        vcf_rows.append(f"1\t{pos}\trs{i}\t{ref}\t{alt_col}\t.\t.\t.")
        doc = {"input": f"1\t{pos}\trs{i}\t{ref}\t{alt_col}",
               "most_severe_consequence": rng.choice(terms_pool)}
        for ctype in ("transcript", "regulatory_feature", "motif_feature",
                      "intergenic"):
            if rng.random() < 0.6:
                conseqs = []
                for _ in range(rng.randint(0, 3)):
                    alt0 = rng.choice(alts)
                    p = 0
                    while p < min(len(ref), len(alt0)) and ref[p] == alt0[p]:
                        p += 1
                    norm = alt0[p:] or "-"
                    conseqs.append({
                        "consequence_terms": sorted(
                            {rng.choice(terms_pool)
                             for _ in range(rng.randint(1, 2))}),
                        "variant_allele": rng.choice([norm, alt0, "Z"]),
                        "extra": rand_value(),
                    })
                doc[ctype + "_consequences"] = conseqs
        if rng.random() < 0.5:
            covars = []
            for _ in range(rng.randint(1, 3)):
                cv = {"id": rng.choice([f"rs{i}", "rsX", "COSV9"]),
                      "allele_string": rng.choice(
                          [f"{ref}/{alts[0]}", "COSMIC_MUTATION"])}
                if rng.random() < 0.8:
                    alt0 = rng.choice(alts)
                    p = 0
                    while p < min(len(ref), len(alt0)) and ref[p] == alt0[p]:
                        p += 1
                    norm = alt0[p:] or "-"
                    cv["frequencies"] = {
                        rng.choice([norm, "T"]): {
                            rng.choice(["af", "aa", "gnomad", "gnomad_afr",
                                        "eas"]): rng.random()
                            for _ in range(rng.randint(1, 3))
                        }
                    }
                covars.append(cv)
            doc["colocated_variants"] = covars
        if rng.random() < 0.4:
            doc["junk_" + str(i)] = rand_value()
        docs.append(doc)

    vcf_text = ("##fileformat=VCFv4.2\n"
                "#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\n"
                + "\n".join(vcf_rows) + "\n")
    vep_text = "".join(json.dumps(d) + "\n" for d in docs)

    stores = {}
    for tag, native in (("py", False), ("nat", True)):
        monkeypatch.setenv("AVDB_NATIVE_VEP", "1" if native else "0")
        work = tmp_path / ("fuzz_" + tag)
        work.mkdir()
        (work / "t.vcf").write_text(vcf_text)
        (work / "t.vep.json").write_text(vep_text)
        store = VariantStore(width=16)
        ledger = AlgorithmLedger(str(work / "l.jsonl"))
        TpuVcfLoader(store, ledger, log=lambda *a: None).load_file(
            str(work / "t.vcf"), commit=True
        )
        loader = TpuVepLoader(
            store, ledger, ConsequenceRanker(), datasource="dbSNP",
            log=lambda *a: None, batch_size=64,  # multiple flushes
        )
        stores[tag] = (store, loader.load_file(str(work / "t.vep.json"),
                                               commit=True))
    s_py, c_py = stores["py"]
    s_nat, c_nat = stores["nat"]
    for k in ("variant", "skipped", "update", "not_found"):
        assert c_py[k] == c_nat[k], (k, c_py[k], c_nat[k])
    for code in s_py.shards:
        a, b = s_py.shard(code), s_nat.shard(code)
        a.compact(), b.compact()
        for col in JSONB_COLUMNS:
            av, bv = a.annotations[col], b.annotations[col]
            for i in range(a.n):
                assert _materialize(av[i]) == _materialize(bv[i]), (
                    code, col, i, av[i], bv[i]
                )


def test_native_hash_matches_python_identity():
    """The transformer's per-row identity hash must be the bit-exact twin
    of the device kernel over the width-bounded matrices, with over-width
    rows full-string re-hashed exactly like the loaders' _fnv32_str."""
    from annotatedvdb_tpu.loaders.vcf_loader import _fnv32_str
    from annotatedvdb_tpu.ops.hashing import allele_hash_np

    width = 8
    long_ref = "A" * 20
    docs = [
        {"input": "1\t100\trs1\tA\tG", "most_severe_consequence": "x",
         "transcript_consequences": [
             {"consequence_terms": ["intron_variant"],
              "variant_allele": "G"}]},
        {"input": f"1\t200\trs2\t{long_ref}\tA", "most_severe_consequence":
         "x", "transcript_consequences": [
             {"consequence_terms": ["intron_variant"],
              "variant_allele": "A"}]},
        {"input": "2\t300\trs3\tCA\tC,CTT", "most_severe_consequence": "x",
         "transcript_consequences": [
             {"consequence_terms": ["intron_variant"],
              "variant_allele": "-"}]},
    ]
    lines = [json.dumps(d) for d in docs]
    blob = native_vep.ranking_blob(ConsequenceRanker())
    res = native_vep.transform(lines, blob, True, width)
    assert res is not None and res.n_rows == 4
    want = allele_hash_np(res.ref, res.alt, res.ref_len, res.alt_len)
    over = (res.ref_len > width) | (res.alt_len > width)
    np.testing.assert_array_equal(res.host_fb.astype(bool), over)
    for i in range(res.n_rows):
        if over[i]:
            want[i] = _fnv32_str(
                bytes(res.text[res.ref_off[i]:res.ref_off[i]
                               + res.ref_slen[i]]).decode(),
                bytes(res.text[res.alt_off[i]:res.alt_off[i]
                               + res.alt_slen[i]]).decode(),
            )
    np.testing.assert_array_equal(res.hash, want)
