"""Native VEP transformer parity: the C++ fast path must produce the exact
store the pure-Python path produces — values compared after materializing
RawJson text back to Python objects."""

import json

import numpy as np
import pytest

from annotatedvdb_tpu.conseq import ConsequenceRanker
from annotatedvdb_tpu.loaders import TpuVcfLoader
from annotatedvdb_tpu.loaders.vep_loader import TpuVepLoader
from annotatedvdb_tpu.native import vep as native_vep
from annotatedvdb_tpu.store import AlgorithmLedger, VariantStore
from annotatedvdb_tpu.store.variant_store import JSONB_COLUMNS, RawJson

pytestmark = pytest.mark.skipif(
    not native_vep.available(), reason="no C++ toolchain for the native lib"
)

VCF = """##fileformat=VCFv4.2
#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO
1\t1000\trs1\tA\tG\t.\t.\tRS=1
1\t2000\trs2\tCA\tC\t.\t.\tRS=2
1\t3000\trs3\tT\tTA,TG\t.\t.\tRS=3
2\t4000\trs4\tG\tC\t.\t.\tRS=4
2\t5000\trs5\tGT\tG,GTT\t.\t.\tRS=5
X\t6000\trs6\tA\tT\t.\t.\tRS=6
"""

DOCS = [
    # plain SNV: consequences + multi-source frequencies
    {"input": "1\t1000\trs1\tA\tG", "most_severe_consequence": "missense_variant",
     "assembly_name": "GRCh38", "strand": 1,
     "transcript_consequences": [
         {"consequence_terms": ["missense_variant"], "variant_allele": "G",
          "impact": "MODERATE", "cadd_phred": 22.5,
          "domains": [{"db": "Pfam", "name": "PF0001"}]},
         {"consequence_terms": ["intron_variant"], "variant_allele": "G"},
         {"consequence_terms": ["missense_variant", "splice_region_variant"],
          "variant_allele": "G"}],
     "colocated_variants": [
         {"id": "rs1", "allele_string": "A/G",
          "frequencies": {"G": {"gnomad": 0.01, "af": 0.5, "aa": 0.125,
                                "gnomad_afr": 0.25, "ea": 0.0625}}}]},
    # deletion: '-'-keyed consequence + frequency
    {"input": "1\t2000\trs2\tCA\tC", "most_severe_consequence": "intron_variant",
     "transcript_consequences": [
         {"consequence_terms": ["intron_variant"], "variant_allele": "-"}],
     "regulatory_feature_consequences": [
         {"consequence_terms": ["regulatory_region_variant"],
          "variant_allele": "-"}],
     "colocated_variants": [
         {"id": "rs2", "allele_string": "CA/C",
          "frequencies": {"-": {"af": 0.25}}}]},
    # multi-allelic site: per-alt consequence split
    {"input": "1\t3000\trs3\tT\tTA,TG", "most_severe_consequence": "intron_variant",
     "transcript_consequences": [
         {"consequence_terms": ["intron_variant"], "variant_allele": "A"},
         {"consequence_terms": ["downstream_gene_variant"],
          "variant_allele": "G"}]},
    # COSMIC filter + id disambiguation across colocated variants
    {"input": "2\t4000\trs4\tG\tC", "most_severe_consequence": "intron_variant",
     "transcript_consequences": [
         {"consequence_terms": ["intron_variant"], "variant_allele": "C"}],
     "colocated_variants": [
         {"id": "COSV1", "allele_string": "COSMIC_MUTATION",
          "frequencies": {"C": {"af": 0.9}}},
         {"id": "rsOTHER", "allele_string": "G/C",
          "frequencies": {"C": {"af": 0.1}}},
         {"id": "rs4", "allele_string": "G/C",
          "frequencies": {"C": {"af": 0.2, "gnomad": 0.3}}}]},
    # multi-alt indels; one alt '.'-skipped in VEP output form
    {"input": "2\t5000\trs5\tGT\tG,GTT", "most_severe_consequence": "intron_variant",
     "transcript_consequences": [
         {"consequence_terms": ["intron_variant"], "variant_allele": "-"},
         {"consequence_terms": ["downstream_gene_variant"],
          "variant_allele": "T"}]},
    # doc with NO consequences for its allele and no frequencies
    {"input": "X\t6000\trs6\tA\tT", "most_severe_consequence": "intergenic_variant",
     "intergenic_consequences": [
         {"consequence_terms": ["intergenic_variant"], "variant_allele": "T"}]},
    # novel combo -> native fallback -> host learn-on-miss (both paths)
    {"input": "1\t1000\trs1\tA\tG",
     "most_severe_consequence": "splice_region_variant",
     "custom_key": {"from": "fallback_doc"},
     "motif_feature_consequences": [
         {"consequence_terms": ["splice_region_variant",
                                "non_coding_transcript_variant"],
          "variant_allele": "G"}]},
    # a NATIVE doc after the fallback doc, updating the SAME store row with
    # a conflicting vep_output key: deep-merge 'patch wins' makes the final
    # value order-sensitive, pinning the interleaved apply order
    {"input": "1\t1000\trs1\tA\tG",
     "most_severe_consequence": "intron_variant",
     "custom_key": {"from": "late_native_doc"},
     "transcript_consequences": [
         {"consequence_terms": ["intron_variant"], "variant_allele": "G"}]},
]


def _load(tmp_path, tag, native: bool, monkeypatch):
    monkeypatch.setenv("AVDB_NATIVE_VEP", "1" if native else "0")
    work = tmp_path / tag
    work.mkdir()
    vcf = work / "t.vcf"
    vcf.write_text(VCF)
    vep = work / "t.vep.json"
    vep.write_text("".join(json.dumps(d) + "\n" for d in DOCS))
    store = VariantStore(width=16)
    ledger = AlgorithmLedger(str(work / "l.jsonl"))
    TpuVcfLoader(store, ledger, log=lambda *a: None).load_file(
        str(vcf), commit=True
    )
    loader = TpuVepLoader(
        store, ledger, ConsequenceRanker(), datasource="dbSNP",
        log=lambda *a: None,
    )
    counters = loader.load_file(str(vep), commit=True)
    return store, counters


def _materialize(v):
    if isinstance(v, RawJson):
        return v.fresh()
    return v


def test_native_python_store_parity(tmp_path, monkeypatch):
    s_py, c_py = _load(tmp_path, "py", native=False, monkeypatch=monkeypatch)
    s_nat, c_nat = _load(tmp_path, "nat", native=True, monkeypatch=monkeypatch)
    for k in ("variant", "skipped", "update", "not_found", "line"):
        assert c_py[k] == c_nat[k], (k, c_py[k], c_nat[k])
    assert set(s_py.shards) == set(s_nat.shards)
    for code in s_py.shards:
        a, b = s_py.shard(code), s_nat.shard(code)
        a.compact(), b.compact()
        np.testing.assert_array_equal(a.cols["pos"], b.cols["pos"])
        for col in JSONB_COLUMNS:
            av, bv = a.annotations[col], b.annotations[col]
            for i in range(a.n):
                assert _materialize(av[i]) == _materialize(bv[i]), (
                    code, col, i, av[i], bv[i]
                )


def test_native_store_persists_and_reloads(tmp_path, monkeypatch):
    """RawJson values round-trip through save/load as plain dicts."""
    store, _ = _load(tmp_path, "persist", native=True, monkeypatch=monkeypatch)
    out = str(tmp_path / "persist" / "vdb")
    store.save(out)
    reloaded = VariantStore.load(out)
    for code in store.shards:
        a, b = store.shard(code), reloaded.shard(code)
        a.compact(), b.compact()
        for col in JSONB_COLUMNS:
            av, bv = a.annotations[col], b.annotations[col]
            for i in range(a.n):
                assert _materialize(av[i]) == bv[i], (code, col, i)


def test_pg_egress_splices_rawjson(tmp_path, monkeypatch):
    """COPY egress emits identical JSONB text content for both paths."""
    from annotatedvdb_tpu.io.pg_egress import export_store

    s_py, _ = _load(tmp_path, "epy", native=False, monkeypatch=monkeypatch)
    s_nat, _ = _load(tmp_path, "enat", native=True, monkeypatch=monkeypatch)
    d_py = tmp_path / "copy_py"
    d_nat = tmp_path / "copy_nat"
    export_store(s_py, str(d_py))
    export_store(s_nat, str(d_nat))
    for f in sorted(
        str(p.relative_to(d_py)) for p in d_py.rglob("*") if p.is_file()
    ):
        py_text = (d_py / f).read_text().splitlines()
        nat_text = (d_nat / f).read_text().splitlines()
        assert len(py_text) == len(nat_text), f
        for lp, ln in zip(py_text, nat_text):
            if lp == ln:
                continue
            # JSONB fields may differ in key order/whitespace only:
            # compare parsed per-field
            fp, fn = lp.split("\t"), ln.split("\t")
            assert len(fp) == len(fn), f
            for vp, vn in zip(fp, fn):
                if vp == vn:
                    continue
                assert json.loads(vp) == json.loads(vn), (f, vp, vn)