"""Unit suite for the batched BITS kernel (``ops/intervals``).

Every span answer is checked against a brute-force host oracle (per query:
count/locate matches by scanning the position array in plain Python), and
every bin token against the scalar closed-form oracle
(``oracle.binindex.closed_form_bin``) — the device kernel, the padded
device entry point, and the numpy host twin must all agree exactly.
"""

from __future__ import annotations

import random

import numpy as np
import pytest

from annotatedvdb_tpu.ops import intervals as iv
from annotatedvdb_tpu.oracle.binindex import closed_form_bin


def _brute_spans(pos: np.ndarray, starts, ends):
    """Oracle: [lo, hi) per query by linear scan (pos is sorted)."""
    lo, hi = [], []
    for s, e in zip(starts, ends):
        lo.append(sum(1 for p in pos.tolist() if p < s))
        hi.append(sum(1 for p in pos.tolist() if p <= e))
    return np.asarray(lo, np.int64), np.asarray(hi, np.int64)


def _random_case(rng, n_rows, n_queries, span=50_000):
    pos = np.sort(rng.integers(1, 5_000_000, n_rows).astype(np.int32))
    # force duplicate positions (multi-allelic sites) into the array
    if n_rows >= 8:
        pos[n_rows // 2] = pos[n_rows // 2 - 1]
        pos[-1] = pos[-2]
        pos = np.sort(pos)
    starts = rng.integers(1, 5_000_000, n_queries).astype(np.int64)
    ends = starts + rng.integers(0, span, n_queries)
    return pos, starts, ends


@pytest.mark.parametrize("n_rows,n_queries", [
    (0, 7), (1, 5), (37, 1), (100, 64), (1000, 257), (4096, 33),
])
def test_spans_match_brute_oracle(n_rows, n_queries):
    rng = np.random.default_rng(1208_3407 + n_rows + n_queries)
    pos, starts, ends = _random_case(rng, n_rows, n_queries)
    want_lo, want_hi = _brute_spans(pos, starts, ends)
    for fn in (iv.interval_spans, iv.interval_spans_host):
        lo, hi, _level, _leaf = fn(pos, starts, ends)
        assert np.array_equal(lo, want_lo), fn.__name__
        assert np.array_equal(hi, want_hi), fn.__name__


def test_device_and_host_paths_identical():
    rng = np.random.default_rng(7)
    pos, starts, ends = _random_case(rng, 513, 100)
    dev = iv.interval_spans(pos, starts, ends)
    host = iv.interval_spans_host(pos, starts, ends)
    for d, h in zip(dev, host):
        assert np.array_equal(np.asarray(d), np.asarray(h))


def test_boundary_semantics_inclusive():
    """1-based inclusive bounds: start == pos and end == pos both match
    (the single-region searchsorted contract)."""
    pos = np.asarray([100, 200, 200, 300], np.int32)
    lo, hi, _l, _b = iv.interval_spans_host(
        pos, [100, 201, 200, 299, 1], [100, 300, 200, 302, 99]
    )
    counts = (hi - lo).tolist()
    assert counts == [1, 1, 2, 1, 0]


def test_prepadded_device_pos_gives_same_spans():
    from annotatedvdb_tpu.utils.arrays import POS_SENTINEL, pad_pow2

    pos = np.asarray([5, 9, 9, 14, 77], np.int32)
    starts, ends = [1, 9, 50], [9, 9, 100]
    padded = pad_pow2(pos, POS_SENTINEL)
    a = iv.interval_spans(pos, starts, ends)
    b = iv.interval_spans(padded, starts, ends, pos_padded=True)
    for x, y in zip(a, b):
        assert np.array_equal(np.asarray(x), np.asarray(y))


def test_bin_tokens_match_scalar_oracle():
    rng = random.Random(2511_01555)
    starts = [rng.randint(1, 200_000_000) for _ in range(200)]
    ends = [s + rng.randint(0, 40_000_000) for s in starts]
    want = [closed_form_bin(s, e) for s, e in zip(starts, ends)]
    for fn in (iv.interval_spans, iv.interval_spans_host):
        _lo, _hi, level, leaf = fn(np.asarray([1], np.int32), starts, ends)
        got = list(zip(np.asarray(level).tolist(),
                       np.asarray(leaf).tolist()))
        assert got == want, fn.__name__


def test_absurd_bounds_clamp_identically():
    """Bounds past the int32 position range clamp the same way on both
    paths (store positions can never reach the clamp, so answers are
    unchanged — and the device kernel's int32 casts can never wrap)."""
    pos = np.asarray([10, 20], np.int32)
    big = iv.MAX_QUERY_POS + 10**10
    a = iv.interval_spans(pos, [1, big], [big, big])
    b = iv.interval_spans_host(pos, [1, big], [big, big])
    for x, y in zip(a, b):
        assert np.array_equal(np.asarray(x), np.asarray(y))
    assert (a[1] - a[0]).tolist() == [2, 0]


def test_count_only_is_span_width():
    """The count-only contract: ``hi - lo`` is the match count with no
    row materialization anywhere in the call."""
    pos = np.asarray([3, 5, 5, 5, 9], np.int32)
    lo, hi, _l, _b = iv.interval_spans_host(pos, [4], [8])
    assert int(hi[0] - lo[0]) == 3
