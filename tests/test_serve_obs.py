"""Request-scoped tracing + fleet telemetry through the serving stack.

The trace-id echo contract (W3C ``traceparent`` / ``X-Request-Id`` /
minted, byte-identical across BOTH front ends), stage attribution
through both batchers and the engine, trace-id propagation across a
paged cursor walk and a batched ``/regions`` panel, the WAL-fsync stage
of an upsert ack, the chaos-gated ``/debug/trace`` dump, the
``/metrics?fleet=1`` fleet view, and the lifecycle events (brownout,
breaker) the flight recorder keeps.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from annotatedvdb_tpu.loaders.lookup import identity_hashes
from annotatedvdb_tpu.obs.flight import FlightRecorder, decode_ring
from annotatedvdb_tpu.obs.metrics import MetricsRegistry
from annotatedvdb_tpu.serve import MemtableSnapshots, SnapshotManager
from annotatedvdb_tpu.serve.aio import build_aio_server
from annotatedvdb_tpu.serve.http import (
    build_server,
    resolve_trace_id,
)
from annotatedvdb_tpu.store import VariantStore
from annotatedvdb_tpu.store.memtable import Memtable
from annotatedvdb_tpu.store.wal import WriteAheadLog
from annotatedvdb_tpu.types import encode_allele_array
from test_serve import _build_store, _vid

WIDTH = 8


@pytest.fixture(scope="module")
def store(tmp_path_factory):
    store_dir = str(tmp_path_factory.mktemp("obs_store"))
    truth = _build_store(store_dir)
    return store_dir, truth


@pytest.fixture(scope="module")
def pair(store):
    """Both front ends over one store — the parity rig."""
    store_dir, _truth = store
    httpd = build_server(store_dir=store_dir, port=0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    aio = build_aio_server(store_dir=store_dir, port=0)
    aio.start_background()
    try:
        yield {
            "pt": httpd.server_address[1], "pa": aio.server_address[1],
            "ctx_t": httpd.ctx, "ctx_a": aio.ctx,
        }
    finally:
        aio.shutdown()
        aio.ctx.batcher.close()
        httpd.shutdown()
        httpd.server_close()
        httpd.ctx.batcher.close()


def _get(port, path, headers=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", headers=headers or {}
    )
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, r.read().decode(), dict(r.headers)
    except urllib.error.HTTPError as err:
        return err.code, err.read().decode(), dict(err.headers)


def _post(port, path, payload, headers=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(), method="POST",
        headers=headers or {},
    )
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, r.read().decode(), dict(r.headers)
    except urllib.error.HTTPError as err:
        return err.code, err.read().decode(), dict(err.headers)


def _records_for(ctx, tid):
    return [r for r in ctx.reqtrace.records() if r[0] == tid]


# ---------------------------------------------------------------------------
# trace-id grammar (the ONE shared resolver)


def test_resolve_trace_id_grammar():
    tp = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
    assert resolve_trace_id(tp, None) == "ab" * 16
    assert resolve_trace_id(tp, "client-id") == "ab" * 16  # W3C wins
    assert resolve_trace_id(None, "req-42_x") == "req-42_x"
    # sanitization: header-unsafe characters strip, length caps at 64
    assert resolve_trace_id(None, "a b\r\nc") == "abc"
    assert len(resolve_trace_id(None, "x" * 200)) == 64
    # malformed traceparent falls through; all-zero trace id is invalid
    assert resolve_trace_id("garbage", "fallback") == "fallback"
    assert resolve_trace_id("00-" + "0" * 32 + "-" + "cd" * 8 + "-01",
                            "fb") == "fb"
    # nothing usable: a fresh 128-bit id mints, unique per call
    a, b = resolve_trace_id(None, None), resolve_trace_id(None, None)
    assert len(a) == 32 and a != b
    int(a, 16)  # hex by construction


# ---------------------------------------------------------------------------
# header echo parity


def test_trace_header_echoes_byte_identical_on_both(store, pair):
    _store_dir, truth = store
    vid = _vid(truth[0])
    for hdrs, want in (
        ({"X-Request-Id": "abc-123"}, "abc-123"),
        ({"traceparent": "00-" + "ef" * 16 + "-" + "12" * 8 + "-00"},
         "ef" * 16),
    ):
        st, _bt, ht = _get(pair["pt"], f"/variant/{vid}", hdrs)
        sa, _ba, ha = _get(pair["pa"], f"/variant/{vid}", hdrs)
        assert st == sa == 200
        assert ht.get("X-Request-Id") == ha.get("X-Request-Id") == want
    # minted when absent: 32 hex chars on every route, errors included
    for path in (f"/variant/{vid}", "/variant/zzz", "/healthz",
                 "/nosuchroute"):
        _s, _b, ht = _get(pair["pt"], path)
        _s, _b, ha = _get(pair["pa"], path)
        assert len(ht.get("X-Request-Id", "")) == 32, path
        assert len(ha.get("X-Request-Id", "")) == 32, path


def test_stage_breakdown_recorded_per_point_request(store, pair):
    _store_dir, truth = store
    vid = _vid(truth[1])
    for port, ctx in ((pair["pt"], pair["ctx_t"]),
                      (pair["pa"], pair["ctx_a"])):
        tid = f"stages-{port}"
        status, _b, _h = _get(port, f"/variant/{vid}",
                              {"X-Request-Id": tid})
        assert status == 200
        recs = _records_for(ctx, tid)
        assert len(recs) == 1 and recs[0][1] == "point"
        stages = dict(recs[0][5])
        # the queue/device split comes from the batcher drain; the rest
        # from the front end
        assert set(stages) >= {"admission", "queue", "device", "render"}
        assert all(s >= 0 for s in stages.values())


# ---------------------------------------------------------------------------
# propagation: paged cursor walk + batched /regions panel


def test_cursor_walk_pages_share_the_trace_id(store, pair):
    tid = "walk-1"
    status, body, hdrs = _get(
        pair["pa"], "/region/8:1-3000000?limit=25&cursor=",
        {"X-Request-Id": tid},
    )
    assert status == 200
    assert hdrs.get("X-Request-Id") == tid
    pages = 1
    nxt = json.loads(body).get("next")
    while nxt and pages < 4:
        status, body, hdrs = _get(
            pair["pa"], f"/region/8:1-3000000?limit=25&cursor={nxt}",
            {"X-Request-Id": tid},
        )
        assert status == 200 and hdrs.get("X-Request-Id") == tid
        nxt = json.loads(body).get("next")
        pages += 1
    assert pages >= 2, "walk never continued: the fixture store shrank?"
    recs = _records_for(pair["ctx_a"], tid)
    assert len(recs) == pages
    for r in recs:
        assert r[1] == "region"
        assert any(name.startswith("region.chr8")
                   for name, _s in r[6]), r[6]


def test_regions_panel_intervals_share_the_trace_id(store, pair):
    body = {"regions": ["8:400-600", "8:119000-121000", "1:400-600"],
            "limit": 10}
    for port, ctx in ((pair["pt"], pair["ctx_t"]),
                      (pair["pa"], pair["ctx_a"])):
        tid = f"panel-{port}"
        status, _b, hdrs = _post(port, "/regions", body,
                                 {"X-Request-Id": tid})
        assert status == 200
        assert hdrs.get("X-Request-Id") == tid
        recs = _records_for(ctx, tid)
        assert len(recs) == 1 and recs[0][1] == "regions"
        span_names = {name for name, _s in recs[0][6]}
        # every touched chromosome group's span hangs off the PANEL's id
        assert {"regions.chr8", "regions.chr1"} <= span_names
        stages = dict(recs[0][5])
        assert {"admission", "device", "render"} <= set(stages)


# ---------------------------------------------------------------------------
# upsert: the WAL-fsync stage is attributed to the ack


def test_upsert_ack_attributes_wal_fsync(tmp_path):
    store_dir = str(tmp_path / "wstore")
    store = VariantStore(width=WIDTH)
    ref, ref_len = encode_allele_array(["A"], WIDTH)
    alt, alt_len = encode_allele_array(["C"], WIDTH)
    store.shard(3).append(
        {"pos": np.asarray([10], np.int32),
         "h": identity_hashes(WIDTH, ref, alt, ref_len, alt_len),
         "ref_len": ref_len, "alt_len": alt_len},
        ref, alt,
    )
    store.save(store_dir)
    registry = MetricsRegistry()
    mgr = SnapshotManager(store_dir, log=lambda m: None)
    mem = Memtable(
        width=WIDTH, store_dir=store_dir,
        wal=WriteAheadLog(store_dir, "serve-obs", log=lambda m: None),
        registry=registry, log=lambda m: None,
    )
    httpd = build_server(manager=MemtableSnapshots(mgr, mem), port=0,
                         memtable=mem, registry=registry)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        port = httpd.server_address[1]
        status, body, hdrs = _post(
            port, "/variants/upsert",
            {"variants": [{"id": "3:77:A:G"}]},
            {"X-Request-Id": "ack-1"},
        )
        assert status == 200, body
        assert hdrs.get("X-Request-Id") == "ack-1"
        recs = _records_for(httpd.ctx, "ack-1")
        assert len(recs) == 1 and recs[0][1] == "upsert"
        stages = dict(recs[0][5])
        assert "wal_fsync" in stages, stages
        assert 0 <= stages["wal_fsync"] <= recs[0][4]
        # histogram series carries it too
        text = registry.render_prometheus()
        assert 'avdb_stage_seconds_count{stage="wal_fsync"} 1' in text
    finally:
        httpd.shutdown()
        httpd.server_close()
        httpd.ctx.batcher.close()
        mem.wal.close()


# ---------------------------------------------------------------------------
# /debug/trace (chaos-gated, both front ends)


def test_debug_trace_is_gated_off_like_chaos(store, pair):
    # the module fixture servers run WITHOUT AVDB_SERVE_CHAOS: the route
    # must 404 byte-identically to any unknown route on BOTH front ends
    st, bt, _h = _get(pair["pt"], "/debug/trace")
    sa, ba, _h = _get(pair["pa"], "/debug/trace")
    assert st == sa == 404
    assert bt == ba
    assert "no such route" in bt


def test_debug_trace_dumps_chrome_events_when_enabled(store, monkeypatch):
    monkeypatch.setenv("AVDB_SERVE_CHAOS", "1")
    store_dir, truth = store
    vid = _vid(truth[0])
    httpd = build_server(store_dir=store_dir, port=0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    aio = build_aio_server(store_dir=store_dir, port=0)
    aio.start_background()
    try:
        for port in (httpd.server_address[1], aio.server_address[1]):
            _get(port, f"/variant/{vid}", {"X-Request-Id": "dump-me"})
            status, body, _h = _get(port, "/debug/trace")
            assert status == 200
            doc = json.loads(body)
            assert doc["displayTimeUnit"] == "ms"
            reqs = [e for e in doc["traceEvents"]
                    if e.get("ph") == "X" and e.get("cat") == "request"]
            assert any(e["args"]["trace_id"] == "dump-me" for e in reqs)
            tracks = [e for e in doc["traceEvents"]
                      if e.get("name") == "thread_name"]
            assert {t["args"]["name"] for t in tracks} >= {
                "requests", "background"}
    finally:
        aio.shutdown()
        aio.ctx.batcher.close()
        httpd.shutdown()
        httpd.server_close()
        httpd.ctx.batcher.close()


# ---------------------------------------------------------------------------
# fleet telemetry plane (/metrics?fleet=1)


def test_plain_metrics_unchanged_and_fleet_view_single_process(store, pair):
    for port in (pair["pt"], pair["pa"]):
        status, body, _h = _get(port, "/metrics")
        assert status == 200
        assert "avdb_fleet_workers_live" not in body  # plain scrape
        status, body, _h = _get(port, "/metrics?fleet=1")
        assert status == 200
        assert "avdb_fleet_workers_live 1" in body
        assert "avdb_fleet_respawns_total 0" in body
        assert "avdb_fleet_worker_age_seconds" in body
        assert "avdb_query_requests_total" in body


def test_fleet_view_sums_published_worker_snapshots(store, tmp_path):
    store_dir, truth = store
    tdir = str(tmp_path / "tm")
    import os

    os.makedirs(tdir)

    def publish(index, n, t=None):
        reg = MetricsRegistry()
        reg.counter("avdb_query_requests_total",
                    labels={"kind": "point"}).inc(n)
        reg.gauge("avdb_serve_queue_depth").set(n)
        with open(os.path.join(tdir, f"worker-{index}.json"), "w") as f:
            json.dump({"index": index,
                       "t": time.time() if t is None else t,
                       "metrics": reg.snapshot()}, f)

    publish(1, 10)
    publish(2, 7)
    publish(3, 1000, t=time.time() - 3600)  # stale: a dead worker's file
    with open(os.path.join(tdir, "fleet.json"), "w") as f:
        json.dump({"t": time.time(), "workers_live": 3,
                   "respawns_total": 4, "worker_age_seconds": 12.5}, f)
    # a DEAD supervisor's fleet.json must age out exactly like a dead
    # worker's snapshot (checked below via the fresh file; see the
    # stale-supervisor test for the other side)
    httpd = build_server(store_dir=store_dir, port=0, telemetry_dir=tdir,
                         worker_index=0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        port = httpd.server_address[1]
        vid = _vid(truth[0])
        assert _get(port, f"/variant/{vid}")[0] == 200  # own: 1 point
        status, body, _h = _get(port, "/metrics?fleet=1")
        assert status == 200
        assert "avdb_fleet_workers_live 3" in body
        assert "avdb_fleet_respawns_total 4" in body
        assert "avdb_fleet_worker_age_seconds 12.5" in body
        # own live registry (1 request) + workers 1 and 2; the stale
        # worker-3 snapshot drops out of the view
        assert 'avdb_query_requests_total{kind="point"} 18' in body
        # gauges take the fleet max
        assert "avdb_serve_queue_depth 10" in body
    finally:
        httpd.shutdown()
        httpd.server_close()
        httpd.ctx.batcher.close()


def test_fleet_view_ages_out_a_dead_supervisors_facts(store, tmp_path):
    """fleet.json past the snapshot TTL is a dead supervisor's leavings:
    the view falls back to the single-process defaults instead of
    serving frozen workers_live/age gauges forever — the gauges exist to
    SURFACE that death."""
    store_dir, _truth = store
    tdir = str(tmp_path / "tm3")
    import os

    os.makedirs(tdir)
    with open(os.path.join(tdir, "fleet.json"), "w") as f:
        json.dump({"t": time.time() - 3600, "workers_live": 4,
                   "respawns_total": 9, "worker_age_seconds": 77.0}, f)
    httpd = build_server(store_dir=store_dir, port=0, telemetry_dir=tdir)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        status, body, _h = _get(httpd.server_address[1],
                                "/metrics?fleet=1")
        assert status == 200
        assert "avdb_fleet_workers_live 1" in body  # NOT the stale 4
        assert "avdb_fleet_respawns_total 0" in body
    finally:
        httpd.shutdown()
        httpd.server_close()
        httpd.ctx.batcher.close()


def test_fleet_view_ignores_torn_snapshot_files(store, tmp_path):
    store_dir, _truth = store
    tdir = str(tmp_path / "tm2")
    import os

    os.makedirs(tdir)
    with open(os.path.join(tdir, "worker-1.json"), "w") as f:
        f.write('{"index": 1, "t":')  # torn mid-publish
    httpd = build_server(store_dir=store_dir, port=0, telemetry_dir=tdir)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        status, body, _h = _get(httpd.server_address[1],
                                "/metrics?fleet=1")
        assert status == 200  # the scrape never fails on a torn sibling
        assert "avdb_fleet_workers_live 1" in body
    finally:
        httpd.shutdown()
        httpd.server_close()
        httpd.ctx.batcher.close()


# ---------------------------------------------------------------------------
# lifecycle events -> flight recorder


def test_brownout_and_breaker_transitions_land_on_the_flight(store,
                                                             tmp_path):
    store_dir, _truth = store
    ring = str(tmp_path / "w0.ring")
    flight = FlightRecorder(ring, slots=32)
    httpd = build_server(store_dir=store_dir, port=0, flight=flight)
    try:
        ctx = httpd.ctx
        ctx.governor.force_level(3)
        ctx.governor.force_level(0)
        assert ctx.engine.breaker is not None
        for _ in range(ctx.engine.breaker.failure_threshold):
            ctx.engine.breaker.record_failure(8, RuntimeError("dev down"))
        events = [e for e in decode_ring(ring)["events"]
                  if e["type"] == "event"]
        names = [(e["name"], e["detail"]) for e in events]
        assert ("brownout", "level 0->3 (shed_bulk)") in names
        assert ("brownout", "level 3->0 (normal)") in names
        assert any(n == "breaker" and "group 8 tripped open" in d
                   for n, d in names)
    finally:
        httpd.server_close()
        httpd.ctx.batcher.close()
        flight.close()


def test_request_summaries_land_on_the_flight(store, tmp_path):
    store_dir, truth = store
    ring = str(tmp_path / "wr.ring")
    flight = FlightRecorder(ring, slots=32)
    httpd = build_server(store_dir=store_dir, port=0, flight=flight)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        port = httpd.server_address[1]
        vid = _vid(truth[0])
        assert _get(port, f"/variant/{vid}",
                    {"X-Request-Id": "boxed"})[0] == 200
        flight.flush()  # the serving flush cadence, forced for the test
        reqs = [e for e in decode_ring(ring)["events"]
                if e["type"] == "request"]
        assert any(e["trace"] == "boxed" and e["kind"] == "point"
                   and e["status"] == 200 and "stages" in e
                   for e in reqs), reqs
    finally:
        httpd.shutdown()
        httpd.server_close()
        httpd.ctx.batcher.close()
        flight.close()


# ---------------------------------------------------------------------------
# doctor flight / doctor trace (the black-box CLIs)


def _seed_blackbox(store_dir):
    from annotatedvdb_tpu.obs import flight as flight_mod

    ring = flight_mod.ring_path(store_dir, 0)
    fr = FlightRecorder(ring, slots=16, event_slots=16)
    fr.event("brownout", "level 0->1 (limit)")
    fr.request("abc", "point", 200, 0.0031,
               [("queue", 0.001), ("device", 0.002)])
    fr.event("breaker", "group 8 tripped open (OSError)")
    fr.close()
    return flight_mod.harvest(ring, store_dir, 0, "died rc=-9",
                              log=lambda m: None)


def test_doctor_flight_renders_harvested_blackbox(tmp_path, capsys):
    from annotatedvdb_tpu.cli import doctor

    store_dir = str(tmp_path / "dstore")
    import os

    os.makedirs(store_dir)
    out = _seed_blackbox(store_dir)
    assert out is not None
    rc = doctor.main(["flight", "--storeDir", store_dir])
    assert rc == 0
    err = capsys.readouterr().err
    assert "died rc=-9" in err
    assert "brownout" in err and "level 0->1" in err
    assert "trace=abc" in err and "device=2.0ms" in err
    # --json emits the structured form
    rc = doctor.main(["flight", "--storeDir", store_dir, "--json"])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["harvested"][0]["meta"]["worker"] == 0
    kinds = [e["type"] for e in doc["harvested"][0]["events"]]
    assert kinds == ["event", "request", "event"]


def test_doctor_flight_decodes_live_rings_without_harvest(tmp_path,
                                                         capsys):
    """A single-process SIGKILL leaves only the ring (no supervisor to
    harvest): doctor flight decodes it directly."""
    from annotatedvdb_tpu.cli import doctor
    from annotatedvdb_tpu.obs import flight as flight_mod

    store_dir = str(tmp_path / "lstore")
    import os

    os.makedirs(store_dir)
    fr = FlightRecorder(flight_mod.ring_path(store_dir, 0), slots=8)
    fr.request("xyz", "region", 200, 0.5, [])
    fr.flush()
    # no close(): SIGKILL semantics
    rc = doctor.main(["flight", "--storeDir", store_dir])
    assert rc == 0
    err = capsys.readouterr().err
    assert "live ring" in err and "trace=xyz" in err
    fr.close()


def test_doctor_flight_exit_2_without_flight_data(tmp_path, capsys):
    from annotatedvdb_tpu.cli import doctor

    store_dir = str(tmp_path / "estore")
    import os

    os.makedirs(store_dir)
    assert doctor.main(["flight", "--storeDir", store_dir]) == 2
    assert "no flight data" in capsys.readouterr().err
    assert doctor.main(["flight", "--storeDir",
                        str(tmp_path / "missing")]) == 2


def test_doctor_trace_merges_ledger_and_flight(tmp_path, capsys):
    from annotatedvdb_tpu.cli import doctor
    from annotatedvdb_tpu.store.ledger import AlgorithmLedger

    store_dir = str(tmp_path / "tstore")
    import os

    os.makedirs(store_dir)
    ledger = AlgorithmLedger(os.path.join(store_dir, "ledger.jsonl"),
                             log=lambda m: None)
    ledger.compact({"labels": ["8"], "files_before": 4, "files_after": 1,
                    "rows": 100, "seconds": 1.5})
    ledger.flush({"labels": ["8"], "rows": 12, "seconds": 0.2})
    _seed_blackbox(store_dir)
    out_path = str(tmp_path / "trace.json")
    rc = doctor.main(["trace", "--storeDir", store_dir,
                      "--out", out_path])
    assert rc == 0
    doc = json.load(open(out_path))
    assert doc["displayTimeUnit"] == "ms"
    names = [e.get("name") for e in doc["traceEvents"]]
    # background track from the ledger + flight request/lifecycle marks
    assert "ledger.compact" in names and "ledger.flush" in names
    assert "point" in names and "breaker" in names
    spans = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    assert all(e["ts"] >= 0 for e in spans)  # rebased to the earliest
    compact = next(e for e in spans if e["name"] == "ledger.compact")
    assert compact["dur"] == pytest.approx(1.5e6)
    # empty store: nothing to render is exit 2
    empty = str(tmp_path / "empty")
    os.makedirs(empty)
    assert doctor.main(["trace", "--storeDir", empty]) == 2
