"""HBM hot-set residency: budget packing, hot/cold churn, generation
swaps, and — the property everything else leans on — byte-parity of
query results with and without a budget engaged (including with the
device probe path forced on, so the managed-cache branch really runs).
"""

from __future__ import annotations

import numpy as np
import pytest

from annotatedvdb_tpu.loaders.lookup import identity_hashes
from annotatedvdb_tpu.serve import QueryEngine, StaticSnapshots
from annotatedvdb_tpu.serve.residency import (
    ResidencyManager,
    budget_from_env,
    device_cache_bytes,
    parse_bytes,
)
from annotatedvdb_tpu.store import VariantStore
from annotatedvdb_tpu.store.variant_store import Segment
from annotatedvdb_tpu.types import encode_allele_array

WIDTH = 8
SEG_ROWS = 64


def _segment_rows(base_pos: int, n: int = SEG_ROWS):
    refs = ["A", "C", "G", "T"][: 4] * (n // 4)
    alts = ["G", "T", "A", "C"][: 4] * (n // 4)
    ref, ref_len = encode_allele_array(refs, WIDTH)
    alt, alt_len = encode_allele_array(alts, WIDTH)
    h = identity_hashes(WIDTH, ref, alt, ref_len, alt_len, refs, alts)
    pos = np.arange(base_pos, base_pos + 31 * n, 31, dtype=np.int32)[:n]
    return {"pos": pos, "h": h, "ref_len": ref_len, "alt_len": alt_len}, \
        ref, alt, refs, alts, pos


def _build_store(n_segments: int = 4):
    """chr8 with n disjoint segments (direct append_segment: no merges),
    plus the list of (id, expected-position) queries per segment."""
    store = VariantStore(width=WIDTH)
    shard = store.shard(8)
    queries = []
    for s in range(n_segments):
        cols, ref, alt, refs, alts, pos = _segment_rows(1000 + s * 100_000)
        shard.append_segment(Segment.build(cols, ref, alt))
        shard._starts_cache = None
        queries.append([
            f"8:{int(p)}:{r}:{a}" for p, r, a in zip(pos, refs, alts)
        ])
    return store, shard, queries


def test_parse_bytes_and_env(monkeypatch):
    assert parse_bytes("1024") == 1024
    assert parse_bytes("4k") == 4096
    assert parse_bytes("2m") == 2 << 20
    assert parse_bytes("1.5g") == int(1.5 * (1 << 30))
    for bad in ("", "x", "-4", "4t"):
        with pytest.raises(ValueError):
            parse_bytes(bad)
    monkeypatch.delenv("AVDB_SERVE_HBM_BUDGET", raising=False)
    assert budget_from_env() is None
    monkeypatch.setenv("AVDB_SERVE_HBM_BUDGET", "512k")
    assert budget_from_env() == 512 << 10


def test_hot_set_respects_budget_and_faults_back():
    store, shard, queries = _build_store(4)
    seg_bytes = device_cache_bytes(shard.segments[0], WIDTH)
    # budget fits exactly ONE segment cache: the hottest segment and only
    # the hottest segment may be resident
    manager = ResidencyManager(
        budget_bytes=seg_bytes, upload=True, min_rows=1,
        async_upload=False, plan_interval_s=0.0,
    )
    engine = QueryEngine(
        StaticSnapshots(store), region_cache_size=0, residency=manager
    )
    # hammer segment 0
    for _ in range(5):
        assert all(r is not None for r in engine.lookup_many(queries[0]))
    stats = manager.stats()
    assert stats["resident"] == 1
    assert stats["resident_bytes"] <= seg_bytes
    assert shard.segments[0]._device is not None
    assert all(s._device is None for s in shard.segments[1:])
    # now hammer segment 2: heat decays off segment 0, segment 2 faults in
    for _ in range(40):
        assert all(r is not None for r in engine.lookup_many(queries[2]))
    assert shard.segments[2]._device is not None     # faulted back in
    assert shard.segments[0]._device is None         # evicted to host
    assert manager.resident_bytes() <= seg_bytes
    # evicted segment still answers (host path) — byte-identical
    assert all(r is not None for r in engine.lookup_many(queries[0]))


def test_zero_budget_keeps_everything_on_host():
    store, shard, queries = _build_store(2)
    manager = ResidencyManager(budget_bytes=0, upload=True, min_rows=1,
                               async_upload=False, plan_interval_s=0.0)
    engine = QueryEngine(
        StaticSnapshots(store), region_cache_size=0, residency=manager
    )
    assert all(r is not None for r in engine.lookup_many(queries[0]))
    assert all(s._device is None for s in shard.segments)
    assert manager.resident_bytes() == 0


def test_managed_segments_never_auto_upload():
    store, shard, queries = _build_store(2)
    manager = ResidencyManager(budget_bytes=1, upload=True, min_rows=1,
                               async_upload=False, plan_interval_s=0.0)
    engine = QueryEngine(
        StaticSnapshots(store), region_cache_size=0, residency=manager
    )
    engine.lookup_many(queries[0])
    assert all(s.residency == "managed" for s in shard.segments)
    # budget of 1 byte fits nothing: no cache may ever appear
    for _ in range(10):
        engine.lookup_many(queries[0] + queries[1])
    assert all(s._device is None for s in shard.segments)


def test_generation_swap_drops_tracking():
    store, _shard, queries = _build_store(2)
    manager = ResidencyManager(budget_bytes=1 << 20, upload=True, min_rows=1,
                               async_upload=False, plan_interval_s=0.0)
    engine = QueryEngine(
        StaticSnapshots(store), region_cache_size=0, residency=manager
    )
    engine.lookup_many(queries[0])
    assert manager.stats()["generation"] == 1
    store2, shard2, queries2 = _build_store(3)
    engine2 = QueryEngine(
        StaticSnapshots(store2, generation=2), region_cache_size=0,
        residency=manager,
    )
    engine2.lookup_many(queries2[0])
    stats = manager.stats()
    assert stats["generation"] == 2
    assert stats["candidates"] == 3
    assert all(s.residency == "managed" for s in shard2.segments)


def test_generation_swap_clears_displaced_residency():
    """govern() must flip resident=False on displaced entries: a queued
    upload batch on the uploader thread still holds them and gates on
    ``e.resident`` — a retired generation must never spend transfers or
    HBM, nor queue ahead of the new generation's hot set."""
    store, _shard, queries = _build_store(2)
    manager = ResidencyManager(budget_bytes=1 << 30, upload=True, min_rows=1,
                               async_upload=False, plan_interval_s=0.0)
    engine = QueryEngine(
        StaticSnapshots(store), region_cache_size=0, residency=manager
    )
    engine.lookup_many(queries[0])
    displaced = list(manager._entries.values())
    assert any(e.resident for e in displaced)
    store2, _shard2, queries2 = _build_store(2)
    engine2 = QueryEngine(
        StaticSnapshots(store2, generation=2), region_cache_size=0,
        residency=manager,
    )
    engine2.lookup_many(queries2[0])
    assert manager.stats()["generation"] == 2
    assert not any(e.resident for e in displaced)


def test_govern_does_not_materialize_key_arrays():
    """govern()'s candidate scan must compute key bounds in O(1) from
    the first/last rows: a freshly loaded store has no combined-key
    arrays, and building them store-wide at govern time (which runs on
    the serving path right after a generation swap) stalls the event
    loop for seconds at genome scale."""
    store, shard, _q = _build_store(3)
    for s in shard.segments:
        s._key = None  # as VariantStore.load leaves them
    manager = ResidencyManager(budget_bytes=1 << 20, upload=False,
                               min_rows=1, async_upload=False,
                               plan_interval_s=0.0)
    manager.govern(StaticSnapshots(store).current())
    assert all(s._key is None for s in shard.segments)
    # O(1) bounds match the materialized truth exactly
    for e in manager._entries.values():
        assert e.key_min == e.seg.key_min
        assert e.key_max == e.seg.key_max


def test_stale_snapshot_cannot_regovern_backwards():
    """An in-flight request still holding a pre-swap snapshot must not
    re-install a retired generation's residency state over the newer
    one — that would displace the live entry set and strand its
    accounted device caches."""
    store1, _s1, queries1 = _build_store(2)
    store2, _s2, queries2 = _build_store(2)
    manager = ResidencyManager(budget_bytes=1 << 30, upload=True, min_rows=1,
                               async_upload=False, plan_interval_s=0.0)
    engine2 = QueryEngine(
        StaticSnapshots(store2, generation=2), region_cache_size=0,
        residency=manager,
    )
    engine2.lookup_many(queries2[0])
    live = list(manager._entries.values())
    assert any(e.resident for e in live)
    # a stale gen-1 snapshot arrives late: govern must be a no-op
    engine1 = QueryEngine(
        StaticSnapshots(store1, generation=1), region_cache_size=0,
        residency=manager,
    )
    engine1.lookup_many(queries1[0])
    assert manager.stats()["generation"] == 2
    assert list(manager._entries.values()) == live
    assert any(e.resident for e in live)


def test_upload_evicted_mid_transfer_drops_cache(monkeypatch):
    """A segment evicted WHILE its host->device transfer is in flight
    must not keep the cache: the plan's ``seg._device = None`` can land
    before the transfer does, and an installed cache on a
    ``resident=False`` entry would be invisible to every future plan —
    unaccounted, unevictable HBM.  The uploader re-checks residency after
    the transfer and drops the orphan."""
    from annotatedvdb_tpu.serve.residency import _Entry

    store, shard, _queries = _build_store(1)
    seg = shard.segments[0]
    seg.residency = "managed"
    manager = ResidencyManager(
        budget_bytes=1 << 20, upload=True, min_rows=1, async_upload=False
    )
    entry = _Entry(seg, device_cache_bytes(seg, WIDTH))
    entry.resident = True
    manager._entries = {id(seg): entry}

    real = Segment._ensure_device_cache

    def racing_upload(self):
        real(self)
        # a newer plan evicts mid-transfer: its seg._device = None is
        # immediately overwritten by the landing cache, leaving exactly
        # the end-state the post-transfer re-check must clean up
        entry.resident = False

    monkeypatch.setattr(Segment, "_ensure_device_cache", racing_upload)
    manager._do_uploads([entry])
    assert seg._device is None
    assert manager.resident_bytes() == 0


def test_evict_applied_after_reupload_keeps_cache():
    """The evict direction of the plan/apply race: an eviction applied
    AFTER a newer plan re-uploaded the segment must leave the fresh
    cache alone — dropping it would strand ``resident=True`` with no
    device bytes behind it (counted against the budget, served from
    host, never re-uploaded because it already looks resident)."""
    from annotatedvdb_tpu.serve.residency import _Entry

    store, shard, _queries = _build_store(1)
    seg = shard.segments[0]
    seg.residency = "managed"
    manager = ResidencyManager(
        budget_bytes=1 << 20, upload=True, min_rows=1, async_upload=False
    )
    entry = _Entry(seg, device_cache_bytes(seg, WIDTH))
    manager._entries = {id(seg): entry}
    # plan1 decided to evict; before its apply runs, a newer plan
    # re-uploads: resident=True with a landed cache
    entry.resident = True
    sentinel = object()
    seg._device = sentinel
    manager._apply(([entry], []))
    assert seg._device is sentinel
    assert manager.resident_bytes() == entry.nbytes
    # and the benign double-apply of a true eviction stays idempotent
    entry.resident = False
    manager._apply(([entry], []))
    manager._apply(([entry], []))
    assert seg._device is None
    assert manager.resident_bytes() == 0


def test_plan_cadence_bounds_plan_rate(monkeypatch):
    """Touches accumulate cheaply; the decay + sort + pack plan runs at
    most once per ``plan_interval_s`` no matter how many probe windows
    land — a bulk spanning many chromosome groups must not pay one plan
    per group, and plan cost must not scale with offered load."""
    store, _shard, queries = _build_store(2)
    manager = ResidencyManager(
        budget_bytes=1 << 20, upload=False, min_rows=1,
        async_upload=False, plan_interval_s=60.0,
    )
    plans = []
    real_plan = ResidencyManager._plan

    def counting_plan(self, entries, decay=1.0):
        plans.append(decay)
        return real_plan(self, entries, decay)

    monkeypatch.setattr(ResidencyManager, "_plan", counting_plan)
    engine = QueryEngine(
        StaticSnapshots(store), region_cache_size=0, residency=manager
    )
    for _ in range(20):
        engine.lookup_many(queries[0] + queries[1])
    # interval far in the future: heat accumulated, zero plans ran
    assert not plans
    assert sum(e.score for e in manager._entries.values()) > 0


def test_decay_is_wall_clock():
    """Aging follows elapsed time, not plan count: back-to-back plans
    (a multi-group request) barely decay just-added heat, while an idle
    gap cools the whole set regardless of how few plans ran in it."""
    store, _shard, queries = _build_store(1)
    manager = ResidencyManager(
        budget_bytes=0, upload=False, min_rows=1,
        async_upload=False, plan_interval_s=0.0,
    )
    engine = QueryEngine(
        StaticSnapshots(store), region_cache_size=0, residency=manager
    )
    engine.lookup_many(queries[0])
    entry = next(iter(manager._entries.values()))
    # the plan ran microseconds after the touch: near-zero elapsed decay
    assert entry.score >= SEG_ROWS * 0.9
    # simulate 5 idle minutes, then touch again: history is cold — only
    # the fresh window's heat remains (not old + new)
    with manager._lock:
        manager._last_plan -= 300.0
    engine.lookup_many(queries[0])
    assert entry.score <= SEG_ROWS * 1.01


@pytest.mark.parametrize("force_device", [False, True])
def test_byte_parity_store_4x_budget(monkeypatch, force_device):
    """A store 4x the HBM budget serves point, bulk, and region reads
    byte-identical to the unbounded (no-residency) engine — with the
    device probe branch forced on so managed caches really get probed."""
    if force_device:
        from annotatedvdb_tpu.store import variant_store

        # the CPU test backend normally disables device lookups; force the
        # latch so resident segments ride _probe_device for real
        monkeypatch.setattr(variant_store, "_DEVICE_LOOKUP_OK", True)
    store, shard, queries = _build_store(4)
    total = sum(device_cache_bytes(s, WIDTH) for s in shard.segments)
    manager = ResidencyManager(
        budget_bytes=total // 4, upload=True, min_rows=1,
        async_upload=False, plan_interval_s=0.0,
    )
    plain = QueryEngine(StaticSnapshots(store), region_cache_size=0)
    budgeted = QueryEngine(
        StaticSnapshots(store), region_cache_size=0, residency=manager
    )
    flat = [q for qs in queries for q in qs]
    misses = [f"8:{p}:A:G" for p in range(2, 30, 7)]
    # interleave hot/cold so some segments are resident and some are not
    for _round in range(3):
        batch = flat + misses
        assert budgeted.lookup_many(batch) == plain.lookup_many(batch)
        hot = queries[_round % 4]
        assert budgeted.lookup_many(hot) == plain.lookup_many(hot)
    assert 0 < manager.resident_bytes() <= total // 4
    # region reads: byte-identical envelopes (host-side slicing either way)
    for spec in ("8:1-200000", "8:100000-400000", "8:1-1000000"):
        assert budgeted.region(spec) == plain.region(spec)
        assert budgeted.region(spec, limit=10) == plain.region(spec, limit=10)
