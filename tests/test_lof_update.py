"""SnpEff LoF/NMD update tests (reference ``load_snpeff_lof.py``)."""

import json
import subprocess
import sys

import numpy as np

from annotatedvdb_tpu.loaders import TpuSnpEffLofLoader, TpuVcfLoader
from annotatedvdb_tpu.loaders.lof_loader import parse_lof_string
from annotatedvdb_tpu.store import AlgorithmLedger, VariantStore

BASE_VCF = """##fileformat=VCFv4.2
#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO
1\t100\t.\tA\tG\t.\t.\t.
1\t200\t.\tC\tT\t.\t.\t.
2\t100\t.\tT\tA\t.\t.\t.
"""

LOF_VCF = """##fileformat=VCFv4.2
#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO
1\t100\t.\tA\tG\t.\t.\tAC=3;LOF=(SFI1|ENSG00000198089|30|0.17)
1\t200\t.\tC\tT\t.\t.\tNMD=(PRAME|ENSG00000185686|14|0.57);AC=1
1\t300\t.\tG\tC\t.\t.\tLOF=(GENE|ENSG0|1|1.0)
2\t100\t.\tT\tA\t.\t.\tAC=9
"""


def build_store(tmp_path):
    store = VariantStore(width=49)
    ledger = AlgorithmLedger(str(tmp_path / "ledger.jsonl"))
    vcf = tmp_path / "base.vcf"
    vcf.write_text(BASE_VCF)
    TpuVcfLoader(store, ledger, log=lambda *a: None).load_file(str(vcf), commit=True)
    return store, ledger


def find_row(store, code, pos):
    shard = store.shard(code)
    i = int(np.searchsorted(shard.cols["pos"], pos))
    assert shard.cols["pos"][i] == pos
    return shard, i


def test_parse_lof_string():
    # load_snpeff_lof.py:112-134 format, incl. multi-record values
    recs = parse_lof_string("(SFI1|ENSG00000198089|30|0.17),(X|ENSGX|2|0.5)")
    assert recs == [
        {"gene_symbol": "SFI1", "gene_id": "ENSG00000198089",
         "num_transcripts": 30, "fraction_affected_transcripts": 0.17},
        {"gene_symbol": "X", "gene_id": "ENSGX",
         "num_transcripts": 2, "fraction_affected_transcripts": 0.5},
    ]
    assert parse_lof_string(None) is None
    # malformed values (bare ;LOF; flag, short/non-numeric records) are
    # skipped, not fatal mid-load
    assert parse_lof_string(True) is None
    assert parse_lof_string("(GENE|ENSG0)") is None
    assert parse_lof_string("(GENE|ENSG0|x|y)") is None


def test_lof_update(tmp_path):
    store, ledger = build_store(tmp_path)
    lof = tmp_path / "lof.vcf"
    lof.write_text(LOF_VCF)
    counters = TpuSnpEffLofLoader(store, ledger, log=lambda *a: None).load_file(
        str(lof), commit=True
    )
    # 1:100 LOF, 1:200 NMD updated; 1:300 unknown (update-only — NOT inserted);
    # 2:100 known but has neither LOF nor NMD -> skipped
    assert counters["update"] == 2
    assert counters["skipped"] >= 1
    assert counters["not_found"] == 1
    assert store.n == 3

    shard, i = find_row(store, 1, 100)
    assert shard.annotations["loss_of_function"][i] == {
        "LOF": [{"gene_symbol": "SFI1", "gene_id": "ENSG00000198089",
                 "num_transcripts": 30,
                 "fraction_affected_transcripts": 0.17}]
    }
    shard, i = find_row(store, 1, 200)
    assert "NMD" in shard.annotations["loss_of_function"][i]
    assert "LOF" not in shard.annotations["loss_of_function"][i]
    shard, i = find_row(store, 2, 100)
    assert shard.annotations["loss_of_function"][i] is None


def test_lof_skip_existing_unless_update_existing(tmp_path):
    store, ledger = build_store(tmp_path)
    lof = tmp_path / "lof.vcf"
    lof.write_text(LOF_VCF)
    TpuSnpEffLofLoader(store, ledger, log=lambda *a: None).load_file(
        str(lof), commit=True
    )
    c2 = TpuSnpEffLofLoader(store, ledger, log=lambda *a: None).load_file(
        str(lof), commit=True
    )
    assert c2["update"] == 0  # existing values not overwritten by default

    c3 = TpuSnpEffLofLoader(
        store, ledger, update_existing=True, log=lambda *a: None
    ).load_file(str(lof), commit=True)
    assert c3["update"] == 2


def test_lof_cli(tmp_path):
    store, ledger = build_store(tmp_path)
    store_dir = tmp_path / "vdb"
    store.save(str(store_dir))
    lof = tmp_path / "lof.vcf"
    lof.write_text(LOF_VCF)
    res = subprocess.run(
        [sys.executable, "-m", "annotatedvdb_tpu.cli.load_snpeff_lof",
         "--fileName", str(lof), "--storeDir", str(store_dir), "--commit"],
        capture_output=True, text=True, check=True,
    )
    counters = json.loads(res.stdout.splitlines()[0])
    assert counters["update"] == 2
    reloaded = VariantStore.load(str(store_dir))
    shard, i = find_row(reloaded, 1, 100)
    assert "LOF" in shard.annotations["loss_of_function"][i]


def test_prefilter_matches_unfiltered(tmp_path, monkeypatch):
    """The pre-lookup LOF/NMD screen must not change stored values or the
    update/variant counters.  Accounting difference BY DESIGN (reference
    semantics — it skips LOF-less lines before any SQL): an excluded row
    absent from the store counts skipped, where an unfiltered pass would
    report not_found; the combined skipped+not_found total is invariant."""
    import numpy as np

    from annotatedvdb_tpu.loaders import TpuVcfLoader
    from annotatedvdb_tpu.loaders.lof_loader import (
        SnpEffLofStrategy,
        TpuSnpEffLofLoader,
    )
    from annotatedvdb_tpu.store import AlgorithmLedger, VariantStore

    base = tmp_path / "base.vcf"
    lof = tmp_path / "lof.vcf"
    rows = []
    for i in range(40):
        info = "."
        if i % 5 == 0:
            info = "LOF=(G%d|ENSG%d|10|0.5)" % (i, i)
        elif i % 7 == 0:
            info = "NMD=(G%d|ENSG%d|3|0.1)" % (i, i)
        elif i % 3 == 0:
            info = "DP=55;AC=2"  # LOF-less: must be screened out pre-lookup
        rows.append(f"1\t{1000 + i}\trs{i}\tA\tG\t.\t.\t{info}")
    # a LOF-less row whose variant is NOT in the store: exercises the
    # skipped-vs-not_found accounting divergence
    rows.append("1\t9999\trsX\tC\tT\t.\t.\tDP=9")
    header = "##fileformat=VCFv4.2\n#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\n"
    base.write_text(header + "\n".join(
        r.replace("LOF=", "X=").replace("NMD=", "Y=")
        for r in rows[:-1]) + "\n")
    lof.write_text(header + "\n".join(rows) + "\n")

    def run(disable_prefilter):
        if disable_prefilter:
            monkeypatch.setattr(
                SnpEffLofStrategy, "prefilter", lambda self, chunk: None
            )
        else:
            monkeypatch.undo()
        store = VariantStore(width=8)
        ledger = AlgorithmLedger(str(tmp_path / f"l{disable_prefilter}.jsonl"))
        TpuVcfLoader(store, ledger, log=lambda *a: None).load_file(
            str(base), commit=True
        )
        c = TpuSnpEffLofLoader(store, ledger, log=lambda *a: None).load_file(
            str(lof), commit=True
        )
        vals = [
            store.shards[1].get_ann("loss_of_function", i)
            for i in range(store.shards[1].n)
        ]
        return {k: c[k] for k in ("variant", "update", "skipped",
                                  "not_found")}, vals

    c_off, v_off = run(disable_prefilter=True)
    c_on, v_on = run(disable_prefilter=False)
    assert v_on == v_off
    for key in ("variant", "update"):
        assert c_on[key] == c_off[key], (key, c_on, c_off)
    # the screened row missing from the store: skipped (reference
    # semantics) instead of not_found; the combined total is invariant
    assert (c_on["skipped"] + c_on["not_found"]
            == c_off["skipped"] + c_off["not_found"])
    assert c_on["not_found"] == c_off["not_found"] - 1
    assert c_on["update"] > 0 and c_on["skipped"] > 0
