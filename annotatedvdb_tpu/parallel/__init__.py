from .mesh import make_mesh, SHARD_AXIS
from .distributed import distributed_annotate_step, reshard_by_owner

__all__ = ["make_mesh", "SHARD_AXIS", "distributed_annotate_step", "reshard_by_owner"]
