"""Device-mesh parallelism: the mesh authority, sharded steps, multi-host.

Only :mod:`.mesh` loads eagerly — it is the leaf module the ``ops/``
kernels import their ``mesh_pjit`` surface from, and an eager
``.distributed`` import here would close the cycle
``ops -> parallel -> distributed -> models.pipeline -> ops``.  The
historical package-level names keep working through PEP 562 lazy
resolution below.
"""

from .mesh import SHARD_AXIS, global_mesh, make_mesh, mesh_pjit

_LAZY = {
    "distributed_annotate_step": ".distributed",
    "reshard_by_owner": ".distributed",
    "init_multihost": ".multihost",
    "multihost_env": ".multihost",
    "process_info": ".multihost",
}

__all__ = [
    "make_mesh", "mesh_pjit", "global_mesh", "SHARD_AXIS",
    "distributed_annotate_step",
    "reshard_by_owner", "init_multihost", "multihost_env", "process_info",
]


def __getattr__(name: str):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(name)
    import importlib

    return getattr(importlib.import_module(mod, __name__), name)
