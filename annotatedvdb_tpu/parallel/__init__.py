from .mesh import make_mesh, SHARD_AXIS
from .distributed import distributed_annotate_step, reshard_by_owner
from .multihost import init_multihost, multihost_env, process_info

__all__ = [
    "make_mesh", "SHARD_AXIS", "distributed_annotate_step",
    "reshard_by_owner", "init_multihost", "multihost_env", "process_info",
]
