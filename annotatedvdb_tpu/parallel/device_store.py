"""Per-shard device-resident membership snapshot of a :class:`VariantStore`.

The multi-chip insert step (``parallel.distributed.distributed_insert_step``)
re-shards each batch to its chromosome owners and needs the store's identity
columns ON DEVICE, sharded the same way, to run membership probes without the
host fan-in the reference's DB round-trips imply
(``Util/lib/python/database/variant.py:287-309``; SURVEY.md §5.8 prescribes
the sorted-merge cross-shard duplicate detection this implements).

Layout: one stacked array per identity column, ``[n_shards, M, ...]`` with
every shard's slice sorted by ``(pos, chrom-mixed hash)`` and padded to the
common power-of-two row count ``M`` with sentinel positions (which sort last
and can never match a probe).  A shard's slice holds ALL chromosomes that
``chromosome_owner_table`` assigns to it, disambiguated inside one sorted run
by the chromosome-salted hash (``ops.dedup.mix_chrom_hash``) plus exact
chromosome confirmation in the probe kernel.

The snapshot is FROZEN at build time: rows appended to the host store
afterwards are not visible (callers keep probing those few segments
host-side — same discipline as the single-device loader's pending-segment
probe).
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

from annotatedvdb_tpu.ops.dedup import CHROM_MIX
from annotatedvdb_tpu.parallel.distributed import (
    chromosome_owner_table,
    position_block_owner,
)
from annotatedvdb_tpu.store import VariantStore
from annotatedvdb_tpu.utils.arrays import POS_SENTINEL, next_pow2


class DeviceShardStore(NamedTuple):
    """Stacked per-shard identity columns (host numpy; callers shard them
    onto the mesh with a ``NamedSharding`` over the shard axis or pass them
    straight into ``shard_map``)."""

    chrom: np.ndarray     # [S, M] int8
    pos: np.ndarray       # [S, M] int32, POS_SENTINEL pad
    hm: np.ndarray        # [S, M] uint32 chromosome-mixed identity hash
    ref: np.ndarray       # [S, M, W] uint8
    alt: np.ndarray       # [S, M, W] uint8
    ref_len: np.ndarray   # [S, M] int32
    alt_len: np.ndarray   # [S, M] int32
    row_id: np.ndarray    # [S, M] int64 host-store global row id (-1 pad);
    #                       valid until the host shard is appended/merged
    n_rows: np.ndarray    # [S] int64 real rows per shard

    @property
    def n_shards(self) -> int:
        return self.chrom.shape[0]


def build_device_shard_store(
    store: VariantStore, n_shards: int, build: str = "GRCh38",
    routing: str = "chrom",
) -> DeviceShardStore:
    """Snapshot ``store``'s identity columns into the stacked per-shard
    layout.  O(store rows): one concat + one sort per shard.

    ``routing`` selects the partition:

    - ``"chrom"`` — all of a chromosome's rows on its owning shard (the
      INSERT-step invariant: per-shard dedup is then globally correct);
    - ``"position"`` — 16kb position blocks round-robin across shards
      (``parallel.distributed.position_block_owner``).  UPDATE lookups
      need no dedup invariant, and real update streams (VEP results,
      CADD tables) arrive chromosome-sorted — chromosome routing would
      land every flush on ONE shard, forfeiting the fan-out.  The query
      side must route the same way (``distributed_update_step``'s
      ``routing`` parameter)."""
    if routing not in ("chrom", "position"):
        raise ValueError(f"unknown snapshot routing {routing!r}")
    owner = chromosome_owner_table(n_shards, build)
    per_shard: list[list] = [[] for _ in range(n_shards)]
    width = store.width
    for code, shard in store.shards.items():
        starts = shard._starts()
        for si, seg in enumerate(list(shard.segments)):
            # host-store global ids (segment-list order): the update step
            # hands matches back as these, so the host applies annotation
            # writes without re-looking-up
            rid = int(starts[si]) + np.arange(seg.n, dtype=np.int64)
            cols = (
                np.full(seg.n, code, np.int8),
                seg.cols["pos"],
                seg.cols["h"],
                seg.ref,
                seg.alt,
                seg.cols["ref_len"],
                seg.cols["alt_len"],
                rid,
            )
            if routing == "chrom":
                per_shard[owner[min(code, len(owner) - 1)]].append(cols)
                continue
            row_owner = position_block_owner(
                np.full(seg.n, code, np.int64), seg.cols["pos"], n_shards
            )
            for s in np.unique(row_owner):
                m = row_owner == s
                per_shard[int(s)].append(tuple(c[m] for c in cols))
    m = max(
        (sum(parts[0].shape[0] for parts in bucket) for bucket in per_shard
         if bucket),
        default=0,
    )
    m = max(next_pow2(max(m, 1)), 1)
    out = {
        "chrom": np.zeros((n_shards, m), np.int8),
        "pos": np.full((n_shards, m), POS_SENTINEL, np.int32),
        "hm": np.zeros((n_shards, m), np.uint32),
        "ref": np.zeros((n_shards, m, width), np.uint8),
        "alt": np.zeros((n_shards, m, width), np.uint8),
        "ref_len": np.zeros((n_shards, m), np.int32),
        "alt_len": np.zeros((n_shards, m), np.int32),
        "row_id": np.full((n_shards, m), -1, np.int64),
    }
    n_rows = np.zeros((n_shards,), np.int64)
    for s, bucket in enumerate(per_shard):
        if not bucket:
            continue
        chrom = np.concatenate([b[0] for b in bucket])
        pos = np.concatenate([b[1] for b in bucket])
        h = np.concatenate([b[2] for b in bucket])
        ref = np.concatenate([b[3] for b in bucket])
        alt = np.concatenate([b[4] for b in bucket])
        rl = np.concatenate([b[5] for b in bucket])
        al = np.concatenate([b[6] for b in bucket])
        rid = np.concatenate([b[7] for b in bucket])
        hm = h ^ (chrom.astype(np.uint32) * np.uint32(CHROM_MIX))
        key = (pos.astype(np.uint64) << np.uint64(32)) | hm
        order = np.argsort(key, kind="stable")
        k = order.shape[0]
        n_rows[s] = k
        out["chrom"][s, :k] = chrom[order]
        out["pos"][s, :k] = pos[order]
        out["hm"][s, :k] = hm[order]
        out["ref"][s, :k] = ref[order]
        out["alt"][s, :k] = alt[order]
        out["ref_len"][s, :k] = rl[order]
        out["alt_len"][s, :k] = al[order]
        out["row_id"][s, :k] = rid[order]
    return DeviceShardStore(n_rows=n_rows, **out)
