"""Distributed annotate step: chromosome re-shard + annotate + global counters.

TPU-native mapping of the reference's share-nothing per-chromosome worker pool
(SURVEY.md §2.5): instead of demuxing a VCF into per-chromosome files and
forking processes, every shard ingests an arbitrary slice of the input,
routes each row to its owning shard with one ``all_to_all``, annotates
locally, and aggregates counters with ``psum``.  Chromosome ownership keeps
the store's partition invariant (one shard owns a chromosome's rows, so
dedup/update never crosses shards — the same lock-avoidance layout the
reference gets from Postgres LIST partitions, ``createVariant.sql:29-50``).

Ownership is **variant-count balanced**: chromosomes are assigned to shards
by greedy longest-first packing over GRCh38 chromosome lengths (a static
proxy for variant counts), the deterministic analog of the reference's
chromosome-order shuffle (``load_cadd_scores.py:306``).

The default exchange capacity is **lossless**: each source shard can send
its entire local slice to a single owner, so chromosome-sorted input (the
common case — VCFs are sorted) routes without drops.  Callers chasing
throughput on chromosome-interleaved input may pass a smaller ``capacity``;
overflow is then dropped *with accounting* (``n_dropped``).
"""

from __future__ import annotations

from functools import lru_cache, partial

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

try:  # jax >= 0.6: top-level export, varying-manual-axes API (check_vma)
    from jax import shard_map
except ImportError:  # jax 0.4/0.5: experimental module, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _experimental_shard_map

    def shard_map(f, **kw):
        if "check_vma" in kw:
            kw["check_rep"] = kw.pop("check_vma")
        return _experimental_shard_map(f, **kw)

from annotatedvdb_tpu.models.pipeline import annotate_pipeline
from annotatedvdb_tpu.parallel.mesh import SHARD_AXIS
from annotatedvdb_tpu.types import NUM_CHROMOSOMES, VariantBatch


def _bucketize(owner, arrays, n_buckets: int, capacity: int):
    """Pack rows into [n_buckets * capacity] slots by owner (pad = dropped).

    Returns (packed arrays, valid mask).  Rows beyond a bucket's capacity are
    dropped and must be counted by the caller (no silent loss: the returned
    ``n_dropped`` reports them)."""
    n = owner.shape[0]
    order = jnp.argsort(owner, stable=True)
    owner_sorted = owner[order]
    # first row index of each bucket in the sorted order
    starts = jnp.searchsorted(owner_sorted, jnp.arange(n_buckets, dtype=owner.dtype))
    rank_in_bucket = jnp.arange(n, dtype=jnp.int32) - starts[owner_sorted]
    in_capacity = rank_in_bucket < capacity
    slot = jnp.where(
        in_capacity, owner_sorted * capacity + rank_in_bucket, n_buckets * capacity
    )

    def pack(x):
        x_sorted = x[order]
        out_shape = (n_buckets * capacity,) + x.shape[1:]
        return jnp.zeros(out_shape, x.dtype).at[slot].set(
            x_sorted, mode="drop", unique_indices=True
        )

    packed = jax.tree.map(pack, arrays)
    valid = (
        jnp.zeros((n_buckets * capacity,), jnp.bool_)
        .at[slot]
        .set(in_capacity, mode="drop", unique_indices=True)
    )
    n_dropped = jnp.sum(~in_capacity, dtype=jnp.int32)
    return packed, valid, n_dropped


def reshard_by_owner(owner, arrays, n_shards: int, capacity: int, axis=SHARD_AXIS):
    """Inside shard_map: route rows to ``owner``-th shard via one all_to_all.

    Each shard sends up to ``capacity`` rows to each destination; returns the
    received rows [n_shards * capacity, ...], their validity mask, and the
    per-shard dropped-row count (psum'd to a global)."""
    packed, valid, n_dropped = _bucketize(owner, arrays, n_shards, capacity)

    def exchange(x):
        grouped = x.reshape((n_shards, capacity) + x.shape[1:])
        received = jax.lax.all_to_all(grouped, axis, split_axis=0, concat_axis=0)
        return received.reshape((n_shards * capacity,) + x.shape[1:])

    received = jax.tree.map(exchange, packed)
    valid = exchange(valid)
    total_dropped = jax.lax.psum(n_dropped, axis)
    return received, valid, total_dropped


@lru_cache(maxsize=None)
def chromosome_owner_table(n_shards: int, build: str = "GRCh38") -> tuple:
    """[NUM_CHROMOSOMES + 1] owner table: greedy longest-first packing of
    chromosomes onto shards weighted by chromosome length — ~proportional to
    variant count, so shard loads stay within ~1.5x of each other (chr1 is
    ~15x chr21; contiguous blocks would skew ~5x).  Index 0 (pad rows) maps
    to shard 0."""
    from annotatedvdb_tpu.genome.assemblies import chromosome_lengths

    lengths = chromosome_lengths(build)
    table = [0] * (NUM_CHROMOSOMES + 1)
    load = [0] * n_shards
    for code in sorted(lengths, key=lambda c: -lengths[c]):
        s = min(range(n_shards), key=load.__getitem__)
        table[code] = s
        load[s] += lengths[code]
    return tuple(table)


def chromosome_owner(chrom, n_shards: int):
    """Owning shard of each row's chromosome code (balanced static table)."""
    table = jnp.asarray(chromosome_owner_table(n_shards), jnp.int32)
    return table[jnp.clip(chrom.astype(jnp.int32), 0, NUM_CHROMOSOMES)]


POSITION_BLOCK_BITS = 14  # 16kb blocks: fine-grained spread, bin-cache friendly


def position_block_owner(chrom, pos, n_shards: int) -> np.ndarray:
    """Host-side owner map for annotate-only fan-out: round-robin 16kb
    position blocks across shards.  Chromosome-sorted input (every VCF) then
    spreads evenly instead of serializing onto one chromosome owner — the
    right routing while dedup/store remain host-side and no device holds
    persistent per-chromosome state.  Chromosome enters the rotation so
    chromosomes don't all start on shard 0."""
    blocks = (np.asarray(pos).astype(np.int64) >> POSITION_BLOCK_BITS)
    return ((blocks + np.asarray(chrom).astype(np.int64)) % n_shards).astype(
        np.int32
    )


def exact_capacity(owner: np.ndarray, n_shards: int) -> int:
    """Smallest per-(source, destination) slot count that loses no rows for
    this owner map, rounded up to a power of two (bounds the set of compiled
    exchange shapes)."""
    from annotatedvdb_tpu.utils.arrays import next_pow2

    per_source = np.asarray(owner).reshape(n_shards, -1)
    cap = 1
    for s in range(n_shards):
        counts = np.bincount(per_source[s], minlength=n_shards)
        cap = max(cap, int(counts.max()))
    return next_pow2(cap)


def _step_prologue(mesh, batch: VariantBatch, capacity: int | None, row_id,
                   owner: np.ndarray | None = None):
    """Shared entry checks/defaults for the three distributed steps:
    divisibility, lossless default capacity for the owner map, and the
    identity row-id map.  Returns (n_shards, capacity, row_id)."""
    n_shards = mesh.devices.size
    if batch.n % n_shards:
        raise ValueError(
            f"batch size {batch.n} not divisible by {n_shards} shards — pad "
            "with chrom-0 rows first (loaders use _pad_batch)"
        )
    n_local = batch.n // n_shards
    if capacity is None:
        if owner is not None:
            capacity = min(exact_capacity(owner, n_shards), n_local)
        else:
            host_owner = np.asarray(chromosome_owner_table(n_shards))[
                np.clip(np.asarray(batch.chrom, np.int32), 0, NUM_CHROMOSOMES)
            ]
            capacity = min(exact_capacity(host_owner, n_shards), n_local)
    if row_id is None:
        row_id = np.arange(batch.n, dtype=np.int32)
    return n_shards, capacity, row_id


def distributed_annotate_step(
    mesh, batch: VariantBatch, capacity: int | None = None, row_id=None,
    owner: np.ndarray | None = None,
):
    """Full sharded load step: reshard rows to chromosome owners, annotate,
    and count classes globally.  This is the function the driver dry-runs
    multi-chip (``__graft_entry__.dryrun_multichip``) and the path
    ``TpuVcfLoader`` takes on a multi-device mesh.

    Returns ``(ann, row_id_out, counts, n_dropped, n_fallback)``:

    - ``ann``: annotated arrays in post-exchange order;
    - ``row_id_out``: for each post-exchange slot, the caller-supplied row id
      of the input row occupying it (−1 for empty slots, pad rows, and
      dropped rows) — the host scatters annotations back to input order
      with it;
    - ``counts``: global per-class psum over device-annotated rows;
    - ``n_dropped``: rows lost to capacity overflow (0 with the lossless
      default);
    - ``n_fallback``: rows flagged for the host long-allele path.

    ``owner`` is an optional host-computed [N] shard assignment (e.g.
    :func:`position_block_owner` for annotate-only fan-out); without it,
    rows route to their chromosome's owner (the device-resident-store
    layout).  ``capacity`` bounds rows each shard sends per destination; the
    default is the host-computed exact lossless minimum for the owner map
    (for the chromosome map on sorted input that is ``n_local`` — the whole
    slice may route to one owner).  Row conservation invariant:
    ``sum(counts) + n_fallback + n_dropped == non-pad input rows``."""
    n_shards, capacity, row_id = _step_prologue(
        mesh, batch, capacity, row_id, owner
    )
    owner_in = (
        np.asarray(owner, np.int32) if owner is not None
        else np.full(batch.n, -1, np.int32)  # -1: chromosome routing in-trace
    )
    step = _annotate_step_program(mesh, n_shards, capacity, owner is None)
    return step(
        batch.chrom, batch.pos, batch.ref, batch.alt,
        batch.ref_len, batch.alt_len, row_id, owner_in,
    )


@lru_cache(maxsize=64)
def _annotate_step_program(mesh, n_shards: int, capacity: int,
                           use_chrom_owner: bool):
    """The shard_map program for :func:`distributed_annotate_step`, cached
    by (mesh, shape parameters) — rebuilding the closure per call would
    re-trace AND re-compile every step (~40s each on a virtual CPU mesh)."""
    spec = P(SHARD_AXIS)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(spec,) * 8,
        out_specs=(
            jax.tree.map(lambda _: spec, _annotated_specs()),
            spec, P(), P(), P(),
        ),
        check_vma=False,
    )
    def step(chrom, pos, ref, alt, ref_len, alt_len, rid, owner_rows):
        owner = (
            chromosome_owner(chrom, n_shards) if use_chrom_owner else owner_rows
        )
        arrays = (chrom, pos, ref, alt, ref_len, alt_len, rid)
        (chrom, pos, ref, alt, ref_len, alt_len, rid), valid, dropped = (
            reshard_by_owner(owner, arrays, n_shards, capacity)
        )
        ann = annotate_pipeline(chrom, pos, ref, alt, ref_len, alt_len)
        # global per-class counters (reference: per-worker counter dicts,
        # variant_loader.py:387-392 — here one psum).  Pad rows (chrom 0,
        # both in-batch padding and empty exchange slots) and truncated
        # host-fallback rows are excluded: their kernel outputs are undefined.
        real = valid & (chrom > 0)
        counted = real & ~ann.host_fallback
        counts = jnp.zeros((8,), jnp.int32).at[ann.variant_class].add(
            counted.astype(jnp.int32), mode="drop"
        )
        counts = jax.lax.psum(counts, SHARD_AXIS)
        n_fallback = jax.lax.psum(
            jnp.sum(real & ann.host_fallback, dtype=jnp.int32), SHARD_AXIS
        )
        # row ids for the host-side scatter; -1 marks unusable slots
        rid_out = jnp.where(real, rid, -1)
        return ann, rid_out, counts, dropped, n_fallback

    # one jitted program: shard_map OUTSIDE jit executes eagerly, paying a
    # per-primitive dispatch (measured ~1000x slower on a CPU mesh)
    return jax.jit(step)


def _annotated_specs():
    from annotatedvdb_tpu.types import AnnotatedBatch

    return AnnotatedBatch(*([0] * len(AnnotatedBatch._fields)))


def distributed_insert_step(mesh, batch: VariantBatch, dev_store=None,
                            capacity: int | None = None, row_id=None):
    """Full sharded INSERT step: chromosome re-shard + annotate + in-batch
    dedup + store membership, all inside one mesh program (VERDICT r3 #4 —
    previously only annotate ran on the mesh; duplicate detection and store
    probes serialized on the host after device fan-in).

    Rows route to their chromosome's owning shard (``chromosome_owner``), so
    each shard sees every row of the chromosomes it owns — the partition
    invariant that makes per-shard dedup GLOBALLY correct (the reference
    gets the same guarantee from per-chromosome worker processes sharing a
    DB, ``database/variant.py:287-309``).

    ``dev_store``: optional
    :class:`~annotatedvdb_tpu.parallel.device_store.DeviceShardStore`
    snapshot; when present each shard probes its resident slice with the
    sorted two-level search (``ops.dedup.lookup_in_sorted_multi``) and
    duplicate counts ride one psum.  Returns
    ``(ann, rid_out, flags, counters)``:

    - ``ann``: annotated arrays in post-exchange order;
    - ``rid_out``: input row id per slot (-1 = empty/pad/dropped);
    - ``flags``: dict of per-slot bool arrays ``dup_batch`` (duplicates an
      earlier row of this batch) and ``in_store`` (identity already present
      in the snapshot) — scatter back with ``rid_out`` exactly like the
      annotate outputs;
    - ``counters``: dict of psum'd globals (``class_counts``, ``n_dropped``,
      ``n_fallback``, ``n_batch_dup``, ``n_store_dup``).

    Host-fallback rows (alleles wider than the device arrays) are excluded
    from both verdicts — their truncated-prefix identity could collide, so
    the host re-checks them exactly as the single-device path does."""
    n_shards, capacity, row_id = _step_prologue(mesh, batch, capacity, row_id)
    has_store = dev_store is not None
    store_arrays = tuple(dev_store[:7]) if has_store else ()
    step = _insert_step_program(mesh, n_shards, capacity, has_store)
    return step(
        batch.chrom, batch.pos, batch.ref, batch.alt,
        batch.ref_len, batch.alt_len, row_id, *store_arrays,
    )


def distributed_update_step(mesh, batch: VariantBatch, dev_store,
                            capacity: int | None = None, row_id=None,
                            routing: str = "chrom"):
    """Sharded UPDATE-identity step: chromosome re-shard + in-mesh store
    lookup, one mesh program.  The TPU mapping of the reference's
    multi-process update fan-out (``load_vep_result.py:304-311``,
    ``load_cadd_scores.py:305-313``): each shard resolves the update rows
    of the chromosomes it owns against its resident snapshot slice, and
    the host gets back *store row ids* — it applies the annotation writes
    directly, no host-side identity search remains.

    No annotate kernel runs (updates need identity only), so the step is
    one all_to_all + hash + two-level sorted lookup per shard plus psum'd
    match counters.

    Returns ``(rid_out, found, store_row, counters)``:

    - ``rid_out``: input row id per post-exchange slot (-1 = empty/pad);
    - ``found``: bool per slot — identity present in the snapshot;
    - ``store_row``: int64 host-store global row id per slot (-1 when not
      found) — valid until the host shard is appended/compacted;
    - ``counters``: psum'd ``{"n_matched", "n_missing", "n_fallback",
      "n_dropped"}``; fallback rows (alleles wider than the device arrays)
      are excluded from both verdicts and re-checked host-side, exactly
      like the insert step.  ``n_dropped`` is nonzero only with an
      explicit undersized ``capacity`` — dropped rows return no rid, so
      callers must treat them as unresolved, not missing.

    ``routing`` must match the snapshot's partition
    (``build_device_shard_store``): ``"chrom"`` routes whole chromosomes,
    ``"position"`` spreads 16kb position blocks across shards — the right
    choice for chromosome-sorted update streams, which would otherwise
    land every flush on one shard."""
    if routing not in ("chrom", "position"):
        raise ValueError(f"unknown update routing {routing!r}")
    owner = (
        position_block_owner(
            np.asarray(batch.chrom, np.int64),
            np.asarray(batch.pos, np.int64), mesh.devices.size,
        )
        if routing == "position" else None
    )
    n_shards, capacity, row_id = _step_prologue(
        mesh, batch, capacity, row_id, owner
    )
    step = _update_step_program(mesh, n_shards, capacity,
                                routing == "position")
    return step(
        batch.chrom, batch.pos, batch.ref, batch.alt,
        batch.ref_len, batch.alt_len, row_id,
        *(dev_store[:7] + (dev_store.row_id,)),
    )


def distributed_serve_lookup_step(mesh, chrom, pos, hm, ref, alt,
                                  ref_len, alt_len, dev_store,
                                  capacity: int | None = None,
                                  row_id=None):
    """Sharded SERVE bulk lookup: chromosome re-shard + in-mesh store
    membership, one mesh program — the serving read path's twin of
    :func:`distributed_update_step`.

    Differences that matter to serving byte-parity:

    - the identity hash arrives **host-computed** (``hm``: the loaders'
      ``identity_hashes`` full-string hash, chromosome-mixed) instead of
      being re-derived in-trace from width-truncated bytes — so
      long-allele queries resolve with EXACTLY the host ``Segment.probe``
      semantics (full-string hash + truncated byte/length confirmation)
      and no host re-check pass is needed;
    - no counters ride the program (serving wants rows, and a psum per
      bulk drain is a collective the hot path should not pay).

    Returns ``(rid_out, found, store_row)``, each ``[n_shards *
    capacity]`` in post-exchange order — materializing them IS the
    cross-device gather.  Scatter back with ``rid_out`` (−1 = empty/pad
    slot); ``store_row`` is the host-store global row id (−1 = miss),
    directly renderable via ``serve.engine.render_variant``."""
    n = chrom.shape[0]
    n_shards = mesh.devices.size
    if n % n_shards:
        raise ValueError(
            f"query batch {n} not divisible by {n_shards} shards — pad "
            "with chrom-0 rows first"
        )
    if capacity is None:
        host_owner = np.asarray(chromosome_owner_table(n_shards))[
            np.clip(np.asarray(chrom, np.int32), 0, NUM_CHROMOSOMES)
        ]
        capacity = min(exact_capacity(host_owner, n_shards), n // n_shards)
    if row_id is None:
        row_id = np.arange(n, dtype=np.int32)
    step = _serve_lookup_program(mesh, n_shards, capacity)
    return step(
        chrom, pos, hm, ref, alt, ref_len, alt_len, row_id,
        *(dev_store[:7] + (dev_store.row_id,)),
    )


@lru_cache(maxsize=64)
def _serve_lookup_program(mesh, n_shards: int, capacity: int):
    """The shard_map program for :func:`distributed_serve_lookup_step`,
    cached by (mesh, shape parameters) — same re-compile trap as the
    other steps."""
    from annotatedvdb_tpu.ops.dedup import lookup_in_sorted_multi

    spec = P(SHARD_AXIS)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(spec,) * 8 + (spec,) * 8,
        out_specs=(spec, spec, spec),
        check_vma=False,
    )
    def step(chrom, pos, hm, ref, alt, ref_len, alt_len, rid, *store_cols):
        owner = chromosome_owner(chrom, n_shards)
        arrays = (chrom, pos, hm, ref, alt, ref_len, alt_len, rid)
        (chrom, pos, hm, ref, alt, ref_len, alt_len, rid), valid, _dropped = (
            reshard_by_owner(owner, arrays, n_shards, capacity)
        )
        (s_chrom, s_pos, s_hm, s_ref, s_alt, s_rl, s_al, s_rid) = store_cols
        s_chrom, s_pos, s_hm = s_chrom[0], s_pos[0], s_hm[0]
        s_ref, s_alt, s_rl, s_al = s_ref[0], s_alt[0], s_rl[0], s_al[0]
        s_rid = s_rid[0]
        real = valid & (chrom > 0)
        # pad/empty slots carry chrom 0 + zero identities: salt their
        # position out of the sorted probe so they can never alias a row
        slot = jnp.arange(pos.shape[0], dtype=jnp.int32)
        pos_k = jnp.where(real, pos, -1 - slot)
        found, idx = lookup_in_sorted_multi(
            s_chrom, s_pos, s_hm, s_ref, s_alt, s_rl, s_al,
            chrom, pos_k, hm, ref, alt, ref_len, alt_len,
        )
        found = found & real
        store_row = jnp.where(
            found, s_rid[jnp.clip(idx, 0, s_rid.shape[0] - 1)], -1
        )
        rid_out = jnp.where(real, rid, -1)
        return rid_out, found, store_row

    # see _annotate_step_program: un-jitted shard_map executes eagerly
    return jax.jit(step)


@lru_cache(maxsize=64)
def _update_step_program(mesh, n_shards: int, capacity: int,
                         position_routing: bool = False):
    """The shard_map program for :func:`distributed_update_step`, cached by
    (mesh, shape parameters) — same re-compile trap as the other steps."""
    from annotatedvdb_tpu.ops.dedup import lookup_in_sorted_multi, mix_chrom_hash
    from annotatedvdb_tpu.ops.hashing import allele_hash

    spec = P(SHARD_AXIS)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(spec,) * 7 + (spec,) * 8,
        out_specs=(
            spec, spec, spec,
            {"n_matched": P(), "n_missing": P(), "n_fallback": P(),
             "n_dropped": P()},
        ),
        check_vma=False,
    )
    def step(chrom, pos, ref, alt, ref_len, alt_len, rid, *store_cols):
        if position_routing:
            # in-trace twin of position_block_owner — must stay identical
            # to the host formula the snapshot was partitioned with.
            # int32 is exact: pos < 2^31 and the shift only shrinks it
            # (int64 would be silently truncated under 32-bit jax anyway)
            owner = (
                ((pos.astype(jnp.int32) >> POSITION_BLOCK_BITS)
                 + chrom.astype(jnp.int32)) % n_shards
            ).astype(jnp.int32)
        else:
            owner = chromosome_owner(chrom, n_shards)
        arrays = (chrom, pos, ref, alt, ref_len, alt_len, rid)
        (chrom, pos, ref, alt, ref_len, alt_len, rid), valid, dropped = (
            reshard_by_owner(owner, arrays, n_shards, capacity)
        )
        (s_chrom, s_pos, s_hm, s_ref, s_alt, s_rl, s_al, s_rid) = store_cols
        s_chrom, s_pos, s_hm = s_chrom[0], s_pos[0], s_hm[0]
        s_ref, s_alt, s_rl, s_al = s_ref[0], s_alt[0], s_rl[0], s_al[0]
        s_rid = s_rid[0]
        real = valid & (chrom > 0)
        # over-width rows: truncated-prefix identity could collide — the
        # host re-checks them with full-string hashes (same discipline as
        # the insert step)
        fallback = real & (
            (ref_len > ref.shape[1]) | (alt_len > alt.shape[1])
        )
        usable = real & ~fallback
        h = allele_hash(ref, alt, ref_len, alt_len)
        slot = jnp.arange(pos.shape[0], dtype=jnp.int32)
        pos_k = jnp.where(usable, pos, -1 - slot)
        hm = mix_chrom_hash(h, chrom)
        found, idx = lookup_in_sorted_multi(
            s_chrom, s_pos, s_hm, s_ref, s_alt, s_rl, s_al,
            chrom, pos_k, hm, ref, alt, ref_len, alt_len,
        )
        found = found & usable
        store_row = jnp.where(
            found, s_rid[jnp.clip(idx, 0, s_rid.shape[0] - 1)], -1
        )
        counters = {
            "n_matched": jax.lax.psum(
                jnp.sum(found, dtype=jnp.int32), SHARD_AXIS
            ),
            "n_missing": jax.lax.psum(
                jnp.sum(usable & ~found, dtype=jnp.int32), SHARD_AXIS
            ),
            "n_fallback": jax.lax.psum(
                jnp.sum(fallback, dtype=jnp.int32), SHARD_AXIS
            ),
            "n_dropped": dropped,
        }
        rid_out = jnp.where(real, rid, -1)
        return rid_out, found, store_row, counters

    # see _annotate_step_program: un-jitted shard_map executes eagerly
    return jax.jit(step)


@lru_cache(maxsize=64)
def _insert_step_program(mesh, n_shards: int, capacity: int, has_store: bool):
    """The shard_map program for :func:`distributed_insert_step`, cached by
    (mesh, shape parameters) — same re-compile trap as
    :func:`_annotate_step_program`."""
    from annotatedvdb_tpu.ops.dedup import (
        lookup_in_sorted_multi,
        mark_batch_duplicates_multi,
        mix_chrom_hash,
    )
    from annotatedvdb_tpu.ops.hashing import allele_hash

    spec = P(SHARD_AXIS)
    store_specs = (spec,) * (7 if has_store else 0)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(spec,) * 7 + store_specs,
        out_specs=(
            jax.tree.map(lambda _: spec, _annotated_specs()),
            spec,
            {"dup_batch": spec, "in_store": spec},
            {"class_counts": P(), "n_dropped": P(), "n_fallback": P(),
             "n_batch_dup": P(), "n_store_dup": P()},
        ),
        check_vma=False,
    )
    def step(chrom, pos, ref, alt, ref_len, alt_len, rid, *store_cols):
        owner = chromosome_owner(chrom, n_shards)
        arrays = (chrom, pos, ref, alt, ref_len, alt_len, rid)
        (chrom, pos, ref, alt, ref_len, alt_len, rid), valid, dropped = (
            reshard_by_owner(owner, arrays, n_shards, capacity)
        )
        ann = annotate_pipeline(chrom, pos, ref, alt, ref_len, alt_len)
        real = valid & (chrom > 0)
        usable = real & ~ann.host_fallback
        h = allele_hash(ref, alt, ref_len, alt_len)
        # pad/empty slots carry chrom 0 + zero alleles and would dedup
        # against each other: salt them out of every identity comparison
        # by replacing their position with a unique negative sentinel
        slot = jnp.arange(pos.shape[0], dtype=jnp.int32)
        pos_k = jnp.where(usable, pos, -1 - slot)
        dup_batch = mark_batch_duplicates_multi(
            chrom, pos_k, h, ref, alt, ref_len, alt_len
        ) & usable
        if store_cols:
            (s_chrom, s_pos, s_hm, s_ref, s_alt, s_rl, s_al) = store_cols
            # shard_map passes the [1, M, ...] local block; drop the axis
            s_chrom, s_pos, s_hm = s_chrom[0], s_pos[0], s_hm[0]
            s_ref, s_alt, s_rl, s_al = s_ref[0], s_alt[0], s_rl[0], s_al[0]
            hm = mix_chrom_hash(h, chrom)
            in_store, _ = lookup_in_sorted_multi(
                s_chrom, s_pos, s_hm, s_ref, s_alt, s_rl, s_al,
                chrom, pos_k, hm, ref, alt, ref_len, alt_len,
            )
            # disjoint verdicts: a row that duplicates an earlier batch row
            # AND exists in the store counts once, as an in-batch dup —
            # matching the host loader's order (dedup filters first, then
            # membership probes survivors) and keeping the conservation
            # identity n_new + n_batch_dup + n_store_dup + n_fallback == n
            in_store = in_store & usable & ~dup_batch
        else:
            in_store = jnp.zeros(pos.shape, jnp.bool_)
        counted = usable & ~dup_batch & ~in_store
        counts = jnp.zeros((8,), jnp.int32).at[ann.variant_class].add(
            counted.astype(jnp.int32), mode="drop"
        )
        counters = {
            "class_counts": jax.lax.psum(counts, SHARD_AXIS),
            "n_dropped": dropped,
            "n_fallback": jax.lax.psum(
                jnp.sum(real & ann.host_fallback, dtype=jnp.int32), SHARD_AXIS
            ),
            "n_batch_dup": jax.lax.psum(
                jnp.sum(dup_batch, dtype=jnp.int32), SHARD_AXIS
            ),
            "n_store_dup": jax.lax.psum(
                jnp.sum(in_store, dtype=jnp.int32), SHARD_AXIS
            ),
        }
        rid_out = jnp.where(real, rid, -1)
        return ann, rid_out, {"dup_batch": dup_batch, "in_store": in_store}, counters

    # see _annotate_step_program: un-jitted shard_map executes eagerly
    return jax.jit(step)
